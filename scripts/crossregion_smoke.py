"""Crossregion smoke: 2×2 federation partition-heal-converge, fast.

ci_fast.sh stage (30 s wall budget, mirroring the feeder/event-front
smoke pattern): drive the REAL MultiRegionManager + fault injector +
per-peer circuit breakers through a full partition arc on a jax-free,
grpc-server-free 2-region × 2-node loopback harness — the smoke
budget is spent on the federation plane, not on XLA warmup or daemon
bootstrap.  The full-stack 2×2 invariants (real daemons, wire RPCs,
degraded metadata end to end) are pinned by tests/test_multiregion.py
in the tier-1 suite.

Asserts, in order:

1. HEALTHY: queued MULTI_REGION deltas aggregate per window, push to
   the remote region's per-key owners with the flag cleared, and the
   remote "engines" converge onto the summed hits.
2. PARTITION: cross-region sends fail into the breakers; failed
   deltas RE-QUEUE (bounded, counted) instead of dropping; once every
   remote member's circuit opens the region aggregate reads `open`.
3. HEAL + CONVERGE: the retry backlog drains, the partition-era
   deltas land remotely, and hits_dropped stays 0 — requeue-and-
   converge, measured inside the budget.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    t0 = time.monotonic()
    import logging
    import threading

    # The partition phase MEANS to fail sends; keep the smoke output
    # to its one OK line.
    logging.getLogger("gubernator_tpu.multiregion").setLevel(
        logging.ERROR
    )

    from gubernator_tpu.cluster import faults
    from gubernator_tpu.cluster.health import REGION_OPEN, PeerHealth
    from gubernator_tpu.cluster.multiregion import MultiRegionManager
    from gubernator_tpu.cluster.peer_client import PeerError
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.types import Behavior, PeerInfo, RateLimitReq

    MR = int(Behavior.MULTI_REGION)

    class Node:
        """One federated 'daemon': an applied-hits ledger standing in
        for the engine, plus its region tag."""

        def __init__(self, addr: str, region: str):
            self.addr = addr
            self.region = region
            self.applied: dict = {}
            self._lock = threading.Lock()

        def apply(self, reqs) -> None:
            with self._lock:
                for r in reqs:
                    assert int(r.behavior) & MR == 0, (
                        "forwarded copy must clear MULTI_REGION"
                    )
                    k = r.hash_key()
                    self.applied[k] = self.applied.get(k, 0) + r.hits

        def total(self) -> int:
            with self._lock:
                return sum(self.applied.values())

    class LoopbackPeer:
        """In-process PeerClient stand-in: the fault injector gates
        the send at the same (src, dst) choke point, outcomes feed a
        real PeerHealth breaker."""

        def __init__(self, src: Node, dst: Node):
            self.info = PeerInfo(
                grpc_address=dst.addr, http_address="",
                datacenter=dst.region,
            )
            self._src, self._dst = src, dst
            self.health = PeerHealth(
                dst.addr, failure_threshold=3, backoff=0.1,
                backoff_cap=0.5,
            )

        def send_peer_hits(self, reqs, timeout=None):
            if not self.health.allow():
                raise PeerError(
                    f"circuit open to {self.info.grpc_address}",
                    not_ready=True, circuit_open=True,
                )
            inj = faults.active()
            if inj is not None:
                try:
                    inj.check(self._src.addr, self._dst.addr)
                except faults.FaultError as e:
                    self.health.record_failure()
                    raise PeerError(str(e), not_ready=True) from e
            self._dst.apply(reqs)
            self.health.record_success()

    class Ring:
        def __init__(self, peers):
            self._peers = list(peers)

        def get(self, key):
            # Deterministic per-key owner inside the region.
            return self._peers[sum(key.encode()) % len(self._peers)]

        def peers(self):
            return list(self._peers)

    class Instance:
        def __init__(self, regions):
            self.regions = regions

        def get_region_pickers(self):
            return self.regions

    conf = BehaviorConfig(
        multi_region_sync_wait=0.005,
        multi_region_timeout=0.2,
        multi_region_batch_limit=100,
        multi_region_fanout_deadline=0.5,
        multi_region_requeue_age=20.0,
        multi_region_backoff=0.01,
        multi_region_backoff_cap=0.05,
    )
    east = [Node(f"10.0.0.{i}:81", "east") for i in (1, 2)]
    west = [Node(f"10.0.1.{i}:81", "west") for i in (1, 2)]
    mgrs = {}
    for node, remote_region, remotes in (
        (east[0], "west", west), (east[1], "west", west),
        (west[0], "east", east), (west[1], "east", east),
    ):
        ring = Ring([LoopbackPeer(node, r) for r in remotes])
        mgrs[node.addr] = MultiRegionManager(
            conf, Instance({remote_region: ring})
        )

    def req(key, hits):
        return RateLimitReq(
            name="xr", unique_key=key, hits=hits, limit=10**9,
            duration=3_600_000, behavior=MR,
        )

    inj = faults.install(faults.FaultInjector(seed=3))
    try:
        # -- phase 1: healthy push + converge --------------------------
        mgrs[east[0].addr].queue_hits(req("a", 5))
        mgrs[east[1].addr].queue_hits(req("b", 7))
        for n in east:
            mgrs[n.addr].retry_now()
        assert sum(w.total() for w in west) == 12, [
            w.applied for w in west
        ]
        st = mgrs[east[0].addr].stats()
        assert st["windows"] >= 1 and st["region_sends"] >= 1, st

        # -- phase 2: partition → requeue + open region ----------------
        for e in east:
            for w in west:
                inj.partition(e.addr, w.addr)
                inj.partition(w.addr, e.addr)
        # Keys owned by BOTH west members (region `open` means every
        # member refuses, so both circuits must see failures).
        k_by_owner = {}
        i = 0
        while len(k_by_owner) < 2:
            key = f"p{i}"
            k_by_owner.setdefault(
                sum(f"xr_{key}".encode()) % 2, key
            )
            i += 1
        mgr0 = mgrs[east[0].addr]
        for key in k_by_owner.values():
            mgr0.queue_hits(req(key, 3))
        for _ in range(4):  # breaker threshold 3 → both circuits open
            mgr0.retry_now()
            time.sleep(0.02)
        st = mgr0.stats()
        assert st["hits_requeued"] >= 2, st
        assert st["hits_dropped"] == 0, st
        assert st["region_states"].get("west") == REGION_OPEN, st
        before = sum(w.total() for w in west)
        assert before == 12, "partitioned deltas must not leak through"

        # -- phase 3: heal → converge ----------------------------------
        inj.heal()
        t_heal = time.monotonic()
        deadline = t_heal + 10.0
        while time.monotonic() < deadline:
            mgr0.retry_now()
            if (
                mgr0.pending_retry() == 0
                and sum(w.total() for w in west) == 18
            ):
                break
            time.sleep(0.05)
        converge_s = time.monotonic() - t_heal
        assert sum(w.total() for w in west) == 18, [
            w.applied for w in west
        ]
        assert mgr0.pending_retry() == 0
        assert mgr0.stats()["hits_dropped"] == 0, mgr0.stats()
    finally:
        faults.uninstall()
        for m in mgrs.values():
            m.close()

    elapsed_ms = (time.monotonic() - t0) * 1e3
    print(
        "crossregion smoke OK: 2x2 partition-heal-converge "
        f"(heal->converge {converge_s * 1e3:.0f} ms, 0 dropped) "
        f"in {elapsed_ms:.0f} ms"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

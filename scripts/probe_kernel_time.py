"""Pure device-compute time of the fused bucket step on the live
backend, split by algorithm mix — checks whether int64/f64 emulation
dominates (TPU has no native 64-bit)."""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("GUBERNATOR_TPU_X64", "1")
import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops.bucket_kernel import (
    PACKED_IN_ROWS,
    fused_step,
    make_state,
    multi_fused_step,
    pack_batch_host,
)

CAP = 131072
B = 8192


def mkbuf(algo_val, seed):
    rng = np.random.default_rng(seed)
    slots = np.sort(rng.choice(CAP, B, replace=False)).astype(np.int32)
    n = B
    return pack_batch_host(
        B, 1_000_000 + seed, CAP, slots,
        np.full(n, algo_val, dtype=np.int64),
        np.zeros(n, dtype=np.int64),
        np.ones(n, dtype=np.int64),
        np.full(n, 1_000_000, dtype=np.int64),
        np.full(n, 3_600_000, dtype=np.int64),
        np.full(n, 1_000_000, dtype=np.int64),
        np.zeros(n, dtype=np.int64),
        np.zeros(n, dtype=np.int64),
    )


def chain(name, algo_val):
    state = make_state(CAP)
    bufs = [jnp.asarray(mkbuf(algo_val, s)) for s in range(8)]
    jax.block_until_ready(bufs)
    state, out = fused_step(state, bufs[0])
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    outs = []
    for i in range(20):
        state, out = fused_step(state, bufs[i % 8])
        outs.append(out)
    jax.block_until_ready(state)
    dt = (time.perf_counter() - t0) / 20 * 1e3
    print(f"{name}: fused_step {dt:.2f} ms/step", flush=True)
    del outs, state


chain("token", 0)
chain("leaky", 1)

# multi (scan) at R=16, token-only
state = make_state(CAP)
pins = jnp.asarray(np.stack([mkbuf(0, 100 + s) for s in range(16)]))
jax.block_until_ready(pins)
state, outs = multi_fused_step(state, pins)
jax.block_until_ready(outs)
t0 = time.perf_counter()
for rep in range(4):
    state, outs = multi_fused_step(state, pins)
jax.block_until_ready(state)
print("multi R16 token: %.2f ms/flush (%.2f ms/round)"
      % ((time.perf_counter() - t0) / 4 * 1e3,
         (time.perf_counter() - t0) / 4 / 16 * 1e3), flush=True)

# --- honest scan timing: DISTINCT pins per rep (defeat memoization) ---
state = make_state(CAP)
pin_sets = [
    jnp.asarray(np.stack([mkbuf(0, 1000 * r + s) for s in range(16)]))
    for r in range(4)
]
jax.block_until_ready(pin_sets)
state, outs = multi_fused_step(state, pin_sets[0])
jax.block_until_ready(outs)
t0 = time.perf_counter()
for rep in range(4):
    state, outs = multi_fused_step(state, pin_sets[rep])
jax.block_until_ready(state)
dt = (time.perf_counter() - t0) / 4
print("multi R16 distinct pins: %.2f ms/flush (%.2f ms/round) [h2d prepaid]"
      % (dt * 1e3, dt / 16 * 1e3), flush=True)

# --- same but WITH h2d per flush (engine-realistic) ---
host_sets = [np.stack([mkbuf(0, 5000 * r + s) for s in range(16)])
             for r in range(4)]
t0 = time.perf_counter()
for rep in range(4):
    state, outs = multi_fused_step(state, jnp.asarray(host_sets[rep]))
jax.block_until_ready(state)
dt = (time.perf_counter() - t0) / 4
print("multi R16 +h2d: %.2f ms/flush (%.2f ms/round)"
      % (dt * 1e3, dt / 16 * 1e3), flush=True)

# --- gather+scatter only over the real state arrays (no bucket math) ---
from gubernator_tpu.ops.bucket_kernel import BucketState

def gs_only(state, pins):
    def body(st, pin):
        slot = pin[1]
        leaves = list(st)
        outs = []
        for a in leaves[:5]:
            g = a.at[slot].get(mode="fill", fill_value=0,
                               indices_are_sorted=True, unique_indices=True)
            outs.append(g)
        new = [a.at[slot].set(
                   (o + 1).astype(a.dtype), mode="drop",
                   indices_are_sorted=True, unique_indices=True)
               for a, o in zip(leaves[:5], outs)] + leaves[5:]
        return type(st)(*new), jnp.stack(outs[:5])
    return jax.lax.scan(body, state, pins)

gs_j = jax.jit(gs_only, donate_argnums=(0,))
state2 = make_state(CAP)
state2, outs = gs_j(state2, pin_sets[0])
jax.block_until_ready(outs)
t0 = time.perf_counter()
for rep in range(4):
    state2, outs = gs_j(state2, pin_sets[rep])
jax.block_until_ready(state2)
dt = (time.perf_counter() - t0) / 4
print("scan gather/scatter-only (5 arrays): %.2f ms/flush (%.2f ms/round)"
      % (dt * 1e3, dt / 16 * 1e3), flush=True)

#!/usr/bin/env bash
# ci_fast.sh — the fast correctness + capture gate for one host.
#
# Runs exactly twelve things:
#   1. guberlint (tools/guberlint): fails on static-analysis findings
#      not in the committed guberlint_baseline.json — lock discipline,
#      JAX trace hygiene, thread lifecycle, peer-network discipline,
#      the NATIVE tier (C guard/GIL/blocking/atomics over
#      core/native/*.cpp), the Python<->C CONTRACT (wire layout,
#      decision-plane constants, GUBER_* knobs), knob/metric/doc
#      DRIFT, and PROTO invariant drift (annotations vs the gubercheck
#      property registry vs RESILIENCE.md, STATIC_ANALYSIS.md);
#      findings also land in guberlint.sarif so CI surfaces them as
#      annotations, and the stage is held to a 10 s wall budget so it
#      stays cheap enough to run first; the passes' seeded bad
#      fixtures run inside the tier-1 pytest below
#      (tests/test_guberlint.py);
#   2. the gubercheck smoke (tools/gubercheck --smoke): CHESS-bounded
#      (dpor + preemption_bound=2) interleaving exploration of every
#      protocol scenario over the REAL lease/handoff/replication code,
#      plus both resurrected-bug mutation fixtures (which must be
#      CAUGHT) — jax-free, 30 s wall budget (measured: ~1 s; the
#      exhaustive full-budget explorations are @slow in
#      tests/test_gubercheck.py, STATIC_ANALYSIS.md);
#   3. the trace smoke (scripts/trace_smoke.py): one in-memory-traced
#      decision end-to-end through the real router, asserting a
#      non-empty stitched span tree (root + engine child sharing one
#      trace id) — jax-free, same 10 s wall budget as guberlint;
#   4. the feeder smoke (scripts/feeder_smoke.py): the native
#      columnar feeder's C-packed columns bit-equal to the Python
#      columnar decode for a multi-RPC window, plus the ring window
#      lifecycle and drain-then-close teardown — jax-free, 30 s wall
#      budget (cold .so rebuild included);
#   5. the event-front smoke (scripts/event_front_smoke.py): a few
#      hundred concurrent connections through the epoll reactor plane
#      from the connscale client — zero errors, reactor stages in the
#      event ring, and a non-starved feeder ring wait — jax-free, 30 s
#      wall budget (PERF.md section 26);
#   6. the fused-kernel parity tier (tests/test_fused_parity.py,
#      GUBER_FUSED=interpret, jax CPU only, 120 s wall budget): the
#      Pallas decision kernel bit-equal to models/spec.py + the
#      single-dispatch-per-batch invariant — the kernel stays
#      CI-enforced without TPU hardware (PERF.md section 24);
#   6b. the paged smoke (scripts/paged_smoke.py): the GUBER_PAGED
#      plane's fault-then-hit roundtrip — cold keys past the resident
#      frames fault (counted), spill a victim, and answer from the
#      refilled page with the spilled bucket's exact remaining —
#      jax CPU, 30 s wall budget (PERF.md section 30);
#   7. the replication smoke (tests/test_replication.py promote/demote
#      round trip on a live 3-node cluster): a measured-hot key
#      promotes to replica credit leases, answers go local, cooldown
#      demotes and the credit reconciles — the hot-key adaptive
#      ownership gate (RESILIENCE.md section 11), 120 s wall budget;
#   8. the crossregion smoke (scripts/crossregion_smoke.py): a
#      jax-free 2×2 region×peer loopback harness driven through a
#      full partition-heal-converge arc — failed cross-region deltas
#      re-queue (counted, zero dropped), the region aggregate circuit
#      reads `open`, and the healed region converges — the
#      multi-region federation gate (RESILIENCE.md section 12), 30 s
#      wall budget;
#   9. the obs smoke (scripts/obs_smoke.py): a jax-free 2×2 loopback
#      harness through the fleet rollup merge (all four nodes, real
#      histogram-merged quantiles), a partition that burns the
#      degraded-fraction SLI past its fast-pair factor, and the
#      admission-bound headroom recovering after the heal — the fleet
#      observability gate (OBSERVABILITY.md sections 9-10), 30 s wall
#      budget;
#  10. the tier-1 pytest line from ROADMAP.md (fuzz soaks marked `slow`
#      are excluded so the suite stays inside its 870 s timeout) —
#      includes the chaos fast cases (tests/test_chaos.py:
#      kill/partition/heal invariants; tests/test_membership.py:
#      join/drain/kill-during-handoff reshard invariants;
#      tests/test_multiregion.py: the full-stack 2×2 federation
#      invariants; the multi-cycle soaks are @slow);
#  11. the `fast_capture` bench tier (scripts/bench_all.py): default +
#      latency + herdfast with shortened knobs, writing
#      BENCH_<round>_fast_capture.json with per-config durations.
#
# Usage: scripts/ci_fast.sh [BENCH_ROUND]
#   BENCH_ROUND (or $1) tags the bench artifacts; default "ci".
# Exit code: the pytest result (a failed capture still exits non-zero
# via set -e unless the bench JSON was produced).

set -o pipefail
cd "$(dirname "$0")/.."

ROUND="${1:-${BENCH_ROUND:-ci}}"

echo "=== guberlint (static analysis vs baseline) ===" >&2
LINT_T0=$(date +%s%N)
if ! python -m tools.guberlint --sarif guberlint.sarif; then
  echo "guberlint: NEW findings vs guberlint_baseline.json — fix or" >&2
  echo "suppress with '# guberlint: ok <pass> — <why>' (STATIC_ANALYSIS.md;" >&2
  echo "machine-readable findings in guberlint.sarif)" >&2
  exit 1
fi
LINT_MS=$(( ($(date +%s%N) - LINT_T0) / 1000000 ))
echo "guberlint: ${LINT_MS} ms (budget 10000 ms)" >&2
if [ "${LINT_MS}" -gt 10000 ]; then
  echo "guberlint: blew its 10 s budget — it must stay cheap enough" >&2
  echo "to run as ci_fast stage one; profile the new pass" >&2
  exit 1
fi

echo "=== gubercheck smoke (protocol interleaving exploration) ===" >&2
GCK_T0=$(date +%s%N)
if ! timeout -k 10 60 python -m tools.gubercheck --smoke; then
  echo "gubercheck: a protocol scenario hit an invariant violation /" >&2
  echo "deadlock, or a resurrected-bug mutation went UNCAUGHT — run" >&2
  echo "'python -m tools.gubercheck --scenario <name>' for the repro" >&2
  echo "schedule (tools/gubercheck; STATIC_ANALYSIS.md)" >&2
  exit 1
fi
GCK_MS=$(( ($(date +%s%N) - GCK_T0) / 1000000 ))
echo "gubercheck smoke: ${GCK_MS} ms (budget 30000 ms)" >&2
if [ "${GCK_MS}" -gt 30000 ]; then
  echo "gubercheck smoke blew its 30 s budget — trim the smoke budgets" >&2
  echo "in scenarios.py (CHESS preemption_bound / max_runs), never the" >&2
  echo "scenario itself; the full budgets live in the @slow suite" >&2
  exit 1
fi

echo "=== trace smoke (in-memory stitched tree) ===" >&2
SMOKE_T0=$(date +%s%N)
if ! python scripts/trace_smoke.py; then
  echo "trace smoke: a traced decision no longer yields a stitched" >&2
  echo "span tree (scripts/trace_smoke.py; OBSERVABILITY.md)" >&2
  exit 1
fi
SMOKE_MS=$(( ($(date +%s%N) - SMOKE_T0) / 1000000 ))
echo "trace smoke: ${SMOKE_MS} ms (budget 10000 ms)" >&2
if [ "${SMOKE_MS}" -gt 10000 ]; then
  echo "trace smoke blew its 10 s budget — it must stay jax-free and" >&2
  echo "cheap enough to run before the tier-1 suite" >&2
  exit 1
fi

echo "=== feeder smoke (columnar pack parity + window lifecycle) ===" >&2
FEED_T0=$(date +%s%N)
if ! timeout -k 10 60 python scripts/feeder_smoke.py; then
  echo "feeder smoke: the native columnar feeder's packed columns no" >&2
  echo "longer match the Python columnar decode, or the ring window" >&2
  echo "lifecycle broke (scripts/feeder_smoke.py; PERF.md section 25)" >&2
  exit 1
fi
FEED_MS=$(( ($(date +%s%N) - FEED_T0) / 1000000 ))
echo "feeder smoke: ${FEED_MS} ms (budget 30000 ms)" >&2
if [ "${FEED_MS}" -gt 30000 ]; then
  echo "feeder smoke blew its 30 s budget — it must stay jax-free and" >&2
  echo "cheap enough to gate every native edit (a cold .so rebuild is" >&2
  echo "the only legitimate slow path)" >&2
  exit 1
fi

echo "=== event-front smoke (epoll reactor plane, C10K canary) ===" >&2
EVF_T0=$(date +%s%N)
if ! timeout -k 10 60 python scripts/event_front_smoke.py; then
  echo "event-front smoke: the reactor plane dropped RPCs, starved the" >&2
  echo "serve thread (feeder ring wait p99 over the bar), or broke its" >&2
  echo "teardown contract (scripts/event_front_smoke.py; PERF.md section 26)" >&2
  exit 1
fi
EVF_MS=$(( ($(date +%s%N) - EVF_T0) / 1000000 ))
echo "event-front smoke: ${EVF_MS} ms (budget 30000 ms)" >&2
if [ "${EVF_MS}" -gt 30000 ]; then
  echo "event-front smoke blew its 30 s budget — it must stay jax-free" >&2
  echo "and cheap enough to gate every native edit (a cold .so rebuild" >&2
  echo "is the only legitimate slow path)" >&2
  exit 1
fi

echo "=== fused-kernel parity (Pallas interpret mode, jax CPU) ===" >&2
PAR_T0=$(date +%s%N)
if ! timeout -k 10 180 env JAX_PLATFORMS=cpu GUBER_FUSED=interpret \
  python -m pytest tests/test_fused_parity.py -q -m 'not slow' \
  -p no:cacheprovider -p no:xdist -p no:randomly; then
  echo "fused parity: the Pallas decision kernel diverged from" >&2
  echo "models/spec.py or the single-dispatch invariant broke" >&2
  echo "(tests/test_fused_parity.py; PERF.md section 24)" >&2
  exit 1
fi
PAR_MS=$(( ($(date +%s%N) - PAR_T0) / 1000000 ))
echo "fused parity: ${PAR_MS} ms (budget 120000 ms)" >&2
if [ "${PAR_MS}" -gt 120000 ]; then
  echo "fused parity blew its 120 s wall budget — the interpret-mode" >&2
  echo "kernel must stay cheap enough to gate every commit without" >&2
  echo "TPU hardware" >&2
  exit 1
fi

echo "=== paged smoke (page-table fault-then-hit roundtrip) ===" >&2
PGD_T0=$(date +%s%N)
if ! timeout -k 10 60 env JAX_PLATFORMS=cpu python scripts/paged_smoke.py; then
  echo "paged smoke: the paged state plane stopped translating, lost a" >&2
  echo "spilled bucket across the refill roundtrip, or faulted silently" >&2
  echo "(scripts/paged_smoke.py; PERF.md section 30)" >&2
  exit 1
fi
PGD_MS=$(( ($(date +%s%N) - PGD_T0) / 1000000 ))
echo "paged smoke: ${PGD_MS} ms (budget 30000 ms)" >&2
if [ "${PGD_MS}" -gt 30000 ]; then
  echo "paged smoke blew its 30 s budget — the fault path must stay" >&2
  echo "cheap enough to gate every engine edit on CPU" >&2
  exit 1
fi

echo "=== replication smoke (promote/demote round trip) ===" >&2
REPL_T0=$(date +%s%N)
if ! timeout -k 10 150 env JAX_PLATFORMS=cpu \
  python -m pytest tests/test_replication.py::test_promote_demote_smoke \
  -q -p no:cacheprovider -p no:xdist -p no:randomly; then
  echo "replication smoke: the hot-key promote/demote round trip broke" >&2
  echo "(tests/test_replication.py; RESILIENCE.md section 11)" >&2
  exit 1
fi
REPL_MS=$(( ($(date +%s%N) - REPL_T0) / 1000000 ))
echo "replication smoke: ${REPL_MS} ms (budget 120000 ms)" >&2
if [ "${REPL_MS}" -gt 120000 ]; then
  echo "replication smoke blew its 120 s budget — promotion must engage" >&2
  echo "within seconds on a test-timescale cluster or the plane is" >&2
  echo "too slow to matter in a real flash crowd" >&2
  exit 1
fi

echo "=== crossregion smoke (2x2 partition-heal-converge) ===" >&2
XR_T0=$(date +%s%N)
if ! timeout -k 10 60 python scripts/crossregion_smoke.py; then
  echo "crossregion smoke: the multi-region federation plane dropped" >&2
  echo "deltas, failed to re-queue across a partition, or did not" >&2
  echo "converge after the heal (scripts/crossregion_smoke.py;" >&2
  echo "RESILIENCE.md section 12)" >&2
  exit 1
fi
XR_MS=$(( ($(date +%s%N) - XR_T0) / 1000000 ))
echo "crossregion smoke: ${XR_MS} ms (budget 30000 ms)" >&2
if [ "${XR_MS}" -gt 30000 ]; then
  echo "crossregion smoke blew its 30 s budget — it must stay jax-free" >&2
  echo "and cheap enough to gate every federation-plane edit" >&2
  exit 1
fi

echo "=== obs smoke (fleet rollup + SLO burn + headroom) ===" >&2
OBS_T0=$(date +%s%N)
if ! timeout -k 10 60 python scripts/obs_smoke.py; then
  echo "obs smoke: the fleet rollup stopped merging all nodes, the" >&2
  echo "degraded-fraction SLI no longer burns under a partition, or" >&2
  echo "the admission-bound headroom failed to recover after heal" >&2
  echo "(scripts/obs_smoke.py; OBSERVABILITY.md sections 9-10)" >&2
  exit 1
fi
OBS_MS=$(( ($(date +%s%N) - OBS_T0) / 1000000 ))
echo "obs smoke: ${OBS_MS} ms (budget 30000 ms)" >&2
if [ "${OBS_MS}" -gt 30000 ]; then
  echo "obs smoke blew its 30 s budget — it must stay jax-free and" >&2
  echo "cheap enough to gate every observability-plane edit" >&2
  exit 1
fi

echo "=== tier-1 tests ===" >&2
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
  python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
  -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)" >&2

echo "=== fast_capture bench tier (round ${ROUND}) ===" >&2
BENCH_ROUND="${ROUND}" python scripts/bench_all.py fast_capture || rc=$((rc ? rc : 1))

exit "$rc"

"""Does the transfer API choice change tunnel bandwidth?
h2d: jnp.asarray vs jax.device_put (same 8MB payload).
d2h: cold np.asarray vs copy_to_host_async-then-wait."""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("GUBERNATOR_TPU_X64", "1")
import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

dev = jax.devices()[0]
print("platform:", dev.platform, flush=True)
rng = np.random.default_rng(0)

MB8 = [rng.integers(0, 1 << 30, (16, 16, 8192)).astype(np.int32)
       for _ in range(6)]

# warm
jnp.asarray(MB8[0]).block_until_ready()
jax.device_put(MB8[0], dev).block_until_ready()

t0 = time.perf_counter()
for i in range(6):
    jnp.asarray(MB8[i]).block_until_ready()
print("h2d jnp.asarray 8MB: %.1f ms" % ((time.perf_counter() - t0) / 6 * 1e3),
      flush=True)

t0 = time.perf_counter()
for i in range(6):
    jax.device_put(MB8[i], dev).block_until_ready()
print("h2d device_put 8MB: %.1f ms" % ((time.perf_counter() - t0) / 6 * 1e3),
      flush=True)

# does device_put REALLY move the bytes? consume on device and check
x = jax.device_put(MB8[0], dev)
s = jnp.sum(x.astype(jnp.int64))
t0 = time.perf_counter()
s.block_until_ready()
print("consume after device_put: %.1f ms (sum=%d)" %
      ((time.perf_counter() - t0) * 1e3, int(s)), flush=True)

y = [jax.device_put(MB8[i], dev) for i in range(6)]
t0 = time.perf_counter()
ss = [jnp.sum(v.astype(jnp.int64)) for v in y]
jax.block_until_ready(ss)
print("consume 6x device_put: %.1f ms each" %
      ((time.perf_counter() - t0) / 6 * 1e3), flush=True)

# d2h comparison on 2.6MB [16,5,8192]
from functools import partial


@partial(jax.jit, static_argnums=(1,))
def gen(seed, n):
    return (jnp.arange(n, dtype=jnp.int32) * seed).reshape(16, 5, 8192)


arrs = [gen(jnp.int32(i + 1), 16 * 5 * 8192) for i in range(8)]
jax.block_until_ready(arrs)
np.asarray(arrs[0])
t0 = time.perf_counter()
for i in range(1, 4):
    np.asarray(arrs[i])
print("d2h cold np.asarray 2.6MB: %.1f ms" %
      ((time.perf_counter() - t0) / 3 * 1e3), flush=True)

for i in range(4, 8):
    arrs[i].copy_to_host_async()
t0 = time.perf_counter()
for i in range(4, 8):
    np.asarray(arrs[i])
print("d2h after async prefetch (no wait): %.1f ms" %
      ((time.perf_counter() - t0) / 4 * 1e3), flush=True)

"""Fold the loose BENCH_r*?_*.json artifacts into one committed trend.

Every perf round leaves a pile of per-config artifacts in the repo
root; reading the trajectory of, say, herd p50 across rounds means
opening a dozen files by hand.  This script normalizes them all into

  * BENCH_TREND.json — {config: {round: {value, p50_ms, p99_ms,
    dispatches_per_decision, native_answered, platform, file}}}
  * a config × round markdown table replaced in PERF.md between the
    `<!-- bench-trend:begin -->` / `<!-- bench-trend:end -->` markers
    (appended to the end when absent), so the trajectory is readable
    in one screen.

Naming convention handled: BENCH_r06_cpu_herd.json (round r06, config
herd), BENCH_r04_default.json (no platform tag), BENCH_r01.json (the
round-1 headline wrapper with n/cmd/rc/parsed — config "default").
A/B companions (*_ledger0, *_native0, *_seedbaseline) keep their
suffix as part of the config name so each pair shows as two columns.

Usage: python scripts/bench_trend.py [--check]
  --check: exit 1 if BENCH_TREND.json or the PERF.md table is stale
  (CI can keep the trend honest without rewriting files).
"""

from __future__ import annotations

import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TREND_PATH = os.path.join(ROOT, "BENCH_TREND.json")
PERF_PATH = os.path.join(ROOT, "PERF.md")
BEGIN = "<!-- bench-trend:begin -->"
END = "<!-- bench-trend:end -->"

_NAME = re.compile(r"^BENCH_(r\d+)(?:_cpu)?(?:_(.+))?\.json$")


def _extract(data: dict) -> dict | None:
    """Normalize one artifact's interesting fields; None if it carries
    no measurement (errored runs keep their error string)."""
    if "parsed" in data and "value" not in data:
        # Round-1 wrapper: {n, cmd, rc, tail, parsed}.
        parsed = data.get("parsed")
        if not isinstance(parsed, dict):
            return {"error": f"rc={data.get('rc')}"}
        data = parsed
    if "configs" in data:  # fast_capture combined tier: skip (its
        return None  # sub-configs land as their own artifacts)
    out: dict = {}
    if "value" in data:
        out["value"] = data["value"]
    for k in ("p50_ms", "p99_ms", "platform", "error"):
        if data.get(k) is not None:
            out[k] = data[k]
    led = data.get("ledger")
    if isinstance(led, dict):
        if "dispatches_per_decision" in led:
            out["dispatches_per_decision"] = led["dispatches_per_decision"]
        if led.get("native_answered"):
            out["native_answered"] = led["native_answered"]
    # Dead-peer A/B artifacts (deadpeer mode): fold the health-plane
    # counters + the same-session healthy control so the trend shows
    # availability under failure alongside throughput.
    dead = data.get("dead")
    if isinstance(dead, dict):
        for k in ("errors", "degraded_answers", "backoff_retries"):
            if dead.get(k) is not None:
                out[k] = dead[k]
        if dead.get("requests"):
            out["error_rate"] = round(
                dead.get("errors", 0) / dead["requests"], 4
            )
    healthy = data.get("healthy")
    if isinstance(healthy, dict) and healthy.get("value") is not None:
        out["healthy_value"] = healthy["value"]
        if healthy.get("p99_ms") is not None:
            out["healthy_p99_ms"] = healthy["p99_ms"]
    # Reshard A/B artifacts (reshard mode): fold the membership-plane
    # counters so the trend shows live-resharding cost alongside
    # throughput (handoff rows shipped/forfeited/received, dual-ring
    # window time, and the end-to-end error rate under the reshard).
    mem = data.get("membership")
    if isinstance(mem, dict):
        hoff = mem.get("handoff") or {}
        for k in ("shipped", "forfeited", "received"):
            if hoff.get(k) is not None:
                out[f"handoff_{k}"] = hoff[k]
        if mem.get("dual_seconds") is not None:
            out["dual_seconds"] = mem["dual_seconds"]
        if data.get("errors") is not None and data.get("requests"):
            out["error_rate"] = round(
                data["errors"] / data["requests"], 4
            )
    # Device-plane fused A/B artifacts (devfused mode): fold the
    # unfused arm, the median pair delta, and each arm's device
    # dispatches/batch — the fused steady state must read 1.0.
    if data.get("fused_delta_pct") is not None:
        if data.get("unfused_value") is not None:
            out["unfused_value"] = data["unfused_value"]
        out["fused_delta_pct"] = data["fused_delta_pct"]
        if data.get("fused_mode") is not None:
            out["fused_mode"] = data["fused_mode"]
    if data.get("dispatches_per_batch") is not None:
        out["dispatches_per_batch"] = data["dispatches_per_batch"]
    if data.get("dispatches_per_batch_unfused") is not None:
        out["dispatches_per_batch_unfused"] = data[
            "dispatches_per_batch_unfused"
        ]
    # Columnar feeder artifacts (feeder mode): fold the pack line vs
    # the Python columnar line, plus the front A/B's queue-wait p99
    # per ingest path — the §23→§25 tail trajectory.
    if data.get("python_line_rows_per_s") is not None:
        out["python_line_rows_per_s"] = data["python_line_rows_per_s"]
        if data.get("pack_speedup") is not None:
            out["pack_speedup"] = data["pack_speedup"]
        ab = data.get("front_ab")
        if isinstance(ab, dict):
            for k in (
                "window_wait_p99_ms_off",
                "feeder_ring_wait_p99_ms_on",
                "feeder_ring_wait_p99_ms_light",
            ):
                if ab.get(k) is not None:
                    out[k] = ab[k]
    # Connection-scale artifacts (connscale mode): fold the conns
    # held, the reactor-front stage attribution (feeder ring wait p99
    # under client load — the §26 starvation acceptance), and the
    # event-vs-threaded equal-load delta with its fd footprint.
    if data.get("conns_held") is not None:
        out["conns_held"] = data["conns_held"]
        if data.get("ring_wait_p99_ms_top") is not None:
            out["ring_wait_p99_ms"] = data["ring_wait_p99_ms_top"]
        if data.get("errors") is not None:
            out["errors"] = data["errors"]
        ab = data.get("ab_equal_load")
        if isinstance(ab, dict):
            if ab.get("event_delta_pct") is not None:
                out["event_delta_pct"] = ab["event_delta_pct"]
            if ab.get("threaded_rate") is not None:
                out["threaded_rate"] = ab["threaded_rate"]
        rungs = data.get("rungs")
        if isinstance(rungs, list) and rungs:
            top = rungs[-1]
            if top.get("server_fd_peak") is not None:
                out["server_fd_peak"] = top["server_fd_peak"]
            if top.get("reactors") is not None:
                out["reactors"] = top["reactors"]
    # Flash-crowd replication artifacts (flashcrowd mode): fold the
    # hot-set-rotation p99 vs steady p99 (the flat-while-moving bar),
    # the replica-answered count, and the canary key's measured
    # over-admission against the N_replicas x lease bound.
    if data.get("rotation_p99_ms") is not None:
        out["rotation_p99_ms"] = data["rotation_p99_ms"]
        if data.get("steady_p99_ms") is not None:
            out["steady_p99_ms"] = data["steady_p99_ms"]
        if data.get("rotation_over_steady") is not None:
            out["rotation_over_steady"] = data["rotation_over_steady"]
        repl = data.get("replication")
        if isinstance(repl, dict):
            if repl.get("answered") is not None:
                out["replicated_answered"] = repl["answered"]
            if repl.get("promoted") is not None:
                out["keys_promoted"] = repl["promoted"]
        can = data.get("canary")
        if isinstance(can, dict) and can.get("over_admission") is not None:
            out["over_admission"] = can["over_admission"]
            out["over_admission_bound"] = can.get("bound")
        if data.get("errors") is not None:
            out["errors"] = data["errors"]
    # Multi-region federation artifacts (crossregion mode): fold the
    # partitioned phase's error rate + degraded-region answers (the
    # 0-errors acceptance), the drift canary's over-admission against
    # its N_regions x limit bound, the post-heal convergence seconds,
    # and the requeue drop count (0 inside the age cap).
    if data.get("heal_convergence_s") is not None:
        out["heal_convergence_s"] = data["heal_convergence_s"]
        part = data.get("partitioned")
        if isinstance(part, dict):
            if part.get("requests"):
                out["error_rate"] = round(
                    part.get("errors", 0) / part["requests"], 4
                )
            if part.get("degraded_region_answers") is not None:
                out["degraded_region_answers"] = part[
                    "degraded_region_answers"
                ]
        can = data.get("canary")
        if isinstance(can, dict) and can.get("over_admission") is not None:
            out["over_admission"] = can["over_admission"]
            out["over_admission_bound"] = can.get("bound")
        if data.get("hits_dropped") is not None:
            out["multiregion_hits_dropped"] = data["hits_dropped"]
    # Multi-node stage budgets: artifacts captured since the PR 15
    # histogram-merge fix carry real cross-node merged p50/p99 per
    # stage (bench.py _stage_budget_diff diffs and merges the nodes'
    # gubernator_stage_seconds buckets); older artifacts folded
    # per-node count/sum into means — the means-of-means lie.  Mark
    # every row so legacy numbers read as the means they are, not as
    # quantiles.
    sb = data.get("stage_budget_ms")
    if isinstance(sb, dict) and sb:
        legacy = not any(
            isinstance(v, dict) and "p99_ms" in v for v in sb.values()
        )
        out["stage_budget_kind"] = (
            "per-node means (legacy)" if legacy else "merged quantiles"
        )
    # Fleet observability A/B artifacts (fleetobs mode): fold the
    # off arm + median pair delta (the < 2% acceptance bar), the live
    # SLO burn-rate / admission-bound headroom columns
    # (gubernator_slo_burn_rate / gubernator_invariant_headroom as
    # measured during the run), and the rollup's scrape coverage.
    if data.get("fleetobs_delta_pct") is not None:
        out["fleetobs_off_value"] = data.get("fleetobs_off_value")
        out["fleetobs_delta_pct"] = data["fleetobs_delta_pct"]
        slo = data.get("slo")
        if isinstance(slo, dict):
            if slo.get("max_burn") is not None:
                out["slo_max_burn"] = slo["max_burn"]
            if slo.get("breaches") is not None:
                out["slo_breaches"] = slo["breaches"]
        can = data.get("canary")
        if isinstance(can, dict) and can.get("headroom") is not None:
            out["invariant_headroom"] = can["headroom"]
            out["invariant_bound"] = can.get("bound")
        fl = data.get("fleet")
        if isinstance(fl, dict) and fl.get("scrape_ok") is not None:
            out["fleet_scrape_ok"] = fl["scrape_ok"]
    # Paged-state artifacts (zipfpaged mode): fold the fault economy
    # (fault rate, spill p99), the residency footprint, and the hot
    # A/B against the dense arm (the ≤10% acceptance bar), so the
    # trend shows what serving 10x the resident key space costs.
    pg = data.get("paged")
    if isinstance(pg, dict):
        for src, dst in (
            ("fault_rate", "fault_rate"),
            ("spill_p99_ms", "spill_p99_ms"),
            ("resident_ratio", "resident_ratio"),
            ("keyspace_ratio", "keyspace_ratio"),
        ):
            if pg.get(src) is not None:
                out[dst] = pg[src]
        hot = data.get("hot")
        if isinstance(hot, dict):
            if hot.get("delta_pct") is not None:
                out["hot_delta_pct"] = hot["delta_pct"]
            if hot.get("dense_value") is not None:
                out["hot_dense_value"] = hot["dense_value"]
        dense = data.get("dense")
        if isinstance(dense, dict) and dense.get("churn_value") is not None:
            out["dense_churn_value"] = dense["churn_value"]
    # Tracing A/B artifacts (herdtrace mode): fold the off-arm value,
    # the delta (the < 2% acceptance bar), and the event-ring drop
    # count so the trend shows observability's cost alongside its
    # coverage.
    if data.get("tracing_delta_pct") is not None:
        out["tracing_off_value"] = data.get("tracing_off_value")
        out["tracing_delta_pct"] = data["tracing_delta_pct"]
    ev = data.get("native_events")
    if isinstance(ev, dict):
        ring = ev.get("ring") or {}
        if ring.get("dropped") is not None:
            out["ring_dropped"] = ring["dropped"]
        if ring.get("written") is not None:
            out["ring_written"] = ring["written"]
    return out or None


def collect() -> dict:
    trend: dict[str, dict] = {}
    for name in sorted(os.listdir(ROOT)):
        m = _NAME.match(name)
        if m is None:
            continue
        rnd, config = m.group(1), m.group(2) or "default"
        if config == "fast_capture":
            continue
        try:
            with open(os.path.join(ROOT, name)) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        row = _extract(data)
        if row is None:
            continue
        row["file"] = name
        trend.setdefault(config, {})[rnd] = row
    return trend


def _fmt(row: dict | None) -> str:
    if row is None:
        return "–"
    if "value" not in row:
        return "err"
    v = row["value"]
    val = f"{v / 1000:.1f}k" if v >= 10_000 else f"{v:g}"
    parts = [val]
    if row.get("p50_ms") is not None:
        parts.append(f"p50 {row['p50_ms']:g}")
    if row.get("dispatches_per_decision") is not None:
        parts.append(f"d/d {row['dispatches_per_decision']:g}")
    if row.get("dispatches_per_batch") is not None:
        parts.append(f"d/b {row['dispatches_per_batch']:g}")
    return " · ".join(parts)


def render_table(trend: dict) -> str:
    rounds = sorted({r for cfg in trend.values() for r in cfg})
    lines = [
        BEGIN,
        "",
        "### Bench trend (generated by `scripts/bench_trend.py` from "
        "the committed `BENCH_*` artifacts — dec/s · p50 ms · "
        "dispatches/decision; `–` = not captured that round)",
        "",
        "| config | " + " | ".join(rounds) + " |",
        "|---| " + " | ".join("---" for _ in rounds) + " |",
    ]
    for config in sorted(trend):
        cells = [_fmt(trend[config].get(r)) for r in rounds]
        lines.append(f"| {config} | " + " | ".join(cells) + " |")
    lines += ["", END]
    return "\n".join(lines)


def splice_perf(table: str) -> str:
    with open(PERF_PATH) as f:
        text = f.read()
    if BEGIN in text and END in text:
        pre = text[: text.index(BEGIN)]
        post = text[text.index(END) + len(END):]
        return pre + table + post
    return text.rstrip("\n") + "\n\n" + table + "\n"


def main() -> int:
    check = "--check" in sys.argv[1:]
    trend = collect()
    trend_json = json.dumps(trend, indent=1, sort_keys=True) + "\n"
    perf_text = splice_perf(render_table(trend))
    if check:
        try:
            with open(TREND_PATH) as f:
                current = f.read()
        except OSError:
            current = ""
        with open(PERF_PATH) as f:
            perf_current = f.read()
        if current != trend_json or perf_current != perf_text:
            print(
                "bench trend stale: run python scripts/bench_trend.py",
                file=sys.stderr,
            )
            return 1
        return 0
    with open(TREND_PATH, "w") as f:
        f.write(trend_json)
    with open(PERF_PATH, "w") as f:
        f.write(perf_text)
    print(
        f"BENCH_TREND.json: {len(trend)} configs, "
        f"{sum(len(v) for v in trend.values())} captures"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

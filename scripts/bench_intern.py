"""Host interning ceiling (VERDICT r3 #5): native table schedule()
throughput vs capacity — 131k / 8M / 100M slots — for both the
miss/insert and the steady-state hit case, plus the share of a full
packed-step dispatch the intern pass costs at batch 8192.

Prints one JSON line; PERF.md carries the table.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("GUBERNATOR_TPU_X64", "1")

# Host-side benchmark: never let the (possibly wedged) axon backend
# initialize — the intern table is pure C++/numpy.
from gubernator_tpu.platform_guard import force_cpu_platform

force_cpu_platform(1)

import numpy as np

from gubernator_tpu.core.native import make_intern_table

B = 8192
res = {}

for cap in (1 << 17, 1 << 23, 100_000_000):
    table = make_intern_table(cap)
    if not hasattr(table, "schedule"):
        res[f"cap{cap}"] = "python-fallback"
        continue
    # Fill to ~60% of capacity or 2M keys, whichever is smaller
    # (bounded run time; probe batches then measure against the
    # populated table).
    fill = min(int(cap * 0.6), 2_000_000)
    t_fill0 = time.perf_counter()
    for lo in range(0, fill, B):
        keys = [b"ik%d" % i for i in range(lo, min(lo + B, fill))]
        table.schedule(keys, 1_000_000)
    fill_dt = time.perf_counter() - t_fill0
    res[f"cap{cap}_fill_keys_per_s"] = round(fill / fill_dt, 0)

    # Steady-state HIT case: re-schedule known keys.
    rng = np.random.default_rng(0)
    batches = [
        [b"ik%d" % i for i in rng.integers(0, fill, B)] for _ in range(8)
    ]
    t0 = time.perf_counter()
    n_it = 24
    for i in range(n_it):
        table.schedule(batches[i % 8], 2_000_000)
    hit_dt = (time.perf_counter() - t0) / n_it
    res[f"cap{cap}_hit_us_per_key"] = round(hit_dt / B * 1e6, 3)
    res[f"cap{cap}_hit_keys_per_s"] = round(B / hit_dt, 0)

# Intern share of the serving step at the default bench shape:
# measured packed-step wall (BENCH/PROFILE artifacts) vs intern pass.
# Only meaningful when the NATIVE table was measured — the Python
# fallback records no timing and must not masquerade as free.
if "cap131072_hit_us_per_key" in res:
    intern_ms = res["cap131072_hit_us_per_key"] * B / 1e3
    res["intern_ms_per_8192_batch_cap131072"] = round(intern_ms, 3)

print(json.dumps(res))

"""d2h readback strategy probe: rows/dtype scaling + device-side
stacking of K step outputs into ONE transfer (the readback combiner
design candidate).  Prints one JSON."""
from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("GUBERNATOR_TPU_X64", "1")
import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

res: dict = {}


def report(k, v):
    res[k] = round(v, 4) if isinstance(v, float) else v
    print(f"{k}: {res[k]}", file=sys.stderr, flush=True)


@partial(jax.jit, static_argnums=(1, 2))
def gen(seed, rows, b):
    return (
        jnp.arange(rows * b, dtype=jnp.int32).reshape(rows, b) * seed
    )


def main():
    dev = jax.devices()[0]
    report("platform", dev.platform)
    B = 8192

    # Warm the d2h path overall (first transfer pays extra).
    np.asarray(gen(jnp.int32(7), 5, B))

    # --- d2h vs rows at fixed B ---
    for rows in (1, 2, 5, 10, 40):
        arrs = [gen(jnp.int32(i + 1), rows, B) for i in range(6)]
        jax.block_until_ready(arrs)
        np.asarray(arrs[0])  # per-shape warmup
        t0 = time.perf_counter()
        for i in range(12):
            np.asarray(arrs[i % 6])
        report(f"d2h_rows{rows}_ms", (time.perf_counter() - t0) / 12 * 1e3)

    # --- K separate [5,B] transfers vs ONE stacked [K*5,B] ---
    for K in (4, 8, 16):
        arrs = [gen(jnp.int32(i + 1), 5, B) for i in range(K)]
        jax.block_until_ready(arrs)
        t0 = time.perf_counter()
        for a in arrs:
            np.asarray(a)
        sep = (time.perf_counter() - t0) * 1e3
        report(f"d2h_K{K}_separate_ms", sep)

        stack_j = jax.jit(lambda *xs: jnp.concatenate(xs, axis=0))
        st = stack_j(*arrs)
        st.block_until_ready()
        np.asarray(st)  # shape warmup
        st2 = stack_j(*[gen(jnp.int32(i + 31), 5, B) for i in range(K)])
        st2.block_until_ready()
        t0 = time.perf_counter()
        np.asarray(st2)
        one = (time.perf_counter() - t0) * 1e3
        report(f"d2h_K{K}_stacked_ms", one)

    # --- dtype check: float32 vs int32 vs int64 on [5,B] ---
    for dt, name in ((jnp.float32, "f32"), (jnp.int64, "i64")):
        arrs = [
            (gen(jnp.int32(i + 3), 5, B)).astype(dt) for i in range(6)
        ]
        jax.block_until_ready(arrs)
        np.asarray(arrs[0])
        t0 = time.perf_counter()
        for i in range(12):
            np.asarray(arrs[i % 6])
        report(f"d2h_5rows_{name}_ms", (time.perf_counter() - t0) / 12 * 1e3)

    # --- does copy_to_host_async prefetch make np.asarray cheap? ---
    arrs = [gen(jnp.int32(i + 11), 5, B) for i in range(8)]
    jax.block_until_ready(arrs)
    for a in arrs:
        a.copy_to_host_async()
    time.sleep(1.0)  # let the background transfers finish (if real)
    t0 = time.perf_counter()
    for a in arrs:
        np.asarray(a)
    report("d2h_after_async_prefetch_each_ms",
           (time.perf_counter() - t0) / 8 * 1e3)

    # --- full pipeline with stacked flush every K=8 steps ---
    cap = 1 << 21

    def step(stmat, pin):
        slot = pin[0]
        rows = stmat.at[slot].get(mode="fill", fill_value=0,
                                  indices_are_sorted=True,
                                  unique_indices=True)
        upd = rows + pin[3][:, None]
        newm = stmat.at[slot].set(upd, mode="drop",
                                  indices_are_sorted=True,
                                  unique_indices=True)
        return newm, jnp.stack([upd[:, i] for i in range(5)])

    step_j = jax.jit(step, donate_argnums=(0,))
    rng = np.random.default_rng(0)
    stmat = jax.device_put(jnp.zeros((cap, 20), jnp.int32), dev)
    ins = []
    for i in range(8):
        a = np.zeros((15, B), np.int32)
        a[0] = np.sort(rng.choice(cap, B, replace=False)).astype(np.int32)
        a[3] = 1
        ins.append(a)
    K = 8
    stack_j = jax.jit(lambda *xs: jnp.concatenate(xs, axis=0))
    stmat, out = step_j(stmat, jnp.asarray(ins[0]))
    np.asarray(stack_j(*[out] * K))  # warm both programs
    NIT = 64
    t0 = time.perf_counter()
    pend = []
    for i in range(NIT):
        stmat, out = step_j(stmat, jnp.asarray(ins[i % 8]))
        pend.append(out)
        if len(pend) == K:
            st = stack_j(*pend)
            st.copy_to_host_async()
            pend = [st]  # keep handle; flush next round reads it
            np.asarray(st)
            pend = []
    dt = (time.perf_counter() - t0) / NIT
    report("step_stackedK8_ms", dt * 1e3)
    report("step_stackedK8_decs_per_s", B / dt)

    print(json.dumps(res))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Event-front smoke: one jax-free pass over the epoll reactor plane
(PERF.md §26), cheap enough to gate every commit (ci_fast stage; wall
budget enforced by the caller).

Drives a few hundred concurrent connections — mostly idle, a closed
active loop on the rest — from the epoll connscale client through a
raw C server running the reactor front with the columnar feeder and
the event ring attached, and asserts:

  1. every connection establishes and survives; ZERO errors end to
     end (transport and grpc);
  2. the serve plane is NOT starved by connection handling: the
     feeder ring wait p99 stays well under the 46 ms starved baseline
     (PERF.md §25) — the §26 acceptance surface;
  3. reactor stages (reactor_wake / reactor_read) actually flow
     through the event ring;
  4. teardown drains cleanly (detach → feeder stop → h2s_stop).

The deep coverage lives in tests/test_h2_event_front.py and the TSan
stress; this is the canary that the reactor protocol still lines up
after any native edit.
"""

import ctypes
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from gubernator_tpu.net import h2_fast


def _payload(key):
    def varint(v):
        out = b""
        while v >= 0x80:
            out += bytes([(v & 0x7F) | 0x80])
            v >>= 7
        return out + bytes([v])

    def field(tag, wt, payload):
        return bytes([(tag << 3) | wt]) + payload

    name = b"evsmoke"
    item = (
        field(1, 2, varint(len(name)) + name)
        + field(2, 2, varint(len(key)) + key)
        + field(3, 0, varint(1))
        + field(4, 0, varint(10**9))
        + field(5, 0, varint(60_000))
    )
    return field(1, 2, varint(len(item)) + item)


def main() -> int:
    lib = h2_fast.load()
    if lib is None:
        print("event-front smoke: native h2 server unavailable; skipping")
        return 0
    from gubernator_tpu.core import h2_client
    from gubernator_tpu.core.native_plane import NativeColumnarFeeder
    from gubernator_tpu.utils.native_events import STAGES

    if h2_client.load() is None:
        print("event-front smoke: native h2 client unavailable; skipping")
        return 0

    served = [0]

    def feeder_window(slot, n_rows, n_rpcs, key_bytes):
        served[0] += n_rows
        slot.out_status[:n_rows] = 0
        slot.out_limit[:n_rows] = 100
        slot.out_remaining[:n_rows] = 99
        slot.out_reset[:n_rows] = 0
        slot.rpc_status[:n_rpcs] = 0
        return 0

    def window(buf, length, counts_ptr, lens_ptr, n_rpcs, total, out_ptr,
               status_ptr):
        # Byte-window fallback (ring pressure): flat UNDER_LIMIT.
        n, nr = int(total), int(n_rpcs)
        if nr > 0 and status_ptr:
            np.ctypeslib.as_array(
                ctypes.cast(status_ptr, ctypes.POINTER(ctypes.c_int64)),
                shape=(nr,),
            )[:] = 0
        if n > 0 and out_ptr:
            cols = np.ctypeslib.as_array(
                ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_int64)),
                shape=(4 * n,),
            )
            cols[:n] = 0
            cols[n : 2 * n] = 100
            cols[2 * n : 3 * n] = 99
            cols[3 * n :] = 0
        return 0

    cb = h2_fast._CALLBACK(window)
    # Event front: 2 reactors, no idle timeout (the idle holders must
    # survive the run).
    handle = lib.h2s_start(0, 1000, 16384, 4096, 0, 1, 2, 0, cb)
    assert handle, "event front failed to bind"
    ring = lib.evr_create(65536)
    feeder = NativeColumnarFeeder(
        n_slots=4, max_rows=2048, window_s=0.001, flush_rows=256,
        window_handler=feeder_window,
    )
    try:
        lib.h2s_attach_feeder(handle, feeder.handle)
        if ring:
            lib.h2s_attach_ring(handle, ctypes.c_void_p(ring))
            feeder.attach_ring(ctypes.c_void_p(ring))
        port = int(lib.h2s_port(handle))
        res = h2_client.connscale(
            f"127.0.0.1:{port}", "/pb.gubernator.V1/GetRateLimits",
            _payload(b"smoke_key_1"), 2.0, 300, 24, threads=1,
            ramp_budget_s=20.0,
        )
        assert res is not None, "connscale client could not connect"
        assert res["connected"] == 300, res
        assert res["alive_at_end"] == 300, res
        assert res["errors"] == 0, res
        assert res["rpcs"] > 100, res
        stats = np.zeros(16, dtype=np.int64)
        lib.h2s_stats(handle, stats.ctypes.data_as(ctypes.c_void_p))
        assert stats[2] == 0, f"server errors: {stats[2]}"
        assert stats[9] == 2, f"reactors: {stats[9]}"

        # Ring attribution: reactor stages present; the serve plane
        # (feeder ring wait) not starved.  Bar: 25 ms — the starved
        # §25 baseline was 46 ms; a healthy reactor run on this box
        # sits in single-digit ms.
        by_stage = {}
        if ring:
            out = np.zeros(4 * 65536, dtype=np.int64)
            n = int(
                lib.evr_drain(
                    ctypes.c_void_p(ring),
                    out.ctypes.data_as(ctypes.c_void_p), 65536,
                )
            )
            rec = out[: 4 * n].reshape(n, 4)
            for kind, stage in STAGES.items():
                durs = rec[rec[:, 0] == kind][:, 2]
                if len(durs):
                    by_stage[stage] = (
                        len(durs),
                        float(np.percentile(durs, 99)) / 1e6,
                    )
            assert "reactor_wake" in by_stage, sorted(by_stage)
            assert "reactor_read" in by_stage, sorted(by_stage)
            if "feeder_ring_wait" in by_stage:
                p99_ms = by_stage["feeder_ring_wait"][1]
                assert p99_ms <= 25.0, (
                    f"feeder ring wait p99 {p99_ms:.1f} ms — the serve "
                    "plane looks starved (the §25 regression)"
                )
    finally:
        lib.h2s_attach_feeder(handle, None)
        feeder.stop()
        if ring:
            lib.h2s_attach_ring(handle, None)
        lib.h2s_stop(handle)
        feeder.close()
        if ring:
            lib.evr_free(ctypes.c_void_p(ring))
    stages = {
        s: (n, round(p, 2)) for s, (n, p) in sorted(by_stage.items())
    }
    print(
        "event-front smoke: 300 conns, %d rpcs, 0 errors, stages %s"
        % (res["rpcs"], stages)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

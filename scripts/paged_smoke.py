#!/usr/bin/env python
"""Paged-state smoke: one fast pass over the GUBER_PAGED plane's
load-bearing contract (ci_fast stage; 30 s wall budget enforced by
the caller, jax on CPU — interpret-mode engine, no TPU).

Asserts, in order:
  1. a paged engine boots with device capacity = frames x page_size
     while interning at the full logical capacity;
  2. fault-then-hit roundtrip: keys past the resident budget fault
     (counted — never silent), spill a victim page, and answer with
     the SAME remaining sequence a dense engine produces;
  3. an evicted key's bucket survives the spill→refill roundtrip
     bit-exactly (the re-hit debits the spilled remaining, not a
     fresh bucket);
  4. resident re-hits after the roundtrip pay zero additional faults.

The deep coverage (spec parity fuzz, TTL boundaries, restore,
host-side sweep) lives in tests/test_paged_state.py and the
three-way harness in tests/test_fused_parity.py; this is the canary
that the page table still translates and the fault path still
counts after any engine/kernel edit.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["GUBER_PAGED"] = "1"
os.environ["GUBER_PAGE_SIZE"] = "16"
os.environ["GUBER_PAGED_RESIDENT"] = "4"
os.environ["GUBER_FUSED"] = "interpret"
os.environ["GUBER_PUMP"] = "0"

import numpy as np


def main() -> int:
    from gubernator_tpu.clock import Clock
    from gubernator_tpu.core.engine import DecisionEngine
    from gubernator_tpu.types import RateLimitReq

    clock = Clock().freeze()
    eng = DecisionEngine(capacity=1024, clock=clock)
    assert eng.paging is not None, "GUBER_PAGED=1 must build the plane"
    assert eng.capacity == 64, eng.capacity  # 4 frames x 16 rows
    assert eng.logical_capacity == 1024

    def hit(lo, hi, expect_remaining):
        reqs = [
            RateLimitReq(
                name="pg", unique_key=str(i), hits=1, limit=10,
                duration=600_000,
            )
            for i in range(lo, hi)
        ]
        rs = eng.get_rate_limits(reqs, now_ms=clock.now_ms())
        bad = [
            (i, r.status, r.remaining)
            for i, r in zip(range(lo, hi), rs)
            if r.error or r.remaining != expect_remaining
        ]
        assert not bad, bad[:5]

    # 1+2. Key space 3x the resident rows: first contact fills the
    # frames, the tail faults — every fault counted, zero errors.
    hit(0, 192, expect_remaining=9)
    f1 = eng.paging.faults
    assert f1 > 0, "cold tail past the frames must fault"
    assert eng.paging.spills > 0
    assert eng.paging.refills == f1
    assert eng.paging.fault_duration.count == f1

    # 3. Fault-then-hit roundtrip: the first keys' pages went cold;
    # re-hitting them must refill the SPILLED bucket (remaining 9→8),
    # not create a fresh one.
    assert not eng.paging.is_resident(0), "slot 0 should have spilled"
    clock.advance(ms=5)
    hit(0, 32, expect_remaining=8)
    assert eng.paging.faults > f1

    # 4. Resident re-hits are fault-free.
    f2 = eng.paging.faults
    clock.advance(ms=5)
    hit(0, 32, expect_remaining=7)
    assert eng.paging.faults == f2, "resident re-hit must not fault"

    print(
        "paged smoke ok: faults=%d spills=%d refills=%d "
        "fault_p99_ms=%.3f" % (
            eng.paging.faults, eng.paging.spills, eng.paging.refills,
            eng.paging.fault_duration.p99() * 1000.0,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

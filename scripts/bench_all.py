"""Run every BASELINE benchmark config and write BENCH_<round>_*.json.

Configs (BASELINE.md / BASELINE.json):
  1. default  — token+leaky mixed, 100k keys, single chip (headline)
  2. leaky1m  — leaky bucket, 1M keys, batch 1000
  3. global4  — GLOBAL behavior, 4-node in-process cluster
  4. zipf     — mixed algos, Zipf-skewed keys over a large space
  wire        — loopback gRPC at the serving window (p99 SLO evidence)

Each config is one bench.py subprocess (fresh backend; a wedged run
cannot poison the next) with its knobs passed via env.  Artifacts land
in the repo root as BENCH_<round>_<name>.json where <round> comes from
BENCH_ROUND (default "r04").

Usage: python scripts/bench_all.py [name ...]   (default: all)
       python scripts/bench_all.py fast_capture
         — the under-3-minute combined tier (default+latency+herdfast
           with shortened knobs) writing BENCH_<round>_fast_capture.json
           with per-config capture durations (VERDICT r5 #1).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUND = os.environ.get("BENCH_ROUND", "r05")

CONFIGS: dict[str, dict] = {
    "default": {},
    "leaky1m": {
        "BENCH_ALGO": "leaky",
        "BENCH_KEYS": "1000000",
        "BENCH_CAPACITY": str(1 << 21),
        "BENCH_BATCH": "8192",
    },
    "global4": {
        "BENCH_MODE": "global",
        "BENCH_NODES": "4",
        "BENCH_KEYS": "100000",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_BATCH": "1000",
        # Steady-state measurement: the first seconds of GLOBAL load
        # are cold XLA compiles + first-window flush bursts; p99 over
        # a 5s window was dominated by them (PERF.md §15).
        "BENCH_WARM_SECONDS": "5",
        "BENCH_SECONDS": "10",
    },
    # GLOBAL's design case: HOT keys, where non-owners answer from the
    # owner-broadcast status cache (reference: architecture.md:46-74).
    # The wide-keyspace variant above defeats that cache by design.
    "global4hot": {
        "BENCH_MODE": "global",
        "BENCH_NODES": "4",
        "BENCH_KEYS": "1000",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_BATCH": "1000",
        "BENCH_WARM_SECONDS": "5",
        "BENCH_SECONDS": "10",
    },
    "zipf": {
        "BENCH_ZIPF": "1.2",
        "BENCH_KEYS": "100000000",
        "BENCH_CAPACITY": str(1 << 24),  # hot working set; full 100M
        # slots is a 7.6GB HBM budget question, answered in PERF.md §8
        "BENCH_BATCH": "8192",
    },
    "wire": {
        "BENCH_MODE": "wire",
        "BENCH_BATCH": "1000",
        "BENCH_KEYS": "100000",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_WIRE_PROCS": "4",
    },
    # Single-client baseline for the lock-split criterion (VERDICT r3
    # #3): multi-client p50 within ~1.5x of this proves host
    # scheduling overlaps device work.  Only meaningful where the
    # server has idle host capacity (TPU); on the one-core CPU host
    # closed-loop p50 scales with concurrency by queueing physics.
    "wire1": {
        "BENCH_MODE": "wire",
        "BENCH_BATCH": "1000",
        "BENCH_KEYS": "100000",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_WIRE_PROCS": "1",
    },
    # Wire-max batch through the native h2 fast front: the front's
    # throughput shape at batch 1000 (the herd configs measure batch 1).
    "wirefast": {
        "BENCH_MODE": "wire",
        "BENCH_BATCH": "1000",
        # The native client replays ONE payload, so exactly batch-many
        # keys are exercised (the metric label says so too).
        "BENCH_KEYS": "1000",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_WIRE_FAST": "1",
        # The group-commit window exists for tiny RPCs; at the
        # wire-max batch it only adds latency — run it near zero.
        "BENCH_LOCAL_BATCH_WAIT": "0.0002",
    },
    # Device decision plane fused/unfused A/B (ISSUE 10): the fused
    # single-dispatch step vs GUBER_FUSED=split, alternating pairs,
    # median of per-pair deltas; carries dispatches/batch per arm.
    "devfused": {
        "BENCH_MODE": "devfused",
        "BENCH_KEYS": "100000",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_BATCH": "8192",
    },
    # Thundering herd: 32 concurrent clients, one hot key, single-item
    # RPCs (reference: benchmark_test.go thundering-herd subtest).
    "herd": {
        "BENCH_MODE": "herd",
        "BENCH_KEYS": "1",
        "BENCH_CAPACITY": str(1 << 17),
    },
    # Same herd served through the native h2 fast front
    # (net/h2_fast.py): C-side framing + group commit, one Python
    # entry per window — the grpc-python per-RPC wall removed.
    "herdfast": {
        "BENCH_MODE": "herd",
        "BENCH_KEYS": "1",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_HERD_FAST": "1",
    },
    # The herd through the fast front's NATIVE DECISION PLANE: hot-key
    # single-item RPCs answered inside the C connection threads — zero
    # GIL, zero Python frames (core/native/decision_plane.cpp).  The
    # same-session A/B is GUBER_NATIVE_LEDGER=0 over this config.
    "herdnative": {
        "BENCH_MODE": "herdnative",
        "BENCH_KEYS": "1",
        "BENCH_CAPACITY": str(1 << 17),
    },
    # Flash crowd through the hot-key replication plane (ISSUE 13 /
    # RESILIENCE §11): a time-varying zipf whose hot set rotates
    # mid-run across a 3-node cluster — promotion keeps every node
    # answering hot keys locally; the _repl0 arm below is the
    # consistent-hash-only A/B.  A finite-limit canary key checks the
    # N_replicas x lease admission bound in the same run.
    "flashcrowd": {
        "BENCH_MODE": "flashcrowd",
        "BENCH_KEYS": "1000",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_SECONDS": "12",
    },
    "flashcrowd_repl0": {
        "BENCH_MODE": "flashcrowd",
        "BENCH_KEYS": "1000",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_SECONDS": "12",
        "BENCH_FLASH_REPL": "0",
    },
    # Connection scale through the epoll event front (PERF.md §26):
    # 1k→10k held connections from the epoll connscale client, with
    # the thread-per-conn A/B at equal load and the feeder-ring-wait
    # starvation attribution per rung.  CPU-tier config (the front is
    # host-side; no device involvement beyond the serve plane).
    "connscale": {
        "BENCH_MODE": "connscale",
        "BENCH_KEYS": "1",
        "BENCH_CAPACITY": str(1 << 17),
    },
    # Throughput-optimal operating point: batch 32768 amortizes the
    # tunneled backend's per-RPC fixed costs 4x deeper than the
    # default-config batch 8192 (PERF.md §9 transport arithmetic).
    "bulk": {
        "BENCH_BATCH": "32768",
        "BENCH_KEYS": "1000000",
        "BENCH_CAPACITY": str(1 << 21),
    },
    # BASELINE config 5: count-min-sketch approximate limiter
    # (Behavior.SKETCH) over the wire — unbounded key cardinality in
    # O(1) memory, one-sided error (ops/sketch.py).
    "sketch": {
        "BENCH_MODE": "sketch",
        "BENCH_BATCH": "1000",
        "BENCH_KEYS": "10000000",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_WARM_SECONDS": "3",
    },
    # Latency mode (VERDICT r4 #4): closed-loop synchronous dispatch,
    # pre-warmed engine — the p50/p99 fields are the artifact; the SLO
    # bar is p99 < 2ms on the CPU backend where no tunnel sits between
    # dispatch and readback (BASELINE.md).  Batch 512 is the latency
    # operating point (the bar allows <= 1000): batch-1000 sits at
    # p50 1.23 / p99 ~2.2ms, batch-512 at p50 0.92 / p99 ~1.5ms —
    # XLA:CPU execute-time variance (3-6ms dispatch spikes, scattered,
    # not GC and not periodic) sets the tail, so the margin comes from
    # a smaller per-step baseline (PERF.md §14).
    "latency": {
        "BENCH_BATCH": "512",
        "BENCH_KEYS": "100000",
        "BENCH_CAPACITY": str(1 << 17),
        "BENCH_LATENCY_BATCHES": "1000",
        "BENCH_SECONDS": "2",
    },
    # The 100M-slot HBM proof (BASELINE config 4 at full scale):
    # 19 arrays x 4B x 100M = 7.6GB of device state on one v5e chip.
    # TPU-only (the CPU fallback would also allocate 7.6GB, fine on
    # this 125GB host, but the number is meaningless there).
    "zipf100m": {
        "BENCH_ZIPF": "1.2",
        "BENCH_KEYS": "100000000",
        "BENCH_CAPACITY": "100000000",
        "BENCH_BATCH": "8192",
        "BENCH_SECONDS": "8",
    },
}


# fast_capture tier (VERDICT r5 next-round #1): ONE combined run
# capturing the three claims that matter — throughput (default), the
# latency SLO (latency), and the native front (herdfast) — in under
# 3 minutes, so even a short backend serving window produces the
# on-chip artifact before the full BENCH_ORDER sweep starts.  Each
# sub-config runs with shortened measure knobs; the combined artifact
# records the per-config capture duration so window use is auditable.
FAST_CAPTURE = ["default", "latency", "herdfast"]
FAST_CAPTURE_OVERRIDES = {
    "default": {"BENCH_SECONDS": "4", "BENCH_LATENCY_BATCHES": "100"},
    "latency": {"BENCH_LATENCY_BATCHES": "400", "BENCH_SECONDS": "2"},
    "herdfast": {"BENCH_SECONDS": "4"},
}


def run_fast_capture() -> dict:
    """Run the fast tier and write BENCH_<round>_fast_capture.json
    (plus the individual per-config artifacts)."""
    import time

    t_all = time.monotonic()
    combined: dict = {"tier": "fast_capture", "configs": {}}
    for name in FAST_CAPTURE:
        overrides = dict(CONFIGS[name])
        overrides.update(FAST_CAPTURE_OVERRIDES.get(name, {}))
        t0 = time.monotonic()
        result = run(name, overrides)
        result["capture_seconds"] = round(time.monotonic() - t0, 1)
        combined["configs"][name] = result
        # Each sub-result also lands as its own artifact so the
        # per-config files exist even if the window closes mid-tier.
        path = os.path.join(ROOT, f"BENCH_{ROUND}_{name}.json")
        with open(path, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
        print(json.dumps(result), flush=True)
    combined["total_seconds"] = round(time.monotonic() - t_all, 1)
    path = os.path.join(ROOT, f"BENCH_{ROUND}_fast_capture.json")
    with open(path, "w") as f:
        json.dump(combined, f, indent=1)
        f.write("\n")
    return combined


def run(name: str, overrides: dict) -> dict:
    env = dict(os.environ)
    env.update(overrides)
    env.setdefault("BENCH_SECONDS", "5")
    # Own process group + group kill on timeout: the bench child's
    # axon relay grandchild can hold the pipes open past a plain
    # subprocess.run timeout (see scripts/tpu_watchdog.run_group).
    import signal

    proc = subprocess.Popen(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, cwd=ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, stdin=subprocess.DEVNULL, text=True,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=1200)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            if proc.stdout:
                proc.stdout.close()
            if proc.stderr:
                proc.stderr.close()
        return {"error": "bench timed out (group-killed)"}
    line = ""
    for ln in (out or "").strip().splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            line = ln
    if not line:
        return {
            "error": f"no JSON line (rc={proc.returncode})",
            "stderr_tail": (err or "")[-400:],
        }
    result = json.loads(line)
    result["config"] = name
    result["env"] = overrides
    return result


def main() -> int:
    names = sys.argv[1:] or list(CONFIGS)
    if "fast_capture" in names:
        names.remove("fast_capture")
        run_fast_capture()
    for name in names:
        print(f"=== {name}: {CONFIGS[name]}", file=sys.stderr, flush=True)
        result = run(name, CONFIGS[name])
        path = os.path.join(ROOT, f"BENCH_{ROUND}_{name}.json")
        with open(path, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
        print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    main()

"""Obs smoke: 2×2 fleet rollup + SLO watchdog arc, jax-free, fast.

ci_fast.sh stage (30 s wall budget, the crossregion-smoke pattern):
drive the REAL FleetCollector + SLOWatchdog + AdmissionWatch + fault
injector + per-peer circuit breakers through a partition arc on a
jax-free, grpc-server-free 2-region × 2-node loopback harness — the
smoke budget is spent on the observability plane, not on XLA warmup
or daemon bootstrap.  The full-stack invariants (real daemons, the
ObsSnapshot RPC end to end, /debug/fleet over HTTP) are pinned by
tests/test_obs.py in the tier-1 suite.

Asserts, in order:

1. MERGE: one collect() from node east-0 reaches all four nodes,
   sums counters per region, and the merged stage p99 lands in the
   slow node's octave — a real histogram-merged quantile (a mean of
   per-node p99s would sit in the empty gap between the modes).
2. FAULT: with the west region partitioned, the scrape counts the
   unreachable peers (failed/skipped, never an exception), and a
   burst of degraded_region answers makes the degraded-fraction SLI
   BURN past its fast-pair factor — a recorded breach.
3. HEAL + RECOVER: the watched canary key admits up to its bound
   with headroom ≥ 0 throughout, and a new duration window after the
   heal re-arms the count — headroom recovers to the full derived
   bound.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    t0 = time.monotonic()

    from gubernator_tpu.cluster import faults
    from gubernator_tpu.cluster.health import PeerHealth
    from gubernator_tpu.cluster.peer_client import PeerError
    from gubernator_tpu.obs.fleet import FleetCollector
    from gubernator_tpu.obs.slo import AdmissionWatch, SLOWatchdog
    from gubernator_tpu.types import PeerInfo
    from gubernator_tpu.utils.metrics import DurationStat

    class Engine:
        requests_total = 0
        over_limit_total = 0

        @staticmethod
        def cache_size() -> int:
            return 0

    class Node:
        """One 'daemon': the narrow instance surface the collector
        snapshots, plus its region tag."""

        def __init__(self, addr: str, region: str):
            self.addr = addr
            self.region = region
            self.engine = Engine()
            self.counters = {
                "check_errors": 0,
                "degraded_region_answers": 0,
            }
            self.stage_timers = {"window_wait": DurationStat()}
            self.admission_watch = AdmissionWatch()
            self._peers: list = []
            self.obs = FleetCollector(
                self, addr=addr, region=region,
                rpc_timeout=0.2, fanout_deadline=0.5,
            )

        def get_peer_list(self):
            return [p for p in self._peers
                    if p.info.datacenter == self.region]

        def get_region_pickers(self):
            remote = {}
            for p in self._peers:
                if p.info.datacenter != self.region:
                    remote.setdefault(
                        p.info.datacenter, _Ring([])
                    )._peers.append(p)
            return remote

    class _Ring:
        def __init__(self, peers):
            self._peers = list(peers)

        def peers(self):
            return list(self._peers)

    class LoopbackPeer:
        """In-process PeerClient stand-in: the fault injector gates
        obs_snapshot_raw at the same (src, dst) choke point, outcomes
        feed a real PeerHealth breaker."""

        def __init__(self, src: Node, dst: Node):
            self.info = PeerInfo(
                grpc_address=dst.addr, http_address="",
                datacenter=dst.region,
            )
            self._src, self._dst = src, dst
            self.health = PeerHealth(
                dst.addr, failure_threshold=3, backoff=0.05,
                backoff_cap=0.2,
            )

        def obs_snapshot_raw(self, timeout=None) -> bytes:
            if not self.health.allow():
                raise PeerError(
                    f"circuit open to {self.info.grpc_address}",
                    not_ready=True, circuit_open=True,
                )
            inj = faults.active()
            if inj is not None:
                try:
                    inj.check(self._src.addr, self._dst.addr)
                except faults.FaultError as e:
                    self.health.record_failure()
                    raise PeerError(str(e), not_ready=True) from e
            self.health.record_success()
            return self._dst.obs.local_snapshot_raw()

    east = [Node(f"10.0.0.{i}:81", "east") for i in (1, 2)]
    west = [Node(f"10.0.1.{i}:81", "west") for i in (1, 2)]
    nodes = east + west
    for n in nodes:
        n._peers = [
            LoopbackPeer(n, other) for other in nodes if other is not n
        ]
    lead = east[0]
    wd = SLOWatchdog(
        lead.obs, lead.admission_watch, interval=0,
        fleet_scope=True,
        fast_windows=(0.05, 0.1), slow_windows=(0.5, 1.0),
        fast_factor=14.4,
    )

    inj = faults.install(faults.FaultInjector(seed=7))
    try:
        # -- phase 1: healthy merge + real quantiles -------------------
        for n in nodes:
            n.engine.requests_total = 100
            for _ in range(99):
                n.stage_timers["window_wait"].observe(0.001)
        # One slow node: the merged p99 must find ITS octave.
        for _ in range(8):
            west[1].stage_timers["window_wait"].observe(0.512)
        fleet = lead.obs.collect()
        assert len(fleet["nodes"]) == 4, fleet["nodes"]
        assert fleet["scrape"] == {
            **fleet["scrape"], "ok": 4, "failed": 0, "skipped": 0,
        }, fleet["scrape"]
        assert fleet["regions"]["east"]["nodes"] == 2
        assert fleet["counters"]["checks"] == 400
        q = fleet["quantiles"]["window_wait"]
        assert q["count"] == 404
        assert 0.5 < q["p50_ms"] < 2.0, q
        assert 250.0 < q["p99_ms"] < 1100.0, (
            "merged p99 must be the histogram-merged quantile "
            f"(the slow octave), got {q}"
        )
        wd.evaluate(fleet)  # baseline sample for the burn windows

        # -- phase 2: partition west + burn the degraded SLI -----------
        for e in east:
            for w in west:
                inj.partition(e.addr, w.addr)
        # Serving continues region-locally; every MULTI_REGION answer
        # is flagged degraded_region while west is unreachable.
        time.sleep(0.07)  # cross the fast short window
        for n in east:
            n.engine.requests_total += 200
            n.counters["degraded_region_answers"] += 150
        fleet = lead.obs.collect()
        scrape = fleet["scrape"]
        assert scrape["ok"] == 2 and (
            scrape["failed"] + scrape["skipped"] == 2
        ), scrape
        out = wd.evaluate(fleet)
        burns = {
            k: v for k, v in out["slis"].items()
            if k.startswith("degraded_region_fraction@fast")
        }
        assert burns and all(v > 14.4 for v in burns.values()), out[
            "slis"
        ]
        assert any(
            b["sli"] == "degraded_region_fraction"
            for b in out["breaches"]
        ), out["breaches"]

        # -- phase 3: canary headroom + recovery after heal ------------
        key = "xr_canary"
        limit = 40
        for n in nodes:
            n.admission_watch.watch(key, limit=limit)

        class Resp:
            error = ""

            def __init__(self, status, reset_time):
                self.status = status
                self.reset_time = reset_time

        class Req:
            hits = 1
            limit = 40

            @staticmethod
            def hash_key():
                return key

        # Each partition side admits up to its regional limit — the
        # §12 drift shape: cluster-admitted ≤ N_regions × limit.
        for n in (east[0], west[0]):
            for _ in range(limit):
                n.admission_watch.observe_batch(
                    [Req()], [Resp(0, 1000)]
                )
        fleet = lead.obs.collect()  # west unreachable: east slice only
        out = wd.evaluate(fleet)
        hr = out["headroom"][key]
        assert hr["headroom"] >= 0, hr
        inj.heal()
        fleet = lead.obs.collect()
        assert fleet["admitted"][key]["admitted"] == 2 * limit
        out = wd.evaluate(fleet)
        hr = out["headroom"][key]
        assert hr["bound"] == f"2_regions_x_{limit}", hr
        assert hr["headroom"] == 0, hr  # exactly at the bound
        # A new duration window re-arms the count: headroom recovers
        # to the full derived bound.
        for n in (east[0], west[0]):
            n.admission_watch.observe_batch([Req()], [Resp(1, 61_000)])
        fleet = lead.obs.collect()
        out = wd.evaluate(fleet)
        assert out["headroom"][key]["headroom"] == 2 * limit, out[
            "headroom"
        ]
        assert wd.status()["breaches"], "breach log must retain phase 2"
    finally:
        faults.uninstall()
        wd.close()
        for n in nodes:
            n.obs.close()

    elapsed_ms = (time.monotonic() - t0) * 1e3
    print(
        "obs smoke OK: 2x2 rollup merge + degraded-SLI burn + "
        "headroom recovery "
        f"in {elapsed_ms:.0f} ms"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Columnar feeder smoke: one jax-free pass over the native feeder
plane's load-bearing contract, cheap enough to gate every commit
(ci_fast stage; wall budget enforced by the caller).

Asserts, in order:
  1. the C pack's columns are BIT-EQUAL to the Python columnar decode
     (key bytes, offsets, every value lane, both FNV hashes) for a
     multi-RPC window;
  2. the ring's window lifecycle works end-to-end: seal → columnar
     callback with zero-copy views → verdict write-back → recycle;
  3. drain-then-close teardown leaves consistent stats.

The deep fuzz/overflow/TSan coverage lives in tests/test_feeder.py and
tests/test_h2_server_san.py; this is the canary that the .so still
builds and the claim protocol still lines up after any native edit.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from gubernator_tpu.net import wire_codec
from gubernator_tpu.net.h2_fast import load


def _payload(n, salt):
    # Hand-rolled GetRateLimitsReq (no protobuf import: stays light).
    def varint(v):
        out = b""
        while v >= 0x80:
            out += bytes([(v & 0x7F) | 0x80])
            v >>= 7
        return out + bytes([v])

    def field(tag, wt, payload):
        return bytes([(tag << 3) | wt]) + payload

    items = b""
    for i in range(n):
        name = f"smoke_{salt}".encode()
        key = f"user_{i}_k{salt}".encode()
        item = (
            field(1, 2, varint(len(name)) + name)
            + field(2, 2, varint(len(key)) + key)
            + field(3, 0, varint(i + 1))
            + field(4, 0, varint(10**9 + i))
            + field(5, 0, varint(60_000))
            + field(6, 0, varint(i % 2))
        )
        items += field(1, 2, varint(len(item)) + item)
    return items


def main() -> int:
    if load() is None:
        print("feeder smoke: native h2 server unavailable; skipping")
        return 0
    from gubernator_tpu.core.native_plane import NativeColumnarFeeder

    captured = []

    def handler(slot, n_rows, n_rpcs, key_bytes):
        captured.append(
            {
                "key_buf": slot.key_buf[:key_bytes].copy(),
                "key_offsets": slot.key_offsets[: n_rows + 1].copy(),
                "lanes": {
                    lane: getattr(slot, lane)[:n_rows].copy()
                    for lane in (
                        "algo", "behavior", "hits", "limit", "duration",
                        "burst", "fnv1", "fnv1a", "name_lens",
                    )
                },
                "rpc_row": slot.rpc_row[:n_rpcs].copy(),
                "rpc_items": slot.rpc_items[:n_rpcs].copy(),
            }
        )
        slot.out_status[:n_rows] = 0
        slot.rpc_status[:n_rpcs] = 0
        return 0

    feeder = NativeColumnarFeeder(
        n_slots=2, max_rows=512, window_s=0.2, window_handler=handler
    )
    try:
        bodies = [_payload(7, s) for s in range(3)]
        for b in bodies:
            rc = feeder.pack(b)
            assert rc == 7, f"pack returned {rc}"
        feeder.flush()
        assert len(captured) == 1, f"windows: {len(captured)}"
        got = captured[0]
        for r, body in enumerate(bodies):
            dec = wire_codec.decode_reqs(body, 512, 0)
            assert dec is not None
            row0 = int(got["rpc_row"][r])
            k = int(got["rpc_items"][r])
            assert k == dec.n
            off0 = int(got["key_offsets"][row0])
            np.testing.assert_array_equal(
                got["key_offsets"][row0 : row0 + k + 1] - off0,
                dec.key_offsets,
            )
            np.testing.assert_array_equal(
                got["key_buf"][off0 : int(got["key_offsets"][row0 + k])],
                dec.key_buf,
            )
            for lane, col in got["lanes"].items():
                ref = getattr(dec, "name_len" if lane == "name_lens" else lane)
                np.testing.assert_array_equal(
                    col[row0 : row0 + k], ref, err_msg=lane
                )
        st = feeder.stats()
        assert st["feeder_rows"] == 21 and st["feeder_served_rows"] == 21
        assert st["feeder_windows"] == 1 and st["feeder_declined"] == 0
    finally:
        feeder.close()
    print("feeder smoke: pack parity + window lifecycle + teardown ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Phase-level timing of the pipelined columnar loop on the live
backend: dispatch wall time per batch vs stacked-fetch wall time per
group, to find where the per-batch 27ms goes."""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("GUBERNATOR_TPU_X64", "1")
import numpy as np

from gubernator_tpu.core.engine import DecisionEngine

B = 8192
engine = DecisionEngine(capacity=131072, max_kernel_width=8192)

batches = []
for b in range(8):
    idx = (np.arange(B, dtype=np.int64) + b * B) % 100000
    batches.append(dict(
        keys=[b"bench_k%d" % i for i in idx.tolist()],
        algo=(idx % 2).astype(np.int32),
        behavior=np.zeros(B, dtype=np.int32),
        hits=np.ones(B, dtype=np.int64),
        limit=np.full(B, 1_000_000, dtype=np.int64),
        duration=np.full(B, 3_600_000, dtype=np.int64),
        burst=np.full(B, 1_000_000, dtype=np.int64),
    ))

for i in range(3):
    engine.apply_columnar(**batches[i % 8])
import jax.numpy as jnp

from gubernator_tpu.ops.bucket_kernel import PACKED_OUT_ROWS

engine.readback.warmup_stacks((PACKED_OUT_ROWS, B), jnp.int32)
if engine._pump is not None:
    engine._pump.warmup(B)

disp = []
fetch = []
from collections import deque

pending = deque()
t_start = time.perf_counter()
N = 64
for i in range(N):
    t0 = time.perf_counter()
    p = engine.apply_columnar(**batches[i % 8], want_async=True)
    disp.append(time.perf_counter() - t0)
    pending.append(p)
    if len(pending) > 16:
        t0 = time.perf_counter()
        pending.popleft().get()
        fetch.append(time.perf_counter() - t0)
while pending:
    t0 = time.perf_counter()
    pending.popleft().get()
    fetch.append(time.perf_counter() - t0)
total = time.perf_counter() - t_start

disp = np.asarray(disp) * 1e3
fetch = np.asarray(fetch) * 1e3
print("dispatch ms: mean=%.2f p50=%.2f max=%.2f sum=%.1f"
      % (disp.mean(), np.percentile(disp, 50), disp.max(), disp.sum()))
print("fetch ms: mean=%.2f p50=%.2f max=%.2f sum=%.1f"
      % (fetch.mean(), np.percentile(fetch, 50), fetch.max(), fetch.sum()))
print("total %.1f ms for %d batches -> %.2f ms/batch, %.0f dec/s"
      % (total * 1e3, N, total * 1e3 / N, N * B / total))
print("combiner: registered=%d transfers=%d stacked=%d"
      % (engine.readback.registered, engine.readback.transfers,
         engine.readback.stacked))

# --- phase split: execution wait vs stacked transfer ---
import jax

pending = deque()
waits = []
reads = []
for rep in range(4):
    t0 = time.perf_counter()
    ps = [engine.apply_columnar(**batches[i % 8], want_async=True)
          for i in range(16)]
    t_disp = time.perf_counter() - t0
    with engine._lock:
        engine._flush_pump()
    tk = ps[-1]._pieces[0][0]
    last = tk.group.handle if hasattr(tk, 'group') else tk.handle
    t0 = time.perf_counter()
    jax.block_until_ready(last)
    waits.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    for p in ps:
        p.get()
    reads.append(time.perf_counter() - t0)
    print("rep%d: disp16=%.1fms exec_wait=%.1fms stacked_read=%.1fms"
          % (rep, t_disp * 1e3, waits[-1] * 1e3, reads[-1] * 1e3))

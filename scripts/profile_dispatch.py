"""Dispatch-overhead experiments for the tunneled TPU backend.

Answers, with real numbers:
  A. blocking round-trip latency of a tiny kernel (sync floor)
  B. async enqueue throughput (ops/sec) when chaining without blocking
  C. whether a FUSED donated read-modify-write program pays
     O(capacity) copy-insertion (step time vs capacity)
  D. pipelined throughput of the packed 4-op step
     (h2d + compute + scatter + async d2h) at several batch widths
Prints one JSON dict.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("GUBERNATOR_TPU_X64", "1")
import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

res: dict = {}


def report(k, v):
    res[k] = v
    print(f"{k}: {v}", file=sys.stderr, flush=True)


def main():
    dev = jax.devices()[0]
    report("platform", dev.platform)

    x = jax.device_put(jnp.ones(8, jnp.float32), dev)

    @jax.jit
    def tiny(a):
        return a + 1

    tiny(x).block_until_ready()

    # A. sync round-trip floor
    t0 = time.perf_counter()
    for _ in range(50):
        tiny(x).block_until_ready()
    report("sync_roundtrip_ms", (time.perf_counter() - t0) / 50 * 1e3)

    # B. async chained enqueue rate
    t0 = time.perf_counter()
    o = x
    for _ in range(200):
        o = tiny(o)
    o.block_until_ready()
    report("async_chain_op_ms", (time.perf_counter() - t0) / 200 * 1e3)

    # C. fused donated RMW: gather+math+scatter in ONE program, donated
    # state, at two capacities — if time scales with capacity, XLA's
    # copy-insertion is cloning the state.
    B = 8192

    def fused(state, slot, hits):
        g = [a.at[slot].get(mode="fill", fill_value=0,
                            indices_are_sorted=True, unique_indices=True)
             for a in state]
        upd = [v + hits.astype(v.dtype) for v in g]
        return [a.at[slot].set(v, mode="drop", indices_are_sorted=True,
                               unique_indices=True)
                for a, v in zip(state, upd)]

    fused_j = jax.jit(fused, donate_argnums=(0,))
    rng = np.random.default_rng(0)
    for cap in (1 << 17, 1 << 21):
        state = [jax.device_put(jnp.zeros(cap, jnp.int32), dev)
                 for _ in range(19)]
        slot = jax.device_put(
            jnp.asarray(np.sort(rng.choice(cap, B, replace=False)).astype(np.int32)), dev)
        hits = jax.device_put(jnp.ones(B, jnp.int32), dev)
        state = fused_j(state, slot, hits)  # warm
        t0 = time.perf_counter()
        for _ in range(20):
            state = fused_j(state, slot, hits)
        jax.block_until_ready(state)
        report(f"fused_rmw_cap{cap}_ms", (time.perf_counter() - t0) / 20 * 1e3)

    # D. packed pipelined step at several widths: one h2d int32 [15,B],
    # one fused RMW kernel (donated packed state [cap,20]), one packed
    # int32 [5,B] output with async d2h, pipeline depth 3.
    cap = 1 << 21

    def step(stmat, pin):
        slot = pin[0]
        rows = stmat.at[slot].get(mode="fill", fill_value=0,
                                  indices_are_sorted=True, unique_indices=True)
        upd = rows + pin[3][:, None]
        newm = stmat.at[slot].set(upd, mode="drop", indices_are_sorted=True,
                                  unique_indices=True)
        out = jnp.stack([upd[:, 0], upd[:, 1], upd[:, 2], upd[:, 3], upd[:, 4]])
        return newm, out

    step_j = jax.jit(step, donate_argnums=(0,))
    for B2 in (1024, 8192, 32768):
        stmat = jax.device_put(jnp.zeros((cap, 20), jnp.int32), dev)
        host_in = np.zeros((15, B2), np.int32)
        host_in[0] = np.sort(rng.choice(cap, B2, replace=False)).astype(np.int32)
        host_in[3] = 1
        stmat, out = step_j(stmat, jnp.asarray(host_in))  # warm
        np.asarray(out)
        pend = []
        t0 = time.perf_counter()
        NIT = 50
        for _ in range(NIT):
            stmat, out = step_j(stmat, jnp.asarray(host_in))
            out.copy_to_host_async()
            pend.append(out)
            if len(pend) > 3:
                np.asarray(pend.pop(0))
        for p in pend:
            np.asarray(p)
        dt = (time.perf_counter() - t0) / NIT
        report(f"packed_step_B{B2}_ms", dt * 1e3)
        report(f"packed_step_B{B2}_decs_per_s", B2 / dt)

    print(json.dumps(res))


if __name__ == "__main__":
    main()

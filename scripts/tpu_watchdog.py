"""Backend-liveness watchdog: ambush the axon TPU backend.

The tunneled TPU backend (JAX_PLATFORMS=axon) serves in unpredictable
windows — it was healthy in round 2 and wedged for all of round 3
(every `jax.devices()` probe hung).  This script loops forever:

  1. probe the backend in a SUBPROCESS with a hard timeout
  2. on first success, immediately capture the TPU artifacts in order
     of value (the window may be short):
       a. scripts/profile_dispatch.py  -> PROFILE_r04_tpu.json
       b. scripts/bench_all.py, one config per subprocess:
          default leaky1m zipf wire zipf100m global4hot herd sketch
  3. commit each artifact AS IT LANDS, using a private git index so a
     concurrent foreground `git commit` can never be corrupted or have
     its staged files stolen
  4. keep looping: re-verify artifacts that came back platform=cpu
     (the backend can wedge mid-run), stop when every target artifact
     is platform=tpu

Run detached:  nohup python scripts/tpu_watchdog.py >/tmp/watchdog.log 2>&1 &
Status file:   /tmp/tpu_watchdog_status.json (atomic rewrite each loop)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUND = os.environ.get("BENCH_ROUND", "r05")
PROBE_TIMEOUT = float(os.environ.get("WATCHDOG_PROBE_TIMEOUT", 120))
POLL_SECONDS = float(os.environ.get("WATCHDOG_POLL_SECONDS", 180))
STATUS_PATH = "/tmp/tpu_watchdog_status.json"

# Capture order = value order: dispatch profile first (smallest, most
# diagnostic), then the headline, then the rest.
# Value order for a SHORT serving window: the post-redesign headline
# (default), the throughput-optimal point (bulk), the overlap
# criterion pair (wire/wire1), the 7.6GB HBM proof (zipf100m), then
# the rest.
BENCH_ORDER = [
    "default",
    "bulk",
    "wire",
    "wire1",
    "zipf100m",
    "latency",
    "leaky1m",
    "zipf",
    "global4hot",
    "global4",
    "sketch",
    "herd",
    "herdfast",
]

PROBE_SRC = (
    "import jax; d = jax.devices();"
    "print(d[0].platform, len(d), flush=True)"
)



def run_group(cmd, timeout, **kw):
    """subprocess.run with a REAL timeout: the probe/bench children
    could leave an axon relay grandchild holding the output pipes, and
    subprocess.run's timeout path then blocks forever in its second
    communicate().  Runs the command in its own process group, kills
    the group on timeout, abandons unreapable pipes.  Returns
    (returncode_or_None, stdout, stderr); returncode None = timeout."""
    import signal

    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        stdin=subprocess.DEVNULL, start_new_session=True, **kw,
    )
    try:
        out, err = proc.communicate(timeout=timeout)
        return proc.returncode, out or "", err or ""
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            if proc.stdout:
                proc.stdout.close()
            if proc.stderr:
                proc.stderr.close()
        return None, "", ""


def log(msg: str) -> None:
    ts = time.strftime("%H:%M:%S")
    print(f"[{ts}] {msg}", flush=True)


def write_status(state: dict) -> None:
    tmp = STATUS_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1)
    os.replace(tmp, STATUS_PATH)


def probe() -> str | None:
    """Return the live platform name, or None if wedged/dead.

    NOT subprocess.run(timeout=...): on timeout that kills the child
    and then calls communicate() with NO timeout — if the axon plugin
    spawns a relay grandchild that inherits the pipes, the second
    communicate blocks forever on pipe EOF and the watchdog would sit
    wedged while serving windows pass.  Run the probe in its own
    process GROUP, kill the whole group on timeout, and abandon the
    pipes if they still will not drain."""
    import signal

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the site hook force axon
    proc = subprocess.Popen(
        [sys.executable, "-c", PROBE_SRC],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        stdin=subprocess.DEVNULL, cwd=ROOT, env=env,
        start_new_session=True,
    )
    try:
        out, _ = proc.communicate(timeout=PROBE_TIMEOUT)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            if proc.stdout:
                proc.stdout.close()
            if proc.stderr:
                proc.stderr.close()
        return None
    if proc.returncode != 0:
        return None
    out_words = (out or "").strip().split()
    return out_words[0] if out_words else None


def commit_paths(paths: list[str], message: str) -> bool:
    """Commit repo-root-relative paths using a PRIVATE index.

    Plumbing only: read-tree HEAD into our own index, add the paths,
    write-tree, commit-tree with parent HEAD, update-ref with an
    old-value guard.  Retries on ref races with a concurrent
    foreground commit.  Never touches .git/index.
    """
    env = dict(os.environ)
    env["GIT_INDEX_FILE"] = os.path.join(ROOT, ".git", "watchdog-index")

    def git(*args: str) -> subprocess.CompletedProcess:
        return subprocess.run(
            ["git", *args], cwd=ROOT, env=env,
            capture_output=True, text=True, timeout=60,
        )

    for attempt in range(5):
        head = git("rev-parse", "HEAD").stdout.strip()
        if not head:
            return False
        if git("read-tree", head).returncode != 0:
            return False
        if git("add", "--", *paths).returncode != 0:
            return False
        tree = git("write-tree").stdout.strip()
        parent_tree = git("rev-parse", f"{head}^{{tree}}").stdout.strip()
        if tree == parent_tree:
            return True  # nothing new to record
        commit = git(
            "commit-tree", tree, "-p", head, "-m", message
        ).stdout.strip()
        if not commit:
            return False
        ref = git("update-ref", "refs/heads/main", commit, head)
        if ref.returncode == 0:
            return True
        time.sleep(1.0 + attempt)  # HEAD moved under us; retry
    return False


def artifact_platform(name: str) -> str | None:
    path = os.path.join(ROOT, f"BENCH_{ROUND}_{name}.json")
    try:
        with open(path) as f:
            return json.load(f).get("platform")
    except (OSError, ValueError):
        return None


def run_profile() -> bool:
    out_path = os.path.join(ROOT, f"PROFILE_{ROUND}_tpu.json")
    rc, out, err = run_group(
        [sys.executable, os.path.join(ROOT, "scripts", "profile_dispatch.py")],
        timeout=900, cwd=ROOT,
    )
    if rc is None:
        log("profile_dispatch timed out")
        return False
    line = ""
    for ln in out.strip().splitlines():
        if ln.strip().startswith("{"):
            line = ln.strip()
    if not line:
        log(f"profile_dispatch produced no JSON (rc={rc}): {err[-300:]}")
        return False
    data = json.loads(line)
    with open(out_path, "w") as f:
        json.dump(data, f, indent=1)
        f.write("\n")
    if data.get("platform") not in ("tpu", "axon"):
        log(f"profile ran on {data.get('platform')}, not committing as TPU")
        return False
    commit_paths([os.path.basename(out_path)],
                 f"TPU dispatch profile ({ROUND}): captured live-backend numbers")
    log(f"profile committed: {data}")
    return True


def run_fast_capture() -> bool:
    """The under-3-minute combined tier (default+latency+herdfast):
    captured and committed FIRST so even a serving window too short
    for the full BENCH_ORDER sweep produces the on-chip artifact
    (VERDICT r5 next-round #1).  Returns True when every sub-config
    ran on the TPU."""
    env = dict(os.environ)
    env["BENCH_ROUND"] = ROUND
    rc, _out, _err = run_group(
        [sys.executable, os.path.join(ROOT, "scripts", "bench_all.py"),
         "fast_capture"],
        timeout=900, cwd=ROOT, env=env,
    )
    if rc is None:
        log("fast_capture: timed out")
        return False
    path = os.path.join(ROOT, f"BENCH_{ROUND}_fast_capture.json")
    try:
        with open(path) as f:
            combined = json.load(f)
    except (OSError, ValueError) as e:
        log(f"fast_capture: no artifact ({e})")
        return False
    plats = {
        name: cfg.get("platform")
        for name, cfg in combined.get("configs", {}).items()
    }
    log(f"fast_capture: rc={rc} platforms={plats}")
    on_tpu = [n for n, p in plats.items() if p in ("tpu", "axon")]
    if on_tpu:
        commit_paths(
            [os.path.basename(path)]
            + [f"BENCH_{ROUND}_{n}.json" for n in on_tpu],
            f"TPU fast-capture tier ({ROUND}): "
            f"{'+'.join(on_tpu)} on live backend",
        )
    return len(on_tpu) == len(plats) and bool(plats)


def run_bench(name: str) -> str | None:
    env = dict(os.environ)
    env["BENCH_ROUND"] = ROUND
    rc, _out, _err = run_group(
        [sys.executable, os.path.join(ROOT, "scripts", "bench_all.py"), name],
        timeout=1800, cwd=ROOT, env=env,
    )
    if rc is None:
        log(f"bench {name}: timed out")
        return None
    plat = artifact_platform(name)
    log(f"bench {name}: rc={rc} platform={plat}")
    if plat in ("tpu", "axon"):
        commit_paths(
            [f"BENCH_{ROUND}_{name}.json"],
            f"TPU bench artifact ({ROUND}): {name} on live backend",
        )
        return plat
    return plat


def main() -> None:
    done: set[str] = set()
    force = os.environ.get("WATCHDOG_FORCE", "") == "1"
    # Artifacts already on TPU (e.g. watchdog restarted) count as done
    # — unless forced (recapture after a serving-path improvement).
    if not force:
        for name in BENCH_ORDER:
            if artifact_platform(name) in ("tpu", "axon"):
                done.add(name)
    profile_done = not force and os.path.exists(
        os.path.join(ROOT, f"PROFILE_{ROUND}_tpu.json"))
    fast_done = not force and os.path.exists(
        os.path.join(ROOT, f"BENCH_{ROUND}_fast_capture.json"))
    probes = 0
    while True:
        plat = probe()
        probes += 1
        write_status({
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "probes": probes,
            "last_platform": plat,
            "captured": sorted(done),
            "profile_done": profile_done,
        })
        if plat in ("tpu", "axon"):
            log(f"BACKEND ALIVE (platform={plat}) — capturing")
            # Fast tier first: the 3-minute default+latency+herdfast
            # combined artifact makes a SHORT serving window count
            # double (committed before the full sweep starts).
            if not fast_done:
                fast_done = run_fast_capture()
                if fast_done:
                    done.update(
                        n for n in ("default", "latency", "herdfast")
                        if artifact_platform(n) in ("tpu", "axon")
                    )
            if not profile_done:
                profile_done = run_profile()
            for name in BENCH_ORDER:
                if name in done:
                    continue
                got = run_bench(name)
                if got in ("tpu", "axon"):
                    done.add(name)
                elif got is None or got == "cpu":
                    # backend may have wedged mid-run; re-probe before
                    # burning time on the remaining configs
                    if probe() not in ("tpu", "axon"):
                        log("backend wedged mid-capture; back to polling")
                        break
            if len(done) == len(BENCH_ORDER) and profile_done:
                log("all TPU artifacts captured — exiting")
                write_status({
                    "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
                    "probes": probes,
                    "complete": True,
                    "captured": sorted(done),
                })
                return
        else:
            log(f"backend not serving (probe={plat}); "
                f"sleeping {POLL_SECONDS:.0f}s")
        time.sleep(POLL_SECONDS)


if __name__ == "__main__":
    main()

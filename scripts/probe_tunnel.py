"""Tunnel transport characterization for the axon TPU backend.

Splits the packed step's per-step cost into: h2d fixed+bandwidth,
d2h fixed+bandwidth, pure dispatch (no transfers), and checks whether
h2d/d2h/compute overlap across pipelined steps.  Inputs VARY per call
(the axon terminal memoizes identical executions).  Prints one JSON.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("GUBERNATOR_TPU_X64", "1")
import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

res: dict = {}


def report(k, v):
    res[k] = round(v, 4) if isinstance(v, float) else v
    print(f"{k}: {res[k]}", file=sys.stderr, flush=True)


def timed(fn, iters):
    t0 = time.perf_counter()
    for i in range(iters):
        fn(i)
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    dev = jax.devices()[0]
    report("platform", dev.platform)
    rng = np.random.default_rng(0)

    # --- h2d: varying payloads, blocking ---
    for kb in (16, 64, 512, 2048):
        n = kb * 256  # int32 words
        bufs = [rng.integers(0, 1000, n).astype(np.int32) for _ in range(8)]
        jax.device_put(bufs[0], dev).block_until_ready()
        ms = timed(lambda i: jax.device_put(bufs[i % 8], dev).block_until_ready(), 16)
        report(f"h2d_{kb}KB_ms", ms)

    # --- d2h: varying on-device payloads ---
    from functools import partial

    @partial(jax.jit, static_argnums=(1,))
    def gen(seed, n):
        return (jnp.arange(n, dtype=jnp.int32) * seed)

    for kb in (16, 64, 512, 2048):
        n = kb * 256
        arrs = [gen(jnp.int32(i + 1), n) for i in range(8)]
        jax.block_until_ready(arrs)
        ms = timed(lambda i: np.asarray(arrs[i % 8]), 16)
        report(f"d2h_{kb}KB_ms", ms)

    # --- pure dispatch: donated state chain, zero host transfer ---
    cap = 1 << 21

    def rmw(state, i):
        idx = (jnp.arange(8192, dtype=jnp.int32) * (i + 1)) % cap
        return state.at[idx].add(1, mode="drop")

    rmw_j = jax.jit(rmw, donate_argnums=(0,))
    st = jax.device_put(jnp.zeros((cap,), jnp.int32), dev)
    st = rmw_j(st, jnp.int32(1)).block_until_ready()
    t0 = time.perf_counter()
    for i in range(100):
        st = rmw_j(st, jnp.int32(i))
    st.block_until_ready()
    report("pure_dispatch_chain_ms", (time.perf_counter() - t0) / 100 * 1e3)

    # --- full step anatomy at B=8192, rows like the engine (15 in/5 out) ---
    B = 8192

    def step(stmat, pin):
        slot = pin[0] % cap
        rows = stmat.at[slot].get(mode="fill", fill_value=0)
        upd = rows + pin[3][:, None]
        newm = stmat.at[slot].set(upd, mode="drop")
        return newm, jnp.stack([upd[:, i] for i in range(5)])

    step_j = jax.jit(step, donate_argnums=(0,))
    stmat = jax.device_put(jnp.zeros((cap, 20), jnp.int32), dev)
    ins = [rng.integers(0, cap, (15, B)).astype(np.int32) for _ in range(8)]
    stmat, out = step_j(stmat, jnp.asarray(ins[0]))
    np.asarray(out)

    # (a) blocking every step (no pipeline)
    t0 = time.perf_counter()
    for i in range(20):
        stmat, out = step_j(stmat, jnp.asarray(ins[i % 8]))
        np.asarray(out)
    report("step_blocking_ms", (time.perf_counter() - t0) / 20 * 1e3)

    # (b) pipeline depths 2/4/8
    for depth in (2, 4, 8):
        pend = []
        t0 = time.perf_counter()
        NIT = 40
        for i in range(NIT):
            stmat, out = step_j(stmat, jnp.asarray(ins[i % 8]))
            out.copy_to_host_async()
            pend.append(out)
            if len(pend) > depth:
                np.asarray(pend.pop(0))
        for p in pend:
            np.asarray(p)
        report(f"step_pipe{depth}_ms", (time.perf_counter() - t0) / NIT * 1e3)

    # (c) h2d only (no readback): does input transfer dominate?
    t0 = time.perf_counter()
    for i in range(20):
        stmat, out = step_j(stmat, jnp.asarray(ins[i % 8]))
    jax.block_until_ready(stmat)
    report("step_no_readback_ms", (time.perf_counter() - t0) / 20 * 1e3)

    # (d) narrow payload: 6 rows in, 3 rows out
    def step6(stmat, pin):
        slot = pin[0] % cap
        rows = stmat.at[slot].get(mode="fill", fill_value=0)
        upd = rows + pin[3][:, None]
        newm = stmat.at[slot].set(upd, mode="drop")
        return newm, jnp.stack([upd[:, 0], upd[:, 1], upd[:, 2]])

    step6_j = jax.jit(step6, donate_argnums=(0,))
    ins6 = [rng.integers(0, cap, (6, B)).astype(np.int32) for _ in range(8)]
    stmat2 = jax.device_put(jnp.zeros((cap, 20), jnp.int32), dev)
    stmat2, out = step6_j(stmat2, jnp.asarray(ins6[0]))
    np.asarray(out)
    pend = []
    t0 = time.perf_counter()
    NIT = 40
    for i in range(NIT):
        stmat2, out = step6_j(stmat2, jnp.asarray(ins6[i % 8]))
        out.copy_to_host_async()
        pend.append(out)
        if len(pend) > 4:
            np.asarray(pend.pop(0))
    for p in pend:
        np.asarray(p)
    report("step_narrow_pipe4_ms", (time.perf_counter() - t0) / NIT * 1e3)

    # (e) pre-staged input: device_put committed ahead from a second
    # thread, then consumed — measures whether h2d can overlap h2d.
    import threading
    from queue import Queue

    q: Queue = Queue(maxsize=4)

    def feeder():
        for i in range(40):
            q.put(jax.device_put(ins[i % 8], dev))
        q.put(None)

    th = threading.Thread(target=feeder)
    pend = []
    t0 = time.perf_counter()
    th.start()
    NIT = 0
    while True:
        pin = q.get()
        if pin is None:
            break
        stmat, out = step_j(stmat, pin)
        out.copy_to_host_async()
        pend.append(out)
        NIT += 1
        if len(pend) > 4:
            np.asarray(pend.pop(0))
    for p in pend:
        np.asarray(p)
    th.join()
    report("step_threaded_feed_ms", (time.perf_counter() - t0) / NIT * 1e3)

    print(json.dumps(res))


if __name__ == "__main__":
    main()

"""Diagnose the GLOBAL p99 tail (VERDICT r4 weak #7).

Reproduces the global4 bench in-process while sampling, per node, the
GLOBAL manager's queue depths and flush durations at 50ms resolution,
then correlates request-latency spikes with the samples.  Run on the
idle host: `python scripts/diag_global_tail.py [seconds]`.
"""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gubernator_tpu.platform_guard import force_cpu_platform

force_cpu_platform(1)

import grpc  # noqa: E402
import numpy as np  # noqa: E402

from gubernator_tpu.cluster.harness import ClusterHarness  # noqa: E402
from gubernator_tpu.net.grpc_service import V1_SERVICE  # noqa: E402
from gubernator_tpu.net.pb import gubernator_pb2 as pb  # noqa: E402
from gubernator_tpu.types import Behavior  # noqa: E402

SECONDS = float(sys.argv[1]) if len(sys.argv) > 1 else 15.0
N_NODES = 4
N_THREADS = 8
BATCH = 1000
N_KEYS = 100_000


def build_payloads():
    payloads = []
    for b in range(64):
        msg = pb.GetRateLimitsReq(
            requests=[
                pb.RateLimitReq(
                    name="bench",
                    unique_key="%dk" % ((b * BATCH + i) % N_KEYS),
                    hits=1,
                    limit=1_000_000,
                    duration=3_600_000,
                    algorithm=i % 2,
                    behavior=int(Behavior.GLOBAL),
                    burst=1_000_000,
                )
                for i in range(BATCH)
            ]
        )
        payloads.append(msg.SerializeToString())
    return payloads


def main() -> None:
    h = ClusterHarness().start(N_NODES, cache_size=1 << 17)
    payloads = build_payloads()
    addrs = [h.peer_at(i).grpc_address for i in range(N_NODES)]
    insts = [h.daemon_at(i).instance for i in range(N_NODES)]

    stop = threading.Event()
    lat_log: list = []  # (t_end, latency)
    lat_lock = threading.Lock()

    def worker(tid: int) -> None:
        ch = grpc.insecure_channel(addrs[tid % N_NODES])
        call = ch.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=lambda raw: raw,
            response_deserializer=lambda raw: raw,
        )
        call(payloads[tid])
        i = tid
        while not stop.is_set():
            t0 = time.perf_counter()
            call(payloads[i % len(payloads)])
            t1 = time.perf_counter()
            with lat_lock:
                lat_log.append((t1, t1 - t0))
            i += N_THREADS

    threads = [
        threading.Thread(target=worker, args=(t,), daemon=True)
        for t in range(N_THREADS)
    ]
    samples: list = []  # (t, [hits_pending...], [upd_pending...])
    for t in threads:
        t.start()
    t_start = time.perf_counter()
    while time.perf_counter() - t_start < SECONDS:
        now = time.perf_counter()
        samples.append(
            (
                now,
                [i.global_mgr._hits.pending() for i in insts],
                [i.global_mgr._updates.pending() for i in insts],
            )
        )
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=30)

    lats = np.asarray([d for _, d in lat_log])
    ts = np.asarray([t for t, _ in lat_log])
    print(
        f"reqs={len(lats)} rate={len(lats) * BATCH / SECONDS:.0f} dec/s "
        f"p50={np.percentile(lats, 50) * 1e3:.0f}ms "
        f"p99={np.percentile(lats, 99) * 1e3:.0f}ms "
        f"max={lats.max() * 1e3:.0f}ms"
    )
    hp = np.asarray([s[1] for s in samples])  # [T, nodes]
    up = np.asarray([s[2] for s in samples])
    print(
        "hits queue depth per node: p50",
        np.percentile(hp, 50, axis=0).astype(int).tolist(),
        "max", hp.max(axis=0).astype(int).tolist(),
    )
    print(
        "upd  queue depth per node: p50",
        np.percentile(up, 50, axis=0).astype(int).tolist(),
        "max", up.max(axis=0).astype(int).tolist(),
    )
    for i, inst in enumerate(insts):
        gm = inst.global_mgr
        hd, bd = gm.hits_duration, gm.broadcast_duration
        print(
            f"node{i}: async_sends={gm.async_sends} "
            f"broadcasts={gm.broadcasts} "
            f"hits_flush mean/max={hd.mean() * 1e3:.0f}/"
            f"{hd.max * 1e3:.0f}ms "
            f"bcast_flush mean/max={bd.mean() * 1e3:.0f}/"
            f"{bd.max * 1e3:.0f}ms"
        )
    # When were the worst requests? Do they align with deep queues?
    worst = np.argsort(lats)[-10:]
    st = np.asarray([s[0] for s in samples])
    for w in sorted(worst.tolist()):
        t_end = ts[w]
        k = np.searchsorted(st, t_end)
        k = min(k, len(samples) - 1)
        print(
            f"lat {lats[w] * 1e3:7.0f}ms at t+{t_end - t_start:5.1f}s  "
            f"hits={samples[k][1]} upd={samples[k][2]}"
        )
    h.stop()


if __name__ == "__main__":
    main()

"""Microbenchmark: where does a serving step's time go? (round-3 path)

Times, with block_until_ready:
  1. the packed fused step (the serving program: one [16,B] input,
     gather → update → scatter with donated state, one [5,B] output)
  2. the split pair (packed_compute + scatter_store)
  3. the collapsed duplicate-segment step
  4. the full engine columnar path (host interning + pack + dispatch +
     readback), distinct keys and hot-key variants
  5. host interning alone
Prints a JSON breakdown.  Run on the TPU when the backend serves
(see PERF.md §2 for the round-2 numbers this superseded).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("GUBERNATOR_TPU_X64", "1")
import gubernator_tpu  # noqa: F401  (sets x64)
import jax
import numpy as np

from gubernator_tpu.core.engine import DecisionEngine

B = int(os.environ.get("PROF_BATCH", 8192))
CAP = int(os.environ.get("PROF_CAP", 1 << 17))
REPS = int(os.environ.get("PROF_REPS", 30))


def main():
    dev = jax.devices()[0]
    print(f"platform={dev.platform}", file=sys.stderr)
    res = {"platform": dev.platform, "batch": B, "cap": CAP}

    eng = DecisionEngine(capacity=CAP, device=dev, max_kernel_width=B)
    res["fused_mode"] = bool(eng._fused)

    algo = np.zeros(B, np.int32)
    beh = np.zeros(B, np.int32)
    hits = np.ones(B, np.int64)
    lim = np.full(B, 10**9, np.int64)
    dur = np.full(B, 3_600_000, np.int64)
    burst = np.zeros(B, np.int64)

    def run(keys, label, reps=REPS):
        eng.apply_columnar(keys, algo, beh, hits, lim, dur, burst,
                           now_ms=12345678)  # warm/compile
        t0 = time.perf_counter()
        for _ in range(reps):
            eng.apply_columnar(keys, algo, beh, hits, lim, dur, burst,
                               now_ms=12345678)
        dt = (time.perf_counter() - t0) / reps
        res[label + "_ms"] = dt * 1e3
        res[label + "_decs_per_s"] = B / dt

    # 4a. distinct keys → packed step.
    run([b"prof_%d" % i for i in range(B)], "engine_distinct")
    # 4b. hot keys (8 keys) → collapsed step.
    run([b"hot_%d" % (i % 8) for i in range(B)], "engine_hotkeys")

    # 5. host interning alone.
    keys = [b"prof_%d" % i for i in range(B)]
    eng.table.schedule(keys, 12345678)
    t0 = time.perf_counter()
    for _ in range(REPS):
        eng.table.schedule(keys, 12345678)
    res["intern_ms"] = (time.perf_counter() - t0) / REPS * 1e3

    # Pipelined throughput (async readback overlap, depth 3).
    pend = []
    t0 = time.perf_counter()
    NIT = 40
    for i in range(NIT):
        pend.append(
            eng.apply_columnar(keys, algo, beh, hits, lim, dur, burst,
                               now_ms=12345678, want_async=True)
        )
        if len(pend) > 3:
            pend.pop(0).get()
    for p in pend:
        p.get()
    dt = (time.perf_counter() - t0) / NIT
    res["pipelined_ms"] = dt * 1e3
    res["pipelined_decs_per_s"] = B / dt

    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()

"""Microbenchmark: where does a serving step's time go?

Times, with device-resident inputs and block_until_ready:
  1. compute_update_sorted alone (gather + math, no state writes)
  2. scatter_store alone
  3. both chained (the engine's per-round device work)
  4. gather-only probe (how expensive is a sorted/unique 1-D gather)
  5. the full engine columnar path (host interning + dispatch + readback)
Prints a JSON breakdown.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("GUBERNATOR_TPU_X64", "1")
import gubernator_tpu  # noqa: F401  (sets x64)
import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.ops.bucket_kernel import (
    BatchInput,
    compute_update_sorted,
    make_state,
    scatter_store,
)

B = int(os.environ.get("PROF_BATCH", 8192))
CAP = int(os.environ.get("PROF_CAP", 1 << 17))
REPS = int(os.environ.get("PROF_REPS", 30))


def timeit(fn, reps=REPS):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    dev = jax.devices()[0]
    print(f"platform={dev.platform}", file=sys.stderr)
    state = make_state(CAP)
    state = jax.device_put(state, dev)

    rng = np.random.default_rng(0)
    slots = np.sort(rng.choice(CAP, size=B, replace=False)).astype(np.int32)
    batch = BatchInput(
        slot=jnp.asarray(slots),
        algo=jnp.asarray(rng.integers(0, 2, B).astype(np.int32)),
        behavior=jnp.asarray(np.zeros(B, np.int32)),
        hits=jnp.asarray(np.ones(B, np.int64)),
        limit=jnp.asarray(np.full(B, 100, np.int64)),
        duration=jnp.asarray(np.full(B, 60000, np.int64)),
        burst=jnp.asarray(np.zeros(B, np.int64)),
        greg_duration=jnp.asarray(np.zeros(B, np.int64)),
        greg_expire=jnp.asarray(np.zeros(B, np.int64)),
    )
    batch = jax.device_put(batch, dev)
    now = jnp.asarray(1_000_000, dtype=jnp.int64)

    res = {}

    # 1. compute only
    res["compute_ms"] = timeit(lambda: compute_update_sorted(state, batch, now)) * 1e3

    # 2. scatter only (state is donated → re-put each call would skew;
    # use a fresh jit without donation for timing)
    vals, _ = compute_update_sorted(state, batch, now)
    from gubernator_tpu.ops.bucket_kernel import _scatter_values

    sc_nodonate = jax.jit(_scatter_values)
    res["scatter_ms"] = timeit(lambda: sc_nodonate(state, batch.slot, vals)) * 1e3

    # 4. gather probe: 19 separate sorted-unique gathers like the kernel
    def g19(st, sl):
        return [a.at[sl].get(mode="fill", fill_value=0,
                             indices_are_sorted=True, unique_indices=True)
                for a in st]

    g19_j = jax.jit(g19)
    res["gather19_ms"] = timeit(lambda: g19_j(list(state), batch.slot)) * 1e3

    # 4b. one gather from a packed [cap, 20] int32 matrix
    packed = jnp.zeros((CAP, 20), dtype=jnp.int32)

    def g_packed(m, sl):
        return m.at[sl].get(mode="fill", fill_value=0,
                            indices_are_sorted=True, unique_indices=True)

    gp_j = jax.jit(g_packed)
    res["gather_packed_ms"] = timeit(lambda: gp_j(packed, batch.slot)) * 1e3

    # 4c. one scatter into packed matrix
    rowvals = jnp.ones((B, 20), dtype=jnp.int32)

    def s_packed(m, sl, v):
        return m.at[sl].set(v, mode="drop",
                            indices_are_sorted=True, unique_indices=True)

    sp_j = jax.jit(s_packed)
    res["scatter_packed_ms"] = timeit(lambda: sp_j(packed, batch.slot, rowvals)) * 1e3

    # 4d. int64 arithmetic probe on batch vectors
    a64 = jnp.asarray(rng.integers(0, 1 << 40, B), dtype=jnp.int64)
    b64 = jnp.asarray(rng.integers(1, 1 << 20, B), dtype=jnp.int64)

    def math64(a, b):
        x = a + b
        x = jnp.where(a > b, x, a - b)
        y = (a.astype(jnp.float64) / b.astype(jnp.float64)).astype(jnp.int64)
        return x + y

    m64_j = jax.jit(math64)
    res["math64_ms"] = timeit(lambda: m64_j(a64, b64)) * 1e3

    # 5. full engine columnar path
    from gubernator_tpu.core.engine import DecisionEngine

    eng = DecisionEngine(capacity=CAP, device=dev)
    keys = [b"bench_%d" % i for i in range(B)]
    algo = np.zeros(B, np.int32)
    beh = np.zeros(B, np.int32)
    hits = np.ones(B, np.int64)
    lim = np.full(B, 100, np.int64)
    dur = np.full(B, 60000, np.int64)
    burst = np.zeros(B, np.int64)

    def full():
        return eng.apply_columnar(keys, algo, beh, hits, lim, dur, burst,
                                  now_ms=12345678)

    full()
    t0 = time.perf_counter()
    for _ in range(10):
        full()
    res["engine_ms"] = (time.perf_counter() - t0) / 10 * 1e3

    # host-only: interning
    t0 = time.perf_counter()
    for _ in range(10):
        eng.table.schedule(keys, 12345678)
    res["intern_ms"] = (time.perf_counter() - t0) / 10 * 1e3

    res["batch"] = B
    res["cap"] = CAP
    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()

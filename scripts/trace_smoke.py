"""Trace smoke: one in-memory-traced decision end-to-end, fast.

ci_fast.sh stage (mirroring the guberlint stage pattern, same 10 s
wall budget): run a single decision through the REAL service router
with the in-memory tracer installed and assert a non-empty STITCHED
tree — a root `service.get_rate_limits` span with a child engine span
sharing its trace id and parented to its span id.  Catches the two
regressions that would silently blind the observability plane: the
tracer no longer recording, or parent/trace ids no longer linking.

Deliberately jax-free (a stub engine): the smoke budget is spent on
the tracing plumbing, not on XLA warmup — the full cross-process
stitching (forwarder → owner → broadcast with remote parents) is
pinned by tests/test_trace_stitch.py in the tier-1 suite.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    t0 = time.monotonic()
    from gubernator_tpu.utils import tracing

    tracer = tracing.InMemoryTracer()
    tracing.set_tracer(tracer)

    from gubernator_tpu.clock import SYSTEM_CLOCK
    from gubernator_tpu.config import Config
    from gubernator_tpu.service import V1Instance
    from gubernator_tpu.types import (
        RateLimitReq,
        RateLimitResp,
        Status,
    )

    class SmokeEngine:
        """Minimal engine: answers UNDER_LIMIT and traces the batch
        so the smoke asserts a parent→child link, not just a root."""

        clock = SYSTEM_CLOCK
        store = None

        def get_rate_limits(self, reqs, now_ms=None):
            with tracing.span("smoke.engine", batch=len(reqs)):
                return [
                    RateLimitResp(
                        status=Status.UNDER_LIMIT,
                        limit=r.limit,
                        remaining=max(0, r.limit - r.hits),
                        reset_time=0,
                    )
                    for r in reqs
                ]

        def cache_size(self) -> int:
            return 0

        def close(self) -> None:
            pass

    inst = V1Instance(Config(global_serve_window=0.0), SmokeEngine())
    try:
        resps = inst.get_rate_limits(
            [
                RateLimitReq(
                    name="smoke", unique_key="k", hits=1, limit=10,
                    duration=60_000,
                )
            ]
        )
        assert resps[0].error == "", resps[0].error
        assert resps[0].remaining == 9
    finally:
        inst.close()

    roots = tracer.spans("service.get_rate_limits")
    assert len(roots) == 1, f"expected one root span, got {len(roots)}"
    root = roots[0]
    children = tracer.spans("smoke.engine")
    assert children, "engine child span missing — tree is empty"
    child = children[0]
    assert child.trace_id == root.trace_id, "trace ids diverged"
    assert child.parent_span_id == root.span_id, "parent link broken"
    assert root.span_id and len(root.trace_id) == 32
    tracing.set_tracer(None)
    elapsed_ms = (time.monotonic() - t0) * 1e3
    print(
        f"trace smoke OK: stitched tree of {len(tracer.spans())} spans "
        f"(trace {root.trace_id[:8]}…) in {elapsed_ms:.0f} ms"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

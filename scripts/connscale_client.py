#!/usr/bin/env python
"""Connection-scale load generator subprocess (BENCH_MODE=connscale).

Runs the epoll connscale client (core/h2_client.h2_connscale_run)
against ADDRESS and prints ONE JSON line with the results.  A
subprocess because fds are the scarce resource: at the 10k rung the
server (the bench process) and the client each hold one fd per
connection, and RLIMIT_NOFILE is per-process — colocating both halves
would cap the ramp at half the limit.

Usage: connscale_client.py ADDRESS CONNS ACTIVE SECONDS THREADS
"""

import json
import os
import resource
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    address, conns, active, seconds, threads = sys.argv[1:6]
    conns, active, threads = int(conns), int(active), int(threads)
    seconds = float(seconds)
    # Raise the fd ceiling to the hard limit; report what we got so a
    # clamped ramp is attributable in the artifact.
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
        soft = hard

    from gubernator_tpu.core import h2_client

    res = h2_client.connscale(
        address, "/pb.gubernator.V1/GetRateLimits",
        bytes.fromhex(os.environ["CONNSCALE_PAYLOAD_HEX"]),
        seconds, conns, active, threads=threads,
        ramp_budget_s=float(os.environ.get("CONNSCALE_RAMP_BUDGET", 120.0)),
    )
    if res is None:
        print(json.dumps({"error": "connscale client failed to connect"}))
        return 1
    import numpy as np

    lats = res.pop("lats_s")
    out = dict(res)
    out["rate"] = res["rpcs"] / seconds
    out["p50_ms"] = (
        round(float(np.percentile(lats, 50)) * 1e3, 3) if len(lats) else None
    )
    out["p99_ms"] = (
        round(float(np.percentile(lats, 99)) * 1e3, 3) if len(lats) else None
    )
    out["nofile_limit"] = soft
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

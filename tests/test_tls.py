"""TLS subsystem tests.

reference: tls_test.go — SetupTLS variants (:73-233), a full TLS
cluster with mTLS client auth (:235-289), HTTPS gateway (:291+).
"""

import json
import ssl
import urllib.request

import grpc
import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster.harness import cluster_behaviors
from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.net.tls import (
    TLSConfig,
    generate_self_ca,
    generate_server_cert,
)
from gubernator_tpu.types import RateLimitReq


def test_generate_self_ca_and_cert():
    ca, ca_key = generate_self_ca()
    assert b"BEGIN CERTIFICATE" in ca
    assert b"PRIVATE KEY" in ca_key
    cert, key = generate_server_cert(ca, ca_key, ["example.test"])
    assert b"BEGIN CERTIFICATE" in cert
    # The cert chains to the CA — verified with whichever x509 stack
    # the environment has (the openssl CLI backend mirrors the
    # cryptography-module backend; net/tls.py).
    try:
        from cryptography import x509
    except ImportError:
        import os
        import subprocess
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            with open(os.path.join(tmp, "ca.pem"), "wb") as f:
                f.write(ca)
            with open(os.path.join(tmp, "cert.pem"), "wb") as f:
                f.write(cert)
            subprocess.run(
                ["openssl", "verify", "-CAfile", "ca.pem", "cert.pem"],
                cwd=tmp, check=True, capture_output=True, timeout=30,
            )
            text = subprocess.run(
                ["openssl", "x509", "-in", "cert.pem", "-noout", "-text"],
                cwd=tmp, check=True, capture_output=True, timeout=30,
                text=True,
            ).stdout
        assert "DNS:example.test" in text
        return
    ca_obj = x509.load_pem_x509_certificate(ca)
    crt = x509.load_pem_x509_certificate(cert)
    assert crt.issuer == ca_obj.subject
    sans = crt.extensions.get_extension_for_class(
        x509.SubjectAlternativeName
    ).value
    assert "example.test" in sans.get_values_for_type(x509.DNSName)


def test_setup_auto_tls():
    bundle = TLSConfig(auto_tls=True).setup()
    assert bundle.ca_pem and bundle.server_cert_pem and bundle.server_key_pem
    assert bundle.server_credentials() is not None
    assert bundle.client_credentials() is not None


def test_setup_requires_material():
    with pytest.raises(ValueError):
        TLSConfig().setup()


@pytest.fixture(scope="module")
def tls_daemon():
    """A daemon serving gRPC+HTTPS with AutoTLS (reference:
    tls_test.go:235 TestSetupTLSWithCluster analog, single node)."""
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        behaviors=cluster_behaviors(),
        cache_size=1000,
        device_count=1,
        tls=TLSConfig(auto_tls=True, auto_tls_hosts=["127.0.0.1"]),
    )
    d = spawn_daemon(conf)
    yield d
    d.close()


def test_tls_grpc_round_trip(tls_daemon):
    creds = tls_daemon._tls_bundle.client_credentials()
    with V1Client(tls_daemon.grpc_address, credentials=creds) as c:
        r = c.get_rate_limits(
            [RateLimitReq(name="tls", unique_key="k", hits=1, limit=5, duration=60_000)],
            timeout=10,
        )[0]
        assert r.error == "" and r.remaining == 4


def test_tls_grpc_rejects_plaintext(tls_daemon):
    with V1Client(tls_daemon.grpc_address) as c:  # no credentials
        with pytest.raises(grpc.RpcError):
            c.health_check(timeout=3)


def test_https_gateway(tls_daemon):
    ctx = ssl.create_default_context()
    ctx.load_verify_locations(
        cadata=tls_daemon._tls_bundle.ca_pem.decode()
    )
    ctx.check_hostname = False
    body = urllib.request.urlopen(
        f"https://{tls_daemon.http_address}/v1/HealthCheck",
        context=ctx,
        timeout=5,
    ).read()
    assert json.loads(body)["status"] == "healthy"


def test_mtls_cluster():
    """Two daemons with required client auth forward between each
    other over mTLS (reference: tls_test.go:235-289)."""
    ca, ca_key = generate_self_ca()
    server_cert, server_key = generate_server_cert(ca, ca_key, ["127.0.0.1"])
    client_cert, client_key = generate_server_cert(ca, ca_key, ["127.0.0.1"])

    def conf():
        return DaemonConfig(
            grpc_listen_address="127.0.0.1:0",
            http_listen_address="127.0.0.1:0",
            behaviors=cluster_behaviors(),
            cache_size=1000,
            device_count=1,
            tls=TLSConfig(
                ca_pem=ca,
                cert_pem=server_cert,
                key_pem=server_key,
                client_auth="require-and-verify",
                client_auth_cert_pem=client_cert,
                client_auth_key_pem=client_key,
            ),
        )

    d1 = spawn_daemon(conf())
    d2 = spawn_daemon(conf())
    try:
        peers = [d1.peer_info(), d2.peer_info()]
        d1.set_peers(peers)
        d2.set_peers(peers)

        # Find a key owned by d2, ask d1 → peer-to-peer mTLS forward.
        from gubernator_tpu.client import random_string

        for i in range(200):
            req = RateLimitReq(
                name="mtls",
                unique_key=random_string(prefix=f"k{i}_"),
                hits=1,
                limit=5,
                duration=60_000,
            )
            owner = d1.instance.get_peer(req.hash_key())
            if not owner.info.is_owner:
                break
        assert not owner.info.is_owner
        creds = d1._tls_bundle.client_credentials()
        with V1Client(d1.grpc_address, credentials=creds) as c:
            r = c.get_rate_limits([req], timeout=10)[0]
            assert r.error == "" and r.remaining == 4
            assert r.metadata.get("owner") == d2.peer_info().grpc_address
    finally:
        d1.close()
        d2.close()

"""Gregorian interval math tests (reference: interval_test.go:29-137)."""

from datetime import datetime, timezone

import pytest

from gubernator_tpu.gregorian import (
    GREGORIAN_DAYS,
    GREGORIAN_HOURS,
    GREGORIAN_MINUTES,
    GREGORIAN_MONTHS,
    GREGORIAN_WEEKS,
    GREGORIAN_YEARS,
    GregorianError,
    gregorian_duration,
    gregorian_expiration,
)


def _dt(y, mo, d, h=0, mi=0, s=0, ms=0):
    return datetime(y, mo, d, h, mi, s, ms * 1000, tzinfo=timezone.utc)


def _ms(dt):
    return int(dt.timestamp() * 1000)


def test_minute_expiration():
    # Mirrors reference example (interval.go:115-116): 11:20:10 → 11:20:59.999
    now = _dt(2019, 1, 1, 11, 20, 10)
    assert gregorian_expiration(now, GREGORIAN_MINUTES) == _ms(_dt(2019, 1, 1, 11, 21)) - 1


def test_hour_day_expiration():
    now = _dt(2019, 6, 15, 11, 20, 10)
    assert gregorian_expiration(now, GREGORIAN_HOURS) == _ms(_dt(2019, 6, 15, 12, 0)) - 1
    assert gregorian_expiration(now, GREGORIAN_DAYS) == _ms(_dt(2019, 6, 16)) - 1


def test_month_year_expiration():
    now = _dt(2019, 12, 31, 23, 59, 59)
    assert gregorian_expiration(now, GREGORIAN_MONTHS) == _ms(_dt(2020, 1, 1)) - 1
    assert gregorian_expiration(now, GREGORIAN_YEARS) == _ms(_dt(2020, 1, 1)) - 1
    feb = _dt(2020, 2, 10)  # leap year
    assert gregorian_expiration(feb, GREGORIAN_MONTHS) == _ms(_dt(2020, 3, 1)) - 1


def test_durations():
    now = _dt(2020, 2, 10)
    assert gregorian_duration(now, GREGORIAN_MINUTES) == 60_000
    assert gregorian_duration(now, GREGORIAN_HOURS) == 3_600_000
    assert gregorian_duration(now, GREGORIAN_DAYS) == 86_400_000
    assert gregorian_duration(now, GREGORIAN_MONTHS) == 29 * 86_400_000  # leap Feb
    assert gregorian_duration(now, GREGORIAN_YEARS) == 366 * 86_400_000


def test_weeks_supported_here():
    #

    # The reference errors on weeks (interval.go:92-93); we support them
    # (documented divergence, gregorian.py module docstring).
    monday = _dt(2026, 7, 27)
    assert gregorian_expiration(monday, GREGORIAN_WEEKS) == _ms(_dt(2026, 8, 3)) - 1
    assert gregorian_duration(monday, GREGORIAN_WEEKS) == 7 * 86_400_000


def test_invalid_interval_raises():
    with pytest.raises(GregorianError):
        gregorian_expiration(_dt(2020, 1, 1), 42)
    with pytest.raises(GregorianError):
        gregorian_duration(_dt(2020, 1, 1), -1)

"""Concurrency storms — the -race suite analog.

reference: lrucache_test.go:111-246 (goroutine storms over the cache),
peer_client_test.go:31 (concurrent requests racing Shutdown).  Python
has no race detector; these tests assert the observable invariants
instead: no lost or misattributed responses, exact bucket accounting
under duplicate-key contention, clean drains while membership churns.
"""

import threading

import pytest

from gubernator_tpu.client import V1Client, random_string
from gubernator_tpu.cluster.harness import ClusterHarness
from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.types import Algorithm, RateLimitReq, Status

N_THREADS = 8
ROUNDS = 20


def _req(key, hits=1, limit=10**9, duration=3_600_000):
    return RateLimitReq(
        name="storm", unique_key=key, hits=hits, limit=limit, duration=duration
    )


def test_engine_storm_exact_accounting(frozen_clock):
    """N threads hammer ONE engine with a shared key + private keys;
    the shared bucket must consume exactly the sum of all hits (per-key
    serialization, reference: gubernator_pool.go:19-37), and every
    private bucket exactly its owner's hits."""
    engine = DecisionEngine(capacity=4096, clock=frozen_clock)
    limit = 10**9
    errs = []

    def worker(tid):
        try:
            for i in range(ROUNDS):
                # duplicate keys inside one batch AND across threads
                reqs = [_req("shared")] * 3 + [_req(f"private_{tid}")]
                resps = engine.get_rate_limits(reqs)
                for r in resps:
                    assert r.status == Status.UNDER_LIMIT
                    assert r.error == ""
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs

    shared = engine.get_rate_limits([_req("shared", hits=0)])[0]
    assert shared.remaining == limit - N_THREADS * ROUNDS * 3
    for tid in range(N_THREADS):
        private = engine.get_rate_limits([_req(f"private_{tid}", hits=0)])[0]
        assert private.remaining == limit - ROUNDS


def test_engine_columnar_storm_mixed_with_dataclass(frozen_clock):
    """Columnar and dataclass callers racing on the same engine keep
    exact accounting (both paths share the engine lock)."""
    import numpy as np

    engine = DecisionEngine(capacity=4096, clock=frozen_clock)
    limit = 10**9
    errs = []

    def columnar_worker():
        try:
            n = 4
            for _ in range(ROUNDS):
                engine.apply_columnar(
                    [b"storm_shared"] * n,
                    np.zeros(n, dtype=np.int32),
                    np.zeros(n, dtype=np.int32),
                    np.ones(n, dtype=np.int64),
                    np.full(n, limit, dtype=np.int64),
                    np.full(n, 3_600_000, dtype=np.int64),
                    np.zeros(n, dtype=np.int64),
                )
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def dataclass_worker():
        try:
            for _ in range(ROUNDS):
                engine.get_rate_limits([_req("shared", hits=2)])
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=columnar_worker) for _ in range(4)] + [
        threading.Thread(target=dataclass_worker) for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    # The columnar raw key b"storm_shared" IS the dataclass hash key
    # "storm"+"_"+"shared" — both paths hit ONE bucket, which must have
    # consumed exactly columnar (4 threads * ROUNDS * 4 hits) plus
    # dataclass (4 threads * ROUNDS * 2 hits).
    r = engine.get_rate_limits([_req("shared", hits=0)])[0]
    assert r.remaining == limit - (4 * ROUNDS * 4 + 4 * ROUNDS * 2)


@pytest.fixture(scope="module")
def storm_cluster():
    h = ClusterHarness().start(3)
    yield h
    h.stop()


def test_wire_storm_no_lost_responses(storm_cluster):
    """N clients hammer one daemon over gRPC; every batch must come
    back complete, ordered, and error-free (mixed local + forwarded
    keys)."""
    addr = storm_cluster.peer_at(0).grpc_address
    errs = []

    def worker(tid):
        try:
            with V1Client(addr) as c:
                key = f"wirestorm_{tid}"
                for i in range(ROUNDS):
                    # one private key (sequenced) + spray keys that land
                    # on all owners (forwarded + local mix)
                    reqs = [_req(key)] + [_req(f"spray_{tid}_{i}_{j}") for j in range(5)]
                    resps = c.get_rate_limits(reqs, timeout=15)
                    assert len(resps) == len(reqs)
                    for r in resps:
                        assert r.error == "", r.error
                        assert r.status == Status.UNDER_LIMIT
                # The private bucket consumed exactly ROUNDS hits.
                final = c.get_rate_limits([_req(key, hits=0)], timeout=15)[0]
                assert final.remaining == 10**9 - ROUNDS
        except Exception as e:  # noqa: BLE001
            errs.append((tid, e))

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs


def test_storm_racing_set_peers(storm_cluster):
    """Traffic keeps flowing while the peer list churns underneath
    (reference: SetPeers diff-rebuild, gubernator.go:657-740).  Requests
    may transiently error while ownership migrates, but must never hang
    or lose responses, and the picker swap must never corrupt routing."""
    d0 = storm_cluster.daemon_at(0)
    full = list(storm_cluster.peers())
    reduced = full[:2]  # drop daemon 2 from the view of daemon 0
    stop = threading.Event()
    errs = []

    def churner():
        flip = False
        while not stop.is_set():
            d0.set_peers(reduced if flip else full)
            flip = not flip
        d0.set_peers(full)

    def worker(tid):
        try:
            with V1Client(storm_cluster.peer_at(0).grpc_address) as c:
                for i in range(ROUNDS):
                    reqs = [_req(f"churn_{tid}_{i}_{j}") for j in range(4)]
                    resps = c.get_rate_limits(reqs, timeout=15)
                    assert len(resps) == len(reqs)
                    # Transient errors allowed mid-migration; success
                    # must be a real decision.
                    for r in resps:
                        if not r.error:
                            assert r.status in (
                                Status.UNDER_LIMIT,
                                Status.OVER_LIMIT,
                            )
        except Exception as e:  # noqa: BLE001
            errs.append((tid, e))

    churn = threading.Thread(target=churner)
    workers = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
    churn.start()
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    stop.set()
    churn.join(timeout=10)
    assert not churn.is_alive()
    assert not errs, errs


def test_storm_racing_peer_shutdown():
    """Concurrent forwarded requests racing a peer daemon's death
    (reference: peer_client_test.go:31).  In-flight requests either
    succeed or surface a peer error in the response; nothing hangs and
    the surviving daemon still serves local keys."""
    h = ClusterHarness().start(2)
    try:
        errs = []

        def worker(tid):
            try:
                with V1Client(h.peer_at(0).grpc_address) as c:
                    for i in range(ROUNDS * 2):
                        reqs = [_req(f"kill_{tid}_{i}_{j}") for j in range(4)]
                        resps = c.get_rate_limits(reqs, timeout=15)
                        assert len(resps) == len(reqs)
            except Exception as e:  # noqa: BLE001
                errs.append((tid, e))

        workers = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
        for t in workers:
            t.start()
        h.kill(1)
        for t in workers:
            t.join(timeout=120)
            assert not t.is_alive(), "request thread hung after peer death"
        assert not errs, errs
        # The survivor must still answer for keys it owns.
        with V1Client(h.peer_at(0).grpc_address) as c:
            d0 = h.daemon_at(0)
            for i in range(64):
                if d0.instance.get_peer(f"storm_alive_{i}").info.is_owner:
                    r = c.get_rate_limits([_req(f"alive_{i}")], timeout=15)[0]
                    assert r.error == ""
                    break
    finally:
        h.stop()


def test_hot_key_collapse_storm_exact_accounting(frozen_clock):
    """Threads race columnar hot-key batches (collapsed path) against
    dataclass batches of the same key; consumption must be exact and a
    bounded-limit bucket must never over-admit."""
    import numpy as np

    engine = DecisionEngine(capacity=1024, clock=frozen_clock)
    errs = []
    admitted = [0] * N_THREADS
    limit = N_THREADS * ROUNDS * 2  # exactly the total demand

    def col_batch(m):
        # Same canonical key as the dataclass path ("name_unique-key").
        return dict(
            keys=[b"storm_hot_storm"] * m,
            algo=np.zeros(m, dtype=np.int32),
            behavior=np.zeros(m, dtype=np.int32),
            hits=np.ones(m, dtype=np.int64),
            limit=np.full(m, limit, dtype=np.int64),
            duration=np.full(m, 3_600_000, dtype=np.int64),
            burst=np.zeros(m, dtype=np.int64),
        )

    def worker(tid):
        try:
            count = 0
            for i in range(ROUNDS):
                if tid % 2 == 0:
                    st, _, rem, _ = engine.apply_columnar(**col_batch(2))
                    count += int((st == 0).sum())
                else:
                    resps = engine.get_rate_limits(
                        [_req("hot_storm", limit=limit)] * 2
                    )
                    count += sum(
                        1 for r in resps if r.status == Status.UNDER_LIMIT
                    )
            admitted[tid] = count
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(N_THREADS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    # Demand == limit exactly: every hit must have been admitted, and
    # the bucket must now be exactly empty.
    assert sum(admitted) == limit
    final = engine.get_rate_limits([_req("hot_storm", hits=0, limit=limit)])[0]
    assert final.remaining == 0

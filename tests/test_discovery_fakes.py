"""etcd / kubernetes discovery behavior, exercised against in-process
fakes (VERDICT r1 item 6: the real client packages aren't in the image,
so without fakes the register/watch/re-register protocols never ran).

The fakes implement the exact client surface the backends consume:
etcd3's kv/lease/watch trio (reference protocol: etcd.go:110-316) and
CoreV1Api's pod list/watch (reference: kubernetes.go:48-244).
"""

import threading
import time
import types
from typing import Dict, List

from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.discovery.etcd import EtcdPool
from gubernator_tpu.discovery.kubernetes import K8sPool
from gubernator_tpu.types import PeerInfo


class FakeDaemon:
    """Just enough Daemon surface for a discovery backend."""

    def __init__(self, grpc="10.0.0.1:1051", http="10.0.0.1:1050"):
        self.grpc_address = grpc
        self.http_address = http
        self.pushes: List[List[PeerInfo]] = []
        self.pushed = threading.Event()

    def peer_info(self) -> PeerInfo:
        return PeerInfo(grpc_address=self.grpc_address, http_address=self.http_address)

    def set_peers(self, peers) -> None:
        self.pushes.append(list(peers))
        self.pushed.set()

    def wait_push(self, pred, timeout=5.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if any(pred(p) for p in self.pushes):
                return True
            time.sleep(0.01)
        return False


# ---------------------------------------------------------------- etcd


class FakeLease:
    def __init__(self, store: "FakeEtcd", ttl: int):
        self.store = store
        self.ttl = ttl
        self.keys: set = set()
        self.revoked = False
        self.fail_refresh = False
        self.refreshes = 0

    def refresh(self):
        if self.fail_refresh:
            raise ConnectionError("lease lost")
        self.refreshes += 1

    def revoke(self):
        self.revoked = True
        for k in list(self.keys):
            self.store.delete(k)


class FakeEtcd:
    """Dict + watch callbacks behind etcd3's client surface."""

    def __init__(self):
        self.kv: Dict[str, str] = {}
        self.leases: List[FakeLease] = []
        self._watches: Dict[int, tuple] = {}
        self._next_watch = 0
        self._lock = threading.Lock()

    def lease(self, ttl):
        lease = FakeLease(self, ttl)
        self.leases.append(lease)
        return lease

    def put(self, key, value, lease=None):
        with self._lock:
            self.kv[key] = value
            if lease is not None:
                lease.keys.add(key)
            watches = list(self._watches.values())
        for prefix, cb in watches:
            if key.startswith(prefix):
                cb(types.SimpleNamespace(key=key, value=value))

    def delete(self, key):
        with self._lock:
            existed = self.kv.pop(key, None) is not None
            watches = list(self._watches.values())
        if existed:
            for prefix, cb in watches:
                if key.startswith(prefix):
                    cb(types.SimpleNamespace(key=key, value=None))
        return existed

    def get_prefix(self, prefix):
        with self._lock:
            return [
                (v, types.SimpleNamespace(key=k))
                for k, v in self.kv.items()
                if k.startswith(prefix)
            ]

    def add_watch_prefix_callback(self, prefix, cb):
        with self._lock:
            self._next_watch += 1
            self._watches[self._next_watch] = (prefix, cb)
            return self._next_watch

    def cancel_watch(self, watch_id):
        with self._lock:
            self._watches.pop(watch_id, None)


def _etcd_pool(daemon, store, keepalive=0.05):
    return EtcdPool(
        DaemonConfig(), daemon, client=store, keepalive_interval=keepalive
    )


def test_etcd_register_and_watch():
    """Registration writes our lease-bound key; a peer's put triggers a
    peer push including both (reference: etcd.go:110-220)."""
    store = FakeEtcd()
    daemon = FakeDaemon()
    pool = _etcd_pool(daemon, store)
    pool.start()
    try:
        my_key = "/gubernator/peers/10.0.0.1:1051"
        assert my_key in store.kv
        assert store.leases and my_key in store.leases[0].keys

        store.put(
            "/gubernator/peers/10.0.0.2:1051",
            '{"grpc": "10.0.0.2:1051", "http": "10.0.0.2:1050", "dc": ""}',
        )
        assert daemon.wait_push(
            lambda peers: {p.grpc_address for p in peers}
            == {"10.0.0.1:1051", "10.0.0.2:1051"}
        )
    finally:
        pool.close()


def test_etcd_peer_departure():
    """A deleted peer key must push a shrunken peer list."""
    store = FakeEtcd()
    store.put("/gubernator/peers/10.0.0.2:1051", '{"grpc": "10.0.0.2:1051"}')
    daemon = FakeDaemon()
    pool = _etcd_pool(daemon, store)
    pool.start()
    try:
        store.delete("/gubernator/peers/10.0.0.2:1051")
        assert daemon.wait_push(
            lambda peers: {p.grpc_address for p in peers} == {"10.0.0.1:1051"}
        )
    finally:
        pool.close()


def test_etcd_lease_keepalive_and_reregister():
    """Keep-alive refreshes the lease; a failed refresh re-registers
    with a fresh lease (reference: etcd.go:222-316)."""
    store = FakeEtcd()
    daemon = FakeDaemon()
    pool = _etcd_pool(daemon, store, keepalive=0.02)
    pool.start()
    try:
        first = store.leases[0]
        deadline = time.monotonic() + 5
        while first.refreshes == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert first.refreshes > 0

        # Simulate a lost lease: the next refresh raises, and the etcd
        # server has dropped our key.
        store.delete("/gubernator/peers/10.0.0.1:1051")
        first.fail_refresh = True
        deadline = time.monotonic() + 5
        while len(store.leases) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(store.leases) >= 2, "re-register never created a new lease"
        assert "/gubernator/peers/10.0.0.1:1051" in store.kv
    finally:
        pool.close()


def test_etcd_close_deregisters():
    """Shutdown deletes our key and revokes the lease
    (reference: etcd.go:298-311)."""
    store = FakeEtcd()
    daemon = FakeDaemon()
    pool = _etcd_pool(daemon, store)
    pool.start()
    pool.close()
    assert "/gubernator/peers/10.0.0.1:1051" not in store.kv
    assert store.leases[-1].revoked
    assert not store._watches


def test_etcd_malformed_values_skipped():
    store = FakeEtcd()
    store.put("/gubernator/peers/bad", "not json")
    store.put("/gubernator/peers/nogrpc", '{"http": "x"}')
    daemon = FakeDaemon()
    pool = _etcd_pool(daemon, store)
    pool.start()
    try:
        assert daemon.wait_push(
            lambda peers: {p.grpc_address for p in peers} == {"10.0.0.1:1051"}
        )
    finally:
        pool.close()


# ----------------------------------------------------------------- k8s


def _pod(ip, ready=True):
    return types.SimpleNamespace(
        status=types.SimpleNamespace(
            pod_ip=ip,
            conditions=[
                types.SimpleNamespace(
                    type="Ready", status="True" if ready else "False"
                )
            ],
        )
    )


class FakeCoreV1:
    def __init__(self):
        self.pods: List = []
        self.lock = threading.Lock()

    def list_namespaced_pod(self, namespace, label_selector=None, **kw):
        with self.lock:
            return types.SimpleNamespace(items=list(self.pods))


class FakeWatch:
    """kubernetes.watch.Watch shape: stream() yields on pod events."""

    events: "queue.Queue" = None  # set per test

    def __init__(self):
        pass

    def stream(self, fn, *args, **kwargs):
        while True:
            ev = FakeWatch.events.get()
            if ev is None:
                return
            yield ev


import queue  # noqa: E402


def test_k8s_ready_pods_become_peers():
    """Initial list + watch events push ready-pod IPs as peers; pods
    that are not Ready are excluded (reference: kubernetes.go:190-244)."""
    core = FakeCoreV1()
    core.pods = [_pod("10.0.0.1"), _pod("10.0.0.2"), _pod("10.0.0.3", ready=False)]
    FakeWatch.events = queue.Queue()
    daemon = FakeDaemon()
    pool = K8sPool(
        DaemonConfig(), daemon, core_api=core, watch_factory=FakeWatch
    )
    pool.start()
    try:
        assert daemon.wait_push(
            lambda peers: {p.grpc_address for p in peers}
            == {"10.0.0.1:1051", "10.0.0.2:1051"}
        )
        # A new pod turns Ready: watch event → fresh list → push.
        with core.lock:
            core.pods.append(_pod("10.0.0.4"))
        FakeWatch.events.put(object())
        assert daemon.wait_push(
            lambda peers: {p.grpc_address for p in peers}
            == {"10.0.0.1:1051", "10.0.0.2:1051", "10.0.0.4:1051"}
        )
        # Pod death shrinks the peer list.
        with core.lock:
            core.pods = [p for p in core.pods if p.status.pod_ip != "10.0.0.2"]
        FakeWatch.events.put(object())
        assert daemon.wait_push(
            lambda peers: {p.grpc_address for p in peers}
            == {"10.0.0.1:1051", "10.0.0.4:1051"}
        )
    finally:
        # Mark closed BEFORE the sentinel: if the watch thread consumed
        # the sentinel first it would re-list and block on the empty
        # queue, stalling close()'s join.
        pool._closed.set()
        FakeWatch.events.put(None)
        pool.close()


def test_k8s_watch_failure_retries():
    """A broken watch stream must not kill the loop — it relists and
    resumes (reference: kubernetes.go watch restart)."""
    core = FakeCoreV1()
    core.pods = [_pod("10.0.0.9")]

    class FlakyWatch:
        calls = 0

        def stream(self, fn, *args, **kwargs):
            FlakyWatch.calls += 1
            if FlakyWatch.calls == 1:
                raise ConnectionError("watch dropped")
            while True:
                ev = FakeWatch.events.get()
                if ev is None:
                    return
                yield ev

    FakeWatch.events = queue.Queue()
    daemon = FakeDaemon()
    pool = K8sPool(
        DaemonConfig(), daemon, core_api=core, watch_factory=FlakyWatch
    )
    pool.start()
    try:
        deadline = time.monotonic() + 10
        while FlakyWatch.calls < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert FlakyWatch.calls >= 2, "watch loop did not restart after failure"
        assert daemon.wait_push(
            lambda peers: {p.grpc_address for p in peers} == {"10.0.0.9:1051"}
        )
    finally:
        pool._closed.set()
        FakeWatch.events.put(None)
        pool.close()

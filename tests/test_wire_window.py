"""WireWindow leader/follower failure paths (ADVICE r3).

The group-commit window must never hang the server's wire threads or
double-apply hits: a follower whose leader died falls back (None) only
when its entry was never taken; once a leader has swapped the batch
out, the follower waits for the apply however long it takes.
"""

import threading
import time

import numpy as np
import pytest

from gubernator_tpu.net.wire_window import WireWindow


class _Dec:
    """Minimal DecodedBatch stand-in (one key, one lane)."""

    def __init__(self, key=b"k"):
        self.n = 1
        self.key_buf = np.frombuffer(key, dtype=np.uint8).copy()
        self.key_offsets = np.asarray([0, len(key)], dtype=np.int64)
        for f in ("algo", "behavior"):
            setattr(self, f, np.zeros(1, dtype=np.int32))
        for f in ("hits", "limit", "duration", "burst"):
            setattr(self, f, np.ones(1, dtype=np.int64))
        self.fnv1a = np.zeros(1, dtype=np.uint64)


class _Engine:
    """Fake engine: counts applies; can stall inside the apply."""

    def __init__(self, stall: float = 0.0):
        self.stall = stall
        self.applies = 0
        self.lanes = 0

    def apply_columnar(self, packed, algo, behavior, hits, limit,
                       duration, burst):
        if self.stall:
            time.sleep(self.stall)
        self.applies += 1
        n = len(algo)
        self.lanes += n
        z = np.zeros(n, dtype=np.int64)
        return z, z, z, z


def test_follower_timeout_dead_leader_falls_back():
    """Leader died before swapping the batch: the follower must remove
    its (never-applied) entry and return None so the caller can use
    the protobuf path without double-counting."""
    ww = WireWindow(_Engine(), wait=0.01, follower_grace=0.05)
    ww._leader_active = True  # simulate a leader that died post-claim
    t0 = time.monotonic()
    assert ww.submit(_Dec()) is None
    assert time.monotonic() - t0 < 5.0
    assert ww._pending == []  # entry removed, not leaked
    assert ww.engine.applies == 0
    # Leadership was released: the next request leads a fresh window
    # immediately instead of eating the follower timeout forever.
    assert not ww._leader_active
    t0 = time.monotonic()
    assert ww.submit(_Dec()) is not None
    assert time.monotonic() - t0 < 0.05 + 1.0
    assert ww.engine.applies == 1


def test_follower_waits_out_inflight_apply_no_double_count():
    """Once a leader swapped the batch out, a slow engine apply must
    NOT push the follower to the fallback path (that would apply the
    same hits twice); it waits and gets the windowed result."""
    eng = _Engine(stall=0.5)
    # adaptive=False: the scenario needs a real leader sleep so the
    # followers deterministically join the first window.
    ww = WireWindow(eng, wait=0.05, follower_grace=0.01, adaptive=False)
    results = {}

    def caller(name):
        results[name] = ww.submit(_Dec())

    threads = [threading.Thread(target=caller, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
        time.sleep(0.005)  # deterministic leader, followers join window
    for t in threads:
        t.join(timeout=10)
    assert all(not t.is_alive() for t in threads)
    # grace (0.01+wait*10=0.51... make stall dominate) — every caller
    # got a real result and the engine ran exactly one window.
    assert all(r is not None for r in results.values())
    assert eng.applies == 1
    assert eng.lanes == 3


def test_leader_exception_during_window_releases_leadership():
    """An injected exception while the leader sleeps must fail the
    pending entries (followers unblock with None) and release
    _leader_active so the next request can lead."""
    eng = _Engine()
    # adaptive=False: the injected exception targets the leader's
    # fixed-length sleep (secs == ww.wait below).
    ww = WireWindow(eng, wait=0.05, follower_grace=0.2, adaptive=False)
    orig_sleep = time.sleep
    fired = [False]

    def boom(secs):
        if secs == ww.wait and not fired[0]:
            fired[0] = True
            orig_sleep(0.1)  # let the follower join the window first
            raise KeyboardInterrupt("injected")
        orig_sleep(secs)

    follower_res = []

    def follower():
        orig_sleep(0.01)  # join after the leader claims the window
        follower_res.append(ww.submit(_Dec()))

    th = threading.Thread(target=follower)
    time.sleep = boom
    try:
        th.start()
        with pytest.raises(KeyboardInterrupt):
            ww.submit(_Dec())
    finally:
        time.sleep = orig_sleep
    th.join(timeout=10)
    assert not th.is_alive()
    assert not ww._leader_active
    assert ww._pending == []
    # Both entries failed closed (None → caller falls back); since the
    # engine never ran, the fallback cannot double-count.
    assert follower_res == [None]
    assert eng.applies == 0
    # The window is usable again: a fresh submit leads and applies.
    assert ww.submit(_Dec()) is not None
    assert eng.applies == 1

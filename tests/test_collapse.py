"""Collapsed duplicate-segment kernel ⇄ sequential semantics (fuzzed).

Hot-key batches collapse each uniform duplicate segment into ONE
device dispatch with a closed form for the sequential per-occurrence
responses (bucket_kernel COLLAPSED_IN_ROWS).  These tests pin exact
equality against (a) the rounds path (the proven sequential execution)
and (b) the scalar spec, across token/leaky, new/existing buckets,
over-limit boundaries, negative hits, queries, and eviction pressure.
"""

import numpy as np
import pytest

from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.types import Algorithm, Behavior


def _columns(rng, n, n_keys, *, uniform=True, hits_range=(0, 4)):
    kidx = rng.integers(0, n_keys, n)
    keys = [b"ck%d" % i for i in kidx]
    if uniform:
        # Per-KEY uniform fields (the collapse precondition).
        per_key_algo = rng.integers(0, 2, n_keys).astype(np.int32)
        per_key_hits = rng.integers(*hits_range, n_keys).astype(np.int64)
        per_key_limit = rng.integers(1, 12, n_keys).astype(np.int64)
        per_key_burst = rng.integers(0, 14, n_keys).astype(np.int64)
        algo = per_key_algo[kidx]
        hits = per_key_hits[kidx]
        limit = per_key_limit[kidx]
        burst = per_key_burst[kidx]
    else:
        algo = rng.integers(0, 2, n).astype(np.int32)
        hits = rng.integers(*hits_range, n).astype(np.int64)
        limit = rng.integers(1, 12, n).astype(np.int64)
        burst = rng.integers(0, 14, n).astype(np.int64)
    return dict(
        keys=keys,
        algo=algo,
        behavior=np.zeros(n, dtype=np.int32),
        hits=hits,
        limit=limit,
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=burst,
    )


def _run(engine, cols, now):
    st, lim, rem, rst = engine.apply_columnar(now_ms=now, **cols)
    return st.tolist(), rem.tolist(), rst.tolist()


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_collapse_matches_rounds_fuzz(frozen_clock, seed):
    """Same random duplicate-heavy traffic through two engines — one
    collapsing, one forced onto the rounds path — must answer
    identically, batch after batch (state evolves too)."""
    rng = np.random.default_rng(seed)
    e_fast = DecisionEngine(capacity=256, clock=frozen_clock)
    e_slow = DecisionEngine(capacity=256, clock=frozen_clock)
    e_slow._try_collapse = lambda *a, **k: None  # force rounds

    now = frozen_clock.now_ms()
    for batch in range(12):
        n = int(rng.integers(1, 120))
        # Odd seeds include negative hits (exercises the leaky
        # negative-duplicate fallback to rounds).
        hr = (-2, 4) if seed % 2 else (0, 4)
        cols = _columns(rng, n, n_keys=6, hits_range=hr)
        assert _run(e_fast, cols, now) == _run(e_slow, cols, now), (
            f"seed={seed} batch={batch}"
        )
        now += int(rng.integers(0, 30_000))


def test_collapse_token_over_limit_boundary(frozen_clock):
    """20 duplicates of one token key, limit 7, hits 2: positions
    0-2 consume (5,3,1 remaining), the rest reject without consuming."""
    eng = DecisionEngine(capacity=64, clock=frozen_clock)
    n = 20
    cols = dict(
        keys=[b"hot"] * n,
        algo=np.zeros(n, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.full(n, 2, dtype=np.int64),
        limit=np.full(n, 7, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
    )
    st, lim, rem, rst = eng.apply_columnar(**cols)
    assert rem[:3].tolist() == [5, 3, 1]
    assert st[:3].tolist() == [0, 0, 0]
    assert st[3:].tolist() == [1] * 17  # OVER, no consume
    assert rem[3:].tolist() == [1] * 17
    # One more batch: bucket still has 1 left.
    st, _, rem, _ = eng.apply_columnar(
        **{**cols, "keys": [b"hot"], "algo": cols["algo"][:1],
           "behavior": cols["behavior"][:1], "hits": np.asarray([1]),
           "limit": cols["limit"][:1], "duration": cols["duration"][:1],
           "burst": cols["burst"][:1]}
    )
    assert (st[0], rem[0]) == (0, 0)


def test_collapse_sticky_over_and_queries(frozen_clock):
    """Exact drain flips the token sticky status only when an extra
    actually sees remaining==0; queries (hits=0) never consume."""
    eng = DecisionEngine(capacity=64, clock=frozen_clock)

    def batch(k, hits, m, limit=4):
        n = m
        return dict(
            keys=[k] * n,
            algo=np.zeros(n, dtype=np.int32),
            behavior=np.zeros(n, dtype=np.int32),
            hits=np.full(n, hits, dtype=np.int64),
            limit=np.full(n, limit, dtype=np.int64),
            duration=np.full(n, 60_000, dtype=np.int64),
            burst=np.zeros(n, dtype=np.int64),
        )

    # 5 x hits=1 on limit 4: last one sees 0 remaining → OVER.
    st, _, rem, _ = eng.apply_columnar(**batch(b"a", 1, 5))
    assert rem.tolist() == [3, 2, 1, 0, 0]
    assert st.tolist() == [0, 0, 0, 0, 1]
    # Queries reflect the stored (now sticky-OVER) status, no consume.
    st, _, rem, _ = eng.apply_columnar(**batch(b"a", 0, 3))
    assert st.tolist() == [1, 1, 1]
    assert rem.tolist() == [0, 0, 0]


def test_collapse_negative_hits_refill(frozen_clock):
    eng = DecisionEngine(capacity=64, clock=frozen_clock)
    n = 4
    base = dict(
        algo=np.zeros(n, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        limit=np.full(n, 10, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
    )
    eng.apply_columnar(keys=[b"neg"] * n, hits=np.full(n, 2, np.int64), **base)
    st, _, rem, _ = eng.apply_columnar(
        keys=[b"neg"] * n, hits=np.full(n, -1, np.int64), **base
    )
    assert rem.tolist() == [3, 4, 5, 6]
    assert st.tolist() == [0, 0, 0, 0]


def test_nonuniform_duplicates_fall_back_to_rounds(frozen_clock):
    """Duplicates with DIFFERENT limits must keep exact sequential
    semantics via the rounds path."""
    eng = DecisionEngine(capacity=64, clock=frozen_clock)
    n = 3
    cols = dict(
        keys=[b"nu"] * n,
        algo=np.zeros(n, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.ones(n, dtype=np.int64),
        limit=np.asarray([10, 20, 20], dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
    )
    st, lim, rem, rst = eng.apply_columnar(**cols)
    # Sequential: 10-1=9; limit change 10→20 adds +10 → 19-1=18; 17.
    assert rem.tolist() == [9, 18, 17]


def test_collapse_leaky_segments(frozen_clock):
    eng = DecisionEngine(capacity=64, clock=frozen_clock)
    n = 8
    cols = dict(
        keys=[b"lk"] * n,
        algo=np.ones(n, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.full(n, 3, dtype=np.int64),
        limit=np.full(n, 10, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=np.full(n, 10, dtype=np.int64),
    )
    st, _, rem, rst = eng.apply_columnar(**cols)
    # 10 → 7 → 4 → 1 → then 3 > 1: reject without consuming.
    assert rem.tolist() == [7, 4, 1, 1, 1, 1, 1, 1]
    assert st.tolist() == [0, 0, 0, 1, 1, 1, 1, 1]
    # reset_time slope: rate = 60000/10 = 6000ms per unit.
    now = frozen_clock.now_ms()
    assert rst[0] == now + (10 - 7) * 6000
    assert rst[2] == now + (10 - 1) * 6000


def test_collapse_under_eviction_pressure(frozen_clock):
    """Evictions (round-0 clears) coexist with collapsed dispatch; a
    tiny capacity forces slot reuse across batches."""
    rng = np.random.default_rng(9)
    e_fast = DecisionEngine(capacity=16, clock=frozen_clock)
    e_slow = DecisionEngine(capacity=16, clock=frozen_clock)
    e_slow._try_collapse = lambda *a, **k: None
    now = frozen_clock.now_ms()
    for batch in range(10):
        n = int(rng.integers(2, 60))
        cols = _columns(rng, n, n_keys=40)  # >> capacity → evictions
        assert _run(e_fast, cols, now) == _run(e_slow, cols, now), batch
        now += 1_000


def test_leaky_negative_hits_duplicates_match_rounds(frozen_clock):
    """Sequential leaky semantics re-clamp remaining to burst on every
    gather; negative-hit duplicate segments must take the rounds path
    (review repro: limit 10 at remaining 2, then 4x hits=-3 →
    [5, 8, 11, 13], stored 13 — NOT 14)."""
    eng = DecisionEngine(capacity=64, clock=frozen_clock)
    n1 = 1
    base = dict(
        algo=np.ones(1, dtype=np.int32),
        behavior=np.zeros(1, dtype=np.int32),
        limit=np.full(1, 10, dtype=np.int64),
        duration=np.full(1, 60_000, dtype=np.int64),
        burst=np.zeros(1, dtype=np.int64),
    )
    eng.apply_columnar(keys=[b"lneg"], hits=np.asarray([8]), **base)
    n = 4
    base4 = {k: np.repeat(v, n) for k, v in base.items()}
    st, _, rem, _ = eng.apply_columnar(
        keys=[b"lneg"] * n, hits=np.full(n, -3, np.int64), **base4
    )
    assert rem.tolist() == [5, 8, 11, 13]
    # The next gather re-clamps the stored 13 to the burst (10).
    st, _, rem, _ = eng.apply_columnar(
        keys=[b"lneg"], hits=np.asarray([0]), **base
    )
    assert rem.tolist() == [10]


def test_sharded_collapse_matches_rounds_fuzz(frozen_clock):
    """The sharded engine's per-shard collapse must equal its own
    rounds path on duplicate-heavy traffic."""
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 virtual devices")
    from gubernator_tpu.parallel.mesh import make_mesh
    from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine

    rng = np.random.default_rng(21)
    mesh = make_mesh(jax.devices()[:2])
    e_fast = ShardedDecisionEngine(
        shard_capacity=128, mesh=mesh, clock=frozen_clock
    )
    e_slow = ShardedDecisionEngine(
        shard_capacity=128, mesh=make_mesh(jax.devices()[:2]),
        clock=frozen_clock,
    )
    e_slow._try_collapse_sharded = lambda *a, **k: None

    now = frozen_clock.now_ms()
    for batch in range(8):
        n = int(rng.integers(2, 100))
        cols = _columns(rng, n, n_keys=5, hits_range=(-1, 4))
        assert _run(e_fast, cols, now) == _run(e_slow, cols, now), batch
        now += int(rng.integers(0, 20_000))


def test_dataclass_path_collapse_matches_rounds(frozen_clock):
    """The dataclass path (get_rate_limits) also collapses hot keys;
    equality with its rounds fallback, fuzzed."""
    from gubernator_tpu.types import RateLimitReq

    rng = np.random.default_rng(31)
    e_fast = DecisionEngine(capacity=128, clock=frozen_clock)
    e_slow = DecisionEngine(capacity=128, clock=frozen_clock)
    e_slow._collapse_dataclass = lambda *a, **k: False

    def reqs_of(n):
        out = []
        for _ in range(n):
            k = int(rng.integers(0, 5))
            out.append(
                RateLimitReq(
                    name="dc",
                    unique_key=f"k{k}",
                    hits=int(rng.integers(0, 4)),
                    limit=5 + k,
                    duration=60_000,
                    algorithm=Algorithm(k % 2),
                    burst=8 + k,
                )
            )
        return out

    now = frozen_clock.now_ms()
    for batch in range(10):
        rs = reqs_of(int(rng.integers(2, 60)))
        a = [(r.status, r.remaining, r.reset_time, r.error)
             for r in e_fast.get_rate_limits(rs, now_ms=now)]
        b = [(r.status, r.remaining, r.reset_time, r.error)
             for r in e_slow.get_rate_limits(rs, now_ms=now)]
        assert a == b, batch
        now += int(rng.integers(0, 20_000))


def test_sharded_dataclass_collapse_matches_rounds(frozen_clock):
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 virtual devices")
    from gubernator_tpu.parallel.mesh import make_mesh
    from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine
    from gubernator_tpu.types import RateLimitReq

    rng = np.random.default_rng(41)
    e_fast = ShardedDecisionEngine(
        shard_capacity=64, mesh=make_mesh(jax.devices()[:2]),
        clock=frozen_clock,
    )
    e_slow = ShardedDecisionEngine(
        shard_capacity=64, mesh=make_mesh(jax.devices()[:2]),
        clock=frozen_clock,
    )
    e_slow._collapse_dataclass_sharded = lambda *a, **k: False

    def reqs_of(n):
        out = []
        for _ in range(n):
            k = int(rng.integers(0, 5))
            out.append(
                RateLimitReq(
                    name="sdc",
                    unique_key=f"k{k}",
                    hits=int(rng.integers(0, 4)),
                    limit=5 + k,
                    duration=60_000,
                    algorithm=Algorithm(k % 2),
                    burst=8 + k,
                )
            )
        return out

    now = frozen_clock.now_ms()
    for batch in range(8):
        rs = reqs_of(int(rng.integers(2, 60)))
        a = [(r.status, r.remaining, r.reset_time, r.error)
             for r in e_fast.get_rate_limits(rs, now_ms=now)]
        b = [(r.status, r.remaining, r.reset_time, r.error)
             for r in e_slow.get_rate_limits(rs, now_ms=now)]
        assert a == b, batch
        now += int(rng.integers(0, 20_000))


def test_gregorian_duplicates_collapse_matches_rounds(frozen_clock):
    """DURATION_IS_GREGORIAN segments are uniform per key (same greg
    fields) and must collapse identically to the rounds path."""
    from gubernator_tpu.types import RateLimitReq

    GREG_MINUTES = 1  # interval enum (gregorian.py)
    e_fast = DecisionEngine(capacity=64, clock=frozen_clock)
    e_slow = DecisionEngine(capacity=64, clock=frozen_clock)
    e_slow._collapse_dataclass = lambda *a, **k: False

    def reqs(n, algo):
        return [
            RateLimitReq(
                name="greg",
                unique_key="dup",
                hits=2,
                limit=30,
                duration=GREG_MINUTES,
                algorithm=algo,
                behavior=Behavior.DURATION_IS_GREGORIAN,
                burst=30,
            )
            for _ in range(n)
        ]

    now = frozen_clock.now_ms()
    for algo in (Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET):
        a = [(r.status, r.remaining, r.reset_time, r.error)
             for r in e_fast.get_rate_limits(reqs(7, algo), now_ms=now)]
        b = [(r.status, r.remaining, r.reset_time, r.error)
             for r in e_slow.get_rate_limits(reqs(7, algo), now_ms=now)]
        assert a == b, algo
        assert all(x[3] == "" for x in a)
        now += 10_000

"""Gossip hardening: datagram segmentation, packet loss, 50-member soak.

VERDICT r2 item 7 — the old wire format was the full member map in ONE
datagram with a documented-but-unenforced size limit; an oversized map
silently failed to gossip.  These tests drive MemberListPool directly
(lightweight fake daemons, no TPU engines) and pin:

- segmentation: maps larger than max_datagram still converge (every
  segment is a standalone partial map);
- loss tolerance: 30% of sends dropped, membership still converges
  (anti-entropy full-map gossip re-sends everything each interval);
- scale: 50 members converge and survive member death.

reference analog: memberlist.go:126-233 (hashicorp memberlist handles
these internally; this backend must handle them itself).
"""

import random
import threading
import time

from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.discovery.memberlist import MemberListPool
from gubernator_tpu.types import PeerInfo


class FakeDaemon:
    """Just enough daemon for a discovery backend: peer_info() and
    set_peers()."""

    def __init__(self, idx: int):
        self.info = PeerInfo(
            grpc_address=f"127.0.0.1:{20000 + idx}",
            http_address=f"127.0.0.1:{30000 + idx}",
        )
        self._lock = threading.Lock()
        self.peers = []

    def peer_info(self) -> PeerInfo:
        return self.info

    def set_peers(self, peers) -> None:
        with self._lock:
            self.peers = list(peers)

    def peer_count(self) -> int:
        with self._lock:
            return len(self.peers)


def _conf(known_hosts):
    return DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        member_list_address="127.0.0.1:0",
        known_hosts=known_hosts,
        advertise_port=0,
    )


def _until(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _start_pools(n, *, max_datagram=1200, drop=0.0, interval=0.05,
                 suspect_after=2.0, seed_rng=0):
    """n gossip pools wired through fake daemons; pool 0 seeds the rest.
    `drop` patches the send seam to lose that fraction of datagrams."""
    rng = random.Random(seed_rng)
    daemons = [FakeDaemon(i) for i in range(n)]
    pools = []
    seed = None
    for i, d in enumerate(daemons):
        p = MemberListPool(
            _conf([seed] if seed else []),
            d,
            interval=interval,
            suspect_after=suspect_after,
            fanout=3,
            max_datagram=max_datagram,
        )
        if drop > 0:
            orig = p._send

            def lossy(blob, addr, _orig=orig):
                if rng.random() >= drop:
                    _orig(blob, addr)

            p._send = lossy
        if seed is None:
            seed = p.gossip_address
        pools.append(p)
    for p in pools:
        p.start()
    return daemons, pools


def _stop(pools):
    for p in pools:
        p.close()


def test_segmentation_converges_with_tiny_datagrams():
    """max_datagram far below the map size → multi-segment gossip, full
    convergence (each member entry is ~120 bytes; 8 members ≫ 300B)."""
    daemons, pools = _start_pools(8, max_datagram=300)
    try:
        assert _until(lambda: all(d.peer_count() == 8 for d in daemons)), [
            d.peer_count() for d in daemons
        ]
        # Segmentation really happened: the snapshot encodes to >1
        # segment, each within budget (allowing the self-entry floor).
        segs = pools[0]._encode_segments(pools[0]._snapshot())
        assert len(segs) > 1
        assert all(len(s) <= 300 for s in segs)
    finally:
        _stop(pools)


def test_convergence_under_30pct_loss():
    daemons, pools = _start_pools(10, drop=0.30)
    try:
        assert _until(
            lambda: all(d.peer_count() == 10 for d in daemons), timeout=45
        ), [d.peer_count() for d in daemons]
    finally:
        _stop(pools)


def test_50_member_soak_with_deaths():
    daemons, pools = _start_pools(50, interval=0.1, suspect_after=3.0)
    try:
        assert _until(
            lambda: all(d.peer_count() == 50 for d in daemons), timeout=60
        ), sorted(d.peer_count() for d in daemons)

        # Kill 5 members; survivors drop them and do NOT resurrect.
        for p in pools[45:]:
            p.close()
        assert _until(
            lambda: all(d.peer_count() == 45 for d in daemons[:45]),
            timeout=60,
        ), sorted(d.peer_count() for d in daemons[:45])
        time.sleep(1.0)  # several gossip rounds of resurrection window
        assert all(d.peer_count() == 45 for d in daemons[:45])
    finally:
        _stop(pools)

"""Columnar GLOBAL wire plane: codec round-trips and cluster-path
equivalence with the pb path (service._serve_wire_global,
wire_codec.encode/decode_globals, GlobalManager chunk queues)."""

import numpy as np
import pytest

from gubernator_tpu.net import wire_codec
from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.net.pb import peers_pb2 as peers_pb

pytestmark = pytest.mark.skipif(
    wire_codec.load() is None, reason="native codec unavailable"
)


def _globals_payload(items):
    msg = peers_pb.UpdatePeerGlobalsReq()
    for key, algo, st, lim, rem, rst in items:
        g = msg.globals.add()
        g.key = key
        g.algorithm = algo
        g.status.status = st
        g.status.limit = lim
        g.status.remaining = rem
        g.status.reset_time = rst
    return msg.SerializeToString()


def test_decode_globals_matches_pb():
    items = [
        ("a_k1", 0, 1, 100, 0, 999_999),
        ("b_k2", 1, 0, 50, 49, 123_456),
        ("c_long_name_key", 0, 0, 0, 0, 0),
    ]
    dec = wire_codec.decode_globals(_globals_payload(items), 1000)
    assert dec is not None and dec.n == 3
    raw = dec.key_buf.tobytes()
    keys = [
        raw[dec.key_offsets[i]:dec.key_offsets[i + 1]].decode()
        for i in range(3)
    ]
    assert keys == [i[0] for i in items]
    assert dec.algo.tolist() == [0, 1, 0]
    assert dec.status.tolist() == [1, 0, 0]
    assert dec.limit.tolist() == [100, 50, 0]
    assert dec.remaining.tolist() == [0, 49, 0]
    assert dec.reset_time.tolist() == [999_999, 123_456, 0]
    assert dec.has_status.tolist() == [1, 1, 1]


def test_encode_globals_roundtrip_via_pb_parser():
    keys = [b"n1_k%d" % i for i in range(50)]
    key_buf = np.frombuffer(b"".join(keys), dtype=np.uint8)
    off = np.zeros(51, dtype=np.int64)
    np.cumsum([len(k) for k in keys], out=off[1:])
    algo = (np.arange(50) % 2).astype(np.int32)
    status = (np.arange(50) % 2).astype(np.int32)
    limit = np.arange(50, dtype=np.int64) * 7
    remaining = np.arange(50, dtype=np.int64) * 3
    reset = np.arange(50, dtype=np.int64) + 10**12
    raw = wire_codec.encode_globals(
        key_buf, off, algo, status, limit, remaining, reset
    )
    msg = peers_pb.UpdatePeerGlobalsReq.FromString(raw)
    assert len(msg.globals) == 50
    for i, g in enumerate(msg.globals):
        assert g.key == keys[i].decode()
        assert g.algorithm == int(algo[i])
        assert g.status.status == int(status[i])
        assert g.status.limit == int(limit[i])
        assert g.status.remaining == int(remaining[i])
        assert g.status.reset_time == int(reset[i])


def test_encode_resps_owner_metadata_roundtrip():
    n = 6
    status = np.array([0, 1, 0, 1, 0, 0], dtype=np.int32)
    limit = np.full(n, 42, dtype=np.int64)
    remaining = np.arange(n, dtype=np.int64)
    reset = np.full(n, 5_000, dtype=np.int64)
    owner_idx = np.array([0, 0, -1, 1, 1, -1], dtype=np.int32)
    owners = [b"10.0.0.1:81", b"10.0.0.2:82"]
    raw = wire_codec.encode_resps_owner(
        status, limit, remaining, reset, owner_idx, owners
    )
    msg = pb.GetRateLimitsResp.FromString(raw)
    assert len(msg.responses) == n
    for i, r in enumerate(msg.responses):
        assert r.status == int(status[i])
        assert r.remaining == int(remaining[i])
        if owner_idx[i] >= 0:
            assert r.metadata["owner"] == owners[owner_idx[i]].decode()
        else:
            assert "owner" not in r.metadata


def test_global_wire_path_equivalence_single_owner():
    """A single-node daemon (owner) serving an all-GLOBAL wire batch
    must give byte-identical decisions to the pb path (which queues
    updates + runs the engine) — and queue the broadcast."""
    import jax

    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.cluster.harness import cluster_behaviors
    from gubernator_tpu.types import Behavior

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        behaviors=cluster_behaviors(),
        cache_size=4096,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
    )
    d = spawn_daemon(conf)
    try:
        reqs = [
            pb.RateLimitReq(
                name="gw", unique_key=f"k{i}", hits=1, limit=100,
                duration=60_000, behavior=int(Behavior.GLOBAL),
            )
            for i in range(40)
        ]
        raw = pb.GetRateLimitsReq(requests=reqs).SerializeToString()
        out = d.instance.serve_wire_bytes(raw)
        assert out is not None, "GLOBAL wire fast path must engage"
        resp = pb.GetRateLimitsResp.FromString(out)
        assert len(resp.responses) == 40
        assert all(r.remaining == 99 for r in resp.responses)
        assert all(r.error == "" for r in resp.responses)
        # Broadcast updates were queued columnar — the adaptive window
        # may already have flushed them (idle batchers fire fast), in
        # which case the broadcast counter moved instead.
        import time as _time

        gm = d.instance.global_mgr
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline:
            if gm._updates.pending() >= 40 or gm.broadcasts >= 1:
                break
            _time.sleep(0.005)
        assert gm._updates.pending() >= 40 or gm.broadcasts >= 1
    finally:
        d.close()


def test_encode_peer_reqs_roundtrip_via_pb_parser():
    keys = [b"nm_%d_k%d" % (i % 3, i) for i in range(40)]
    name_len = np.array([len(b"nm_%d" % (i % 3)) for i in range(40)],
                        dtype=np.int32)
    key_buf = np.frombuffer(b"".join(keys), dtype=np.uint8)
    off = np.zeros(41, dtype=np.int64)
    np.cumsum([len(k) for k in keys], out=off[1:])
    algo = (np.arange(40) % 2).astype(np.int32)
    behavior = np.full(40, 2, dtype=np.int32)  # GLOBAL
    hits = np.arange(40, dtype=np.int64) + 1
    limit = np.full(40, 1000, dtype=np.int64)
    duration = np.full(40, 60_000, dtype=np.int64)
    burst = np.zeros(40, dtype=np.int64)
    raw = wire_codec.encode_peer_reqs(
        key_buf, off, name_len, algo, behavior, hits, limit, duration,
        burst,
    )
    msg = peers_pb.GetPeerRateLimitsReq.FromString(raw)
    assert len(msg.requests) == 40
    for i, r in enumerate(msg.requests):
        kb = keys[i]
        nl = int(name_len[i])
        assert r.name == kb[:nl].decode()
        assert r.unique_key == kb[nl + 1:].decode()
        assert r.hits == i + 1
        assert r.limit == 1000 and r.duration == 60_000
        assert r.algorithm == int(algo[i]) and r.behavior == 2


def test_columnar_hits_fanout_converges(frozen_clock):
    """2-node cluster: non-owner GLOBAL wire traffic must reach the
    owner through the COLUMNAR hits fan-out (aggregate → route by
    hash → C encode → raw RPC) with exact summed accounting."""
    from gubernator_tpu.cluster.harness import ClusterHarness
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.types import Behavior, RateLimitReq

    behaviors = BehaviorConfig(
        global_sync_wait=3600.0, global_batch_limit=10**9,
    )
    h = ClusterHarness().start(
        2, clock=frozen_clock, behaviors=behaviors, cache_size=4096
    )
    try:
        inst0 = h.daemon_at(0).instance
        inst1 = h.daemon_at(1).instance
        key = next(
            f"{i}cf" for i in range(500)
            if not inst0.get_peer(
                RateLimitReq(name="cw", unique_key=f"{i}cf").hash_key()
            ).info.is_owner
        )
        reqs = [
            pb.RateLimitReq(
                name="cw", unique_key=key, hits=2, limit=10**6,
                duration=3_600_000, behavior=int(Behavior.GLOBAL),
            )
        ] * 7
        raw = pb.GetRateLimitsReq(requests=reqs).SerializeToString()
        for _ in range(3):
            assert inst0.serve_wire_bytes(raw) is not None
        # Everything is queued as chunks (no dict entries): the flush
        # must take the columnar fan-out and the owner must count
        # exactly 3 batches x 7 dups x 2 hits = 42.
        inst0.global_mgr.flush_now()
        ro = inst1.get_rate_limits(
            [RateLimitReq(name="cw", unique_key=key, hits=0,
                          limit=10**6, duration=3_600_000)]
        )[0]
        assert 10**6 - ro.remaining == 42, ro
    finally:
        h.stop()

"""Adaptive batching windows + the GLOBAL stage-budget instrumentation
(round 6, VERDICT r5 weak #2 / next-round #3).

The *_wait knobs are CAPS: an idle batcher fires immediately instead of
waiting out its window, and the wait grows toward the cap only while
batches actually fill.  The five-stage pipeline budget (client window,
engine serve, hit window, owner RPC, broadcast age) is measured where
it happens and exported as gubernator_stage_duration{stage=...}.
"""

import time

import numpy as np
import pytest

from gubernator_tpu.cluster.batch_loop import AdaptiveWait, IntervalBatcher
from gubernator_tpu.net.wire_window import WireWindow


def _combine(existing, item):
    return (existing or 0) + item


# ---------------------------------------------------------------------
# AdaptiveWait semantics


def test_adaptive_wait_starts_immediate_grows_with_fill():
    aw = AdaptiveWait(0.5, 1000)
    assert aw.next_wait() == 0.0  # cold start: no wait
    for _ in range(20):
        aw.observe(1000)  # windows fill completely
    assert aw.next_wait() == pytest.approx(0.5)  # full cap
    for _ in range(40):
        aw.observe(1)  # traffic stops filling windows
    assert aw.next_wait() < 0.01  # decays back toward immediate


def test_adaptive_wait_zero_cap_stays_zero():
    aw = AdaptiveWait(0.0, 1000)
    aw.observe(1000)
    assert aw.next_wait() == 0.0


# ---------------------------------------------------------------------
# IntervalBatcher: idle windows must not wait out their cap


def test_idle_interval_batcher_fires_without_cap_wait():
    """One item into an idle ADAPTIVE batcher with a huge cap must
    flush in milliseconds, not sync_wait (the cluster-tier p50
    mechanism: fixed windows stack in series on the GLOBAL path)."""
    import threading

    flushed = threading.Event()

    def flush(batch):
        flushed.set()

    b = IntervalBatcher(30.0, 1000, _combine, flush)
    try:
        t0 = time.monotonic()
        b.add("k", 1)
        assert flushed.wait(5.0), "idle window never fired"
        assert time.monotonic() - t0 < 2.0  # nowhere near the 30s cap
    finally:
        b.close()


def test_interval_batcher_current_wait_gauge():
    b = IntervalBatcher(0.5, 100, _combine, lambda batch: None)
    try:
        assert b.current_wait() == 0.0  # idle: fires immediately
    finally:
        b.close()
    fixed = IntervalBatcher(
        0.5, 100, _combine, lambda batch: None, adaptive=False
    )
    try:
        assert fixed.current_wait() == 0.5
    finally:
        fixed.close()


# ---------------------------------------------------------------------
# WireWindow: a single caller must not pay the window


class _Dec:
    def __init__(self, key=b"k"):
        self.n = 1
        self.key_buf = np.frombuffer(key, dtype=np.uint8).copy()
        self.key_offsets = np.asarray([0, len(key)], dtype=np.int64)
        for f in ("algo", "behavior"):
            setattr(self, f, np.zeros(1, dtype=np.int32))
        for f in ("hits", "limit", "duration", "burst"):
            setattr(self, f, np.ones(1, dtype=np.int64))
        self.fnv1a = np.zeros(1, dtype=np.uint64)


class _Engine:
    def apply_columnar(self, packed, algo, behavior, hits, limit,
                       duration, burst):
        n = len(algo)
        z = np.zeros(n, dtype=np.int64)
        return z, z, z, z


def test_wire_window_single_caller_no_wait():
    """An isolated submit through an adaptive window with a huge cap
    must return ~immediately (VERDICT r5: the client window was one of
    the serial stages taxing the GLOBAL median)."""
    ww = WireWindow(_Engine(), wait=5.0)
    t0 = time.monotonic()
    assert ww.submit(_Dec()) is not None
    assert time.monotonic() - t0 < 1.0, "single caller paid the window"
    assert ww.next_wait() == 0.0  # occupancy stayed at one RPC


def test_wire_window_wait_grows_under_grouping():
    ww = WireWindow(_Engine(), wait=0.002)
    # Simulate sustained grouped windows (what a herd produces).
    for _ in range(10):
        ww._observe(8)
    assert ww.next_wait() == pytest.approx(0.002)


# ---------------------------------------------------------------------
# The five-stage budget: reported end to end on the GLOBAL pipeline


STAGES = (
    "wire_window_wait",
    "engine_serve",
    "hits_window_wait",
    "owner_rpc",
    "broadcast_age",
    # Device-plane stages (ISSUE 10 / PERF.md §24).  device.window_wait
    # joins only when the step pump is live (conftest forces
    # GUBER_PUMP=1, so in-process cluster nodes carry it).
    "device.step",
    "device.readback",
    "device.window_wait",
    # Cross-region hop budget (ISSUE 14 / RESILIENCE.md §12).
    "multiregion.window_wait",
    "multiregion.region_rpc",
)


def test_global_pipeline_reports_all_stage_timers():
    from gubernator_tpu.cluster.harness import ClusterHarness
    from gubernator_tpu.net import wire_codec
    from gubernator_tpu.net.pb import gubernator_pb2 as pb
    from gubernator_tpu.types import Behavior, RateLimitReq

    if wire_codec.load() is None:
        pytest.skip("native codec unavailable")
    h = ClusterHarness().start(2, cache_size=4096)
    try:
        inst0 = h.daemon_at(0).instance
        # Every stage timer exists on every node.
        for inst in (inst0, h.daemon_at(1).instance):
            assert set(inst.stage_timers) == set(STAGES)
        # Drive non-owner GLOBAL wire traffic from node 0 so hits
        # forward to node 1 and its broadcast comes back.
        keys = [
            f"{i}sb" for i in range(400)
            if not inst0.get_peer(
                RateLimitReq(name="sb", unique_key=f"{i}sb").hash_key()
            ).info.is_owner
        ][:50]
        assert keys
        reqs = [
            pb.RateLimitReq(
                name="sb", unique_key=k, hits=1, limit=1000,
                duration=3_600_000, behavior=int(Behavior.GLOBAL),
            )
            for k in keys
        ]
        raw = pb.GetRateLimitsReq(requests=reqs).SerializeToString()
        for _ in range(3):
            assert inst0.serve_wire_bytes(raw) is not None
        inst0.global_mgr.flush_now()  # hits → owner
        h.daemon_at(1).instance.global_mgr.flush_now()  # broadcast
        t = inst0.stage_timers
        assert t["engine_serve"].count > 0  # local miss copies served
        assert t["hits_window_wait"].count > 0
        assert t["owner_rpc"].count > 0
        t1 = h.daemon_at(1).instance.stage_timers
        assert t1["broadcast_age"].count > 0
        # The daemon surfaces the budget (and /metrics exports it).
        budget = h.daemon_at(0).stage_budget()
        assert set(budget) == set(STAGES)
        assert budget["owner_rpc"]["count"] > 0
        from prometheus_client import generate_latest

        text = generate_latest(h.daemon_at(0).registry).decode()
        assert 'gubernator_stage_duration_count{stage="owner_rpc"}' in text
        assert "gubernator_adaptive_window_seconds" in text
    finally:
        h.stop()


def test_wire_window_wait_stage_counts():
    """A daemon with the client group-commit window enabled must
    observe the wire_window_wait stage on served wire batches."""
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.net import wire_codec
    from gubernator_tpu.net.pb import gubernator_pb2 as pb

    if wire_codec.load() is None:
        pytest.skip("native codec unavailable")
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=4096,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
        local_batch_wait=0.002,
    )
    d = spawn_daemon(conf)
    try:
        raw = pb.GetRateLimitsReq(
            requests=[
                pb.RateLimitReq(
                    name="ws", unique_key="k", hits=1, limit=100,
                    duration=60_000,
                )
            ]
        ).SerializeToString()
        t0 = time.monotonic()
        assert d.instance.serve_wire_bytes(raw) is not None
        # Adaptive: the isolated caller did not pay the 2ms window
        # (and the stage recorded a ~zero wait).
        assert time.monotonic() - t0 < 1.0
        assert d.instance.stage_timers["wire_window_wait"].count >= 1
    finally:
        d.close()

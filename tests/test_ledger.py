"""Decision-ledger conformance: ledger-path decisions must be
bit-equal to the sequential engine/spec (models/spec.py), and lease
over-admission under races must stay inside the configured budget.

The harness drives every batch through the SAME partition the serving
fronts use (ledger.plan → engine lane with settles prepended → learn),
and the oracle applies the identical rows one at a time through the
scalar spec.  Covered: lease grant→drain→settle cycles, lease TTL
expiry mid-stream, the sticky over-limit boundary exactly at reset
time, RESET_REMAINING/limit-change/duration-change/negative-hit
bypasses, leaky-bucket exclusion, and concurrent windows racing one
lease."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine, PackedKeys
from gubernator_tpu.core.ledger import DecisionLedger
from gubernator_tpu.hashing import fnv1a_64
from gubernator_tpu.models.spec import SlotState, SpecInput, apply_spec
from gubernator_tpu.types import Algorithm, Behavior, Status


class _Dec:
    """Minimal DecodedBatch stand-in (what wire_codec.decode_reqs
    produces) built from per-row python values."""

    __slots__ = (
        "n", "key_buf", "key_offsets", "algo", "behavior", "hits",
        "limit", "duration", "burst", "fnv1a",
    )


def make_dec(rows):
    """rows: list of (key_bytes, algo, behavior, hits, limit, duration,
    burst)."""
    d = _Dec()
    n = len(rows)
    d.n = n
    keys = [r[0] for r in rows]
    d.key_buf = np.frombuffer(b"".join(keys), dtype=np.uint8)
    off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(k) for k in keys], out=off[1:])
    d.key_offsets = off
    d.algo = np.asarray([r[1] for r in rows], np.int32)
    d.behavior = np.asarray([r[2] for r in rows], np.int32)
    d.hits = np.asarray([r[3] for r in rows], np.int64)
    d.limit = np.asarray([r[4] for r in rows], np.int64)
    d.duration = np.asarray([r[5] for r in rows], np.int64)
    d.burst = np.asarray([r[6] for r in rows], np.int64)
    d.fnv1a = np.asarray([fnv1a_64(k) for k in keys], np.uint64)
    return d


class Harness:
    """Engine + ledger behind the same serve shape the fronts use."""

    def __init__(self, clock, capacity=2048, **ledger_kw):
        ledger_kw.setdefault("settle_interval", 0)  # deterministic
        self.clock = clock
        self.engine = DecisionEngine(capacity=capacity, clock=clock)
        self.ledger = DecisionLedger(self.engine, **ledger_kw)

    def serve(self, dec):
        now = self.clock.now_ms()
        plan = self.ledger.plan(dec, now)
        if plan.full:
            return plan.dense_cols()
        lane = plan.build_engine_lane()
        st, lim, rem, rst = self.engine.apply_columnar(
            PackedKeys(lane.key_buf, lane.key_offsets, lane.n),
            lane.algo, lane.behavior, lane.hits, lane.limit,
            lane.duration, lane.burst, now_ms=now,
        )
        plan.learn(st, lim, rem, rst)
        # The same reassembly the serving fronts use.
        return plan.merge_outputs(st, rem, rst)

    def device_view(self, key, limit, duration):
        """Read the DEVICE state of one key (hits=0 query) bypassing
        the ledger — what an external racer would observe."""
        st, lim, rem, rst = self.engine.apply_columnar(
            [key],
            np.zeros(1, np.int32), np.zeros(1, np.int32),
            np.zeros(1, np.int64),
            np.asarray([limit], np.int64),
            np.asarray([duration], np.int64),
            np.zeros(1, np.int64),
        )
        return int(st[0]), int(rem[0]), int(rst[0])


class SpecOracle:
    """Sequential scalar-spec application of the same rows."""

    def __init__(self, clock):
        self.clock = clock
        self.states: dict = {}

    def serve(self, rows):
        now = self.clock.now_ms()
        out = []
        for key, algo, behavior, hits, limit, duration, burst in rows:
            state = self.states.get(key)
            inp = SpecInput(
                hits=hits, limit=limit, duration=duration, burst=burst,
                algorithm=algo, behavior=behavior,
            )
            new_state, resp = apply_spec(state, inp, now)
            if new_state is None:
                self.states.pop(key, None)
            else:
                self.states[key] = new_state
            out.append(
                (int(resp.status), int(resp.limit), int(resp.remaining),
                 int(resp.reset_time))
            )
        return out


def _check_batch(h, oracle, rows, tag=""):
    st, lim, rem, rst = h.serve(make_dec(rows))
    expect = oracle.serve(rows)
    for i, (es, el, er, et) in enumerate(expect):
        got = (int(st[i]), int(lim[i]), int(rem[i]), int(rst[i]))
        assert got == (es, el, er, et), (
            f"{tag} row {i} key={rows[i][0]!r} hits={rows[i][3]}: "
            f"ledger={got} spec={(es, el, er, et)}"
        )


def _fuzz(seed, n_batches, batch, n_keys, lease_ttl=0.05, limit_hi=12):
    rng = np.random.default_rng(seed)
    clock = Clock().freeze()
    h = Harness(
        clock, lease_size=8, lease_ttl=lease_ttl, hot_threshold=2,
    )
    oracle = SpecOracle(clock)
    keys = [b"led_k%d" % i for i in range(n_keys)]
    limits = rng.integers(0, limit_hi, n_keys)
    durations = rng.integers(1, 4, n_keys) * 40
    try:
        for b in range(n_batches):
            clock.advance(ms=int(rng.integers(0, 30)))
            if rng.random() < 0.1:
                # Occasionally jump past resets / lease TTLs.
                clock.advance(ms=int(rng.integers(40, 200)))
            if rng.random() < 0.15:
                # Config churn: a key's limit or duration changes.
                j = int(rng.integers(0, n_keys))
                if rng.random() < 0.5:
                    limits[j] = int(rng.integers(0, limit_hi))
                else:
                    durations[j] = int(rng.integers(1, 4)) * 40
            rows = []
            for _ in range(batch):
                j = int(rng.integers(0, n_keys))
                algo = (
                    int(Algorithm.LEAKY_BUCKET)
                    if rng.random() < 0.1
                    else int(Algorithm.TOKEN_BUCKET)
                )
                behavior = 0
                r = rng.random()
                if r < 0.04:
                    behavior = int(Behavior.RESET_REMAINING)
                hits = int(rng.integers(0, 4))
                if rng.random() < 0.05:
                    hits = int(rng.integers(4, 20))  # over-asks
                if rng.random() < 0.03:
                    hits = -int(rng.integers(1, 3))  # leaky refills etc
                # Nonzero burst values pin that the token path (and so
                # the ledger, which is token-only) is burst-inert —
                # settle/acquisition rows carry burst=0 on purpose.
                burst = int(rng.integers(0, 3)) * 7
                rows.append(
                    (keys[j], algo, behavior, hits, int(limits[j]),
                     int(durations[j]), burst)
                )
            _check_batch(h, oracle, rows, tag=f"batch {b}")
    finally:
        h.ledger.close()
    # The fuzz must actually exercise the fast paths.
    stats = h.ledger.stats()
    assert stats["answered"] > 0
    assert stats["leases_granted"] > 0


def test_ledger_fuzz_vs_spec_fast():
    _fuzz(seed=7, n_batches=60, batch=48, n_keys=6)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_ledger_fuzz_vs_spec_soak(seed):
    _fuzz(seed=seed, n_batches=400, batch=96, n_keys=10)


@pytest.mark.slow
def test_ledger_fuzz_long_ttl_soak():
    # Long TTL: leases persist across many batches; settles happen via
    # precondition breaks and exhaustion only.
    _fuzz(seed=11, n_batches=300, batch=64, n_keys=4, lease_ttl=10.0,
          limit_hi=400)


def _hot_rows(key, n, hits=1, limit=1000, duration=60000):
    return [(key, 0, 0, hits, limit, duration, 0)] * n


def test_sticky_over_limit_boundary_at_reset():
    clock = Clock().freeze()
    h = Harness(clock, lease_size=4, hot_threshold=100)
    oracle = SpecOracle(clock)
    key = b"svc_sticky"
    rows = [(key, 0, 0, 3, 3, 1000, 0)]
    _check_batch(h, oracle, rows)          # consumes to 0
    _check_batch(h, oracle, rows)          # OVER via engine; entry learned
    assert h.ledger.stats()["over_entries"] == 1
    before = h.engine.rounds_total
    _check_batch(h, oracle, rows)          # answered by the ledger
    _check_batch(h, oracle, [(key, 0, 0, 0, 3, 1000, 0)])  # query: OVER too
    assert h.engine.rounds_total == before  # zero device work
    # Exactly AT the reset the bucket is still live (expire >= now).
    st0, _, _, rst = h.serve(make_dec(rows))
    reset_ms = int(rst[0])
    clock.advance(ms=reset_ms - clock.now_ms())
    _check_batch(h, oracle, rows, tag="at reset")
    # One past the reset the item is dead: a fresh bucket, UNDER again.
    clock.advance(ms=1)
    _check_batch(h, oracle, rows, tag="past reset")
    st, _, rem, _ = h.serve(make_dec([(key, 0, 0, 0, 3, 1000, 0)]))
    assert int(st[0]) == int(Status.UNDER_LIMIT)
    h.ledger.close()


def test_duration_change_renewal_is_not_sticky():
    """Regression (found by the native-plane RPC fuzz, seed 23): a
    duration change that renews an expired bucket makes the engine
    respond (OVER, remaining=0) — a PRE-renewal snapshot — while the
    stored remaining silently becomes `limit` (models/spec.py:173-185).
    Learning that response as a sticky-OVER record then answers OVER
    until the new reset on a bucket that is actually full.  The insert
    must be suppressed whenever the row's duration differs from the
    entry's last engine-observed duration."""
    clock = Clock().freeze()
    h = Harness(clock, lease_size=4, hot_threshold=100)
    oracle = SpecOracle(clock)
    key = b"svc_renew"
    rows = [(key, 0, 0, 3, 3, 1000, 0)]
    _check_batch(h, oracle, rows)            # consumes to 0
    _check_batch(h, oracle, rows)            # OVER; sticky record learned
    assert h.ledger.stats()["over_entries"] == 1
    # Advance so that created + NEW duration has already passed, while
    # the OLD reset has not: the duration-change row renews the bucket.
    clock.advance(ms=500)
    renew = [(key, 0, 0, 1, 3, 400, 0)]
    _check_batch(h, oracle, renew, tag="renewing row")
    # The renewed bucket is FULL; a sticky re-insert from the renewing
    # row's (OVER, 0) response would answer OVER here instead.
    _check_batch(h, oracle, [(key, 0, 0, 0, 3, 400, 0)], tag="post-renewal")
    _check_batch(h, oracle, [(key, 0, 0, 1, 3, 400, 0)], tag="drains again")
    h.ledger.close()


def test_reset_remaining_bypasses_and_revokes():
    clock = Clock().freeze()
    h = Harness(clock, lease_size=16, hot_threshold=1)
    oracle = SpecOracle(clock)
    key = b"svc_reset"
    _check_batch(h, oracle, _hot_rows(key, 1, limit=10, duration=5000))
    _check_batch(h, oracle, _hot_rows(key, 1, limit=10, duration=5000))
    assert h.ledger.stats()["leases_granted"] == 1
    _check_batch(h, oracle, _hot_rows(key, 3, limit=10, duration=5000))
    # RESET_REMAINING must reach the engine (removes the item), with
    # the lease's consumed credits settled first in the same batch.
    rows = [(key, 0, int(Behavior.RESET_REMAINING), 1, 10, 5000, 0)]
    _check_batch(h, oracle, rows, tag="reset-remaining")
    assert h.ledger.stats()["settles"] >= 1
    # Post-reset state agrees with the spec.
    _check_batch(h, oracle, _hot_rows(key, 2, limit=10, duration=5000))
    h.ledger.close()


def test_lease_expiry_mid_stream_settles():
    clock = Clock().freeze()
    h = Harness(clock, lease_size=64, lease_ttl=0.02, hot_threshold=1)
    oracle = SpecOracle(clock)
    key = b"svc_ttl"
    for _ in range(4):
        _check_batch(h, oracle, _hot_rows(key, 2, limit=100, duration=60000))
    clock.advance(ms=25)  # past the lease TTL, inside the bucket window
    _check_batch(h, oracle, _hot_rows(key, 2, limit=100, duration=60000),
                 tag="post-ttl")
    assert h.ledger.stats()["settles"] >= 1
    h.ledger.close()


def test_background_flush_settles_idle_lease():
    clock = Clock().freeze()
    h = Harness(clock, lease_size=64, lease_ttl=0.02, hot_threshold=1)
    key = b"svc_idle"
    h.serve(make_dec(_hot_rows(key, 1, limit=100, duration=60000)))
    # Second batch: 3 engine hits + the acquisition row pre-debits the
    # lease credit — capped at HALF the post-batch remaining
    # (min(64, (99-3)//2) = 48; the racing-sliver guard).
    h.serve(make_dec(_hot_rows(key, 3, limit=100, duration=60000)))
    assert h.ledger.stats()["leases_granted"] == 1
    _, dev_rem, _ = h.device_view(key, 100, 60000)
    assert dev_rem == 100 - 4 - 48  # hits + pre-debited credit
    clock.advance(ms=30)  # past the lease TTL: flusher returns unused
    settled = h.ledger.flush_settles()
    assert settled == 1
    _, dev_rem, _ = h.device_view(key, 100, 60000)
    assert dev_rem == 96  # all 48 unused credits returned
    h.ledger.close()


def test_over_admission_bounded_by_lease_budget():
    """Leases PRE-DEBIT their credit, so an external racer reading the
    device mid-lease can never be over-admitted by lease accounting —
    it sees AT MOST `lease_size` FEWER remaining than the ledger's
    sequential truth (bounded under-admission, the mirror of GLOBAL's
    staleness contract), never more."""
    clock = Clock().freeze()
    budget = 16
    h = Harness(clock, lease_size=budget, lease_ttl=10.0, hot_threshold=1)
    key = b"svc_bound"
    limit = 1000
    h.serve(make_dec(_hot_rows(key, 1, limit=limit)))   # counter
    h.serve(make_dec(_hot_rows(key, 1, limit=limit)))   # grant (debit)
    for _ in range(200):
        st, _, rem, _ = h.serve(make_dec(_hot_rows(key, 1, limit=limit)))
        assert int(st[0]) == int(Status.UNDER_LIMIT)
        _, dev_rem, _ = h.device_view(key, limit, 60000)
        ledger_rem = int(rem[0])
        lag = dev_rem - ledger_rem  # device minus sequential truth
        assert -budget <= lag <= 0, (dev_rem, ledger_rem)
    h.ledger.close()


def test_concurrent_windows_racing_one_lease():
    """Threads hammer one leased key concurrently; the total admitted
    never exceeds limit + lease budget, and the drained bucket ends
    OVER for everyone."""
    clock = Clock().freeze()
    budget = 32
    limit = 300
    h = Harness(clock, lease_size=budget, lease_ttl=10.0, hot_threshold=1)
    key = b"svc_race"
    admitted = []
    lock = threading.Lock()

    def worker():
        mine = 0
        for _ in range(150):
            st, _, _, _ = h.serve(make_dec(_hot_rows(key, 1, limit=limit)))
            if int(st[0]) == int(Status.UNDER_LIMIT):
                mine += 1
        with lock:
            admitted.append(mine)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(admitted)
    assert total <= limit + budget, total
    # 4*150 > limit: the tail must be rejected.
    st, _, rem, _ = h.serve(make_dec(_hot_rows(key, 1, limit=limit)))
    assert int(st[0]) == int(Status.OVER_LIMIT)
    h.ledger.close()


def test_small_hot_bucket_not_starved_by_lease_churn():
    """Regression for the flashcrowd-canary starvation: under
    concurrent mixed traffic with real (unfrozen) time, lease
    acquire/expire/return churn on a SMALL-limit hot key used to let
    a racing fall-through hit flip the device bucket sticky-OVER
    while the revoked credit was mid-return — the returned remainder
    then sat unservable until the reset, admitting a fraction of the
    limit.  Three fixes hold the line: sticky inserts are suppressed
    while a return is queued/in flight, drains extend the lease TTL
    (no churn while hot), and acquisitions take at most half the
    remaining budget (racing slivers can't zero the bucket)."""
    import time as _time

    clock = Clock()
    engine = DecisionEngine(capacity=1024, clock=clock)
    led = DecisionLedger(
        engine, lease_size=512, lease_ttl=0.2, hot_threshold=8,
        settle_interval=0.05,
    )
    limit = 150
    key = b"svc_canary"
    lock = threading.Lock()
    admitted = [0]

    def serve(dec):
        now = clock.now_ms()
        plan = led.plan(dec, now)
        if plan.full:
            return plan.dense_cols()
        lane = plan.build_engine_lane()
        st, lim, rem, rst = engine.apply_columnar(
            PackedKeys(lane.key_buf, lane.key_offsets, lane.n),
            lane.algo, lane.behavior, lane.hits, lane.limit,
            lane.duration, lane.burst,
        )
        plan.learn(st, lim, rem, rst)
        return plan.merge_outputs(st, rem, rst)

    intended_sleep = [0.0] * 8

    def worker(tid):
        rng = np.random.default_rng(tid)
        mine = 0
        for _ in range(90):
            rows = []
            for _j in range(int(rng.integers(1, 6))):
                if rng.random() < 0.3:
                    rows.append((key, 0, 0, 1, limit, 3_600_000, 0))
                else:
                    rows.append(
                        (b"svc_hot_%d" % rng.integers(8), 0, 0, 1,
                         10**9, 3_600_000, 0)
                    )
            st, _l, _r, _t = serve(make_dec(rows))
            for j, r in enumerate(rows):
                if r[0] == key and int(st[j]) == int(Status.UNDER_LIMIT):
                    mine += 1
            nap = float(rng.uniform(0.002, 0.015))
            intended_sleep[tid] += nap
            _time.sleep(nap)
        with lock:
            admitted[0] += mine

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(8)
    ]
    t0 = _time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = _time.monotonic() - t0
    led.close()
    # ~470 canary requests against limit 150: the full budget must be
    # observable (small slack for credit still leased at the final
    # request), and pre-debit can never admit past the limit.
    assert admitted[0] <= limit, admitted[0]
    # The admission floor depends on real time: on a loaded CI host
    # the workers run dilated, lease TTLs (0.2 s) expire mid-churn
    # more often, and more credit sits leased/returning when the last
    # request lands.  Scale the slack by the observed dilation — the
    # ratio of wall time to the longest worker's intended sleep total
    # (the run's nominal duration; serve() itself is microseconds) —
    # and cap it so the test always proves at least two thirds of the
    # budget is observable.
    dilation = elapsed / max(1e-9, max(intended_sleep))
    slack = min(limit // 3, max(10, int(round(10 * dilation))))
    assert admitted[0] >= limit - slack, (
        admitted[0], limit, slack, round(dilation, 2)
    )


def test_leaky_rows_never_ledger_answered():
    clock = Clock().freeze()
    h = Harness(clock, lease_size=8, hot_threshold=1)
    oracle = SpecOracle(clock)
    key = b"svc_leaky"
    rows = [(key, int(Algorithm.LEAKY_BUCKET), 0, 1, 10, 1000, 0)]
    for i in range(6):
        clock.advance(ms=30)
        _check_batch(h, oracle, rows, tag=f"leaky {i}")
    assert h.ledger.stats()["answered"] == 0
    assert h.ledger.stats()["leases_granted"] == 0
    h.ledger.close()


def test_rollback_restores_consumed_credits():
    clock = Clock().freeze()
    h = Harness(clock, lease_size=16, lease_ttl=10.0, hot_threshold=1)
    key = b"svc_rb"
    h.serve(make_dec(_hot_rows(key, 1, limit=100)))
    h.serve(make_dec(_hot_rows(key, 1, limit=100)))  # grant (rem 98)
    plan = h.ledger.plan(make_dec(_hot_rows(key, 3, limit=100)),
                         clock.now_ms())
    assert len(plan.answered_rows) == 3
    plan.rollback()
    # The three consumed hits were restored: the next serve sees the
    # same remaining the spec would.
    st, _, rem, _ = h.serve(make_dec(_hot_rows(key, 1, limit=100)))
    assert int(rem[0]) == 97
    h.ledger.close()


def test_invalidate_keys_settles_before_dataclass_path():
    clock = Clock().freeze()
    h = Harness(clock, lease_size=64, lease_ttl=10.0, hot_threshold=1)
    key = b"svc_inv"
    h.serve(make_dec(_hot_rows(key, 1, limit=50)))
    h.serve(make_dec(_hot_rows(key, 5, limit=50)))  # grant + drain
    h.serve(make_dec(_hot_rows(key, 5, limit=50)))
    h.ledger.invalidate_keys([key, b"svc_absent"])
    # Device now reflects every ledger-admitted hit.
    _, dev_rem, _ = h.device_view(key, 50, 60000)
    assert dev_rem == 50 - 11
    assert h.ledger.stats()["entries"] >= 1  # counter remains
    h.ledger.close()

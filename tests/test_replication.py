"""Hot-key replication chaos suite: promote / demote / kill / reshard.

Pins the replication plane's state machine on real in-process
clusters (cluster/replication.py + RESILIENCE.md §11):

- PROMOTE: a measured-hot key owned elsewhere starts answering
  LOCALLY on every replica from pre-debited credit leases — the
  forward counter stalls while replicated_local grows (zero forward
  hops);
- DEMOTE on cooldown: traffic stops, the owner revokes, replicas
  empty, and the unused credit settles back onto the owner's bucket
  (the probe reads the reconciled remaining);
- replica killed mid-lease: per-key admission stays within the
  N_replicas × lease bound (pre-debit makes the over-admission side
  exactly zero on a healthy owner; the dead replica's unused slice is
  bounded under-admission);
- owner killed mid-promotion: replicas drain their leases, then
  converge through the health plane (degraded local answering) with
  zero error responses; leases expire out;
- promotion racing a membership reshard: epoch ordering wins — stale
  epochs and out-of-order sequence numbers are dropped, and a lease
  whose grantor is no longer the key's ring owner is expired by
  housekeeping;
- the metrics surface: gubernator_replication_keys/events/answered/
  credit on /metrics, mirrored by Daemon.replication_stats().

The smoke case doubles as the ci_fast.sh promotion/demotion gate.
"""

import json
import time

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster.harness import ClusterHarness
from gubernator_tpu.types import RateLimitReq, Status


def _req(name, key, limit=1_000_000, hits=1, duration=60_000):
    return RateLimitReq(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=duration,
    )


def _key_owned_by(h, daemon_idx, name, prefix):
    """One key whose owner is daemons[daemon_idx] (leading-byte
    variation: FNV-1 does not avalanche trailing bytes)."""
    want = h.daemons[daemon_idx].peer_info().grpc_address
    for i in range(50_000):
        key = f"{i}_{prefix}"
        if (
            h.daemons[0].instance.get_peer(f"{name}_{key}").info.grpc_address
            == want
        ):
            return key
    raise AssertionError("ring never mapped a key to the target")


def _tune(h, *, promote_rate=30.0, cooldown=1.0, lease=64,
          lease_ttl=1.0, interval=0.05, hk_window=0.5):
    """Re-point every daemon's replication knobs to a test timescale
    (the manager re-reads them each tick)."""
    for d in h.daemons:
        assert d.replication is not None
        r = d.replication
        r.promote_rate = promote_rate
        r.cooldown = cooldown
        r.lease = lease
        r.lease_ttl = lease_ttl
        r.interval = interval
        d.instance.hotkeys.window_s = hk_window


def _drive_until(clients, req, deadline_s, cond, *, collect=None):
    """Round-robin single-item requests through `clients` until `cond`
    (polled between rounds) or the deadline; returns (admitted,
    cond_met)."""
    admitted = 0
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for c in clients:
            r = c.get_rate_limits([req], timeout=15)[0]
            assert r.error == ""
            if r.status == Status.UNDER_LIMIT:
                admitted += 1
            if collect is not None:
                collect.append(r)
        if cond():
            return admitted, True
    return admitted, False


def test_promote_demote_smoke():
    """The fast promotion/demotion round trip (the ci_fast gate):
    replica traffic promotes the key, answers go local, cooldown
    demotes, unused credit returns to the owner's bucket."""
    h = ClusterHarness().start(3)
    try:
        _tune(h)
        name = "replsmoke"
        key = _key_owned_by(h, 0, name, "rsm")
        limit = 100_000
        req = _req(name, key, limit=limit)
        owner, ra, rb = h.daemons[0], h.daemons[1], h.daemons[2]
        clients = [V1Client(d.grpc_address) for d in (ra, rb)]
        try:
            admitted, ok = _drive_until(
                clients, req, 15.0,
                lambda: owner.replication.stats()["promoted_keys"] >= 1
                and ra.replication.stats()["replica_leases"] >= 1
                and rb.replication.stats()["replica_leases"] >= 1,
            )
            assert ok, (
                "promotion never engaged: "
                f"{[d.replication_stats() for d in h.daemons]}"
            )
            # Zero forward hops while the leases are live: the
            # replicas answer locally (small slack for a refresh gap).
            f0 = ra.instance.counters["forward"]
            rl0 = ra.instance.counters["replicated_local"]
            for _ in range(50):
                r = clients[0].get_rate_limits([req], timeout=15)[0]
                assert r.error == ""
                if r.status == Status.UNDER_LIMIT:
                    admitted += 1
            assert ra.instance.counters["replicated_local"] > rl0
            assert ra.instance.counters["forward"] <= f0 + 5
            ostats = owner.replication_stats()
            assert ostats["grants_sent"] >= 2
            assert ostats["credit_granted"] > 0
            # Cooldown: traffic stops → the owner demotes and the
            # replicas' leases drain out (revoked or expired).
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if (
                    owner.replication.stats()["demoted"] >= 1
                    and ra.replication.stats()["replica_leases"] == 0
                    and rb.replication.stats()["replica_leases"] == 0
                ):
                    break
                time.sleep(0.05)
            else:
                raise AssertionError(
                    "demotion never converged: "
                    f"{[d.replication_stats() for d in h.daemons]}"
                )
            # Reconciliation: the unused replica credit settled back —
            # the owner's logical remaining accounts every admitted
            # hit, give or take one in-flight refresh slice.
            probe = _req(name, key, limit=limit, hits=0)
            r = clients[0].get_rate_limits([probe], timeout=15)[0]
            assert r.error == ""
            admitted_floor = limit - admitted - owner.replication.lease
            assert r.remaining >= admitted_floor, (
                r.remaining, admitted, owner.replication_stats(),
            )
        finally:
            for c in clients:
                c.close()
    finally:
        h.stop()


def test_replica_killed_mid_lease_admission_within_bound():
    """Kill a replica holding a live lease; total admission on the key
    stays within limit (pre-debit: zero over-admission on a healthy
    owner) and within N_replicas × lease of it from below (the dead
    slice is bounded under-admission)."""
    h = ClusterHarness().start(3)
    try:
        lease = 50
        _tune(h, lease=lease, lease_ttl=2.0, cooldown=30.0)
        name = "replkill"
        key = _key_owned_by(h, 0, name, "rkl")
        limit = 2_000
        req = _req(name, key, limit=limit)
        owner, ra, rb = h.daemons[0], h.daemons[1], h.daemons[2]
        ca = V1Client(ra.grpc_address)
        cb = V1Client(rb.grpc_address)
        co = V1Client(owner.grpc_address)
        try:
            admitted, ok = _drive_until(
                [ca, cb], req, 15.0,
                lambda: ra.replication.stats()["replica_leases"] >= 1
                and rb.replication.stats()["replica_leases"] >= 1,
            )
            assert ok, "replicas never leased"
            h.kill(2)  # rb dies holding pre-debited credit
            # Consume the rest through the owner and the survivor
            # until the bucket is dry everywhere.
            over_streak = 0
            deadline = time.monotonic() + 30.0
            while over_streak < 30 and time.monotonic() < deadline:
                for c in (ca, co):
                    r = c.get_rate_limits([req], timeout=15)[0]
                    assert r.error == ""
                    if r.status == Status.UNDER_LIMIT:
                        admitted += 1
                        over_streak = 0
                    else:
                        over_streak += 1
            n_replicas = 2
            # Over-admission side of the bound: pre-debited credit can
            # never admit past the limit.
            assert admitted <= limit, (admitted, limit)
            # Under-admission side: only outstanding slices (the dead
            # replica's + in-flight refreshes) may go unserved.
            assert admitted >= limit - 2 * n_replicas * lease, (
                admitted, limit, owner.replication_stats(),
            )
        finally:
            ca.close()
            cb.close()
            co.close()
    finally:
        h.stop()


def test_owner_lost_mid_promotion_replicas_converge():
    """Cut the owner off (seeded isolation — the abrupt-death shape;
    a graceful kill would deliver close-time revokes) while its
    grants are live: replicas keep answering from pre-debited credit,
    then converge through the health plane (degraded local answers)
    with zero error responses; the orphaned leases expire out."""
    h = ClusterHarness().start(3)
    try:
        _tune(h, lease=64, lease_ttl=0.8, cooldown=30.0)
        name = "replokill"
        key = _key_owned_by(h, 0, name, "rok")
        req = _req(name, key, limit=1_000_000)
        ra, rb = h.daemons[1], h.daemons[2]
        ca = V1Client(ra.grpc_address)
        try:
            _admitted, ok = _drive_until(
                [ca], req, 15.0,
                lambda: ra.replication.stats()["replica_leases"] >= 1,
            )
            assert ok, "replica never leased"
            h.install_faults(seed=11)
            h.isolate(0)
            # Every post-kill answer must be error-free: lease first,
            # degraded-local once the circuit opens.
            for _ in range(10):
                r = ca.get_rate_limits([req], timeout=15)[0]
                assert r.error == "", r.error
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if (
                    ra.replication.stats()["replica_leases"] == 0
                    and rb.replication.stats()["replica_leases"] == 0
                ):
                    break
                time.sleep(0.05)
            else:
                raise AssertionError(
                    "orphaned leases never expired: "
                    f"{ra.replication_stats()} {rb.replication_stats()}"
                )
            assert (
                ra.replication.stats()["expired"] >= 1
                or rb.replication.stats()["expired"] >= 1
            )
        finally:
            ca.close()
    finally:
        h.stop()


def test_promotion_racing_reshard_epoch_ordering():
    """Epoch ordering wins every promotion/reshard race: stale-epoch
    grants and out-of-order sequence numbers are dropped, and a lease
    whose grantor is no longer the key's ring owner is expired by the
    next housekeeping tick."""
    h = ClusterHarness().start(2)
    try:
        _tune(h)
        a, b = h.daemons[0], h.daemons[1]
        now_ms = b.instance.engine.clock.now_ms()
        src = a.peer_info().grpc_address
        boot = a.membership.boot_id
        epoch = b.membership.epoch()

        def grant(key, *, epoch, seq, src=src, boot=boot):
            return b.instance.receive_replication(json.dumps({
                "op": "grant", "src": src, "boot": boot,
                "epoch": epoch, "seq": seq,
                "grants": [[key, 100, 60_000, now_ms + 60_000,
                            80, 40, now_ms + 60_000]],
            }).encode())

        # Leases must name keys their grantor actually owns, or the
        # grantor-changed housekeeping (the thing under test below)
        # would drop them as superseded.
        name = "replrace"
        key = f"{name}_{_key_owned_by(h, 0, name, 'rc')}"
        key2 = f"{name}_{_key_owned_by(h, 0, name, 'rcb')}"
        resp = json.loads(grant(key, epoch=epoch, seq=1))
        assert not resp.get("stale") and not resp.get("disabled")
        assert b.replication.stats()["replica_leases"] == 1
        # Stale epoch: the reshard already observed here wins (the
        # message still consumes its stream slot).
        resp = json.loads(grant("1_race", epoch=epoch - 1, seq=2))
        assert resp["stale"]
        # Out-of-order sequence within the same (src, boot) stream.
        resp = json.loads(grant(key2, epoch=epoch, seq=3))
        assert not resp.get("stale")
        resp = json.loads(grant("3_race", epoch=epoch, seq=1))
        assert resp["stale"]
        assert b.replication.stats()["stale_dropped"] >= 2
        # A revoke settles the lease and reports its accounting.
        resp = json.loads(b.instance.receive_replication(json.dumps({
            "op": "revoke", "src": src, "boot": boot, "epoch": epoch,
            "seq": 4, "revokes": [key],
        }).encode()))
        assert resp["returns"] and resp["returns"][0][0] == key
        # A lease from a grantor that is NOT the key's ring owner
        # (a superseded owner after a reshard) is dropped by
        # housekeeping; key2's lease — grantor still the owner —
        # survives.
        bogus = json.dumps({
            "op": "grant", "src": "198.51.100.9:81", "boot": "zz",
            "epoch": epoch, "seq": 1,
            "grants": [["4_race", 100, 60_000, now_ms + 60_000,
                        80, 40, now_ms + 60_000]],
        }).encode()
        json.loads(b.instance.receive_replication(bogus))
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            st = b.replication.stats()
            if st["replica_leases"] == 1 and st["expired"] >= 1:
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                f"superseded-owner lease never dropped: "
                f"{b.replication_stats()}"
            )
    finally:
        h.stop()


def test_refused_grant_returns_credit():
    """A replica that answers but refuses (replication disabled there)
    must count as a FAILED grant: the pre-debited slice returns to the
    owner's engine instead of leaking on every refresh."""
    h = ClusterHarness().start(2)
    try:
        _tune(h, cooldown=30.0)
        h.daemons[1].replication.enabled = False  # refuses all grants
        name = "replref"
        key = _key_owned_by(h, 0, name, "rrf")
        limit = 10_000
        req = _req(name, key, limit=limit)
        owner = h.daemons[0]
        c = V1Client(h.daemons[1].grpc_address)
        try:
            admitted, _ok = _drive_until(
                [c], req, 8.0,
                lambda: owner.replication.stats()["grants_failed"] >= 1,
            )
            st = owner.replication_stats()
            assert st["grants_failed"] >= 1, st
            assert st["grants_sent"] == 0, st
            # Every refused slice flowed back: granted == returned.
            assert st["credit_granted"] == st["credit_returned"], st
            # And the bucket's remaining accounts only real admits.
            probe = _req(name, key, limit=limit, hits=0)
            r = c.get_rate_limits([probe], timeout=15)[0]
            assert r.remaining >= limit - admitted - 5, (r, admitted, st)
        finally:
            c.close()
    finally:
        h.stop()


def test_columnar_answer_is_transactional():
    """try_answer_columns must not debit anything when it declines:
    a batch mixing a leased and an unleased row returns None with the
    lease untouched (the pb-path replay would otherwise double-debit
    the leased rows)."""
    import numpy as np

    from gubernator_tpu.hashing import fnv1a_64

    h = ClusterHarness().start(2)
    try:
        _tune(h)
        b = h.daemons[1]
        now_ms = b.instance.engine.clock.now_ms()
        src = h.daemons[0].peer_info().grpc_address
        boot = h.daemons[0].membership.boot_id
        name = "repltx"
        key = f"{name}_{_key_owned_by(h, 0, name, 'rtx')}"
        b.instance.receive_replication(json.dumps({
            "op": "grant", "src": src, "boot": boot,
            "epoch": b.membership.epoch(), "seq": 1,
            "grants": [[key, 100, 60_000, now_ms + 60_000,
                        80, 40, now_ms + 60_000]],
        }).encode())
        repl = b.replication

        def dec_for(rows):
            class D:  # the decoded-batch column shape
                pass

            d = D()
            keys = [r[0] for r in rows]
            d.n = len(rows)
            d.key_buf = np.frombuffer(b"".join(keys), np.uint8).copy()
            off = np.zeros(d.n + 1, np.int64)
            np.cumsum([len(k) for k in keys], out=off[1:])
            d.key_offsets = off
            d.algo = np.zeros(d.n, np.int32)
            d.behavior = np.zeros(d.n, np.int32)
            d.hits = np.asarray([r[1] for r in rows], np.int64)
            d.limit = np.asarray([r[2] for r in rows], np.int64)
            d.duration = np.full(d.n, 60_000, np.int64)
            d.burst = np.zeros(d.n, np.int64)
            d.fnv1a = np.asarray(
                [fnv1a_64(k) for k in keys], np.uint64
            )
            return d

        kb = key.encode()
        # Mixed batch: leased row first, unleased row second → decline
        # with ZERO mutation.
        dec = dec_for([(kb, 3, 100), (b"repltx_absent", 1, 100)])
        out = repl.try_answer_columns(
            dec, np.arange(2, dtype=np.int64), now_ms
        )
        assert out is None
        with repl._lock:
            assert repl._leases[kb].consumed == 0
        assert repl.stats()["answered"] == 0
        # All-leased batch (duplicate rows) commits cumulatively.
        dec = dec_for([(kb, 3, 100), (kb, 2, 100)])
        out = repl.try_answer_columns(
            dec, np.arange(2, dtype=np.int64), now_ms
        )
        assert out is not None
        st, rem, _rst = out
        assert st.tolist() == [0, 0] and rem.tolist() == [77, 75]
        with repl._lock:
            assert repl._leases[kb].consumed == 5
    finally:
        h.stop()


def test_replication_metrics_exported():
    """gubernator_replication_* on /metrics, mirrored by
    Daemon.replication_stats()."""
    import urllib.request

    h = ClusterHarness().start(2)
    try:
        d = h.daemons[0]
        stats = d.replication_stats()
        assert stats["promoted_keys"] == 0
        body = urllib.request.urlopen(
            f"http://{d.http_address}/metrics", timeout=10
        ).read().decode()
        for series in (
            "gubernator_replication_keys",
            "gubernator_replication_events",
            "gubernator_replication_answered",
            "gubernator_replication_credit",
        ):
            assert series in body, series
    finally:
        h.stop()


def test_remote_lease_rides_native_plane():
    """Replica-held remote leases delegate to the C decision plane
    (core/ledger.remote_install): the plane answers drains natively
    and remote_pull linearizes the consumed count back."""
    from gubernator_tpu.core import native_plane

    if native_plane.load() is None:
        pytest.skip("native decision plane unavailable")
    from gubernator_tpu.core.ledger import DecisionLedger

    class _Clock:
        @staticmethod
        def now_ms():
            return int(time.time() * 1000)

    class _Engine:
        clock = _Clock()

        @staticmethod
        def apply_columnar(*cols):  # pragma: no cover - never called
            raise AssertionError("remote leases never touch the engine")

    led = DecisionLedger(_Engine(), settle_interval=0)
    plane = native_plane.NativeDecisionPlane(max_keys=64)
    try:
        led.attach_native(plane)
        now = _Clock.now_ms()
        assert led.remote_install(
            b"repl_nk", 100, 60_000, now + 60_000, 80, 40, 0,
            now + 60_000,
        )
        out = plane.probe(b"repl_nk", 0, 0, 5, 100, 60_000, now)
        assert out is not None
        st, rem, _rst = out
        # UNDER, remaining = rem 80 - 5 drained
        assert (st, rem) == (int(Status.UNDER_LIMIT), 75)
        assert led.remote_pull(b"repl_nk") == 5
        assert led.remote_pull(b"repl_nk") is None  # pulled = gone
    finally:
        led.detach_native()
        plane.close()
        led.close()


def test_max_replicas_caps_fanout_to_least_loaded():
    """Replica-count policy (GUBER_REPL_MAX_REPLICAS, ISSUE 14
    satellite): with the cap set, grant fan-out targets the N
    LEAST-LOADED local-DC peers (load = in-flight RPCs + queued batch
    items, PeerClient.inflight()) instead of every peer; circuit-open
    peers are excluded before the cut; 0 keeps the grant-everyone
    behavior."""
    from types import SimpleNamespace

    from gubernator_tpu.cluster.replication import ReplicationManager

    class FakePeer:
        def __init__(self, addr, load, allow=True, owner=False):
            self.info = SimpleNamespace(
                grpc_address=addr, is_owner=owner
            )
            self.health = SimpleNamespace(
                would_allow=lambda allow=allow: allow
            )
            self._load = load

        def inflight(self):
            return self._load

    peers = [
        FakePeer("10.0.0.1:81", 5),
        FakePeer("10.0.0.2:81", 1),
        FakePeer("10.0.0.9:81", 0, owner=True),  # self: never a replica
        FakePeer("10.0.0.3:81", 3),
        FakePeer("10.0.0.4:81", 9, allow=False),  # broken: skipped
    ]
    daemon = SimpleNamespace(
        instance=SimpleNamespace(get_peer_list=lambda: peers)
    )
    capped = ReplicationManager(daemon, max_replicas=2)
    got = [p.info.grpc_address for p in capped._replica_peers()]
    assert got == ["10.0.0.2:81", "10.0.0.3:81"], got

    uncapped = ReplicationManager(daemon, max_replicas=0)
    assert {p.info.grpc_address for p in uncapped._replica_peers()} == {
        "10.0.0.1:81", "10.0.0.2:81", "10.0.0.3:81",
    }

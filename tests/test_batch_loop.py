"""IntervalBatcher overload semantics (the GLOBAL tail fix, PERF §15):
bounded drains, blocking backpressure for must-not-drop traffic, and
drop-oldest shedding for supersedable traffic."""

import threading
import time

import pytest

from gubernator_tpu.cluster.batch_loop import IntervalBatcher


def _combine(existing, item):
    return (existing or 0) + item


def test_drain_limit_bounds_each_flush():
    """A deep queue must drain as a stream of <= drain_limit flushes,
    never one monster flush."""
    sizes = []
    gate = threading.Event()

    def flush(batch, chunks):
        gate.wait(5.0)
        sizes.append(100 * len(chunks))  # every queued chunk holds 100

    b = IntervalBatcher(
        0.005, 100, _combine, flush, chunked=True, drain_limit=250,
    )
    try:
        # Queue 2000 items while the first flush is gated so the
        # backlog builds behind it.
        for i in range(20):
            b.add_chunk(("chunk", i), 100)
        gate.set()
        deadline = time.monotonic() + 10
        while b.pending() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert b.pending() == 0
        assert sum(sizes) == 2000
        # Every cycle after the first gated one is capped: the limit
        # plus at most one chunk of overshoot (chunk granularity).
        assert max(sizes) <= 250 + 100, sizes
        assert len(sizes) >= 6, sizes
    finally:
        b.close()


def test_max_pending_blocks_producer():
    """overflow='block': a full queue makes add_chunk wait for drain
    space instead of growing without bound (reference: the GLOBAL
    hits channel backpressure)."""
    release = threading.Event()

    def flush(batch, chunks):
        release.wait(10.0)

    b = IntervalBatcher(
        0.001, 100, _combine, flush, chunked=True,
        drain_limit=100, max_pending=300,
    )
    try:
        blocked_at = []

        def producer():
            for i in range(8):
                b.add_chunk(("c", i), 100)
            blocked_at.append(time.monotonic())

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        time.sleep(0.3)
        # Producer must be stuck: the queue holds at most the cap
        # (plus the one batch the gated flush already took out).
        assert not blocked_at, "producer should be blocked on the cap"
        assert b.pending() <= 300
        release.set()
        t.join(10.0)
        assert blocked_at, "producer must finish once flushes drain"
    finally:
        b.close()


def test_drop_oldest_sheds_and_counts():
    """overflow='drop_oldest': overload sheds the oldest chunks, the
    queue stays bounded, and the shed count is observable."""
    release = threading.Event()

    def flush(batch, chunks):
        release.wait(10.0)

    b = IntervalBatcher(
        0.001, 100, _combine, flush, chunked=True,
        drain_limit=100, max_pending=500, overflow="drop_oldest",
    )
    try:
        for i in range(20):
            b.add_chunk(("c", i), 100)
        assert b.pending() <= 500
        assert b.dropped >= 1400  # 2000 queued - cap - one in-flight
        release.set()
    finally:
        b.close()


def test_backlog_age_tracks_oldest():
    seen = threading.Event()

    def flush(batch, chunks):
        seen.set()

    # adaptive=False: the gauge check needs the item to SIT in the
    # queue for a measurable time (an adaptive window flushes an idle
    # batcher immediately — pinned by test_adaptive_window.py).
    b = IntervalBatcher(
        10.0, 10_000, _combine, flush, chunked=True, adaptive=False
    )
    try:
        assert b.backlog_age() == 0.0
        b.add_chunk(("c", 0), 1)
        time.sleep(0.05)
        age = b.backlog_age()
        assert 0.04 <= age < 5.0
    finally:
        b.close()


def test_backlog_age_reanchors_after_drop_oldest_shed():
    """ADVICE r5: drop_oldest shedding must re-anchor the age gauge to
    the oldest SURVIVING chunk — after the old chunks are shed, the
    gauge must stop reporting their (dropped) arrival time."""
    release = threading.Event()

    def flush(batch, chunks):
        release.wait(10.0)

    b = IntervalBatcher(
        3600.0, 10_000, _combine, flush, chunked=True, adaptive=False,
        drain_limit=1, max_pending=300, overflow="drop_oldest",
    )
    try:
        b.add_chunk(("old", 0), 100)
        time.sleep(0.3)  # age the chunk the gauge must NOT keep
        # These sheds the "old" chunk (cap 300): survivors are fresh.
        for i in range(3):
            b.add_chunk(("new", i), 100)
        assert b.dropped >= 100
        age = b.backlog_age()
        assert age < 0.25, f"gauge still reports the shed chunk: {age}"
        release.set()
    finally:
        b.close()


def test_deferred_requeue_held_until_due_then_flushes():
    """requeue_many(delay=): the held batch is invisible to the drain
    until its due time, then re-admits and flushes WITHOUT any fresh
    traffic — the multiregion damped-retry primitive (RESILIENCE.md
    section 12): no flush-worker sleep, no spin against an open
    circuit, and a healed peer converges even after clients go
    quiet."""
    flushes = []

    def flush(batch):
        flushes.append(dict(batch))

    b = IntervalBatcher(0.001, 100, _combine, flush)
    try:
        t0 = time.monotonic()
        assert b.requeue_many([("k", 3)], oldest_ts=t0 - 1.0, delay=0.25) == 1
        assert b.pending() == 1  # held items count as pending
        assert b.backlog_age() >= 0.9  # ...with their ORIGINAL age
        time.sleep(0.1)
        assert flushes == []  # not due yet: nothing drained
        deadline = time.monotonic() + 5
        while not flushes and time.monotonic() < deadline:
            time.sleep(0.01)
        assert flushes == [{"k": 3}]
        # The retry fired at (roughly) its due time, unprompted.
        assert time.monotonic() - t0 >= 0.24
    finally:
        b.close()


def test_flush_now_force_held_promotes_early():
    """flush_now(force_held=True) delivers a not-yet-due held batch
    immediately (the post-heal convergence probe)."""
    flushes = []

    def flush(batch):
        flushes.append(dict(batch))

    b = IntervalBatcher(0.001, 100, _combine, flush)
    try:
        b.requeue_many([("k", 7)], delay=30.0)
        b.flush_now()  # NOT forced: the held batch must stay held
        assert flushes == []
        b.flush_now(force_held=True)
        assert flushes == [{"k": 7}]
    finally:
        b.close()


def test_close_drains_held_batches():
    """close() must deliver-or-fail the held retry backlog, not
    strand it."""
    flushes = []

    def flush(batch):
        flushes.append(dict(batch))

    b = IntervalBatcher(0.001, 100, _combine, flush)
    b.requeue_many([("k", 1)], delay=30.0)
    b.close()
    assert flushes == [{"k": 1}]

"""Sharded columnar path must agree with the sharded dataclass path
(and therefore, transitively, with the conformance spec)."""

import random

import numpy as np

from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine
from gubernator_tpu.types import Algorithm, RateLimitReq


def _columns(reqs):
    return (
        [r.hash_key().encode() for r in reqs],
        np.asarray([int(r.algorithm) for r in reqs], dtype=np.int32),
        np.asarray([int(r.behavior) for r in reqs], dtype=np.int32),
        np.asarray([r.hits for r in reqs], dtype=np.int64),
        np.asarray([r.limit for r in reqs], dtype=np.int64),
        np.asarray([r.duration for r in reqs], dtype=np.int64),
        np.asarray([r.burst for r in reqs], dtype=np.int64),
    )


def test_sharded_columnar_matches_dataclass(frozen_clock):
    rng = random.Random(11)
    eng_a = ShardedDecisionEngine(shard_capacity=128, clock=frozen_clock)
    eng_b = ShardedDecisionEngine(shard_capacity=128, clock=frozen_clock)

    for step in range(6):
        reqs = [
            RateLimitReq(
                name="shcol",
                unique_key=f"k{rng.randint(0, 60)}",
                hits=rng.randint(0, 3),
                limit=10,
                duration=60_000,
                algorithm=rng.choice(
                    [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]
                ),
                burst=10,
            )
            for _ in range(rng.randint(1, 50))
        ]
        resps = eng_a.get_rate_limits(reqs)
        st, li, rem, rst = eng_b.apply_columnar(*_columns(reqs))
        for i, r in enumerate(resps):
            assert (int(st[i]), int(li[i]), int(rem[i]), int(rst[i])) == (
                int(r.status), r.limit, r.remaining, r.reset_time,
            ), f"step {step} item {i}"
        frozen_clock.advance(ms=rng.randint(0, 3_000))


def test_sharded_columnar_async(frozen_clock):
    eng = ShardedDecisionEngine(shard_capacity=128, clock=frozen_clock)
    reqs = [
        RateLimitReq(name="a", unique_key=f"x{i}", hits=1, limit=5, duration=60_000)
        for i in range(30)
    ]
    p1 = eng.apply_columnar(*_columns(reqs), want_async=True)
    p2 = eng.apply_columnar(*_columns(reqs), want_async=True)
    _, _, rem1, _ = p1.get()
    _, _, rem2, _ = p2.get()
    assert rem1.tolist() == [4] * 30
    assert rem2.tolist() == [3] * 30


def test_psum_merge_matches_host_merge(frozen_clock, monkeypatch):
    """The psum GLOBAL column merge (ISSUE 10): a whole-batch round's
    per-shard outputs merged by one on-device psum must equal the
    host-side per-shard unpermute, and the merged piece must be
    request-ordered (dst rows = arange)."""
    eng_psum = ShardedDecisionEngine(shard_capacity=128, clock=frozen_clock)
    monkeypatch.setenv("GUBER_PSUM_MERGE", "0")
    eng_host = ShardedDecisionEngine(shard_capacity=128, clock=frozen_clock)
    assert eng_psum._use_psum_merge and not eng_host._use_psum_merge

    rng = random.Random(5)
    for step in range(4):
        reqs = [
            RateLimitReq(
                name="psum",
                unique_key=f"k{i}",
                hits=rng.randint(0, 2),
                limit=8,
                duration=60_000,
                algorithm=(
                    Algorithm.TOKEN_BUCKET if i % 2 else Algorithm.LEAKY_BUCKET
                ),
                burst=8,
            )
            for i in range(57)  # unique keys: round 0, whole batch
        ]
        a = eng_psum.apply_columnar(*_columns(reqs))
        b = eng_host.apply_columnar(*_columns(reqs))
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
    # The merge actually engaged (one compiled program per pad pair)
    # and the batch was a single merged dispatch + psum.
    assert eng_psum._merge_progs, "psum merge never engaged"


def test_psum_merge_skips_multi_round_batches(frozen_clock):
    """Duplicate keys fall to the collapse/rounds paths — the merge
    only claims whole-batch round-0 dispatches, and results stay
    exact either way."""
    eng = ShardedDecisionEngine(shard_capacity=128, clock=frozen_clock)
    keys = [b"hot"] * 30 + [b"cold_%d" % i for i in range(10)]
    n = len(keys)
    st, lim, rem, rst = eng.apply_columnar(
        keys,
        np.zeros(n, np.int32), np.zeros(n, np.int32),
        np.ones(n, np.int64), np.full(n, 100, np.int64),
        np.full(n, 60_000, np.int64), np.zeros(n, np.int64),
    )
    # 30 sequential debits of the hot key: remaining walks 99..70.
    hot_rem = rem[:30]
    assert list(hot_rem) == list(range(99, 69, -1))

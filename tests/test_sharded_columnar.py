"""Sharded columnar path must agree with the sharded dataclass path
(and therefore, transitively, with the conformance spec)."""

import random

import numpy as np

from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine
from gubernator_tpu.types import Algorithm, RateLimitReq


def _columns(reqs):
    return (
        [r.hash_key().encode() for r in reqs],
        np.asarray([int(r.algorithm) for r in reqs], dtype=np.int32),
        np.asarray([int(r.behavior) for r in reqs], dtype=np.int32),
        np.asarray([r.hits for r in reqs], dtype=np.int64),
        np.asarray([r.limit for r in reqs], dtype=np.int64),
        np.asarray([r.duration for r in reqs], dtype=np.int64),
        np.asarray([r.burst for r in reqs], dtype=np.int64),
    )


def test_sharded_columnar_matches_dataclass(frozen_clock):
    rng = random.Random(11)
    eng_a = ShardedDecisionEngine(shard_capacity=128, clock=frozen_clock)
    eng_b = ShardedDecisionEngine(shard_capacity=128, clock=frozen_clock)

    for step in range(6):
        reqs = [
            RateLimitReq(
                name="shcol",
                unique_key=f"k{rng.randint(0, 60)}",
                hits=rng.randint(0, 3),
                limit=10,
                duration=60_000,
                algorithm=rng.choice(
                    [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]
                ),
                burst=10,
            )
            for _ in range(rng.randint(1, 50))
        ]
        resps = eng_a.get_rate_limits(reqs)
        st, li, rem, rst = eng_b.apply_columnar(*_columns(reqs))
        for i, r in enumerate(resps):
            assert (int(st[i]), int(li[i]), int(rem[i]), int(rst[i])) == (
                int(r.status), r.limit, r.remaining, r.reset_time,
            ), f"step {step} item {i}"
        frozen_clock.advance(ms=rng.randint(0, 3_000))


def test_sharded_columnar_async(frozen_clock):
    eng = ShardedDecisionEngine(shard_capacity=128, clock=frozen_clock)
    reqs = [
        RateLimitReq(name="a", unique_key=f"x{i}", hits=1, limit=5, duration=60_000)
        for i in range(30)
    ]
    p1 = eng.apply_columnar(*_columns(reqs), want_async=True)
    p2 = eng.apply_columnar(*_columns(reqs), want_async=True)
    _, _, rem1, _ = p1.get()
    _, _, rem2, _ = p2.get()
    assert rem1.tolist() == [4] * 30
    assert rem2.tolist() == [3] * 30

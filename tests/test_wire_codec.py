"""Native wire codec ⇄ protobuf equivalence (fuzzed).

The hand-rolled proto3 codec (core/native/wire_codec.cpp) must agree
byte-for-byte with the generated protobuf library on the two hot
messages, including negative int64s, unknown-field skipping, and the
decline cases that route a batch to the slow path.
"""

import numpy as np
import pytest

from gubernator_tpu.net import wire_codec
from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.net.server import _COLUMNAR_DISQUALIFIERS
from gubernator_tpu.hashing import fnv1_64, fnv1a_64

pytestmark = pytest.mark.skipif(
    wire_codec.load() is None, reason="native codec unavailable"
)


def msg(items):
    return pb.GetRateLimitsReq(
        requests=[pb.RateLimitReq(**kw) for kw in items]
    ).SerializeToString()


def test_decode_matches_protobuf_fuzz():
    rng = np.random.default_rng(7)
    for trial in range(30):
        n = int(rng.integers(1, 60))
        items = []
        for i in range(n):
            items.append(
                dict(
                    name=f"name{trial}",
                    unique_key=f"k{i}_{rng.integers(0, 1 << 20)}",
                    hits=int(rng.integers(-5, 1 << 40)),
                    limit=int(rng.integers(0, 1 << 50)),
                    duration=int(rng.integers(0, 1 << 40)),
                    algorithm=int(rng.integers(0, 2)),
                    behavior=int(rng.choice([0, 1, 8, 9])),  # eligible bits
                    burst=int(rng.integers(0, 1 << 30)),
                )
            )
        raw = msg(items)
        dec = wire_codec.decode_reqs(raw, 1000, _COLUMNAR_DISQUALIFIERS)
        assert dec is not None and dec.n == n
        parsed = pb.GetRateLimitsReq.FromString(raw)
        kraw = dec.key_buf.tobytes()
        keys = [
            kraw[dec.key_offsets[i] : dec.key_offsets[i + 1]]
            for i in range(dec.n)
        ]
        for i, m in enumerate(parsed.requests):
            key = f"{m.name}_{m.unique_key}".encode()
            assert keys[i] == key
            assert dec.algo[i] == m.algorithm
            assert dec.behavior[i] == m.behavior
            assert dec.hits[i] == m.hits
            assert dec.limit[i] == m.limit
            assert dec.duration[i] == m.duration
            assert dec.burst[i] == m.burst
            assert dec.fnv1[i] == fnv1_64(key)
            assert dec.fnv1a[i] == fnv1a_64(key)


def test_decode_declines_slow_path_batches():
    # Disqualifying behavior (GLOBAL).
    raw = msg([dict(name="a", unique_key="b", hits=1, behavior=2)])
    assert wire_codec.decode_reqs(raw, 1000, _COLUMNAR_DISQUALIFIERS) is None
    # Empty name / unique_key.
    raw = msg([dict(name="", unique_key="b", hits=1)])
    assert wire_codec.decode_reqs(raw, 1000, _COLUMNAR_DISQUALIFIERS) is None
    raw = msg([dict(name="a", unique_key="", hits=1)])
    assert wire_codec.decode_reqs(raw, 1000, _COLUMNAR_DISQUALIFIERS) is None
    # Over the batch limit.
    raw = msg([dict(name="a", unique_key=f"k{i}", hits=1) for i in range(5)])
    assert wire_codec.decode_reqs(raw, 4, _COLUMNAR_DISQUALIFIERS) is None
    # Malformed bytes.
    assert wire_codec.decode_reqs(b"\xff\xff\xff", 10, 0) is None


def test_decode_skips_unknown_fields():
    # A future field (99) must be skipped, not rejected.
    inner = pb.RateLimitReq(name="a", unique_key="b", hits=3).SerializeToString()
    inner += bytes([0x98, 0x06, 42])  # unknown varint field 99 (tag 792)
    raw = bytes([1 << 3 | 2, len(inner)]) + inner
    dec = wire_codec.decode_reqs(raw, 10, 0)
    assert dec is not None and dec.n == 1 and dec.hits[0] == 3


def test_encode_matches_protobuf():
    rng = np.random.default_rng(11)
    for _ in range(20):
        n = int(rng.integers(0, 40))
        status = rng.integers(0, 2, n).astype(np.int32)
        limit = rng.integers(0, 1 << 50, n).astype(np.int64)
        remaining = rng.integers(0, 1 << 50, n).astype(np.int64)
        reset = rng.integers(0, 1 << 45, n).astype(np.int64)
        raw = wire_codec.encode_resps(status, limit, remaining, reset)
        parsed = pb.GetRateLimitsResp.FromString(raw)
        assert len(parsed.responses) == n
        for i, r in enumerate(parsed.responses):
            assert (r.status, r.limit, r.remaining, r.reset_time) == (
                status[i], limit[i], remaining[i], reset[i],
            )
        # Byte-identical to the protobuf library's own serialization.
        ref = pb.GetRateLimitsResp(
            responses=[
                pb.RateLimitResp(
                    status=int(status[i]), limit=int(limit[i]),
                    remaining=int(remaining[i]), reset_time=int(reset[i]),
                )
                for i in range(n)
            ]
        ).SerializeToString()
        assert raw == ref

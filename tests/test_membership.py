"""Elastic membership chaos suite: join / leave / drain under traffic.

Pins the ISSUE 7 acceptance invariants on real in-process clusters
(cluster/membership.py + cluster/handoff.py):

- JOIN ships moved bucket state to the new owner — a consumed limit
  stays consumed after the cutover (no fresh-bucket amnesia);
- DRAIN under live traffic completes with 0 forfeited rows and 0%
  request errors (planned leave = zero-downtime deploy primitive);
- kill-during-handoff (seeded injector, deterministic fault point via
  the sender's window hook) converges — epochs settle, survivors stay
  healthy — with measured over-admission ≤ N_partitions × limit;
- unplanned leave (remove_peer) forfeits within the same bound;
- no-op peer pushes do NOT open epochs/dual windows (discovery
  re-pushes on every watch event);
- the metrics surface: gubernator_membership_epoch,
  gubernator_handoff_keys{event}, gubernator_ring_dual_window_seconds
  on /metrics, mirrored by Daemon.membership_stats().

Fast cases run tier-1; the sustained reshard soak is @slow.
"""

import threading
import time

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster.harness import ClusterHarness
from gubernator_tpu.cluster.health import HEALTHY
from gubernator_tpu.types import RateLimitReq, Status


def _req(name, key, limit=1_000_000, hits=1, duration=60_000):
    return RateLimitReq(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=duration,
    )


def _keys_owned_by(h, daemon_idx, name, n, prefix):
    """`n` keys whose owner is daemons[daemon_idx].  Keys vary a
    LEADING byte (FNV-1 does not avalanche trailing-byte differences;
    see hash_ring.py)."""
    want = h.daemons[daemon_idx].peer_info().grpc_address
    out = []
    i = 0
    while len(out) < n:
        key = f"{i}_{prefix}"
        if (
            h.daemons[0].instance.get_peer(f"{name}_{key}").info.grpc_address
            == want
        ):
            out.append(key)
        i += 1
        assert i < 50_000, "ring never mapped enough keys to the target"
    return out


def _consume(h, name, key, limit):
    """Exhaust `key`'s limit through node 0; returns hits admitted."""
    admitted = 0
    with V1Client(h.peer_at(0).grpc_address) as c:
        for _ in range(limit + 2):
            r = c.get_rate_limits(
                [_req(name, key, limit=limit)], timeout=15
            )[0]
            assert r.error == ""
            if r.status == Status.UNDER_LIMIT:
                admitted += 1
    return admitted


# ----------------------------------------------------------------------
# Wire round trip (pure unit).


def test_transfer_codec_round_trip():
    from gubernator_tpu.cluster.handoff import (
        decode_transfer,
        encode_transfer,
    )
    from gubernator_tpu.store import CacheItem, LeakyBucketItem, TokenBucketItem

    items = [
        CacheItem(
            key="tok_1", algorithm=0, expire_at=123_456, invalid_at=7,
            value=TokenBucketItem(
                status=1, limit=100, duration=60_000, remaining=3,
                created_at=99,
            ),
        ),
        CacheItem(
            key="leak_1", algorithm=1, expire_at=222_222,
            value=LeakyBucketItem(
                limit=50, duration=30_000, burst=60, updated_at=88,
                remaining=12.5, remaining_words=(12, 1 << 31),
            ),
        ),
    ]
    epoch, src, boot, out = decode_transfer(
        encode_transfer(7, "1.2.3.4:81", items, boot="bootabc")
    )
    assert (epoch, src, boot) == (7, "1.2.3.4:81", "bootabc")
    assert out[0].key == "tok_1"
    assert out[0].value.remaining == 3
    assert out[0].value.status == 1
    assert out[0].invalid_at == 7
    assert out[1].value.remaining_words == (12, 1 << 31)
    assert out[1].value.burst == 60


def test_receiver_drops_stale_epoch_windows():
    """A delayed window from a superseded transition must not
    overwrite rows a newer transition installed — unless the sender
    rebooted (fresh boot token resets its epoch stream)."""
    from gubernator_tpu.cluster.handoff import encode_transfer
    from gubernator_tpu.store import CacheItem, TokenBucketItem

    h = ClusterHarness().start(1)
    try:
        inst = h.daemons[0].instance
        now = inst.engine.clock.now_ms()

        def row(key, remaining):
            return [
                CacheItem(
                    key=key, algorithm=0, expire_at=now + 60_000,
                    value=TokenBucketItem(
                        status=0, limit=10, duration=60_000,
                        remaining=remaining, created_at=now,
                    ),
                )
            ]

        src = "10.0.0.9:81"
        assert inst.receive_transfer(
            encode_transfer(5, src, row("st_k", 4), boot="b1")
        ) == 1
        # Older epoch, same boot: dropped.
        assert inst.receive_transfer(
            encode_transfer(4, src, row("st_k", 9), boot="b1")
        ) == 0
        # Same epoch (another window of the same transition): applied.
        assert inst.receive_transfer(
            encode_transfer(5, src, row("st_k2", 4), boot="b1")
        ) == 1
        # Lower epoch but a NEW boot (sender restarted): applied.
        assert inst.receive_transfer(
            encode_transfer(1, src, row("st_k3", 4), boot="b2")
        ) == 1
        assert inst.handoff_counters["received"] == 3
    finally:
        h.stop()


# ----------------------------------------------------------------------
# JOIN: moved state ships to the new owner.


def test_join_ships_moved_state():
    h = ClusterHarness().start(3)
    try:
        limit = 3
        keys = [f"{i}_js" for i in range(40)]
        with V1Client(h.peer_at(0).grpc_address) as c:
            for k in keys:
                for _ in range(limit):
                    c.get_rate_limits(
                        [_req("mem_join", k, limit=limit)], timeout=15
                    )
        pre = {
            k: h.daemons[0].instance.get_peer(f"mem_join_{k}").info.grpc_address
            for k in keys
        }
        d_new = h.add_peer()
        assert h.wait_membership_settled(10)
        new_addr = d_new.peer_info().grpc_address
        moved = [
            k for k in keys
            if pre[k] != new_addr
            and h.daemons[0].instance.get_peer(
                f"mem_join_{k}"
            ).info.grpc_address == new_addr
        ]
        assert moved, "the join moved no sampled keys (ring bug?)"
        assert d_new.instance.handoff_counters["received"] >= len(moved)
        shipped = sum(
            d.instance.handoff_counters["shipped"] for d in h.daemons
        )
        assert shipped >= len(moved)
        # Every moved, fully-consumed key is still OVER_LIMIT at its
        # new owner: the bucket state travelled, it did not restart.
        with V1Client(h.peer_at(0).grpc_address) as c:
            for k in moved:
                r = c.get_rate_limits(
                    [_req("mem_join", k, limit=limit)], timeout=15
                )[0]
                assert r.error == ""
                assert r.status == Status.OVER_LIMIT, (
                    f"moved key {k} restarted fresh at the new owner"
                )
        # The join opened (and closed) dual windows on the old nodes.
        assert any(
            d.membership.dual_seconds() > 0 for d in h.daemons[:3]
        )
    finally:
        h.stop()


def test_non_authoritative_copies_do_not_ship():
    """The engine can hold LOCAL copies of peer-owned keys (degraded
    answers, GLOBAL miss-local copies).  A membership event must ship
    only rows this node was the authoritative owner of — a stale
    fresh copy travelling would overwrite the real owner's consumed
    state and re-admit past the limit."""
    h = ClusterHarness().start(3)
    try:
        assert h.wait_membership_settled(10)
        limit = 4
        key = _keys_owned_by(h, 2, "mem_copy", 1, "cp")[0]
        # Plant a NON-authoritative fresh copy of the key on node 0
        # (the peer-serving path answers anything it is sent; hits=0
        # interns the bucket without consuming).
        h.daemons[0].instance.get_peer_rate_limits(
            [_req("mem_copy", key, limit=limit, hits=0)]
        )
        # Properly exhaust the key at its real owner via routing.
        assert _consume(h, "mem_copy", key, limit) == limit
        # An unrelated membership event (a join) triggers transitions
        # on every node — node 0's stale copy must stay put.
        h.add_peer()
        assert h.wait_membership_settled(10)
        with V1Client(h.peer_at(0).grpc_address) as c:
            r = c.get_rate_limits(
                [_req("mem_copy", key, limit=limit)], timeout=15
            )[0]
        assert r.error == ""
        assert r.status == Status.OVER_LIMIT, (
            "a non-authoritative local copy was shipped over the "
            "owner's consumed state"
        )
    finally:
        h.stop()


# ----------------------------------------------------------------------
# DRAIN under live traffic: 0 forfeited, 0% errors (ISSUE 7 acceptance).


def test_drain_under_traffic_zero_forfeit_zero_errors():
    h = ClusterHarness().start(4)
    try:
        limit = 5
        victim = 3
        owned = _keys_owned_by(h, victim, "mem_drain", 6, "dr")
        for k in owned[:3]:
            assert _consume(h, "mem_drain", k, limit) == limit

        stop = threading.Event()
        errors = []
        served = [0]

        def traffic():
            with V1Client(h.peer_at(0).grpc_address) as c:
                i = 0
                while not stop.is_set():
                    batch = [
                        _req("mem_drain", owned[i % len(owned)], limit=limit),
                        _req("mem_live", f"{i}_lv"),
                    ]
                    for r in c.get_rate_limits(batch, timeout=15):
                        served[0] += 1
                        if r.error:
                            errors.append(r.error)
                    i += 1

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        time.sleep(0.3)  # traffic flowing before the drain
        stats = h.drain_peer(victim)
        time.sleep(0.5)  # traffic across the cutover
        stop.set()
        t.join(timeout=10)

        assert stats["forfeited"] == 0, stats
        assert stats["shipped"] >= 3, stats
        assert errors == [], errors[:5]
        assert served[0] > 0
        assert h.wait_membership_settled(10)
        # The consumed keys remain OVER_LIMIT at their new owners.
        with V1Client(h.peer_at(0).grpc_address) as c:
            for k in owned[:3]:
                r = c.get_rate_limits(
                    [_req("mem_drain", k, limit=limit)], timeout=15
                )[0]
                assert r.error == ""
                assert r.status == Status.OVER_LIMIT
        # Survivors agree on the epoch.
        assert len(set(h.membership_epochs().values())) == 1
    finally:
        h.stop()


# ----------------------------------------------------------------------
# Kill-during-handoff: convergence + the over-admission bound.


def test_kill_during_handoff_converges_within_bound():
    """Seeded + deterministic: the drain's sender delivers exactly one
    window, then the victim is isolated (the hook fires inside the
    sender loop, not on a timer).  The rest of its rows forfeit at the
    deadline; total admission per key stays ≤ N_partitions × limit
    (source side ≤ limit before the kill, fresh side ≤ limit after)."""
    h = ClusterHarness().start(4)
    try:
        limit = 5
        victim = 3
        victim_addr = h.daemons[victim].peer_info().grpc_address
        owned = _keys_owned_by(h, victim, "mem_kill", 6, "kd")
        admitted = {k: _consume(h, "mem_kill", k, limit) for k in owned}
        assert all(v == limit for v in admitted.values())

        h.install_faults(seed=77)
        mgr = h.daemons[victim].membership
        mgr.handoff_window = 1  # several windows → a mid-handoff point

        fired = []

        def kill_mid_handoff(addr, n_rows):
            if not fired:
                fired.append(addr)
                h._injector.isolate(victim_addr)

        mgr.handoff_hook = kill_mid_handoff
        stats = h.drain_peer(victim, deadline=1.0)
        assert fired, "the handoff never delivered a first window"
        assert stats["shipped"] >= 1
        assert stats["forfeited"] >= 1, stats
        h.heal()

        # Convergence: every survivor settles, healthy, equal epochs.
        assert h.wait_membership_settled(10)
        assert len(set(h.membership_epochs().values())) == 1
        states = h.health_states()
        for _src, peers in states.items():
            for dst, st in peers.items():
                if dst != victim_addr:
                    assert st == HEALTHY, states

        # Over-admission bound, asserted per key: limit before + what
        # the (shipped-or-fresh) new owner admits after ≤ 2 × limit.
        n_partitions = 2
        with V1Client(h.peer_at(0).grpc_address) as c:
            for k in owned:
                after = 0
                for _ in range(3 * limit):
                    r = c.get_rate_limits(
                        [_req("mem_kill", k, limit=limit)], timeout=15
                    )[0]
                    assert r.error == ""
                    if r.status == Status.UNDER_LIMIT:
                        after += 1
                total = admitted[k] + after
                assert total <= n_partitions * limit, (
                    f"key {k}: {admitted[k]} + {after} > "
                    f"{n_partitions} × {limit}"
                )
        # At least one key forfeited → took the fresh path (the bound
        # was exercised, not vacuous).
        assert any(
            stats["forfeited"] > 0 for stats in [stats]
        )
    finally:
        h.stop()


def test_remove_peer_forfeits_within_bound():
    """Unplanned leave (node killed and dropped from the ring): its
    buckets restart fresh at the survivors — total admission per key
    stays within the same 2 × limit bound, with zero request errors
    after the cutover."""
    h = ClusterHarness().start(3)
    try:
        limit = 4
        key = _keys_owned_by(h, 2, "mem_rm", 1, "rm")[0]
        assert _consume(h, "mem_rm", key, limit) == limit
        h.remove_peer(2)
        assert h.wait_membership_settled(10)
        after = 0
        with V1Client(h.peer_at(0).grpc_address) as c:
            for _ in range(3 * limit):
                r = c.get_rate_limits(
                    [_req("mem_rm", key, limit=limit)], timeout=15
                )[0]
                assert r.error == ""
                if r.status == Status.UNDER_LIMIT:
                    after += 1
        assert after <= limit
        assert limit + after <= 2 * limit
    finally:
        h.stop()


# ----------------------------------------------------------------------
# Epoch hygiene + metrics surface.


def test_noop_peer_push_does_not_bump_epoch():
    h = ClusterHarness().start(2)
    try:
        # Barrier on the start-up transition first — its commit may
        # still be in flight right after start() under suite load.
        assert h.wait_membership_settled(10)
        before = h.membership_epochs()
        for _ in range(3):
            h._push_peers()  # discovery-style re-push, same view
        assert h.membership_epochs() == before
        for d in h.daemons:
            assert d.membership.phase() == "stable"
    finally:
        h.stop()


def test_membership_metrics_exported():
    import urllib.request

    h = ClusterHarness().start(3)
    try:
        with V1Client(h.peer_at(0).grpc_address) as c:
            for i in range(8):
                c.get_rate_limits([_req("mem_m", f"{i}_mm")], timeout=15)
        h.drain_peer(2)
        assert h.wait_membership_settled(10)
        body = urllib.request.urlopen(
            f"http://{h.daemons[0].http_address}/metrics", timeout=5
        ).read().decode()
        assert "gubernator_membership_epoch" in body
        assert 'gubernator_handoff_keys_total{event="received"}' in body
        assert 'gubernator_handoff_keys_total{event="shipped"}' in body
        assert "gubernator_ring_dual_window_seconds" in body
        ms = h.daemons[0].membership_stats()
        assert ms["epoch"] >= 2
        assert ms["phase"] == "stable"
        assert set(ms["handoff"]) == {"shipped", "forfeited", "received"}
    finally:
        h.stop()


# ----------------------------------------------------------------------
# Soak: repeated join/drain cycles under sustained traffic.


@pytest.mark.slow
def test_reshard_soak_cycles():
    """Two full join+drain cycles with traffic throughout: zero
    errors, every cycle settles, epochs agree, and a limited key's
    cumulative admission stays within the cycle-count bound."""
    h = ClusterHarness().start(4)
    try:
        original = {d.peer_info().grpc_address for d in h.daemons}
        limit = 50
        bound_key = "0_soakb"
        n_err = 0
        n_total = 0
        admitted = 0
        stop = threading.Event()

        def traffic():
            nonlocal n_err, n_total, admitted
            with V1Client(h.peer_at(0).grpc_address) as c:
                i = 0
                while not stop.is_set():
                    rs = c.get_rate_limits(
                        [
                            _req("soak_r", f"{i % 61}_sk"),
                            _req("soak_rb", bound_key, limit=limit),
                        ],
                        timeout=15,
                    )
                    for r in rs:
                        n_total += 1
                        if r.error:
                            n_err += 1
                    if rs[1].status == Status.UNDER_LIMIT and not rs[1].error:
                        admitted += 1
                    i += 1

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        try:
            for _cycle in range(2):
                h.add_peer()
                assert h.wait_membership_settled(15)
                time.sleep(0.5)
                stats = h.drain_peer(1)
                assert stats["forfeited"] == 0, stats
                assert h.wait_membership_settled(15)
                time.sleep(0.5)
        finally:
            stop.set()
            t.join(timeout=10)
        assert n_total > 0
        assert n_err == 0, f"{n_err}/{n_total}"
        # Each membership event may fork the bound key's bucket once:
        # ≤ (1 + events) × limit total.
        assert admitted <= 5 * limit, admitted
        # Per-node epochs agree exactly for nodes that observed every
        # view — i.e. the original daemons still in the cluster
        # (mid-soak joiners booted later and counted fewer views).
        survivors_from_start = {
            addr: e
            for addr, e in h.membership_epochs().items()
            if addr in original
        }
        assert survivors_from_start
        assert len(set(survivors_from_start.values())) == 1, (
            survivors_from_start
        )
    finally:
        h.stop()

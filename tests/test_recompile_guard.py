"""Runtime companion to guberlint's trace pass: the recompile guard.

The trace pass keeps unpinned shapes out of the jit surface statically;
these tests close the loop at runtime — a warmed engine serving
steady-state traffic must trigger ZERO XLA backend compiles, across
every wire width the serving paths produce, and the count is exported
as the ``gubernator_jit_recompiles`` metric.
"""

import numpy as np
import pytest

from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.types import Algorithm, RateLimitReq


def _columns(n, start=0, name="soak"):
    return dict(
        keys=[b"%s_k%d" % (name.encode(), start + i) for i in range(n)],
        algo=np.asarray([i % 2 for i in range(n)], dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.ones(n, dtype=np.int64),
        limit=np.full(n, 100, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=np.full(n, 100, dtype=np.int64),
    )


def test_monitoring_hook_counts_compiles_not_cache_hits(jit_recompile_guard):
    """Pin the event semantics the guard depends on: a fresh shape
    compiles (count moves), a repeated shape is a cache hit (flat)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2 + 1)
    before = jit_recompile_guard.count()
    f(jnp.ones(7)).block_until_ready()
    after_first = jit_recompile_guard.count()
    assert after_first > before, "first call must compile"
    jit_recompile_guard.snapshot()
    f(jnp.ones(7)).block_until_ready()
    jit_recompile_guard.assert_flat("jit cache hit")
    f(jnp.ones(9)).block_until_ready()  # new shape -> recompile
    assert jit_recompile_guard.count() > after_first


def test_steady_state_serve_soak_zero_recompiles(
    frozen_clock, jit_recompile_guard
):
    """The acceptance soak: after warmup, a steady-state mix of every
    serving width (dataclass + columnar + duplicate-key collapse) runs
    with a flat compile count."""
    engine = DecisionEngine(
        capacity=8192, clock=frozen_clock, max_kernel_width=1024
    )
    engine.warmup(max_width=1024)

    jit_recompile_guard.snapshot()
    for round_no in range(3):
        for width in (1, 63, 64, 65, 500, 1000, 1024):
            engine.apply_columnar(
                **_columns(width, start=round_no * 10_000 + width * 7)
            )
        # Dataclass path at a couple of widths.
        for width in (3, 100):
            engine.get_rate_limits(
                [
                    RateLimitReq(
                        name="soak2",
                        unique_key=str(i),
                        hits=1,
                        limit=100,
                        duration=60_000,
                        algorithm=Algorithm.TOKEN_BUCKET,
                    )
                    for i in range(width)
                ]
            )
        # Hot-key collapse path (duplicate keys in one batch).
        engine.apply_columnar(
            keys=[b"soak_hot" for _ in range(200)],
            algo=np.zeros(200, dtype=np.int32),
            behavior=np.zeros(200, dtype=np.int32),
            hits=np.ones(200, dtype=np.int64),
            limit=np.full(200, 1_000_000, dtype=np.int64),
            duration=np.full(200, 60_000, dtype=np.int64),
            burst=np.full(200, 1_000_000, dtype=np.int64),
        )
    jit_recompile_guard.assert_flat("steady-state serve soak")


def test_recompile_metric_exported(frozen_clock, jit_recompile_guard):
    """gubernator_jit_recompiles rides the /metrics collector."""
    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.service import V1Instance
    from gubernator_tpu.utils.metrics import build_registry

    engine = DecisionEngine(capacity=1024, clock=frozen_clock)
    inst = V1Instance(Config(behaviors=BehaviorConfig()), engine)
    try:
        reg = build_registry(inst)
        sample = reg.get_sample_value("gubernator_jit_recompiles_total")
        assert sample is not None
        assert sample == jit_recompile_guard.count()
    finally:
        inst.close()

"""Runtime companion to guberlint's trace pass: the recompile guard.

The trace pass keeps unpinned shapes out of the jit surface statically;
these tests close the loop at runtime — a warmed engine serving
steady-state traffic must trigger ZERO XLA backend compiles, across
every wire width the serving paths produce, and the count is exported
as the ``gubernator_jit_recompiles`` metric.
"""

import numpy as np
import pytest

from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.types import Algorithm, RateLimitReq


def _columns(n, start=0, name="soak"):
    return dict(
        keys=[b"%s_k%d" % (name.encode(), start + i) for i in range(n)],
        algo=np.asarray([i % 2 for i in range(n)], dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.ones(n, dtype=np.int64),
        limit=np.full(n, 100, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=np.full(n, 100, dtype=np.int64),
    )


def test_monitoring_hook_counts_compiles_not_cache_hits(jit_recompile_guard):
    """Pin the event semantics the guard depends on: a fresh shape
    compiles (count moves), a repeated shape is a cache hit (flat)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2 + 1)
    before = jit_recompile_guard.count()
    f(jnp.ones(7)).block_until_ready()
    after_first = jit_recompile_guard.count()
    assert after_first > before, "first call must compile"
    jit_recompile_guard.snapshot()
    f(jnp.ones(7)).block_until_ready()
    jit_recompile_guard.assert_flat("jit cache hit")
    f(jnp.ones(9)).block_until_ready()  # new shape -> recompile
    assert jit_recompile_guard.count() > after_first


def test_steady_state_serve_soak_zero_recompiles(
    frozen_clock, jit_recompile_guard
):
    """The acceptance soak: after warmup, a steady-state mix of every
    serving width (dataclass + columnar + duplicate-key collapse) runs
    with a flat compile count."""
    engine = DecisionEngine(
        capacity=8192, clock=frozen_clock, max_kernel_width=1024
    )
    engine.warmup(max_width=1024)

    jit_recompile_guard.snapshot()
    for round_no in range(3):
        for width in (1, 63, 64, 65, 500, 1000, 1024):
            engine.apply_columnar(
                **_columns(width, start=round_no * 10_000 + width * 7)
            )
        # Dataclass path at a couple of widths.
        for width in (3, 100):
            engine.get_rate_limits(
                [
                    RateLimitReq(
                        name="soak2",
                        unique_key=str(i),
                        hits=1,
                        limit=100,
                        duration=60_000,
                        algorithm=Algorithm.TOKEN_BUCKET,
                    )
                    for i in range(width)
                ]
            )
        # Hot-key collapse path (duplicate keys in one batch).
        engine.apply_columnar(
            keys=[b"soak_hot" for _ in range(200)],
            algo=np.zeros(200, dtype=np.int32),
            behavior=np.zeros(200, dtype=np.int32),
            hits=np.ones(200, dtype=np.int64),
            limit=np.full(200, 1_000_000, dtype=np.int64),
            duration=np.full(200, 60_000, dtype=np.int64),
            burst=np.full(200, 1_000_000, dtype=np.int64),
        )
    jit_recompile_guard.assert_flat("steady-state serve soak")


def test_recompile_metric_exported(frozen_clock, jit_recompile_guard):
    """gubernator_jit_recompiles rides the /metrics collector."""
    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.service import V1Instance
    from gubernator_tpu.utils.metrics import build_registry

    engine = DecisionEngine(capacity=1024, clock=frozen_clock)
    inst = V1Instance(Config(behaviors=BehaviorConfig()), engine)
    try:
        reg = build_registry(inst)
        sample = reg.get_sample_value("gubernator_jit_recompiles_total")
        assert sample is not None
        assert sample == jit_recompile_guard.count()
    finally:
        inst.close()


def _algo_columns(n, algo, start=0, name="fz"):
    return dict(
        keys=[b"%s_%d_%d" % (name.encode(), algo, start + i) for i in range(n)],
        algo=np.full(n, algo, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.ones(n, dtype=np.int64),
        limit=np.full(n, 1000, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=np.full(n, 1000, dtype=np.int64),
    )


def test_fused_step_soak_zero_recompiles_both_algorithms(
    frozen_clock, jit_recompile_guard
):
    """ISSUE 10 satellite: the FUSED decision step (default mode,
    single dispatch per batch) stays recompile-flat across every wire
    width and BOTH algorithms — token and leaky exercise different
    jnp.where arms of the same compiled program, so a flat count here
    pins that the algorithm mix cannot fork the compile cache."""
    engine = DecisionEngine(
        capacity=8192, clock=frozen_clock, max_kernel_width=1024
    )
    assert engine.fused_mode in ("xla", "pallas", "pallas-interpret")
    engine.warmup(max_width=1024)

    jit_recompile_guard.snapshot()
    for round_no in range(2):
        for width in (1, 64, 65, 500, 1000, 1024):
            for algo in (0, 1):
                engine.apply_columnar(
                    **_algo_columns(
                        width, algo, start=round_no * 5_000 + width
                    )
                )
    jit_recompile_guard.assert_flat("fused-step width x algorithm soak")


def test_pallas_interpret_soak_zero_recompiles(
    frozen_clock, jit_recompile_guard, monkeypatch
):
    """The Pallas step family (interpret mode — what CPU CI runs) is
    warmed by the same pad ladder as every other program: steady-state
    traffic through it must not compile."""
    monkeypatch.setenv("GUBER_FUSED", "interpret")
    monkeypatch.setenv("GUBER_PUMP", "0")
    engine = DecisionEngine(
        capacity=4096, clock=frozen_clock, max_kernel_width=512
    )
    assert engine.fused_mode == "pallas-interpret"
    engine.warmup(max_width=512)

    jit_recompile_guard.snapshot()
    for width in (1, 63, 64, 200, 512):
        for algo in (0, 1):
            engine.apply_columnar(
                **_algo_columns(width, algo, start=width * 11, name="pz")
            )
    jit_recompile_guard.assert_flat("pallas interpret-mode soak")


def test_sharded_psum_merge_soak_zero_recompiles(
    frozen_clock, jit_recompile_guard
):
    """Review regression (ISSUE 10): the psum-merge program universe
    — every pow2 (n_pad, width) pair with width <= n_pad <=
    pad(n_shards*width), WITH the serve path's input shardings — is
    warmed by ShardedDecisionEngine.warmup; arbitrary whole-batch
    sizes then serve with a flat compile count (a host-committed
    warmup dummy used to warm a program the serve path never hit)."""
    from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine

    engine = ShardedDecisionEngine(
        shard_capacity=1024, clock=frozen_clock
    )
    if not engine._use_psum_merge:
        pytest.skip("psum merge disabled on this mesh")
    engine.warmup(max_width=256)
    assert engine.dispatches_total == 0  # warmup restores the counter

    jit_recompile_guard.snapshot()
    for n in (1, 57, 100, 200, 250, 256):
        engine.apply_columnar(
            **_columns(n, start=n * 13, name="psmk")
        )
    jit_recompile_guard.assert_flat("sharded psum-merge width soak")

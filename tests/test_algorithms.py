"""Algorithm conformance tests — ported from the reference functional suite.

Each test transcribes a table from reference functional_test.go (cited
per test) and drives the DecisionEngine directly with a frozen,
manually-advanced clock.  The tables are the behavioral spec
(SURVEY.md §4.5c)."""

from __future__ import annotations

import pytest

from gubernator_tpu import Algorithm, Behavior, RateLimitReq, Status
from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine

SECOND = 1000
MINUTE = 60 * SECOND

GREGORIAN_MINUTES = 0


@pytest.fixture
def engine(frozen_clock: Clock) -> DecisionEngine:
    return DecisionEngine(capacity=1024, clock=frozen_clock)


def hit(engine: DecisionEngine, **kw):
    req = RateLimitReq(**kw)
    (resp,) = engine.get_rate_limits([req])
    return resp


def test_over_the_limit(engine, frozen_clock):
    """reference: functional_test.go:64-109 (TestOverTheLimit)"""
    table = [
        (1, Status.UNDER_LIMIT),
        (0, Status.UNDER_LIMIT),
        (0, Status.OVER_LIMIT),
    ]
    for remaining, status in table:
        resp = hit(
            engine,
            name="test_over_limit",
            unique_key="account:1234",
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=SECOND * 9,
            limit=2,
            hits=1,
        )
        assert resp.error == ""
        assert resp.status == status
        assert resp.remaining == remaining
        assert resp.limit == 2
        assert resp.reset_time != 0


def test_token_bucket(engine, frozen_clock):
    """reference: functional_test.go:159-218 (TestTokenBucket)"""
    table = [
        ("remaining should be one", 1, Status.UNDER_LIMIT, 0),
        ("remaining should be zero and under limit", 0, Status.UNDER_LIMIT, 100),
        ("after waiting 100ms remaining should be 1 and under limit", 1, Status.UNDER_LIMIT, 0),
    ]
    for name, remaining, status, sleep_ms in table:
        resp = hit(
            engine,
            name="test_token_bucket",
            unique_key="account:1234",
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=5,
            limit=2,
            hits=1,
        )
        assert resp.error == "", name
        assert resp.status == status, name
        assert resp.remaining == remaining, name
        assert resp.limit == 2, name
        assert resp.reset_time != 0, name
        frozen_clock.advance(ms=sleep_ms)


def test_token_bucket_gregorian(engine, frozen_clock):
    """reference: functional_test.go:220-293 (TestTokenBucketGregorian)"""
    table = [
        ("first hit", 1, 59, Status.UNDER_LIMIT, 0),
        ("second hit", 1, 58, Status.UNDER_LIMIT, 0),
        ("consume remaining hits", 58, 0, Status.UNDER_LIMIT, 0),
        ("should be over the limit", 1, 0, Status.OVER_LIMIT, 61 * SECOND),
        ("should be under the limit", 0, 60, Status.UNDER_LIMIT, 0),
    ]
    for name, hits, remaining, status, sleep_ms in table:
        resp = hit(
            engine,
            name="test_token_bucket_greg",
            unique_key="account:12345",
            behavior=Behavior.DURATION_IS_GREGORIAN,
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=GREGORIAN_MINUTES,
            hits=hits,
            limit=60,
        )
        assert resp.error == "", name
        assert resp.status == status, name
        assert resp.remaining == remaining, name
        assert resp.limit == 60, name
        assert resp.reset_time != 0, name
        frozen_clock.advance(ms=sleep_ms)


def test_token_bucket_negative_hits(engine, frozen_clock):
    """reference: functional_test.go:295-365 (TestTokenBucketNegativeHits)"""
    table = [
        ("remaining should be three", 3, Status.UNDER_LIMIT, -1),
        ("remaining should be four and under limit", 4, Status.UNDER_LIMIT, -1),
        ("remaining should be 0 and under limit", 0, Status.UNDER_LIMIT, 4),
        ("remaining should be 1 and under limit", 1, Status.UNDER_LIMIT, -1),
    ]
    for name, remaining, status, hits in table:
        resp = hit(
            engine,
            name="test_token_bucket_negative",
            unique_key="account:12345",
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=5,
            limit=2,
            hits=hits,
        )
        assert resp.error == "", name
        assert resp.status == status, name
        assert resp.remaining == remaining, name
        assert resp.limit == 2, name
        assert resp.reset_time != 0, name


def _leaky_assert(resp, clock, remaining, status, name, limit=10):
    assert resp.status == status, name
    assert resp.remaining == remaining, name
    assert resp.limit == limit, name
    # reference: functional_test.go:544 — reset follows the leak rate.
    assert resp.reset_time // 1000 == clock.now_ms() // 1000 + (resp.limit - resp.remaining) * 3, name


def test_leaky_bucket(engine, frozen_clock):
    """reference: functional_test.go:367-492 (TestLeakyBucket)"""
    table = [
        ("first hit", 1, 9, Status.UNDER_LIMIT, SECOND),
        ("second hit; no leak", 1, 8, Status.UNDER_LIMIT, SECOND),
        ("third hit; no leak", 1, 7, Status.UNDER_LIMIT, 1500),
        ("should leak one hit 3 seconds after first hit", 0, 8, Status.UNDER_LIMIT, 3 * SECOND),
        ("3 Seconds later we should have leaked another hit", 0, 9, Status.UNDER_LIMIT, 0),
        ("max out our bucket and sleep for 3 seconds", 9, 0, Status.UNDER_LIMIT, 0),
        ("should be over the limit", 1, 0, Status.OVER_LIMIT, 3 * SECOND),
        ("should have leaked 1 hit", 0, 1, Status.UNDER_LIMIT, 60 * SECOND),
        ("should max out the limit", 0, 10, Status.UNDER_LIMIT, 60 * SECOND),
        ("should use up the limit and wait until 1 second before duration period", 10, 0, Status.UNDER_LIMIT, 29 * SECOND),
        ("should use up all hits one second before duration period", 9, 0, Status.UNDER_LIMIT, 3 * SECOND),
        ("only have 1 hit remaining", 1, 0, Status.UNDER_LIMIT, SECOND),
    ]
    for name, hits, remaining, status, sleep_ms in table:
        resp = hit(
            engine,
            name="test_leaky_bucket",
            unique_key="account:1234",
            algorithm=Algorithm.LEAKY_BUCKET,
            duration=SECOND * 30,
            hits=hits,
            limit=10,
        )
        assert resp.error == "", name
        _leaky_assert(resp, frozen_clock, remaining, status, name)
        frozen_clock.advance(ms=sleep_ms)


def test_leaky_bucket_with_burst(engine, frozen_clock):
    """reference: functional_test.go:494-599 (TestLeakyBucketWithBurst)"""
    table = [
        ("first hit", 1, 19, Status.UNDER_LIMIT, SECOND),
        ("second hit; no leak", 1, 18, Status.UNDER_LIMIT, SECOND),
        ("third hit; no leak", 1, 17, Status.UNDER_LIMIT, 1500),
        ("should leak one hit 3 seconds after first hit", 0, 18, Status.UNDER_LIMIT, 3 * SECOND),
        ("3 Seconds later we should have leaked another hit", 0, 19, Status.UNDER_LIMIT, 0),
        ("max out our bucket and sleep for 3 seconds", 19, 0, Status.UNDER_LIMIT, 0),
        ("should be over the limit", 1, 0, Status.OVER_LIMIT, 3 * SECOND),
        ("should have leaked 1 hit", 0, 1, Status.UNDER_LIMIT, 60 * SECOND),
        ("should max out remaining", 0, 20, Status.UNDER_LIMIT, SECOND),
    ]
    for name, hits, remaining, status, sleep_ms in table:
        resp = hit(
            engine,
            name="test_leaky_bucket_with_burst",
            unique_key="account:1234",
            algorithm=Algorithm.LEAKY_BUCKET,
            duration=SECOND * 30,
            hits=hits,
            limit=10,
            burst=20,
        )
        assert resp.error == "", name
        _leaky_assert(resp, frozen_clock, remaining, status, name)
        frozen_clock.advance(ms=sleep_ms)


def test_leaky_bucket_gregorian(engine, frozen_clock):
    """reference: functional_test.go:601-664 (TestLeakyBucketGregorian)"""
    # The Gregorian leaky rate is (ms remaining in the current minute)
    # / limit, so the expected leak depends on where in the minute the
    # first hit lands — pin the clock early in a minute instead of
    # freezing at the wall time (flaked when the suite crossed a minute
    # boundary's tail; observed at a midnight rollover).
    frozen_clock.freeze_at(
        (frozen_clock.now_ms() // 60_000 * 60_000 + 5_000) * 1_000_000
    )
    engine.clock = frozen_clock
    table = [
        ("first hit", 1, 59, Status.UNDER_LIMIT, 500),
        ("second hit; no leak", 1, 58, Status.UNDER_LIMIT, SECOND),
        ("third hit; leak one hit", 1, 58, Status.UNDER_LIMIT, 0),
    ]
    for name, hits, remaining, status, sleep_ms in table:
        resp = hit(
            engine,
            name="test_leaky_bucket_greg",
            unique_key="account:12345",
            behavior=Behavior.DURATION_IS_GREGORIAN,
            algorithm=Algorithm.LEAKY_BUCKET,
            duration=GREGORIAN_MINUTES,
            hits=hits,
            limit=60,
        )
        assert resp.error == "", name
        assert resp.status == status, name
        assert resp.remaining == remaining, name
        assert resp.limit == 60, name
        assert resp.reset_time > frozen_clock.now_ms() // 1000, name
        frozen_clock.advance(ms=sleep_ms)


def test_leaky_bucket_negative_hits(engine, frozen_clock):
    """reference: functional_test.go:666-735 (TestLeakyBucketNegativeHits)"""
    table = [
        ("first hit", 1, 9, Status.UNDER_LIMIT),
        ("can increase remaining", -1, 10, Status.UNDER_LIMIT),
        ("remaining should be zero", 10, 0, Status.UNDER_LIMIT),
        ("can append one to remaining when remaining is zero", -1, 1, Status.UNDER_LIMIT),
    ]
    for name, hits, remaining, status in table:
        resp = hit(
            engine,
            name="test_leaky_bucket_negative",
            unique_key="account:12345",
            algorithm=Algorithm.LEAKY_BUCKET,
            duration=SECOND * 30,
            hits=hits,
            limit=10,
        )
        assert resp.error == "", name
        _leaky_assert(resp, frozen_clock, remaining, status, name)


def test_leaky_bucket_div_bug(engine, frozen_clock):
    """reference: functional_test.go:1106-1146 (TestLeakyBucketDivBug)"""
    resp = hit(
        engine,
        name="test_leaky_bucket_div",
        unique_key="account:12345",
        algorithm=Algorithm.LEAKY_BUCKET,
        duration=1000,
        hits=1,
        limit=2000,
    )
    assert resp.error == ""
    assert resp.status == Status.UNDER_LIMIT
    assert resp.remaining == 1999
    assert resp.limit == 2000

    resp = hit(
        engine,
        name="test_leaky_bucket_div",
        unique_key="account:12345",
        algorithm=Algorithm.LEAKY_BUCKET,
        duration=1000,
        hits=100,
        limit=2000,
    )
    assert resp.remaining == 1899
    assert resp.limit == 2000


def test_change_limit(engine, frozen_clock):
    """reference: functional_test.go:870-963 (TestChangeLimit)"""
    table = [
        ("Should subtract 1 from remaining", Algorithm.TOKEN_BUCKET, 99, 100),
        ("Should subtract 1 from remaining", Algorithm.TOKEN_BUCKET, 98, 100),
        ("Should subtract 1 from remaining and change limit to 10", Algorithm.TOKEN_BUCKET, 7, 10),
        ("Should subtract 1 from remaining with new limit of 10", Algorithm.TOKEN_BUCKET, 6, 10),
        ("Should subtract 1 from remaining with new limit of 200", Algorithm.TOKEN_BUCKET, 195, 200),
        ("Should subtract 1 from remaining for leaky bucket", Algorithm.LEAKY_BUCKET, 99, 100),
        ("Should subtract 1 from remaining for leaky bucket after limit change", Algorithm.LEAKY_BUCKET, 9, 10),
        ("Should subtract 1 from remaining for leaky bucket with new limit", Algorithm.LEAKY_BUCKET, 8, 10),
    ]
    for name, algorithm, remaining, limit in table:
        resp = hit(
            engine,
            name="test_change_limit",
            unique_key="account:1234",
            algorithm=algorithm,
            duration=9000,
            limit=limit,
            hits=1,
        )
        assert resp.error == "", name
        assert resp.status == Status.UNDER_LIMIT, name
        assert resp.remaining == remaining, name
        assert resp.limit == limit, name
        assert resp.reset_time != 0, name


def test_reset_remaining(engine, frozen_clock):
    """reference: functional_test.go:965-1035 (TestResetRemaining)"""
    table = [
        ("Should subtract 1 from remaining", Behavior.BATCHING, 99),
        ("Should subtract 2 from remaining", Behavior.BATCHING, 98),
        ("Should reset the remaining", Behavior.RESET_REMAINING, 100),
        ("Should subtract 1 from remaining after reset", Behavior.BATCHING, 99),
    ]
    for name, behavior, remaining in table:
        resp = hit(
            engine,
            name="test_reset_remaining",
            unique_key="account:1234",
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=9000,
            behavior=behavior,
            limit=100,
            hits=1,
        )
        assert resp.error == "", name
        assert resp.status == Status.UNDER_LIMIT, name
        assert resp.remaining == remaining, name
        assert resp.limit == 100, name


def test_batch_order_and_multiple_keys(engine, frozen_clock):
    """reference: functional_test.go:113-157 (TestMultipleAsync) — batch
    responses come back in request order."""
    reqs = [
        RateLimitReq(
            name="test_multiple_async",
            unique_key="account:9234",
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=SECOND * 9,
            limit=2,
            hits=1,
        ),
        RateLimitReq(
            name="test_multiple_async",
            unique_key="account:5678",
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=SECOND * 9,
            limit=10,
            hits=5,
        ),
    ]
    resps = engine.get_rate_limits(reqs)
    assert len(resps) == 2
    assert resps[0].status == Status.UNDER_LIMIT
    assert resps[0].remaining == 1
    assert resps[0].limit == 2
    assert resps[1].status == Status.UNDER_LIMIT
    assert resps[1].remaining == 5
    assert resps[1].limit == 10


def test_duplicate_keys_in_one_batch_apply_sequentially(engine, frozen_clock):
    """Duplicate keys within one batch are applied in arrival order
    (the reference serializes them through one worker's FIFO,
    gubernator_pool.go:19-37; here: kernel rounds)."""
    req = dict(
        name="dup",
        unique_key="k",
        algorithm=Algorithm.TOKEN_BUCKET,
        duration=SECOND * 9,
        limit=3,
        hits=1,
    )
    resps = engine.get_rate_limits([RateLimitReq(**req) for _ in range(5)])
    assert [r.remaining for r in resps] == [2, 1, 0, 0, 0]
    assert [r.status for r in resps] == [
        Status.UNDER_LIMIT,
        Status.UNDER_LIMIT,
        Status.UNDER_LIMIT,
        Status.OVER_LIMIT,
        Status.OVER_LIMIT,
    ]


def test_eviction_and_reuse_within_one_batch(frozen_clock):
    """A slot evicted and reused inside one batch must not leak the old
    key's bucket state into the new key (regression: clears used to run
    only in round 0, before the evicted key's own round-0 write)."""
    eng = DecisionEngine(capacity=2, clock=frozen_clock)
    reqs = [
        RateLimitReq(name="e", unique_key=f"k{i}", hits=1, limit=10, duration=60_000)
        for i in range(5)
    ]
    resps = eng.get_rate_limits(reqs)
    assert [r.remaining for r in resps] == [9, 9, 9, 9, 9]
    # And an existing key evicted mid-batch starts fresh afterwards.
    resps = eng.get_rate_limits(reqs)
    assert [r.remaining for r in resps] == [9, 9, 9, 9, 9]


def test_algorithm_switch_resets(engine, frozen_clock):
    """Client switching algorithms resets the bucket
    (reference: algorithms.go:104-117,333-345)."""
    common = dict(name="switch", unique_key="k", duration=SECOND * 9, limit=10)
    r1 = hit(engine, algorithm=Algorithm.TOKEN_BUCKET, hits=4, **common)
    assert r1.remaining == 6
    r2 = hit(engine, algorithm=Algorithm.LEAKY_BUCKET, hits=1, **common)
    assert r2.remaining == 9  # fresh leaky bucket
    r3 = hit(engine, algorithm=Algorithm.TOKEN_BUCKET, hits=1, **common)
    assert r3.remaining == 9  # fresh token bucket


def test_hits_zero_status_query(engine, frozen_clock):
    """Hits=0 returns status without consuming
    (reference: algorithms.go:173-176,439-442)."""
    common = dict(
        name="q", unique_key="k", algorithm=Algorithm.TOKEN_BUCKET,
        duration=SECOND * 9, limit=5,
    )
    hit(engine, hits=3, **common)
    for _ in range(3):
        resp = hit(engine, hits=0, **common)
        assert resp.remaining == 2
        assert resp.status == Status.UNDER_LIMIT


def test_over_limit_does_not_consume(engine, frozen_clock):
    """Requesting more than available rejects without mutating state
    (reference: algorithms.go:195-202)."""
    common = dict(
        name="noconsume", unique_key="k", algorithm=Algorithm.TOKEN_BUCKET,
        duration=SECOND * 9, limit=100,
    )
    hit(engine, hits=50, **common)
    resp = hit(engine, hits=60, **common)
    assert resp.status == Status.OVER_LIMIT
    assert resp.remaining == 50
    resp = hit(engine, hits=50, **common)
    assert resp.status == Status.UNDER_LIMIT
    assert resp.remaining == 0

"""Cross-tier tracing: wire-propagated context, native event ring,
tail flight recorder, hot-key attribution (ISSUE 9).

The acceptance invariants, pinned:

- one same-host global4 decision produces ONE stitched trace spanning
  forwarder → owner → broadcast across processes boundaries (remote
  parents via the W3C traceparent metadata pair);
- chaos outcomes (degraded answers, circuit-open refusals) appear as
  span EVENTS, so a tail tree explains why it took the path it took;
- the native event ring drops (counted) instead of blocking when
  full, and the collector turns records into histograms + span stubs;
- natively-answered decisions produce `native.decide` span stubs —
  the first spans for the zero-Python fast path;
- /debug/trace, /debug/vars, /debug/hotkeys serve live data;
- DurationStat exports real streaming quantiles; the space-saving
  sketch obeys its error-bound contract.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from gubernator_tpu.cluster.harness import ClusterHarness
from gubernator_tpu.types import Behavior, RateLimitReq
from gubernator_tpu.utils.tracing import (
    InMemoryTracer,
    TraceContext,
    format_traceparent,
    parse_traceparent,
    set_tracer,
)


@pytest.fixture
def tracer():
    t = InMemoryTracer()
    set_tracer(t)
    yield t
    set_tracer(None)


def _until(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _req(name, key, behavior=0, hits=1, limit=1_000_000):
    return RateLimitReq(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=60_000, behavior=behavior,
    )


def _keys_not_owned_by(inst, name, n, tag):
    out, i = [], 0
    while len(out) < n and i < 4000:
        r = _req(name, f"{i}{tag}")
        if not inst.get_peer(r.hash_key()).info.is_owner:
            out.append(f"{i}{tag}")
        i += 1
    assert len(out) >= n, "expected remotely-owned keys"
    return out


# ----------------------------------------------------------------------
# Traceparent codec.


def test_traceparent_roundtrip():
    ctx = TraceContext(trace_id="ab" * 16, span_id="cd" * 8, sampled=True)
    tp = format_traceparent(ctx)
    assert tp == f"00-{'ab' * 16}-{'cd' * 8}-01"
    back = parse_traceparent(tp)
    assert back == ctx


def test_traceparent_rejects_malformed():
    for bad in (
        "", "00-zz-cd-01", "00-abc-def-01", "garbage",
        "00-" + "ab" * 16 + "-" + "cd" * 8,  # missing flags
        "00-" + "gg" * 16 + "-" + "cd" * 8 + "-01",  # non-hex
    ):
        assert parse_traceparent(bad) is None


def test_remote_parent_and_parent_ctx(tracer):
    from gubernator_tpu.utils.tracing import current_context, span

    with span("outer.root") as root:
        ctx = current_context()
        assert ctx.trace_id == root.trace_id
    with span("cross.thread", parent_ctx=ctx) as child:
        assert child.trace_id == root.trace_id
        assert child.parent_span_id == root.span_id
        assert not child.remote
    remote = parse_traceparent(format_traceparent(ctx))
    with span("remote.server", remote_parent=remote) as srv:
        assert srv.trace_id == root.trace_id
        assert srv.parent_span_id == root.span_id
        assert srv.remote


# ----------------------------------------------------------------------
# The headline invariant: one global4 decision = one stitched trace.


def test_global_decision_single_stitched_trace(tracer):
    h = ClusterHarness().start(2, cache_size=1024)
    try:
        inst = h.daemon_at(0).instance
        keys = _keys_not_owned_by(inst, "stitch", 3, "g")
        tracer.clear()
        inst.get_rate_limits(
            [_req("stitch", k, behavior=Behavior.GLOBAL) for k in keys]
        )
        roots = tracer.spans("service.get_rate_limits")
        assert len(roots) == 1
        tid = roots[0].trace_id

        def _stitched():
            names = {s.name for s in tracer.trace(tid)}
            return (
                ("global.hits_window" in names
                 or "global.hits_window_columnar" in names)
                and "rpc.get_peer_rate_limits" in names
                and "global.broadcast" in names
                and "rpc.update_peer_globals" in names
            )

        assert _until(_stitched, timeout=60), sorted(
            {s.name for s in tracer.trace(tid)}
        )
        spans = {s.name: s for s in tracer.trace(tid)}
        # The owner-side handler crossed a process boundary: its
        # parent is REMOTE and is the hits fan-out task's span.
        owner = spans["rpc.get_peer_rate_limits"]
        assert owner.remote
        parent = next(
            s for s in tracer.trace(tid) if s.span_id == owner.parent_span_id
        )
        assert parent.name in ("global.owner_rpc", "global.owner_rpc_pb")
        # The broadcast landed back on the forwarder with a remote
        # parent under the broadcast fan-out.
        upd = spans["rpc.update_peer_globals"]
        assert upd.remote
        bparent = next(
            s for s in tracer.trace(tid) if s.span_id == upd.parent_span_id
        )
        assert bparent.name == "global.broadcast_push"
        # And the whole tree shares the ONE trace id (the point).
        assert all(s.trace_id == tid for s in tracer.trace(tid))
    finally:
        h.stop()


def test_forwarded_request_carries_context(tracer):
    """Plain (non-GLOBAL) forwarding: the owner's handler span joins
    the forwarder's trace via gRPC metadata."""
    h = ClusterHarness().start(2, cache_size=1024)
    try:
        inst = h.daemon_at(0).instance
        keys = _keys_not_owned_by(inst, "fwd_tp", 3, "f")
        tracer.clear()
        inst.get_rate_limits([_req("fwd_tp", k) for k in keys])
        roots = tracer.spans("service.get_rate_limits")
        assert len(roots) == 1
        tid = roots[0].trace_id
        names = {s.name for s in tracer.trace(tid)}
        assert "forward.group" in names
        assert "peer.batch_rpc" in names
        assert "rpc.get_peer_rate_limits" in names
        owner = next(
            s for s in tracer.trace(tid)
            if s.name == "rpc.get_peer_rate_limits"
        )
        assert owner.remote
    finally:
        h.stop()


# ----------------------------------------------------------------------
# Chaos outcomes surface as span events.


def test_degraded_and_circuit_open_span_events(tracer):
    h = ClusterHarness().start(3)
    try:
        inst = h.daemon_at(0).instance
        keys = _keys_not_owned_by(inst, "chaos_tp", 4, "c")
        h.install_faults(seed=5)
        h.partition(0, 1)
        h.partition(0, 2)

        def _events():
            evs = {
                name
                for s in tracer.spans()
                for name, _attrs in s.events
            }
            return "degraded_answer" in evs and "circuit_open" in evs

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not _events():
            inst.get_rate_limits([_req("chaos_tp", keys[0])])
            time.sleep(0.05)
        assert _events(), [
            (s.name, s.events) for s in tracer.spans() if s.events
        ]
        # The degraded event names the unreachable owner.
        ev = next(
            attrs
            for s in tracer.spans()
            for name, attrs in s.events
            if name == "degraded_answer"
        )
        assert ev["owner"]
        assert ev["items"] >= 1
    finally:
        h.stop()


# ----------------------------------------------------------------------
# Native event ring: overflow drops, never blocks; collector stitches.


def _ring_lib():
    from gubernator_tpu.net import h2_fast

    lib = h2_fast.load()
    if lib is None:
        pytest.skip("native h2 server unavailable")
    return lib


def test_event_ring_overflow_drops_counted():
    import ctypes

    lib = _ring_lib()
    ring = ctypes.c_void_p(lib.evr_create(8))
    t0 = time.monotonic()
    for i in range(1000):
        lib.evr_record(ring, 1, 123456789 + i, 1000, 1)
    elapsed = time.monotonic() - t0
    # Never blocks: 1000 writes into an 8-slot ring complete ~instantly.
    assert elapsed < 1.0
    st = np.zeros(2, dtype=np.int64)
    lib.evr_stats(ring, st.ctypes.data_as(ctypes.c_void_p))
    assert st[0] == 8  # written
    assert st[1] == 992  # dropped, counted
    out = np.zeros(4 * 64, dtype=np.int64)
    n = lib.evr_drain(ring, out.ctypes.data_as(ctypes.c_void_p), 64)
    assert n == 8
    # Drain frees the slots: the ring accepts new events again.
    assert lib.evr_record(ring, 2, 1, 2, 3) == 1
    lib.evr_free(ring)


def test_event_ring_concurrent_producers():
    """Multi-producer claim: concurrent writers never corrupt records
    (every drained record is one of the written shapes) and
    written + dropped == attempts."""
    import ctypes
    import threading

    lib = _ring_lib()
    ring = ctypes.c_void_p(lib.evr_create(1024))
    per_thread = 5000
    n_threads = 4

    def producer(kind):
        for _ in range(per_thread):
            lib.evr_record(ring, kind, 1000 * kind, 10 * kind, kind)

    threads = [
        threading.Thread(target=producer, args=(k + 1,))
        for k in range(n_threads)
    ]
    drained = []
    stop = threading.Event()

    def consumer():
        out = np.zeros(4 * 512, dtype=np.int64)
        while not stop.is_set() or True:
            n = lib.evr_drain(
                ring, out.ctypes.data_as(ctypes.c_void_p), 512
            )
            if n:
                drained.append(out[: 4 * n].reshape(n, 4).copy())
            elif stop.is_set():
                return
            else:
                time.sleep(0.001)

    c = threading.Thread(target=consumer)
    c.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    c.join()
    st = np.zeros(2, dtype=np.int64)
    lib.evr_stats(ring, st.ctypes.data_as(ctypes.c_void_p))
    total = sum(len(d) for d in drained)
    assert int(st[0]) == total
    assert int(st[0]) + int(st[1]) == per_thread * n_threads
    for d in drained:
        for kind, t_ns, dur, items in d.tolist():
            assert kind in (1, 2, 3, 4)
            assert (t_ns, dur, items) == (1000 * kind, 10 * kind, kind)
    lib.evr_free(ring)


class _FakeFront:
    """Collector unit-test stand-in for H2FastFront's ring surface."""

    def __init__(self, records):
        self._records = list(records)
        self._drops = 0

    def drain_events(self, out):
        n = min(len(self._records), len(out) // 4)
        for i in range(n):
            out[4 * i: 4 * i + 4] = self._records.pop(0)
        return n

    def ring_stats(self):
        return {"written": 3, "dropped": self._drops, "enabled": True}


def test_collector_histograms_and_span_stubs(tracer):
    from gubernator_tpu.utils.native_events import NativeEventCollector

    t_end = time.monotonic_ns()
    front = _FakeFront(
        [
            [1, t_end, 250_000, 2],       # native_serve 250µs
            [2, t_end, 2_000_000, 1],     # window_wait 2ms
            [3, t_end, 1_000_000, 3],     # window_serve 1ms
        ]
    )
    col = NativeEventCollector(front, interval=10.0)  # drain manually
    try:
        assert col.drain_once() == 3
        counts = col.event_counts()
        assert {k: v for k, v in counts.items() if v} == {
            "native_serve": 1, "window_wait": 1, "window_serve": 1,
        }
        h = col.histograms()["native_serve"]
        assert h.count == 1
        # Log2 buckets: 250µs lands within a factor of 2.
        assert 1e-4 < h.p50() < 1e-3
        stubs = tracer.spans("native.decide")
        assert len(stubs) == 1
        assert stubs[0].attributes["items"] == 2
        assert stubs[0].end_ns - stubs[0].start_ns == 250_000
        assert col.stats()["stages"]["window_wait"]["count"] == 1
    finally:
        col.close()


def test_native_answers_emit_span_stubs(tracer):
    """Harness-level: a hot key answered by the native decision plane
    yields native.decide span stubs via the ring collector — the
    first tracing signal from the zero-Python path."""
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.net import h2_fast
    from gubernator_tpu.net.grpc_service import V1Stub, dial
    from gubernator_tpu.net.pb import gubernator_pb2 as pb

    if h2_fast.load() is None:
        pytest.skip("native h2 server unavailable")
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=1 << 12,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
        h2_fast_address="127.0.0.1:0",
        h2_fast_window=0.001,
        ledger_hot_threshold=2,
    )
    d = spawn_daemon(conf)
    try:
        if d.h2_fast.plane is None:
            pytest.skip("native decision plane not attached")
        assert d.instance.native_events is not None
        stub = V1Stub(dial(d.h2_fast_address))
        payload = pb.GetRateLimitsReq(
            requests=[
                pb.RateLimitReq(
                    name="natspan", unique_key="hot", hits=1,
                    limit=10**9, duration=3_600_000,
                )
            ]
        )

        def _stubbed():
            stub.GetRateLimits(payload)
            return (
                d.h2_fast.stats().get("native_rpcs", 0) > 0
                and tracer.spans("native.decide")
            )

        assert _until(_stubbed, timeout=30, interval=0.02), d.h2_fast.stats()
        # The ring actually carried the events (no silent bypass).
        assert d.instance.native_events.ring_stats()["written"] > 0
        assert d.instance.native_events.event_counts()["native_serve"] > 0
    finally:
        d.close()


# ----------------------------------------------------------------------
# /debug introspection surface.


def _get_json(http_address, path):
    return json.loads(
        urllib.request.urlopen(
            f"http://{http_address}{path}", timeout=10
        ).read().decode()
    )


def test_debug_endpoints_serve_live_data(tracer, monkeypatch):
    monkeypatch.setenv("GUBER_TRACE_TAIL_MIN_MS", "0")
    monkeypatch.setenv("GUBER_TRACE_TAIL_FACTOR", "0")
    h = ClusterHarness().start(1, cache_size=1024)
    try:
        inst = h.daemon_at(0).instance
        inst.get_rate_limits(
            [_req("dbg", f"k{i}", hits=3) for i in range(5)]
        )
        addr = h.daemon_at(0).http_address
        vars_ = _get_json(addr, "/debug/vars")
        assert vars_["counters"]["local"] >= 5
        assert "engine_serve" in vars_["stage_budget"]
        assert {"count", "mean_ms", "p50_ms", "p99_ms", "max_ms"} <= set(
            vars_["stage_budget"]["engine_serve"]
        )
        # Device-plane attribution (ISSUE 10): where device
        # milliseconds go, in the same budget table.
        assert "device.step" in vars_["stage_budget"]
        assert vars_["stage_budget"]["device.step"]["count"] >= 1
        assert "device.readback" in vars_["stage_budget"]
        # PR 13/14 planes (ISSUE 15 satellite): the replication and
        # multiregion managers' stats in the one-stop snapshot —
        # manager counters, per-region circuit state, held/requeued
        # accounting.
        mr = vars_["multiregion"]
        assert {
            "windows", "region_sends", "hits_requeued",
            "hits_dropped", "region_states", "pending",
            "pending_retry", "window_wait", "region_rpc",
        } <= set(mr)
        if vars_.get("replication") is not None:
            repl = vars_["replication"]
            assert {
                "promoted_keys", "replica_leases", "promoted",
                "demoted", "answered", "credit_granted",
            } <= set(repl)
        hot = _get_json(addr, "/debug/hotkeys")
        assert hot["enabled"]
        assert any(r["key"].startswith("dbg_") for r in hot["top"])
        assert all(
            {"key", "count", "err"} <= set(r) for r in hot["top"]
        )
        # Threshold 0 ⇒ every root records: the trace dump has trees.
        trace = _get_json(addr, "/debug/trace")
        assert trace["enabled"]
        assert trace["recorded"] >= 1
        assert trace["traces"], trace
        tree = trace["traces"][-1]
        assert tree["spans"] and tree["trace_id"]
        assert any(
            s["name"] == "service.get_rate_limits" for s in tree["spans"]
        )
    finally:
        h.stop()


def test_debug_endpoints_disabled_shapes(monkeypatch):
    """Without a tracer / with hotkeys off, the endpoints answer their
    disabled shapes instead of erroring."""
    monkeypatch.setenv("GUBER_HOTKEYS", "0")
    set_tracer(None)
    h = ClusterHarness().start(1, cache_size=256)
    try:
        addr = h.daemon_at(0).http_address
        assert _get_json(addr, "/debug/trace") == {
            "enabled": False, "traces": [],
        }
        hot = _get_json(addr, "/debug/hotkeys")
        assert hot == {"enabled": False, "top": []}
        vars_ = _get_json(addr, "/debug/vars")
        assert "stage_budget" in vars_
        # The PR 13/14 sections answer their shapes even when the
        # planes are idle (single node, no replication traffic).
        assert "multiregion" in vars_
        assert "replication" in vars_ or h.daemon_at(
            0
        ).replication is None
    finally:
        h.stop()


# ----------------------------------------------------------------------
# DurationStat streaming quantiles.


def test_duration_stat_quantiles():
    from gubernator_tpu.utils.metrics import DurationStat

    s = DurationStat()
    assert s.p50() == 0.0 and s.p99() == 0.0
    for _ in range(90):
        s.observe(0.001)
    for _ in range(10):
        s.observe(0.512)
    # p50 within the 1ms octave, p99 within the 512ms octave.
    assert 0.0005 < s.p50() < 0.002
    assert 0.25 < s.p99() < 1.1
    assert s.max == 0.512
    assert s.count == 100
    # Bucket merge (the collector's path) agrees with observe.
    m = DurationStat()
    counts = [0] * DurationStat.N_BUCKETS
    counts[DurationStat.bucket_of(0.001)] = 90
    counts[DurationStat.bucket_of(0.512)] = 10
    m.observe_bucket_counts(counts)
    assert m.count == 100
    assert 0.0005 < m.p50() < 0.002
    assert 0.25 < m.p99() < 1.1


def test_duration_stat_bucket_edges():
    from gubernator_tpu.utils.metrics import DurationStat

    assert DurationStat.bucket_of(0.0) == 0
    assert DurationStat.bucket_of(1e-9) == 0
    assert DurationStat.bucket_of(1e6) == DurationStat.N_BUCKETS - 1
    # Monotone non-decreasing over magnitudes.
    prev = -1
    for e in range(-7, 3):
        b = DurationStat.bucket_of(10.0 ** e)
        assert b >= prev
        prev = b


# ----------------------------------------------------------------------
# Space-saving hot-key sketch.


def test_space_saving_topk_contract():
    from gubernator_tpu.utils.hotkeys import SpaceSaving

    sk = SpaceSaving(capacity=8)
    true = {}
    # A heavy hitter + a long tail larger than capacity.
    for i in range(200):
        key = b"hot" if i % 2 == 0 else f"tail{i}".encode()
        n = 5 if key == b"hot" else 1
        true[key] = true.get(key, 0) + n
        sk.offer(key, n)
    top = sk.top(3)
    assert top[0][0] == b"hot"
    hot_est, hot_err = top[0][1], top[0][2]
    # Estimate bounds: true <= est <= true + err.
    assert true[b"hot"] <= hot_est <= true[b"hot"] + hot_err
    assert sk.stats()["tracked"] <= 8
    assert sk.stats()["offered"] == sum(true.values())


def test_space_saving_offer_columns():
    from gubernator_tpu.utils.hotkeys import SpaceSaving

    keys = [b"aa_1", b"bb_2", b"aa_1", b"cc_3"]
    buf = np.frombuffer(b"".join(keys), dtype=np.uint8)
    offs = np.zeros(len(keys) + 1, dtype=np.int64)
    np.cumsum([len(k) for k in keys], out=offs[1:])
    hits = np.array([2, 1, 3, 0], dtype=np.int64)
    sk = SpaceSaving(capacity=16)
    sk.offer_columns(buf, offs, hits)
    table = {k: c for k, c, _e in sk.top(10)}
    assert table[b"aa_1"] == 5
    assert table[b"bb_2"] == 1
    assert table[b"cc_3"] == 1  # hits=0 counts as one observation
    # idx subset restriction.
    sk2 = SpaceSaving(capacity=16)
    sk2.offer_columns(buf, offs, hits, idx=np.array([0, 1]))
    assert {k for k, _c, _e in sk2.top(10)} == {b"aa_1", b"bb_2"}


# ----------------------------------------------------------------------
# Flight recorder semantics.


def test_flight_recorder_adaptive_threshold(tracer):
    from gubernator_tpu.utils.flight_recorder import FlightRecorder
    from gubernator_tpu.utils.tracing import span

    fr = FlightRecorder(tracer, factor=2.0, min_ms=20.0, cap=4)
    # Fast roots stay below the 20ms floor: not recorded.
    for _ in range(5):
        with span("fast.root"):
            pass
    assert fr.dump()["recorded"] == 0
    # A slow root records its whole tree, children included.
    with span("slow.root"):
        with span("slow.child"):
            time.sleep(0.03)
    dump = fr.dump()
    assert dump["recorded"] == 1
    tree = dump["traces"][0]
    assert {s["name"] for s in tree["spans"]} == {
        "slow.root", "slow.child",
    }
    assert tree["duration_ms"] >= 20
    # Bounded retention: the ring keeps at most `cap` trees.
    for _ in range(10):
        with span("slow.root2"):
            time.sleep(0.025)
    assert len(fr.dump()["traces"]) <= 4
    fr.close()
    assert tracer.on_root_finish is None


def test_log_lines_carry_trace_id(tracer, capsys):
    import logging
    import os

    from gubernator_tpu.utils.logging_setup import configure_logging
    from gubernator_tpu.utils.tracing import span

    os.environ["GUBER_LOG_FORMAT"] = "json"
    try:
        configure_logging()
        log = logging.getLogger("stitch.test")
        with span("logged.op") as s:
            log.warning("inside")
            tid = s.trace_id
        log.warning("outside")
        lines = [
            json.loads(l)
            for l in capsys.readouterr().err.strip().splitlines()
            if l
        ]
        inside = next(l for l in lines if l["msg"] == "inside")
        outside = next(l for l in lines if l["msg"] == "outside")
        assert inside["trace_id"] == tid
        assert "trace_id" not in outside
    finally:
        os.environ.pop("GUBER_LOG_FORMAT")
        logging.getLogger().handlers[:] = []

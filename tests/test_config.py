"""GUBER_* env surface (config.setup_daemon_config) — the round-4
additions: picker selection, replicas, etcd auth/TLS block, gRPC
connection age, debug flag (reference: config.go:247-496)."""

import pytest

from gubernator_tpu.config import parse_duration, setup_daemon_config


def test_defaults_match_reference():
    c = setup_daemon_config(env={"GUBER_GRPC_ADDRESS": "localhost:0"})
    assert c.peer_picker == "replicated-hash"
    assert c.picker_replicas == 512
    assert c.hash_algorithm == "fnv1"
    assert c.grpc_max_conn_age_sec == 0
    assert c.debug is False
    assert c.etcd_dial_timeout == 5.0


def test_peer_picker_selection_and_hash_default():
    # Explicit picker selection flips the hash default to fnv1a
    # (reference: config.go:403).
    c = setup_daemon_config(env={"GUBER_PEER_PICKER": "replicated-hash"})
    assert c.peer_picker == "replicated-hash"
    assert c.hash_algorithm == "fnv1a"
    c = setup_daemon_config(
        env={
            "GUBER_PEER_PICKER": "consistent-hash",
            "GUBER_PEER_PICKER_HASH": "fnv1",
        }
    )
    assert c.peer_picker == "consistent-hash"
    assert c.hash_algorithm == "fnv1"
    with pytest.raises(ValueError, match="GUBER_PEER_PICKER="):
        setup_daemon_config(env={"GUBER_PEER_PICKER": "bogus"})


def test_replicated_hash_replicas():
    c = setup_daemon_config(env={"GUBER_REPLICATED_HASH_REPLICAS": "64"})
    assert c.picker_replicas == 64


def test_etcd_auth_tls_block():
    c = setup_daemon_config(
        env={
            "GUBER_ETCD_ENDPOINTS": "e1:2379,e2:2379",
            "GUBER_ETCD_DIAL_TIMEOUT": "2s",
            "GUBER_ETCD_USER": "u",
            "GUBER_ETCD_PASSWORD": "p",
            "GUBER_ETCD_ADVERTISE_ADDRESS": "10.0.0.9:81",
            "GUBER_ETCD_DATA_CENTER": "dc-b",
            "GUBER_ETCD_TLS_CA": "/ca.pem",
            "GUBER_ETCD_TLS_CERT": "/c.pem",
            "GUBER_ETCD_TLS_KEY": "/k.pem",
            "GUBER_ETCD_TLS_SKIP_VERIFY": "true",
        }
    )
    assert c.etcd_endpoints == ["e1:2379", "e2:2379"]
    assert c.etcd_dial_timeout == 2.0
    assert c.etcd_user == "u" and c.etcd_password == "p"
    assert c.etcd_advertise_address == "10.0.0.9:81"
    assert c.etcd_data_center == "dc-b"
    assert c.etcd_tls_ca == "/ca.pem"
    assert c.etcd_tls_cert == "/c.pem" and c.etcd_tls_key == "/k.pem"
    assert c.etcd_tls_skip_verify is True


def test_etcd_data_center_defaults_to_node_dc():
    c = setup_daemon_config(env={"GUBER_DATA_CENTER": "dc-a"})
    assert c.etcd_data_center == "dc-a"


def test_grpc_conn_age_and_debug():
    c = setup_daemon_config(
        env={"GUBER_GRPC_MAX_CONN_AGE_SEC": "30", "GUBER_DEBUG": "true"}
    )
    assert c.grpc_max_conn_age_sec == 30
    assert c.debug is True


def test_duration_parsing():
    assert parse_duration("500us") == pytest.approx(500e-6)
    assert parse_duration("1m30s") == pytest.approx(90.0)
    assert parse_duration("0.25") == 0.25


def test_consistent_hash_picker_routes_and_rebuilds():
    from gubernator_tpu.cluster.hash_ring import (
        ConsistentHash,
        make_picker,
    )
    from gubernator_tpu.types import PeerInfo

    class M:
        def __init__(self, addr, owner=False):
            self.info = PeerInfo(grpc_address=addr, is_owner=owner)

    p = make_picker("consistent-hash", "fnv1a")
    assert isinstance(p, ConsistentHash)
    members = [M(f"10.0.0.{i}:81") for i in range(5)]
    p.add_all(members)
    # Deterministic routing, and batch agrees with scalar.
    keys = [f"key{i}" for i in range(200)]
    scalar = [p.get(k).info.grpc_address for k in keys]
    batch = [m.info.grpc_address for m in p.get_batch(keys)]
    assert scalar == batch
    # Every peer owns at least something at 200 keys / 5 peers? Not
    # guaranteed with 1 point each, but >1 distinct owner must appear.
    assert len(set(scalar)) > 1
    # new() keeps config; removing a member reroutes only its keys.
    p2 = p.new()
    p2.add_all(members[:4])
    moved = sum(
        1
        for k, was in zip(keys, scalar)
        if was != p2.get(k).info.grpc_address
    )
    kept_addr = {m.info.grpc_address for m in members[:4]}
    for k, was in zip(keys, scalar):
        if was in kept_addr:
            # Keys owned by surviving peers must not move (the whole
            # point of consistent hashing).
            assert p2.get(k).info.grpc_address == was
    assert moved >= 0


def test_make_picker_rejects_unknown():
    from gubernator_tpu.cluster.hash_ring import make_picker

    with pytest.raises(ValueError):
        make_picker("bogus", "fnv1")

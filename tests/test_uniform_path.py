"""Uniform narrow-format path (bucket_kernel UNIFORM_IN_ROWS) vs the
general packed path: bit-equal decisions on identical traffic.

The uniform format ships 4B/decision uphill and 8B down (vs 64/20) on
the transfer-bound backend; its gate (engine._uniform_params) and the
scalar-broadcast kernel must preserve exact semantics — fuzzed here
across algorithms, behaviors (incl. RESET_REMAINING), negative hits,
duplicate keys (rounds), state evolution, and the int32-range gate
boundaries."""

import numpy as np
import pytest

from gubernator_tpu.core.engine import DecisionEngine


def _apply(engine, keys, now, **cfg):
    n = len(keys)
    cols = dict(
        algo=np.full(n, cfg.get("algo", 0), dtype=np.int32),
        behavior=np.full(n, cfg.get("behavior", 0), dtype=np.int32),
        hits=np.full(n, cfg.get("hits", 1), dtype=np.int64),
        limit=np.full(n, cfg.get("limit", 100), dtype=np.int64),
        duration=np.full(n, cfg.get("duration", 60_000), dtype=np.int64),
        burst=np.full(n, cfg.get("burst", 0), dtype=np.int64),
    )
    return engine.apply_columnar(list(keys), now_ms=now, **cols)


@pytest.fixture
def engines():
    e_uni = DecisionEngine(capacity=4096)
    e_gen = DecisionEngine(capacity=4096)
    e_gen._pump = None  # force the general packed path
    if e_uni._pump is None:
        pytest.skip("pump unavailable (split-pair platform)")
    return e_uni, e_gen


def _check_equal(r1, r2, ctx):
    for a, b, name in zip(r1, r2, ("status", "limit", "remaining", "reset")):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"{name} @ {ctx}"
        )


def test_uniform_fuzz_vs_general(engines):
    e_uni, e_gen = engines
    rng = np.random.default_rng(42)
    for step in range(25):
        b = int(rng.integers(2, 300))
        keys = [b"f%d" % i for i in rng.integers(0, 80, b)]
        cfg = dict(
            algo=int(rng.integers(0, 2)),
            behavior=[0, 0, 8, 0][step % 4],  # RESET_REMAINING mixed in
            hits=int(rng.integers(-2, 6)),
            limit=int(rng.integers(0, 60)),
            duration=int(rng.integers(1, 90_000)),
            burst=int(rng.integers(0, 70)),
        )
        now = 5_000_000 + step * int(rng.integers(0, 40_000))
        r1 = _apply(e_uni, keys, now, **cfg)
        r2 = _apply(e_gen, keys, now, **cfg)
        _check_equal(r1, r2, f"step={step} cfg={cfg}")


def test_uniform_gate_boundaries(engines):
    """Values at/over the int32 gate fall back to the general format
    and still agree with the forced-general engine."""
    e_uni, e_gen = engines
    shapes = []
    orig = e_uni._pump.submit
    e_uni._pump.submit = lambda buf: (shapes.append(buf.shape), orig(buf))[1]
    cases = [
        dict(limit=2**31 - 1),            # at the edge: general path
        dict(limit=2**31 + 5),            # over: general path
        dict(duration=2**31 + 1),         # over: general path
        dict(hits=2**31),                 # over: general path
        dict(limit=2**31 - 2, burst=2**30),  # within: uniform ok
    ]
    for i, cfg in enumerate(cases):
        keys = [b"g%d_%d" % (i, j) for j in range(10)]
        r1 = _apply(e_uni, keys, 7_000_000, **cfg)
        r2 = _apply(e_gen, keys, 7_000_000, **cfg)
        _check_equal(r1, r2, f"case={cfg}")
    from gubernator_tpu.ops.bucket_kernel import UNIFORM_IN_ROWS

    uniform_used = [s for s in shapes if s[0] == UNIFORM_IN_ROWS]
    general_used = [s for s in shapes if s[0] != UNIFORM_IN_ROWS]
    assert general_used, "out-of-range configs must use the general path"
    assert uniform_used, "in-range config must use the uniform path"


def test_uniform_pipelined_cross_batch_state(engines):
    """Queued uniform batches across async calls apply sequentially
    (scan order) — shared-key accounting must be exact."""
    e_uni, e_gen = engines
    ps1, ps2 = [], []
    for r in range(10):
        ps1.append(
            e_uni.apply_columnar(
                [b"shared"], np.zeros(1, np.int32), np.zeros(1, np.int32),
                np.ones(1, np.int64), np.full(1, 1000, np.int64),
                np.full(1, 60_000, np.int64), np.zeros(1, np.int64),
                now_ms=9_000_000, want_async=True,
            )
        )
        ps2.append(
            e_gen.apply_columnar(
                [b"shared"], np.zeros(1, np.int32), np.zeros(1, np.int32),
                np.ones(1, np.int64), np.full(1, 1000, np.int64),
                np.full(1, 60_000, np.int64), np.zeros(1, np.int64),
                now_ms=9_000_000, want_async=True,
            )
        )
    rems1 = [int(p.get()[2][0]) for p in ps1]
    rems2 = [int(p.get()[2][0]) for p in ps2]
    assert rems1 == rems2 == list(range(999, 989, -1))


def test_reset_remaining_reset_time_zero_not_wrapped(engines):
    """RESET_REMAINING responds reset_time=0 (reference semantics); the
    narrow (reset-now) delta cannot encode that, so the gate must route
    such batches to the general format (code-review r4 repro: the
    uniform path returned now+wrap instead of 0)."""
    e_uni, e_gen = engines
    keys = [b"rr%d" % i for i in range(8)]
    # Seed existing buckets, then hit them again with RESET_REMAINING.
    _apply(e_uni, keys, 1_700_000_000_000, hits=3, limit=10)
    _apply(e_gen, keys, 1_700_000_000_000, hits=3, limit=10)
    r1 = _apply(e_uni, keys, 1_700_000_000_500, hits=1, limit=10,
                behavior=8)
    r2 = _apply(e_gen, keys, 1_700_000_000_500, hits=1, limit=10,
                behavior=8)
    _check_equal(r1, r2, "reset-remaining")
    assert (np.asarray(r1[3]) == 0).all(), "reset_time must be 0"

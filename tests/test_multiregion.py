"""Multi-region federation suite (RESILIENCE.md §12, ISSUE 14).

Two tiers:

- UNIT: a MultiRegionManager over fake region rings/peers (no jax, no
  grpc servers) pins window aggregation, the cleared MULTI_REGION flag
  on forwarded copies, requeue-on-failure with age-capped counted
  drops, per-region circuit aggregation, and the fan-out barrier.
- CLUSTER: a real 2×2 region×peer harness (two datacenters, two
  daemons each) pins the federation invariants end to end — degraded
  region metadata under partition, the canary over-admission bound
  (≤ N_regions × limit), heal convergence with zero drops, and the
  metrics surface.

Fast cases run tier-1; the multi-cycle partition soak is @slow.
"""

import time
from dataclasses import replace as dc_replace

import pytest

from gubernator_tpu.client import V1Client, random_string
from gubernator_tpu.cluster.harness import ClusterHarness, cluster_behaviors
from gubernator_tpu.cluster.health import (
    REGION_DEGRADED,
    REGION_HEALTHY,
    REGION_OPEN,
    PeerHealth,
    aggregate_region_state,
)
from gubernator_tpu.cluster.multiregion import MultiRegionManager, _combine
from gubernator_tpu.cluster.peer_client import PeerError
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.types import Behavior, PeerInfo, RateLimitReq, Status

_MR = int(Behavior.MULTI_REGION)


def _until(pred, timeout=8.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _req(name, key, limit=1_000_000, hits=1, behavior=_MR):
    return RateLimitReq(
        name=name,
        unique_key=key,
        hits=hits,
        limit=limit,
        duration=60_000,
        behavior=behavior,
    )


# ----------------------------------------------------------------------
# Unit tier: fake regions.


class FakePeer:
    def __init__(self, addr, dc):
        self.info = PeerInfo(
            grpc_address=addr, http_address="", datacenter=dc
        )
        self.health = PeerHealth(
            addr, failure_threshold=3, backoff=0.4, backoff_cap=2.0
        )
        self.fail = False
        self.delay = 0.0
        self.sent = []  # list of request lists, in delivery order

    def send_peer_hits(self, reqs, timeout=None):
        if not self.health.allow():
            raise PeerError(
                f"circuit open to {self.info.grpc_address}",
                not_ready=True, circuit_open=True,
            )
        if self.delay:
            time.sleep(self.delay)
        if self.fail:
            self.health.record_failure()
            raise PeerError("injected region fault", not_ready=True)
        self.health.record_success()
        self.sent.append(list(reqs))


class FakeRing:
    def __init__(self, peers):
        self._peers = list(peers)

    def get(self, key):
        # Deterministic key→member mapping (tests pick keys per peer).
        return self._peers[hash(key) % len(self._peers)]

    def peers(self):
        return list(self._peers)


class FakeInstance:
    def __init__(self, regions):
        self.regions = {dc: FakeRing(peers) for dc, peers in regions.items()}

    def get_region_pickers(self):
        return self.regions


def _behaviors(**over):
    base = dict(
        multi_region_sync_wait=0.01,
        multi_region_timeout=0.2,
        multi_region_batch_limit=100,
        multi_region_fanout_deadline=0.5,
        multi_region_requeue_age=2.0,
        multi_region_backoff=0.01,
        multi_region_backoff_cap=0.05,
    )
    base.update(over)
    return BehaviorConfig(**base)


def _mgr(regions, **over):
    inst = FakeInstance(regions)
    return MultiRegionManager(_behaviors(**over), inst), inst


def test_combine_sums_hits_latest_config_wins():
    a = _req("mr", "k", hits=3, limit=10)
    b = _req("mr", "k", hits=4, limit=20)
    assert _combine(None, a) is a
    merged = _combine(a, b)
    assert merged.hits == 7
    assert merged.limit == 20  # latest occurrence's config


def test_window_aggregates_and_clears_flag_per_region():
    east = FakePeer("10.0.0.1:81", "dc-b")
    west = FakePeer("10.0.1.1:81", "dc-c")
    mgr, _ = _mgr({"dc-b": [east], "dc-c": [west]})
    try:
        for h in (1, 2, 4):
            mgr.queue_hits(_req("mr", "agg", hits=h))
        mgr.retry_now()
        for peer in (east, west):
            assert len(peer.sent) == 1, peer.sent
            (r,) = peer.sent[0]
            assert r.hits == 7  # one aggregated delta per region
            # The forwarded copy clears MULTI_REGION: the receiving
            # region applies locally — no DCN ping-pong loop.
            assert int(r.behavior) & _MR == 0
        st = mgr.stats()
        assert st["windows"] == 1
        assert st["region_sends_by"] == {"dc-b": 1, "dc-c": 1}
    finally:
        mgr.close()


def test_failed_region_requeues_only_there_and_converges():
    ok = FakePeer("10.0.0.1:81", "dc-b")
    down = FakePeer("10.0.1.1:81", "dc-c")
    down.fail = True
    mgr, _ = _mgr({"dc-b": [ok], "dc-c": [down]})
    try:
        mgr.queue_hits(_req("mr", "cv", hits=5))
        mgr.retry_now()
        assert len(ok.sent) == 1
        assert down.sent == []
        st = mgr.stats()
        assert st["hits_requeued"] >= 1
        assert st["pending_retry"] == 1
        # Heal: the retry is bound to dc-c ONLY — dc-b must not see
        # the delta twice (that would double-count its region).
        down.fail = False
        assert _until(
            lambda: (mgr.retry_now(), None)[1] or len(down.sent) >= 1,
            timeout=5.0,
        ), mgr.stats()
        (r,) = down.sent[0]
        assert r.hits == 5
        assert len(ok.sent) == 1  # never resent to the healthy region
        assert mgr.pending_retry() == 0
        assert mgr.stats()["hits_dropped"] == 0
    finally:
        mgr.close()


def test_requeue_age_cap_drops_counted():
    down = FakePeer("10.0.1.1:81", "dc-c")
    down.fail = True
    mgr, _ = _mgr({"dc-c": [down]}, multi_region_requeue_age=0.1)
    try:
        mgr.queue_hits(_req("mr", "age", hits=1))
        mgr.retry_now()  # fails → first-failure ts recorded
        assert mgr.stats()["hits_requeued"] >= 1
        time.sleep(0.15)  # inside (age_cap, 2*age_cap]
        mgr.retry_now()  # fails again → the age check drops, counted
        assert _until(
            lambda: (mgr.retry_now(), None)[1]
            or mgr.stats()["hits_dropped"] >= 1,
            timeout=3.0,
        ), mgr.stats()
        assert mgr.pending_retry() == 0
    finally:
        mgr.close()


def test_region_state_aggregates_member_breakers():
    a = PeerHealth("a", failure_threshold=1, backoff=5.0)
    b = PeerHealth("b", failure_threshold=1, backoff=5.0)
    assert aggregate_region_state([a, b]) == REGION_HEALTHY
    a.record_failure()  # breaks immediately (threshold 1)
    assert aggregate_region_state([a, b]) == REGION_DEGRADED
    b.record_failure()
    assert aggregate_region_state([a, b]) == REGION_OPEN
    assert aggregate_region_state([]) == REGION_HEALTHY
    b.record_success()
    assert aggregate_region_state([a, b]) == REGION_DEGRADED


def test_open_region_surfaces_in_manager_states():
    down = FakePeer("10.0.1.1:81", "dc-c")
    down.fail = True
    mgr, _ = _mgr({"dc-c": [down]})
    try:
        for i in range(3):  # threshold 3 → circuit opens
            mgr.queue_hits(_req("mr", f"st{i}", hits=1))
            mgr.retry_now()
        assert _until(
            lambda: mgr.region_states().get("dc-c") == REGION_OPEN,
            timeout=3.0,
        ), mgr.region_states()
        assert mgr.open_regions() == ["dc-c"]
    finally:
        mgr.close()


def test_fanout_deadline_bounds_slow_region():
    """A region swallowing sends whole (2 s per RPC) must not stall
    the window past the barrier budget — the healthy region's delta
    still lands inside it."""
    slow = FakePeer("10.0.1.1:81", "dc-slow")
    slow.delay = 2.0
    quick = FakePeer("10.0.0.1:81", "dc-quick")
    mgr, _ = _mgr(
        {"dc-slow": [slow], "dc-quick": [quick]},
        multi_region_fanout_deadline=0.4,
    )
    try:
        mgr.queue_hits(_req("mr", "dl", hits=1))
        t0 = time.monotonic()
        mgr.retry_now()
        elapsed = time.monotonic() - t0
        assert elapsed < 1.5, f"window stalled {elapsed:.2f}s"
        assert len(quick.sent) == 1
        from gubernator_tpu.utils.metrics import swallowed_counts

        assert swallowed_counts().get("multiregion.fanout_deadline", 0) > 0
    finally:
        mgr.close()


def test_unroutable_key_counts_swallow():
    class BadRing(FakeRing):
        def get(self, key):
            raise RuntimeError("picker torn down")

    inst = FakeInstance({})
    inst.regions = {"dc-x": BadRing([FakePeer("10.9.9.9:81", "dc-x")])}
    mgr = MultiRegionManager(_behaviors(), inst)
    try:
        from gubernator_tpu.utils.metrics import swallowed_counts

        before = swallowed_counts().get("multiregion.pick", 0)
        mgr.queue_hits(_req("mr", "bad", hits=1))
        mgr.retry_now()
        assert swallowed_counts().get("multiregion.pick", 0) > before
    finally:
        mgr.close()


# ----------------------------------------------------------------------
# Cluster tier: the 2×2 region×peer topology.

WEST = "dc-west"


@pytest.fixture(scope="module")
def xr():
    h = ClusterHarness().start(4, datacenters=["", "", WEST, WEST])
    h.install_faults(seed=21)
    yield h
    h.stop()


def _mr_keys_by_west_owner(h, name, prefix):
    """Two keys with ONE east owner (daemon 0) but DIFFERENT west
    owners: region `open` means the whole region refuses, so the
    answering east owner's circuits to BOTH west daemons must open —
    which takes failed pushes toward both."""
    east_addr = h.daemons[0].peer_info().grpc_address
    out = {}
    i = 0
    while len(out) < 2:
        key = f"{i}_{prefix}{random_string()}"
        hk = f"{name}_{key}"
        if h.owner_of(hk).peer_info().grpc_address != east_addr:
            i += 1
            continue
        addr = h.owner_of(hk, WEST).peer_info().grpc_address
        if addr not in out:
            out[addr] = key
        i += 1
        assert i < 40_000
    return list(out.values())


def test_crossregion_hits_converge_when_healthy(xr):
    h = xr
    key = f"h_{random_string()}"
    req = _req("xr_ok", key, hits=7)
    east_owner = h.owner_of(req.hash_key())
    west_owner = h.owner_of(req.hash_key(), WEST)
    with V1Client(east_owner.grpc_address) as c:
        r = c.get_rate_limits([req], timeout=15)[0]
        assert r.error == ""
        assert r.metadata.get("degraded_region") is None
    # The west owner's engine converges onto the same count.
    def _west_sees():
        east_owner.instance.multi_region_mgr.retry_now()
        with V1Client(west_owner.grpc_address) as wc:
            wr = wc.get_rate_limits(
                [_req("xr_ok", key, hits=0)], timeout=15
            )[0]
            return wr.remaining == 1_000_000 - 7
    assert _until(_west_sees, timeout=10.0, interval=0.2)


def test_partition_degraded_region_metadata_and_requeue(xr):
    h = xr
    keys = _mr_keys_by_west_owner(h, "xr_deg", "dg")
    h.partition_regions("", WEST)
    try:
        east = h.daemons[0]
        mgr = east.instance.multi_region_mgr
        with V1Client(east.grpc_address) as c:
            # Traffic on two keys east-owned by daemon 0 but
            # west-owned by DIFFERENT west daemons: the failed pushes
            # open daemon 0's circuit to every west member, the region
            # aggregate reads `open`, and answers flag
            # degraded_region.
            def _degraded():
                mgr.retry_now()  # push (and re-push) the deltas
                flagged = False
                for key in keys:
                    r = c.get_rate_limits(
                        [_req("xr_deg", key)], timeout=15
                    )[0]
                    assert r.error == ""
                    if r.metadata.get("degraded_region") == "true":
                        assert WEST in r.metadata.get(
                            "degraded_regions", ""
                        )
                        flagged = True
                return flagged
            assert _until(_degraded, timeout=20.0, interval=0.2), (
                h.multiregion_states()
            )
        # The failed deltas are re-queued, not dropped.
        total = {}
        for d, dc in zip(h.daemons, h._datacenters):
            if dc == "":
                total[d.grpc_address] = d.multiregion_stats()
        assert any(
            st["hits_requeued"] > 0 for st in total.values()
        ), total
        assert sum(
            d.instance.counters["degraded_region_answers"]
            for d, dc in zip(h.daemons, h._datacenters)
            if dc == ""
        ) > 0
    finally:
        h.heal()
        _settle_heal(h)


def _settle_heal(h, timeout=20.0):
    """Drain every node's retry backlog after a heal (probes ride the
    retries themselves) and wait for circuits to converge."""
    def _drained():
        for d in h.daemons:
            d.instance.multi_region_mgr.retry_now()
        return all(
            d.instance.multi_region_mgr.pending_retry() == 0
            for d in h.daemons
        )
    assert _until(_drained, timeout=timeout, interval=0.2), {
        d.grpc_address: d.multiregion_stats() for d in h.daemons
    }


def test_partition_canary_over_admission_within_region_bound(xr):
    """The §12 drift bound, asserted live: under a full inter-region
    partition each region's owner admits from local state, so a
    finite-limit canary admits at most N_regions × limit cluster-wide
    (and at least `limit` — the healthy region share)."""
    h = xr
    limit = 10
    key = f"cb_{random_string()}"
    name = "xr_bound"
    h.partition_regions("", WEST)
    try:
        admitted = 0
        for dc in ("", WEST):
            owner = h.owner_of(f"{name}_{key}", dc)
            with V1Client(owner.grpc_address) as c:
                for _ in range(3 * limit):
                    r = c.get_rate_limits(
                        [_req(name, key, limit=limit)], timeout=15
                    )[0]
                    assert r.error == ""
                    if r.status == Status.UNDER_LIMIT:
                        admitted += 1
        n_regions = 2
        assert limit <= admitted <= n_regions * limit, admitted
    finally:
        h.heal()
        _settle_heal(h)


def test_heal_convergence_delivers_requeued_hits(xr):
    """Deltas queued during the partition land after the heal: the
    west owner's bucket reflects the east hits, nothing dropped —
    requeue-and-converge end to end."""
    h = xr
    key = f"cv_{random_string()}"
    name = "xr_conv"
    hits = 5
    east_owner = h.owner_of(f"{name}_{key}")
    west_owner = h.owner_of(f"{name}_{key}", WEST)
    dropped_before = east_owner.multiregion_stats()["hits_dropped"]
    h.partition_regions("", WEST)
    try:
        with V1Client(east_owner.grpc_address) as c:
            r = c.get_rate_limits(
                [_req(name, key, hits=hits)], timeout=15
            )[0]
            assert r.error == ""
        mgr = east_owner.instance.multi_region_mgr
        mgr.retry_now()  # fails against the partition → requeued
        assert _until(
            lambda: (mgr.retry_now(), None)[1]
            or mgr.pending_retry() > 0,
            timeout=8.0,
        ), east_owner.multiregion_stats()
    finally:
        h.heal()
    _settle_heal(h)
    def _west_converged():
        with V1Client(west_owner.grpc_address) as wc:
            wr = wc.get_rate_limits(
                [_req(name, key, hits=0)], timeout=15
            )[0]
            return wr.remaining == 1_000_000 - hits
    assert _until(_west_converged, timeout=10.0, interval=0.2)
    assert (
        east_owner.multiregion_stats()["hits_dropped"] == dropped_before
    )


def test_multiregion_metrics_exported(xr):
    import urllib.request

    h = xr
    body = urllib.request.urlopen(
        f"http://{h.daemons[0].http_address}/metrics", timeout=5
    ).read().decode()
    assert "gubernator_multiregion_windows" in body
    assert "gubernator_multiregion_region_sends" in body
    assert "gubernator_multiregion_hits_requeued" in body
    assert "gubernator_multiregion_hits_dropped" in body
    assert 'gubernator_multiregion_region_state{' in body
    assert "gubernator_multiregion_degraded_answers" in body
    # The operator entry mirrors the scrape.
    st = h.daemons[0].multiregion_stats()
    assert WEST in st["region_states"]
    assert "window_wait" in st and "region_rpc" in st


# ----------------------------------------------------------------------
# Soak: partition/heal cycles with sustained federated traffic.


@pytest.mark.slow
def test_multiregion_partition_soak():
    """Three partition-heal cycles under sustained MULTI_REGION
    traffic: zero errors throughout (region-local answering), the
    canary never exceeds N_regions × limit, every cycle converges the
    retry backlog after heal, and age-cap drops stay zero (the heal
    always lands inside the requeue age)."""
    b = dc_replace(cluster_behaviors(), multi_region_requeue_age=30.0)
    h = ClusterHarness().start(
        4, datacenters=["", "", WEST, WEST], behaviors=b
    )
    h.install_faults(seed=77)
    try:
        limit = 50
        key = f"sk_{random_string()}"
        n_err = 0
        admitted = 0
        def drive(dc, rounds):
            nonlocal n_err, admitted
            owner = h.owner_of(f"xr_soak_{key}", dc)
            with V1Client(owner.grpc_address) as c:
                for i in range(rounds):
                    rs = c.get_rate_limits(
                        [
                            _req("xr_soak", key, limit=limit),
                            _req("xr_soak_t", f"t{i % 13}_{dc}"),
                        ],
                        timeout=15,
                    )
                    for r in rs:
                        if r.error:
                            n_err += 1
                    if rs[0].status == Status.UNDER_LIMIT and not rs[0].error:
                        admitted += 1
        for cycle in range(3):
            drive("", 10)
            drive(WEST, 10)
            h.partition_regions("", WEST)
            drive("", 15)
            drive(WEST, 15)
            h.heal()
            _settle_heal(h)
        assert n_err == 0
        assert admitted <= 2 * limit, admitted
        dropped = sum(
            d.multiregion_stats()["hits_dropped"] for d in h.daemons
        )
        assert dropped == 0, {
            d.grpc_address: d.multiregion_stats() for d in h.daemons
        }
    finally:
        h.stop()

"""Differential fuzz: the vectorized kernel must match the scalar spec.

`gubernator_tpu.models.spec.apply_spec` is the hand-checked transcription
of reference algorithms.go; the engine runs the same stream through the
device kernel.  Every response field must match exactly on every step.
"""

from __future__ import annotations

import random

from gubernator_tpu import Algorithm, Behavior, RateLimitReq
from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.gregorian import (
    GregorianError,
    gregorian_duration,
    gregorian_expiration,
)
from gubernator_tpu.models.spec import SlotState, SpecInput, apply_spec


class SpecShadow:
    """Scalar shadow state: key → SlotState, applied in arrival order."""

    def __init__(self, clock: Clock):
        self.clock = clock
        self.buckets: dict[str, SlotState] = {}

    def apply(self, reqs: list[RateLimitReq]):
        from gubernator_tpu.gregorian import dt_from_ms

        now = self.clock.now_ms()
        now_dt = dt_from_ms(now)
        outs = []
        for r in reqs:
            greg_dur = greg_exp = 0
            if int(r.behavior) & Behavior.DURATION_IS_GREGORIAN:
                try:
                    greg_dur = gregorian_duration(now_dt, r.duration)
                    greg_exp = gregorian_expiration(now_dt, r.duration)
                except GregorianError:
                    outs.append(None)  # engine returns an error response
                    continue
            inp = SpecInput(
                hits=r.hits,
                limit=r.limit,
                duration=r.duration,
                burst=r.burst,
                algorithm=int(r.algorithm),
                behavior=int(r.behavior),
                greg_duration=greg_dur,
                greg_expire=greg_exp,
            )
            key = r.hash_key()
            state, out = apply_spec(self.buckets.get(key), inp, now)
            if state is None:
                self.buckets.pop(key, None)
            else:
                self.buckets[key] = state
            outs.append(out)
        return outs


def _random_req(rng: random.Random, keys: list[str]) -> RateLimitReq:
    behavior = 0
    if rng.random() < 0.15:
        behavior |= Behavior.RESET_REMAINING
    duration = rng.choice([0, 1, 5, 100, 1000, 9000, 30000])
    if rng.random() < 0.2:
        behavior |= Behavior.DURATION_IS_GREGORIAN
        duration = rng.choice([0, 1, 2, 3, 4, 5])
    return RateLimitReq(
        name="fuzz",
        unique_key=rng.choice(keys),
        hits=rng.choice([-3, -1, 0, 1, 1, 1, 2, 5, 10, 100]),
        limit=rng.choice([0, 1, 2, 5, 10, 100]),
        duration=duration,
        algorithm=rng.choice([Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]),
        behavior=behavior,
        burst=rng.choice([0, 0, 0, 5, 20]),
    )


def test_kernel_matches_spec_fuzz():
    rng = random.Random(1234)
    clock = Clock().freeze()
    engine = DecisionEngine(capacity=256, clock=clock)
    shadow = SpecShadow(clock)
    keys = [f"k{i}" for i in range(12)]

    for step in range(300):
        batch = [_random_req(rng, keys) for _ in range(rng.randint(1, 8))]
        got = engine.get_rate_limits(batch)
        want = shadow.apply(batch)
        for i, (g, w) in enumerate(zip(got, want)):
            ctx = f"step={step} i={i} req={batch[i]}"
            if w is None:
                assert g.error != "", ctx
                continue
            assert g.error == "", ctx
            assert int(g.status) == int(w.status), ctx
            assert g.limit == w.limit, ctx
            assert g.remaining == w.remaining, ctx
            assert g.reset_time == w.reset_time, ctx
        clock.advance(ms=rng.choice([0, 0, 1, 3, 7, 100, 1000, 40000]))


def test_kernel_matches_spec_single_key_long_stream():
    """Long sequential stream on one key — exercises state carry-over."""
    rng = random.Random(99)
    clock = Clock().freeze()
    engine = DecisionEngine(capacity=16, clock=clock)
    shadow = SpecShadow(clock)

    for step in range(400):
        batch = [_random_req(rng, ["solo"])]
        got = engine.get_rate_limits(batch)
        want = shadow.apply(batch)
        g, w = got[0], want[0]
        ctx = f"step={step} req={batch[0]}"
        if w is None:
            assert g.error != "", ctx
            continue
        assert int(g.status) == int(w.status), ctx
        assert g.remaining == w.remaining, ctx
        assert g.reset_time == w.reset_time, ctx
        clock.advance(ms=rng.choice([0, 1, 2, 500, 1500, 61000]))

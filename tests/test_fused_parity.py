"""Fused-kernel parity: the Pallas decision step (interpret mode on
CPU) must be BIT-EQUAL to the scalar spec (models/spec.py), to the XLA
fused program, and to the ledger-fronted serve partition — token and
leaky buckets, duration-change renewal, and expiry boundaries included
(the test_ledger.py harness shape).

Also pins the ISSUE 10 acceptance invariant directly: a steady-state
fused decision batch runs as a SINGLE device dispatch.
"""

from __future__ import annotations

import numpy as np
import pytest

from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine, PackedKeys
from gubernator_tpu.models.spec import SlotState, SpecInput, apply_spec
from gubernator_tpu.ops import bucket_kernel as bk
from gubernator_tpu.ops.pallas_step import pallas_fused_step
from gubernator_tpu.types import Algorithm, Behavior, Status

SECOND = 1000


class PallasShadow:
    """Drives the Pallas kernel (interpret mode) directly: key → slot
    interning on the host, packed rounds through pallas_fused_step —
    the exact serving layout, minus the engine plumbing."""

    def __init__(self, capacity: int = 512, width: int = 64):
        self.capacity = capacity
        self.width = width
        self.state = bk.make_state(capacity)
        self.slots: dict[bytes, int] = {}

    def _slot(self, key: bytes) -> int:
        s = self.slots.get(key)
        if s is None:
            s = len(self.slots)
            assert s < self.capacity
            self.slots[key] = s
        return s

    def apply(self, rows, now_ms: int):
        """rows: [(key, algo, behavior, hits, limit, duration, burst)]
        with unique keys (callers split duplicate keys into rounds).
        Returns [(status, limit, remaining, reset)] in row order."""
        import jax.numpy as jnp

        m = len(rows)
        slot = np.asarray([self._slot(r[0]) for r in rows], np.int32)
        order = np.argsort(slot, kind="stable")
        cols = [np.asarray([r[j] for r in rows], np.int64) for j in range(1, 7)]
        buf = bk.pack_batch_host(
            self.width,
            now_ms,
            self.capacity,
            np.ascontiguousarray(slot[order]),
            *(c[order] for c in cols),
            np.zeros(m, np.int64),
            np.zeros(m, np.int64),
        )
        self.state, pout = pallas_fused_step(
            self.state, jnp.asarray(buf), interpret=True
        )
        st, rem, rst = bk.unpack_out_host(np.asarray(pout), m)
        inv = np.empty(m, np.int64)
        inv[order] = np.arange(m)
        limits = cols[3]
        return [
            (int(st[inv[i]]), int(limits[i]), int(rem[inv[i]]), int(rst[inv[i]]))
            for i in range(m)
        ]


class SpecShadow:
    def __init__(self):
        self.states: dict[bytes, SlotState] = {}

    def apply(self, rows, now_ms: int):
        out = []
        for key, algo, behavior, hits, limit, duration, burst in rows:
            inp = SpecInput(
                hits=int(hits), limit=int(limit), duration=int(duration),
                burst=int(burst), algorithm=int(algo), behavior=int(behavior),
            )
            state, resp = apply_spec(self.states.get(key), inp, now_ms)
            if state is None:
                self.states.pop(key, None)
            else:
                self.states[key] = state
            out.append(
                (int(resp.status), int(resp.limit), int(resp.remaining),
                 int(resp.reset_time))
            )
        return out


def _rand_rows(rng, keys, n):
    rows = []
    for _ in range(n):
        key = rng.choice(keys)
        algo = int(rng.choice([0, 1]))
        behavior = 0
        if rng.random() < 0.1:
            behavior |= int(Behavior.RESET_REMAINING)
        rows.append(
            (
                key,
                algo,
                behavior,
                int(rng.choice([-2, 0, 1, 1, 1, 2, 5, 11])),
                int(rng.choice([0, 1, 3, 10, 50])),
                int(rng.choice([1, 40, 200, 1000])),
                int(rng.choice([0, 0, 0, 5, 20])),
            )
        )
    # Unique keys per kernel round (the engine's rounds invariant).
    seen, uniq = set(), []
    for r in rows:
        if r[0] in seen:
            continue
        seen.add(r[0])
        uniq.append(r)
    return uniq


def test_pallas_interpret_bit_equal_to_spec_fuzz():
    """Token + leaky fuzz across advancing time: every response field
    of the Pallas kernel equals the scalar spec, including expiry
    boundaries crossed by the clock advances."""
    rng = np.random.default_rng(11)
    shadow = PallasShadow()
    oracle = SpecShadow()
    keys = [b"fz_%d" % i for i in range(24)]
    now = 1_000_000
    for step in range(120):
        now += int(rng.integers(0, 120))  # crosses 40/200/1000ms expiries
        rows = _rand_rows(rng, keys, int(rng.integers(1, 16)))
        got = shadow.apply(rows, now)
        want = oracle.apply(rows, now)
        assert got == want, f"step {step} now={now}: {rows}"


def test_pallas_duration_change_renewal_boundary():
    """The duration-change renewal quirk (stored remaining becomes
    limit, response reports the pre-renewal snapshot — spec docstring)
    must hold bit-for-bit through the Pallas kernel, on both sides of
    the `new_expire <= now` boundary."""
    shadow = PallasShadow()
    oracle = SpecShadow()
    now = 50_000
    key = b"renew"
    for rows, dt in [
        ([(key, 0, 0, 3, 10, 100, 0)], 0),     # create, expire=now+100
        ([(key, 0, 0, 1, 10, 100, 0)], 40),    # consume inside window
        ([(key, 0, 0, 1, 10, 70, 0)], 0),      # dur change, not renewed
        ([(key, 0, 0, 1, 10, 100, 0)], 65),    # back; still live
        ([(key, 0, 0, 1, 10, 30, 0)], 0),      # dur change → renewal
        ([(key, 0, 0, 0, 10, 30, 0)], 0),      # query the renewed bucket
    ]:
        now += dt
        assert shadow.apply(rows, now) == oracle.apply(rows, now), (
            rows, now,
        )


def test_pallas_expiry_boundary_exact():
    """`expire_at < now` is a strict miss; equality still serves the
    item (lrucache.go semantics) — pinned at the exact millisecond."""
    shadow = PallasShadow()
    oracle = SpecShadow()
    key = b"edge"
    base = 10_000
    assert shadow.apply([(key, 0, 0, 2, 5, 100, 0)], base) == oracle.apply(
        [(key, 0, 0, 2, 5, 100, 0)], base
    )
    for now in (base + 100, base + 101):  # at expiry, one past it
        rows = [(key, 0, 0, 1, 5, 100, 0)]
        assert shadow.apply(rows, now) == oracle.apply(rows, now), now


def test_pallas_leaky_fractional_leak_parity():
    """Leaky buckets accrue fractional leak by leaving t0 untouched
    (the TestLeakyBucketDivBug quirk) — the 32.32 fixed-point path
    through the kernel must track the spec's quantization exactly."""
    shadow = PallasShadow()
    oracle = SpecShadow()
    key = b"leak"
    now = 77_000
    rows = [(key, 1, 0, 3, 7, 700, 0)]
    assert shadow.apply(rows, now) == oracle.apply(rows, now)
    for dt in (30, 30, 30, 110, 1, 49, 1000):
        now += dt
        rows = [(key, 1, 0, 1, 7, 700, 0)]
        assert shadow.apply(rows, now) == oracle.apply(rows, now), now


def _ledger_harness(clock):
    from gubernator_tpu.core.ledger import DecisionLedger
    from gubernator_tpu.hashing import fnv1a_64

    class _Dec:
        __slots__ = (
            "n", "key_buf", "key_offsets", "algo", "behavior", "hits",
            "limit", "duration", "burst", "fnv1a",
        )

    def make_dec(rows):
        d = _Dec()
        keys = [r[0] for r in rows]
        d.n = len(rows)
        d.key_buf = np.frombuffer(
            b"".join(keys) or b"\0", dtype=np.uint8
        )
        off = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum([len(k) for k in keys], out=off[1:])
        d.key_offsets = off
        for j, name in enumerate(
            ("algo", "behavior", "hits", "limit", "duration", "burst")
        ):
            setattr(
                d, name,
                np.asarray([r[j + 1] for r in rows],
                           np.int32 if j < 2 else np.int64),
            )
        d.fnv1a = np.asarray([fnv1a_64(k) for k in keys], np.uint64)
        return d

    engine = DecisionEngine(capacity=2048, clock=clock)
    ledger = DecisionLedger(engine, settle_interval=0, lease_size=4)

    def serve(rows):
        now = clock.now_ms()
        plan = ledger.plan(make_dec(rows), now)
        if plan.full:
            st, lim, rem, rst = plan.dense_cols()
        else:
            lane = plan.build_engine_lane()
            st, lim, rem, rst = engine.apply_columnar(
                PackedKeys(lane.key_buf, lane.key_offsets, lane.n),
                lane.algo, lane.behavior, lane.hits, lane.limit,
                lane.duration, lane.burst, now_ms=now,
            )
            plan.learn(st, lim, rem, rst)
            st, _lim, rem, rst = plan.merge_outputs(st, rem, rst)
        return st, rem, rst

    return engine, ledger, serve


@pytest.mark.parametrize("seed", [3, 19])
def test_pallas_vs_spec_vs_ledger_three_way(seed, monkeypatch):
    """The three-tier pin the ISSUE asks for: the Pallas kernel
    (interpret, forced via GUBER_FUSED for the ENGINE the ledger
    fronts), the host ledger's answers through that engine, and the
    scalar spec all agree row for row — token AND leaky, across
    duration changes and expiries."""
    monkeypatch.setenv("GUBER_FUSED", "interpret")
    monkeypatch.setenv("GUBER_PUMP", "0")
    rng = np.random.default_rng(seed)
    clock = Clock().freeze()
    engine, ledger, serve = _ledger_harness(clock)
    assert engine.fused_mode == "pallas-interpret"
    oracle = SpecShadow()
    keys = [b"led_%d" % i for i in range(10)]
    try:
        for step in range(60):
            clock.advance(ms=int(rng.integers(0, 60)))
            rows = []
            for _ in range(int(rng.integers(1, 8))):
                key = keys[int(rng.integers(0, len(keys)))]
                algo = int(key[-1] % 2)  # algo is a property of the key
                rows.append(
                    (
                        key, algo, 0,
                        int(rng.choice([0, 1, 1, 2, 4])),
                        int(rng.choice([2, 5, 9])),
                        int(rng.choice([40, 90, 400])),
                        0,
                    )
                )
            st, rem, rst = serve(rows)
            now = clock.now_ms()
            want = oracle.apply(rows, now)
            for i, (es, _el, er, et) in enumerate(want):
                got = (int(st[i]), int(rem[i]), int(rst[i]))
                assert got == (es, er, et), (
                    f"seed {seed} step {step} row {i} {rows[i]}: "
                    f"ledger+pallas={got} spec={(es, er, et)}"
                )
    finally:
        ledger.close()


@pytest.mark.parametrize("seed", [7])
def test_paged_vs_spec_vs_ledger_three_way(seed, monkeypatch):
    """The three-way harness with the PAGED plane underneath
    (GUBER_PAGED, core/paging.py): ledger-fronted answers through a
    paged Pallas-interpret engine squeezed to 64 resident rows under a
    2048-slot key space still match the scalar spec row for row —
    eviction→spill→refill roundtrips land mid-fuzz (asserted via the
    fault counters), so residency is exercised, not incidental."""
    monkeypatch.setenv("GUBER_FUSED", "interpret")
    monkeypatch.setenv("GUBER_PUMP", "0")
    monkeypatch.setenv("GUBER_PAGED", "1")
    monkeypatch.setenv("GUBER_PAGE_SIZE", "16")
    monkeypatch.setenv("GUBER_PAGED_RESIDENT", "4")
    rng = np.random.default_rng(seed)
    clock = Clock().freeze()
    engine, ledger, serve = _ledger_harness(clock)
    assert engine.paging is not None
    assert engine.capacity == 64 and engine.logical_capacity == 2048
    oracle = SpecShadow()
    # 7x more keys than resident rows: cold keys keep faulting pages.
    keys = [b"pgl_%d" % i for i in range(420)]
    try:
        for step in range(60):
            clock.advance(ms=int(rng.integers(0, 60)))
            rows = []
            for _ in range(int(rng.integers(1, 8))):
                key = keys[int(rng.integers(0, len(keys)))]
                algo = int(key[-1] % 2)
                rows.append(
                    (
                        key, algo, 0,
                        int(rng.choice([0, 1, 1, 2, 4])),
                        int(rng.choice([2, 5, 9])),
                        int(rng.choice([40, 90, 400])),
                        0,
                    )
                )
            st, rem, rst = serve(rows)
            now = clock.now_ms()
            want = oracle.apply(rows, now)
            for i, (es, _el, er, et) in enumerate(want):
                got = (int(st[i]), int(rem[i]), int(rst[i]))
                assert got == (es, er, et), (
                    f"seed {seed} step {step} row {i} {rows[i]}: "
                    f"ledger+paged={got} spec={(es, er, et)}"
                )
        assert engine.paging.faults > 0 and engine.paging.spills > 0
    finally:
        ledger.close()


def test_fused_steady_state_is_single_dispatch(monkeypatch):
    """ISSUE 10 acceptance: in steady state one batch = ONE device
    dispatch (unique keys, no evictions, fused step), and the split
    control dispatches more — the A/B the devfused bench measures."""
    monkeypatch.setenv("GUBER_PUMP", "0")
    clock = Clock().freeze()
    engine = DecisionEngine(capacity=4096, clock=clock)
    assert engine.fused_mode in ("xla", "pallas", "pallas-interpret")

    def batch(engine, start, n=100):
        return engine.apply_columnar(
            [b"sd_%d" % i for i in range(start, start + n)],
            np.zeros(n, np.int32), np.zeros(n, np.int32),
            np.ones(n, np.int64), np.full(n, 10, np.int64),
            np.full(n, 60_000, np.int64), np.zeros(n, np.int64),
        )

    batch(engine, 0)  # first contact interns + compiles
    before = engine.dispatches_total
    batch(engine, 0)  # steady state: same keys, no evictions
    assert engine.dispatches_total - before == 1
    before = engine.dispatches_total
    batch(engine, 200)  # new keys, capacity ample: still one dispatch
    assert engine.dispatches_total - before == 1

    monkeypatch.setenv("GUBER_FUSED", "split")
    unfused = DecisionEngine(capacity=4096, clock=clock)
    assert unfused.fused_mode == "split"
    batch(unfused, 0)
    before = unfused.dispatches_total
    batch(unfused, 0)
    assert unfused.dispatches_total - before >= 2


def test_guber_fused_knob_rejects_unknown(monkeypatch):
    monkeypatch.setenv("GUBER_FUSED", "warp")
    with pytest.raises(ValueError, match="GUBER_FUSED"):
        DecisionEngine(capacity=256, clock=Clock().freeze())


def test_pallas_interpret_engine_serves_wire_shapes(monkeypatch):
    """An engine forced onto the Pallas step serves the ordinary
    columnar + dataclass paths with responses equal to a default
    engine (integration: packers, rounds, readback all route through
    the kernel)."""
    monkeypatch.setenv("GUBER_PUMP", "0")
    clock = Clock().freeze()
    monkeypatch.setenv("GUBER_FUSED", "interpret")
    a = DecisionEngine(capacity=1024, clock=clock)
    monkeypatch.setenv("GUBER_FUSED", "xla")
    b = DecisionEngine(capacity=1024, clock=clock)
    assert a.fused_mode == "pallas-interpret"
    n = 150  # spans two pad widths vs the 64 floor
    cols = dict(
        algo=np.asarray([i % 2 for i in range(n)], np.int32),
        behavior=np.zeros(n, np.int32),
        hits=np.ones(n, np.int64),
        limit=np.full(n, 7, np.int64),
        duration=np.full(n, 2_000, np.int64),
        burst=np.zeros(n, np.int64),
    )
    for step in range(4):
        clock.advance(ms=700)
        keys = [b"w_%d" % (i % 90) for i in range(n)]
        keys = [k + b"!%d" % i for i, k in enumerate(keys)]
        ra = a.apply_columnar(keys, **cols)
        rb = b.apply_columnar(keys, **cols)
        for x, y in zip(ra, rb):
            np.testing.assert_array_equal(x, y)

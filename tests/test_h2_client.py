"""Native h2 gRPC client loop: protocol correctness against a real
grpc-python server (the load-generator's responses must decode as
valid GetRateLimitsResp messages and agree with a stub call)."""

import struct

import pytest

from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.core import h2_client
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.net.pb import gubernator_pb2 as pb


@pytest.fixture
def daemon():
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=1 << 12,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
    )
    d = spawn_daemon(conf)
    yield d
    d.close()


def test_h2_client_round_trip(daemon):
    if h2_client.load() is None:
        pytest.skip("native h2 client unavailable")
    payload = pb.GetRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name="h2", unique_key="k", hits=1, limit=100,
                duration=60_000,
            )
        ]
    ).SerializeToString()
    res = h2_client.bench_unary(
        daemon.grpc_address, "/pb.gubernator.V1/GetRateLimits",
        payload, 0.5, 2,
    )
    assert res is not None, "native client could not connect"
    rpcs, errors, lats, frame, connected = res
    assert rpcs > 0
    assert errors == 0
    assert connected == 2
    assert len(lats) > 0
    # The first captured response must be a valid grpc frame holding a
    # well-formed GetRateLimitsResp with the engine's real answer.
    assert frame and frame[0] == 0
    (ln,) = struct.unpack(">I", frame[1:5])
    resp = pb.GetRateLimitsResp.FromString(frame[5 : 5 + ln])
    assert len(resp.responses) == 1
    r = resp.responses[0]
    assert r.limit == 100
    # hits were applied by some RPC; remaining must have decreased and
    # stayed within range.
    assert 0 <= r.remaining < 100

"""Columnar fast path must agree exactly with the dataclass path."""

import numpy as np
import pytest

from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.types import Algorithm, Behavior, RateLimitReq, Status


def _columns(reqs):
    n = len(reqs)
    return (
        [r.hash_key().encode() for r in reqs],
        np.asarray([int(r.algorithm) for r in reqs], dtype=np.int32),
        np.asarray([int(r.behavior) for r in reqs], dtype=np.int32),
        np.asarray([r.hits for r in reqs], dtype=np.int64),
        np.asarray([r.limit for r in reqs], dtype=np.int64),
        np.asarray([r.duration for r in reqs], dtype=np.int64),
        np.asarray([r.burst for r in reqs], dtype=np.int64),
    )


def test_columnar_matches_dataclass_path(frozen_clock):
    import random

    rng = random.Random(7)
    eng_a = DecisionEngine(capacity=500, clock=frozen_clock)
    eng_b = DecisionEngine(capacity=500, clock=frozen_clock)

    for step in range(10):
        reqs = [
            RateLimitReq(
                name="col",
                unique_key=f"k{rng.randint(0, 80)}",
                hits=rng.randint(0, 3),
                limit=10,
                duration=60_000,
                algorithm=rng.choice(
                    [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]
                ),
                burst=10,
            )
            for _ in range(rng.randint(1, 60))
        ]
        resps = eng_a.get_rate_limits(reqs)
        st, li, rem, rst = eng_b.apply_columnar(*_columns(reqs))
        for i, r in enumerate(resps):
            assert (int(st[i]), int(li[i]), int(rem[i]), int(rst[i])) == (
                int(r.status), r.limit, r.remaining, r.reset_time,
            ), f"step {step} item {i}"
        frozen_clock.advance(ms=rng.randint(0, 5_000))


def test_columnar_duplicate_keys_sequential(frozen_clock):
    eng = DecisionEngine(capacity=100, clock=frozen_clock)
    reqs = [
        RateLimitReq(name="dup", unique_key="same", hits=1, limit=3, duration=60_000)
        for _ in range(5)
    ]
    st, _, rem, _ = eng.apply_columnar(*_columns(reqs))
    assert list(rem) == [2, 1, 0, 0, 0]
    assert list(st) == [0, 0, 0, 1, 1]


def test_columnar_eviction_pressure(frozen_clock):
    eng = DecisionEngine(capacity=64, clock=frozen_clock)
    for wave in range(4):
        reqs = [
            RateLimitReq(
                name="ev", unique_key=f"w{wave}:{i}", hits=1, limit=5,
                duration=60_000,
            )
            for i in range(60)
        ]
        st, _, rem, _ = eng.apply_columnar(*_columns(reqs))
        assert all(r == 4 for r in rem)
    assert eng.table.evictions > 0


def test_columnar_rejects_store(frozen_clock):
    from gubernator_tpu.store import MemoryStore

    eng = DecisionEngine(capacity=64, clock=frozen_clock, store=MemoryStore())
    reqs = [RateLimitReq(name="s", unique_key="k", hits=1, limit=5, duration=1000)]
    with pytest.raises(RuntimeError):
        eng.apply_columnar(*_columns(reqs))

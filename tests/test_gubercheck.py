"""gubercheck proves its own teeth.

Three directions, mirroring tests/test_guberlint.py's seeded-bad
philosophy (STATIC_ANALYSIS.md, gubercheck chapter):

1. **Mutations are caught** — resurrecting two shipped-and-fixed bugs
   (PR 4 duration-renewal, PR 13 lease-churn return race) in a twin
   ledger module makes exploration find a violating schedule within
   the ci_fast smoke budget.  If these ever stop failing, the checker
   has gone blind.
2. **Pristine scenarios are clean** — the smoke budgets in tier-1,
   the committed full budgets (with exploration COMPLETE) in @slow.
3. **The reductions are sound** — dpor agrees with full-mode ground
   truth on verdicts, and the scheduler's structural guarantees
   (determinism, deadlock detection) hold on minimal scenarios.
"""

import threading

import pytest

from tools.gubercheck import explore as explore_mod
from tools.gubercheck import mutations as mut_mod
from tools.gubercheck import scenarios as scn_mod
from tools.gubercheck.explore import explore, run_once
from tools.gubercheck.sched import DeadlockError, Scheduler, instrumented


def _factory(name):
    cls = scn_mod.get_scenario(name)
    return lambda: cls()


# ------------------------------------------------------- mutations


def test_mutation_needles_still_match_the_ledger():
    """Fixture-rot guard: every registered mutation's needle occurs
    exactly once in core/ledger.py (build_mutated_ledger asserts it).
    When a refactor moves a guard, this fails with the fixture name
    instead of the mutation silently mutating nothing."""
    for name in mut_mod.mutation_names():
        mod = mut_mod.build_mutated_ledger(name)
        assert mod.__name__ == "gubernator_tpu.core.ledger"
        assert f"[mutated:{name}]" in mod.__file__


def test_mutations_target_registered_scenarios_and_properties():
    from tools.gubercheck import properties as props

    registered = props.registry()
    for name in mut_mod.mutation_names():
        m = mut_mod.MUTATIONS[name]
        assert m.scenario in scn_mod.scenario_names()
        for p in m.properties:
            assert p in registered, (
                f"mutation {name} expects unregistered property {p}"
            )


@pytest.mark.parametrize("name", list(mut_mod.mutation_names()))
def test_mutation_is_caught_within_smoke_budget(name):
    """The acceptance gate from ISSUE 18: both resurrected historical
    bugs are found by exploration under the ci_fast smoke budget
    (dpor + preemption_bound=2).  Measured: pr4 at run 1, pr13 at
    run 27 — max_runs=2000 leaves two orders of magnitude of slack."""
    m = mut_mod.MUTATIONS[name]
    budget = scn_mod.get_scenario(m.scenario).smoke
    res = explore(
        mut_mod.mutated_scenario_factory(name),
        scenario_name=f"{m.scenario}[{name}]",
        **budget,
    )
    assert res.violations, (
        f"mutation {name} NOT caught in {res.runs} runs — "
        "the checker lost its teeth"
    )
    v = res.violations[0]
    if v.kind == "property":
        assert v.prop in m.properties, (
            f"caught the wrong invariant: {v.prop!r} not in "
            f"{m.properties}"
        )
    assert v.schedule, "a violation must carry its repro schedule"


def test_caught_schedule_replays_deterministically():
    """The schedule attached to a violation is a repro: forcing it
    through run_once re-triggers the same property violation."""
    name = "pr4-duration-renewal-guard"
    m = mut_mod.MUTATIONS[name]
    factory = mut_mod.mutated_scenario_factory(name)
    res = explore(
        factory, scenario_name="repro",
        **scn_mod.get_scenario(m.scenario).smoke,
    )
    v = res.violations[0]
    rr = run_once(factory, v.schedule)
    assert rr.violation is not None
    assert rr.violation.kind == v.kind
    assert rr.violation.prop == v.prop


# ------------------------------------------------- clean scenarios


@pytest.mark.parametrize("name", scn_mod.scenario_names())
def test_clean_scenario_smoke_budget_is_clean(name):
    """Pristine protocol code under the CHESS-bounded smoke budget:
    no violations.  (Whole-catalog measured cost: under a second.)"""
    cls = scn_mod.get_scenario(name)
    res = explore(_factory(name), scenario_name=name, **cls.smoke)
    assert res.ok, (
        f"{name}: {res.violations[0].kind} "
        f"{res.violations[0].detail} on {res.violations[0].schedule}"
    )
    assert res.runs >= 1


@pytest.mark.slow
@pytest.mark.parametrize("name", scn_mod.scenario_names())
def test_clean_scenario_full_budget_explores_completely(name):
    """The committed budgets in Scenario.full are real: exhaustive
    (dpor-reduced) exploration DRAINS — complete=True, no truncation
    — and stays clean.  Measured ceiling: ledger-native-delegation,
    11172 runs / ~25 s; everything else well under 10 s."""
    cls = scn_mod.get_scenario(name)
    res = explore(
        _factory(name), scenario_name=name,
        stop_on_violation=False, **cls.full,
    )
    assert res.ok, f"{name}: {[v.detail for v in res.violations]}"
    assert res.complete, (
        f"{name} truncated by {res.truncated_by} after {res.runs} "
        "runs — the committed budget in scenarios.py is stale"
    )


# ------------------------------------------------------ reductions


def test_dpor_agrees_with_full_ground_truth():
    """Cross-validation on the cheapest full-mode scenario: dpor must
    reach the same verdict as unreduced exploration while visiting a
    strict subset of schedules.  (Measured: 1069 vs 3774 runs.)"""
    full = explore(
        _factory("circuit-breaker"), mode="full",
        max_runs=60000, max_steps=400, stop_on_violation=False,
        scenario_name="cb-full",
    )
    dpor = explore(
        _factory("circuit-breaker"), mode="dpor",
        max_runs=60000, max_steps=400, stop_on_violation=False,
        scenario_name="cb-dpor",
    )
    assert full.complete and dpor.complete
    assert full.ok and dpor.ok
    assert 1 < dpor.runs < full.runs, (
        f"dpor visited {dpor.runs} vs full {full.runs} — reduction "
        "should prune some schedules but never down to one"
    )


def test_dpor_still_catches_mutation_vs_full():
    """Soundness where it matters: the reduction may not prune away
    the violating schedule.  Both modes catch pr4."""
    factory = mut_mod.mutated_scenario_factory(
        "pr4-duration-renewal-guard"
    )
    for mode in ("full", "dpor"):
        res = explore(
            factory, mode=mode, max_runs=2000, max_steps=400,
            scenario_name=f"pr4-{mode}",
        )
        assert res.violations, f"mode={mode} missed the mutation"


def test_preemption_bound_zero_is_sequential_only():
    """preemption_bound=0 explores only non-preemptive schedules — a
    tiny space (it may still catch ordering bugs, but never races
    needing a mid-critical-section switch)."""
    bounded = explore(
        _factory("circuit-breaker"), mode="full", preemption_bound=0,
        max_runs=60000, max_steps=400, stop_on_violation=False,
        scenario_name="cb-pb0",
    )
    unbounded = explore(
        _factory("circuit-breaker"), mode="full",
        max_runs=60000, max_steps=400, stop_on_violation=False,
        scenario_name="cb-pb-none",
    )
    assert bounded.complete
    assert bounded.runs < unbounded.runs


def test_explore_honors_max_runs_truncation():
    res = explore(
        _factory("circuit-breaker"), mode="full", max_runs=3,
        max_steps=400, stop_on_violation=False, scenario_name="cb-3",
    )
    assert res.runs == 3
    assert not res.complete
    assert res.truncated_by == "max_runs"


# ------------------------------------------------------- scheduler


class _DeadlockScenario(scn_mod.Scenario):
    """Minimal AB-BA deadlock: two tasks taking two locks in opposite
    order.  Some schedule must deadlock, and the scheduler must report
    it as DeadlockError rather than hanging."""

    name = "abba"

    def build(self, sched):
        a, b = threading.Lock(), threading.Lock()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        sched.spawn("t1", t1)
        sched.spawn("t2", t2)


def test_scheduler_detects_abba_deadlock():
    res = explore(
        lambda: _DeadlockScenario(), mode="full", max_runs=200,
        max_steps=100, scenario_name="abba",
    )
    assert res.violations
    assert res.violations[0].kind == "deadlock"


def test_scheduler_replay_is_deterministic():
    """Same forced schedule, same step trace — the determinism
    contract exploration is built on."""
    first = run_once(_factory("circuit-breaker"), [])
    sched = [s.chosen for s in first.steps]
    second = run_once(_factory("circuit-breaker"), sched)
    assert [s.chosen for s in second.steps] == sched
    assert [s.op for s in second.steps] == [s.op for s in first.steps]


def test_instrumented_patch_is_scoped():
    """Outside the context manager, threading primitives are the real
    stdlib ones — the patch may not leak into the host process (the
    test suite itself uses threading heavily)."""
    real_lock_cls = type(threading.Lock())
    clock = scn_mod.Clock().freeze_at(scn_mod.EPOCH_NS)
    sched = Scheduler(clock, max_steps=10)
    with instrumented(sched):
        assert type(threading.Lock()) is not real_lock_cls
    assert type(threading.Lock()) is real_lock_cls

"""Intern table unit tests (reference behaviors: lrucache_test.go)."""

from gubernator_tpu.core.interning import InternTable


def test_basic_intern_stable_slots():
    t = InternTable(8)
    cleared: list[int] = []
    s1 = t.intern("a", 0, cleared)
    s2 = t.intern("b", 0, cleared)
    assert s1 != s2
    assert t.intern("a", 0, cleared) == s1
    assert t.hits == 1 and t.misses == 2
    assert not cleared


def test_lru_eviction_order_and_unexpired_metric():
    """Oldest (least recently used) evicted first; unexpired evictions
    counted (reference: lrucache.go:148-159)."""
    import numpy as np

    t = InternTable(2)
    cleared: list[int] = []
    sa = t.intern("a", 0, cleared)
    sb = t.intern("b", 0, cleared)
    # Touch "a" so "b" becomes LRU; mark b unexpired.
    t.intern("a", 0, cleared)
    t.set_expiry(np.array([sb]), np.array([10_000]))
    sc = t.intern("c", 5_000, cleared)
    assert sc == sb  # b evicted, slot reused
    assert cleared == [sb]
    assert t.evictions == 1
    assert t.unexpired_evictions == 1
    # "a" survived
    assert t.intern("a", 0, cleared) == sa
    assert len(t) == 2


def test_remove_and_release():
    import numpy as np

    t = InternTable(4)
    cleared: list[int] = []
    s = t.intern("x", 0, cleared)
    assert t.remove("x") == s
    assert t.remove("x") is None
    s2 = t.intern("y", 0, cleared)
    t.release_slots(np.array([s2]))
    assert len(t) == 0

"""Step pump failure paths (core/pump.py): a dispatch exception must
fail every swapped-out ticket closed (no fetch() hangs or AttributeError
masking), and the queue keeps working afterwards."""

import numpy as np
import pytest

from gubernator_tpu.core.engine import DecisionEngine


def _cols(n, start=0):
    return dict(
        algo=np.zeros(n, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.ones(n, dtype=np.int64),
        limit=np.full(n, 1000, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
    )


def test_flush_exception_fails_tickets_closed():
    eng = DecisionEngine(capacity=2048)
    if eng._pump is None:
        pytest.skip("pump unavailable")
    p1 = eng.apply_columnar([b"a%d" % i for i in range(10)], **_cols(10),
                            want_async=True)
    p2 = eng.apply_columnar([b"b%d" % i for i in range(10)], **_cols(10),
                            want_async=True)

    boom = RuntimeError("injected dispatch failure")
    orig = eng._pump._flush_group

    def failing(group):
        raise boom

    eng._pump._flush_group = failing
    with pytest.raises(RuntimeError, match="injected"):
        with eng._lock:
            eng._pump.flush_locked()
    eng._pump._flush_group = orig

    # Both queued batches fail closed with the REAL error, not an
    # AttributeError on group=None.
    for p in (p1, p2):
        with pytest.raises(RuntimeError, match="injected"):
            p.get()

    # The pump (and engine) keep serving after the failure.
    out = eng.apply_columnar([b"c%d" % i for i in range(10)], **_cols(10))
    assert (np.asarray(out[2]) == 999).all()


def test_multi_scan_matches_sequential_singles():
    """The fused lax.scan multi-round program (the TPU dispatch path,
    bypassed on CPU serving) must be bit-equal to sequentially applied
    single steps — pinned here directly at one controlled shape."""
    import jax.numpy as jnp

    from gubernator_tpu.ops.bucket_kernel import (
        fused_step,
        make_state,
        multi_fused_step,
        pack_batch_host,
        unpack_out_host,
    )

    cap, width, rounds = 512, 64, 4
    rng = np.random.default_rng(3)

    def buf(r):
        slots = np.sort(
            rng.choice(cap, width, replace=False)
        ).astype(np.int32)
        return pack_batch_host(
            width, 1_000_000 + r, cap, slots,
            np.zeros(width, dtype=np.int64),
            np.zeros(width, dtype=np.int64),
            np.ones(width, dtype=np.int64),
            np.full(width, 100, dtype=np.int64),
            np.full(width, 60_000, dtype=np.int64),
            np.zeros(width, dtype=np.int64),
            np.zeros(width, dtype=np.int64),
            np.zeros(width, dtype=np.int64),
        )

    bufs = [buf(r) for r in range(rounds)]

    s1 = make_state(cap)
    outs_seq = []
    for b in bufs:
        s1, pout = fused_step(s1, jnp.asarray(b))
        outs_seq.append(np.asarray(pout))

    s2 = make_state(cap)
    s2, pouts = multi_fused_step(s2, jnp.asarray(np.stack(bufs)))
    pouts = np.asarray(pouts)

    for r in range(rounds):
        for seq_col, scan_col in zip(
            unpack_out_host(outs_seq[r], width),
            unpack_out_host(pouts[r], width),
        ):
            np.testing.assert_array_equal(seq_col, scan_col)
    # Final states agree too.
    for a, b in zip(s1, s2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_multi_uniform_scan_matches_sequential_singles():
    """Same pin for the UNIFORM scan program (the narrow-format TPU
    dispatch path, bypassed on CPU serving)."""
    import jax.numpy as jnp

    from gubernator_tpu.ops.bucket_kernel import (
        make_state,
        multi_uniform_step,
        pack_uniform_host,
        uniform_step,
        unpack_uniform_out_host,
    )

    cap, width, rounds = 512, 64, 4
    rng = np.random.default_rng(9)
    now0 = 2_000_000

    def buf(r):
        slots = np.sort(
            rng.choice(cap, width, replace=False)
        ).astype(np.int32)
        return pack_uniform_host(
            width, now0 + r, cap, slots,
            algo=r % 2, behavior=0, hits=1, limit=100,
            duration=60_000, burst=0,
        )

    bufs = [buf(r) for r in range(rounds)]

    s1 = make_state(cap)
    outs_seq = []
    for b in bufs:
        s1, pout = uniform_step(s1, jnp.asarray(b))
        outs_seq.append(np.asarray(pout))

    s2 = make_state(cap)
    s2, pouts = multi_uniform_step(s2, jnp.asarray(np.stack(bufs)))
    pouts = np.asarray(pouts)

    for r in range(rounds):
        for seq_col, scan_col in zip(
            unpack_uniform_out_host(outs_seq[r], width, now0 + r),
            unpack_uniform_out_host(pouts[r], width, now0 + r),
        ):
            np.testing.assert_array_equal(seq_col, scan_col)
    for a, b in zip(s1, s2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grouped_scan_dispatch_forced_on_cpu(monkeypatch):
    """GUBER_PUMP_SCAN=1 exercises the grouped scan dispatch path end
    to end on CPU: pow2 noop padding, shared group, per-ticket rows."""
    monkeypatch.setenv("GUBER_PUMP_SCAN", "1")
    eng = DecisionEngine(capacity=2048)
    if eng._pump is None:
        pytest.skip("pump unavailable")
    assert eng._pump._scan_ok
    ps = [
        eng.apply_columnar(
            [b"g%d_%d" % (r, i) for i in range(20)], **_cols(20),
            want_async=True,
        )
        for r in range(3)  # 3 rounds → padded to a 4-scan
    ]
    for p in ps:
        st, lim, rem, rst = p.get()
        assert (np.asarray(rem) == 999).all()
    assert eng._pump.fused_rounds == 3

"""Step pump failure paths (core/pump.py): a dispatch exception must
fail every swapped-out ticket closed (no fetch() hangs or AttributeError
masking), and the queue keeps working afterwards."""

import numpy as np
import pytest

from gubernator_tpu.core.engine import DecisionEngine


def _cols(n, start=0):
    return dict(
        algo=np.zeros(n, dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.ones(n, dtype=np.int64),
        limit=np.full(n, 1000, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=np.zeros(n, dtype=np.int64),
    )


def test_flush_exception_fails_tickets_closed():
    eng = DecisionEngine(capacity=2048)
    if eng._pump is None:
        pytest.skip("pump unavailable")
    p1 = eng.apply_columnar([b"a%d" % i for i in range(10)], **_cols(10),
                            want_async=True)
    p2 = eng.apply_columnar([b"b%d" % i for i in range(10)], **_cols(10),
                            want_async=True)

    boom = RuntimeError("injected dispatch failure")
    orig = eng._pump._flush_group

    def failing(group):
        raise boom

    eng._pump._flush_group = failing
    with pytest.raises(RuntimeError, match="injected"):
        with eng._lock:
            eng._pump.flush_locked()
    eng._pump._flush_group = orig

    # Both queued batches fail closed with the REAL error, not an
    # AttributeError on group=None.
    for p in (p1, p2):
        with pytest.raises(RuntimeError, match="injected"):
            p.get()

    # The pump (and engine) keep serving after the failure.
    out = eng.apply_columnar([b"c%d" % i for i in range(10)], **_cols(10))
    assert (np.asarray(out[2]) == 999).all()

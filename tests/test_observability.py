"""Observability: gRPC stats metrics, no-op tracing, metric catalog."""

import urllib.request

from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster.harness import ClusterHarness
from gubernator_tpu.types import RateLimitReq
from gubernator_tpu.utils.tracing import span


def test_span_is_noop_without_init():
    with span("anything", attr=1) as s:
        assert s is None


def test_grpc_stats_and_metric_catalog():
    h = ClusterHarness().start(1)
    try:
        with V1Client(h.peer_at(0).grpc_address) as c:
            c.get_rate_limits(
                [RateLimitReq(name="obs", unique_key="k", hits=1, limit=5, duration=60_000)],
                timeout=10,
            )
            c.health_check(timeout=10)
        body = urllib.request.urlopen(
            f"http://{h.daemon_at(0).http_address}/metrics", timeout=5
        ).read().decode()
        # gRPC request counters per method (reference: grpc_stats.go).
        assert 'gubernator_grpc_request_counts_total{failed="0",method="/pb.gubernator.V1/GetRateLimits"}' in body
        assert "gubernator_grpc_request_duration" in body
        # Engine/service series (reference: prometheus.md:17-36).
        for name in (
            "gubernator_check_counter",
            "gubernator_over_limit_counter",
            "gubernator_check_error_counter",
            "gubernator_getratelimit_counter",
            "gubernator_cache_size",
            "gubernator_engine_batches",
            "gubernator_queue_length",
            "gubernator_global_queue_length",
            "gubernator_batch_send_duration",
            "gubernator_global_send_duration",
            "gubernator_broadcast_duration",
            "gubernator_engine_round_duration",
        ):
            assert name in body, name
        # Round-duration summary must move under load (the request
        # above ran at least one device round).
        assert _sample(body, "gubernator_engine_round_duration_count") >= 1
        assert _sample(body, "gubernator_engine_round_duration_sum") > 0
    finally:
        h.stop()


def _sample(body: str, series: str) -> float:
    for line in body.splitlines():
        if line.startswith(series + " ") or line.startswith(series + "{"):
            return float(line.rsplit(" ", 1)[1])
    raise AssertionError(f"series {series} not found")


def test_process_collectors_flagged(frozen_clock):
    """GUBER_METRIC_FLAGS equivalent: os/python collectors appear only
    when flagged (reference: flags.go:19-57, daemon.go:251-263)."""
    from prometheus_client import generate_latest

    from gubernator_tpu.cluster.harness import cluster_behaviors
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        behaviors=cluster_behaviors(),
        cache_size=512,
        device_count=1,
        sweep_interval=0.0,
        metric_flags=["os", "python"],
    )
    d = spawn_daemon(conf, clock=frozen_clock)
    try:
        body = generate_latest(d.registry).decode()
        assert "process_resident_memory_bytes" in body
        assert "process_cpu_seconds_total" in body
        assert "python_gc_collections_total" in body
        assert "python_info" in body
        assert _sample(body, "process_resident_memory_bytes") > 0
    finally:
        d.close()


def test_global_series_move_under_load():
    """The GLOBAL windows' queue/duration series move when GLOBAL
    traffic flows (metrics-as-oracle, functional_test.go:843-867)."""
    import time

    from gubernator_tpu.types import Behavior

    h = ClusterHarness().start(2)
    try:
        inst = h.daemon_at(0).instance

        def g(i):
            return RateLimitReq(
                name="obsglobal", unique_key=f"{i}k", hits=1, limit=100,
                duration=60_000, behavior=Behavior.GLOBAL,
            )

        # Prefix-varied keys: FNV-1 does not avalanche trailing-byte
        # differences, so "k{i}"-style names would collapse into one
        # ring gap (see hash_ring.py docstring); the harness verifies
        # routing health at start, so a short scan suffices.
        remote = [
            g(i)
            for i in range(2000)
            if not inst.get_peer(g(i).hash_key()).info.is_owner
        ][:5]
        assert remote
        inst.get_rate_limits(remote)
        # Generous deadline: the async windows run on 1 shared core and
        # the full suite loads it.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            body = urllib.request.urlopen(
                f"http://{h.daemon_at(0).http_address}/metrics", timeout=5
            ).read().decode()
            if _sample(body, "gubernator_global_send_duration_count") >= 1:
                break
            time.sleep(0.05)
        assert _sample(body, "gubernator_global_send_duration_count") >= 1
        assert _sample(body, "gubernator_global_send_duration_sum") > 0
    finally:
        h.stop()


def test_log_level_and_format_env(capsys):
    """GUBER_LOG_LEVEL / GUBER_LOG_FORMAT drive the logging layer
    (reference: config.go:255-280)."""
    import json as _json
    import logging
    import os

    from gubernator_tpu.utils.logging_setup import configure_logging

    os.environ["GUBER_LOG_FORMAT"] = "json"
    os.environ["GUBER_LOG_LEVEL"] = "warn"
    try:
        configure_logging()
        log = logging.getLogger("obs.test")
        log.info("hidden")
        log.warning("shown %d", 7)
        err = capsys.readouterr().err
        lines = [l for l in err.strip().splitlines() if l]
        assert len(lines) == 1
        rec = _json.loads(lines[0])
        assert rec["level"] == "warning" and rec["msg"] == "shown 7"
        assert rec["logger"] == "obs.test"
    finally:
        os.environ.pop("GUBER_LOG_FORMAT")
        os.environ.pop("GUBER_LOG_LEVEL")
        logging.getLogger().handlers[:] = []

"""Observability: gRPC stats metrics, no-op tracing, metric catalog."""

import urllib.request

from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster.harness import ClusterHarness
from gubernator_tpu.types import RateLimitReq
from gubernator_tpu.utils.tracing import span


def test_span_is_noop_without_init():
    with span("anything", attr=1) as s:
        assert s is None


def test_grpc_stats_and_metric_catalog():
    h = ClusterHarness().start(1)
    try:
        with V1Client(h.peer_at(0).grpc_address) as c:
            c.get_rate_limits(
                [RateLimitReq(name="obs", unique_key="k", hits=1, limit=5, duration=60_000)],
                timeout=10,
            )
            c.health_check(timeout=10)
        body = urllib.request.urlopen(
            f"http://{h.daemon_at(0).http_address}/metrics", timeout=5
        ).read().decode()
        # gRPC request counters per method (reference: grpc_stats.go).
        assert 'gubernator_grpc_request_counts_total{failed="0",method="/pb.gubernator.V1/GetRateLimits"}' in body
        assert "gubernator_grpc_request_duration" in body
        # Engine/service series (reference: prometheus.md:17-36).
        for name in (
            "gubernator_check_counter",
            "gubernator_over_limit_counter",
            "gubernator_check_error_counter",
            "gubernator_getratelimit_counter",
            "gubernator_cache_size",
            "gubernator_engine_batches",
        ):
            assert name in body, name
    finally:
        h.stop()

"""Sharded engine tests on the 8-device virtual CPU mesh.

Multi-chip semantics must equal single-device semantics: same
conformance behavior, keys spread across shards, psum'd over-limit
aggregation."""

from __future__ import annotations

import jax
import pytest

from gubernator_tpu import Algorithm, Behavior, RateLimitReq, Status
from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.parallel.mesh import make_mesh
from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine

SECOND = 1000


@pytest.fixture
def sharded(frozen_clock: Clock) -> ShardedDecisionEngine:
    assert len(jax.devices()) == 8
    return ShardedDecisionEngine(shard_capacity=256, clock=frozen_clock)


def test_mesh_has_8_devices():
    mesh = make_mesh()
    assert mesh.shape["keys"] == 8


def test_sharded_matches_single_device(sharded, frozen_clock):
    """Same request stream → same responses as the 1-device engine."""
    single = DecisionEngine(capacity=2048, clock=frozen_clock)
    import random

    rng = random.Random(7)
    keys = [f"acct:{i}" for i in range(64)]
    for step in range(30):
        reqs = [
            RateLimitReq(
                name="par",
                unique_key=rng.choice(keys),
                hits=rng.choice([0, 1, 1, 2, 5]),
                limit=rng.choice([5, 10, 100]),
                duration=rng.choice([1000, 9000, 30000]),
                algorithm=rng.choice(
                    [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]
                ),
            )
            for _ in range(rng.randint(1, 12))
        ]
        got = sharded.get_rate_limits(reqs)
        want = single.get_rate_limits(reqs)
        for g, w, r in zip(got, want, reqs):
            assert (int(g.status), g.limit, g.remaining, g.reset_time) == (
                int(w.status),
                w.limit,
                w.remaining,
                w.reset_time,
            ), f"step={step} req={r}"
        frozen_clock.advance(ms=rng.choice([0, 100, 1000, 5000]))


def test_keys_spread_across_shards(sharded):
    touched = set()
    for i in range(200):
        sharded.shard_of(f"key:{i}")
        touched.add(sharded.shard_of(f"key:{i}"))
    assert len(touched) == 8  # fnv1a spreads over every shard


def test_over_limit_psum_aggregation(sharded, frozen_clock):
    """The step's psum'd over-limit counter sums across shards."""
    reqs = [
        RateLimitReq(
            name="over", unique_key=f"k{i}", hits=10, limit=5, duration=9000
        )
        for i in range(32)
    ]
    resps = sharded.get_rate_limits(reqs)
    assert all(r.status == Status.OVER_LIMIT for r in resps)
    assert sharded.over_limit_total == 32


def test_duplicate_keys_sequential_on_shard(sharded, frozen_clock):
    req = dict(name="dup", unique_key="k", hits=1, limit=3, duration=9000)
    resps = sharded.get_rate_limits([RateLimitReq(**req) for _ in range(5)])
    assert [r.remaining for r in resps] == [2, 1, 0, 0, 0]


def test_sharded_sweep_reclaims_expired(sharded, frozen_clock):
    reqs = [
        RateLimitReq(name="sw", unique_key=f"k{i}", hits=1, limit=5, duration=SECOND)
        for i in range(32)
    ]
    sharded.get_rate_limits(reqs)
    assert sharded.cache_size() == 32
    assert sharded.sweep() == 0  # nothing expired yet
    frozen_clock.advance(ms=2 * SECOND)
    assert sharded.sweep() == 32
    assert sharded.cache_size() == 0


def test_eviction_and_reuse_within_one_batch_sharded(frozen_clock):
    eng = ShardedDecisionEngine(shard_capacity=1, clock=frozen_clock)
    reqs = [
        RateLimitReq(name="e", unique_key=f"k{i}", hits=1, limit=10, duration=60_000)
        for i in range(20)
    ]
    resps = eng.get_rate_limits(reqs)
    assert [r.remaining for r in resps] == [9] * 20

"""Persistence tests: write-through Store, bulk Loader, checkpoints.

Mirrors the reference's store tests (reference: store_test.go —
TestLoader:76 startup/shutdown persistence, TestStore:127 read-through
and write-through including expiry) against the TPU engine.
"""

import os

import pytest

from gubernator_tpu.checkpoint import NpzFileLoader
from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.store import (
    CacheItem,
    LeakyBucketItem,
    MemoryLoader,
    MemoryStore,
    TokenBucketItem,
)
from gubernator_tpu.types import Algorithm, Behavior, RateLimitReq, Status


def req(key="k1", hits=1, limit=10, duration=60_000, **kw):
    return RateLimitReq(
        name="test_store", unique_key=key, hits=hits, limit=limit,
        duration=duration, **kw,
    )


@pytest.fixture(params=["single", "sharded"])
def store_engine(request, frozen_clock):
    """Both engines must speak the write-through Store protocol
    (VERDICT r2 item 4; reference: store.go:49-65 works at any
    deployment size)."""

    def build(store):
        if request.param == "single":
            return DecisionEngine(capacity=100, clock=frozen_clock, store=store)
        from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine

        return ShardedDecisionEngine(
            shard_capacity=64, clock=frozen_clock, store=store
        )

    return build


def test_store_write_through(frozen_clock, store_engine):
    store = MemoryStore()
    eng = store_engine(store)
    r = eng.get_rate_limits([req()])[0]
    assert r.remaining == 9
    assert store.on_change_calls == 1
    item = store.data["test_store_k1"]
    assert isinstance(item.value, TokenBucketItem)
    assert item.value.remaining == 9
    assert item.value.limit == 10
    assert item.expire_at == frozen_clock.now_ms() + 60_000
    # Second hit updates the stored value.
    eng.get_rate_limits([req()])
    assert store.data["test_store_k1"].value.remaining == 8


def test_store_read_through_restores_bucket(frozen_clock, store_engine):
    """A new engine with a primed Store continues the persisted bucket
    instead of starting fresh (reference: TestStore read-through)."""
    now = frozen_clock.now_ms()
    store = MemoryStore()
    store.data["test_store_k1"] = CacheItem(
        key="test_store_k1",
        value=TokenBucketItem(
            status=Status.UNDER_LIMIT, limit=10, duration=60_000,
            remaining=3, created_at=now - 1_000,
        ),
        expire_at=now + 59_000,
        algorithm=Algorithm.TOKEN_BUCKET,
    )
    eng = store_engine(store)
    r = eng.get_rate_limits([req()])[0]
    assert store.get_calls == 1
    assert r.remaining == 2  # 3 persisted - 1 hit
    assert r.reset_time == now - 1_000 + 60_000


def test_store_read_through_leaky(frozen_clock, store_engine):
    now = frozen_clock.now_ms()
    store = MemoryStore()
    store.data["test_store_lk"] = CacheItem(
        key="test_store_lk",
        value=LeakyBucketItem(
            limit=10, duration=60_000, remaining=5.0, updated_at=now, burst=10,
        ),
        expire_at=now + 60_000,
        algorithm=Algorithm.LEAKY_BUCKET,
    )
    eng = store_engine(store)
    r = eng.get_rate_limits(
        [req(key="lk", algorithm=Algorithm.LEAKY_BUCKET, burst=10)]
    )[0]
    assert r.remaining == 4


def test_store_remove_on_reset_remaining(frozen_clock, store_engine):
    store = MemoryStore()
    eng = store_engine(store)
    eng.get_rate_limits([req(hits=5)])
    assert store.data["test_store_k1"].value.remaining == 5
    r = eng.get_rate_limits(
        [req(hits=0, behavior=Behavior.RESET_REMAINING)]
    )[0]
    assert store.remove_calls == 1
    assert r.remaining == 10


def test_loader_round_trip(frozen_clock):
    """Save at shutdown, restore at startup, bucket continues.

    reference: store_test.go TestLoader:76.
    """
    eng1 = DecisionEngine(capacity=100, clock=frozen_clock)
    eng1.get_rate_limits(
        [
            req(key="a", hits=4),
            req(key="b", hits=2, algorithm=Algorithm.LEAKY_BUCKET, burst=10),
        ]
    )
    loader = MemoryLoader()
    eng1.save(loader)
    assert loader.save_calls == 1
    assert len(loader.items) == 2

    eng2 = DecisionEngine(capacity=100, clock=frozen_clock)
    assert eng2.load(loader) == 2
    assert eng2.cache_size() == 2
    ra = eng2.get_rate_limits([req(key="a", hits=0)])[0]
    assert ra.remaining == 6  # 10 - 4, continued exactly
    rb = eng2.get_rate_limits(
        [req(key="b", hits=0, algorithm=Algorithm.LEAKY_BUCKET, burst=10)]
    )[0]
    assert rb.remaining == 8


def test_leaky_fraction_survives_loader(frozen_clock):
    """The leaky sub-integer remainder round-trips bit-exactly through
    the Loader (fixed-point words are snapshotted, not the int floor)."""
    eng1 = DecisionEngine(capacity=100, clock=frozen_clock)
    # limit 3 / duration 1000ms → rate 333.33ms per unit; advancing
    # 500ms leaks 1.5 units: fraction lands in the bucket state.
    r = eng1.get_rate_limits(
        [req(key="f", hits=3, limit=3, duration=1000,
             algorithm=Algorithm.LEAKY_BUCKET, burst=3)]
    )[0]
    assert r.remaining == 0
    frozen_clock.advance(ms=500)
    loader = MemoryLoader()
    eng1.save(loader)

    eng2 = DecisionEngine(capacity=100, clock=frozen_clock)
    eng2.load(loader)
    r1 = eng1.get_rate_limits(
        [req(key="f", hits=1, limit=3, duration=1000,
             algorithm=Algorithm.LEAKY_BUCKET, burst=3)]
    )[0]
    r2 = eng2.get_rate_limits(
        [req(key="f", hits=1, limit=3, duration=1000,
             algorithm=Algorithm.LEAKY_BUCKET, burst=3)]
    )[0]
    assert (r1.status, r1.remaining, r1.reset_time) == (
        r2.status, r2.remaining, r2.reset_time,
    )


def test_npz_checkpoint(tmp_path, frozen_clock):
    path = os.fspath(tmp_path / "ckpt.npz")
    eng1 = DecisionEngine(capacity=100, clock=frozen_clock)
    eng1.get_rate_limits([req(key=f"k{i}", hits=i % 5) for i in range(50)])
    ckpt = NpzFileLoader(path)
    eng1.save(ckpt)
    assert os.path.exists(path)

    eng2 = DecisionEngine(capacity=100, clock=frozen_clock)
    assert eng2.load(ckpt) == 50
    r = eng2.get_rate_limits([req(key="k4", hits=0)])[0]
    assert r.remaining == 10 - 4


def test_daemon_periodic_sweep(frozen_clock):
    """The daemon's background sweeper reclaims expired slots."""
    import time

    from gubernator_tpu.cluster.harness import cluster_behaviors
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        behaviors=cluster_behaviors(),
        cache_size=1000,
        device_count=1,
        sweep_interval=0.2,
    )
    d = spawn_daemon(conf, clock=frozen_clock)
    try:
        eng = d.instance.engine
        eng.get_rate_limits(
            [req(key=f"sw{i}", hits=1, duration=1_000) for i in range(20)]
        )
        assert eng.cache_size() == 20
        frozen_clock.advance(ms=2_000)  # all buckets expire
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and eng.cache_size() > 0:
            time.sleep(0.1)
        assert eng.cache_size() == 0
    finally:
        d.close()


def test_sharded_loader_round_trip(frozen_clock):
    """Sharded-engine Loader save/restore continues buckets exactly."""
    from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine

    eng1 = ShardedDecisionEngine(shard_capacity=64, clock=frozen_clock)
    eng1.get_rate_limits(
        [req(key=f"s{i}", hits=i % 4) for i in range(40)]
        + [
            req(key=f"l{i}", hits=2, algorithm=Algorithm.LEAKY_BUCKET, burst=10)
            for i in range(10)
        ]
    )
    loader = MemoryLoader()
    eng1.save(loader)
    assert len(loader.items) == 50

    eng2 = ShardedDecisionEngine(shard_capacity=64, clock=frozen_clock)
    assert eng2.load(loader) == 50
    assert eng2.cache_size() == 50
    r = eng2.get_rate_limits([req(key="s3", hits=0)])[0]
    assert r.remaining == 10 - 3
    rl = eng2.get_rate_limits(
        [req(key="l0", hits=0, algorithm=Algorithm.LEAKY_BUCKET, burst=10)]
    )[0]
    assert rl.remaining == 8


def test_daemon_loader_integration(tmp_path, frozen_clock):
    """Daemon restores at start and persists at close."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from gubernator_tpu.cluster.harness import cluster_behaviors
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.client import V1Client

    path = os.fspath(tmp_path / "daemon.npz")
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        behaviors=cluster_behaviors(),
        cache_size=1000,
        device_count=1,
    )
    d1 = spawn_daemon(conf, clock=frozen_clock, loader=NpzFileLoader(path))
    with V1Client(d1.grpc_address) as c:
        c.get_rate_limits([req(key="persist", hits=7)], timeout=10)
    d1.close()
    assert os.path.exists(path)

    d2 = spawn_daemon(conf, clock=frozen_clock, loader=NpzFileLoader(path))
    try:
        with V1Client(d2.grpc_address) as c:
            r = c.get_rate_limits([req(key="persist", hits=0)], timeout=10)[0]
            assert r.remaining == 3
    finally:
        d2.close()

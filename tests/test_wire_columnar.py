"""The columnar wire fast path must be indistinguishable from the
dataclass path (VERDICT r1 item 2: the served path IS the benched path).

Covers: fast-path hit on a single-node daemon, decline + fallback on
special behaviors / invalid fields / peer-owned keys, duplicate keys in
one wire batch, and cross-checks responses against the dataclass path's
semantics (reference: gubernator.go:197-317).
"""

import pytest

from gubernator_tpu.client import V1Client, random_string
from gubernator_tpu.cluster.harness import ClusterHarness
from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.net.server import _decode_columns
from gubernator_tpu.types import Algorithm, Behavior, RateLimitReq, Status


@pytest.fixture(scope="module")
def single():
    h = ClusterHarness().start(1)
    yield h
    h.stop()


@pytest.fixture(scope="module")
def pair():
    h = ClusterHarness().start(2)
    yield h
    h.stop()


def _req(key, hits=1, limit=5, duration=60_000, algo=Algorithm.TOKEN_BUCKET,
         behavior=0, burst=0):
    return RateLimitReq(
        name="wire", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algo, behavior=behavior, burst=burst,
    )


def test_decode_columns_disqualifiers():
    ok = pb.RateLimitReq(name="a", unique_key="b", hits=1, limit=5, duration=1000)
    assert _decode_columns([ok]) is not None
    for bad in [
        pb.RateLimitReq(name="", unique_key="b", hits=1, limit=5, duration=1000),
        pb.RateLimitReq(name="a", unique_key="", hits=1, limit=5, duration=1000),
        pb.RateLimitReq(
            name="a", unique_key="b", behavior=int(Behavior.GLOBAL), limit=5
        ),
        pb.RateLimitReq(
            name="a", unique_key="b", behavior=int(Behavior.MULTI_REGION), limit=5
        ),
        pb.RateLimitReq(
            name="a",
            unique_key="b",
            behavior=int(Behavior.DURATION_IS_GREGORIAN),
            limit=5,
        ),
    ]:
        assert _decode_columns([ok, bad]) is None
    assert _decode_columns([]) is None


def test_fast_path_token_bucket_sequence(single):
    """Token-bucket drain + over-limit-does-not-consume over the wire."""
    d = single.daemon_at(0)
    local_before = d.instance.counters["local"]
    columnar_before = d.instance.counters["columnar"]
    with V1Client(single.peer_at(0).grpc_address) as c:
        key = random_string(prefix="colfast_")
        for expect_status, expect_remaining in [
            (Status.UNDER_LIMIT, 1),
            (Status.UNDER_LIMIT, 0),
            (Status.OVER_LIMIT, 0),
            (Status.OVER_LIMIT, 0),
        ]:
            r = c.get_rate_limits([_req(key, limit=2)])[0]
            assert r.error == ""
            assert r.status == expect_status
            assert r.remaining == expect_remaining
            assert r.limit == 2
    # The sequence must have been served locally AND via the columnar
    # fast path specifically (the "columnar" counter only moves there).
    assert d.instance.counters["local"] >= local_before + 4
    assert d.instance.counters["columnar"] >= columnar_before + 4


def test_fast_path_duplicate_keys_one_batch(single):
    """Duplicates in one wire batch apply sequentially (round splitting,
    reference semantics: per-worker FIFO gubernator_pool.go:19-37)."""
    with V1Client(single.peer_at(0).grpc_address) as c:
        key = random_string(prefix="coldup_")
        rs = c.get_rate_limits([_req(key, limit=3)] * 5)
        assert [r.status for r in rs] == [
            Status.UNDER_LIMIT,
            Status.UNDER_LIMIT,
            Status.UNDER_LIMIT,
            Status.OVER_LIMIT,
            Status.OVER_LIMIT,
        ]
        assert [r.remaining for r in rs] == [2, 1, 0, 0, 0]


def test_fast_path_mixed_algorithms(single):
    """Token + leaky lanes in one wire batch."""
    with V1Client(single.peer_at(0).grpc_address) as c:
        kt = random_string(prefix="colmix_t_")
        kl = random_string(prefix="colmix_l_")
        rs = c.get_rate_limits(
            [
                _req(kt, limit=10),
                _req(kl, limit=10, algo=Algorithm.LEAKY_BUCKET),
            ]
        )
        assert rs[0].status == Status.UNDER_LIMIT and rs[0].remaining == 9
        assert rs[1].status == Status.UNDER_LIMIT and rs[1].remaining == 9


def test_validation_errors_still_error_in_response(single):
    """Invalid fields decline the fast path; the dataclass path answers
    with error-in-response (reference: gubernator.go:231-243)."""
    with V1Client(single.peer_at(0).grpc_address) as c:
        rs = c.get_rate_limits(
            [
                RateLimitReq(name="", unique_key="x", hits=1, limit=5, duration=1000),
                _req(random_string(prefix="colval_"), limit=5),
            ]
        )
        assert "cannot be empty" in rs[0].error
        assert rs[1].error == "" and rs[1].status == Status.UNDER_LIMIT


def test_hits_zero_status_query(single):
    """Hits=0 must report without consuming (algorithms.go:173-176)."""
    with V1Client(single.peer_at(0).grpc_address) as c:
        key = random_string(prefix="colh0_")
        c.get_rate_limits([_req(key, hits=1, limit=5)])
        r = c.get_rate_limits([_req(key, hits=0, limit=5)])[0]
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 4


def test_forwarding_still_works_with_fast_path(pair):
    """Keys owned by the other node decline the fast path and forward;
    both nodes must agree on the shared counter."""
    d0 = pair.daemon_at(0)
    with V1Client(pair.peer_at(0).grpc_address) as c0:
        # Find a key owned by the other daemon so client 0 must forward.
        # The reference-exact 2-member ring can be lumpy; scan wide.
        for i in range(4096):
            key = f"colfwd_{i}"
            owner = d0.instance.get_peer("wire_" + key)
            if not owner.info.is_owner:
                break
        else:
            pytest.skip("no remote-owned key found in 4096 tries")
        r0 = c0.get_rate_limits([_req(key, limit=3)])[0]
        assert r0.error == ""
        assert r0.metadata.get("owner") == owner.info.grpc_address
        # Second hit on the same bucket via the owner directly.
        with V1Client(owner.info.grpc_address) as c1:
            r1 = c1.get_rate_limits([_req(key, limit=3)])[0]
        assert r1.remaining == 1


def test_native_wire_path_sharded_engine(frozen_clock):
    """The native codec path (raw bytes → packed schedule with
    codec-precomputed route hashes → packed mesh step → C encode) on a
    multi-device daemon agrees with the dataclass semantics."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 virtual devices")
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.cluster.harness import cluster_behaviors

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        behaviors=cluster_behaviors(),
        cache_size=4096,
        peer_discovery_type="none",
        device_count=4,
        sweep_interval=0.0,
    )
    d = spawn_daemon(conf, clock=frozen_clock)
    try:
        with V1Client(d.grpc_address) as c:
            rs = c.get_rate_limits(
                [_req(f"shw{i}", hits=2, limit=9) for i in range(50)]
                + [_req("shw0", hits=1, limit=9)],  # duplicate → round 1
                timeout=30,
            )
            assert all(r.error == "" for r in rs)
            assert all(r.remaining == 7 for r in rs[:50])
            assert rs[50].remaining == 6  # sequential after the duplicate
            # Second wire batch continues the buckets.
            rs = c.get_rate_limits(
                [_req(f"shw{i}", hits=0, limit=9) for i in range(50)],
                timeout=30,
            )
            assert [r.remaining for r in rs[:1]] == [6]
            assert all(r.remaining == 7 for r in rs[1:])
        # The native path actually served (counter moved).
        from gubernator_tpu.net import wire_codec

        if wire_codec.load() is not None:
            assert d.instance.counters["columnar"] >= 100
    finally:
        d.close()


def test_wire_window_group_commit(frozen_clock):
    """Concurrent wire RPCs inside the group-commit window share one
    engine dispatch and still get exact per-caller slices."""
    import threading

    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.cluster.harness import cluster_behaviors
    from gubernator_tpu.net import wire_codec

    if wire_codec.load() is None:
        pytest.skip("native codec unavailable")
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        behaviors=cluster_behaviors(),
        cache_size=4096,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
        local_batch_wait=0.005,  # wide window: threads surely share it
    )
    d = spawn_daemon(conf, clock=frozen_clock)
    try:
        n_threads = 8
        # The window is load-ADAPTIVE: a cold window fires immediately
        # (no grouping).  Prime its occupancy EWMA as if the herd had
        # been running, so the first windows sleep the cap and the
        # burst below deterministically shares them.
        d.instance._wire_window._ewma_rpcs = float(n_threads)
        results = [None] * n_threads
        barrier = threading.Barrier(n_threads)

        def worker(tid):
            barrier.wait()
            with V1Client(d.grpc_address) as c:
                results[tid] = c.get_rate_limits(
                    [
                        _req(f"win{tid}", hits=2, limit=50),
                        _req("win_shared", hits=1, limit=1000),
                    ],
                    timeout=30,
                )

        threads = [
            threading.Thread(target=worker, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        shared_rems = sorted(
            r[1].remaining for r in results if r is not None
        )
        for tid, r in enumerate(results):
            assert r is not None and r[0].error == ""
            assert r[0].remaining == 48  # private key: own hits only
        # Shared key consumed exactly once per thread, sequentially.
        assert shared_rems == list(range(1000 - n_threads, 1000))
        ww = d.instance._wire_window
        assert ww is not None and ww.grouped_batches >= 2
    finally:
        d.close()

"""Native h2 fast front: protocol correctness, real grpc-python client
compatibility, scope enforcement (UNIMPLEMENTED for non-columnar
traffic), and the cluster ownership gate."""

import struct

import pytest

from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.core import h2_client
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.net import h2_fast
from gubernator_tpu.net.grpc_service import V1Stub, dial
from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.types import Behavior


@pytest.fixture
def daemon():
    if h2_fast.load() is None:
        pytest.skip("native h2 server unavailable")
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=1 << 12,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
        h2_fast_address="127.0.0.1:0",
        h2_fast_window=0.001,
    )
    d = spawn_daemon(conf)
    yield d
    d.close()


def test_fast_front_serves_real_grpc_client(daemon):
    """A stock grpc-python client must work against the front — the
    single-method port design depends on ignoring request header
    blocks, not on a cooperative client."""
    stub = V1Stub(dial(daemon.h2_fast_address))
    for expect in (4, 3, 2):
        got = stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="f", unique_key="k", hits=1, limit=5,
                        duration=60_000,
                    )
                ]
            )
        )
        assert got.responses[0].remaining == expect
    # State is shared with the full listener: the same bucket.
    full = V1Stub(dial(daemon.grpc_address))
    got = full.GetRateLimits(
        pb.GetRateLimitsReq(
            requests=[
                pb.RateLimitReq(
                    name="f", unique_key="k", hits=1, limit=5,
                    duration=60_000,
                )
            ]
        )
    )
    assert got.responses[0].remaining == 1


def test_fast_front_multi_item_and_native_client(daemon):
    payload = pb.GetRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name="m", unique_key=f"{i}k", hits=1, limit=100,
                duration=60_000,
            )
            for i in range(7)
        ]
    ).SerializeToString()
    res = h2_client.bench_unary(
        daemon.h2_fast_address, "/pb.gubernator.V1/GetRateLimits",
        payload, 0.4, 2,
    )
    assert res is not None
    rpcs, errors, lats, frame, connected = res
    assert errors == 0 and rpcs > 0
    (ln,) = struct.unpack(">I", frame[1:5])
    resp = pb.GetRateLimitsResp.FromString(frame[5 : 5 + ln])
    assert len(resp.responses) == 7
    assert all(0 <= r.remaining < 100 for r in resp.responses)


def test_fast_front_declines_non_columnar(daemon):
    """Behaviors outside the front's scope must answer UNIMPLEMENTED,
    never a wrong decision (GLOBAL et al belong on the full listener)."""
    import grpc

    stub = V1Stub(dial(daemon.h2_fast_address))
    with pytest.raises(grpc.RpcError) as err:
        stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="g", unique_key="k", hits=1, limit=5,
                        duration=60_000,
                        behavior=int(Behavior.GLOBAL),
                    )
                ]
            )
        )
    assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_fast_front_sharded_engine():
    """The front must route through the sharded engine's columnar path
    (codec hashes as shard routes) when the daemon runs multi-device."""
    if h2_fast.load() is None:
        pytest.skip("native h2 server unavailable")
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=1 << 12,
        peer_discovery_type="none",
        device_count=8,
        sweep_interval=0.0,
        h2_fast_address="127.0.0.1:0",
        h2_fast_window=0.001,
    )
    d = spawn_daemon(conf)
    try:
        assert hasattr(d.instance.engine, "tables"), "expected sharded"
        stub = V1Stub(dial(d.h2_fast_address))
        got = stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="sh", unique_key=f"{i}k", hits=1, limit=5,
                        duration=60_000,
                    )
                    for i in range(20)
                ]
            )
        )
        assert [r.remaining for r in got.responses] == [4] * 20
    finally:
        d.close()


def test_fast_front_window_isolation(daemon):
    """One out-of-scope RPC in a window must not fail its window-mates
    (the per-RPC fallback in H2FastFront._window)."""
    import ctypes

    import numpy as np

    front = daemon.h2_fast
    plain = pb.GetRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name="iso", unique_key="a", hits=1, limit=9,
                duration=60_000,
            )
        ]
    ).SerializeToString()
    glob = pb.GetRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name="iso", unique_key="b", hits=1, limit=9,
                duration=60_000, behavior=int(Behavior.GLOBAL),
            )
        ]
    ).SerializeToString()
    concat = plain + glob
    buf = ctypes.create_string_buffer(concat, len(concat))
    counts = np.array([1, 1], dtype=np.int64)
    lens = np.array([len(plain), len(glob)], dtype=np.int64)
    cols = np.zeros(8, dtype=np.int64)
    status = np.zeros(2, dtype=np.int64)
    rc = front._window(
        ctypes.addressof(buf), len(concat),
        counts.ctypes.data, lens.ctypes.data, 2, 2,
        cols.ctypes.data, status.ctypes.data,
    )
    assert rc == 0
    assert status.tolist() == [0, 12]  # plain served, GLOBAL declined
    assert cols[2 * 2 + 0] == 8  # remaining column, first lane


def test_fast_front_ownership_gate():
    """In a cluster, the front must decline peer-owned keys rather
    than answer them locally."""
    if h2_fast.load() is None:
        pytest.skip("native h2 server unavailable")
    import grpc

    from gubernator_tpu.cluster.harness import ClusterHarness
    from gubernator_tpu.net.h2_fast import H2FastFront

    h = ClusterHarness().start(2, cache_size=1 << 12)
    try:
        d0 = h.daemons[0]
        front = H2FastFront(d0.instance, window_s=0.001)
        try:
            stub = V1Stub(dial(front.address))
            # Find a key owned by the OTHER node.  Candidate keys keep
            # ≥3 constant bytes AFTER the varying digits: FNV-1's final
            # op is an xor, so a byte changed k positions before the
            # end only moves the hash by ~Δ·prime^k — with k=1 all 200
            # candidates cluster into one ring gap and can land on one
            # node (the documented hash_ring.py distribution caveat).
            remote_key = None
            for i in range(200):
                key = f"{i}rem"
                owner = d0.instance.local_picker.get(f"own_{key}")
                if owner.info.grpc_address != d0.grpc_address:
                    remote_key = key
                    break
            assert remote_key is not None
            with pytest.raises(grpc.RpcError) as err:
                stub.GetRateLimits(
                    pb.GetRateLimitsReq(
                        requests=[
                            pb.RateLimitReq(
                                name="own", unique_key=remote_key,
                                hits=1, limit=5, duration=60_000,
                            )
                        ]
                    )
                )
            assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED
        finally:
            front.close()
    finally:
        h.stop()


def _h2_frames(sock, deadline):
    """Yield (type, flags, stream, payload) frames until the socket
    times out or closes."""
    import socket as _socket
    import time

    buf = b""
    while True:
        while len(buf) < 9:
            sock.settimeout(max(0.05, deadline - time.monotonic()))
            try:
                chunk = sock.recv(65536)
            except (_socket.timeout, TimeoutError):
                return
            if not chunk:
                return
            buf += chunk
        flen = (buf[0] << 16) | (buf[1] << 8) | buf[2]
        ftype, flags = buf[3], buf[4]
        stream = struct.unpack(">I", buf[5:9])[0] & 0x7FFFFFFF
        while len(buf) < 9 + flen:
            sock.settimeout(max(0.05, deadline - time.monotonic()))
            try:
                chunk = sock.recv(65536)
            except (_socket.timeout, TimeoutError):
                return
            if not chunk:
                return
            buf += chunk
        yield ftype, flags, stream, buf[9 : 9 + flen]
        buf = buf[9 + flen :]


def test_fast_front_honors_send_flow_control(daemon):
    """RFC 9113 send-side flow control (ADVICE r5 low #2): when the
    peer advertises a tiny INITIAL_WINDOW_SIZE, response DATA must stop
    at the window and resume only on WINDOW_UPDATE — before the fix the
    front wrote the whole response regardless of the peer's windows."""
    import socket
    import time

    host, port = daemon.h2_fast_address.rsplit(":", 1)
    n_items = 120
    body = pb.GetRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name="fc", unique_key=f"{i}k", hits=1, limit=1000,
                duration=60_000,
            )
            for i in range(n_items)
        ]
    ).SerializeToString()
    window = 32  # far below the response size

    sock = socket.create_connection((host, int(port)), timeout=5)
    try:
        sock.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
        # SETTINGS: INITIAL_WINDOW_SIZE = 32.
        sock.sendall(
            struct.pack(">I", 6)[1:] + bytes([4, 0])
            + struct.pack(">I", 0)            # stream 0
            + struct.pack(">H", 4) + struct.pack(">I", window)
        )
        # HEADERS (empty block — the port is the route), then the
        # grpc-framed request body with END_STREAM.
        sock.sendall(struct.pack(">I", 0)[1:] + bytes([1, 4]) + struct.pack(">I", 1))
        grpc_frame = b"\x00" + struct.pack(">I", len(body)) + body
        sock.sendall(
            struct.pack(">I", len(grpc_frame))[1:] + bytes([0, 1])
            + struct.pack(">I", 1) + grpc_frame
        )
        # Phase 1: the server must send HEADERS and AT MOST `window`
        # bytes of DATA, then stall.
        data = b""
        saw_headers = False
        saw_trailers = False
        deadline = time.monotonic() + 3.0
        for ftype, flags, stream, payload in _h2_frames(sock, deadline):
            if stream != 1:
                continue
            if ftype == 1:  # HEADERS
                if not saw_headers:
                    saw_headers = True
                elif flags & 0x1:
                    saw_trailers = True
            elif ftype == 0:
                data += payload
        assert saw_headers
        assert len(data) <= window, (
            f"server sent {len(data)} DATA bytes into a {window}-byte "
            "window"
        )
        assert not saw_trailers
        # Phase 2: open the stream window; the rest must arrive.
        sock.sendall(
            struct.pack(">I", 4)[1:] + bytes([8, 0])
            + struct.pack(">I", 1) + struct.pack(">I", 1 << 20)
        )
        deadline = time.monotonic() + 5.0
        for ftype, flags, stream, payload in _h2_frames(sock, deadline):
            if stream != 1:
                continue
            if ftype == 0:
                data += payload
            elif ftype == 1 and flags & 0x1:
                saw_trailers = True
                break
        assert saw_trailers
        assert data[0] == 0
        (ln,) = struct.unpack(">I", data[1:5])
        resp = pb.GetRateLimitsResp.FromString(data[5 : 5 + ln])
        assert len(resp.responses) == n_items
        assert all(r.remaining == 999 for r in resp.responses)
    finally:
        sock.close()


def test_fast_front_banks_early_window_credit(daemon):
    """WINDOW_UPDATE arriving BEFORE the response is queued must not
    be dropped: with a zero initial window the response would
    otherwise stall forever even though the client already granted
    credit."""
    import socket
    import time

    host, port = daemon.h2_fast_address.rsplit(":", 1)
    body = pb.GetRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name="ec", unique_key=f"{i}k", hits=1, limit=10,
                duration=60_000,
            )
            for i in range(40)
        ]
    ).SerializeToString()
    sock = socket.create_connection((host, int(port)), timeout=5)
    try:
        sock.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
        # SETTINGS: INITIAL_WINDOW_SIZE = 0 — nothing moves on credit
        # the server forgets.
        sock.sendall(
            struct.pack(">I", 6)[1:] + bytes([4, 0])
            + struct.pack(">I", 0)
            + struct.pack(">H", 4) + struct.pack(">I", 0)
        )
        sock.sendall(
            struct.pack(">I", 0)[1:] + bytes([1, 4]) + struct.pack(">I", 1)
        )
        grpc_frame = b"\x00" + struct.pack(">I", len(body)) + body
        sock.sendall(
            struct.pack(">I", len(grpc_frame))[1:] + bytes([0, 1])
            + struct.pack(">I", 1) + grpc_frame
        )
        # Credit granted IMMEDIATELY — likely before the window fires.
        sock.sendall(
            struct.pack(">I", 4)[1:] + bytes([8, 0])
            + struct.pack(">I", 1) + struct.pack(">I", 1 << 20)
        )
        data = b""
        saw_trailers = False
        deadline = time.monotonic() + 5.0
        for ftype, flags, stream, payload in _h2_frames(sock, deadline):
            if stream != 1:
                continue
            if ftype == 0:
                data += payload
            elif ftype == 1 and flags & 0x1:
                saw_trailers = True
                break
        assert saw_trailers, "response stalled: early credit was dropped"
        (ln,) = struct.unpack(">I", data[1:5])
        resp = pb.GetRateLimitsResp.FromString(data[5 : 5 + ln])
        assert len(resp.responses) == 40
    finally:
        sock.close()


def test_fast_front_zero_item_request(daemon):
    """A zero-item GetRateLimitsReq must answer empty-OK, not
    INTERNAL(13): the C side passes a NULL out_ptr for an empty
    window and the Python entry must not dereference it (ADVICE r5)."""
    stub = V1Stub(dial(daemon.h2_fast_address))
    got = stub.GetRateLimits(pb.GetRateLimitsReq(), timeout=10)
    assert len(got.responses) == 0


def test_fast_front_oversized_rpc_not_starved(daemon):
    """dispatch_loop starvation (ADVICE r5, medium): an RPC with more
    items than max_batch must still be admitted and served — before
    the fix it sat at the queue head forever, busy-spinning the
    dispatch thread and starving every later RPC."""
    from gubernator_tpu.net.h2_fast import H2FastFront

    front = H2FastFront(
        daemon.instance, window_s=0.001, max_batch=4, flush_items=4
    )
    try:
        stub = V1Stub(dial(front.address))
        got = stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="big", unique_key=f"{i}k", hits=1,
                        limit=100, duration=60_000,
                    )
                    for i in range(9)  # > max_batch
                ]
            ),
            timeout=15,
        )
        assert len(got.responses) == 9
        assert all(r.remaining == 99 for r in got.responses)
        # And later, smaller RPCs are not starved behind it.
        got = stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="big", unique_key="0k", hits=1, limit=100,
                        duration=60_000,
                    )
                ]
            ),
            timeout=15,
        )
        assert got.responses[0].remaining == 98
    finally:
        front.close()

"""Native h2 fast front: protocol correctness, real grpc-python client
compatibility, scope enforcement (UNIMPLEMENTED for non-columnar
traffic), and the cluster ownership gate."""

import struct

import pytest

from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.core import h2_client
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.net import h2_fast
from gubernator_tpu.net.grpc_service import V1Stub, dial
from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.types import Behavior


@pytest.fixture
def daemon():
    if h2_fast.load() is None:
        pytest.skip("native h2 server unavailable")
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=1 << 12,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
        h2_fast_address="127.0.0.1:0",
        h2_fast_window=0.001,
    )
    d = spawn_daemon(conf)
    yield d
    d.close()


def test_fast_front_serves_real_grpc_client(daemon):
    """A stock grpc-python client must work against the front — the
    single-method port design depends on ignoring request header
    blocks, not on a cooperative client."""
    stub = V1Stub(dial(daemon.h2_fast_address))
    for expect in (4, 3, 2):
        got = stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="f", unique_key="k", hits=1, limit=5,
                        duration=60_000,
                    )
                ]
            )
        )
        assert got.responses[0].remaining == expect
    # State is shared with the full listener: the same bucket.
    full = V1Stub(dial(daemon.grpc_address))
    got = full.GetRateLimits(
        pb.GetRateLimitsReq(
            requests=[
                pb.RateLimitReq(
                    name="f", unique_key="k", hits=1, limit=5,
                    duration=60_000,
                )
            ]
        )
    )
    assert got.responses[0].remaining == 1


def test_fast_front_multi_item_and_native_client(daemon):
    payload = pb.GetRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name="m", unique_key=f"{i}k", hits=1, limit=100,
                duration=60_000,
            )
            for i in range(7)
        ]
    ).SerializeToString()
    res = h2_client.bench_unary(
        daemon.h2_fast_address, "/pb.gubernator.V1/GetRateLimits",
        payload, 0.4, 2,
    )
    assert res is not None
    rpcs, errors, lats, frame, connected = res
    assert errors == 0 and rpcs > 0
    (ln,) = struct.unpack(">I", frame[1:5])
    resp = pb.GetRateLimitsResp.FromString(frame[5 : 5 + ln])
    assert len(resp.responses) == 7
    assert all(0 <= r.remaining < 100 for r in resp.responses)


def test_fast_front_declines_non_columnar(daemon):
    """Behaviors outside the front's scope must answer UNIMPLEMENTED,
    never a wrong decision (GLOBAL et al belong on the full listener)."""
    import grpc

    stub = V1Stub(dial(daemon.h2_fast_address))
    with pytest.raises(grpc.RpcError) as err:
        stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="g", unique_key="k", hits=1, limit=5,
                        duration=60_000,
                        behavior=int(Behavior.GLOBAL),
                    )
                ]
            )
        )
    assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_fast_front_sharded_engine():
    """The front must route through the sharded engine's columnar path
    (codec hashes as shard routes) when the daemon runs multi-device."""
    if h2_fast.load() is None:
        pytest.skip("native h2 server unavailable")
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=1 << 12,
        peer_discovery_type="none",
        device_count=8,
        sweep_interval=0.0,
        h2_fast_address="127.0.0.1:0",
        h2_fast_window=0.001,
    )
    d = spawn_daemon(conf)
    try:
        assert hasattr(d.instance.engine, "tables"), "expected sharded"
        stub = V1Stub(dial(d.h2_fast_address))
        got = stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="sh", unique_key=f"{i}k", hits=1, limit=5,
                        duration=60_000,
                    )
                    for i in range(20)
                ]
            )
        )
        assert [r.remaining for r in got.responses] == [4] * 20
    finally:
        d.close()


def test_fast_front_window_isolation(daemon):
    """One out-of-scope RPC in a window must not fail its window-mates
    (the per-RPC fallback in H2FastFront._window)."""
    import ctypes

    import numpy as np

    front = daemon.h2_fast
    plain = pb.GetRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name="iso", unique_key="a", hits=1, limit=9,
                duration=60_000,
            )
        ]
    ).SerializeToString()
    glob = pb.GetRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name="iso", unique_key="b", hits=1, limit=9,
                duration=60_000, behavior=int(Behavior.GLOBAL),
            )
        ]
    ).SerializeToString()
    concat = plain + glob
    buf = ctypes.create_string_buffer(concat, len(concat))
    counts = np.array([1, 1], dtype=np.int64)
    lens = np.array([len(plain), len(glob)], dtype=np.int64)
    cols = np.zeros(8, dtype=np.int64)
    status = np.zeros(2, dtype=np.int64)
    rc = front._window(
        ctypes.addressof(buf), len(concat),
        counts.ctypes.data, lens.ctypes.data, 2, 2,
        cols.ctypes.data, status.ctypes.data,
    )
    assert rc == 0
    assert status.tolist() == [0, 12]  # plain served, GLOBAL declined
    assert cols[2 * 2 + 0] == 8  # remaining column, first lane


def test_fast_front_ownership_gate():
    """In a cluster, the front must decline peer-owned keys rather
    than answer them locally."""
    if h2_fast.load() is None:
        pytest.skip("native h2 server unavailable")
    import grpc

    from gubernator_tpu.cluster.harness import ClusterHarness
    from gubernator_tpu.net.h2_fast import H2FastFront

    h = ClusterHarness().start(2, cache_size=1 << 12)
    try:
        d0 = h.daemons[0]
        front = H2FastFront(d0.instance, window_s=0.001)
        try:
            stub = V1Stub(dial(front.address))
            # Find a key owned by the OTHER node.
            remote_key = None
            for i in range(200):
                key = f"{i}r"
                owner = d0.instance.local_picker.get(f"own_{key}")
                if owner.info.grpc_address != d0.grpc_address:
                    remote_key = key
                    break
            assert remote_key is not None
            with pytest.raises(grpc.RpcError) as err:
                stub.GetRateLimits(
                    pb.GetRateLimitsReq(
                        requests=[
                            pb.RateLimitReq(
                                name="own", unique_key=remote_key,
                                hits=1, limit=5, duration=60_000,
                            )
                        ]
                    )
                )
            assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED
        finally:
            front.close()
    finally:
        h.stop()


def test_fast_front_zero_item_request(daemon):
    """A zero-item GetRateLimitsReq must answer empty-OK, not
    INTERNAL(13): the C side passes a NULL out_ptr for an empty
    window and the Python entry must not dereference it (ADVICE r5)."""
    stub = V1Stub(dial(daemon.h2_fast_address))
    got = stub.GetRateLimits(pb.GetRateLimitsReq(), timeout=10)
    assert len(got.responses) == 0


def test_fast_front_oversized_rpc_not_starved(daemon):
    """dispatch_loop starvation (ADVICE r5, medium): an RPC with more
    items than max_batch must still be admitted and served — before
    the fix it sat at the queue head forever, busy-spinning the
    dispatch thread and starving every later RPC."""
    from gubernator_tpu.net.h2_fast import H2FastFront

    front = H2FastFront(
        daemon.instance, window_s=0.001, max_batch=4, flush_items=4
    )
    try:
        stub = V1Stub(dial(front.address))
        got = stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="big", unique_key=f"{i}k", hits=1,
                        limit=100, duration=60_000,
                    )
                    for i in range(9)  # > max_batch
                ]
            ),
            timeout=15,
        )
        assert len(got.responses) == 9
        assert all(r.remaining == 99 for r in got.responses)
        # And later, smaller RPCs are not starved behind it.
        got = stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="big", unique_key="0k", hits=1, limit=100,
                        duration=60_000,
                    )
                ]
            ),
            timeout=15,
        )
        assert got.responses[0].remaining == 98
    finally:
        front.close()

"""Paged device bucket state (GUBER_PAGED, core/paging.py): the page
table + LRU host spill plane must be INVISIBLE to decisions.

The pins:
- dense vs paged fuzz: a paged engine squeezed to a fraction of its
  key space resident answers bit-equal to a dense engine AND the
  scalar spec (models/spec.py), across token/leaky, pad widths, and
  TTL expiries — while actually faulting (the harness asserts the
  fault counters moved, so the parity is not vacuous);
- eviction→spill→refill roundtrips are bit-exact at exact TTL/reset
  boundaries, including the leaky 32.32 fixed-point remaining;
- restore is page-aware: a bulk load of a key space far larger than
  the resident frames writes cold pages host-side and faults NOTHING
  (the core/engine.py bulk-load small fix);
- oversized batches segment by unique-key working set instead of
  blowing the frame budget;
- the host-side TTL sweep frees cold expired slots without faulting
  their pages back in.
"""

from __future__ import annotations

import numpy as np
import pytest

from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.models.spec import SlotState, SpecInput, apply_spec
from gubernator_tpu.types import RateLimitReq, Status


def _paged_env(monkeypatch, page_size=16, resident=4, fused="interpret"):
    monkeypatch.setenv("GUBER_FUSED", fused)
    monkeypatch.setenv("GUBER_PUMP", "0")
    monkeypatch.setenv("GUBER_PAGED", "1")
    monkeypatch.setenv("GUBER_PAGE_SIZE", str(page_size))
    monkeypatch.setenv("GUBER_PAGED_RESIDENT", str(resident))


def _dense_env(monkeypatch, fused="interpret"):
    monkeypatch.setenv("GUBER_FUSED", fused)
    monkeypatch.setenv("GUBER_PUMP", "0")
    monkeypatch.delenv("GUBER_PAGED", raising=False)


class _SpecOracle:
    def __init__(self):
        self.states: dict[bytes, SlotState] = {}

    def apply(self, rows, now_ms):
        out = []
        for key, algo, behavior, hits, limit, duration, burst in rows:
            inp = SpecInput(
                hits=int(hits), limit=int(limit), duration=int(duration),
                burst=int(burst), algorithm=int(algo),
                behavior=int(behavior),
            )
            state, resp = apply_spec(self.states.get(key), inp, now_ms)
            if state is None:
                self.states.pop(key, None)
            else:
                self.states[key] = state
            out.append(
                (int(resp.status), int(resp.limit), int(resp.remaining),
                 int(resp.reset_time))
            )
        return out


def _columnar(engine, rows, now_ms):
    n = len(rows)
    res = engine.apply_columnar(
        [r[0] for r in rows],
        np.asarray([r[1] for r in rows], np.int32),
        np.asarray([r[2] for r in rows], np.int32),
        np.asarray([r[3] for r in rows], np.int64),
        np.asarray([r[4] for r in rows], np.int64),
        np.asarray([r[5] for r in rows], np.int64),
        np.asarray([r[6] for r in rows], np.int64),
        now_ms=now_ms,
    )
    st, lim, rem, rst = res
    return [
        (int(st[i]), int(lim[i]), int(rem[i]), int(rst[i]))
        for i in range(n)
    ]


@pytest.mark.parametrize("seed", [5, 23])
def test_dense_vs_paged_vs_spec_fuzz(seed, monkeypatch):
    """Token + leaky fuzz over a key space ~6x the resident rows:
    paged == dense == spec on every response field, across advancing
    time (TTL expiries crossed) and pad widths — and the paged arm
    really pages (fault/spill counters move)."""
    rng = np.random.default_rng(seed)
    clock = Clock().freeze()
    _paged_env(monkeypatch)
    paged = DecisionEngine(capacity=1024, clock=clock)
    _dense_env(monkeypatch)
    dense = DecisionEngine(capacity=1024, clock=clock)
    assert paged.paging is not None and dense.paging is None
    assert paged.capacity == 64 and paged.logical_capacity == 1024
    oracle = _SpecOracle()

    keys = [b"pz_%d" % i for i in range(380)]
    for step in range(50):
        clock.advance(ms=int(rng.integers(0, 120)))
        now = clock.now_ms()
        nrows = int(rng.integers(1, 24))
        rows = []
        for _ in range(nrows):
            key = keys[int(rng.integers(0, len(keys)))]
            rows.append(
                (
                    key,
                    int(key[-1] % 2),  # algo is a property of the key
                    0,
                    int(rng.choice([-1, 0, 1, 1, 2, 5])),
                    int(rng.choice([1, 3, 10, 50])),
                    int(rng.choice([40, 200, 1000])),
                    int(rng.choice([0, 0, 5])),
                )
            )
        got_p = _columnar(paged, rows, now)
        got_d = _columnar(dense, rows, now)
        want = oracle.apply(rows, now)
        assert got_p == want, f"paged vs spec, step {step}: {rows}"
        assert got_d == want, f"dense vs spec, step {step}: {rows}"
    # The parity must not be vacuous: the key space (380) is ~6x the
    # resident rows (64), so the paged arm must have faulted.
    assert paged.paging.faults > 0
    assert paged.paging.spills > 0
    assert paged.paging.refills == paged.paging.faults


def test_spill_refill_roundtrip_exact_ttl_boundary(monkeypatch):
    """Evict→spill→refill must preserve the bucket bit-exactly across
    the residency roundtrip: re-hit at expire_at (equality serves) and
    at expire_at+1 (strict miss → fresh bucket), matching the spec on
    both sides of the boundary.  Leaky included — the 32.32 fractional
    words survive the raw-word spill."""
    clock = Clock().freeze()
    _paged_env(monkeypatch, page_size=16, resident=2)
    eng = DecisionEngine(capacity=512, clock=clock)
    oracle = _SpecOracle()
    now = clock.now_ms()

    tok = [(b"tok", 0, 0, 3, 10, 5_000, 0)]
    lky = [(b"lky", 1, 0, 3, 7, 700, 0)]
    assert _columnar(eng, tok, now) == oracle.apply(tok, now)
    clock.advance(ms=33)  # leaky fractional leak accrues mid-window
    now = clock.now_ms()
    assert _columnar(eng, lky, now) == oracle.apply(lky, now)

    # Flush both pages out through cold traffic (2 resident frames,
    # 16-row pages: 3 pages of strangers evict everything).
    before = eng.paging.spills
    for i in range(60):
        rows = [(b"cold_%d" % i, 0, 0, 1, 5, 60_000, 0)]
        now = clock.now_ms()
        assert _columnar(eng, rows, now) == oracle.apply(rows, now)
    assert eng.paging.spills > before
    assert not eng.paging.is_resident(0)  # the first page went cold

    # Refill at an exact boundary: leaky first (the fractional-words
    # pin), then the token bucket at expire_at and one past it.
    clock.advance(ms=44)
    now = clock.now_ms()
    lrows = [(b"lky", 1, 0, 1, 7, 700, 0)]
    assert _columnar(eng, lrows, now) == oracle.apply(lrows, now)

    exp = oracle.states[b"tok"].expire_at
    clock.advance(ms=exp - clock.now_ms())
    now = clock.now_ms()
    trows = [(b"tok", 0, 0, 1, 10, 5_000, 0)]
    assert _columnar(eng, trows, now) == oracle.apply(trows, now)
    clock.advance(ms=1)
    now = clock.now_ms()
    assert _columnar(eng, trows, now) == oracle.apply(trows, now)


def test_dataclass_path_pages_and_matches_dense(monkeypatch):
    """The dataclass serve path (get_rate_limits) through a paged
    engine answers exactly like a dense engine over a key space well
    past the resident rows."""
    clock = Clock().freeze()
    _paged_env(monkeypatch)
    paged = DecisionEngine(capacity=1024, clock=clock)
    _dense_env(monkeypatch)
    dense = DecisionEngine(capacity=1024, clock=clock)

    def reqs(lo, hi):
        return [
            RateLimitReq(
                name="dp", unique_key=str(i), hits=1, limit=4,
                duration=30_000,
            )
            for i in range(lo, hi)
        ]

    for _round in range(3):
        for lo in range(0, 300, 50):
            clock.advance(ms=7)
            now = clock.now_ms()
            rp = paged.get_rate_limits(reqs(lo, lo + 50), now_ms=now)
            rd = dense.get_rate_limits(reqs(lo, lo + 50), now_ms=now)
            for a, b in zip(rp, rd):
                assert (a.status, a.limit, a.remaining, a.reset_time) == (
                    b.status, b.limit, b.remaining, b.reset_time,
                )
    assert paged.paging.faults > 0


def test_oversized_batch_segments_by_working_set(monkeypatch):
    """One batch with more unique keys than the device can hold
    resident splits into sequential segments — answers stay exact and
    arrival-ordered (duplicate keys count their earlier segments)."""
    clock = Clock().freeze()
    _paged_env(monkeypatch, page_size=16, resident=2)  # 32 device rows
    eng = DecisionEngine(capacity=2048, clock=clock)
    oracle = _SpecOracle()
    now = clock.now_ms()

    # 200 unique keys + a straggler duplicate of key 0 at the end:
    # its hit must see the segment-1 debit (sequential semantics
    # across the segment boundary).
    rows = [(b"seg_%d" % i, 0, 0, 1, 10, 60_000, 0) for i in range(200)]
    rows.append((b"seg_0", 0, 0, 1, 10, 60_000, 0))
    assert _columnar(eng, rows, now) == oracle.apply(rows, now)

    # Same shape through the dataclass path.
    reqs = [
        RateLimitReq(
            name="seg2", unique_key=str(i % 150), hits=1, limit=9,
            duration=60_000,
        )
        for i in range(160)
    ]
    got = eng.get_rate_limits(reqs, now_ms=now)
    rows2 = [
        (b"r2_%d" % (i % 150), 0, 0, 1, 9, 60_000, 0) for i in range(160)
    ]
    want = oracle.apply(rows2, now)
    for g, (ws, _wl, wr, wt) in zip(got, want):
        assert (int(g.status), g.remaining, g.reset_time) == (ws, wr, wt)


def test_restore_is_page_aware_no_fault_storm(monkeypatch):
    """Bulk restore (engine.load) of a key space ≫ resident frames
    writes cold pages straight into the host store: ZERO page faults
    during the load, and the restored buckets answer exactly after a
    (counted) fault on first traffic.  The export side roundtrips the
    same rows, cold pages included."""
    clock = Clock().freeze()
    _paged_env(monkeypatch)
    src = DecisionEngine(capacity=1024, clock=clock)
    now = clock.now_ms()

    # Populate 300 keys with distinct consumption, then snapshot.
    rows = [
        (b"rst_%d" % i, i % 2, 0, 1 + i % 3, 10, 600_000, 0)
        for i in range(300)
    ]
    _columnar(src, rows, now)
    items = list(src.export_items())
    assert len(items) == 300

    class _Loader:
        def load(self):
            return iter(items)

        def save(self, it):
            raise AssertionError("unused")

    dst = DecisionEngine(capacity=1024, clock=clock)
    assert dst.load(_Loader()) == 300
    assert dst.paging.faults == 0, (
        "page-aware restore must not fault the key space through the "
        "resident frames"
    )

    # Restored state is exact: a fresh export matches the source's,
    # and a query (hits=0) on a cold restored key reports the restored
    # remaining after one counted fault.
    src_by_key = {
        it.key: it.value.remaining for it in items if it.value is not None
    }
    probe = [(b"rst_7", 1, 0, 0, 10, 600_000, 0),
             (b"rst_8", 0, 0, 0, 10, 600_000, 0)]
    got = _columnar(dst, probe, clock.now_ms())
    assert got[1][2] == src_by_key["rst_8"]
    assert dst.paging.faults >= 1

    out = {it.key for it in dst.export_items()}
    assert out == set(src_by_key)


def test_host_sweep_frees_cold_pages_without_faults(monkeypatch):
    """TTL sweep: expired buckets on NON-resident pages free from the
    host words alone — slots return to the intern table, fault count
    stays flat."""
    clock = Clock().freeze()
    _paged_env(monkeypatch, page_size=16, resident=2)
    eng = DecisionEngine(capacity=512, clock=clock)
    now = clock.now_ms()
    rows = [(b"sw_%d" % i, 0, 0, 1, 5, 1_000, 0) for i in range(96)]
    assert len(_columnar(eng, rows, now)) == 96
    assert len(eng.paging.nonresident_used_pages()) > 0

    faults_before = eng.paging.faults
    clock.advance(ms=60_000)
    freed = eng.sweep(now_ms=clock.now_ms())
    assert freed == 96
    assert eng.paging.faults == faults_before
    assert list(eng.export_items()) == []


def test_resident_only_traffic_never_faults(monkeypatch):
    """The A/B contract the bench leans on: a working set inside the
    resident frames pays zero faults after first contact — the paged
    plane is pure overhead-free indexing for resident traffic."""
    clock = Clock().freeze()
    _paged_env(monkeypatch, page_size=16, resident=4)  # 64 rows
    eng = DecisionEngine(capacity=1024, clock=clock)
    rows = [(b"hot_%d" % i, 0, 0, 1, 1000, 600_000, 0) for i in range(48)]
    _columnar(eng, rows, clock.now_ms())
    base = eng.paging.faults
    for _ in range(10):
        clock.advance(ms=5)
        _columnar(eng, rows, clock.now_ms())
    assert eng.paging.faults == base


def test_paged_knob_defaults_and_validation(monkeypatch):
    """GUBER_PAGE_SIZE rejects non-pow2/<16 by falling back to the
    default; GUBER_PAGED_RESIDENT=0 keeps every page resident (paged
    indexing, no spill possible)."""
    from gubernator_tpu.config import env_page_size, env_paged_resident

    monkeypatch.setenv("GUBER_PAGE_SIZE", "48")
    assert env_page_size() == 512
    monkeypatch.setenv("GUBER_PAGE_SIZE", "8")
    assert env_page_size() == 512
    monkeypatch.setenv("GUBER_PAGE_SIZE", "64")
    assert env_page_size() == 64
    monkeypatch.setenv("GUBER_PAGED_RESIDENT", "-3")
    assert env_paged_resident() == 0

    clock = Clock().freeze()
    _paged_env(monkeypatch, page_size=16, resident=0)
    eng = DecisionEngine(capacity=256, clock=clock)
    assert eng.capacity == eng.logical_capacity == 256
    rows = [(b"all_%d" % i, 0, 0, 1, 5, 60_000, 0) for i in range(200)]
    _columnar(eng, rows, clock.now_ms())
    assert eng.paging.faults == 0 and eng.paging.spills == 0


def test_paged_metrics_exported(monkeypatch):
    """The gubernator_paged_* family rides the engine collector when
    (and only when) the plane exists; device.page_fault joins the
    stage timers through the service wiring."""
    from gubernator_tpu.core.paging import PagePlane

    plane = PagePlane(1024, 16, 4)
    assert plane.frames == 4
    assert plane.device_capacity == 64
    assert plane.num_pages == 64
    # The counters the metric family reads exist and start at zero.
    assert (plane.faults, plane.spills, plane.refills) == (0, 0, 0)
    assert plane.refill_wait.count == 0
    # Metric names stay in lockstep with utils/metrics.py literals.
    import inspect

    from gubernator_tpu.utils import metrics as m

    src = inspect.getsource(m)
    for name in (
        "gubernator_paged_pages_resident",
        "gubernator_paged_faults",
        "gubernator_paged_spills",
        "gubernator_paged_refill_wait",
    ):
        assert name in src, name

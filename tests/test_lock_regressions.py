"""Regression tests for the races the guberlint lock pass surfaced.

Each test pins an invariant that held only probabilistically before the
fix; with the fix the outcome is exact.  STATIC_ANALYSIS.md records the
audit (ledger / batch_loop / global_manager verified clean; these are
the neighbors that were not).
"""

import threading

import numpy as np
import pytest

from gubernator_tpu.clock import Clock


def test_readback_transfer_counters_exact_under_concurrent_leaders():
    """ReadbackCombiner.transfers/stacked were incremented OUTSIDE the
    combiner lock; concurrent leaders (different shape groups) lost
    updates and under-reported the RPC savings PERF.md is based on.
    With the fix the counters are exact."""
    import jax.numpy as jnp

    from gubernator_tpu.core.readback import ReadbackCombiner

    combiner = ReadbackCombiner()
    n = 96
    # Strictly distinct shapes => every ticket is its own group (no
    # stacking) => every materialize is a leader, concurrently.
    tickets = [
        combiner.register(jnp.zeros((2, 3 + i), dtype=jnp.int32))
        for i in range(n)
    ]
    errs = []

    def fetch(t):
        try:
            t.fetch()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [
        threading.Thread(target=fetch, args=(t,), daemon=True)
        for t in tickets
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs
    assert combiner.registered == n
    # Every ticket materialized alone: transfers counts each exactly
    # once (the unlocked += lost increments here); no stacking.
    assert combiner.transfers == n
    assert combiner.stacked == 0


def test_batcher_current_wait_consistent_under_concurrent_scrape():
    """current_wait() read AdaptiveWait state without the queue lock;
    a metrics scrape racing the drain could observe mid-update EWMA
    state.  With the fix the scrape serializes with drains and always
    returns a value in [0, cap]."""
    from gubernator_tpu.cluster.batch_loop import IntervalBatcher

    flushed = []
    b = IntervalBatcher(
        0.005, 8, lambda old, new: new, lambda batch: flushed.append(batch),
        name="t-scrape", adaptive=True,
    )
    stop = threading.Event()
    bad = []

    def scrape():
        while not stop.is_set():
            w = b.current_wait()
            if not (0.0 <= w <= 0.005):
                bad.append(w)

    t = threading.Thread(target=scrape, daemon=True)
    t.start()
    try:
        for i in range(300):
            b.add(i, i)  # unique keys: every add must survive
        b.flush_now()
    finally:
        stop.set()
        t.join(timeout=5)
        b.close()
    assert bad == []
    assert sum(len(f) for f in flushed) == 300


def test_engine_warmup_serialized_with_serving(frozen_clock):
    """engine.warmup mutated _state and save/restored the metric
    counters WITHOUT the engine lock; a serving thread interleaving
    with warmup could have its requests_total increments clobbered by
    warmup's counter restore.  Under the lock the restore is exact:
    only warmup's own traffic is discounted."""
    from gubernator_tpu.core.engine import DecisionEngine
    from gubernator_tpu.types import RateLimitReq

    engine = DecisionEngine(capacity=2048, clock=frozen_clock,
                            max_kernel_width=256)
    served = 50
    errs = []

    def serve():
        try:
            for i in range(served):
                engine.get_rate_limits(
                    [RateLimitReq(name="serve", unique_key=str(i), hits=1,
                                  limit=10, duration=60_000)]
                )
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    engine.warmup(max_width=64)
    t.join(timeout=60)
    assert not errs
    assert engine.requests_total == served


def test_set_peers_snapshot_under_lock():
    """set_peers built its new pickers from a snapshot taken OUTSIDE
    _peer_lock; two racing rebuilds could both derive from the same
    superseded ring.  Pin the post-fix behavior: concurrent set_peers
    calls never raise and the published picker matches one caller's
    full list exactly (no torn merge)."""
    from gubernator_tpu.clock import Clock
    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.core.engine import DecisionEngine
    from gubernator_tpu.service import V1Instance
    from gubernator_tpu.types import PeerInfo

    conf = Config(behaviors=BehaviorConfig())
    engine = DecisionEngine(capacity=1024, clock=Clock().freeze())
    inst = V1Instance(conf, engine)
    try:
        lists = [
            [PeerInfo(grpc_address=f"10.0.{g}.{i}:81") for i in range(4)]
            for g in range(2)
        ]
        errs = []

        def push(peers):
            try:
                for _ in range(20):
                    inst.set_peers(peers)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [
            threading.Thread(target=push, args=(pl,), daemon=True)
            for pl in lists
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs
        addrs = sorted(p.info.grpc_address for p in inst.get_peer_list())
        expect = [sorted(p.grpc_address for p in pl) for pl in lists]
        assert addrs in expect, f"torn peer publish: {addrs}"
    finally:
        inst.close()


def test_daemon_threads_reaped_on_close():
    """The daemon sweeper (and gateway listener) are joined on close:
    no guber-named background threads survive."""
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=1024,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.05,
    )
    d = spawn_daemon(conf)
    d.close()
    leftover = [
        t.name for t in threading.enumerate()
        if t.name.startswith(("guber-sweep", "guber-gateway"))
        and t.is_alive()
    ]
    assert leftover == []


def test_membership_close_joins_shipper_snapshotted_under_lock():
    """MembershipManager.close() read self._shipper OUTSIDE _lock
    while discovery watch threads swap it in apply_view: a torn read
    could join a superseded thread while the freshly-spawned shipper
    outlived close().  Post-fix, _closed and the shipper snapshot are
    taken atomically, so every transition thread ever spawned is dead
    once close() returns and no apply_view can start one afterwards
    (the post-PR-3 sender/receiver-state audit; guberlint lock pass
    now declares _shipper/_closed guarded)."""
    from types import SimpleNamespace

    from gubernator_tpu.cluster.membership import MembershipManager
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.types import PeerInfo

    daemon = SimpleNamespace(conf=DaemonConfig(), instance=None)
    mem = MembershipManager(daemon, epoch_timeout=5.0)
    spawned = []
    orig_transition = MembershipManager._transition

    def tracked(self, epoch, prev, window):
        spawned.append(threading.current_thread())
        # Hold the transition open until close() signals shutdown, so
        # close always races a live shipper.
        self._stop.wait(timeout=10.0)
        orig_transition(self, epoch, prev, window)

    try:
        MembershipManager._transition = tracked
        views = [
            [PeerInfo(grpc_address=f"10.1.0.{i}:81") for i in range(n)]
            for n in (2, 3, 4, 5)
        ]
        mem.apply_view(views[0])  # boot: no transition
        mem.apply_view(views[1])  # live shipper, parked on _stop
        errs = []

        def guarded(fn, *args):
            try:
                fn(*args)
            except Exception as e:  # noqa: BLE001 — the assert below
                errs.append(e)

        racers = [
            threading.Thread(
                target=guarded, args=(mem.apply_view, v), daemon=True
            )
            for v in views[2:]
        ]
        closer = threading.Thread(
            target=guarded, args=(mem.close,), daemon=True
        )
        for t in racers + [closer]:
            t.start()
        for t in racers + [closer]:
            t.join(timeout=30)
        assert not closer.is_alive(), "close() wedged"
        # A close() that died (e.g. joining a published-but-unstarted
        # shipper raises RuntimeError) is not alive either — the crash
        # must fail the test, not hide in a thread-exception warning.
        assert errs == []
        for t in spawned:
            t.join(timeout=10)
        assert all(not t.is_alive() for t in spawned), (
            "a shipper thread outlived close()"
        )
        assert mem.apply_view(views[0]) is False, (
            "apply_view after close must be a no-op"
        )
    finally:
        MembershipManager._transition = orig_transition
        mem.close()

"""Discovery backend tests: gossip convergence, gated backends.

reference analog: memberlist join/leave handling (memberlist.go:187-233)
— here daemons find each other through the gossip backend instead of
injected peer lists.
"""

import time

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster.harness import cluster_behaviors
from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.types import RateLimitReq


def _until(pred, timeout=10.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _daemon_conf(known_hosts):
    return DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        behaviors=cluster_behaviors(),
        cache_size=2_000,
        peer_discovery_type="member-list",
        member_list_address="127.0.0.1:0",
        known_hosts=known_hosts,
        device_count=1,
    )


def test_memberlist_gossip_convergence():
    """Three daemons discover each other via gossip alone and serve a
    forwarded request."""
    daemons = []
    try:
        d0 = spawn_daemon(_daemon_conf([]))
        seed = d0._discovery.gossip_address
        daemons.append(d0)
        for _ in range(2):
            daemons.append(spawn_daemon(_daemon_conf([seed])))

        def all_know_all():
            return all(
                d.instance.local_picker.size() == 3 for d in daemons
            )

        assert _until(all_know_all), [
            d.instance.local_picker.size() for d in daemons
        ]

        # A request through any daemon routes to the gossip-discovered
        # owner and succeeds.
        req = RateLimitReq(
            name="gossip", unique_key="k1", hits=1, limit=5, duration=60_000
        )
        with V1Client(daemons[1].grpc_address) as c:
            rs = c.get_rate_limits([req], timeout=10)
            assert rs[0].error == ""
            assert rs[0].remaining == 4

        # Kill one daemon; the survivors drop it from membership (death
        # certificates prevent second-hand gossip resurrecting it).
        daemons[2].close()
        assert _until(
            lambda: all(
                d.instance.local_picker.size() == 2 for d in daemons[:2]
            ),
            timeout=20,
        )
        # ...and it STAYS dropped (no resurrection oscillation).
        import time as _time

        _time.sleep(3)
        assert all(d.instance.local_picker.size() == 2 for d in daemons[:2])

        # A new daemon (fresh incarnation) still joins cleanly.
        daemons.append(spawn_daemon(_daemon_conf([seed])))
        assert _until(
            lambda: all(
                d.instance.local_picker.size() == 3
                for d in (daemons[0], daemons[1], daemons[3])
            ),
            timeout=20,
        )
    finally:
        for d in daemons:
            d.close()


def test_etcd_backend_uses_wire_client_without_etcd3():
    """etcd3 is not installed in this image: the backend must fall back
    to the built-in wire-level client (discovery/etcd_wire.py) instead
    of failing — etcd discovery works without the optional package."""
    conf = DaemonConfig(peer_discovery_type="etcd")
    from gubernator_tpu.discovery import create_discovery
    from gubernator_tpu.discovery.etcd_wire import EtcdWireClient

    pool = create_discovery(conf, daemon=None)
    try:
        assert isinstance(pool._client, EtcdWireClient)
    finally:
        pool._client.close()


def test_k8s_backend_gated():
    conf = DaemonConfig(peer_discovery_type="k8s")
    from gubernator_tpu.discovery import create_discovery

    with pytest.raises((RuntimeError, ImportError)) as exc:
        create_discovery(conf, daemon=None)
    assert "k8s" in str(exc.value) or "kubernetes" in str(exc.value)


def test_static_peers_membership():
    """GUBER_STATIC_PEERS (discovery 'none'): the full membership is
    configuration — both daemons see both peers, each marks exactly
    itself as owner, and cross-node routing works."""
    import socket

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    addrs = [f"127.0.0.1:{free_port()}" for _ in range(2)]
    daemons = []
    try:
        for a in addrs:
            daemons.append(
                spawn_daemon(
                    DaemonConfig(
                        grpc_listen_address=a,
                        http_listen_address="127.0.0.1:0",
                        behaviors=cluster_behaviors(),
                        cache_size=1 << 12,
                        peer_discovery_type="none",
                        static_peers=list(addrs),
                        device_count=1,
                        sweep_interval=0.0,
                    )
                )
            )
        for d in daemons:
            members = d.instance.get_peer_list()
            assert len(members) == 2
            owners = [p for p in members if p.info.is_owner]
            assert [p.info.grpc_address for p in owners] == [d.grpc_address]
        # Routing probe: some key maps to the OTHER node from node 0,
        # and a client decision round-trips through the cluster.
        d0 = daemons[0]
        assert any(
            not d0.instance.get_peer(f"{i}_sp").info.is_owner
            for i in range(64)
        )
        with V1Client(d0.grpc_address) as c:
            rs = c.get_rate_limits(
                [
                    RateLimitReq(
                        name="sp", unique_key=f"{i}k", hits=1,
                        limit=100, duration=60_000,
                    )
                    for i in range(20)
                ]
            )
        assert all(r.error == "" and r.remaining == 99 for r in rs)
    finally:
        for d in daemons:
            d.close()

"""Native columnar feeder plane tests (core/native/columnar_feeder.cpp).

The load-bearing guarantee is PARITY: the columns the C conn threads
pack straight from wire bytes must be bit-equal to what the Python
columnar line (net/wire_codec.decode_reqs) produces for the same
payload — key bytes, offsets, every value lane, and both FNV hashes.
Plus the ring's operational contract: overflow backpressure declines
(never blocks, never drops), teardown drains then closes (no
use-after-free, no stranded RPCs), and the retry-hint metadata rides
natively answered OVER_LIMIT items.
"""

import os
import threading
import time

import numpy as np
import pytest

from gubernator_tpu.net import h2_fast, wire_codec
from gubernator_tpu.net.pb import gubernator_pb2 as pb

pytestmark = pytest.mark.skipif(
    h2_fast.load() is None, reason="native h2 server unavailable"
)


def _payload(items):
    return pb.GetRateLimitsReq(
        requests=[pb.RateLimitReq(**kw) for kw in items]
    ).SerializeToString()


def _capture_feeder(**kw):
    """A feeder whose window handler snapshots the packed columns."""
    from gubernator_tpu.core.native_plane import NativeColumnarFeeder

    captured = []

    def handler(slot, n_rows, n_rpcs, key_bytes):
        captured.append(
            {
                "key_buf": slot.key_buf[:key_bytes].copy(),
                "key_offsets": slot.key_offsets[: n_rows + 1].copy(),
                "algo": slot.algo[:n_rows].copy(),
                "behavior": slot.behavior[:n_rows].copy(),
                "hits": slot.hits[:n_rows].copy(),
                "limit": slot.limit[:n_rows].copy(),
                "duration": slot.duration[:n_rows].copy(),
                "burst": slot.burst[:n_rows].copy(),
                "fnv1": slot.fnv1[:n_rows].copy(),
                "fnv1a": slot.fnv1a[:n_rows].copy(),
                "name_lens": slot.name_lens[:n_rows].copy(),
                "rpc_row": slot.rpc_row[:n_rpcs].copy(),
                "rpc_items": slot.rpc_items[:n_rpcs].copy(),
            }
        )
        slot.rpc_status[:n_rpcs] = 0
        return 0

    feeder = NativeColumnarFeeder(window_handler=handler, **kw)
    return feeder, captured


def _fuzz_items(rng, n):
    """Random request rows across algorithms, value widths (32-bit
    boundaries, int64 extremes, negative hits = settle rows), and
    key shapes (incl. '_' in names — name_lens must still split)."""
    items = []
    for _ in range(n):
        name = rng.choice(
            ["r", "rate_limit", "x" * 60, "a_b_c", "Ω≈ç"]
        ) + str(rng.integers(0, 99))
        key = rng.choice(["k", "user_1234", "z" * 120]) + str(
            rng.integers(0, 999)
        )
        items.append(
            dict(
                name=name,
                unique_key=key,
                hits=int(
                    rng.choice(
                        [0, 1, -1, 7, 2**31 - 1, 2**31, -(2**40), 2**62]
                    )
                ),
                limit=int(rng.choice([1, 100, 2**32 + 5, 2**62])),
                duration=int(rng.choice([1000, 60_000, 2**40])),
                algorithm=int(rng.choice([0, 1])),
                behavior=int(rng.choice([0, 2, 8, 32])),  # non-disqualifying
                burst=int(rng.choice([0, 5, 2**33])),
            )
        )
    return items


def test_pack_parity_fuzz():
    """C-packed columns bit-equal to the Python columnar decode across
    wire widths/algorithms — single-RPC windows."""
    feeder, captured = _capture_feeder(n_slots=2, max_rows=2048)
    rng = np.random.default_rng(7)
    try:
        payloads = []
        for round_ in range(20):
            body = _payload(_fuzz_items(rng, int(rng.integers(1, 40))))
            payloads.append(body)
            rc = feeder.pack(body)
            assert rc > 0
            feeder.flush()
        assert len(captured) == len(payloads)
        for body, got in zip(payloads, captured):
            dec = wire_codec.decode_reqs(body, 2048, 0)
            assert dec is not None
            assert got["key_offsets"][0] == 0
            np.testing.assert_array_equal(got["key_buf"], dec.key_buf)
            np.testing.assert_array_equal(
                got["key_offsets"], dec.key_offsets
            )
            for lane in (
                "algo", "behavior", "hits", "limit", "duration", "burst",
                "fnv1", "fnv1a",
            ):
                np.testing.assert_array_equal(
                    got[lane], getattr(dec, lane), err_msg=lane
                )
            np.testing.assert_array_equal(got["name_lens"], dec.name_len)
    finally:
        feeder.close()


def test_pack_parity_multi_rpc_window():
    """Several RPCs packed into ONE window: per-RPC ranges (rpc_row /
    rpc_items) recover each body's own decode exactly, and the joint
    offsets column stays gap-free."""
    feeder, captured = _capture_feeder(
        n_slots=2, max_rows=2048, window_s=0.5
    )
    rng = np.random.default_rng(11)
    try:
        bodies = [
            _payload(_fuzz_items(rng, int(rng.integers(1, 12))))
            for _ in range(6)
        ]
        for b in bodies:
            assert feeder.pack(b) > 0
        feeder.flush()
        assert len(captured) == 1
        got = captured[0]
        assert len(got["rpc_row"]) == len(bodies)
        # Ranges are contiguous and ordered (claims are sequential).
        assert got["rpc_row"][0] == 0
        np.testing.assert_array_equal(
            got["rpc_row"][1:],
            (got["rpc_row"] + got["rpc_items"])[:-1],
        )
        for r, body in enumerate(bodies):
            dec = wire_codec.decode_reqs(body, 2048, 0)
            row0 = int(got["rpc_row"][r])
            k = int(got["rpc_items"][r])
            assert k == dec.n
            off0 = int(got["key_offsets"][row0])
            np.testing.assert_array_equal(
                got["key_offsets"][row0 : row0 + k + 1] - off0,
                dec.key_offsets,
            )
            np.testing.assert_array_equal(
                got["key_buf"][off0 : int(got["key_offsets"][row0 + k])],
                dec.key_buf,
            )
            for lane in ("hits", "limit", "duration", "fnv1a"):
                np.testing.assert_array_equal(
                    got[lane][row0 : row0 + k], getattr(dec, lane),
                    err_msg=lane,
                )
    finally:
        feeder.close()


def test_pack_declines_disqualified_and_malformed():
    from gubernator_tpu.service import COLUMNAR_DISQUALIFIERS
    from gubernator_tpu.types import Behavior

    feeder, captured = _capture_feeder(
        disqualify_mask=COLUMNAR_DISQUALIFIERS
    )
    try:
        body = _payload(
            [
                dict(
                    name="g", unique_key="k", hits=1, limit=5,
                    duration=1000, behavior=int(Behavior.GLOBAL),
                )
            ]
        )
        assert feeder.pack(body) == -1  # disqualified → byte path
        assert feeder.pack(b"\xff\xff\xff") == -1  # malformed
        assert feeder.stats()["feeder_declined"] == 2
        assert not captured
    finally:
        feeder.close()


def test_oversized_claim_declines_without_sealing():
    """An RPC whose key bytes can never fit even an EMPTY window must
    decline to the byte path (-1) WITHOUT sealing the open window —
    sealing would force-flush co-producers' group-commit windows on
    every oversized arrival."""
    feeder, captured = _capture_feeder(
        n_slots=2, max_rows=2048, key_cap=1, window_s=0.5,
    )  # key_cap clamps to the 64 KiB floor
    try:
        small = _payload(
            [dict(name="sm", unique_key="k1xyz", hits=1, limit=9,
                  duration=1000)]
        )
        big = _payload(
            [
                dict(name="big", unique_key="k" * 80 + str(i), hits=1,
                     limit=9, duration=1000)
                for i in range(1000)
            ]
        )  # ~80 KB of key bytes > the 64 KiB window floor
        assert feeder.pack(small) == 1
        before = feeder.stats()
        assert feeder.pack(big, max_items=1000) == -1
        after = feeder.stats()
        assert after["feeder_declined"] == before["feeder_declined"] + 1
        assert after["feeder_ring_full"] == before["feeder_ring_full"]
        # The open window kept its claim open: more rows still join it.
        assert feeder.pack(small) == 1
        feeder.flush()
        assert len(captured) == 1 and len(captured[0]["algo"]) == 2
    finally:
        feeder.close()


def test_max_rpcs_clamp_reflected_in_views():
    """The C side clamps max_rpcs to its cursor field width; the
    Python views must map the CLAMPED capacity, not the raw argument
    (an oversized view would let whole-array writes run past the C
    allocation)."""
    feeder, _ = _capture_feeder(n_slots=2, max_rows=64, max_rpcs=100_000)
    try:
        assert feeder.max_rpcs == 8191  # kRpcsMask
        assert len(feeder.slots[0].rpc_status) == 8191
        assert feeder.stats()["feeder_max_rpcs"] == 8191
    finally:
        feeder.close()


def test_ring_overflow_backpressure_and_recovery():
    """A blocked serve thread + tiny ring ⇒ cf_pack returns the
    backpressure decline (never blocks, never drops); once the serve
    thread drains, packing works again."""
    from gubernator_tpu.core.native_plane import NativeColumnarFeeder

    release = threading.Event()
    served = []

    def handler(slot, n_rows, n_rpcs, key_bytes):
        release.wait(timeout=10)
        served.append(n_rows)
        slot.rpc_status[:n_rpcs] = 0
        return 0

    feeder = NativeColumnarFeeder(
        n_slots=2, max_rows=64, max_rpcs=16, flush_rows=8,
        window_s=0.001, window_handler=handler,
    )
    try:
        body = _payload(
            [
                dict(name="bp", unique_key=f"k{i}xyz", hits=1, limit=9,
                     duration=1000)
                for i in range(8)
            ]
        )
        # Window A seals at flush_rows=8 and blocks in the handler;
        # window B fills and seals; with n_slots=2 there is nowhere to
        # rotate → backpressure.
        deadline = time.monotonic() + 10
        rc = feeder.pack(body)
        while rc > 0 and time.monotonic() < deadline:
            rc = feeder.pack(body)
        assert rc == -2
        assert feeder.stats()["feeder_ring_full"] >= 1
        release.set()
        feeder.flush()
        assert sum(served) == feeder.stats()["feeder_served_rows"]
        # Recovered: the ring accepts claims again.
        deadline = time.monotonic() + 10
        rc = feeder.pack(body)
        while rc == -2 and time.monotonic() < deadline:
            time.sleep(0.005)
            rc = feeder.pack(body)
        assert rc > 0
        feeder.flush()
    finally:
        feeder.close()


def test_teardown_drains_claimed_windows():
    """close() with claimed-but-unserved windows must drain (stats
    account every packed row) and free without crash — the
    drain-then-close contract."""
    from gubernator_tpu.core.native_plane import NativeColumnarFeeder

    hold = threading.Event()

    def handler(slot, n_rows, n_rpcs, key_bytes):
        hold.wait(timeout=3)
        slot.rpc_status[:n_rpcs] = 0
        return 0

    feeder = NativeColumnarFeeder(
        n_slots=3, max_rows=64, flush_rows=8, window_s=0.001,
        window_handler=handler,
    )
    body = _payload(
        [dict(name="td", unique_key=f"x{i}abc", hits=1, limit=9,
              duration=1000) for i in range(8)]
    )
    packed = 0
    for _ in range(3):
        rc = feeder.pack(body)
        if rc > 0:
            packed += rc
    hold.set()
    feeder.close()  # stop drains remaining windows, then frees
    assert packed > 0


def test_flush_observes_late_claims_row_conservation():
    """Regression for the PR-12 teardown flake: a cf_pack claim
    landing AFTER cf_flush's seal scan (or a window sealed by a flush
    just after the serve loop's rotation passed it) could leave one
    RPC packed-but-unserved past the flush's bounded wait.  The fix
    repeats the seal scan inside the wait loop, re-kicks the serve
    thread each iteration, and makes the serve loop sweep sealed
    non-open windows — so at quiesce every packed row is served.
    Producers hammer packs while other threads hammer flushes; the
    final flush must account for every row."""
    from gubernator_tpu.core.native_plane import NativeColumnarFeeder

    served = [0]
    lock = threading.Lock()

    def handler(slot, n_rows, n_rpcs, key_bytes):
        with lock:
            served[0] += n_rows
        slot.out_status[:n_rows] = 0
        slot.out_limit[:n_rows] = 9
        slot.out_remaining[:n_rows] = 8
        slot.out_reset[:n_rows] = 0
        slot.rpc_status[:n_rpcs] = 0
        return 0

    # Small windows + a tiny group-commit so seals, rotations, and
    # flushes interleave densely.
    feeder = NativeColumnarFeeder(
        n_slots=3, max_rows=64, flush_rows=8, window_s=0.0005,
        window_handler=handler,
    )
    try:
        body = _payload(
            [dict(name="fl", unique_key=f"y{i}abc", hits=1, limit=9,
                  duration=1000) for i in range(4)]
        )
        n_packers, reps = 4, 150
        packed = [0] * n_packers
        stop = threading.Event()

        def packer(t):
            for _ in range(reps):
                rc = feeder.pack(body)
                if rc > 0:
                    packed[t] += rc

        def flusher():
            while not stop.is_set():
                feeder.flush()

        ts = [
            threading.Thread(target=packer, args=(t,))
            for t in range(n_packers)
        ]
        fs = [threading.Thread(target=flusher) for _ in range(2)]
        for t in ts + fs:
            t.start()
        for t in ts:
            t.join()
        stop.set()
        for t in fs:
            t.join()
        # The teardown contract: after the final flush with no
        # producers in flight, NOTHING may remain packed-but-unserved.
        feeder.flush()
        st = feeder.stats()
        total = sum(packed)
        assert total > 0
        assert st["feeder_rows"] == total
        assert st["feeder_served_rows"] == total, (st, total)
        assert served[0] == total
    finally:
        feeder.close()


def test_concurrent_pack_parity():
    """Many Python threads pack concurrently; every packed row must
    appear exactly once across the captured windows (claim/commit
    protocol: no losses, no duplicates, offsets gap-free)."""
    feeder, captured = _capture_feeder(
        n_slots=4, max_rows=4096, window_s=0.002
    )
    try:
        n_threads, reps = 8, 50
        body = _payload(
            [dict(name="cc", unique_key=f"u{i}qrs", hits=1, limit=9,
                  duration=1000) for i in range(5)]
        )
        dec = wire_codec.decode_reqs(body, 64, 0)
        ok = [0] * n_threads

        def worker(t):
            for _ in range(reps):
                rc = feeder.pack(body)
                if rc > 0:
                    ok[t] += rc

        ts = [
            threading.Thread(target=worker, args=(t,))
            for t in range(n_threads)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        feeder.flush()
        total = sum(ok)
        assert total > 0
        got_rows = sum(len(c["algo"]) for c in captured)
        assert got_rows == total
        klen = int(dec.key_offsets[-1])
        for c in captured:
            # Offsets stay cumulative and gap-free across interleaved
            # claims, and every row's key slice is one of the body's.
            lens = np.diff(c["key_offsets"])
            assert c["key_offsets"][0] == 0
            assert int(c["key_offsets"][-1]) == len(c["key_buf"])
            assert (lens > 0).all()
            n = len(c["algo"])
            assert n % dec.n == 0  # whole RPCs only
            for r0 in range(0, n, dec.n):
                o0 = int(c["key_offsets"][r0])
                np.testing.assert_array_equal(
                    c["key_buf"][o0 : o0 + klen], dec.key_buf
                )
    finally:
        feeder.close()


def test_encode_resps_hint_parity_and_metadata():
    """The hint encoder is wire_encode_resps plus ONLY the metadata
    entry on OVER items: parse both and compare field-by-field."""
    from gubernator_tpu.types import Status

    status = np.array(
        [int(Status.UNDER_LIMIT), int(Status.OVER_LIMIT)], dtype=np.int32
    )
    limit = np.array([10, 10], dtype=np.int64)
    remaining = np.array([3, 0], dtype=np.int64)
    reset = np.array([50_000, 60_000], dtype=np.int64)
    plain = pb.GetRateLimitsResp.FromString(
        wire_codec.encode_resps(status, limit, remaining, reset)
    )
    hinted = pb.GetRateLimitsResp.FromString(
        wire_codec.encode_resps_hint(
            status, limit, remaining, reset,
            int(Status.OVER_LIMIT), 45_000,
        )
    )
    for a, b in zip(plain.responses, hinted.responses):
        assert (a.status, a.limit, a.remaining, a.reset_time) == (
            b.status, b.limit, b.remaining, b.reset_time
        )
    assert not dict(hinted.responses[0].metadata)  # UNDER: no hint
    assert dict(hinted.responses[1].metadata) == {
        "retry_after_ms": "15000"
    }
    # Stale reset clamps at zero, never negative.
    again = pb.GetRateLimitsResp.FromString(
        wire_codec.encode_resps_hint(
            status, limit, remaining, reset,
            int(Status.OVER_LIMIT), 99_000,
        )
    )
    assert dict(again.responses[1].metadata) == {"retry_after_ms": "0"}


def _spawn_fast_daemon(**over):
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=4096,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
        h2_fast_address="127.0.0.1:0",
        h2_fast_window=0.001,
        **over,
    )
    return spawn_daemon(conf)


def _fast_call(daemon):
    import grpc

    from gubernator_tpu.net.grpc_service import V1_SERVICE

    ch = grpc.insecure_channel(daemon.h2_fast_address)
    return ch, ch.unary_unary(
        f"/{V1_SERVICE}/GetRateLimits",
        request_serializer=lambda r: r,
        response_deserializer=lambda r: r,
    )


def test_feeder_e2e_through_front():
    """Fall-through RPCs (ledger off ⇒ every RPC falls through) ride
    the feeder ring end-to-end: answers match the engine contract,
    OVER_LIMIT carries the retry hint, and the byte window path stays
    idle (windows == 0)."""
    d = _spawn_fast_daemon(ledger=False)
    try:
        ch, call = _fast_call(d)
        payload = _payload(
            [
                dict(name="fe2e", unique_key=f"k{i}end", hits=1, limit=2,
                     duration=60_000)
                for i in range(3)
            ]
        )
        for _ in range(3):
            raw = call(payload)
        resp = pb.GetRateLimitsResp.FromString(raw)
        sts = [r.status for r in resp.responses]
        assert sts == [1, 1, 1]  # limit 2, third round: all OVER
        for r in resp.responses:
            hint = int(dict(r.metadata)["retry_after_ms"])
            # reset-derived and in the ENGINE clock domain: a fresh
            # 60 s bucket's reset is near-full, so the hint must be a
            # sane wait, not a clock-offset artifact.
            assert 50_000 < hint <= 60_000, hint
        st = d.h2_fast.stats()
        assert st["feeder_front_rpcs"] == 3
        assert st["feeder_windows"] >= 1
        assert st["windows"] == 0  # byte window path never entered
        assert st["errors"] == 0
        ch.close()
    finally:
        d.close()


def test_feeder_front_declines_global_to_byte_path():
    """A GLOBAL-behavior RPC must NOT enter the feeder (C-side
    disqualify) — it falls to the byte window path and answers
    UNIMPLEMENTED exactly like the pre-feeder front."""
    import grpc

    from gubernator_tpu.types import Behavior

    d = _spawn_fast_daemon(ledger=False)
    try:
        ch, call = _fast_call(d)
        payload = _payload(
            [
                dict(name="g", unique_key="k1end", hits=1, limit=5,
                     duration=60_000, behavior=int(Behavior.GLOBAL))
            ]
        )
        with pytest.raises(grpc.RpcError) as err:
            call(payload)
        assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED
        st = d.h2_fast.stats()
        assert st["feeder_front_rpcs"] == 0
        assert st["feeder_declined"] >= 1
        assert st["windows"] >= 1  # byte path handled it
        ch.close()
    finally:
        d.close()


def test_feeder_disabled_restores_byte_path(monkeypatch):
    monkeypatch.setenv("GUBER_NATIVE_FEEDER", "0")
    d = _spawn_fast_daemon(ledger=False)
    try:
        assert d.h2_fast.feeder is None
        ch, call = _fast_call(d)
        payload = _payload(
            [dict(name="off", unique_key="k1end", hits=1, limit=5,
                  duration=60_000)]
        )
        raw = call(payload)
        resp = pb.GetRateLimitsResp.FromString(raw)
        assert resp.responses[0].remaining == 4
        st = d.h2_fast.stats()
        assert st["windows"] >= 1
        assert "feeder_rpcs" not in st
        ch.close()
    finally:
        d.close()


def test_retry_hints_disabled(monkeypatch):
    monkeypatch.setenv("GUBER_RETRY_HINTS", "0")
    d = _spawn_fast_daemon(ledger=False)
    try:
        ch, call = _fast_call(d)
        payload = _payload(
            [dict(name="noh", unique_key="k1end", hits=1, limit=1,
                  duration=60_000)]
        )
        call(payload)
        resp = pb.GetRateLimitsResp.FromString(call(payload))
        assert resp.responses[0].status == 1  # OVER
        assert not dict(resp.responses[0].metadata)
        ch.close()
    finally:
        d.close()


def test_feeder_stats_in_front_stats():
    d = _spawn_fast_daemon(ledger=False)
    try:
        st = d.h2_fast.stats()
        for k in (
            "feeder_rpcs", "feeder_rows", "feeder_windows",
            "feeder_ring_full", "feeder_declined",
        ):
            assert k in st
    finally:
        d.close()

"""Event-driven native front (PERF.md §26): the epoll reactor plane.

RPC correctness through the reactors (parity with the thread-per-conn
plane, across the native decision plane and the columnar feeder),
partial/coalesced frame delivery under edge-triggered reads, writev
short-write resumption and backpressure when a client stops reading,
idle-connection reaping, teardown under live load, and the reactor
stages in the event ring.
"""

import socket
import struct
import threading
import time

import pytest

from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.core import h2_client
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.net import h2_fast
from gubernator_tpu.net.grpc_service import V1Stub, dial
from gubernator_tpu.net.h2_fast import H2FastFront
from gubernator_tpu.net.pb import gubernator_pb2 as pb

PATH = "/pb.gubernator.V1/GetRateLimits"


@pytest.fixture
def daemon():
    if h2_fast.load() is None:
        pytest.skip("native h2 server unavailable")
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=1 << 12,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
        h2_fast_address="127.0.0.1:0",
        h2_fast_window=0.001,
    )
    d = spawn_daemon(conf)
    yield d
    d.close()


def _req(name, key, hits=1, limit=100, n=1):
    return pb.GetRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name=name, unique_key=f"{key}{i}", hits=hits,
                limit=limit, duration=60_000,
            )
            for i in range(n)
        ]
    )


def _h2_frames(sock, deadline):
    """Yield (type, flags, stream, payload) until timeout/close."""
    buf = b""
    while True:
        while len(buf) < 9:
            sock.settimeout(max(0.05, deadline - time.monotonic()))
            try:
                chunk = sock.recv(65536)
            except (socket.timeout, TimeoutError):
                return
            if not chunk:
                return
            buf += chunk
        flen = (buf[0] << 16) | (buf[1] << 8) | buf[2]
        ftype, flags = buf[3], buf[4]
        stream = struct.unpack(">I", buf[5:9])[0] & 0x7FFFFFFF
        while len(buf) < 9 + flen:
            sock.settimeout(max(0.05, deadline - time.monotonic()))
            try:
                chunk = sock.recv(65536)
            except (socket.timeout, TimeoutError):
                return
            if not chunk:
                return
            buf += chunk
        yield ftype, flags, stream, buf[9 : 9 + flen]
        buf = buf[9 + flen :]


def _frame(ftype, flags, stream, payload=b""):
    return (
        struct.pack(">I", len(payload))[1:]
        + bytes([ftype, flags])
        + struct.pack(">I", stream)
        + payload
    )


def _grpc_frame(body):
    return b"\x00" + struct.pack(">I", len(body)) + body


def _read_responses(sock, want_streams, timeout=5.0):
    """Collect {stream: (data, saw_trailers)} until every wanted
    stream finished."""
    out = {s: b"" for s in want_streams}
    done = set()
    deadline = time.monotonic() + timeout
    for ftype, flags, stream, payload in _h2_frames(sock, deadline):
        if stream not in out:
            continue
        if ftype == 0:
            out[stream] += payload
        elif ftype == 1 and flags & 0x1:
            done.add(stream)
            if done == set(want_streams):
                break
    return out, done


def test_event_front_is_default_and_serves(daemon):
    """spawn_daemon's front must come up on the reactor plane and
    serve a stock grpc client correctly."""
    cs = daemon.h2_fast.conn_stats()
    assert cs["event_front"] is True
    assert cs["reactors"] >= 1
    stub = V1Stub(dial(daemon.h2_fast_address))
    for expect in (99, 98, 97):
        got = stub.GetRateLimits(_req("ev", "k"))
        assert got.responses[0].remaining == expect


def test_event_vs_threaded_parity(daemon):
    """The two connection planes share one frame machine and one
    serve pipeline: alternating RPCs across an event front and a
    threaded front on the SAME instance must hit the same buckets."""
    threaded = H2FastFront(
        daemon.instance, window_s=0.001, event_front=False
    )
    try:
        ev = V1Stub(dial(daemon.h2_fast_address))
        th = V1Stub(dial(threaded.address))
        remaining = []
        for i in range(6):
            stub = ev if i % 2 == 0 else th
            got = stub.GetRateLimits(_req("par", "x"))
            remaining.append(got.responses[0].remaining)
        assert remaining == [99, 98, 97, 96, 95, 94]
    finally:
        threaded.close()


@pytest.mark.parametrize("feeder", [True, False], ids=["feeder", "bytepath"])
def test_event_front_feeder_attach_detach_parity(daemon, feeder):
    """Reactor-packed feeder windows and the byte window path must
    answer identically through the event front (attach-detach
    parity)."""
    front = H2FastFront(
        daemon.instance, window_s=0.001, native_feeder=feeder
    )
    try:
        stub = V1Stub(dial(front.address))
        got = stub.GetRateLimits(_req("fd" + str(int(feeder)), "k", n=7))
        assert [r.remaining for r in got.responses] == [99] * 7
        got = stub.GetRateLimits(_req("fd" + str(int(feeder)), "k", n=7))
        assert [r.remaining for r in got.responses] == [98] * 7
    finally:
        front.close()


def test_partial_frame_delivery(daemon):
    """Edge-triggered reads must reassemble a request delivered one
    dribble at a time: preface split mid-token, frame headers split
    mid-header, DATA split mid-payload."""
    host, port = daemon.h2_fast_address.rsplit(":", 1)
    body = _req("part", "k", n=3).SerializeToString()
    wire = (
        b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
        + _frame(1, 0x4, 1)  # HEADERS, empty block (port is the route)
        + _frame(0, 0x1, 1, _grpc_frame(body))
    )
    sock = socket.create_connection((host, int(port)), timeout=5)
    try:
        # 5-byte dribbles with pauses: every chunk crosses a frame or
        # preface boundary somewhere in the stream.
        for i in range(0, len(wire), 5):
            sock.sendall(wire[i : i + 5])
            time.sleep(0.002)
        out, done = _read_responses(sock, [1])
        assert done == {1}
        data = out[1]
        (ln,) = struct.unpack(">I", data[1:5])
        resp = pb.GetRateLimitsResp.FromString(data[5 : 5 + ln])
        assert [r.remaining for r in resp.responses] == [99] * 3
    finally:
        sock.close()


def test_coalesced_frames_one_read(daemon):
    """Multiple complete RPCs landing in ONE read (streams 1/3/5
    coalesced into a single send) must all answer."""
    host, port = daemon.h2_fast_address.rsplit(":", 1)
    wire = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
    for sid in (1, 3, 5):
        body = _req("coal", f"s{sid}_").SerializeToString()
        wire += _frame(1, 0x4, sid) + _frame(0, 0x1, sid, _grpc_frame(body))
    sock = socket.create_connection((host, int(port)), timeout=5)
    try:
        sock.sendall(wire)  # one send: the reactor sees them coalesced
        out, done = _read_responses(sock, [1, 3, 5])
        assert done == {1, 3, 5}
        for sid in (1, 3, 5):
            data = out[sid]
            (ln,) = struct.unpack(">I", data[1:5])
            resp = pb.GetRateLimitsResp.FromString(data[5 : 5 + ln])
            assert resp.responses[0].remaining == 99
    finally:
        sock.close()


def test_writev_short_write_resumption_backpressure(daemon):
    """A client that stops reading must park the response in the
    egress queue (short writev → EPOLLOUT resumption), NOT block a
    reactor — proven by a second client staying fully served during
    the stall — and the parked response must complete once the client
    resumes reading."""
    host, port = daemon.h2_fast_address.rsplit(":", 1)
    n_items = 900  # ~9KB response
    body = _req("bp", "k", n=n_items).SerializeToString()
    slow = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    # Tiny receive buffer: the response cannot fit in flight, so the
    # server's writev MUST short-write once the client stops reading.
    slow.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 2048)
    slow.connect((host, int(port)))
    try:
        slow.sendall(
            b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
            + _frame(1, 0x4, 1)
            + _frame(0, 0x1, 1, _grpc_frame(body))
        )
        # Stall: read NOTHING while a second client runs a full loop.
        time.sleep(0.3)
        fast = V1Stub(dial(daemon.h2_fast_address))
        for expect in (99, 98, 97):
            got = fast.GetRateLimits(_req("bp_fast", "k"), timeout=5)
            assert got.responses[0].remaining == expect
        # Resume: the parked response must drain completely.
        out, done = _read_responses(sock=slow, want_streams=[1], timeout=8.0)
        assert done == {1}, "parked response never resumed"
        data = out[1]
        (ln,) = struct.unpack(">I", data[1:5])
        resp = pb.GetRateLimitsResp.FromString(data[5 : 5 + ln])
        assert len(resp.responses) == n_items
        assert all(r.remaining == 99 for r in resp.responses)
    finally:
        slow.close()


def test_idle_connection_reaped(daemon):
    """A connection silent past GUBER_H2_IDLE_TIMEOUT gets GOAWAY +
    close, and the conns gauge books it — the pre-§26 front held dead
    connections forever."""
    front = H2FastFront(daemon.instance, window_s=0.001, idle_timeout_s=0.3)
    try:
        sock = socket.create_connection(("127.0.0.1", front.port), timeout=5)
        sock.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
        types = [
            t for t, _f, _s, _p in _h2_frames(sock, time.monotonic() + 3.0)
        ]
        sock.close()
        assert 7 in types, f"no GOAWAY before close (saw {types})"
        cs = front.conn_stats()
        assert cs["conns_idle_reaped"] >= 1
        assert cs["conns_open"] == 0
    finally:
        front.close()


def test_active_connection_not_reaped(daemon):
    """The idle sweep must key on ACTIVITY, not connection age: a
    connection older than the timeout but still trafficking stays."""
    front = H2FastFront(daemon.instance, window_s=0.001, idle_timeout_s=0.4)
    try:
        stub = V1Stub(dial(front.address))
        deadline = time.monotonic() + 1.2  # 3× the timeout
        n = 0
        while time.monotonic() < deadline:
            got = stub.GetRateLimits(_req("alive", "k", limit=10**6))
            assert not got.responses[0].error
            n += 1
            time.sleep(0.1)
        assert front.conn_stats()["conns_idle_reaped"] == 0
        assert n >= 8
    finally:
        front.close()


def test_teardown_under_live_load(daemon):
    """close() with RPC traffic mid-flight must drain cleanly: no
    hang, no crash, and the daemon's shared engine stays serviceable
    through another front afterwards."""
    front = H2FastFront(daemon.instance, window_s=0.001)
    payload = _req("tear", "k", limit=10**9).SerializeToString()
    res = [None]

    def load():
        res[0] = h2_client.bench_unary(front.address, PATH, payload, 1.5, 4)

    t = threading.Thread(target=load)
    t.start()
    time.sleep(0.4)  # traffic is flowing
    front.close()
    t.join(timeout=20)
    assert not t.is_alive(), "client hung through server teardown"
    # The engine survived: a fresh front serves.
    front2 = H2FastFront(daemon.instance, window_s=0.001)
    try:
        stub = V1Stub(dial(front2.address))
        got = stub.GetRateLimits(_req("tear2", "k"))
        assert got.responses[0].remaining == 99
    finally:
        front2.close()


def test_reactor_stages_reach_event_ring(daemon):
    """reactor_wake / reactor_read must flow through the native event
    ring into the collector's histograms after traffic."""
    stub = V1Stub(dial(daemon.h2_fast_address))
    for _ in range(20):
        stub.GetRateLimits(_req("ring", "k", limit=10**6))
    ev = daemon.instance.native_events
    assert ev is not None
    ev.drain_once()
    counts = ev.event_counts()
    assert counts.get("reactor_wake", 0) > 0
    assert counts.get("reactor_read", 0) > 0
    stats = ev.stats()
    assert "reactor_wake" in stats["stages"]


def test_h2_conns_gauge_exported(daemon):
    """gubernator_h2_conns{state} must come out of the instance
    collector while a connection is held open."""
    from gubernator_tpu.utils.metrics import InstanceCollector

    host, port = daemon.h2_fast_address.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)), timeout=5)
    try:
        sock.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
        time.sleep(0.1)
        metrics = {
            m.name: m for m in InstanceCollector(daemon.instance).collect()
        }
        assert "gubernator_h2_conns" in metrics
        samples = {
            s.labels["state"]: s.value
            for s in metrics["gubernator_h2_conns"].samples
        }
        assert samples["open"] >= 1
        assert "idle_reaped" in samples
    finally:
        sock.close()


def test_connscale_client_against_event_front(daemon):
    """The epoll connscale client holds hundreds of mostly-idle
    connections plus a closed active loop with zero errors — the
    C10K building block the §26 bench ramps."""
    payload = _req("cs", "hot", limit=10**12).SerializeToString()
    res = [None]

    def run():
        res[0] = h2_client.connscale(
            daemon.h2_fast_address, PATH, payload, 1.5, 200, 8, threads=1
        )

    t = threading.Thread(target=run)
    t.start()
    # The server must be HOLDING all 200 while the run is live (the
    # client closes them at its deadline, so sample mid-flight).
    peak = 0
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and peak < 200:
        peak = max(peak, daemon.h2_fast.conn_stats()["conns_open"])
        time.sleep(0.05)
    t.join(timeout=30)
    assert not t.is_alive()
    assert peak >= 200
    out = res[0]
    assert out is not None
    assert out["connected"] == 200
    assert out["alive_at_end"] == 200
    assert out["errors"] == 0
    assert out["rpcs"] > 0

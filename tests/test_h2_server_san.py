"""ThreadSanitizer stress of the native h2 server (guberlint's native
runtime companion, STATIC_ANALYSIS.md).

Builds core/native/h2_server.cpp with GUBER_NATIVE_SAN=thread (separate
cache tag, -fsanitize=thread -O1 -g) and hammers it from concurrent
gRPC clients in a SUBPROCESS with the TSan runtime LD_PRELOADed — a
sanitizer runtime cannot initialize inside an already-running
uninstrumented python, so in-process loading is not an option.  Any
data race inside the instrumented .so fails the subprocess
(halt_on_error=1, exitcode=66).

Marked slow: TSan startup + the hammer take tens of seconds; run it
with `GUBER_NATIVE_SAN=1 pytest -m slow tests/test_h2_server_san.py`
or via the scheduled soak, not tier-1.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

from gubernator_tpu.core.native_build import ensure_built, sanitizer_preload

REPO = Path(__file__).resolve().parents[1]

# Runs PRELOADED (TSan): the instrumented server + a flat columnar
# callback.  It prints its port, then blocks on stdin until the parent
# closes it — the server process must NEVER fork once its C threads
# run (fork from a TSan'd multithreaded process deadlocks), so the
# unpreloaded pytest parent is the one that spawns the client hammer.
_SERVER_SRC = r"""
import ctypes, sys
import numpy as np

from gubernator_tpu.net import h2_fast

lib = h2_fast.load()
assert lib is not None, "sanitized h2_server build unavailable"

def window(buf, length, counts_ptr, lens_ptr, n_rpcs, total, out_ptr,
           status_ptr):
    n = int(total); nr = int(n_rpcs)
    if nr > 0 and status_ptr:
        np.ctypeslib.as_array(
            ctypes.cast(status_ptr, ctypes.POINTER(ctypes.c_int64)),
            shape=(nr,),
        )[:] = 0
    if n > 0 and out_ptr:
        cols = np.ctypeslib.as_array(
            ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_int64)),
            shape=(4 * n,),
        )
        cols[:n] = 0          # status UNDER_LIMIT
        cols[n:2 * n] = 100   # limit
        cols[2 * n:3 * n] = 99  # remaining
        cols[3 * n:] = 0      # reset
    return 0

cb = h2_fast._CALLBACK(window)
handle = lib.h2s_start(0, 500, 16384, 4096, cb)
assert handle, "h2 server failed to bind"
print("PORT", int(lib.h2s_port(handle)), flush=True)
sys.stdin.read()  # parent closes stdin when the hammer is done
# Stats BEFORE stop: h2s_stop frees the server (TSan caught this
# harness's original stats-after-stop as a heap-use-after-free).
stats = np.zeros(8, dtype=np.int64)
lib.h2s_stats(handle, stats.ctypes.data_as(ctypes.c_void_p))
lib.h2s_stop(handle)
print("san stress ok rpcs=%d windows=%d" % (stats[0], stats[1]), flush=True)
"""

_CLIENT_SRC = r"""
import sys, threading
import grpc
from gubernator_tpu.net.pb import gubernator_pb2 as pb

port = int(sys.argv[1])
payload = pb.GetRateLimitsReq(
    requests=[
        pb.RateLimitReq(name="san", unique_key=str(i), hits=1, limit=100,
                        duration=60000)
        for i in range(8)
    ]
).SerializeToString()

N_THREADS = 8
N_RPCS = 60
errs = []

def hammer(tid):
    try:
        ch = grpc.insecure_channel("127.0.0.1:%d" % port)
        stub = ch.unary_unary(
            "/pb.gubernator.V1/GetRateLimits",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        for i in range(N_RPCS):
            resp = stub(payload, timeout=30)
            out = pb.GetRateLimitsResp.FromString(resp)
            assert len(out.responses) == 8, len(out.responses)
        ch.close()
    except Exception as e:
        errs.append("t%d: %r" % (tid, e))

threads = [threading.Thread(target=hammer, args=(t,)) for t in range(N_THREADS)]
for t in threads:
    t.start()
for t in threads:
    t.join(timeout=120)
if errs:
    print("CLIENT ERRORS:", errs[:5], file=sys.stderr)
    sys.exit(1)
print("client ok: %d rpcs" % (N_THREADS * N_RPCS))
"""


@pytest.mark.slow
def test_h2_server_threaded_stress_under_tsan():
    if os.environ.get("GUBER_NATIVE_SAN", "") in ("", "0"):
        pytest.skip("set GUBER_NATIVE_SAN=1 to run the TSan stress")
    preload = sanitizer_preload("thread")
    if preload is None:
        pytest.skip("libtsan not available from this toolchain")
    # Build the instrumented .so in-process (compilation needs no
    # preload); the subprocess then dlopens the cached artifact.
    orig_san = os.environ.get("GUBER_NATIVE_SAN")
    env = dict(os.environ, GUBER_NATIVE_SAN="thread")
    os.environ["GUBER_NATIVE_SAN"] = "thread"
    try:
        so = ensure_built("h2_server")
    finally:
        if orig_san is None:
            os.environ.pop("GUBER_NATIVE_SAN", None)
        else:
            os.environ["GUBER_NATIVE_SAN"] = orig_san
    if so is None:
        pytest.skip("sanitized h2_server build failed (no g++?)")

    supp = REPO / "tests" / "tsan_suppressions.txt"
    server_env = dict(
        env,
        LD_PRELOAD=preload,
        TSAN_OPTIONS=(
            # Mutex-misuse reports are off: gcc-10's libtsan
            # false-positives "double lock" on pthread_cond_wait
            # re-acquisition (and on uninstrumented Eigen pools in
            # jaxlib).  Data-race detection — what this stress is
            # for — stays fully on.
            "halt_on_error=1 exitcode=66 report_thread_leaks=0 "
            f"report_mutex_bugs=0 detect_deadlocks=0 suppressions={supp}"
        ),
        # Import gubernator_tpu without jax: TSan instruments every
        # malloc; the XLA runtime under TSan is noise we don't want.
        GUBERNATOR_TPU_X64="0",
        GUBERNATOR_TPU_COMPILE_CACHE="0",
    )
    server = subprocess.Popen(
        [sys.executable, "-c", _SERVER_SRC],
        cwd=REPO,
        env=server_env,
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        port_line = server.stdout.readline()
        assert port_line.startswith("PORT "), (
            f"server failed to start: {port_line!r}\n"
            + server.stderr.read()[-4000:]
        )
        port = int(port_line.split()[1])
        client = subprocess.run(
            [sys.executable, "-c", _CLIENT_SRC, str(port)],
            cwd=REPO,
            env=dict(env, GUBERNATOR_TPU_X64="0",
                     GUBERNATOR_TPU_COMPILE_CACHE="0"),
            capture_output=True,
            text=True,
            timeout=240,
        )
        assert client.returncode == 0, (
            f"client hammer failed rc={client.returncode}\n"
            f"{client.stdout[-1000:]}\n{client.stderr[-2000:]}"
        )
        out, err = server.communicate(input="", timeout=120)
    except Exception:
        server.kill()
        raise
    assert "ThreadSanitizer" not in err, (
        "TSan report from h2_server:\n" + err[-4000:]
    )
    assert server.returncode == 0, (
        f"san server failed rc={server.returncode}\n"
        f"stdout: {out[-2000:]}\nstderr: {err[-4000:]}"
    )
    assert "san stress ok" in out

"""ThreadSanitizer stress of the native h2 server (guberlint's native
runtime companion, STATIC_ANALYSIS.md).

Builds core/native/h2_server.cpp with GUBER_NATIVE_SAN=thread (separate
cache tag, -fsanitize=thread -O1 -g) and hammers it from concurrent
gRPC clients in a SUBPROCESS with the TSan runtime LD_PRELOADed — a
sanitizer runtime cannot initialize inside an already-running
uninstrumented python, so in-process loading is not an option.  Any
data race inside the instrumented .so fails the subprocess
(halt_on_error=1, exitcode=66).

Marked slow: TSan startup + the hammer take tens of seconds; run it
with `GUBER_NATIVE_SAN=1 pytest -m slow tests/test_h2_server_san.py`
or via the scheduled soak, not tier-1.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

from gubernator_tpu.core.native_build import ensure_built, sanitizer_preload

REPO = Path(__file__).resolve().parents[1]

# Runs PRELOADED (TSan): the instrumented server + a flat columnar
# callback.  It prints its port, then blocks on stdin until the parent
# closes it — the server process must NEVER fork once its C threads
# run (fork from a TSan'd multithreaded process deadlocks), so the
# unpreloaded pytest parent is the one that spawns the client hammer.
_SERVER_SRC = r"""
import ctypes, os, sys
import numpy as np

from gubernator_tpu.net import h2_fast

lib = h2_fast.load()
assert lib is not None, "sanitized h2_server build unavailable"

def window(buf, length, counts_ptr, lens_ptr, n_rpcs, total, out_ptr,
           status_ptr):
    n = int(total); nr = int(n_rpcs)
    if nr > 0 and status_ptr:
        np.ctypeslib.as_array(
            ctypes.cast(status_ptr, ctypes.POINTER(ctypes.c_int64)),
            shape=(nr,),
        )[:] = 0
    if n > 0 and out_ptr:
        cols = np.ctypeslib.as_array(
            ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_int64)),
            shape=(4 * n,),
        )
        cols[:n] = 0          # status UNDER_LIMIT
        cols[n:2 * n] = 100   # limit
        cols[2 * n:3 * n] = 99  # remaining
        cols[3 * n:] = 0      # reset
    return 0

cb = h2_fast._CALLBACK(window)
# SAN_EVENT_FRONT=1: the epoll reactor plane (2 reactors racing the
# dispatch/feeder threads through the shared Conn write side);
# otherwise the thread-per-conn plane with 2 listener lanes.
event = int(os.environ.get("SAN_EVENT_FRONT", "0"))
handle = lib.h2s_start(0, 500, 16384, 4096, 2, event, 2, 0, cb)
assert handle, "h2 server failed to bind"

# Columnar feeder attached: the hammer's fall-through RPCs now run
# the REAL integrated path — conn threads cf_pack into the ring, the
# feeder serve thread enters this columnar handler, and the scatter
# rides h2s_feeder_respond back through the connections — all under
# TSan.  Windows are tiny (flush_rows=8) so seal/rotate churns.
from gubernator_tpu.core import native_plane

def feeder_window(slot, n_rows, n_rpcs, key_bytes):
    slot.out_status[:n_rows] = 0
    slot.out_limit[:n_rows] = 100
    slot.out_remaining[:n_rows] = 99
    slot.out_reset[:n_rows] = 0
    slot.rpc_status[:n_rpcs] = 0
    return 0

feeder = native_plane.NativeColumnarFeeder(
    n_slots=3, max_rows=256, max_rpcs=64, flush_rows=8,
    window_s=0.0005, window_handler=feeder_window,
)
lib.h2s_attach_feeder(handle, feeder.handle)

print("PORT", int(lib.h2s_port(handle)), flush=True)
sys.stdin.read()  # parent closes stdin when the hammer is done
# Stats BEFORE stop: h2s_stop frees the server (TSan caught this
# harness's original stats-after-stop as a heap-use-after-free).
# 16 slots: h2s_stats writes eleven now (conn-plane fields) — an
# 8-slot buffer here would be a 24-byte heap overflow.
stats = np.zeros(16, dtype=np.int64)
lib.h2s_stats(handle, stats.ctypes.data_as(ctypes.c_void_p))
# Teardown order contract (net/h2_fast.close): detach, drain-stop the
# feeder, stop the server, then free the ring.
lib.h2s_attach_feeder(handle, None)
feeder.stop()
lib.h2s_stop(handle)
feeder.close()
assert stats[5] > 0, "hammer never exercised the feeder path"
print("san stress ok rpcs=%d windows=%d feeder_rpcs=%d"
      % (stats[0], stats[1], stats[5]), flush=True)
"""

_CLIENT_SRC = r"""
import sys, threading
import grpc
from gubernator_tpu.net.pb import gubernator_pb2 as pb

port = int(sys.argv[1])
payload = pb.GetRateLimitsReq(
    requests=[
        pb.RateLimitReq(name="san", unique_key=str(i), hits=1, limit=100,
                        duration=60000)
        for i in range(8)
    ]
).SerializeToString()

N_THREADS = 8
N_RPCS = 60
errs = []

def hammer(tid):
    try:
        ch = grpc.insecure_channel("127.0.0.1:%d" % port)
        stub = ch.unary_unary(
            "/pb.gubernator.V1/GetRateLimits",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        for i in range(N_RPCS):
            resp = stub(payload, timeout=30)
            out = pb.GetRateLimitsResp.FromString(resp)
            assert len(out.responses) == 8, len(out.responses)
        ch.close()
    except Exception as e:
        errs.append("t%d: %r" % (tid, e))

threads = [threading.Thread(target=hammer, args=(t,)) for t in range(N_THREADS)]
for t in threads:
    t.start()
for t in threads:
    t.join(timeout=120)
if errs:
    print("CLIENT ERRORS:", errs[:5], file=sys.stderr)
    sys.exit(1)
print("client ok: %d rpcs" % (N_THREADS * N_RPCS))
"""


@pytest.mark.slow
@pytest.mark.parametrize("event_front", [0, 1], ids=["threaded", "reactor"])
def test_h2_server_threaded_stress_under_tsan(event_front):
    if os.environ.get("GUBER_NATIVE_SAN", "") in ("", "0"):
        pytest.skip("set GUBER_NATIVE_SAN=1 to run the TSan stress")
    preload = sanitizer_preload("thread")
    if preload is None:
        pytest.skip("libtsan not available from this toolchain")
    # Build the instrumented .so in-process (compilation needs no
    # preload); the subprocess then dlopens the cached artifact.
    orig_san = os.environ.get("GUBER_NATIVE_SAN")
    env = dict(os.environ, GUBER_NATIVE_SAN="thread")
    os.environ["GUBER_NATIVE_SAN"] = "thread"
    try:
        so = ensure_built("h2_server")
    finally:
        if orig_san is None:
            os.environ.pop("GUBER_NATIVE_SAN", None)
        else:
            os.environ["GUBER_NATIVE_SAN"] = orig_san
    if so is None:
        pytest.skip("sanitized h2_server build failed (no g++?)")

    supp = REPO / "tests" / "tsan_suppressions.txt"
    server_env = dict(
        env,
        SAN_EVENT_FRONT=str(event_front),
        LD_PRELOAD=preload,
        TSAN_OPTIONS=(
            # Mutex-misuse reports are off: gcc-10's libtsan
            # false-positives "double lock" on pthread_cond_wait
            # re-acquisition (and on uninstrumented Eigen pools in
            # jaxlib).  Data-race detection — what this stress is
            # for — stays fully on.
            "halt_on_error=1 exitcode=66 report_thread_leaks=0 "
            f"report_mutex_bugs=0 detect_deadlocks=0 suppressions={supp}"
        ),
        # Import gubernator_tpu without jax: TSan instruments every
        # malloc; the XLA runtime under TSan is noise we don't want.
        GUBERNATOR_TPU_X64="0",
        GUBERNATOR_TPU_COMPILE_CACHE="0",
    )
    server = subprocess.Popen(
        [sys.executable, "-c", _SERVER_SRC],
        cwd=REPO,
        env=server_env,
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        port_line = server.stdout.readline()
        assert port_line.startswith("PORT "), (
            f"server failed to start: {port_line!r}\n"
            + server.stderr.read()[-4000:]
        )
        port = int(port_line.split()[1])
        client = subprocess.run(
            [sys.executable, "-c", _CLIENT_SRC, str(port)],
            cwd=REPO,
            env=dict(env, GUBERNATOR_TPU_X64="0",
                     GUBERNATOR_TPU_COMPILE_CACHE="0"),
            capture_output=True,
            text=True,
            timeout=240,
        )
        assert client.returncode == 0, (
            f"client hammer failed rc={client.returncode}\n"
            f"{client.stdout[-1000:]}\n{client.stderr[-2000:]}"
        )
        out, err = server.communicate(input="", timeout=120)
    except Exception:
        server.kill()
        raise
    assert "ThreadSanitizer" not in err, (
        "TSan report from h2_server:\n" + err[-4000:]
    )
    assert server.returncode == 0, (
        f"san server failed rc={server.returncode}\n"
        f"stdout: {out[-2000:]}\nstderr: {err[-4000:]}"
    )
    assert "san stress ok" in out


# Decision-plane stress, PRELOADED: concurrent dp_try_serve lanes race
# install/pull/probe churn on a shared hot key — the coherence
# protocol's exact concurrency shape (conn threads drain while the
# Python tier pulls/re-delegates).  Admissions are conserved: every
# pulled `consumed` count plus the post-pull admissions must equal the
# lanes' observed total.
_PLANE_SRC = r"""
import ctypes, sys, threading
import numpy as np

from gubernator_tpu.core import native_plane

plane = native_plane.NativeDecisionPlane(disqualify_mask=0)
key = b"san_hot"
NOW = 1_000_000
N_LANES = 6
ITERS = 2000

# A tiny hand-rolled GetRateLimitsReq: name="san", unique_key="hot",
# hits=1, limit=1<<40, duration=60000 (avoids importing protobuf into
# the TSan'd process).
def enc_field(tag, wt, payload):
    return bytes([(tag << 3) | wt]) + payload
def varint(v):
    out = b""
    while v >= 0x80:
        out += bytes([(v & 0x7F) | 0x80]); v >>= 7
    return out + bytes([v])
item = (enc_field(1, 2, varint(3) + b"san") + enc_field(2, 2, varint(3) + b"hot")
        + enc_field(3, 0, varint(1)) + enc_field(4, 0, varint(1 << 40))
        + enc_field(5, 0, varint(60000)))
body = enc_field(1, 2, varint(len(item)) + item)

admitted = [0] * N_LANES
def lane(t):
    for _ in range(ITERS):
        if plane.try_serve(body, max_items=1, now_ms=NOW) is not None:
            admitted[t] += 1

def churn():
    # The Python tier's pull/re-install cycle racing the lanes.
    consumed_total = 0
    for i in range(400):
        res = plane.pull(key)
        if res is not None:
            consumed_total += res[1]
        plane.install_lease(key, 1 << 40, 60000, NOW + 60000,
                            1 << 40, 1 << 30, 0, NOW + 60000)
    return consumed_total

plane.install_lease(key, 1 << 40, 60000, NOW + 60000, 1 << 40, 1 << 30, 0, NOW + 60000)
threads = [threading.Thread(target=lane, args=(t,)) for t in range(N_LANES)]
for t in threads: t.start()
pulled = churn()
for t in threads: t.join()
res = plane.pull(key)
final = res[1] if res is not None else 0
total = sum(admitted)
assert total == pulled + final, (total, pulled, final)
plane.close()
print("plane san stress ok admitted=%d" % total, flush=True)
"""


# Columnar feeder stress, PRELOADED: C bench threads (true
# multi-producer claim/commit against the lock-free window cursor)
# race the serve thread's seal/rotate/recycle AND a Python window
# callback writing verdict lanes, then a mid-traffic flush and a
# drain-then-close teardown.  Row conservation is asserted: every
# packed row is either served or drained, never lost or duplicated.
_FEEDER_SRC = r"""
import threading
import numpy as np

from gubernator_tpu.core import native_plane

def enc_field(tag, wt, payload):
    return bytes([(tag << 3) | wt]) + payload
def varint(v):
    out = b""
    while v >= 0x80:
        out += bytes([(v & 0x7F) | 0x80]); v >>= 7
    return out + bytes([v])
items = b""
for i in range(4):
    k = ("hot%dxyz" % i).encode()
    item = (enc_field(1, 2, varint(3) + b"san") + enc_field(2, 2, varint(len(k)) + k)
            + enc_field(3, 0, varint(1)) + enc_field(4, 0, varint(100))
            + enc_field(5, 0, varint(60000)))
    items += enc_field(1, 2, varint(len(item)) + item)
body = items

served = [0]
def handler(slot, n_rows, n_rpcs, key_bytes):
    served[0] += n_rows
    slot.out_status[:n_rows] = 0
    slot.out_limit[:n_rows] = 100
    slot.out_remaining[:n_rows] = 99
    slot.out_reset[:n_rows] = 0
    slot.rpc_status[:n_rpcs] = 0
    return 0

feeder = native_plane.NativeColumnarFeeder(
    n_slots=3, max_rows=256, max_rpcs=64, flush_rows=64,
    window_s=0.0005, window_handler=handler,
)
# Phase 1: C-threaded multi-producer hammer (true parallel claims).
packed = feeder.bench_pack(body, 4, 1500, 4)
feeder.flush()
# Phase 2: Python threads interleave packs with flushes.
py_packed = [0] * 4
def pylane(t):
    for i in range(300):
        rc = feeder.pack(body)
        if rc > 0:
            py_packed[t] += rc
        if i % 50 == 0:
            feeder.flush()
threads = [threading.Thread(target=pylane, args=(t,)) for t in range(4)]
for t in threads: t.start()
for t in threads: t.join()
feeder.flush()
st = feeder.stats()
total = packed + sum(py_packed)
assert st["feeder_rows"] == total, (st, total)
assert served[0] == st["feeder_served_rows"]
# served_rows excludes sink-mode/drain windows; everything packed must
# be accounted as served once callbacks were attached the whole run.
assert st["feeder_served_rows"] == total, (st, total)
feeder.close()
print("feeder san stress ok rows=%d" % total, flush=True)
"""


@pytest.mark.slow
def test_columnar_feeder_threaded_stress_under_tsan():
    """TSan over the feeder's lock-free claim/commit/seal/recycle
    protocol — C producer threads, the serve thread, and the Python
    callback racing on one ring."""
    if os.environ.get("GUBER_NATIVE_SAN", "") in ("", "0"):
        pytest.skip("set GUBER_NATIVE_SAN=1 to run the TSan stress")
    preload = sanitizer_preload("thread")
    if preload is None:
        pytest.skip("libtsan not available from this toolchain")
    orig_san = os.environ.get("GUBER_NATIVE_SAN")
    os.environ["GUBER_NATIVE_SAN"] = "thread"
    try:
        so = ensure_built("h2_server")
    finally:
        if orig_san is None:
            os.environ.pop("GUBER_NATIVE_SAN", None)
        else:
            os.environ["GUBER_NATIVE_SAN"] = orig_san
    if so is None:
        pytest.skip("sanitized h2_server build failed (no g++?)")
    supp = REPO / "tests" / "tsan_suppressions.txt"
    proc = subprocess.run(
        [sys.executable, "-c", _FEEDER_SRC],
        cwd=REPO,
        env=dict(
            os.environ,
            GUBER_NATIVE_SAN="thread",
            LD_PRELOAD=preload,
            TSAN_OPTIONS=(
                "halt_on_error=1 exitcode=66 report_thread_leaks=0 "
                f"report_mutex_bugs=0 detect_deadlocks=0 suppressions={supp}"
            ),
            PYTHONMALLOC="malloc",
            GUBERNATOR_TPU_X64="0",
            GUBERNATOR_TPU_COMPILE_CACHE="0",
        ),
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert "ThreadSanitizer" not in proc.stderr, (
        "TSan report from columnar feeder:\n" + proc.stderr[-4000:]
    )
    assert proc.returncode == 0, (
        f"feeder san stress failed rc={proc.returncode}\n"
        f"stdout: {proc.stdout[-1000:]}\nstderr: {proc.stderr[-3000:]}"
    )
    assert "feeder san stress ok" in proc.stdout


@pytest.mark.slow
def test_decision_plane_threaded_stress_under_tsan():
    """TSan over the decision plane's install/probe/pull protocol —
    the exact lock shape the h2 connection threads and the ledger
    bridge exercise concurrently (round-8 harness, extended per the
    native-plane PR)."""
    if os.environ.get("GUBER_NATIVE_SAN", "") in ("", "0"):
        pytest.skip("set GUBER_NATIVE_SAN=1 to run the TSan stress")
    preload = sanitizer_preload("thread")
    if preload is None:
        pytest.skip("libtsan not available from this toolchain")
    orig_san = os.environ.get("GUBER_NATIVE_SAN")
    os.environ["GUBER_NATIVE_SAN"] = "thread"
    try:
        so = ensure_built("h2_server")
    finally:
        if orig_san is None:
            os.environ.pop("GUBER_NATIVE_SAN", None)
        else:
            os.environ["GUBER_NATIVE_SAN"] = orig_san
    if so is None:
        pytest.skip("sanitized h2_server build failed (no g++?)")
    supp = REPO / "tests" / "tsan_suppressions.txt"
    proc = subprocess.run(
        [sys.executable, "-c", _PLANE_SRC],
        cwd=REPO,
        env=dict(
            os.environ,
            GUBER_NATIVE_SAN="thread",
            LD_PRELOAD=preload,
            TSAN_OPTIONS=(
                "halt_on_error=1 exitcode=66 report_thread_leaks=0 "
                f"report_mutex_bugs=0 detect_deadlocks=0 suppressions={supp}"
            ),
            # pymalloc recycles the ctypes output buffers through its
            # own pools, invisible to TSan — a stale encode write then
            # pairs with a fresh buffer's memset in another thread as
            # a phantom race.  Raw malloc keeps the free/malloc
            # happens-before visible.
            PYTHONMALLOC="malloc",
            GUBERNATOR_TPU_X64="0",
            GUBERNATOR_TPU_COMPILE_CACHE="0",
        ),
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert "ThreadSanitizer" not in proc.stderr, (
        "TSan report from decision plane:\n" + proc.stderr[-4000:]
    )
    assert proc.returncode == 0, (
        f"plane san stress failed rc={proc.returncode}\n"
        f"stdout: {proc.stdout[-1000:]}\nstderr: {proc.stderr[-3000:]}"
    )
    assert "plane san stress ok" in proc.stdout

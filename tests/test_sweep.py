"""Windowed expiry sweep: device-side compaction + incremental cursor.

VERDICT r1 item 4: sweep host transfer must be O(freed), not
O(capacity), and incremental sweeps must cover the whole capacity over
successive calls — including non-power-of-two capacities whose tail
window clamps and overlaps."""

import numpy as np

from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.types import Algorithm, RateLimitReq, Status


def _fill(engine, n, duration, now_ms, name="sw"):
    reqs = [
        RateLimitReq(
            name=name,
            unique_key=f"{i}",
            hits=1,
            limit=10,
            duration=duration,
            algorithm=Algorithm.TOKEN_BUCKET,
        )
        for i in range(n)
    ]
    engine.get_rate_limits(reqs, now_ms=now_ms)


def test_full_sweep_reclaims_expired_only(frozen_clock):
    engine = DecisionEngine(capacity=1000, clock=frozen_clock)
    now = frozen_clock.now_ms()
    _fill(engine, 50, duration=1_000, now_ms=now, name="short")
    _fill(engine, 30, duration=1_000_000, now_ms=now, name="long")
    assert engine.cache_size() == 80
    assert engine.sweep(now_ms=now + 500) == 0
    freed = engine.sweep(now_ms=now + 2_000)
    assert freed == 50
    assert engine.cache_size() == 30


def test_windowed_sweep_covers_nonmultiple_capacity(frozen_clock):
    # capacity deliberately not a multiple of the window → the tail
    # window clamps and overlaps an already-swept range.
    engine = DecisionEngine(capacity=1000, clock=frozen_clock)
    engine.SWEEP_WINDOW = 256  # 1000 = 3×256 + 232
    now = frozen_clock.now_ms()
    _fill(engine, 900, duration=1_000, now_ms=now)
    freed = engine.sweep(now_ms=now + 2_000)
    assert freed == 900
    assert engine.cache_size() == 0


def test_incremental_sweep_cursor(frozen_clock):
    engine = DecisionEngine(capacity=1024, clock=frozen_clock)
    engine.SWEEP_WINDOW = 256
    now = frozen_clock.now_ms()
    _fill(engine, 1000, duration=1_000, now_ms=now)
    total = 0
    # 4 windows of 256 cover 1024; one window per call.
    for _ in range(4):
        total += engine.sweep(now_ms=now + 2_000, max_windows=1)
    assert total == 1000
    assert engine.cache_size() == 0


def test_swept_slot_is_reusable(frozen_clock):
    engine = DecisionEngine(capacity=64, clock=frozen_clock)
    now = frozen_clock.now_ms()
    _fill(engine, 60, duration=1_000, now_ms=now)
    engine.sweep(now_ms=now + 2_000)
    # New keys must intern into the reclaimed slots without eviction.
    ev_before = getattr(engine.table, "evictions", 0)
    _fill(engine, 60, duration=1_000, now_ms=now + 3_000, name="fresh")
    assert engine.cache_size() == 60
    assert getattr(engine.table, "evictions", 0) == ev_before
    # And the new buckets behave as fresh buckets.
    r = engine.get_rate_limits(
        [
            RateLimitReq(
                name="fresh", unique_key="0", hits=1, limit=10, duration=1_000
            )
        ],
        now_ms=now + 3_000,
    )[0]
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 8  # second hit on the fresh bucket


def test_sharded_sweep_windowed(frozen_clock):
    import jax

    from gubernator_tpu.parallel.mesh import make_mesh
    from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine

    mesh = make_mesh(jax.devices()[:4])
    engine = ShardedDecisionEngine(
        shard_capacity=512, mesh=mesh, clock=frozen_clock
    )
    engine.SWEEP_WINDOW = 128
    now = frozen_clock.now_ms()
    reqs = [
        RateLimitReq(name="shsw", unique_key=f"{i}", hits=1, limit=10, duration=1_000)
        for i in range(300)
    ]
    engine.get_rate_limits(reqs, now_ms=now)
    assert engine.sweep(now_ms=now + 500) == 0
    assert engine.sweep(now_ms=now + 2_000) == 300
    assert engine.cache_size() == 0

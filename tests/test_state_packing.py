"""Packed BucketState invariants (VERDICT r4 #6 — 48 B/slot layout).

The packings must be invisible at the API: decisions identical to the
scalar spec (covered by test_kernel_vs_spec), full-fidelity
export/load round-trips, correct behavior across the documented clamp
boundary (timestamps/durations beyond 2^43 ms), and the occupied-bit
clear leaving the rest of the meta word intact."""

import numpy as np
import pytest

from gubernator_tpu import Algorithm, RateLimitReq
from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.ops import bucket_kernel as bk


def test_state_is_48_bytes_per_slot():
    state = bk.make_state(64)
    per_slot = sum(a.dtype.itemsize for a in state)
    assert per_slot == 48, [f"{f}:{a.dtype}" for f, a in zip(state._fields, state)]


def test_pack_unpack_round_trip_host():
    rng = np.random.default_rng(3)
    n = 256
    logical = {
        "occupied": rng.integers(0, 2, n).astype(bool),
        "algo": rng.integers(0, 2, n),
        "status": rng.integers(0, 2, n),
        "t0": rng.integers(0, bk.TS_CLAMP_MAX, n),
        "invalid": rng.integers(0, bk.TS_CLAMP_MAX, n),
        "expire": rng.integers(0, bk.TS_CLAMP_MAX, n),
        "duration": rng.integers(0, bk.TS_CLAMP_MAX, n),
        "limit": rng.integers(-(2**62), 2**62, n),
        "remaining": rng.integers(-(2**62), 2**62, n),
        "remf_hi": rng.integers(-(2**31), 2**31, n).astype(np.int32),
        "remf_lo": rng.integers(0, 2**32, n).astype(np.uint32),
        "burst": rng.integers(-(2**62), 2**62, n),
    }
    packed = bk.pack_state_host(logical)

    class _S:
        pass

    s = _S()
    for f, a in packed.items():
        setattr(s, f, a)
    u = bk.unpack_state_host(s)
    np.testing.assert_array_equal(u["occupied"], logical["occupied"])
    np.testing.assert_array_equal(u["algo"], logical["algo"])
    np.testing.assert_array_equal(u["status"], logical["status"])
    for f in ("t0", "invalid", "expire", "duration", "limit", "burst"):
        np.testing.assert_array_equal(u[f], logical[f], err_msg=f)
    # Merged remaining: token lanes round-trip the int64; leaky lanes
    # round-trip the fixed-point words.
    tok = np.asarray(logical["algo"]) == 0
    np.testing.assert_array_equal(
        u["remaining"][tok], np.asarray(logical["remaining"])[tok]
    )
    np.testing.assert_array_equal(
        u["remf_hi"][~tok], logical["remf_hi"][~tok]
    )
    np.testing.assert_array_equal(
        u["remf_lo"][~tok], logical["remf_lo"][~tok]
    )


def test_timestamp_clamp_boundary():
    """Values beyond 2^43 ms clamp at encode (documented divergence);
    values inside the bound are exact."""
    logical = {
        "occupied": np.array([True, True]),
        "algo": np.array([0, 0]),
        "status": np.array([0, 0]),
        "t0": np.array([bk.TS_CLAMP_MAX, bk.TS_CLAMP_MAX + 12345]),
        "invalid": np.array([0, -5]),  # negatives clamp to 0
        "expire": np.array([17, 2**50]),
        "duration": np.array([3_600_000, 2**55]),
        "limit": np.array([10, 10]),
        "remaining": np.array([1, 1]),
        "remf_hi": np.zeros(2, np.int32),
        "remf_lo": np.zeros(2, np.uint32),
        "burst": np.array([0, 0]),
    }
    packed = bk.pack_state_host(logical)

    class _S:
        pass

    s = _S()
    for f, a in packed.items():
        setattr(s, f, a)
    u = bk.unpack_state_host(s)
    assert u["t0"].tolist() == [bk.TS_CLAMP_MAX, bk.TS_CLAMP_MAX]
    assert u["invalid"].tolist() == [0, 0]
    assert u["expire"].tolist() == [17, bk.TS_CLAMP_MAX]
    assert u["duration"].tolist() == [3_600_000, bk.TS_CLAMP_MAX]


def test_clear_preserves_other_meta_bits(frozen_clock):
    """Evicting a slot clears ONLY the occupied bit: the engine relies
    on liveness, but the packed t0/invalid hi words and algo/status
    bits must not be corrupted by the clear scatter."""
    import jax.numpy as jnp

    state = bk.make_state(8)
    meta_word = bk.pack_meta(
        jnp.asarray([True]), jnp.asarray([1]), jnp.asarray([1]),
        jnp.asarray([123 << 32], dtype=jnp.int64),
        jnp.asarray([77 << 32], dtype=jnp.int64),
    )
    meta = state.meta.at[3].set(meta_word[0])
    cleared = bk._clear_occupied_impl(meta, jnp.asarray([3], dtype=jnp.int32))
    w = int(cleared[3])
    assert (w & 1) == 0  # unoccupied
    assert bk.meta_algo(np.asarray([w]))[0] == 1
    assert bk.meta_status(np.asarray([w]))[0] == 1
    assert int(bk.meta_t0(np.asarray([w]), np.zeros(1, np.uint32))[0]) == (
        123 << 32
    )


def test_export_round_trip_through_engine(frozen_clock):
    """End to end: decisions → export_items → fresh engine load →
    identical follow-up decisions (the packing must be invisible)."""
    eng = DecisionEngine(capacity=64, clock=frozen_clock)
    reqs = [
        RateLimitReq(
            name="rt", unique_key=f"{i}k", hits=2, limit=11,
            duration=60_000,
            algorithm=(
                Algorithm.TOKEN_BUCKET if i % 2 == 0
                else Algorithm.LEAKY_BUCKET
            ),
        )
        for i in range(20)
    ]
    eng.get_rate_limits(reqs)
    items = list(eng.export_items())
    assert len(items) == 20

    class _Loader:
        def load(self):
            return iter(items)

        def save(self, it):
            pass

    eng2 = DecisionEngine(capacity=64, clock=frozen_clock)
    assert eng2.load(_Loader()) == 20
    r1 = eng.get_rate_limits(reqs)
    r2 = eng2.get_rate_limits(reqs)
    for a, b in zip(r1, r2):
        assert (a.status, a.remaining, a.reset_time) == (
            b.status, b.remaining, b.reset_time,
        )

"""Hot-key sketch: space-saving invariants + the windowed rate decay.

The decay tests drive an injected clock, pinning the demotion
contract the replication plane depends on (cluster/replication.py): a
key hot an hour ago must read ~0 in `top_rates()` even though its
cumulative count still ranks it in `top()`.
"""

import numpy as np

from gubernator_tpu.utils.hotkeys import SpaceSaving


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_space_saving_counts_and_error_bounds():
    ss = SpaceSaving(capacity=4)
    for i in range(8):
        ss.offer(f"k{i}".encode(), i + 1)
    top = ss.top(4)
    assert len(top) == 4
    # Every reported count over-estimates by at most its error bound.
    for _key, count, err in top:
        assert count >= 1
        assert err <= count
    assert ss.stats()["tracked"] == 4


def test_rate_reflects_current_window_only():
    clk = _Clock()
    ss = SpaceSaving(capacity=16, window_s=1.0, now=clk)
    ss.offer(b"hot", 500)
    assert ss.rate(b"hot") == 500.0
    # Next window: the previous window's mass decays with the elapsed
    # fraction of the new one.
    clk.t = 1.5
    assert 0 < ss.rate(b"hot") <= 500.0
    # Two windows later: a key nobody offers reads 0, cumulative count
    # untouched.
    clk.t = 3.0
    assert ss.rate(b"hot") == 0.0
    assert ss.top(1)[0][:2] == (b"hot", 500)


def test_top_rates_tracks_a_moving_zipf_hot_set():
    """Rotate the hot set across three windows; top_rates must follow
    the CURRENT hot keys while top() stays dominated by history."""
    clk = _Clock()
    rng = np.random.default_rng(3)
    ss = SpaceSaving(capacity=64, window_s=1.0, now=clk)
    phases = [b"alpha", b"beta", b"gamma"]
    for p, hot in enumerate(phases):
        clk.t = p * 2.0  # two windows apart: the old hot set decays out
        # Zipf-ish: the phase's hot key takes ~90% of offers.
        for _ in range(200):
            if rng.random() < 0.9:
                ss.offer(hot, 5)
            else:
                ss.offer(b"cold%d" % rng.integers(0, 20), 1)
        rates = ss.top_rates(3)
        assert rates[0][0] == hot, (p, rates)
        # Earlier phases' hot keys must have decayed out of the rate
        # ranking entirely.
        for earlier in phases[:p]:
            assert all(k != earlier or r < 1.0 for k, r, _l, _d in rates)
    # Cumulative top() still remembers phase 0's mass.
    assert b"alpha" in [k for k, _c, _e in ss.top(5)]


def test_rate_params_carry_last_limit_duration():
    clk = _Clock()
    ss = SpaceSaving(capacity=8, window_s=1.0, now=clk)
    ss.offer_many_params([(b"k", 10, 1000, 60_000)])
    (key, rate, limit, duration), = ss.top_rates(1)
    assert (key, limit, duration) == (b"k", 1000, 60_000)
    assert rate == 10.0
    # A params-less offer must not clobber the stored params.
    ss.offer(b"k", 3)
    (_k, _r, limit, duration), = ss.top_rates(1)
    assert (limit, duration) == (1000, 60_000)


def test_offer_columns_masks_ineligible_params():
    """offer_columns with a masked limit column (the service stamps 0
    for rows the lease algebra can't cover) must keep those keys'
    params at 0 so the promotion plane skips them."""
    clk = _Clock()
    ss = SpaceSaving(capacity=8, window_s=1.0, now=clk)
    keys = [b"aaa", b"bbb"]
    buf = np.frombuffer(b"".join(keys), dtype=np.uint8)
    offs = np.array([0, 3, 6], dtype=np.int64)
    ss.offer_columns(
        buf, offs, np.array([4, 4]),
        hashes=np.array([11, 22], dtype=np.uint64),
        limit=np.array([100, 0]), duration=np.array([60_000, 60_000]),
    )
    by_key = {k: (lim, dur) for k, _r, lim, dur in ss.top_rates(4)}
    assert by_key[b"aaa"] == (100, 60_000)
    # limit 0 is the "never promotable" stamp the replication plane
    # keys off; duration alone is inert.
    assert by_key[b"bbb"][0] == 0


def test_eviction_resets_window_counters():
    """A newcomer that evicts a counter inherits the cumulative error
    bound but NOT the old key's rate — rates carry no inherited
    error."""
    clk = _Clock()
    ss = SpaceSaving(capacity=2, window_s=1.0, now=clk)
    ss.offer(b"a", 10)
    ss.offer(b"b", 20)
    ss.offer(b"c", 1)  # evicts the min (a): inherits count 10
    top = {k: (c, e) for k, c, e in ss.top(2)}
    assert top[b"c"] == (11, 10)
    assert ss.rate(b"c") == 1.0  # window counter started fresh

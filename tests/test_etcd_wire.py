"""etcd discovery over the REAL etcd v3 gRPC wire protocol.

EtcdPool (protocol logic unchanged) drives EtcdWireClient — hand-rolled
stubs speaking /etcdserverpb.KV/Lease/Watch with etcd's published
message numbering — against MiniEtcdServer over real gRPC framing.
This closes VERDICT r4 missing #4 as far as this image allows: no etcd
binary exists here and there is no network egress to record a live
session, so the server side is a protocol-faithful reimplementation
(discovery/etcd_wire.py documents the supported subset).  Pointed at a
real cluster, EtcdWireClient emits the same bytes these tests pin.
"""

import json
import time

import pytest

from gubernator_tpu.discovery.etcd import EtcdPool
from gubernator_tpu.discovery.etcd_wire import (
    EtcdWireClient,
    MiniEtcdServer,
    prefix_range_end,
)


class _FakeDaemon:
    """Just enough daemon surface for EtcdPool."""

    def __init__(self, grpc_address: str):
        self._grpc = grpc_address
        self.updates = []

    def peer_info(self):
        from gubernator_tpu.types import PeerInfo

        return PeerInfo(
            grpc_address=self._grpc,
            http_address=self._grpc.replace("91", "92"),
            datacenter="dc-test",
        )

    def set_peers(self, peers):
        self.updates.append(list(peers))


class _Conf:
    etcd_key_prefix = "/test-gubernator/"
    etcd_endpoints = None
    etcd_advertise_address = ""
    etcd_data_center = ""


@pytest.fixture
def mini_etcd():
    server = MiniEtcdServer(sweep_interval=0.1).start()
    yield server
    server.stop()


def _pool(server, addr, **kw):
    client = EtcdWireClient(server.address)
    daemon = _FakeDaemon(addr)
    pool = EtcdPool(_Conf(), daemon, client=client, **kw)
    return pool, daemon, client


def test_prefix_range_end():
    assert prefix_range_end(b"/a/") == b"/a0"
    assert prefix_range_end(b"a\xff") == b"b"
    assert prefix_range_end(b"\xff\xff") == b"\x00"


def test_register_discover_and_watch(mini_etcd):
    pool_a, daemon_a, client_a = _pool(mini_etcd, "127.0.0.1:9101")
    pool_b, daemon_b, client_b = _pool(mini_etcd, "127.0.0.1:9102")
    try:
        pool_a.start()
        pool_b.start()
        # B registered after A started: A's watch must deliver B.
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if daemon_a.updates and len(daemon_a.updates[-1]) == 2:
                break
            time.sleep(0.05)
        got = {p.grpc_address for p in daemon_a.updates[-1]}
        assert got == {"127.0.0.1:9101", "127.0.0.1:9102"}
        # The registered value is the reference's JSON shape.
        values = [
            json.loads(v)
            for v, _meta in client_a.get_prefix("/test-gubernator/")
        ]
        assert {v["dc"] for v in values} == {"dc-test"}

        # Graceful close deletes the key; the other node observes it.
        pool_b.close()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if daemon_a.updates and len(daemon_a.updates[-1]) == 1:
                break
            time.sleep(0.05)
        assert {p.grpc_address for p in daemon_a.updates[-1]} == {
            "127.0.0.1:9101"
        }
    finally:
        pool_a.close()
        client_a.close()
        client_b.close()


def test_lease_expiry_removes_dead_peer(mini_etcd):
    """A crashed node (no keep-alives) must disappear when its lease
    TTL lapses — reference: etcd.go's 30s lease contract."""
    import gubernator_tpu.discovery.etcd as etcd_mod

    pool_a, daemon_a, client_a = _pool(mini_etcd, "127.0.0.1:9111")
    # Node B grants a SHORT lease and then never refreshes (simulated
    # crash: keep-alive interval far beyond the test).
    client_b = EtcdWireClient(mini_etcd.address)
    lease_b = client_b.lease(1)
    client_b.put(
        "/test-gubernator/127.0.0.1:9112",
        json.dumps({"grpc": "127.0.0.1:9112", "http": "", "dc": "x"}),
        lease=lease_b,
    )
    try:
        pool_a.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if daemon_a.updates and len(daemon_a.updates[-1]) == 2:
                break
            time.sleep(0.05)
        assert len(daemon_a.updates[-1]) == 2
        # Lease lapses; the DELETE event must shrink A's view.
        deadline = time.monotonic() + 6
        while time.monotonic() < deadline:
            if daemon_a.updates and len(daemon_a.updates[-1]) == 1:
                break
            time.sleep(0.05)
        assert {p.grpc_address for p in daemon_a.updates[-1]} == {
            "127.0.0.1:9111"
        }
    finally:
        pool_a.close()
        client_a.close()
        client_b.close()


def test_keepalive_sustains_lease(mini_etcd):
    client = EtcdWireClient(mini_etcd.address)
    lease = client.lease(1)
    client.put("/test-gubernator/k", "v", lease=lease)
    try:
        for _ in range(15):
            time.sleep(0.2)
            lease.refresh()
        assert [v for v, _ in client.get_prefix("/test-gubernator/")] == [
            b"v"
        ]
        lease.revoke()
        time.sleep(0.3)
        assert (
            list(client.get_prefix("/test-gubernator/")) == []
        ), "revoke must delete attached keys"
    finally:
        client.close()


def test_refresh_of_expired_lease_raises(mini_etcd):
    """Real etcd answers TTL=0 for an unknown/expired lease; the
    keep-alive loop turns that into re-registration (etcd.go:222-316)."""
    client = EtcdWireClient(mini_etcd.address)
    lease = client.lease(1)
    try:
        time.sleep(1.5)  # let the sweep revoke it
        with pytest.raises(RuntimeError):
            lease.refresh()
    finally:
        client.close()

"""ReadbackCombiner: stacked device→host transfers (core/readback.py).

Correctness contract: every ticket's fetch() returns exactly the bytes
its own dispatch produced, no matter how tickets interleave across
threads, shapes, or group boundaries; RPC count drops when callers
pipeline.
"""

import threading

import jax.numpy as jnp
import numpy as np

from gubernator_tpu.core.readback import MAX_GROUP, ReadbackCombiner


def _dev(arr):
    return jnp.asarray(arr)


def test_single_ticket_roundtrip():
    rc = ReadbackCombiner()
    a = np.arange(10, dtype=np.int32).reshape(2, 5)
    t = rc.register(_dev(a))
    np.testing.assert_array_equal(t.fetch(), a)
    assert rc.transfers == 1
    # Second fetch is cached, no new transfer.
    np.testing.assert_array_equal(t.fetch(), a)
    assert rc.transfers == 1


def test_pipelined_tickets_share_one_transfer():
    rc = ReadbackCombiner()
    arrs = [
        (np.arange(20, dtype=np.int32) * (i + 1)).reshape(4, 5)
        for i in range(6)
    ]
    tickets = [rc.register(_dev(a)) for a in arrs]
    # First fetch leads: everything outstanding rides one stacked RPC.
    np.testing.assert_array_equal(tickets[0].fetch(), arrs[0])
    assert rc.transfers == 1
    for t, a in zip(tickets, arrs):
        np.testing.assert_array_equal(t.fetch(), a)
    assert rc.transfers == 1
    assert rc.stacked == 6


def test_mixed_shapes_group_separately():
    rc = ReadbackCombiner()
    small = [np.full((2, 4), i, dtype=np.int32) for i in range(3)]
    big = [np.full((2, 8), 10 + i, dtype=np.int32) for i in range(3)]
    ts = [rc.register(_dev(a)) for a in small]
    tb = [rc.register(_dev(a)) for a in big]
    for t, a in zip(ts + tb, small + big):
        np.testing.assert_array_equal(t.fetch(), a)
    # One stacked transfer per shape class.
    assert rc.transfers == 2


def test_more_than_max_group_still_exact():
    rc = ReadbackCombiner()
    n = MAX_GROUP + 5
    arrs = [np.full((1, 8), i, dtype=np.int32) for i in range(n)]
    tickets = [rc.register(_dev(a)) for a in arrs]
    for t, a in zip(tickets, arrs):
        np.testing.assert_array_equal(t.fetch(), a)
    assert rc.transfers >= 2  # capped groups


def test_threaded_fetch_no_lost_tickets():
    rc = ReadbackCombiner()
    n = 24
    arrs = [np.full((3, 4), i, dtype=np.int32) for i in range(n)]
    tickets = [rc.register(_dev(a)) for a in arrs]
    errs = []

    def fetch_one(i):
        try:
            np.testing.assert_array_equal(tickets[i].fetch(), arrs[i])
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [
        threading.Thread(target=fetch_one, args=(i,)) for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs
    assert all(not t.is_alive() for t in threads)
    # Far fewer transfers than tickets (leaders covered followers).
    assert rc.transfers < n


def test_overflow_drains_fire_and_forget():
    import time

    rc = ReadbackCombiner()
    arrs = [np.full((2, 2), i, dtype=np.int32) for i in range(4 * MAX_GROUP + 8)]
    tickets = [rc.register(_dev(a)) for a in arrs]
    # The drain runs on a DETACHED thread (register must never block
    # behind a transfer — it is called under the engine lock); wait
    # for it to cover some early tickets.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not any(
        t.host is not None for t in tickets[:MAX_GROUP]
    ):
        time.sleep(0.01)
    assert any(t.host is not None for t in tickets[:MAX_GROUP])
    # And every ticket still fetches its own bytes.
    for t, a in zip(tickets, arrs):
        np.testing.assert_array_equal(t.fetch(), a)

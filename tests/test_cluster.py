"""Functional tests over a real in-process cluster.

Mirrors the reference's strategy (reference: functional_test.go:42-62 +
cluster/cluster.go): a module-scoped cluster of full daemons — each
with its own gRPC server, gateway, engine and managers — peer lists
injected directly, metrics endpoints used as the test oracle.
"""

import json
import time
import urllib.request

import grpc
import pytest

from gubernator_tpu.client import V1Client, random_string
from gubernator_tpu.cluster.harness import ClusterHarness
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    Status,
)

# 6 daemons in the default DC + 2 in datacenter-1 (the reference boots
# 6 + 4; two regional peers exercise the same paths faster).
DCS = [""] * 6 + ["datacenter-1"] * 2


@pytest.fixture(scope="module")
def cluster():
    h = ClusterHarness().start(len(DCS), datacenters=DCS)
    yield h
    h.stop()


def _metric_value(http_address: str, name: str, labels: str = "") -> float:
    """Scrape one metric series off a daemon's /metrics endpoint.

    reference: functional_test.go:1223-1248 (getMetric).
    """
    body = urllib.request.urlopen(
        f"http://{http_address}/metrics", timeout=5
    ).read().decode()
    want = name + (labels and "{" + labels + "}")
    for line in body.splitlines():
        if line.startswith("#"):
            continue
        if line.startswith(want + " ") or line.startswith(want + "{" if not labels else want):
            if labels and not line.startswith(want):
                continue
            try:
                return float(line.rsplit(" ", 1)[1])
            except ValueError:
                continue
    return 0.0


def _until(pred, timeout=5.0, interval=0.05):
    """reference: testutil.UntilPass."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ----------------------------------------------------------------------


def test_over_the_limit(cluster):
    """reference: functional_test.go:64-111 (TestOverTheLimit)."""
    with V1Client(cluster.peer_at(0).grpc_address) as c:
        key = random_string(prefix="otl_")
        for expect_status, expect_remaining in [
            (Status.UNDER_LIMIT, 1),
            (Status.UNDER_LIMIT, 0),
            (Status.OVER_LIMIT, 0),
        ]:
            rs = c.get_rate_limits(
                [
                    RateLimitReq(
                        name="test_over_limit",
                        unique_key=key,
                        algorithm=Algorithm.TOKEN_BUCKET,
                        duration=60_000,
                        limit=2,
                        hits=1,
                    )
                ],
                timeout=10,
            )
            assert rs[0].error == ""
            assert rs[0].status == expect_status
            assert rs[0].remaining == expect_remaining
            assert rs[0].limit == 2


def test_multiple_async(cluster):
    """Fan a batch across many owners in one request.

    reference: functional_test.go:113-157 (TestMultipleAsync).
    """
    reqs = [
        RateLimitReq(
            name=f"test_async_{i}",
            unique_key=random_string(prefix="async_"),
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=60_000,
            limit=10,
            hits=1,
        )
        for i in range(20)
    ]
    with V1Client(cluster.peer_at(1).grpc_address) as c:
        rs = c.get_rate_limits(reqs, timeout=10)
    assert len(rs) == 20
    for r in rs:
        assert r.error == ""
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 9


def test_missing_fields(cluster):
    """Per-item validation errors. reference: functional_test.go:737-798."""
    cases = [
        (RateLimitReq(name="exists", unique_key="", hits=1, limit=10), "field 'unique_key' cannot be empty"),
        (RateLimitReq(name="", unique_key="key", hits=1, limit=10), "field 'namespace' cannot be empty"),
    ]
    with V1Client(cluster.peer_at(0).grpc_address) as c:
        for req, want in cases:
            rs = c.get_rate_limits([req], timeout=10)
            assert rs[0].error == want
    # Valid-but-zero fields do not error (reference asserts empty error
    # for missing duration/limit).
    with V1Client(cluster.peer_at(0).grpc_address) as c:
        rs = c.get_rate_limits(
            [RateLimitReq(name="no_duration", unique_key=random_string(), hits=1, limit=5)],
            timeout=10,
        )
        assert rs[0].error == ""


def test_batch_too_large(cluster):
    """>1000 items is the one RPC-level error.

    reference: gubernator.go:212-216.
    """
    reqs = [
        RateLimitReq(name="big", unique_key=str(i), hits=1, limit=10, duration=60_000)
        for i in range(1001)
    ]
    with V1Client(cluster.peer_at(0).grpc_address) as c:
        with pytest.raises(grpc.RpcError) as exc:
            c.get_rate_limits(reqs, timeout=10)
        assert exc.value.code() == grpc.StatusCode.OUT_OF_RANGE


def test_batch_order_stability(cluster):
    """Responses are in request order at every batch size.

    reference: functional_test.go:1175-1221 (TestGetPeerRateLimits).
    """
    with V1Client(cluster.peer_at(2).grpc_address) as c:
        for n in (1, 13, 100, 1000):
            tag = random_string(prefix=f"order{n}_")
            reqs = [
                RateLimitReq(
                    name="test_order",
                    unique_key=f"{tag}{i}",
                    hits=0,
                    limit=100 + i,
                    duration=60_000,
                )
                for i in range(n)
            ]
            rs = c.get_rate_limits(reqs, timeout=30)
            assert len(rs) == n
            for i, r in enumerate(rs):
                assert r.error == ""
                assert r.limit == 100 + i, f"n={n} idx={i}"


def test_global_rate_limits(cluster):
    """GLOBAL: non-owner answers locally, hits flow to the owner
    asynchronously, owner broadcasts status to all peers.

    reference: functional_test.go:800-867 (TestGlobalRateLimits) — uses
    the prometheus metrics of specific daemons as the oracle.
    """
    key = random_string(prefix="global_")
    req = RateLimitReq(
        name="test_global",
        unique_key=key,
        algorithm=Algorithm.TOKEN_BUCKET,
        behavior=Behavior.GLOBAL,
        duration=60_000,
        limit=100,
        hits=1,
    )
    owner = cluster.owner_of(req.hash_key())
    non_owner = cluster.non_owner_of(req.hash_key())
    assert owner.grpc_address != non_owner.grpc_address

    with V1Client(non_owner.grpc_address) as c:
        rs = c.get_rate_limits([req], timeout=10)
        assert rs[0].error == ""
        assert rs[0].status == Status.UNDER_LIMIT
        assert rs[0].remaining == 99
        assert rs[0].metadata.get("owner") == owner.peer_info().grpc_address

    # Async hits reach the owner (non-owner's async send counter moves,
    # owner's broadcast counter moves).
    assert _until(
        lambda: _metric_value(
            non_owner.http_address, "gubernator_global_async_sends_total"
        )
        >= 1
    ), "async hit window never flushed"
    assert _until(
        lambda: _metric_value(
            owner.http_address, "gubernator_global_broadcasts_total"
        )
        >= 1
    ), "owner never broadcast"

    # After the broadcast every peer (owner included) must agree the
    # hit count: owner state shows 1 consumed hit.
    def owner_remaining_99():
        with V1Client(owner.grpc_address) as oc:
            r = oc.get_rate_limits(
                [
                    RateLimitReq(
                        name="test_global",
                        unique_key=key,
                        behavior=Behavior.GLOBAL,
                        duration=60_000,
                        limit=100,
                        hits=0,
                    )
                ],
                timeout=10,
            )[0]
            return r.remaining == 99
    assert _until(owner_remaining_99), "owner never applied the async hit"

    # A second non-owner answers from the broadcast cache.
    others = [
        d
        for d, dc in zip(cluster.daemons, DCS)
        if dc == ""
        and d.grpc_address
        not in (owner.grpc_address, non_owner.grpc_address)
    ]
    with V1Client(others[0].grpc_address) as c2:
        def cached_status():
            r = c2.get_rate_limits(
                [
                    RateLimitReq(
                        name="test_global",
                        unique_key=key,
                        behavior=Behavior.GLOBAL,
                        duration=60_000,
                        limit=100,
                        hits=0,
                    )
                ],
                timeout=10,
            )[0]
            return r.remaining == 99 and r.error == ""
        assert _until(cached_status), "broadcast status never cached on peers"


def test_grpc_gateway(cluster):
    """JSON contract: snake_case + unpopulated fields emitted.

    reference: functional_test.go:1158-1173 (TestGRPCGateway).
    """
    body = urllib.request.urlopen(
        f"http://{cluster.daemon_at(0).http_address}/v1/HealthCheck", timeout=5
    ).read().decode()
    assert "peer_count" in body
    hc = json.loads(body)
    assert hc["peer_count"] == len(DCS)

    # POST path round-trips snake_case fields and string int64s.
    data = json.dumps(
        {
            "requests": [
                {
                    "name": "gw",
                    "unique_key": random_string(),
                    "hits": "1",
                    "limit": "5",
                    "duration": "60000",
                }
            ]
        }
    ).encode()
    resp = json.loads(
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://{cluster.daemon_at(0).http_address}/v1/GetRateLimits",
                data=data,
                headers={"Content-Type": "application/json"},
            ),
            timeout=5,
        ).read()
    )
    assert resp["responses"][0]["status"] == "UNDER_LIMIT"
    assert resp["responses"][0]["remaining"] == "4"
    assert resp["responses"][0]["reset_time"] != "0"


def test_peer_rest_gateway(cluster):
    """Peer-service REST routes: grpc-gateway's unbound-method default
    paths (reference: peers.pb.gw.go)."""
    d = cluster.daemon_at(0)
    key = random_string(prefix="peerrest_")
    data = json.dumps(
        {
            "requests": [
                {
                    "name": "test_peer_rest",
                    "unique_key": key,
                    "hits": "2",
                    "limit": "9",
                    "duration": "60000",
                }
            ]
        }
    ).encode()
    resp = json.loads(
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://{d.http_address}/pb.gubernator.PeersV1/GetPeerRateLimits",
                data=data,
                headers={"Content-Type": "application/json"},
            ),
            timeout=5,
        ).read()
    )
    assert resp["rate_limits"][0]["status"] == "UNDER_LIMIT"
    assert resp["rate_limits"][0]["remaining"] == "7"

    # UpdatePeerGlobals installs a broadcast status readable via the
    # GLOBAL non-owner path.
    upd = json.dumps(
        {
            "globals": [
                {
                    "key": f"test_peer_rest_{key}",
                    "algorithm": "TOKEN_BUCKET",
                    "status": {
                        "status": "OVER_LIMIT",
                        "limit": "9",
                        "remaining": "0",
                        "reset_time": "99999999999999",
                    },
                }
            ]
        }
    ).encode()
    out = json.loads(
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://{d.http_address}/pb.gubernator.PeersV1/UpdatePeerGlobals",
                data=upd,
                headers={"Content-Type": "application/json"},
            ),
            timeout=5,
        ).read()
    )
    assert out == {}


def test_multi_region_queues(cluster):
    """MULTI_REGION hits are queued and windows flush."""
    req = RateLimitReq(
        name="test_mr",
        unique_key=random_string(prefix="mr_"),
        behavior=Behavior.MULTI_REGION,
        duration=60_000,
        limit=10,
        hits=1,
    )
    owner = cluster.owner_of(req.hash_key())
    with V1Client(owner.grpc_address) as c:
        rs = c.get_rate_limits([req], timeout=10)
        assert rs[0].error == ""
    assert _until(lambda: owner.instance.multi_region_mgr.windows >= 1)


def test_multi_region_hits_converge_across_dcs(cluster):
    """MULTI_REGION hits applied in one DC converge onto the key's
    owner in the OTHER DC (exceeds the reference, whose sendHits is an
    empty stub: multiregion.go:94-98).  Forwarded copies carry the
    flag cleared, so counts do not ping-pong back."""
    req = RateLimitReq(
        name="test_mr_conv",
        unique_key=random_string(prefix="mrc_"),
        behavior=Behavior.MULTI_REGION,
        duration=60_000,
        limit=100,
        hits=7,
    )
    # Apply in the default DC.
    owner = cluster.owner_of(req.hash_key())
    with V1Client(owner.grpc_address) as c:
        rs = c.get_rate_limits([req], timeout=10)
        assert rs[0].error == ""
        assert rs[0].remaining == 93
    assert _until(lambda: owner.instance.multi_region_mgr.region_sends >= 1)

    # The datacenter-1 owner of this key must eventually see the hits.
    dc1 = next(
        d
        for d, dc in zip(cluster.daemons, cluster._datacenters)
        if dc == "datacenter-1"
    )

    def dc1_remaining():
        query = RateLimitReq(
            name="test_mr_conv",
            unique_key=req.unique_key,
            duration=60_000,
            limit=100,
            hits=0,
        )
        with V1Client(dc1.grpc_address) as c:
            return c.get_rate_limits([query], timeout=10)[0].remaining

    assert _until(lambda: dc1_remaining() == 93), dc1_remaining()
    # ...and it stays there: no cross-DC amplification loop.
    time.sleep(0.3)
    assert dc1_remaining() == 93


def test_health_check_detects_dead_peer():
    """Kill a peer; forwarding to it must serve a DEGRADED local
    answer (flagged in metadata — the health plane's availability
    contract, RESILIENCE.md) and flip health of the reporting daemon
    to unhealthy; a cluster restart recovers.

    reference: functional_test.go:1037-1104 (TestHealthCheck) — the
    reference asserts an error string here; GUBER_DEGRADED_LOCAL=0
    restores that (tests/test_chaos.py pins the fail-closed mode).
    """
    h = ClusterHarness().start(3)
    try:
        # Find a key owned by daemon 2 as seen from daemon 0.
        owner_idx = None
        for attempt in range(200):
            key = random_string(prefix=f"hc{attempt}_")
            owner_addr = h.owner_of("test_health_" + key).grpc_address
            idxs = [
                i
                for i, d in enumerate(h.daemons)
                if d.grpc_address == owner_addr
            ]
            if idxs and idxs[0] != 0:
                owner_idx = idxs[0]
                break
        assert owner_idx is not None

        h.kill(owner_idx)
        with V1Client(h.peer_at(0).grpc_address) as c:
            rs = c.get_rate_limits(
                [
                    RateLimitReq(
                        name="test_health",
                        unique_key=key,
                        hits=1,
                        limit=5,
                        duration=60_000,
                    )
                ],
                timeout=15,
            )
            # The owner is dead, but the request still gets an answer
            # from the caller's own engine, flagged degraded.
            assert rs[0].error == ""
            assert rs[0].metadata.get("degraded") == "true"

            hc = c.health_check(timeout=10)
            assert hc.status == "unhealthy"
            assert "UNAVAILABLE" in hc.message or "connect" in hc.message.lower()

        h.restart(owner_idx)
        with V1Client(h.peer_at(owner_idx).grpc_address) as c:
            assert c.health_check(timeout=10).status == "healthy"
    finally:
        h.stop()


def test_cluster_token_bucket_frozen_clock():
    """Cluster-level token bucket against a shared frozen clock.

    reference: functional_test.go:159-218 (TestTokenBucket) — the
    algorithm tables run engine-level in test_algorithms.py; this
    verifies the frozen clock threads through daemon → service → engine.
    """
    from gubernator_tpu.clock import Clock

    clock = Clock().freeze()
    h = ClusterHarness().start(2, clock=clock)
    try:
        key = random_string(prefix="tb_")
        req = RateLimitReq(
            name="test_tb",
            unique_key=key,
            duration=5_000,
            limit=2,
            hits=1,
        )
        with V1Client(h.peer_at(0).grpc_address) as c:
            r1 = c.get_rate_limits([req], timeout=10)[0]
            assert (r1.status, r1.remaining) == (Status.UNDER_LIMIT, 1)
            reset1 = r1.reset_time
            r2 = c.get_rate_limits([req], timeout=10)[0]
            assert (r2.status, r2.remaining) == (Status.UNDER_LIMIT, 0)
            r3 = c.get_rate_limits([req], timeout=10)[0]
            assert r3.status == Status.OVER_LIMIT

            # Advance past the window: bucket resets.
            clock.advance(ms=6_000)
            r4 = c.get_rate_limits([req], timeout=10)[0]
            assert (r4.status, r4.remaining) == (Status.UNDER_LIMIT, 1)
            assert r4.reset_time > reset1
    finally:
        h.stop()

"""GLOBAL's eventually-consistent over-admission is BOUNDED
(VERDICT r3 #9; reference trade-off: architecture.md:46-74).

Worst case: with the broadcast fully lagged, every node's local copy
independently admits up to `limit` — total admitted <= n_nodes * limit.
One hits-forward + broadcast round trip converges the status cache,
after which non-owners reject from the cached OVER status and admit
nothing further.  Deterministic via GlobalManager.flush_now() and
effectively-infinite sync windows.
"""

import numpy as np

from gubernator_tpu.cluster.harness import ClusterHarness, cluster_behaviors
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.types import Behavior, RateLimitReq, Status


def _greq(key, hits=1, limit=50):
    return RateLimitReq(
        name="oa", unique_key=key, hits=hits, limit=limit,
        duration=3_600_000, behavior=int(Behavior.GLOBAL),
    )


def test_global_over_admission_bounded_and_converges(frozen_clock):
    # Windows that never fire on their own: the test drives every sync
    # explicitly, so the lag (and thus over-admission) is exact.
    # adaptive_windows=False — an adaptive window fires an idle
    # batcher immediately, which would forward hits/broadcasts mid-
    # phase and destroy the controlled lag this test measures.
    behaviors = BehaviorConfig(
        global_sync_wait=3600.0, global_batch_limit=10**9,
        batch_wait=cluster_behaviors().batch_wait,
        adaptive_windows=False,
    )
    h = ClusterHarness().start(
        2, clock=frozen_clock, behaviors=behaviors, cache_size=4096
    )
    try:
        limit = 50
        inst0 = h.daemon_at(0).instance
        inst1 = h.daemon_at(1).instance
        # A key owned by node 1 (so node 0 is the non-owner).
        key = next(
            f"{i}k" for i in range(500)
            if not inst0.get_peer(_greq(f"{i}k").hash_key()).info.is_owner
        )

        def admitted(inst, n):
            count = 0
            for _ in range(n):
                r = inst.get_rate_limits([_greq(key, limit=limit)])[0]
                assert r.error == ""
                if r.status == Status.UNDER_LIMIT:
                    count += 1
            return count

        # Phase 1 — broadcast fully lagged: each node's local copy
        # admits EXACTLY `limit`, so the cluster-wide worst case is
        # n_nodes * limit, not unbounded.
        a0 = admitted(inst0, 2 * limit)  # non-owner local-miss copies
        a1 = admitted(inst1, 2 * limit)  # owner authoritative
        assert a0 == limit, f"non-owner admitted {a0}, bound {limit}"
        assert a1 == limit, f"owner admitted {a1}, bound {limit}"
        assert inst0.counters["global_miss_local"] >= 2 * limit

        # Phase 2 — one explicit sync round: non-owner forwards its
        # aggregated hits, the owner broadcasts authoritative status.
        inst0.global_mgr.flush_now()  # hits → owner
        inst1.global_mgr.flush_now()  # broadcast → caches
        # The owner saw its own 100 hits + the forwarded 100: hard over
        # limit; its broadcast status must be OVER with remaining 0.

        # Phase 3 — converged: the non-owner now answers OVER from the
        # cache and admits NOTHING further.
        a0_post = admitted(inst0, 50)
        assert a0_post == 0, f"post-convergence admits: {a0_post}"
        # And the responses come from the cache, not local copies.
        before = inst0.counters["global_miss_local"]
        admitted(inst0, 20)
        assert inst0.counters["global_miss_local"] == before
    finally:
        h.stop()

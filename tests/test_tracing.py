"""Hot-path tracing: span structure with the in-memory recorder.

VERDICT r2 item 5 — the reference weaves spans through every function
(reference: gubernator.go:198-202, algorithms.go:32-44); our spans
cover the serving entry points, engine batches/rounds, peer batch
flushes, GLOBAL windows, and sweeps, each with batch/round attributes.
Disabled tracing must stay a no-op (no recorder, no spans).
"""

import numpy as np
import pytest

from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.types import Algorithm, RateLimitReq
from gubernator_tpu.utils.tracing import (
    InMemoryTracer,
    current_tracer,
    set_tracer,
    span,
)


@pytest.fixture
def tracer():
    t = InMemoryTracer()
    set_tracer(t)
    yield t
    set_tracer(None)


def req(key, hits=1, **kw):
    return RateLimitReq(
        name="trace", unique_key=key, hits=hits, limit=10,
        duration=60_000, **kw,
    )


def test_disabled_tracing_is_noop():
    set_tracer(None)
    with span("anything", batch=1) as s:
        assert s is None
    assert current_tracer() is None


def test_engine_batch_and_round_spans(frozen_clock, tracer):
    eng = DecisionEngine(capacity=256, clock=frozen_clock)
    # 3 distinct keys + one duplicated twice: hot-key batches normally
    # collapse to one dispatch; force the rounds path to trace rounds.
    eng._collapse_dataclass = lambda *a, **k: False
    eng.get_rate_limits([req("a"), req("b"), req("a"), req("c")])

    batches = tracer.spans("engine.batch")
    assert len(batches) == 1
    assert batches[0].attributes == {"batch": 4, "rounds": 2}

    rounds = tracer.spans("engine.round")
    assert [s.attributes["round"] for s in rounds] == [0, 1]
    assert rounds[0].attributes["width"] == 3
    assert rounds[1].attributes["width"] == 1
    # Nesting: rounds are children of the batch span.
    assert all(s.parent == "engine.batch" for s in rounds)
    # Spans carry real durations.
    assert all(s.end_ns > s.start_ns for s in rounds)


def test_engine_collapsed_span(frozen_clock, tracer):
    eng = DecisionEngine(capacity=256, clock=frozen_clock)
    eng.get_rate_limits([req("a"), req("b"), req("a"), req("c")])
    collapsed = tracer.spans("engine.collapsed")
    assert len(collapsed) == 1
    assert collapsed[0].attributes == {"width": 4}
    assert collapsed[0].parent == "engine.batch"


def test_columnar_and_sweep_spans(frozen_clock, tracer):
    eng = DecisionEngine(capacity=256, clock=frozen_clock)
    n = 8
    eng.apply_columnar(
        [b"col%d" % i for i in range(n)],
        np.zeros(n, dtype=np.int32),
        np.zeros(n, dtype=np.int32),
        np.ones(n, dtype=np.int64),
        np.full(n, 10, dtype=np.int64),
        np.full(n, 1_000, dtype=np.int64),
        np.zeros(n, dtype=np.int64),
    )
    cols = tracer.spans("engine.columnar")
    assert len(cols) == 1 and cols[0].attributes["batch"] == n

    frozen_clock.advance(ms=5_000)
    freed = eng.sweep()
    assert freed == n
    sweeps = tracer.spans("engine.sweep")
    assert len(sweeps) == 1 and sweeps[0].attributes["freed"] == n


def test_sharded_engine_spans(frozen_clock, tracer):
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 virtual devices")
    from gubernator_tpu.parallel.mesh import make_mesh
    from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine

    eng = ShardedDecisionEngine(
        shard_capacity=128,
        mesh=make_mesh(jax.devices()[:2]),
        clock=frozen_clock,
    )
    eng.get_rate_limits([req("sa"), req("sb"), req("sa")])
    batches = tracer.spans("engine.batch")
    assert len(batches) == 1
    assert batches[0].attributes["batch"] == 3
    assert batches[0].attributes["rounds"] == 2
    # Hot-key duplicates collapse into one traced dispatch.
    assert len(tracer.spans("engine.collapsed")) == 1
    # Forcing the fallback traces per-round spans.
    tracer.clear()
    eng._collapse_dataclass_sharded = lambda *a, **k: False
    eng.get_rate_limits([req("sa2"), req("sb2"), req("sa2")])
    assert len(tracer.spans("engine.round")) == 2


def test_cluster_peer_flush_and_global_spans(frozen_clock, tracer):
    """Drive a 2-node in-process cluster: forwarded traffic must emit
    peer.flush spans; GLOBAL traffic must emit hits/broadcast windows
    (metrics-as-oracle analog of functional_test.go:843-867)."""
    import time

    from gubernator_tpu.cluster.harness import ClusterHarness
    from gubernator_tpu.types import Behavior

    h = ClusterHarness().start(2, cache_size=1024)
    try:
        inst = h.daemon_at(0).instance
        # Keys owned by the OTHER node.  A multi-item forward group
        # rides the unary batch RPC (peer.batch_rpc); a single item
        # rides the 500µs batcher (peer.flush).
        # The reference-exact ring can be lumpy for 2 members and the
        # arcs depend on the ephemeral ports; scan until enough
        # remotely-owned keys turn up.
        fwd = [
            req(f"{i}fwd")
            for i in range(2000)
            if not inst.get_peer(req(f"{i}fwd").hash_key()).info.is_owner
        ][:3]
        assert len(fwd) >= 3, "expected remotely-owned keys"
        inst.get_rate_limits(fwd[:3])
        # Order-independent: ANY batch_rpc span of width 3 qualifies
        # (background windows may interleave spans under suite load).
        rpc = tracer.spans("peer.batch_rpc")
        assert any(
            s.attributes["batch"] == 3 and s.attributes["peer"] for s in rpc
        ), rpc

        inst.get_rate_limits(fwd[:1])  # single item → batcher window
        # The flush span is recorded on the flusher thread just after
        # the response futures resolve; poll generously — the full
        # suite saturates this one-core host and flusher threads can
        # starve for tens of seconds.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not tracer.spans("peer.flush"):
            time.sleep(0.02)
        assert tracer.spans("peer.flush"), "forwarding did not trace a flush"
        assert any(
            s.attributes["batch"] >= 1 and s.attributes["peer"]
            for s in tracer.spans("peer.flush")
        )

        # GLOBAL behavior → async hits window (+ broadcast on owner).
        g = [
            req(f"{i}g", behavior=Behavior.GLOBAL)
            for i in range(2000)
            if not inst.get_peer(req(f"{i}g").hash_key()).info.is_owner
        ][:3]
        assert g
        inst.get_rate_limits(g)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not (
            tracer.spans("global.hits_window")
            and tracer.spans("global.broadcast")
        ):
            time.sleep(0.05)
        assert tracer.spans("global.hits_window")
        assert tracer.spans("global.broadcast")
        assert any(
            s.attributes["keys"] >= 1
            for s in tracer.spans("global.hits_window")
        )
    finally:
        h.stop()

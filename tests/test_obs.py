"""Fleet observability plane (ISSUE 15): cluster rollup merge, the
SLO/invariant watchdog, admission-bound headroom, and metric→trace
exemplars.

The acceptance invariants, pinned:

- the rollup merges counters by SUM (per region + fleet-wide) and
  histograms bucket-for-bucket, so fleet quantiles are REAL
  quantiles — a merged p99 must land where the union of observations
  puts it, not at the mean of per-node p99s;
- DurationStat's merge paths stay exact under concurrent observers;
- the admission watch counts ADMITTED hits per duration window and
  re-arms on window rollover (headroom recovers);
- the watchdog burns on bad-fraction growth, breaches only when both
  windows of a pair exceed the factor, and derives the N×limit bound
  from the cluster topology;
- /debug/fleet, /debug/slo, /metrics?fleet=1 and the ObsSnapshot RPC
  serve live data end-to-end on a real cluster;
- histogram-bucket exemplars capture only under an active sampled
  span, export via OpenMetrics, and NEVER dangle past the tracer's
  deque bound.
"""

import json
import threading
import time
import urllib.request

import pytest

from gubernator_tpu.cluster.harness import ClusterHarness
from gubernator_tpu.obs.fleet import FleetCollector
from gubernator_tpu.obs.slo import (
    SLI,
    AdmissionWatch,
    SLOWatchdog,
)
from gubernator_tpu.types import RateLimitReq
from gubernator_tpu.utils.metrics import DurationStat
from gubernator_tpu.utils.tracing import InMemoryTracer, set_tracer


@pytest.fixture
def tracer():
    t = InMemoryTracer()
    set_tracer(t)
    yield t
    set_tracer(None)


def _get_json(addr: str, path: str) -> dict:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=10) as r:
        return json.loads(r.read())


def _req(name, key, hits=1, limit=1_000_000, duration=60_000, behavior=0):
    return RateLimitReq(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=duration, behavior=behavior,
    )


# ----------------------------------------------------------------------
# The merge: counters sum, quantiles are real (not means-of-means).


def _snap(addr, region, counters=None, hists=None, admitted=None):
    return {
        "v": 1, "addr": addr, "region": region,
        "counters": counters or {}, "gauges": {},
        "hists": hists or {}, "admitted": admitted or {},
    }


def _hist_of(observations):
    d = DurationStat()
    for s in observations:
        d.observe(s)
    return d.bucket_snapshot()


def test_fleet_merge_sums_counters_per_region():
    merged = FleetCollector.merge(
        [
            _snap("a:1", "east", {"checks": 10, "check_errors": 1}),
            _snap("a:2", "east", {"checks": 20}),
            _snap("b:1", "west", {"checks": 5, "check_errors": 2}),
        ]
    )
    assert merged["counters"]["checks"] == 35
    assert merged["counters"]["check_errors"] == 3
    assert merged["regions"]["east"]["nodes"] == 2
    assert merged["regions"]["east"]["counters"]["checks"] == 30
    assert merged["regions"]["west"]["counters"]["checks"] == 5
    assert len(merged["nodes"]) == 3


def test_fleet_merge_quantiles_are_histogram_merged_not_means():
    # Node A: 99 fast observations (1ms).  Node B: 99 slow (512ms).
    # The TRUE merged p99 sits in the slow octave; the mean of the
    # per-node p99s (~256ms) and the mean of means would both lie in
    # the gap between the modes.  Merge must find the slow octave.
    fast = _hist_of([0.001] * 99)
    slow = _hist_of([0.512] * 99)
    merged = FleetCollector.merge(
        [
            _snap("a:1", "", hists={"window_wait": fast}),
            _snap("b:1", "", hists={"window_wait": slow}),
        ]
    )
    q = merged["quantiles"]["window_wait"]
    assert q["count"] == 198
    # p50 in the fast octave, p99 in the slow one — only a real
    # histogram merge produces this shape.
    assert 0.5 < q["p50_ms"] < 2.0
    assert 250.0 < q["p99_ms"] < 1100.0
    # The merged mean is the exact pooled mean, not a midpoint guess.
    assert abs(q["mean_ms"] - (99 * 1.0 + 99 * 512.0) / 198) < 30.0


def test_duration_stat_merge_snapshot_exact():
    a, b = DurationStat(), DurationStat()
    for s in (0.001, 0.002, 0.1):
        a.observe(s)
    for s in (0.0005, 0.25):
        b.observe(s)
    m = DurationStat()
    m.merge_snapshot(a.bucket_snapshot())
    m.merge_snapshot(b.bucket_snapshot())
    assert m.count == 5
    assert abs(m.total - (0.001 + 0.002 + 0.1 + 0.0005 + 0.25)) < 1e-12
    assert m.max == 0.25
    assert sum(m.buckets) == 5


def test_observe_bucket_counts_concurrent_observers():
    """The collector's pre-bucketed merge and direct observes racing
    must conserve every event (the satellite's concurrency pin)."""
    stat = DurationStat()
    n_threads, per_thread = 8, 200
    counts = [0] * DurationStat.N_BUCKETS
    counts[DurationStat.bucket_of(0.004)] = 3
    counts[DurationStat.bucket_of(0.512)] = 2
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        barrier.wait()
        for i in range(per_thread):
            if (tid + i) % 2:
                stat.observe_bucket_counts(counts)
            else:
                stat.observe(0.001)

    threads = [
        threading.Thread(target=worker, args=(t,))
        for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    merges = sum(
        1 for t in range(n_threads) for i in range(per_thread)
        if (t + i) % 2
    )
    observes = n_threads * per_thread - merges
    assert stat.count == merges * 5 + observes
    assert sum(stat.buckets) == stat.count
    assert stat.buckets[DurationStat.bucket_of(0.001)] == observes
    assert stat.buckets[DurationStat.bucket_of(0.004)] == merges * 3
    assert stat.buckets[DurationStat.bucket_of(0.512)] == merges * 2


# ----------------------------------------------------------------------
# Exemplars: capture, export, and the deque-bound pruning contract.


def test_exemplar_capture_requires_active_span(tracer):
    from gubernator_tpu.utils.tracing import span

    stat = DurationStat()
    stat.observe(0.002)  # no span open -> no exemplar
    assert stat.exemplar_snapshot() == {}
    with span("obs.test_root"):
        stat.observe(0.002)
    exs = stat.exemplar_snapshot()
    b = DurationStat.bucket_of(0.002)
    assert b in exs
    tid, val = exs[b]
    assert len(tid) == 32 and val == 0.002
    assert tracer.has_trace(tid)


def test_exemplar_survives_scrape_while_span_open(tracer):
    """An exemplar is captured while its span is still OPEN; a scrape
    racing the span's finish must not prune it (open spans hold a
    trace ref — the review-round fix)."""
    from gubernator_tpu.utils.tracing import span

    stat = DurationStat()
    with span("obs.test_open_root"):
        stat.observe(0.002)
        # Scrape BEFORE the span finishes: nothing of this trace is
        # in the finished deque yet, but the trace is live.
        exs = stat.exemplar_snapshot()
        b = DurationStat.bucket_of(0.002)
        assert b in exs, "exemplar pruned while its span was open"
        assert tracer.has_trace(exs[b][0])
    # And it still links after the finish lands in the deque.
    assert DurationStat.bucket_of(0.002) in stat.exemplar_snapshot()


def test_exemplar_disabled_without_tracer():
    set_tracer(None)
    stat = DurationStat()
    stat.observe(0.002)
    assert stat.exemplars == {}


def test_exemplar_pruned_at_tracer_deque_bound():
    """Evicting a trace from the bounded deque must not leave a
    dangling exemplar trace_id (the satellite's retention pin)."""
    from gubernator_tpu.utils.tracing import span

    t = InMemoryTracer(max_spans=4)
    set_tracer(t)
    try:
        stat = DurationStat()
        with span("obs.test_exemplar_root"):
            stat.observe(0.002)
        (tid, _v) = stat.exemplar_snapshot()[
            DurationStat.bucket_of(0.002)
        ]
        assert t.has_trace(tid)
        # Roll the deque over: 4 fresh spans evict the exemplar's.
        for _ in range(4):
            with span("obs.test_filler"):
                pass
        assert not t.has_trace(tid)
        assert stat.exemplar_snapshot() == {}
        # Pruned from the retained table too, not just the view.
        assert DurationStat.bucket_of(0.002) not in stat.exemplars
    finally:
        set_tracer(None)


def test_tracer_refcount_survives_clear_and_multi_span(tracer):
    from gubernator_tpu.utils.tracing import span

    with span("obs.test_outer"):
        with span("obs.test_inner"):
            pass
    tid = tracer.spans("obs.test_outer")[0].trace_id
    assert tracer.has_trace(tid)
    tracer.clear()
    assert not tracer.has_trace(tid)


# ----------------------------------------------------------------------
# AdmissionWatch: windowed admitted counts.


def test_admission_watch_counts_and_window_reset():
    aw = AdmissionWatch()
    assert not aw.active
    assert aw.watch("t_k1", limit=10)
    assert aw.active

    class R:
        def __init__(self, status, reset_time, error=""):
            self.status = status
            self.reset_time = reset_time
            self.error = error

    reqs = [_req("t", "k1", hits=3, limit=10)]
    aw.observe_batch(reqs, [R(0, 1000)])
    aw.observe_batch(reqs, [R(0, 1000)])
    aw.observe_batch(reqs, [R(1, 1000)])  # OVER: not admitted
    snap = aw.snapshot()["t_k1"]
    assert snap["admitted"] == 6 and snap["limit"] == 10
    # reset_time advances -> NEW window -> the count re-arms.
    aw.observe_batch(reqs, [R(0, 61_000)])
    snap = aw.snapshot()["t_k1"]
    assert snap["admitted"] == 3 and snap["reset_time"] == 61_000
    aw.unwatch("t_k1")
    assert not aw.active


def test_admission_watch_columns_route():
    import numpy as np

    aw = AdmissionWatch()
    aw.watch("t_k2")
    aw.observe_columns(
        ["t_k2", "t_other"],
        np.asarray([4, 9]),
        (
            np.asarray([0, 0]),        # status
            np.asarray([10, 10]),      # limit
            np.asarray([6, 1]),        # remaining
            np.asarray([5000, 5000]),  # reset
        ),
    )
    snap = aw.snapshot()
    assert snap["t_k2"]["admitted"] == 4
    assert "t_other" not in snap


# ----------------------------------------------------------------------
# Watchdog: burn rates, breach pairing, bound derivation.


class _StubFleet:
    def __init__(self, rollups):
        self.rollups = list(rollups)

    def collect(self, peers=True):
        return self.rollups.pop(0)


def _rollup(checks, errors, regions=("",), nodes=1, admitted=None):
    return {
        "nodes": [{"addr": f"n{i}", "region": regions[i % len(regions)]}
                  for i in range(nodes)],
        "regions": {r: {"nodes": 1, "counters": {}} for r in regions},
        "counters": {"checks": checks, "check_errors": errors},
        "gauges": {},
        "quantiles": {},
        "admitted": admitted or {},
    }


def test_watchdog_burn_and_breach_needs_both_windows():
    wd = SLOWatchdog(
        _StubFleet([]), None, interval=0,
        slis=(
            SLI(
                name="error_rate",
                metric="gubernator_check_error_counter",
                kind="ratio", bad="check_errors", total="checks",
                objective=0.999,
            ),
        ),
        fast_windows=(0.01, 0.02), slow_windows=(0.05, 0.1),
        # The slow pair is deliberately un-trippable here: this test
        # pins the FAST pair's arc (breach, then decay); t2's slow
        # windows still see t0's error burst by design.
        fast_factor=2.0, slow_factor=1e9,
    )
    try:
        wd.evaluate(_rollup(1000, 0))
        time.sleep(0.03)
        # 50% of the window's traffic errored: burn = 0.5/0.001 >> 2
        # on BOTH fast windows -> breach.
        out = wd.evaluate(_rollup(1200, 100))
        burns = out["slis"]
        assert any(
            k.startswith("error_rate@fast") and v > 2.0
            for k, v in burns.items()
        )
        assert any(b["sli"] == "error_rate" for b in out["breaches"])
        # A short-window blip alone must NOT breach: fresh watchdog,
        # errors only in a sample newer than the long window's span
        # is impossible to fake here (both windows share history), so
        # instead pin the recovery: burns decay once errors stop.
        time.sleep(0.03)
        out2 = wd.evaluate(_rollup(2400, 100))
        fast_short = [
            v for k, v in out2["slis"].items()
            if k.startswith("error_rate@fast_0.01")
        ][0]
        assert fast_short < 2.0  # no new errors in the fast window
        assert not any(
            b["sli"] == "error_rate" for b in out2["breaches"]
        )
    finally:
        wd.close()


def test_watchdog_derives_region_bound_and_headroom():
    wd = SLOWatchdog(_StubFleet([]), None, interval=0)
    try:
        out = wd.evaluate(
            _rollup(
                100, 0, regions=("east", "west"), nodes=4,
                admitted={
                    "xr_canary": {"admitted": 70, "limit": 40,
                                  "nodes": 2},
                },
            )
        )
        hr = out["headroom"]["xr_canary"]
        # 2 regions x limit 40 = bound 80; admitted 70 -> headroom 10.
        assert hr["bound"] == "2_regions_x_40"
        assert hr["headroom"] == 10.0
        snap = wd.metrics_snapshot()
        assert snap["headroom"][("xr_canary", "2_regions_x_40")] == 10.0
        # Single-region topology falls back to the N_nodes bound.
        out = wd.evaluate(
            _rollup(
                100, 0, regions=("",), nodes=3,
                admitted={"k": {"admitted": 0, "limit": 10,
                                "nodes": 3}},
            )
        )
        assert out["headroom"]["k"]["bound"] == "3_nodes_x_10"
    finally:
        wd.close()


def test_watchdog_unwindowed_skips_history_backed_slis():
    """/debug/fleet on a local-scope watchdog evaluates the fleet
    rollup with windowed=False: ratio/drops burns (which would
    difference a fleet rollup against local-slice history — other
    nodes' lifetime totals masquerading as window traffic) are
    skipped; quantile + invariant SLIs still evaluate."""
    wd = SLOWatchdog(
        _StubFleet([]), None, interval=0,
        fast_windows=(0.01, 0.02), slow_windows=(0.05, 0.1),
    )
    try:
        wd.evaluate(_rollup(1000, 0))  # local-slice history sample
        fleet_rollup = _rollup(
            50_000, 5_000,  # "fleet" totals >> the local history
            regions=("east", "west"), nodes=4,
            admitted={"k": {"admitted": 10, "limit": 40, "nodes": 2}},
        )
        fleet_rollup["quantiles"] = {
            "window_wait": {"count": 10, "p50_ms": 1.0, "p99_ms": 9.0}
        }
        out = wd.evaluate(fleet_rollup, record=False, windowed=False)
        assert not any(
            k.startswith(("error_rate@", "ring_drops@"))
            for k in out["slis"]
        ), out["slis"]
        assert not out["breaches"]
        assert any(
            k.startswith("window_wait_p99@") for k in out["slis"]
        )
        assert out["headroom"]["k"]["headroom"] == 70.0
    finally:
        wd.close()


def test_watchdog_status_shape():
    wd = SLOWatchdog(_StubFleet([]), None, interval=0)
    try:
        wd.evaluate(_rollup(10, 0))
        st = wd.status()
        assert st["enabled"]
        assert {"pairs", "slis", "burn", "headroom", "breaches",
                "samples"} <= set(st)
        assert any(s["name"] == "admission_bound" for s in st["slis"])
    finally:
        wd.close()


# ----------------------------------------------------------------------
# End to end on a real cluster.


def test_fleet_rollup_end_to_end(monkeypatch):
    monkeypatch.setenv("GUBER_SLO_INTERVAL", "0.2")
    monkeypatch.setenv("GUBER_SLO_FLEET", "1")
    monkeypatch.setenv("GUBER_SLO_FAST_WINDOWS", "0.5,1")
    h = ClusterHarness().start(2, cache_size=1024)
    try:
        inst = h.daemon_at(0).instance
        inst.get_rate_limits(
            [_req("fleet", f"k{i}", hits=2) for i in range(8)]
        )
        addr = h.daemon_at(0).http_address
        fleet = _get_json(addr, "/debug/fleet")
        assert fleet["enabled"]
        assert len(fleet["nodes"]) == 2
        assert fleet["scrape"]["ok"] == 2
        assert fleet["counters"]["checks"] >= 8
        assert "engine_serve" in fleet["quantiles"]
        assert {"count", "p50_ms", "p99_ms"} <= set(
            fleet["quantiles"]["engine_serve"]
        )
        assert "slo" in fleet  # the on-demand evaluation rides along
        # The watchdog thread has ticked: /debug/slo carries samples.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            slo = _get_json(addr, "/debug/slo")
            if slo.get("samples", 0) >= 1:
                break
            time.sleep(0.05)
        assert slo["enabled"] and slo["samples"] >= 1
        assert any(k.startswith("error_rate@") for k in slo["burn"])
        # The scrape surfaces: fleet families + SLO gauges + the raw
        # stage histograms on one /metrics?fleet=1 answer.
        with urllib.request.urlopen(
            f"http://{addr}/metrics?fleet=1", timeout=10
        ) as r:
            text = r.read().decode()
        assert "gubernator_fleet_counter" in text
        assert "gubernator_fleet_stage_quantile_seconds" in text
        assert "gubernator_slo_burn_rate" in text
        assert "gubernator_stage_seconds_bucket" in text
    finally:
        h.stop()


def test_obs_snapshot_rpc_and_disabled_shape(monkeypatch):
    monkeypatch.setenv("GUBER_OBS", "0")
    h = ClusterHarness().start(1, cache_size=256)
    try:
        inst = h.daemon_at(0).instance
        assert json.loads(inst.obs_snapshot_raw()) == {
            "v": 1, "disabled": True,
        }
        addr = h.daemon_at(0).http_address
        assert _get_json(addr, "/debug/fleet") == {"enabled": False}
        assert _get_json(addr, "/debug/slo") == {"enabled": False}
    finally:
        h.stop()


def test_admission_headroom_live_and_window_recovery(monkeypatch):
    """A finite-limit watched key driven past its limit shows
    non-negative headroom live, and a new duration window restores
    the full bound."""
    monkeypatch.setenv("GUBER_SLO_INTERVAL", "0")  # on-demand only
    h = ClusterHarness().start(2, cache_size=1024)
    try:
        d0 = h.daemon_at(0)
        key = "adm_9canary"
        for d in h.daemons:
            d.instance.admission_watch.watch(key, limit=6)
        owner = h.owner_of(key)
        duration = 1_500
        for _ in range(10):
            owner.instance.get_rate_limits(
                [_req("adm", "9canary", hits=1, limit=6,
                      duration=duration)]
            )
        fleet = d0.fleet_stats()
        adm = fleet["admitted"][key]
        assert adm["admitted"] == 6  # exactly the limit admitted
        out = d0.slo.evaluate(fleet, record=False) if d0.slo else None
        if out is not None:
            hr = out["headroom"][key]
            assert hr["headroom"] >= 0
        # New window: the engine answers UNDER again, the watch
        # re-arms, cluster headroom recovers to the full bound.
        time.sleep(duration / 1e3 + 0.3)
        owner.instance.get_rate_limits(
            [_req("adm", "9canary", hits=1, limit=6,
                  duration=duration)]
        )
        fleet = d0.fleet_stats()
        assert fleet["admitted"][key]["admitted"] == 1
    finally:
        h.stop()

"""Chaos suite: the health plane under kill / partition / heal.

Drives real in-process clusters through failure and pins the
invariants RESILIENCE.md promises (ISSUE 5 acceptance):

- with 1 of 4 peers dead and degraded mode ON, ≥99% of requests still
  receive non-error answers, flagged via response metadata;
- broken peers are SKIPPED, not re-dialed — the forward path keeps a
  bounded latency once circuits open (no connect-timeout storms);
- over-admission under partition stays within N_alive × limit;
- GLOBAL flush cycles are bounded by the fan-out deadline even when a
  peer swallows sends whole;
- hits re-queue (bounded, age-capped) and land once the owner heals;
- health states converge back to `healthy` after heal/restart;
- GUBER_DEGRADED_LOCAL=0 restores the reference's fail-closed errors.

Failure is injected two ways: daemon kill (real dead TCP port) and
the SEEDED fault injector (cluster/faults.py) for asymmetric
partitions and latency — deterministic where the assertion needs it.
Fast cases run tier-1; the multi-cycle soak is @slow.
"""

import time
from dataclasses import replace as dc_replace

import pytest

from gubernator_tpu.client import V1Client, random_string
from gubernator_tpu.cluster import faults
from gubernator_tpu.cluster.harness import ClusterHarness, cluster_behaviors
from gubernator_tpu.cluster.health import BROKEN, HEALTHY
from gubernator_tpu.types import Behavior, RateLimitReq, Status


def _until(pred, timeout=8.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _req(name, key, limit=1_000_000, hits=1, behavior=0):
    return RateLimitReq(
        name=name,
        unique_key=key,
        hits=hits,
        limit=limit,
        duration=60_000,
        behavior=behavior,
    )


def _keys_owned_by(h, daemon_idx, name, n, prefix):
    """Find `n` keys whose owner is daemons[daemon_idx]."""
    want = h.daemons[daemon_idx].peer_info().grpc_address
    out = []
    i = 0
    while len(out) < n:
        key = f"{prefix}{i}_{random_string()}"
        if (
            h.daemons[0].instance.get_peer(f"{name}_{key}").info.grpc_address
            == want
        ):
            out.append(key)
        i += 1
        assert i < 20_000, "ring never mapped enough keys to the target"
    return out


def _all_healthy(h, skip=()):
    states = h.health_states()
    return all(
        st == HEALTHY
        for src, peers in states.items()
        for dst, st in peers.items()
        if dst not in skip and src not in skip
    )


# ----------------------------------------------------------------------
# Kill / restart arc (one 4-node cluster, ordered tests).


@pytest.fixture(scope="module")
def kill_cluster():
    h = ClusterHarness().start(4)
    yield h
    h.stop()


@pytest.fixture(scope="module")
def killed(kill_cluster):
    """Kill daemon 3 once for the whole arc; expose its address."""
    h = kill_cluster
    addr = h.daemons[3].peer_info().grpc_address
    dead_keys = _keys_owned_by(h, 3, "chaos_kill", 8, "dk")
    h.kill(3)
    return {"addr": addr, "dead_keys": dead_keys}


def test_owner_killed_degraded_availability(kill_cluster, killed):
    """ISSUE 5 acceptance: 1 of 4 peers dead → ≥99% non-error answers
    (here: 100%), dead-owner items flagged degraded."""
    h = kill_cluster
    n_err = 0
    n_degraded = 0
    n_total = 0
    with V1Client(h.peer_at(0).grpc_address) as c:
        for round_ in range(12):
            for key in killed["dead_keys"]:
                r = c.get_rate_limits(
                    [_req("chaos_kill", key)], timeout=15
                )[0]
                n_total += 1
                if r.error:
                    n_err += 1
                elif r.metadata.get("degraded") == "true":
                    n_degraded += 1
            # Live-owner traffic keeps flowing untouched.
            r = c.get_rate_limits(
                [_req("chaos_live", f"live{round_}")], timeout=15
            )[0]
            n_total += 1
            if r.error:
                n_err += 1
    assert n_err / n_total <= 0.01, f"{n_err}/{n_total} errors"
    assert n_degraded > 0  # the dead owner's items were served locally
    inst = h.daemons[0].instance
    assert inst.counters["degraded_answers"] > 0


def test_circuit_opens_and_forwarding_stays_fast(kill_cluster, killed):
    """Broken peers are skipped, not re-dialed: once the circuit is
    open, a dead-owner request costs a dict probe + a local engine
    apply — far under one gRPC timeout, with no 5-retry spin."""
    h = kill_cluster
    assert _until(
        lambda: h.health_states()[
            h.daemons[0].peer_info().grpc_address
        ].get(killed["addr"]) == BROKEN,
        timeout=5.0,
    ), h.health_states()
    with V1Client(h.peer_at(0).grpc_address) as c:
        t0 = time.monotonic()
        n = 20
        for i in range(n):
            r = c.get_rate_limits(
                [_req("chaos_kill", killed["dead_keys"][i % 8])],
                timeout=15,
            )[0]
            assert r.error == ""
        per_req = (time.monotonic() - t0) / n
    # One gRPC timeout is 1s in cluster_behaviors; circuit-open
    # serving must be orders faster (generous CI bound).
    assert per_req < 0.25, f"{per_req * 1e3:.0f}ms per request"


def test_restart_heals_and_states_converge(kill_cluster, killed):
    """Restart the killed daemon; with light traffic driving probes,
    every node's circuit to it must return to `healthy`."""
    h = kill_cluster
    h.restart(3)

    def _probe_and_check():
        # Traffic is what half-opens circuits (probes ride real RPCs).
        with V1Client(h.peer_at(0).grpc_address) as c:
            for key in killed["dead_keys"]:
                c.get_rate_limits([_req("chaos_kill", key)], timeout=15)
        return _all_healthy(h)

    assert _until(_probe_and_check, timeout=20.0, interval=0.2), (
        h.health_states()
    )
    # And the answers are authoritative again (no degraded flag).
    with V1Client(h.peer_at(0).grpc_address) as c:
        r = c.get_rate_limits(
            [_req("chaos_kill", killed["dead_keys"][0])], timeout=15
        )[0]
    assert r.error == ""
    assert r.metadata.get("degraded") is None


# ----------------------------------------------------------------------
# Fail-closed mode (GUBER_DEGRADED_LOCAL=0 semantics).


def test_degraded_off_restores_fail_closed_errors():
    b = dc_replace(cluster_behaviors(), degraded_local=False)
    h = ClusterHarness().start(3, behaviors=b)
    try:
        keys = _keys_owned_by(h, 2, "chaos_fc", 2, "fc")
        h.kill(2)
        with V1Client(h.peer_at(0).grpc_address) as c:
            # First requests may burn the retry loop; once the circuit
            # opens the error is immediate — but ALWAYS an error.
            for _ in range(6):
                r = c.get_rate_limits(
                    [_req("chaos_fc", keys[0])], timeout=15
                )[0]
                assert r.error != ""
                assert r.metadata.get("degraded") is None
        assert h.daemons[0].instance.counters["degraded_answers"] == 0
    finally:
        h.stop()


# ----------------------------------------------------------------------
# Asymmetric partition via the seeded injector.


def test_asymmetric_partition_degrades_only_blocked_direction():
    h = ClusterHarness().start(3)
    try:
        keys = _keys_owned_by(h, 1, "chaos_part", 4, "ap")
        h.install_faults(seed=42)
        h.partition(0, 1)  # node0 cannot reach node1; node2 can
        with V1Client(h.peer_at(0).grpc_address) as c0, V1Client(
            h.peer_at(2).grpc_address
        ) as c2:
            # node0's circuit to node1 opens, then answers degrade.
            def _degraded():
                r = c0.get_rate_limits(
                    [_req("chaos_part", keys[0])], timeout=15
                )[0]
                return r.metadata.get("degraded") == "true"

            assert _until(_degraded, timeout=10.0, interval=0.1)
            # The unblocked direction keeps authoritative answers.
            r2 = c2.get_rate_limits(
                [_req("chaos_part", keys[1])], timeout=15
            )[0]
            assert r2.error == ""
            assert r2.metadata.get("degraded") is None

            # Heal: probes ride the traffic; states converge, answers
            # turn authoritative again.
            h.heal()

            def _healed():
                r = c0.get_rate_limits(
                    [_req("chaos_part", keys[2])], timeout=15
                )[0]
                return (
                    r.error == ""
                    and r.metadata.get("degraded") is None
                    and _all_healthy(h)
                )

            assert _until(_healed, timeout=15.0, interval=0.2), (
                h.health_states()
            )
    finally:
        h.stop()


# ----------------------------------------------------------------------
# Over-admission bound under partition (RESILIENCE.md).


def test_partition_over_admission_within_bound():
    """Dead owner, limit=10: every surviving node admits at most
    `limit` from its OWN engine, so total admission stays within
    N_alive × limit — the documented degraded-mode bound."""
    h = ClusterHarness().start(3)
    try:
        limit = 10
        key = _keys_owned_by(h, 2, "chaos_bound", 1, "ob")[0]
        h.kill(2)
        admitted = 0
        for idx in (0, 1):
            with V1Client(h.peer_at(idx).grpc_address) as c:
                for _ in range(3 * limit):
                    r = c.get_rate_limits(
                        [_req("chaos_bound", key, limit=limit)],
                        timeout=15,
                    )[0]
                    assert r.error == ""
                    if r.status == Status.UNDER_LIMIT:
                        admitted += 1
        alive = 2
        assert limit <= admitted <= alive * limit, admitted
    finally:
        h.stop()


# ----------------------------------------------------------------------
# GLOBAL plane: bounded fan-out barrier + hit re-queue.


def test_global_fanout_deadline_bounds_flush():
    """A peer whose sends hang (injected 2.5s latency) must not stall
    a broadcast flush past the fan-out budget (1s in the harness
    behaviors): the barrier stops waiting, counts the timeout, and the
    cycle completes."""
    h = ClusterHarness().start(3)
    try:
        inj = h.install_faults(seed=7)
        inj.latency_rate = 1.0
        inj.latency_s = 2.5
        d0 = h.daemons[0]
        d0.instance.global_mgr.queue_update(
            _req("chaos_dl", "k", behavior=int(Behavior.GLOBAL))
        )
        t0 = time.monotonic()
        d0.instance.global_mgr._updates.flush_now()
        elapsed = time.monotonic() - t0
        # 2 peers × 2.5s serial would be 5s; the pool + 1s barrier
        # budget must cut the cycle to ~1s (slack for CI).
        assert elapsed < 2.2, f"flush took {elapsed:.2f}s"
        from gubernator_tpu.utils.metrics import swallowed_counts

        assert swallowed_counts().get("global.fanout_deadline", 0) > 0
    finally:
        h.stop()


def test_hits_requeue_until_owner_heals():
    """GLOBAL hits toward a partitioned owner are re-queued (bounded,
    age-capped) and delivered once the partition heals — the owner
    converges instead of permanently under-counting."""
    h = ClusterHarness().start(3)
    try:
        key = _keys_owned_by(h, 1, "chaos_rq", 1, "rq")[0]
        owner = h.daemons[1]
        src = h.daemons[0]
        h.install_faults(seed=9)
        h.partition(0, 1)
        gm = src.instance.global_mgr
        gm.queue_hit(
            _req("chaos_rq", key, hits=5, behavior=int(Behavior.GLOBAL))
        )
        gm._hits.flush_now()  # fails against the partition → re-queue
        assert _until(lambda: gm.hits_requeued > 0, timeout=5.0), (
            gm.hits_requeued,
            gm.hits_requeue_dropped,
        )
        before = owner.instance.engine.requests_total
        h.heal()
        # The re-queued hits ride a later window; poke flushes until
        # the owner's engine has seen them.
        assert _until(
            lambda: (gm._hits.flush_now(), None)[1]
            or owner.instance.engine.requests_total > before,
            timeout=8.0,
            interval=0.2,
        )
    finally:
        h.stop()


def test_hits_requeue_age_cap_drops_stale():
    """Past hit_requeue_age the backlog is dropped (counted), not
    replayed — a long-dead owner must not absorb an unbounded replay
    the moment it returns."""
    b = dc_replace(cluster_behaviors(), hit_requeue_age=0.3)
    h = ClusterHarness().start(3, behaviors=b)
    try:
        key = _keys_owned_by(h, 1, "chaos_age", 1, "ag")[0]
        src = h.daemons[0]
        h.install_faults(seed=11)
        h.partition(0, 1)
        gm = src.instance.global_mgr
        r = _req("chaos_age", key, hits=1, behavior=int(Behavior.GLOBAL))
        gm.queue_hit(r)
        gm._hits.flush_now()
        assert _until(lambda: gm.hits_requeued > 0, timeout=5.0)
        time.sleep(0.4)  # outlive the age cap
        # Next failing flush evaluates the age cap and drops.
        gm._hits.flush_now()
        assert _until(lambda: gm.hits_requeue_dropped > 0, timeout=5.0), (
            gm.hits_requeued,
            gm.hits_requeue_dropped,
        )
    finally:
        h.stop()


# ----------------------------------------------------------------------
# Metrics surface.


def test_health_metrics_exported():
    import urllib.request

    h = ClusterHarness().start(2)
    try:
        keys = _keys_owned_by(h, 1, "chaos_m", 2, "mx")
        h.kill(1)
        with V1Client(h.peer_at(0).grpc_address) as c:
            for _ in range(6):
                c.get_rate_limits([_req("chaos_m", keys[0])], timeout=15)
        body = urllib.request.urlopen(
            f"http://{h.daemons[0].http_address}/metrics", timeout=5
        ).read().decode()
        assert 'gubernator_peer_state{' in body
        assert 'state="broken"' in body
        assert "gubernator_degraded_answers" in body
        assert 'gubernator_circuit_transitions_total{' in body
        assert 'to="broken"' in body
        # The operator entry mirrors the scrape.
        ph = h.daemons[0].peer_health()
        (peer_view,) = ph.values()
        assert peer_view["state"] == BROKEN
        assert peer_view["transitions"].get(BROKEN, 0) >= 1
    finally:
        h.stop()


# ----------------------------------------------------------------------
# Soak: kill / partition / heal cycles under sustained traffic.


@pytest.mark.slow
def test_chaos_soak_cycles():
    """Three full failure cycles (kill+restart, asymmetric partition,
    isolate+heal) with traffic throughout: availability ≥99%, health
    states converge after every heal, the GLOBAL queues never wedge
    (backlog age stays bounded), and over-admission of a limited key
    stays within the partition bound."""
    h = ClusterHarness().start(4)
    try:
        h.install_faults(seed=1234)
        limit = 50
        bound_key = _keys_owned_by(h, 1, "soak_bound", 1, "sb")[0]
        n_err = 0
        n_total = 0
        admitted = 0
        # One counter across ALL drive calls: the convergence loops
        # call drive(5) repeatedly, and restarting at k0/g0 each time
        # would leave whole owners without traffic — circuits only
        # probe on real sends, so an unlucky ring layout would never
        # converge (observed ~25% flake before the rotation).
        import itertools

        tick = itertools.count()

        def drive(rounds, client_idx=0):
            nonlocal n_err, n_total, admitted
            with V1Client(h.peer_at(client_idx).grpc_address) as c:
                for _ in range(rounds):
                    i = next(tick)
                    rs = c.get_rate_limits(
                        [
                            _req("soak", f"k{i % 37}"),
                            _req(
                                "soak_g",
                                f"g{i % 11}",
                                behavior=int(Behavior.GLOBAL),
                            ),
                            _req("soak_bound", bound_key, limit=limit),
                        ],
                        timeout=15,
                    )
                    for r in rs:
                        n_total += 1
                        if r.error:
                            n_err += 1
                    if rs[2].status == Status.UNDER_LIMIT and not rs[2].error:
                        admitted += 1

        # Cycle 1: kill + restart.
        drive(30)
        h.kill(3)
        drive(60)
        h.restart(3)
        drive(30)
        assert _until(
            lambda: (drive(5), None)[1] or _all_healthy(h), timeout=30.0,
            interval=0.3,
        ), h.health_states()

        # Cycle 2: asymmetric partition + heal.
        h.partition(0, 2)
        drive(60)
        h.heal()
        assert _until(
            lambda: (drive(5), None)[1] or _all_healthy(h), timeout=30.0,
            interval=0.3,
        ), h.health_states()

        # Cycle 3: full isolation of one node + heal.
        h.isolate(2)
        drive(60)
        h.heal()
        assert _until(
            lambda: (drive(5), None)[1] or _all_healthy(h), timeout=30.0,
            interval=0.3,
        ), h.health_states()

        assert n_err / n_total <= 0.01, f"{n_err}/{n_total}"
        # The limited key never over-admits past N_nodes × limit.
        assert admitted <= 4 * limit, admitted
        # GLOBAL queues drained — nothing wedged behind a dead flush.
        for d in h.daemons:
            gm = d.instance.global_mgr
            assert gm._hits.backlog_age() < 10.0
            assert gm._updates.backlog_age() < 10.0
    finally:
        h.stop()

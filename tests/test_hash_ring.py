"""Consistent-hash ring tests.

Mirrors the reference's golden-number distribution test
(reference: replicated_hash_test.go:28-99): hash 10k random IPs over 3
hosts and assert the exact per-host counts per hash function — any
change to ring construction or hashing shifts these numbers.
"""

import random
from types import SimpleNamespace

import pytest

from gubernator_tpu.cluster.hash_ring import (
    DEFAULT_REPLICAS,
    PoolEmptyError,
    RegionPicker,
    ReplicatedConsistentHash,
)
from gubernator_tpu.types import PeerInfo


def member(addr: str, dc: str = "") -> SimpleNamespace:
    return SimpleNamespace(info=PeerInfo(grpc_address=addr, datacenter=dc))


HOSTS = ["a.svc.local", "b.svc.local", "c.svc.local"]

# Golden per-host counts for 10k seeded random IPs (seed 1234); computed
# once from this implementation, frozen to catch distribution drift.
GOLDEN = {
    "fnv1": {"a.svc.local": 3400, "b.svc.local": 3298, "c.svc.local": 3302},
    "fnv1a": {"a.svc.local": 3274, "b.svc.local": 3365, "c.svc.local": 3361},
}


def _random_ips(n: int, seed: int = 1234):
    rng = random.Random(seed)
    return [".".join(str(rng.randint(0, 255)) for _ in range(4)) for _ in range(n)]


@pytest.mark.parametrize("hash_name", ["fnv1", "fnv1a"])
def test_golden_distribution(hash_name):
    ring = ReplicatedConsistentHash(hash_name)
    for h in HOSTS:
        ring.add(member(h))
    counts = {h: 0 for h in HOSTS}
    for m in ring.get_batch(_random_ips(10_000)):
        counts[m.info.grpc_address] += 1
    assert counts == GOLDEN[hash_name]


@pytest.mark.parametrize("hash_name", ["fnv1", "fnv1a"])
def test_batch_matches_scalar(hash_name):
    ring = ReplicatedConsistentHash(hash_name)
    ring.add_all([member(h) for h in HOSTS])
    keys = _random_ips(500, seed=9)
    batch = [m.info.grpc_address for m in ring.get_batch(keys)]
    scalar = [ring.get(k).info.grpc_address for k in keys]
    assert batch == scalar


def test_stability_under_membership_change():
    """Adding one host moves only a fraction of keys (the point of
    consistent hashing)."""
    ring = ReplicatedConsistentHash()
    ring.add_all([member(h) for h in HOSTS])
    keys = _random_ips(10_000)
    before = [m.info.grpc_address for m in ring.get_batch(keys)]
    ring.add(member("d.svc.local"))
    after = [m.info.grpc_address for m in ring.get_batch(keys)]
    moved = sum(1 for b, a in zip(before, after) if b != a)
    # Expect ~1/4 of keys to move to the new host; none should move
    # between surviving hosts' ownership in large numbers.
    assert 0.15 < moved / len(keys) < 0.35
    assert all(a == "d.svc.local" for b, a in zip(before, after) if b != a)


def test_empty_pool_raises():
    ring = ReplicatedConsistentHash()
    with pytest.raises(PoolEmptyError):
        ring.get("x")
    with pytest.raises(PoolEmptyError):
        ring.get_batch(["x"])


def test_get_by_peer_info_and_size():
    ring = ReplicatedConsistentHash()
    ring.add_all([member(h) for h in HOSTS])
    assert ring.size() == 3
    assert ring.get_by_peer_info(PeerInfo(grpc_address="b.svc.local")).info.grpc_address == "b.svc.local"
    assert ring.get_by_peer_info(PeerInfo(grpc_address="zz")) is None
    assert len(ring._hashes) == 3 * DEFAULT_REPLICAS


def test_re_add_same_peer_is_idempotent():
    ring = ReplicatedConsistentHash()
    ring.add(member("a.svc.local"))
    ring.add(member("a.svc.local"))
    assert ring.size() == 1
    assert len(ring._hashes) == DEFAULT_REPLICAS


# ----------------------------------------------------------------------
# Churn properties (ISSUE 7): the minimal-disruption invariant that
# elastic membership leans on, pinned as seeded property tests across
# both hash functions and several cluster sizes — the latent bug class
# here is any ring-construction change that silently reshuffles
# unrelated keys on a one-peer membership delta.


@pytest.mark.parametrize("hash_name", ["fnv1", "fnv1a"])
@pytest.mark.parametrize("n_hosts", [3, 5, 8])
def test_add_one_peer_moves_about_one_over_n(hash_name, n_hosts):
    """Adding one peer to an N-ring moves ~1/(N+1) of keys — and every
    moved key moves TO the new peer, never between survivors."""
    hosts = [f"h{i}.svc.local" for i in range(n_hosts)]
    ring = ReplicatedConsistentHash(hash_name)
    ring.add_all([member(h) for h in hosts])
    keys = _random_ips(20_000, seed=n_hosts)
    before = [m.info.grpc_address for m in ring.get_batch(keys)]
    ring.add(member("joiner.svc.local"))
    after = [m.info.grpc_address for m in ring.get_batch(keys)]
    moved = [(b, a) for b, a in zip(before, after) if b != a]
    expected = 1.0 / (n_hosts + 1)
    assert 0.5 * expected < len(moved) / len(keys) < 1.6 * expected, (
        f"{len(moved)} of {len(keys)} moved, expected ~{expected:.2%}"
    )
    assert all(a == "joiner.svc.local" for _b, a in moved), (
        "a key moved between surviving peers on an add"
    )


@pytest.mark.parametrize("hash_name", ["fnv1", "fnv1a"])
@pytest.mark.parametrize("n_hosts", [4, 6])
def test_remove_one_peer_moves_only_its_keys(hash_name, n_hosts):
    """Removing one peer re-homes exactly the keys it owned; every
    other key keeps its owner (the drain/leave invariant)."""
    hosts = [f"h{i}.svc.local" for i in range(n_hosts)]
    ring = ReplicatedConsistentHash(hash_name)
    ring.add_all([member(h) for h in hosts])
    keys = _random_ips(20_000, seed=100 + n_hosts)
    before = [m.info.grpc_address for m in ring.get_batch(keys)]
    gone = hosts[1]
    survivor_ring = ring.new()
    survivor_ring.add_all([member(h) for h in hosts if h != gone])
    after = [m.info.grpc_address for m in survivor_ring.get_batch(keys)]
    for b, a in zip(before, after):
        if b != gone:
            assert a == b, "an unaffected key changed owner on a remove"
        else:
            assert a != gone
    departed = sum(1 for b in before if b == gone)
    expected = len(keys) / n_hosts
    assert 0.5 * expected < departed < 1.6 * expected


@pytest.mark.parametrize("hash_name", ["fnv1", "fnv1a"])
@pytest.mark.parametrize("delta", ["join", "leave"])
def test_dual_ring_window_routes_old_or_new_never_third(hash_name, delta):
    """The cutover window's core property: while both rings are
    valid, every key is routed/accepted at its OLD or NEW owner —
    never a third node (cluster membership can change under traffic
    without a single misrouted key)."""
    from gubernator_tpu.cluster.hash_ring import DualRingWindow, address_ring

    hosts = [f"h{i}.svc.local" for i in range(5)]
    old_infos = [PeerInfo(grpc_address=h) for h in hosts]
    if delta == "join":
        new_infos = old_infos + [PeerInfo(grpc_address="joiner.svc.local")]
    else:
        new_infos = old_infos[:-1]
    window = DualRingWindow(
        address_ring(old_infos, hash_name),
        address_ring(new_infos, hash_name),
    )
    keys = _random_ips(5_000, seed=7)
    n_moved = 0
    for k in keys:
        old_addr, new_addr = window.owners(k)
        routed = window.owner(k)
        # Routing converges on the new topology...
        assert routed == new_addr
        # ...and acceptance covers exactly the two owners.
        assert window.acceptable(k, old_addr)
        assert window.acceptable(k, new_addr)
        third = next(
            h for h in hosts if h not in (old_addr, new_addr)
        )
        assert not window.acceptable(k, third)
        if window.moved(k):
            n_moved += 1
    # The window is consistent with the minimal-disruption property:
    # only the delta's share of keys sees two distinct owners.
    assert n_moved / len(keys) < 0.35


def test_region_picker_routes_per_dc():
    rp = RegionPicker()
    rp.add(member("a1", dc="us-east"))
    rp.add(member("a2", dc="us-east"))
    rp.add(member("b1", dc="eu-west"))
    assert rp.size() == 3
    assert set(rp.pickers()) == {"us-east", "eu-west"}
    clients = rp.get_clients("some_key")
    assert len(clients) == 2  # one owner per region
    dcs = {c.info.datacenter for c in clients}
    assert dcs == {"us-east", "eu-west"}
    assert rp.get_by_peer_info(PeerInfo(grpc_address="b1")).info.grpc_address == "b1"

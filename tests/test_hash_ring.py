"""Consistent-hash ring tests.

Mirrors the reference's golden-number distribution test
(reference: replicated_hash_test.go:28-99): hash 10k random IPs over 3
hosts and assert the exact per-host counts per hash function — any
change to ring construction or hashing shifts these numbers.
"""

import random
from types import SimpleNamespace

import pytest

from gubernator_tpu.cluster.hash_ring import (
    DEFAULT_REPLICAS,
    PoolEmptyError,
    RegionPicker,
    ReplicatedConsistentHash,
)
from gubernator_tpu.types import PeerInfo


def member(addr: str, dc: str = "") -> SimpleNamespace:
    return SimpleNamespace(info=PeerInfo(grpc_address=addr, datacenter=dc))


HOSTS = ["a.svc.local", "b.svc.local", "c.svc.local"]

# Golden per-host counts for 10k seeded random IPs (seed 1234); computed
# once from this implementation, frozen to catch distribution drift.
GOLDEN = {
    "fnv1": {"a.svc.local": 3400, "b.svc.local": 3298, "c.svc.local": 3302},
    "fnv1a": {"a.svc.local": 3274, "b.svc.local": 3365, "c.svc.local": 3361},
}


def _random_ips(n: int, seed: int = 1234):
    rng = random.Random(seed)
    return [".".join(str(rng.randint(0, 255)) for _ in range(4)) for _ in range(n)]


@pytest.mark.parametrize("hash_name", ["fnv1", "fnv1a"])
def test_golden_distribution(hash_name):
    ring = ReplicatedConsistentHash(hash_name)
    for h in HOSTS:
        ring.add(member(h))
    counts = {h: 0 for h in HOSTS}
    for m in ring.get_batch(_random_ips(10_000)):
        counts[m.info.grpc_address] += 1
    assert counts == GOLDEN[hash_name]


@pytest.mark.parametrize("hash_name", ["fnv1", "fnv1a"])
def test_batch_matches_scalar(hash_name):
    ring = ReplicatedConsistentHash(hash_name)
    ring.add_all([member(h) for h in HOSTS])
    keys = _random_ips(500, seed=9)
    batch = [m.info.grpc_address for m in ring.get_batch(keys)]
    scalar = [ring.get(k).info.grpc_address for k in keys]
    assert batch == scalar


def test_stability_under_membership_change():
    """Adding one host moves only a fraction of keys (the point of
    consistent hashing)."""
    ring = ReplicatedConsistentHash()
    ring.add_all([member(h) for h in HOSTS])
    keys = _random_ips(10_000)
    before = [m.info.grpc_address for m in ring.get_batch(keys)]
    ring.add(member("d.svc.local"))
    after = [m.info.grpc_address for m in ring.get_batch(keys)]
    moved = sum(1 for b, a in zip(before, after) if b != a)
    # Expect ~1/4 of keys to move to the new host; none should move
    # between surviving hosts' ownership in large numbers.
    assert 0.15 < moved / len(keys) < 0.35
    assert all(a == "d.svc.local" for b, a in zip(before, after) if b != a)


def test_empty_pool_raises():
    ring = ReplicatedConsistentHash()
    with pytest.raises(PoolEmptyError):
        ring.get("x")
    with pytest.raises(PoolEmptyError):
        ring.get_batch(["x"])


def test_get_by_peer_info_and_size():
    ring = ReplicatedConsistentHash()
    ring.add_all([member(h) for h in HOSTS])
    assert ring.size() == 3
    assert ring.get_by_peer_info(PeerInfo(grpc_address="b.svc.local")).info.grpc_address == "b.svc.local"
    assert ring.get_by_peer_info(PeerInfo(grpc_address="zz")) is None
    assert len(ring._hashes) == 3 * DEFAULT_REPLICAS


def test_re_add_same_peer_is_idempotent():
    ring = ReplicatedConsistentHash()
    ring.add(member("a.svc.local"))
    ring.add(member("a.svc.local"))
    assert ring.size() == 1
    assert len(ring._hashes) == DEFAULT_REPLICAS


def test_region_picker_routes_per_dc():
    rp = RegionPicker()
    rp.add(member("a1", dc="us-east"))
    rp.add(member("a2", dc="us-east"))
    rp.add(member("b1", dc="eu-west"))
    assert rp.size() == 3
    assert set(rp.pickers()) == {"us-east", "eu-west"}
    clients = rp.get_clients("some_key")
    assert len(clients) == 2  # one owner per region
    dcs = {c.info.datacenter for c in clients}
    assert dcs == {"us-east", "eu-west"}
    assert rp.get_by_peer_info(PeerInfo(grpc_address="b1")).info.grpc_address == "b1"

"""Unit suite for the peer health plane (cluster/health.py).

Pins the circuit-breaker transition table, the half-open probe-slot
semantics, the exponential open-period growth, and the backoff_delay
jitter envelope — plus the fault injector's determinism contract
(cluster/faults.py): equal seeds replay equal fates.
"""

import random

import pytest

from gubernator_tpu.cluster import faults
from gubernator_tpu.cluster.health import (
    BROKEN,
    HALF_OPEN,
    HEALTHY,
    SUSPECT,
    PeerHealth,
    backoff_delay,
)


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _health(threshold=3, backoff=1.0, cap=8.0):
    clock = FakeClock()
    h = PeerHealth(
        "peer:1",
        failure_threshold=threshold,
        backoff=backoff,
        backoff_cap=cap,
        now=clock,
    )
    return h, clock


# -- transition table --------------------------------------------------


def test_starts_healthy_and_allows():
    h, _ = _health()
    assert h.state() == HEALTHY
    assert h.allow()
    assert h.would_allow()


def test_first_failure_moves_to_suspect():
    h, _ = _health()
    h.record_failure()
    assert h.state() == SUSPECT
    assert h.allow()  # suspect still sends


def test_suspect_success_returns_to_healthy():
    h, _ = _health()
    h.record_failure()
    h.record_success()
    assert h.state() == HEALTHY


def test_threshold_failures_open_the_circuit():
    h, _ = _health(threshold=3)
    for _ in range(3):
        h.record_failure()
    assert h.state() == BROKEN
    assert not h.allow()
    assert not h.would_allow()
    assert h.retry_after() > 0


def test_broken_until_open_period_expires_then_one_probe():
    h, clock = _health(threshold=1, backoff=2.0)
    h.record_failure()
    assert h.state() == BROKEN
    assert not h.allow()
    clock.advance(2.01)
    assert h.would_allow()
    # First caller wins the probe slot...
    assert h.allow()
    assert h.state() == HALF_OPEN
    # ...everyone else is refused while the probe is in flight.
    assert not h.allow()
    assert not h.would_allow()


def test_half_open_success_closes_circuit():
    h, clock = _health(threshold=1, backoff=1.0)
    h.record_failure()
    clock.advance(1.01)
    assert h.allow()
    h.record_success()
    assert h.state() == HEALTHY
    assert h.allow()


def test_half_open_failure_reopens_with_doubled_period():
    h, clock = _health(threshold=1, backoff=1.0, cap=8.0)
    h.record_failure()  # open @ 1.0
    clock.advance(1.01)
    assert h.allow()  # half-open probe
    h.record_failure()  # probe failed → open @ 2.0
    assert h.state() == BROKEN
    assert h.retry_after() == pytest.approx(2.0, abs=0.01)
    clock.advance(2.01)
    assert h.allow()
    h.record_failure()  # → 4.0
    assert h.retry_after() == pytest.approx(4.0, abs=0.01)
    clock.advance(4.01)
    assert h.allow()
    h.record_failure()  # → 8.0 (cap)
    assert h.retry_after() == pytest.approx(8.0, abs=0.01)
    clock.advance(8.01)
    assert h.allow()
    h.record_failure()  # capped: stays 8.0
    assert h.retry_after() == pytest.approx(8.0, abs=0.01)


def test_recovery_resets_open_period():
    h, clock = _health(threshold=1, backoff=1.0, cap=8.0)
    for _ in range(3):  # grow the period to 4.0
        h.record_failure()
        clock.advance(h.retry_after() + 0.01)
        assert h.allow()
    h.record_success()
    assert h.state() == HEALTHY
    # Next break starts back at the base period.
    h.record_failure()
    assert h.retry_after() == pytest.approx(1.0, abs=0.01)


def test_failure_while_broken_is_absorbed():
    """A racing in-flight RPC failing after the circuit opened must
    not grow the period or disturb the probe schedule."""
    h, _ = _health(threshold=1, backoff=2.0)
    h.record_failure()
    before = h.retry_after()
    h.record_failure()
    assert h.state() == BROKEN
    assert h.retry_after() == pytest.approx(before, abs=0.01)


def test_stale_probe_slot_is_reclaimed():
    """A probe whose sender dies between winning the slot and the RPC
    (no outcome ever recorded) must not blacklist the peer forever:
    past probe_timeout the next caller reclaims the slot."""
    clock = FakeClock()
    h = PeerHealth(
        "peer:1", failure_threshold=1, backoff=1.0, backoff_cap=8.0,
        probe_timeout=5.0, now=clock,
    )
    h.record_failure()
    clock.advance(1.01)
    assert h.allow()  # probe slot taken... and the prober vanishes
    assert not h.allow()
    assert not h.would_allow()
    clock.advance(5.01)  # probe_timeout elapsed with no outcome
    assert h.would_allow()
    assert h.allow()  # reclaimed
    h.record_success()
    assert h.state() == HEALTHY


def test_transition_counters():
    h, clock = _health(threshold=1, backoff=1.0)
    h.record_failure()  # healthy→suspect→broken
    clock.advance(1.01)
    h.allow()  # → half-open
    h.record_success()  # → healthy
    t = h.transition_counts()
    assert t[SUSPECT] == 1
    assert t[BROKEN] == 1
    assert t[HALF_OPEN] == 1
    assert t[HEALTHY] == 1


# -- backoff_delay -----------------------------------------------------


def test_backoff_delay_full_jitter_envelope():
    rng = random.Random(7)
    for attempt in range(6):
        ceiling = min(0.25, 0.01 * 2**attempt)
        for _ in range(50):
            d = backoff_delay(attempt, 0.01, 0.25, rng)
            assert 0.0 <= d <= ceiling


def test_backoff_delay_zero_base_disables():
    assert backoff_delay(3, 0.0, 1.0) == 0.0


def test_backoff_delay_deterministic_with_seed():
    a = [backoff_delay(i, 0.01, 0.25, random.Random(42)) for i in range(5)]
    b = [backoff_delay(i, 0.01, 0.25, random.Random(42)) for i in range(5)]
    assert a == b


# -- fault injector ----------------------------------------------------


def test_injector_same_seed_same_fates():
    def fates(seed):
        inj = faults.FaultInjector(seed, drop_rate=0.3, reset_rate=0.2)
        out = []
        for _ in range(200):
            try:
                inj.check("a", "b")
                out.append("ok")
            except faults.FaultError as e:
                out.append(e.kind)
        return out

    assert fates(123) == fates(123)
    assert fates(123) != fates(124)  # and the seed actually matters


def test_injector_asymmetric_partition():
    inj = faults.FaultInjector(0)
    inj.partition("a", "b")
    with pytest.raises(faults.FaultError):
        inj.check("a", "b")
    inj.check("b", "a")  # reverse direction flows
    inj.heal()
    inj.check("a", "b")


def test_injector_isolate_and_heal():
    inj = faults.FaultInjector(0)
    inj.isolate("n1")
    with pytest.raises(faults.FaultError):
        inj.check("n1", "n2")
    with pytest.raises(faults.FaultError):
        inj.check("n3", "n1")
    inj.check("n2", "n3")
    inj.heal()
    inj.check("n1", "n2")
    assert inj.counts().get("partition", 0) == 2


def test_injector_targeted_heal_leaves_other_rules():
    """heal(src, dst) wildcards only on the ARGUMENT side: healing
    node A's partitions must not tear down node B's isolation."""
    inj = faults.FaultInjector(0)
    inj.isolate("B")
    inj.partition("A", "C")
    inj.heal("A", None)
    with pytest.raises(faults.FaultError):
        inj.check("X", "B")  # B's inbound isolation survives
    inj.check("A", "C")  # A's rule is gone
    inj.heal(dst="B")
    with pytest.raises(faults.FaultError):
        inj.check("B", "X")  # B's OUTBOUND rule ("B","*") survives
    inj.heal()
    inj.check("B", "X")
    inj.check("X", "B")


def test_injector_install_uninstall():
    assert faults.active() is None
    inj = faults.install(faults.FaultInjector(1))
    try:
        assert faults.active() is inj
    finally:
        faults.uninstall()
    assert faults.active() is None

"""Sharded-engine concurrency storm over the WIRE columnar path
(VERDICT r3 weak 7): racing raw-bytes wire clients against the mesh
engine — the single-device analog lives in test_concurrency.py.

Invariants: no lost/misattributed responses, exact accounting for
shared keys across racing columnar (serve_wire_bytes) and dataclass
(get_rate_limits) callers, hot-key collapse included.
"""

import threading

import numpy as np
import pytest

from gubernator_tpu.clock import Clock
from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.net import wire_codec
from gubernator_tpu.net.pb import gubernator_pb2 as pb
from gubernator_tpu.types import RateLimitReq, Status

N_THREADS = 8
ROUNDS = 12


@pytest.fixture
def sharded_daemon(frozen_clock):
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=8 * 4096,
        peer_discovery_type="none",
        device_count=8,  # virtual CPU mesh (tests/conftest.py)
        sweep_interval=0.0,
    )
    d = spawn_daemon(conf, clock=frozen_clock)
    assert hasattr(d.instance.engine, "tables"), "expected sharded engine"
    yield d
    d.close()


def _payload(tid, rep, shared_hits=3, privates=20):
    reqs = [
        pb.RateLimitReq(
            name="storm", unique_key="shared", hits=1,
            limit=10**9, duration=3_600_000,
        )
        for _ in range(shared_hits)
    ] + [
        pb.RateLimitReq(
            name="storm", unique_key=f"p{tid}_{rep}_{i}", hits=1,
            limit=10**9, duration=3_600_000,
        )
        for i in range(privates)
    ]
    return pb.GetRateLimitsReq(requests=reqs).SerializeToString()


@pytest.mark.skipif(
    wire_codec.load() is None, reason="native codec unavailable"
)
def test_sharded_wire_storm_exact_accounting(sharded_daemon):
    """Racing wire-bytes clients (columnar, route_hashes) + dataclass
    callers on the SHARDED engine: the shared key consumes exactly the
    sum of all hits; every response decodes with no errors."""
    d = sharded_daemon
    inst = d.instance
    errs = []

    def wire_worker(tid):
        try:
            for rep in range(ROUNDS):
                out = inst.serve_wire_bytes(_payload(tid, rep))
                assert out is not None, "columnar wire path must engage"
                resp = pb.GetRateLimitsResp.FromString(out)
                assert len(resp.responses) == 23
                for r in resp.responses:
                    assert r.error == ""
                    assert r.status == int(Status.UNDER_LIMIT)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def dataclass_worker(tid):
        try:
            for rep in range(ROUNDS):
                # Duplicate shared keys inside one batch: collapse path.
                reqs = [
                    RateLimitReq(
                        name="storm", unique_key="shared", hits=1,
                        limit=10**9, duration=3_600_000,
                    )
                ] * 2 + [
                    RateLimitReq(
                        name="storm", unique_key=f"d{tid}_{rep}", hits=1,
                        limit=10**9, duration=3_600_000,
                    )
                ]
                resps = inst.get_rate_limits(reqs)
                assert all(r.error == "" for r in resps)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [
        threading.Thread(target=wire_worker, args=(t,))
        for t in range(N_THREADS // 2)
    ] + [
        threading.Thread(target=dataclass_worker, args=(t,))
        for t in range(N_THREADS // 2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errs, errs[:2]
    assert all(not t.is_alive() for t in threads)

    # Exact accounting: wire workers 4*12*3 + dataclass workers 4*12*2.
    expected = (N_THREADS // 2) * ROUNDS * 3 + (N_THREADS // 2) * ROUNDS * 2
    probe = inst.get_rate_limits(
        [
            RateLimitReq(
                name="storm", unique_key="shared", hits=0,
                limit=10**9, duration=3_600_000,
            )
        ]
    )[0]
    assert 10**9 - probe.remaining == expected, (
        f"shared consumed {10**9 - probe.remaining}, want {expected}"
    )

"""Count-min-sketch approximate limiter (BASELINE config 5 stretch).

No reference counterpart (the reference's state is bounded by its LRU
and evicts); the sketch answers for unbounded key cardinality with
one-sided (overcount-only) error.
"""

import numpy as np

from gubernator_tpu.ops.sketch import SketchLimiter


def apply1(lim, key, hits, limit, now):
    over, est = lim.apply(
        [key], np.asarray([hits]), np.asarray([limit]), now
    )
    return bool(over[0]), int(est[0])


def test_single_key_accumulates_and_limits():
    lim = SketchLimiter(window_ms=1_000, depth=4, width=1 << 12)
    now = 10_000  # window start (frac = 0)
    over, est = apply1(lim, b"k1", 3, 5, now)
    assert (over, est) == (False, 3)
    over, est = apply1(lim, b"k1", 2, 5, now)
    assert (over, est) == (False, 5)
    over, est = apply1(lim, b"k1", 1, 5, now)
    assert (over, est) == (True, 6)


def test_distinct_keys_do_not_interfere():
    lim = SketchLimiter(window_ms=1_000, depth=4, width=1 << 16)
    now = 0
    n = 200
    keys = [b"key_%d" % i for i in range(n)]
    hits = np.arange(1, n + 1, dtype=np.int64)
    limit = np.full(n, 10_000, dtype=np.int64)
    over, est = lim.apply(keys, hits, limit, now)
    # With width 65536 and 200 keys, collisions across all 4 rows are
    # essentially impossible: estimates are exact.
    assert not over.any()
    np.testing.assert_array_equal(est, hits)


def test_duplicates_in_one_batch_sum():
    lim = SketchLimiter(window_ms=1_000, depth=4, width=1 << 12)
    keys = [b"dup"] * 4 + [b"other"]
    hits = np.asarray([1, 2, 3, 4, 7], dtype=np.int64)
    limit = np.full(5, 100, dtype=np.int64)
    over, est = lim.apply(keys, hits, limit, 0)
    # Batch semantics: every duplicate sees the post-batch total.
    assert est[0] == est[1] == est[2] == est[3] == 10
    assert est[4] == 7


def test_window_rotation_decays_and_expires():
    lim = SketchLimiter(window_ms=1_000, depth=4, width=1 << 12)
    _, est = apply1(lim, b"w", 100, 10_000, 0)
    assert est == 100
    # Next window, halfway in: previous counts ~half-weighted.
    _, est = apply1(lim, b"w", 0, 10_000, 1_500)
    assert 40 <= est <= 60
    # Two windows later: everything expired.
    _, est = apply1(lim, b"w", 0, 10_000, 3_000)
    assert est == 0


def test_overcount_is_one_sided():
    """Collisions may only INFLATE estimates — with a tiny width the
    estimate for a key is always >= its true count."""
    lim = SketchLimiter(window_ms=1_000, depth=2, width=64)
    n = 300
    keys = [b"c%d" % i for i in range(n)]
    hits = np.ones(n, dtype=np.int64)
    limit = np.full(n, 10**9, dtype=np.int64)
    _, est = lim.apply(keys, hits, limit, 0)
    assert (est >= 1).all()


def test_hot_key_saturates_instead_of_wrapping():
    """A hot key whose combined hits exceed int32 must saturate the
    counter at 2^31-1, never wrap negative (ADVICE r3: wrapping would
    under-count, violating the one-sided error contract)."""
    lim = SketchLimiter(window_ms=1_000, depth=2, width=1 << 10)
    big = 2**30
    keys = [b"hot"] * 4  # combined 4*2^30 = 2^32 > int32 max
    hits = np.full(4, big, dtype=np.int64)
    limit = np.full(4, 10**6, dtype=np.int64)
    over, est = lim.apply(keys, hits, limit, 0)
    assert (est == 2**31 - 1).all()
    assert over.all()
    # A second saturated batch must stay saturated, not wrap.
    over, est = lim.apply(keys, hits, limit, 10)
    assert (est >= 2**31 - 1).all()
    assert over.all()


def test_sketch_behavior_end_to_end_grpc():
    """Behavior.SKETCH routes decisions to the approximate limiter over
    real gRPC — both the native wire path (all-sketch batch) and the pb
    dataclass path (mixed batch) — with sketch semantics: estimates
    never under-count, OVER_LIMIT when estimate exceeds limit."""
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.cluster.harness import cluster_behaviors
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.types import Behavior, RateLimitReq, Status

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        behaviors=cluster_behaviors(),
        cache_size=2048,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
        sketch_window_ms=3_600_000,  # one long window: deterministic
        sketch_depth=4,
        sketch_width=1 << 16,
    )
    d = spawn_daemon(conf)
    try:
        with V1Client(d.grpc_address) as c:
            # All-sketch batch (native wire route): 5 hits on one key.
            rs = c.get_rate_limits(
                [
                    RateLimitReq(
                        name="sk", unique_key="hot", hits=1, limit=3,
                        duration=60_000, behavior=int(Behavior.SKETCH),
                    )
                    for _ in range(5)
                ],
                timeout=30,
            )
            # Batch semantics: every duplicate sees the post-batch
            # total estimate (5 > 3 -> OVER, remaining 0).
            assert all(r.status == Status.OVER_LIMIT for r in rs), rs
            assert all(r.remaining == 0 for r in rs)
            assert all(r.limit == 3 for r in rs)
            assert all(r.reset_time > 0 for r in rs)
            # A different key is unaffected (sketch width is ample).
            r2 = c.get_rate_limits(
                [RateLimitReq(name="sk", unique_key="cold", hits=1,
                              limit=3, duration=60_000,
                              behavior=int(Behavior.SKETCH))],
                timeout=30,
            )[0]
            assert r2.status == Status.UNDER_LIMIT and r2.remaining == 2
            # Mixed batch (pb path): sketch + bucket items coexist and
            # route independently.
            rs = c.get_rate_limits(
                [
                    RateLimitReq(name="sk", unique_key="hot", hits=0,
                                 limit=3, duration=60_000,
                                 behavior=int(Behavior.SKETCH)),
                    RateLimitReq(name="bucket", unique_key="b1", hits=1,
                                 limit=10, duration=60_000),
                ],
                timeout=30,
            )
            assert rs[0].status == Status.OVER_LIMIT  # estimate >= 5
            assert rs[1].remaining == 9  # exact engine decision
        assert d.instance.counters["sketch"] >= 7
    finally:
        d.close()


def test_sketch_concurrent_apply_exact_totals():
    """Racing apply() calls must serialize on the limiter's lock: the
    donated-state step would otherwise see deleted buffers or drop
    updates (code-review r4).  Total estimate after N concurrent
    single-hit batches on one key == N exactly (ample width)."""
    import threading

    lim = SketchLimiter(window_ms=3_600_000, depth=2, width=1 << 14)
    n_threads, per_thread = 8, 25
    errs = []

    def worker():
        try:
            for _ in range(per_thread):
                lim.apply([b"conc"], np.ones(1, dtype=np.int64),
                          np.full(1, 10**9, dtype=np.int64), 0)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs, errs
    _, est = lim.apply([b"conc"], np.zeros(1, dtype=np.int64),
                       np.full(1, 10**9, dtype=np.int64), 0)
    assert int(est[0]) == n_threads * per_thread

"""Native C++ intern table: build, equivalence fuzz, batch scheduling.

The native table must behave identically to the Python InternTable
(core/interning.py) — same slots, rounds, evictions, and metrics — so
the engine can use either transparently.
"""

import random

import numpy as np
import pytest

from gubernator_tpu.core.interning import InternTable

native = pytest.importorskip("gubernator_tpu.core.native")


@pytest.fixture(scope="module")
def lib():
    lib = native.load_library()
    if lib is None:
        pytest.skip("native table not buildable in this environment")
    return lib


def test_basic_ops(lib):
    t = native.NativeInternTable(8)
    cleared: list = []
    s1 = t.intern("a", 0, cleared)
    s2 = t.intern("b", 0, cleared)
    assert s1 != s2
    assert t.intern("a", 0, cleared) == s1
    assert len(t) == 2
    assert t.contains("a") and not t.contains("zz")
    assert t.key_for_slot(s1) == "a"
    assert t.remove("a") == s1
    assert not t.contains("a")
    assert t.key_for_slot(s1) is None
    assert len(t) == 1
    assert cleared == []


def test_eviction_lru_order(lib):
    t = native.NativeInternTable(3)
    cleared: list = []
    sa = t.intern("a", 0, cleared)
    t.intern("b", 0, cleared)
    t.intern("c", 0, cleared)
    t.intern("a", 0, cleared)  # refresh a: LRU order is now b,c,a
    t.intern("d", 0, cleared)  # evicts b
    assert cleared == [t.remove("d")]  # d took b's slot
    assert not t.contains("b")
    assert t.contains("a") and t.contains("c")
    assert t.evictions == 1


def test_unexpired_eviction_metric(lib):
    t = native.NativeInternTable(2)
    cleared: list = []
    s = t.intern("x", 100, cleared)
    t.set_expiry(np.asarray([s], dtype=np.int32), np.asarray([500], dtype=np.int64))
    t.intern("y", 100, cleared)
    t.intern("z", 100, cleared)  # evicts x (expire 500 > now 100)
    assert t.unexpired_evictions == 1


def test_schedule_rounds(lib):
    t = native.NativeInternTable(16)
    keys = [b"k1", b"k2", b"k1", b"k3", b"k1", b"k2"]
    slots, rounds, evicted, _ = t.schedule(keys, 0)
    assert len(evicted) == 0
    assert slots[0] == slots[2] == slots[4]
    assert slots[1] == slots[5]
    assert list(rounds) == [0, 0, 1, 0, 2, 1]
    # Rounds reset per batch.
    slots2, rounds2, _, _ = t.schedule([b"k1", b"k1"], 0)
    assert list(rounds2) == [0, 1]
    assert slots2[0] == slots[0]


def test_fuzz_equivalence_with_python_table(lib):
    """Random workload: native and Python tables must agree on every
    observable (slots per key, rounds, evictions, metrics, length)."""
    rng = random.Random(42)
    cap = 50
    py = InternTable(cap)
    nat = native.NativeInternTable(cap)
    keyspace = [f"key:{i}" for i in range(200)]

    for step in range(300):
        now = step * 10
        batch = [rng.choice(keyspace) for _ in range(rng.randint(1, 40))]

        # Python path (per-key, like the engine fallback).
        py_slots, py_rounds, py_ev = [], [], []
        seq: dict = {}
        for k in batch:
            ev: list = []
            s = py.intern(k, now, ev)
            py_ev.extend(ev)
            r = seq.get(s, 0)
            seq[s] = r + 1
            py_slots.append(s)
            py_rounds.append(r)

        n_slots, n_rounds, n_ev, _ = nat.schedule(
            [k.encode() for k in batch], now
        )

        # Slot numbering may differ (allocation order), but key→slot
        # mapping must be consistent within each table; rounds and
        # eviction counts are directly comparable.
        assert list(n_rounds) == py_rounds, f"step {step}"
        assert len(n_ev) == len(py_ev), f"step {step}"
        assert len(py) == len(nat), f"step {step}"

        if rng.random() < 0.3:
            k = rng.choice(keyspace)
            assert (py.remove(k) is None) == (nat.remove(k) is None)

        assert py.hits == nat.hits and py.misses == nat.misses, f"step {step}"
        assert py.evictions == nat.evictions, f"step {step}"
        assert py.unexpired_evictions == nat.unexpired_evictions, f"step {step}"


def test_same_slot_for_same_key_between_tables_after_release(lib):
    t = native.NativeInternTable(4)
    cleared: list = []
    s = t.intern("r1", 0, cleared)
    t.release_slots(np.asarray([s], dtype=np.int32))
    assert not t.contains("r1")
    assert len(t) == 0
    # Slot is reusable.
    s2 = t.intern("r2", 0, cleared)
    assert s2 == s

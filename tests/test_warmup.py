"""Warmup must cover every program the serving paths run.

VERDICT r1 weak item 4 / next-round item 7: a daemon that warms up but
then pays an XLA compile on a served batch blows the peer-batch timeout
(an uncompiled apply_batch_sorted cost 1.1s on the wire path).  These
tests pin "zero compile-cache misses while serving" for both engines by
snapshotting the jit caches of every kernel after warmup and asserting
they do not grow while serving widths up to the warmed max.
"""

import numpy as np
import pytest

from gubernator_tpu.clock import Clock
from gubernator_tpu.core.engine import DecisionEngine
from gubernator_tpu.ops import bucket_kernel as bk
from gubernator_tpu.types import Algorithm, RateLimitReq

# The serving programs: dataclass path (apply_batch), packed columnar
# path (fused_step when in-place donation compiles, else
# packed_compute + scatter_store), eviction clears.
_KERNELS = (
    bk.apply_batch,
    bk.fused_step,
    bk.packed_compute,
    bk.collapsed_step,
    bk.collapsed_compute,
    bk.scatter_store,
    bk.clear_occupied,
)


def _cache_sizes():
    return tuple(k._cache_size() for k in _KERNELS)


def _columns(n, start=0, name="serve"):
    return dict(
        keys=[b"%s_k%d" % (name.encode(), start + i) for i in range(n)],
        algo=np.asarray([i % 2 for i in range(n)], dtype=np.int32),
        behavior=np.zeros(n, dtype=np.int32),
        hits=np.ones(n, dtype=np.int64),
        limit=np.full(n, 100, dtype=np.int64),
        duration=np.full(n, 60_000, dtype=np.int64),
        burst=np.full(n, 100, dtype=np.int64),
    )


def test_single_device_warmup_covers_serving_widths(frozen_clock):
    engine = DecisionEngine(capacity=4096, clock=frozen_clock, max_kernel_width=1024)
    engine.warmup(max_width=1024)
    before = _cache_sizes()

    # Serve every width the wire path can produce (1..MAX_BATCH_SIZE
    # pads to 64..1024) through BOTH serving programs.
    for width in (1, 63, 64, 65, 500, 1000, 1024):
        engine.apply_columnar(**_columns(width, start=width * 2000))
        reqs = [
            RateLimitReq(
                name="serve2",
                unique_key=f"{width}_{i}",
                hits=1,
                limit=100,
                duration=60_000,
                algorithm=Algorithm.TOKEN_BUCKET if i % 2 == 0 else Algorithm.LEAKY_BUCKET,
            )
            for i in range(width)
        ]
        engine.get_rate_limits(reqs)

    assert _cache_sizes() == before, (
        "serving compiled a new kernel variant after warmup"
    )


def test_sharded_warmup_covers_serving_widths(frozen_clock):
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 virtual devices")
    from gubernator_tpu.parallel.mesh import make_mesh
    from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine

    mesh = make_mesh(jax.devices()[:4])
    engine = ShardedDecisionEngine(
        shard_capacity=2048, mesh=mesh, clock=frozen_clock, max_kernel_width=256
    )
    engine.warmup(max_width=256)
    before = tuple(
        f._cache_size()
        for f in (
            engine._packed_fused,
            engine._packed_compute,
            engine._collapsed_fused,
            engine._collapsed_compute,
            engine._step_scatter,
            engine._clear_step,
        )
    )

    for width in (1, 65, 200, 256 * 4):
        engine.apply_columnar(**_columns(width, start=width * 3000, name="shserve"))
        reqs = [
            RateLimitReq(
                name="shserve2",
                unique_key=f"{width}_{i}",
                hits=1,
                limit=100,
                duration=60_000,
            )
            for i in range(width)
        ]
        engine.get_rate_limits(reqs)

    after = tuple(
        f._cache_size()
        for f in (
            engine._packed_fused,
            engine._packed_compute,
            engine._collapsed_fused,
            engine._collapsed_compute,
            engine._step_scatter,
            engine._clear_step,
        )
    )
    assert after == before, "sharded serving compiled a new variant after warmup"

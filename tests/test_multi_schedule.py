"""git_multi_schedule (one-FFI sharded host tier) must be behaviorally
identical to the per-shard schedule_packed loop it replaces.

The native path changes scheduling mechanics only — shard routing,
interning, rounds, TTL, dispatch order — so an engine taking the
multi-call path and one forced onto the per-shard fallback must
produce bit-equal decisions and identical table occupancy under
duplicate keys, evictions, Gregorian durations, and hot-key collapse.
"""

import random

import numpy as np
import pytest

from gubernator_tpu.parallel.sharded_engine import ShardedDecisionEngine
from gubernator_tpu.types import Algorithm, Behavior, RateLimitReq


def _columns(reqs):
    return (
        [r.hash_key().encode() for r in reqs],
        np.asarray([int(r.algorithm) for r in reqs], dtype=np.int32),
        np.asarray([int(r.behavior) for r in reqs], dtype=np.int32),
        np.asarray([r.hits for r in reqs], dtype=np.int64),
        np.asarray([r.limit for r in reqs], dtype=np.int64),
        np.asarray([r.duration for r in reqs], dtype=np.int64),
        np.asarray([r.burst for r in reqs], dtype=np.int64),
    )


def _require_native(engine):
    if not engine._multi_ok:
        pytest.skip("native intern table unavailable")


def _fuzz_reqs(rng, n_keys, n_items, greg=False):
    reqs = []
    for _ in range(n_items):
        i = rng.randint(0, n_keys - 1)
        behavior = Behavior.BATCHING
        duration = 60_000
        if greg and i % 7 == 0:
            behavior |= Behavior.DURATION_IS_GREGORIAN
            duration = 1  # GregorianMinutes
        reqs.append(
            RateLimitReq(
                # Leading-byte variation: FNV-1 trailing-byte
                # non-avalanche makes f"k{i}" keys collapse onto one
                # ring owner (cluster/hash_ring.py).
                name=f"{i}ms",
                unique_key=f"{i}x",
                hits=rng.randint(0, 3),
                limit=10,
                duration=duration,
                algorithm=rng.choice(
                    [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]
                ),
                behavior=behavior,
                burst=10,
            )
        )
    return reqs


@pytest.mark.parametrize("single_program", [False, True])
@pytest.mark.parametrize("shard_capacity,n_keys", [
    (128, 60),     # no evictions
    (8, 200),      # constant eviction pressure
])
def test_multi_schedule_matches_fallback(
    frozen_clock, shard_capacity, n_keys, single_program
):
    rng = random.Random(5)
    eng_native = ShardedDecisionEngine(
        shard_capacity=shard_capacity, clock=frozen_clock,
        single_program=single_program,
    )
    _require_native(eng_native)
    eng_fallback = ShardedDecisionEngine(
        shard_capacity=shard_capacity, clock=frozen_clock
    )
    eng_fallback._multi_ok = False  # force the per-shard loop

    for step in range(8):
        reqs = _fuzz_reqs(rng, n_keys, rng.randint(1, 80), greg=True)
        cols = _columns(reqs)
        a = eng_native.apply_columnar(*cols)
        b = eng_fallback.apply_columnar(*cols)
        for col_a, col_b, label in zip(a, b, "slrr"):
            np.testing.assert_array_equal(
                np.asarray(col_a), np.asarray(col_b),
                err_msg=f"step {step} column {label}",
            )
        for sh, (ta, tb) in enumerate(
            zip(eng_native.tables, eng_fallback.tables)
        ):
            assert len(ta) == len(tb), f"step {step} shard {sh} occupancy"
            assert (
                ta.hits, ta.misses, ta.evictions, ta.unexpired_evictions
            ) == (
                tb.hits, tb.misses, tb.evictions, tb.unexpired_evictions
            ), f"step {step} shard {sh} stats"
        frozen_clock.advance(ms=rng.randint(0, 3_000))


@pytest.mark.parametrize("single_program", [False, True])
def test_multi_schedule_hot_key_collapse(frozen_clock, single_program):
    """An all-duplicate batch must still collapse (uniform segments)
    and agree with the fallback path."""
    eng_native = ShardedDecisionEngine(
        shard_capacity=64, clock=frozen_clock, single_program=single_program
    )
    _require_native(eng_native)
    eng_fallback = ShardedDecisionEngine(shard_capacity=64, clock=frozen_clock)
    eng_fallback._multi_ok = False

    reqs = [
        RateLimitReq(
            name="hot", unique_key="key", hits=1, limit=1000,
            duration=60_000, burst=1000,
        )
    ] * 50
    cols = _columns(reqs)
    rounds_before = eng_native.rounds_total
    a = eng_native.apply_columnar(*cols)
    assert eng_native.rounds_total == rounds_before + 1, (
        "hot-key batch should collapse to one mesh dispatch"
    )
    b = eng_fallback.apply_columnar(*cols)
    for col_a, col_b in zip(a, b):
        np.testing.assert_array_equal(np.asarray(col_a), np.asarray(col_b))
    # Remaining must reflect all 50 hits on one bucket.
    assert int(a[2][-1]) == 1000 - 50


def test_multi_schedule_threaded_matches_serial(frozen_clock):
    """The per-shard parallel workers (multi-core hosts; GIL released
    in the FFI call) must be bit-identical to the serial path —
    correctness is core-count-independent, so this pins it even on a
    one-core runner."""
    from gubernator_tpu.core.engine import PackedKeys
    from gubernator_tpu.core.native import multi_schedule

    rng = random.Random(9)
    eng_a = ShardedDecisionEngine(shard_capacity=16, clock=frozen_clock)
    _require_native(eng_a)
    eng_b = ShardedDecisionEngine(shard_capacity=16, clock=frozen_clock)
    for step in range(6):
        reqs = _fuzz_reqs(rng, 120, rng.randint(1, 96))
        keys = [r.hash_key().encode() for r in reqs]
        packed = PackedKeys.from_list(keys)
        now = frozen_clock.now_ms()
        exp = np.full(len(keys), now + 60_000, dtype=np.int64)
        a = multi_schedule(
            eng_a.tables, packed.buf, packed.offsets, None, now, exp,
            threads=1,
        )
        b = multi_schedule(
            eng_b.tables, packed.buf, packed.offsets, None, now, exp,
            threads=4,
        )
        assert a[0] == b[0], f"step {step} max_round"
        for ai, bi, label in zip(a[1:6], b[1:6],
                                 ("shard", "slots", "rounds", "order",
                                  "counts")):
            np.testing.assert_array_equal(
                ai, bi, err_msg=f"step {step} {label}"
            )
        # Evictions: same multiset per shard (inter-shard order is the
        # documented free variable).
        ev_a = sorted(zip(a[7].tolist(), a[6].tolist(), a[8].tolist()))
        ev_b = sorted(zip(b[7].tolist(), b[6].tolist(), b[8].tolist()))
        assert ev_a == ev_b, f"step {step} evictions"
        frozen_clock.advance(ms=500)


def test_multi_schedule_ttl_mirror(frozen_clock):
    """The in-call TTL writes must match the deferred set_expiry they
    replace: after the TTLs lapse, cross-batch evictions must count as
    EXPIRED (unexpired_evictions equivalence is pinned per-batch in
    test_multi_schedule_matches_fallback; this pins the absolute
    semantics across a clock jump)."""
    eng = ShardedDecisionEngine(shard_capacity=4, clock=frozen_clock)
    _require_native(eng)
    eng.apply_columnar(*_columns(_fuzz_reqs(random.Random(7), 64, 60)))
    base_unexpired = [t.unexpired_evictions for t in eng.tables]
    # Push far past every TTL, then force evictions with fresh keys —
    # every evicted slot's mirror TTL must read as lapsed.
    frozen_clock.advance(ms=10 * 60_000)
    reqs2 = [
        RateLimitReq(
            name=f"{i}fresh", unique_key=f"{i}y", hits=1, limit=10,
            duration=60_000,
        )
        for i in range(64)
    ]
    eng.apply_columnar(*_columns(reqs2))
    assert [t.unexpired_evictions for t in eng.tables] == base_unexpired, [
        (t.evictions, t.unexpired_evictions) for t in eng.tables
    ]

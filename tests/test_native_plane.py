"""Native decision plane ←→ Python ledger parity.

The plane (core/native/decision_plane.cpp) must be bit-equal to the
Python ledger (core/ledger.py) — and transitively to models/spec.py —
across grant→drain→revoke cycles, TTL expiry mid-stream, the sticky
boundary exactly at reset, and every precondition break the ledger
declines on (leaky rows, Gregorian, RESET_REMAINING, config changes,
negative hits).  The harness serves each RPC exactly the way the h2
connection threads do: dp_try_serve first (explicit clock), the Python
plan/learn path on decline; the oracle applies the identical rows
sequentially through the scalar spec.

The coherence protocol's concurrency contract is pinned separately:
racing native drains against the Python pull can only ever
UNDER-admit, and credit is conserved exactly once the dust settles.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from gubernator_tpu.clock import Clock
from gubernator_tpu.core import native_plane
from gubernator_tpu.service import COLUMNAR_DISQUALIFIERS
from gubernator_tpu.types import Algorithm, Behavior, Status

from test_ledger import Harness, SpecOracle, make_dec

if native_plane.load() is None:
    pytest.skip(
        "native decision plane unavailable (no g++?)",
        allow_module_level=True,
    )

from gubernator_tpu.net.pb import gubernator_pb2 as pb  # noqa: E402


def _encode(rows) -> bytes:
    """rows [(key, algo, behavior, hits, limit, duration, burst)] →
    GetRateLimitsReq bytes.  Keys are b"<name>_<unique>"."""
    reqs = []
    for key, algo, behavior, hits, limit, duration, burst in rows:
        name, _, uk = key.decode().partition("_")
        reqs.append(
            pb.RateLimitReq(
                name=name, unique_key=uk, hits=hits, limit=limit,
                duration=duration, algorithm=algo, behavior=behavior,
                burst=burst,
            )
        )
    return pb.GetRateLimitsReq(requests=reqs).SerializeToString()


class NativeHarness(Harness):
    """Engine + ledger + attached native plane, served RPC-shaped the
    way the h2 connection threads do it."""

    def __init__(self, clock, **kw):
        super().__init__(clock, **kw)
        self.plane = native_plane.NativeDecisionPlane(
            disqualify_mask=COLUMNAR_DISQUALIFIERS
        )
        self.ledger.attach_native(self.plane)
        self.native_answers = 0

    def serve_rpc(self, rows):
        """Native-first: the C table answers whole hot RPCs; declines
        fall to the ledger's plan/learn path (the window callback)."""
        now = self.clock.now_ms()
        out = self.plane.try_serve(
            _encode(rows), max_items=len(rows), now_ms=now
        )
        if out is not None:
            self.native_answers += len(rows)
            resp = pb.GetRateLimitsResp.FromString(out)
            assert len(resp.responses) == len(rows)
            return [
                (int(r.status), int(r.limit), int(r.remaining),
                 int(r.reset_time))
                for r in resp.responses
            ]
        st, lim, rem, rst = self.serve(make_dec(rows))
        return [
            (int(st[i]), int(lim[i]), int(rem[i]), int(rst[i]))
            for i in range(len(rows))
        ]

    def close(self):
        self.ledger.close()
        self.plane.close()


def _check_rpc(h, oracle, rows, tag=""):
    got = h.serve_rpc(rows)
    expect = oracle.serve(rows)
    for i, (e, g) in enumerate(zip(expect, got)):
        assert g == e, (
            f"{tag} row {i} key={rows[i][0]!r} hits={rows[i][3]}: "
            f"native/ledger={g} spec={e}"
        )


def _hot(key, hits=1, limit=1000, duration=60000, behavior=0, algo=0):
    return (key, algo, behavior, hits, limit, duration, 0)


def _fuzz_native(seed, n_rpcs, n_keys, lease_ttl=0.05, limit_hi=12):
    rng = np.random.default_rng(seed)
    clock = Clock().freeze()
    h = NativeHarness(
        clock, lease_size=8, lease_ttl=lease_ttl, hot_threshold=2
    )
    oracle = SpecOracle(clock)
    keys = [b"n_k%d" % i for i in range(n_keys)]
    limits = rng.integers(1, limit_hi, n_keys)
    durations = rng.integers(1, 4, n_keys) * 40
    try:
        for b in range(n_rpcs):
            clock.advance(ms=int(rng.integers(0, 12)))
            if rng.random() < 0.06:
                # Jump past resets / lease TTLs.
                clock.advance(ms=int(rng.integers(40, 200)))
            if rng.random() < 0.1:
                # Config churn: limit or duration changes mid-lease.
                j = int(rng.integers(0, n_keys))
                if rng.random() < 0.5:
                    limits[j] = int(rng.integers(1, limit_hi))
                else:
                    durations[j] = int(rng.integers(1, 4)) * 40
            rows = []
            # RPC-shaped: mostly single-item (the herd shape the
            # native path exists for), sometimes multi-item so the
            # all-or-nothing decline and the mixed pull/re-delegate
            # paths run.
            for _ in range(1 if rng.random() < 0.7 else int(rng.integers(2, 5))):
                j = int(rng.integers(0, n_keys))
                algo = (
                    int(Algorithm.LEAKY_BUCKET)
                    if rng.random() < 0.08
                    else int(Algorithm.TOKEN_BUCKET)
                )
                # Gregorian stays out: COLUMNAR_DISQUALIFIERS keeps it
                # off every columnar front (and off the plane — pinned
                # in test_native_declines_out_of_scope_rows), so the
                # oracle comparison would be vacuous here.
                behavior = 0
                if rng.random() < 0.04:
                    behavior = int(Behavior.RESET_REMAINING)
                hits = int(rng.integers(0, 4))
                if rng.random() < 0.05:
                    hits = int(rng.integers(4, 20))  # over-asks
                if rng.random() < 0.03:
                    hits = -int(rng.integers(1, 3))
                rows.append(
                    (keys[j], algo, behavior, hits, int(limits[j]),
                     int(durations[j]), int(rng.integers(0, 3)) * 7)
                )
            _check_rpc(h, oracle, rows, tag=f"rpc {b}")
    finally:
        h.close()
    # The fuzz must actually exercise the native tier.
    assert h.native_answers > 0
    assert h.ledger.stats()["leases_granted"] > 0


def test_native_parity_fuzz_vs_spec():
    _fuzz_native(seed=13, n_rpcs=300, n_keys=5)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [21, 22, 23])
def test_native_parity_fuzz_soak(seed):
    _fuzz_native(seed=seed, n_rpcs=1500, n_keys=8)


def test_native_drains_are_sequential_and_exact():
    """Steady-state herd shape: grant → delegate → every subsequent
    single-item RPC answers in C with the exact sequential remaining."""
    clock = Clock().freeze()
    h = NativeHarness(clock, lease_size=64, lease_ttl=10.0, hot_threshold=1)
    oracle = SpecOracle(clock)
    key = b"n_hot"
    for i in range(80):
        _check_rpc(h, oracle, [_hot(key, limit=10_000)], tag=f"hit {i}")
    assert h.native_answers >= 60  # all post-grant traffic native
    assert h.plane.stats()["native_answered"] == h.native_answers
    h.close()


def test_native_sticky_over_boundary_at_reset():
    """Sticky OVER must answer natively until EXACTLY the reset (now ==
    reset still answers, matching ledger.plan's `now > reset` lapse),
    and decline one past it so the engine serves the fresh window."""
    clock = Clock().freeze()
    h = NativeHarness(clock, lease_size=4, hot_threshold=100)
    oracle = SpecOracle(clock)
    key = b"n_sticky"
    rows = [_hot(key, hits=3, limit=3, duration=1000)]
    _check_rpc(h, oracle, rows)              # consumes to 0
    _check_rpc(h, oracle, rows)              # OVER via engine; learned
    native_before = h.native_answers
    _check_rpc(h, oracle, rows)              # native sticky answer
    assert h.native_answers == native_before + 1
    got = h.serve_rpc([_hot(key, hits=0, limit=3, duration=1000)])
    assert got[0][0] == int(Status.OVER_LIMIT)
    oracle.serve([_hot(key, hits=0, limit=3, duration=1000)])
    reset_ms = got[0][3]
    clock.advance(ms=reset_ms - clock.now_ms())
    native_before = h.native_answers
    _check_rpc(h, oracle, rows, tag="at reset")      # still native OVER
    assert h.native_answers == native_before + 1
    clock.advance(ms=1)
    _check_rpc(h, oracle, rows, tag="past reset")    # declined → engine
    assert h.native_answers == native_before + 1
    h.close()


def test_native_lease_ttl_expiry_mid_stream():
    """TTL expiry while delegated: the native probe declines, the
    Python path pulls the exact drained count, settles the remainder,
    and the post-expiry decisions still match the spec."""
    clock = Clock().freeze()
    h = NativeHarness(clock, lease_size=64, lease_ttl=0.02, hot_threshold=1)
    oracle = SpecOracle(clock)
    key = b"n_ttl"
    for _ in range(4):
        _check_rpc(h, oracle, [_hot(key, hits=2, limit=100)])
    assert h.native_answers > 0
    clock.advance(ms=25)  # past the lease TTL, inside the bucket window
    _check_rpc(h, oracle, [_hot(key, hits=2, limit=100)], tag="post-ttl")
    assert h.ledger.stats()["settles"] >= 1
    h.close()


def test_native_declines_out_of_scope_rows():
    """Precondition breakers and out-of-scope behaviors must never be
    answered natively, lease or no lease — they are the rows that keep
    the Python window path authoritative."""
    clock = Clock().freeze()
    h = NativeHarness(clock, lease_size=64, lease_ttl=10.0, hot_threshold=1)
    oracle = SpecOracle(clock)
    key = b"n_scope"
    for _ in range(3):
        _check_rpc(h, oracle, [_hot(key, limit=1000)])
    assert h.native_answers > 0
    now = clock.now_ms()
    for behavior in (
        int(Behavior.RESET_REMAINING),
        int(Behavior.DURATION_IS_GREGORIAN),
        int(Behavior.GLOBAL),
        int(Behavior.SKETCH),
    ):
        body = _encode([_hot(key, behavior=behavior, limit=1000)])
        assert h.plane.try_serve(body, now_ms=now) is None, behavior
    # Leaky rows and negative hits decline too.
    assert h.plane.try_serve(
        _encode([_hot(key, algo=int(Algorithm.LEAKY_BUCKET), limit=1000)]),
        now_ms=now,
    ) is None
    assert h.plane.try_serve(
        _encode([_hot(key, hits=-1, limit=1000)]), now_ms=now
    ) is None
    # Config mismatch (limit change) declines so the engine re-decides.
    assert h.plane.try_serve(
        _encode([_hot(key, limit=999)]), now_ms=now
    ) is None
    h.close()


def test_native_invalidate_keys_pulls_plane():
    """The dataclass-path coherence hook must stop native drains and
    settle off the exact pulled count before the engine runs the key
    outside the ledger."""
    clock = Clock().freeze()
    h = NativeHarness(clock, lease_size=64, lease_ttl=10.0, hot_threshold=1)
    oracle = SpecOracle(clock)
    key = b"n_inv"
    for _ in range(3):
        _check_rpc(h, oracle, [_hot(key, limit=100)])
    assert h.plane.peek(key) is not None
    h.ledger.invalidate_keys([key])
    assert h.plane.peek(key) is None
    # The unused credit is back on the device: an engine-only read sees
    # the sequential remaining.
    _, dev_rem, _ = h.device_view(key, 100, 60000)
    assert dev_rem == 100 - 3
    h.close()


def test_native_under_admission_race_bound():
    """Concurrent lane drains against a mid-flight pull: admissions
    stop the instant the pull lands, the pulled count equals the
    admitted count exactly (the mutex linearizes), and the total never
    exceeds the granted credit — the coherence protocol's
    under-admission bound."""
    plane = native_plane.NativeDecisionPlane(disqualify_mask=0)
    key = b"n_race"
    credit = 1000
    now = 1_000_000
    assert plane.install_lease(
        key, 10**6, 60000, now + 60000, 10**6, credit, 0, now + 10**6
    )
    n_threads = 8
    admitted = [0] * n_threads
    pulled = {}
    start = threading.Barrier(n_threads)

    def lane(t):
        start.wait()
        for _ in range(400):
            if plane.probe(key, 0, 0, 1, 10**6, 60000, now) is not None:
                admitted[t] += 1

    threads = [
        threading.Thread(target=lane, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    # Pull mid-race: every answer before the pull is counted in
    # `consumed`; every probe after it declines.
    res = None
    while res is None:
        res = plane.pull(key)
    pulled["consumed"] = res[1]
    for t in threads:
        t.join()
    total = sum(admitted)
    assert total == pulled["consumed"]
    assert total <= credit
    plane.close()


def test_native_coherence_race_conserves_credit():
    """Native try_serve lanes racing the Python plan path (pull →
    local answer → re-delegate churn): after everything settles, the
    device remaining must account for EVERY admitted hit exactly —
    no hit lost, none double-counted."""
    clock = Clock().freeze()
    h = NativeHarness(clock, lease_size=32, lease_ttl=10.0, hot_threshold=1)
    key = b"n_cons"
    limit = 1_000_000
    row = _hot(key, limit=limit)
    body = _encode([row])
    # Prime: two Python serves grant + delegate the lease.
    h.serve_rpc([row])
    h.serve_rpc([row])
    now = clock.now_ms()
    n_threads, per = 4, 150
    native_admits = [0] * n_threads
    stop = threading.Event()

    def lane(t):
        for _ in range(per):
            if h.plane.try_serve(body, now_ms=now) is not None:
                native_admits[t] += 1

    threads = [
        threading.Thread(target=lane, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    # Python-path churn racing the lanes: each serve pulls the lease
    # up, answers (or re-leases), and re-delegates.
    py_admits = 0
    for _ in range(30):
        st, _, _, _ = h.serve(make_dec([row]))
        assert int(st[0]) == int(Status.UNDER_LIMIT)
        py_admits += 1
    for t in threads:
        t.join()
    stop.set()
    total = sum(native_admits) + py_admits + 2  # + the priming serves
    # Settle everything: invalidate pulls the delegated lease and
    # returns its unused credit synchronously (close alone leaves a
    # LIVE lease's credit pre-debited, by design).
    h.ledger.invalidate_keys([key])
    _, dev_rem, _ = h.device_view(key, limit, 60000)
    assert dev_rem == limit - total, (dev_rem, total)
    h.ledger.close()
    h.plane.close()


def test_fast_front_native_plane_end_to_end():
    """Daemon-level: the h2 front's connection threads answer hot-key
    RPCs in C, and state stays coherent with the full gRPC listener
    (cross-front traffic pulls the lease, the sequence stays exact)."""
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.net.grpc_service import V1Stub, dial

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=1 << 12,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
        h2_fast_address="127.0.0.1:0",
        h2_fast_window=0.001,
        ledger_hot_threshold=2,
        ledger_lease_ttl=30.0,
    )
    d = spawn_daemon(conf)
    try:
        assert d.h2_fast.plane is not None
        assert d.h2_fast.lanes >= 1
        limit = 10**6
        req = pb.GetRateLimitsReq(
            requests=[
                pb.RateLimitReq(
                    name="e2e", unique_key="hot", hits=1, limit=limit,
                    duration=3_600_000,
                )
            ]
        )
        fast = V1Stub(dial(d.h2_fast_address))
        rems = [
            fast.GetRateLimits(req, timeout=10).responses[0].remaining
            for _ in range(60)
        ]
        assert rems == list(range(limit - 1, limit - 61, -1))
        assert d.h2_fast.stats()["native_rpcs"] > 0
        # Cross-front: the grpc listener continues the same sequence
        # (its plan pulls the delegated lease and re-delegates).
        full = V1Stub(dial(d.grpc_address))
        got = full.GetRateLimits(req, timeout=10).responses[0].remaining
        assert got == limit - 61
        # And back on the fast front.
        got = fast.GetRateLimits(req, timeout=10).responses[0].remaining
        assert got == limit - 62
    finally:
        d.close()


def test_fast_front_native_ledger_off():
    """GUBER_NATIVE_LEDGER=0 (config native_ledger=False) must run the
    front without a plane — the window path serves everything."""
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon
    from gubernator_tpu.net.grpc_service import V1Stub, dial

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        cache_size=1 << 12,
        peer_discovery_type="none",
        device_count=1,
        sweep_interval=0.0,
        h2_fast_address="127.0.0.1:0",
        h2_fast_window=0.001,
        native_ledger=False,
    )
    d = spawn_daemon(conf)
    try:
        assert d.h2_fast.plane is None
        stub = V1Stub(dial(d.h2_fast_address))
        got = stub.GetRateLimits(
            pb.GetRateLimitsReq(
                requests=[
                    pb.RateLimitReq(
                        name="off", unique_key="k", hits=1, limit=5,
                        duration=60_000,
                    )
                ]
            ),
            timeout=10,
        )
        assert got.responses[0].remaining == 4
        assert d.h2_fast.stats()["native_rpcs"] == 0
    finally:
        d.close()

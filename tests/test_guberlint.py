"""guberlint proves each pass catches its seeded bad fixture.

Each case writes a known-bad snippet, runs the pass directly, and
asserts the finding (and that the suppression escape hatch silences
it).  STATIC_ANALYSIS.md documents the grammar these fixtures pin.
"""

import json
import textwrap
from pathlib import Path

import pytest

from tools.guberlint import baseline as baseline_mod
from tools.guberlint import lockcheck, threadcheck, tracecheck
from tools.guberlint.common import Finding, SourceFile


def _src(tmp_path: Path, code: str, name: str = "fix.py") -> SourceFile:
    p = tmp_path / name
    p.write_text(textwrap.dedent(code))
    return SourceFile(p, name)


def _lock_findings(src):
    edges = set()
    out = lockcheck.check_file(src, edges)
    return out, edges


# ---------------------------------------------------------------- lock


LOCK_BAD = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0  # guberlint: guarded-by _lock

        def good(self):
            with self._lock:
                self._n += 1

        def bad(self):
            return self._n
"""


def test_lock_pass_catches_unguarded_access(tmp_path):
    findings, _ = _lock_findings(_src(tmp_path, LOCK_BAD))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "unguarded-access"
    assert f.scope == "Counter.bad"
    assert "self._n" in f.message


def test_lock_pass_suppression_escape_hatch(tmp_path):
    code = LOCK_BAD.replace(
        "return self._n",
        "return self._n  # guberlint: ok lock — racy read tolerated, metrics only",
    )
    findings, _ = _lock_findings(_src(tmp_path, code))
    assert findings == []


def test_lock_pass_suppression_requires_reason(tmp_path):
    code = LOCK_BAD.replace(
        "return self._n", "return self._n  # guberlint: ok lock"
    )
    src = _src(tmp_path, code)
    assert any(
        f.rule == "bad-suppression" for f in src.bad_suppressions
    ), "reasonless suppression must itself be a finding"


def test_lock_pass_holds_annotation_and_locked_convention(tmp_path):
    code = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guberlint: guarded-by _lock

            def _bump_locked(self):
                self._n += 1

            def bump_held(self):  # guberlint: holds _lock
                self._n += 1
    """
    findings, _ = _lock_findings(_src(tmp_path, code))
    assert findings == []


def test_lock_pass_condition_alias(tmp_path):
    code = """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._items = []  # guberlint: guarded-by _lock

            def put(self, x):
                with self._cv:
                    self._items.append(x)
    """
    findings, _ = _lock_findings(_src(tmp_path, code))
    assert findings == [], "acquiring the condition acquires the wrapped lock"


def test_lock_pass_nested_def_resets_held_context(tmp_path):
    code = """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guberlint: guarded-by _lock

            def kick(self, pool):
                with self._lock:
                    def later():
                        return self._items.pop()
                    pool.submit(later)
    """
    findings, _ = _lock_findings(_src(tmp_path, code))
    assert len(findings) == 1, "closure may run after the with exits"


def test_lock_order_inversion_detected(tmp_path):
    code = """
        import threading

        class AB:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self.x = 0  # guberlint: guarded-by _a_lock

            def fwd(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def rev(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """
    _, edges = _lock_findings(_src(tmp_path, code))
    cyc = lockcheck.order_findings(edges)
    assert len(cyc) == 1
    assert cyc[0].rule == "lock-order-inversion"
    assert "AB._a_lock" in cyc[0].message and "AB._b_lock" in cyc[0].message


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    code = """
        import threading

        class AB:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self.x = 0  # guberlint: guarded-by _a_lock

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
    """
    _, edges = _lock_findings(_src(tmp_path, code))
    assert lockcheck.order_findings(edges) == []


# --------------------------------------------------------------- trace


def test_trace_pass_catches_tracer_branch(tmp_path):
    code = """
        import jax
        import jax.numpy as jnp

        # guberlint: shapes x [n] on the pad ladder
        @jax.jit
        def f(x):
            if x.sum() > 0:
                return x
            return -x
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["trace-branch"]


def test_trace_pass_static_shape_branch_ok(tmp_path):
    code = """
        import jax

        # guberlint: shapes x [n] on the pad ladder
        @jax.jit
        def f(x):
            if x.shape[0] > 4:
                return x
            return x + 1
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert findings == [], "shape tests are static under trace"


def test_trace_pass_static_argnames_not_tainted(tmp_path):
    code = """
        import jax
        from functools import partial

        # guberlint: shapes x [n]; window static
        @partial(jax.jit, static_argnames=("window",))
        def f(x, window):
            if window > 4:
                return x
            return x + 1
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert findings == []


def test_trace_pass_catches_host_transfer(tmp_path):
    code = """
        import jax
        import numpy as np

        # guberlint: shapes x [n]
        @jax.jit
        def f(x):
            y = x + 1
            return np.asarray(y)
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["trace-transfer"]


def test_trace_pass_transfer_reaches_helpers(tmp_path):
    code = """
        import jax

        def helper(v):
            return float(v)

        # guberlint: shapes x [n]
        @jax.jit
        def f(x):
            return helper(x * 2)
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert any(
        f.rule == "trace-transfer" and f.scope == "helper" for f in findings
    ), "helpers called from jit roots execute traced"


def test_trace_pass_requires_shapes_annotation(tmp_path):
    code = """
        import jax

        @jax.jit
        def f(x):
            return x + 1
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["trace-shapes"]
    # ... and the annotation satisfies it (any of the eligible lines).
    ok = code.replace(
        "@jax.jit", "# guberlint: shapes x [n] padded pow2\n@jax.jit"
    )
    assert tracecheck.check_file(_src(tmp_path, ok, "ok.py")) == []


def test_trace_pass_suppression(tmp_path):
    code = """
        import jax

        # guberlint: ok trace — host callback by design (io_callback wrapper)
        @jax.jit
        def f(x):
            return x + 1
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert findings == []


# -------------------------------------------------------------- thread


def test_thread_pass_catches_orphan_daemon(tmp_path):
    code = """
        import threading

        class Svc:
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                pass
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["thread-orphan"]


def test_thread_pass_join_via_local_alias_ok(tmp_path):
    """`shipper = self._shipper` under the lock, then
    `shipper.join()` — the snapshot-under-lock shape the lock pass
    encourages for guarded thread handles — must count as a join path
    (membership.close regression, post-PR-3 audit)."""
    code = """
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self._t = threading.Thread(target=print, daemon=True)
                self._t.start()

            def close(self):
                with self._lock:
                    t = self._t
                t.join(timeout=5.0)
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert findings == []


def test_thread_pass_start_before_publish_ok(tmp_path):
    """`t = Thread(...); t.start(); self._t = t` — start-before-publish
    (so close() can never join an unstarted thread) still counts as a
    self-owned thread with a class join path."""
    code = """
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                t = threading.Thread(target=print, daemon=True)
                t.start()
                self._t = t

            def close(self):
                with self._lock:
                    t = self._t
                t.join(timeout=5.0)
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert findings == []


def test_thread_pass_joined_daemon_ok(tmp_path):
    code = """
        import threading

        class Svc:
            def __init__(self):
                self._stop = threading.Event()
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                while not self._stop.wait(1.0):
                    pass

            def close(self):
                self._stop.set()
                self._t.join(timeout=2.0)
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert findings == []


def test_thread_pass_local_threads_joined_via_loop(tmp_path):
    code = """
        import threading

        def run(n):
            threads = [
                threading.Thread(target=print, daemon=True) for _ in range(n)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert findings == []


def test_thread_pass_fire_and_forget_needs_suppression(tmp_path):
    code = """
        import threading

        def kick(fn):
            threading.Thread(target=fn, daemon=True).start()
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["thread-orphan"]
    ok = code.replace(
        "    threading.Thread",
        "    # guberlint: ok thread — bounded one-shot drain\n"
        "    threading.Thread",
    )
    assert threadcheck.check_file(_src(tmp_path, ok, "ok.py")) == []


def test_thread_pass_catches_silent_swallow(tmp_path):
    code = """
        import threading

        def loop():
            while True:
                try:
                    work()
                except Exception:
                    pass
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["thread-swallow"]


def test_thread_pass_logged_swallow_ok(tmp_path):
    code = """
        import logging
        import threading

        def loop():
            while True:
                try:
                    work()
                except Exception:
                    logging.getLogger("x").exception("work failed")
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert findings == []


def test_thread_pass_non_threaded_module_exempt_from_swallow(tmp_path):
    code = """
        def f():
            try:
                work()
            except Exception:
                pass
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert findings == []


# ------------------------------------------------------------ baseline


def test_baseline_round_trip_and_staleness(tmp_path):
    f1 = Finding("lock", "unguarded-access", "a.py", 3, "C.m", "self.x", "x")
    f2 = Finding("trace", "trace-branch", "b.py", 9, "f", "if@f", "y")
    path = tmp_path / "base.json"
    baseline_mod.save(path, [f1, f2])
    base = baseline_mod.load(path)
    assert len(base) == 2
    # f2 fixed; f3 new.
    f3 = Finding("thread", "thread-orphan", "c.py", 1, "S", "thread@S._t", "z")
    new, accepted, stale = baseline_mod.partition([f1, f3], base)
    assert [f.rule for f in new] == ["thread-orphan"]
    assert [f.rule for f in accepted] == ["unguarded-access"]
    assert len(stale) == 1 and stale[0][1] == "trace-branch"


def test_baseline_save_preserves_audit_record(tmp_path):
    path = tmp_path / "base.json"
    path.write_text(json.dumps({"findings": [], "audited_clean": {"lock": {}}}))
    baseline_mod.save(path, [])
    assert "audited_clean" in json.loads(path.read_text())


def test_repo_is_clean_against_committed_baseline():
    """The acceptance gate: `python -m tools.guberlint` exits 0."""
    from tools.guberlint.__main__ import main

    assert main([]) == 0


# ----------------------------------------------------- fix-annotations


def test_fix_annotations_inserts_stub(tmp_path, monkeypatch):
    import tools.guberlint.__main__ as main_mod

    p = tmp_path / "mod.py"
    p.write_text(
        textwrap.dedent(
            """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1
            """
        )
    )
    monkeypatch.setattr(main_mod, "REPO_ROOT", tmp_path)
    inserted = main_mod.fix_annotations([p])
    assert inserted == 1
    assert "self._n = 0  # guberlint: guarded-by _lock" in p.read_text()
    # The annotated file now verifies clean.
    src = SourceFile(p, "mod.py")
    findings, _ = _lock_findings(src)
    assert findings == []


def test_fix_annotations_skips_mixed_lock_attrs(tmp_path, monkeypatch):
    import tools.guberlint.__main__ as main_mod

    p = tmp_path / "mod.py"
    p.write_text(
        textwrap.dedent(
            """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def read(self):
                    return self._n
            """
        )
    )
    monkeypatch.setattr(main_mod, "REPO_ROOT", tmp_path)
    assert main_mod.fix_annotations([p]) == 0, (
        "an attr with unlocked accesses must not get a stub"
    )


# ----------------------------------------------------------------- net


NET_RETRY_BAD = """
    from gubernator_tpu.cluster.peer_client import PeerError

    def forward(groups, pick):
        while groups:
            retry = []
            for p, ids in groups:
                try:
                    p.rpc(ids)
                except PeerError as e:
                    if e.not_ready:
                        retry.extend(ids)
                        continue
            groups = pick(retry)
"""


def test_net_pass_catches_retry_without_backoff(tmp_path):
    from tools.guberlint import netcheck

    findings = netcheck.check_file(_src(tmp_path, NET_RETRY_BAD))
    assert any(f.rule == "net-retry-no-backoff" for f in findings)


def test_net_pass_backoff_in_enclosing_loop_ok(tmp_path):
    from tools.guberlint import netcheck

    code = NET_RETRY_BAD.replace(
        "            groups = pick(retry)",
        "            time.sleep(backoff_delay(1, 0.01, 0.25))\n"
        "            groups = pick(retry)",
    )
    findings = netcheck.check_file(_src(tmp_path, code))
    assert not [f for f in findings if f.rule == "net-retry-no-backoff"]


def test_net_pass_log_and_continue_is_not_a_retry_loop(tmp_path):
    """multiregion-style per-peer iteration: catching PeerError to
    skip a peer (no not_ready decision, no retry collection) is not a
    retry loop and must not demand backoff."""
    from tools.guberlint import netcheck

    code = """
        from gubernator_tpu.cluster.peer_client import PeerError

        def send_all(by_peer, log):
            for addr, reqs in by_peer.items():
                try:
                    addr.rpc(reqs)
                except PeerError as e:
                    log.error("send to %s failed: %s", addr, e)
                    continue
    """
    findings = netcheck.check_file(_src(tmp_path, code))
    assert not [f for f in findings if f.rule == "net-retry-no-backoff"]


def test_net_pass_flags_backoffless_crossregion_retry(tmp_path):
    """ISSUE 14: the multiregion log-and-continue exemption is gone —
    a cross-region push loop that RE-QUEUES failed deltas (a requeue
    IS a retry decision, one window removed) without any backoff must
    flag.  The live multiregion send path passes because its handler
    computes a backoff_delay for the deferred requeue."""
    from tools.guberlint import netcheck

    code = """
        from gubernator_tpu.cluster.peer_client import PeerError

        def push_regions(self, by_region, conf):
            for region, (peer, reqs) in by_region.items():
                try:
                    peer.send_peer_hits(
                        reqs, timeout=conf.multi_region_timeout
                    )
                except PeerError as e:
                    self._requeue_region(region, reqs)
                    continue
    """
    findings = netcheck.check_file(_src(tmp_path, code))
    assert any(f.rule == "net-retry-no-backoff" for f in findings), (
        findings
    )


def test_net_pass_crossregion_retry_with_backoff_ok(tmp_path):
    """The §12 multiregion shape: the handler computes a capped
    full-jitter backoff_delay for the deferred requeue — clean."""
    from tools.guberlint import netcheck

    code = """
        from gubernator_tpu.cluster.peer_client import PeerError
        from gubernator_tpu.cluster.health import backoff_delay

        def push_regions(self, by_region, conf):
            for region, (peer, reqs) in by_region.items():
                try:
                    peer.send_peer_hits(
                        reqs, timeout=conf.multi_region_timeout
                    )
                except PeerError as e:
                    delay = backoff_delay(
                        self.attempts.get(region, 0), 0.05, 2.0
                    )
                    self._requeue_region(region, reqs, delay)
                    continue
    """
    findings = netcheck.check_file(_src(tmp_path, code))
    assert not [
        f for f in findings if f.rule == "net-retry-no-backoff"
    ], findings


def test_net_pass_catches_rpc_without_timeout(tmp_path):
    from tools.guberlint import netcheck

    code = """
        def flush(peer, reqs):
            peer.send_peer_hits(reqs)
    """
    findings = netcheck.check_file(_src(tmp_path, code))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "net-rpc-no-timeout"
    assert "send_peer_hits" in f.message


def test_net_pass_rpc_with_timeout_ok(tmp_path):
    from tools.guberlint import netcheck

    code = """
        def flush(peer, reqs, conf):
            peer.send_peer_hits(reqs, timeout=conf.global_timeout)
    """
    assert netcheck.check_file(_src(tmp_path, code)) == []


def test_net_pass_server_side_receivers_exempt(tmp_path):
    from tools.guberlint import netcheck

    code = """
        class Adapter:
            def handle(self, reqs):
                return self.instance.get_peer_rate_limits(reqs)

        class Client:
            def one(self, req):
                return self.get_peer_rate_limits([req], timeout=1.0)
    """
    assert netcheck.check_file(_src(tmp_path, code)) == []


def test_net_pass_suppression_escape_hatch(tmp_path):
    from tools.guberlint import netcheck

    code = """
        def flush(peer, reqs):
            peer.send_peer_hits(reqs)  # guberlint: ok net — probe uses channel default
    """
    assert netcheck.check_file(_src(tmp_path, code)) == []


# Handoff RPC discipline (ISSUE 7): TransferBuckets call sites are held
# to the same rules as every peer RPC — an epoch commit waits on the
# sender, so an unbudgeted send or a backoff-free retry loop stalls a
# membership transition, not just one request.

HANDOFF_BAD = """
    from gubernator_tpu.cluster.peer_client import PeerError

    def ship(pending, window):
        while pending:
            for addr, (peer, rows) in list(pending.items()):
                try:
                    peer.transfer_buckets_raw(rows[:window])
                except PeerError as e:
                    if e.not_ready:
                        continue
                pending.pop(addr)
"""


def test_net_pass_catches_handoff_rpc_without_timeout(tmp_path):
    from tools.guberlint import netcheck

    findings = netcheck.check_file(_src(tmp_path, HANDOFF_BAD))
    assert any(
        f.rule == "net-rpc-no-timeout"
        and "transfer_buckets_raw" in f.message
        for f in findings
    )


def test_net_pass_catches_handoff_retry_without_backoff(tmp_path):
    from tools.guberlint import netcheck

    findings = netcheck.check_file(_src(tmp_path, HANDOFF_BAD))
    assert any(f.rule == "net-retry-no-backoff" for f in findings)


def test_net_pass_handoff_with_timeout_and_backoff_ok(tmp_path):
    from tools.guberlint import netcheck

    code = """
        import time
        from gubernator_tpu.cluster.health import backoff_delay
        from gubernator_tpu.cluster.peer_client import PeerError

        def ship(pending, window, deadline):
            attempt = 0
            while pending:
                for addr, (peer, rows) in list(pending.items()):
                    try:
                        peer.transfer_buckets_raw(rows[:window], timeout=1.0)
                    except PeerError as e:
                        if e.not_ready:
                            continue
                    pending.pop(addr)
                time.sleep(backoff_delay(attempt, 0.01, 0.25))
                attempt += 1
    """
    assert netcheck.check_file(_src(tmp_path, code)) == []


# Replication RPC discipline (the hot-key promotion plane): the
# ReplicateKeys call sites are held to the same rules — an unbudgeted
# grant stalls the owner's whole promotion tick, and a backoff-free
# grant-retry loop would hammer a broken replica the health plane
# already refused.

REPLICATION_BAD = """
    from gubernator_tpu.cluster.peer_client import PeerError

    def grant_all(peers, payload):
        retry = list(peers)
        while retry:
            for peer in list(retry):
                try:
                    peer.replicate_keys_raw(payload)
                except PeerError as e:
                    if e.not_ready:
                        retry.append(peer)
                        continue
                retry.remove(peer)
"""


def test_net_pass_catches_replication_rpc_without_timeout(tmp_path):
    from tools.guberlint import netcheck

    findings = netcheck.check_file(_src(tmp_path, REPLICATION_BAD))
    assert any(
        f.rule == "net-rpc-no-timeout"
        and "replicate_keys_raw" in f.message
        for f in findings
    )


def test_net_pass_catches_replication_retry_without_backoff(tmp_path):
    from tools.guberlint import netcheck

    findings = netcheck.check_file(_src(tmp_path, REPLICATION_BAD))
    assert any(f.rule == "net-retry-no-backoff" for f in findings)


def test_net_pass_replication_with_timeout_and_backoff_ok(tmp_path):
    from tools.guberlint import netcheck

    code = """
        import time
        from gubernator_tpu.cluster.health import backoff_delay
        from gubernator_tpu.cluster.peer_client import PeerError

        def grant_all(peers, payload, conf):
            retry = list(peers)
            attempt = 0
            while retry:
                for peer in list(retry):
                    try:
                        peer.replicate_keys_raw(
                            payload, timeout=conf.global_timeout
                        )
                    except PeerError as e:
                        if e.not_ready:
                            retry.append(peer)
                            continue
                    retry.remove(peer)
                time.sleep(backoff_delay(attempt, 0.01, 0.25))
                attempt += 1
    """
    assert netcheck.check_file(_src(tmp_path, code)) == []


# -------------------------------------------------------------- native
# The C tier (tools/guberlint/csource.py + nativecheck.py): each rule
# proves it fires on a seeded bad fixture and that the escape hatches
# (suppression, *_locked, holds) work — mirroring the Python passes.


def _csrc(tmp_path: Path, code: str, name: str = "fix.cpp"):
    from tools.guberlint.csource import CSourceFile

    p = tmp_path / name
    p.write_text(textwrap.dedent(code))
    return CSourceFile(p, name)


C_GUARD_BAD = """
    #include <mutex>

    struct Plane {
      std::mutex mu;
      long count = 0;  // guberlint: guarded-by mu
    };

    void good(Plane* p) {
      std::lock_guard<std::mutex> lock(p->mu);
      ++p->count;
    }

    long bad(Plane* p) {
      return p->count;
    }
"""


def test_native_pass_catches_unguarded_c_field(tmp_path):
    from tools.guberlint import nativecheck

    findings = nativecheck.check_files([_csrc(tmp_path, C_GUARD_BAD)])
    assert [f.rule for f in findings] == ["unguarded-access"]
    f = findings[0]
    assert f.scope == "bad" and f.detail == "Plane.count"


def test_native_pass_suppression_and_locked_convention(tmp_path):
    from tools.guberlint import nativecheck

    ok = C_GUARD_BAD.replace(
        "    long bad(Plane* p) {\n      return p->count;\n    }",
        "    long read_locked(Plane* p) {\n      return p->count;\n    }\n"
        "\n"
        "    // guberlint: holds mu\n"
        "    long read_held(Plane* p) {\n      return p->count;\n    }\n"
        "\n"
        "    long scrape(Plane* p) {\n"
        "      return p->count;  // guberlint: ok native — racy stats read tolerated\n"
        "    }",
    )
    assert nativecheck.check_files([_csrc(tmp_path, ok)]) == []


def test_native_pass_struct_registry_form(tmp_path):
    from tools.guberlint import nativecheck

    code = """
        #include <mutex>

        struct S {
          // guberlint: guard a, b by mu
          std::mutex mu;
          long a = 0;
          long b = 0;
        };

        long bad(S* s) { return s->a + s->b; }
    """
    findings = nativecheck.check_files([_csrc(tmp_path, code)])
    assert sorted(f.detail for f in findings) == ["S.a", "S.b"]


def test_native_pass_member_function_bare_access(tmp_path):
    from tools.guberlint import nativecheck

    code = """
        #include <mutex>

        struct Conn {
          // guberlint: guard window by write_mu
          std::mutex write_mu;
          long window = 0;

          void good() {
            std::lock_guard<std::mutex> lock(write_mu);
            ++window;
          }

          long bad() { return window; }
        };
    """
    findings = nativecheck.check_files([_csrc(tmp_path, code)])
    assert [(f.scope, f.detail) for f in findings] == [("bad", "Conn.window")]


def test_native_pass_gil_violation_direct_and_transitive(tmp_path):
    from tools.guberlint import nativecheck

    code = """
        long helper(long x) {
          PyGILState_Ensure();
          return x;
        }

        // guberlint: gil-free
        long serve(long x) {
          return helper(x);
        }
    """
    findings = nativecheck.check_files([_csrc(tmp_path, code)])
    assert [f.rule for f in findings] == ["gil-call"]
    assert findings[0].scope == "serve"
    assert "PyGILState_Ensure" in findings[0].message


def test_native_pass_gil_callback_trampoline(tmp_path):
    from tools.guberlint import nativecheck

    code = """
        struct Srv { long (*callback)(long); };

        // guberlint: gil-free
        long serve(Srv* s) {
          return s->callback(1);
        }
    """
    findings = nativecheck.check_files([_csrc(tmp_path, code)])
    assert [f.rule for f in findings] == ["gil-call"]
    assert "callback" in findings[0].detail


def test_native_pass_gil_free_clean_path_ok(tmp_path):
    from tools.guberlint import nativecheck

    code = """
        long helper(long x) { return x * 2; }

        // guberlint: gil-free
        long serve(long x) { return helper(x); }
    """
    assert nativecheck.check_files([_csrc(tmp_path, code)]) == []


def test_native_pass_blocking_call_under_mutex(tmp_path):
    from tools.guberlint import nativecheck

    code = """
        #include <mutex>

        struct C { std::mutex mu; int fd; };

        void bad(C* c, const char* buf, long n) {
          std::lock_guard<std::mutex> lock(c->mu);
          send(c->fd, buf, n, 0);
        }

        void fine(C* c, const char* buf, long n) {
          send(c->fd, buf, n, 0);
        }
    """
    findings = nativecheck.check_files([_csrc(tmp_path, code)])
    assert [f.rule for f in findings] == ["blocking-under-lock"]
    assert findings[0].scope == "bad"
    ok = code.replace(
        "          send(c->fd, buf, n, 0);\n        }\n\n        void fine",
        "          // guberlint: ok native — bounded by the socket buffer\n"
        "          send(c->fd, buf, n, 0);\n        }\n\n        void fine",
    )
    assert nativecheck.check_files([_csrc(tmp_path, ok, "ok.cpp")]) == []


def test_native_pass_atomic_order_needs_reason(tmp_path):
    from tools.guberlint import nativecheck

    code = """
        #include <atomic>

        void f(std::atomic<long>* a) {
          a->fetch_add(1, std::memory_order_relaxed);
        }
    """
    findings = nativecheck.check_files([_csrc(tmp_path, code)])
    assert [f.rule for f in findings] == ["atomic-order"]
    ok = code.replace(
        "std::memory_order_relaxed);",
        "std::memory_order_relaxed);  // guberlint: ok native — join publishes",
    )
    assert nativecheck.check_files([_csrc(tmp_path, ok, "ok.cpp")]) == []


def test_native_pass_blocking_in_reactor(tmp_path):
    """The epoll-root reachability rule: send/recv without
    MSG_DONTWAIT and accept without SOCK_NONBLOCK flag anywhere in
    the call graph under an epoll loop root — directly or
    transitively."""
    from tools.guberlint import nativecheck

    code = """
        #include <sys/socket.h>

        void drain(int fd) {
          char buf[64];
          recv(fd, buf, sizeof(buf), 0);
        }

        // guberlint: epoll-root
        void loop(int epfd, int lfd) {
          int c = accept(lfd, nullptr, nullptr);
          (void)c;
          drain(lfd);
        }
    """
    findings = nativecheck.check_files([_csrc(tmp_path, code)])
    assert sorted(f.rule for f in findings) == [
        "blocking-in-reactor", "blocking-in-reactor",
    ]
    details = sorted(f.detail for f in findings)
    assert details == ["loop->accept", "loop->recv"]
    assert all(f.scope == "loop" for f in findings)
    # The transitive finding names the path and the real call site.
    recv_f = [f for f in findings if f.detail == "loop->recv"][0]
    assert "loop->drain" in recv_f.message


def test_native_pass_reactor_nonblocking_and_suppression_ok(tmp_path):
    """Nonblocking variants (MSG_DONTWAIT, accept4+SOCK_NONBLOCK) and
    reasoned call-site suppressions pass; functions NOT under an
    epoll root may block freely."""
    from tools.guberlint import nativecheck

    code = """
        #include <sys/socket.h>

        void drain(int fd) {
          char buf[64];
          recv(fd, buf, sizeof(buf), MSG_DONTWAIT);
        }

        void legacy_branch(int fd) {
          char buf[64];
          // guberlint: ok native — threaded-plane branch, runtime-gated off the reactor
          send(fd, buf, sizeof(buf), 0);
        }

        // guberlint: epoll-root
        void loop(int epfd, int lfd) {
          int c = accept4(lfd, nullptr, nullptr, SOCK_NONBLOCK);
          (void)c;
          drain(lfd);
          legacy_branch(lfd);
        }

        void not_a_reactor(int fd) {
          char buf[64];
          recv(fd, buf, sizeof(buf), 0);  // blocking is fine here
        }
    """
    assert nativecheck.check_files([_csrc(tmp_path, code)]) == []


def test_native_pass_reasonless_c_suppression_is_a_finding(tmp_path):
    from tools.guberlint import nativecheck

    code = C_GUARD_BAD.replace(
        "      return p->count;",
        "      return p->count;  // guberlint: ok native",
    )
    findings = nativecheck.check_files([_csrc(tmp_path, code)])
    assert any(f.rule == "bad-suppression" for f in findings)


# ------------------------------------------------------------ contract
# The Python<->C boundary pins: each fixture mutates ONE side and the
# pass must trip (the acceptance criterion).


def _contract_repo(tmp_path: Path, proto: str) -> Path:
    root = tmp_path / "repo"
    pdir = root / "gubernator_tpu" / "net" / "proto"
    pdir.mkdir(parents=True)
    (pdir / "contract.proto").write_text(textwrap.dedent(proto))
    return root


CONTRACT_PROTO = """
    syntax = "proto3";
    message Ping {
      string name = 1;
      int64 hits = 2;
    }
    enum Verdict {
      UNDER = 0;
      OVER = 1;
    }
"""

CONTRACT_CPP_OK = """
    // guberlint: wire Ping name=1:len hits=2:varint
    long encode(long* out) {
      out[0] = (1 << 3) | 2;
      out[1] = (2 << 3) | 0;
      return 2;
    }
"""


def _contract_check(root, csrc, **kw):
    from tools.guberlint import contractcheck

    kw.setdefault(
        "proto_files", ("gubernator_tpu/net/proto/contract.proto",)
    )
    kw.setdefault("constants", ())
    kw.setdefault("enum_contracts", ())
    return contractcheck.check([csrc], root, **kw)


def test_contract_pass_wire_clean_when_aligned(tmp_path):
    root = _contract_repo(tmp_path, CONTRACT_PROTO)
    assert _contract_check(root, _csrc(tmp_path, CONTRACT_CPP_OK)) == []


def test_contract_pass_trips_on_proto_field_move(tmp_path):
    """Mutating the PYTHON-side contract (the proto the pb codec is
    generated from) trips the pin."""
    root = _contract_repo(
        tmp_path, CONTRACT_PROTO.replace("int64 hits = 2;", "int64 hits = 9;")
    )
    findings = _contract_check(root, _csrc(tmp_path, CONTRACT_CPP_OK))
    assert [f.rule for f in findings] == ["wire-mismatch"]
    assert findings[0].detail == "Ping.hits"


def test_contract_pass_trips_on_c_literal_move(tmp_path):
    """Mutating the C side (the tag literal) trips both directions of
    the code pin: the declared field is no longer built, and an
    undeclared number appears."""
    root = _contract_repo(tmp_path, CONTRACT_PROTO)
    bad = CONTRACT_CPP_OK.replace("(2 << 3) | 0", "(9 << 3) | 0")
    findings = _contract_check(root, _csrc(tmp_path, bad))
    assert sorted(f.rule for f in findings) == [
        "wire-undeclared-field", "wire-unimplemented-field",
    ]


def test_contract_pass_trips_on_annotation_drift(tmp_path):
    root = _contract_repo(tmp_path, CONTRACT_PROTO)
    bad = CONTRACT_CPP_OK.replace("hits=2:varint", "hits=2:len")
    findings = _contract_check(root, _csrc(tmp_path, bad))
    assert [f.rule for f in findings] == ["wire-mismatch"]


def test_contract_pass_decode_idioms_recognized(tmp_path):
    root = _contract_repo(tmp_path, CONTRACT_PROTO)
    code = """
        // guberlint: wire Ping name=1:len hits=2:varint
        long decode(const unsigned char* p, long tag) {
          if ((tag >> 3) != 1) return -1;
          long field = tag;
          if (field == 2) return 2;
          return 0;
        }
    """
    assert _contract_check(root, _csrc(tmp_path, code)) == []


def test_contract_pass_constant_mismatch(tmp_path):
    root = _contract_repo(tmp_path, CONTRACT_PROTO)
    (root / "gubernator_tpu" / "core").mkdir(parents=True)
    (root / "gubernator_tpu" / "core" / "ledger.py").write_text(
        "_K_OVER = 1\n_K_LEASE = 2\n"
    )
    cpp = _csrc(
        tmp_path,
        "constexpr int kOver = 3, kLease = 2;\nlong f(long x) { return x; }\n",
        "plane.cpp",
    )
    cpp.rel = "plane.cpp"
    findings = _contract_check(
        root, cpp,
        constants=(
            ("plane.cpp", "kOver", "gubernator_tpu/core/ledger.py", "_K_OVER"),
            ("plane.cpp", "kLease", "gubernator_tpu/core/ledger.py", "_K_LEASE"),
        ),
    )
    assert [f.rule for f in findings] == ["constant-mismatch"]
    assert "kOver" in findings[0].detail


def test_contract_pass_enum_mismatch(tmp_path):
    root = _contract_repo(tmp_path, CONTRACT_PROTO)
    (root / "gubernator_tpu").mkdir(exist_ok=True)
    (root / "gubernator_tpu" / "types.py").write_text(
        textwrap.dedent(
            """
            import enum

            class Verdict(enum.IntEnum):
                UNDER = 0
                OVER = 5
            """
        )
    )
    findings = _contract_check(
        root, _csrc(tmp_path, CONTRACT_CPP_OK),
        enum_contracts=(("Verdict", "gubernator_tpu/types.py"),),
    )
    assert [f.rule for f in findings] == ["enum-mismatch"]
    assert findings[0].detail == "Verdict.OVER"


def test_contract_pass_c_getenv_needs_config_home(tmp_path):
    root = _contract_repo(tmp_path, CONTRACT_PROTO)
    (root / "gubernator_tpu" / "config.py").write_text(
        '"""knobs"""\nKNOWN = ("GUBER_REAL_KNOB",)\n'
    )
    code = """
        #include <cstdlib>
        long f() {
          const char* a = getenv("GUBER_REAL_KNOB");
          const char* b = getenv("GUBER_PHANTOM_KNOB");
          return (a != 0) + (b != 0);
        }
    """
    findings = _contract_check(
        root, _csrc(tmp_path, code),
        knob_home="gubernator_tpu/config.py",
    )
    assert [f.rule for f in findings] == ["knob-homeless"]
    assert findings[0].detail == "GUBER_PHANTOM_KNOB"


def test_contract_repo_constants_actually_resolve():
    """The committed CONTRACT_CONSTANTS pairs must all resolve — an
    unresolved pin (rename without updating config) is itself caught,
    but a silently-empty table would check nothing."""
    from pathlib import Path as P

    from tools.guberlint import contractcheck
    from tools.guberlint.__main__ import REPO_ROOT
    from tools.guberlint.config import CONTRACT_CONSTANTS
    from tools.guberlint.csource import iter_c_files

    csrcs = iter_c_files(
        [REPO_ROOT / "gubernator_tpu" / "core" / "native"], REPO_ROOT
    )
    findings = contractcheck.check(csrcs, P(REPO_ROOT))
    assert not [f for f in findings if f.rule == "constant-unresolved"]
    assert len(CONTRACT_CONSTANTS) >= 3


# --------------------------------------------------------------- drift


def _drift_repo(tmp_path: Path) -> Path:
    root = tmp_path / "repo"
    (root / "gubernator_tpu" / "utils").mkdir(parents=True)
    (root / "scripts").mkdir()
    (root / "gubernator_tpu" / "config.py").write_text(
        'KNOWN = ("GUBER_DOCUMENTED",)\n'
    )
    (root / "gubernator_tpu" / "mod.py").write_text(
        'import os\n'
        'A = os.environ.get("GUBER_DOCUMENTED")\n'
        'B = os.environ.get("GUBER_ORPHAN")\n'
    )
    (root / "gubernator_tpu" / "utils" / "metrics.py").write_text(
        textwrap.dedent(
            """
            from prometheus_client.core import CounterMetricFamily

            def collect():
                yield CounterMetricFamily("gubernator_documented_total", "d")
                yield CounterMetricFamily("gubernator_secret_total", "s")
            """
        )
    )
    (root / "README.md").write_text(
        "| `GUBER_DOCUMENTED` | - | a knob |\n"
        "`gubernator_documented_total` counts things.\n"
    )
    (root / "PERF.md").write_text("perf notes\n")
    (root / "RESILIENCE.md").write_text("resilience notes\n")
    (root / "STATIC_ANALYSIS.md").write_text("lint notes\n")
    return root


def test_drift_pass_orphan_knob_and_undocumented_metric(tmp_path):
    from tools.guberlint import driftcheck

    findings = driftcheck.check(_drift_repo(tmp_path), [])
    rules = sorted((f.rule, f.detail) for f in findings)
    assert ("knob-no-config-home", "GUBER_ORPHAN") in rules
    assert ("knob-undocumented", "GUBER_ORPHAN") in rules
    assert ("metric-undocumented", "gubernator_secret_total") in rules
    assert not any(r == "knob-stale" for r, _ in rules)
    assert not any(
        d == "GUBER_DOCUMENTED" or d == "gubernator_documented_total"
        for _, d in rules
    )


def test_drift_pass_stale_doc_rows(tmp_path):
    from tools.guberlint import driftcheck

    root = _drift_repo(tmp_path)
    (root / "README.md").write_text(
        "| `GUBER_DOCUMENTED` | - | a knob |\n"
        "| `GUBER_GHOST` | - | removed years ago |\n"
        "`gubernator_documented_total` and `gubernator_ghost_total`.\n"
    )
    findings = driftcheck.check(root, [])
    details = {(f.rule, f.detail) for f in findings}
    assert ("knob-stale", "GUBER_GHOST") in details
    assert ("metric-stale", "gubernator_ghost_total") in details


def _slo_repo(tmp_path: Path, slo_body: str) -> Path:
    """A drift fixture repo with an SLI registry (obs/slo.py) — the
    slo sub-rule's seed bed."""
    root = _drift_repo(tmp_path)
    (root / "gubernator_tpu" / "obs").mkdir()
    (root / "gubernator_tpu" / "obs" / "slo.py").write_text(slo_body)
    return root


def test_drift_slo_unregistered_metric_is_a_finding(tmp_path):
    """An SLI naming a metric the registry never exports flags — the
    burn rate would watch a series that does not exist."""
    from tools.guberlint import driftcheck

    root = _slo_repo(
        tmp_path,
        textwrap.dedent(
            """
            from dataclasses import dataclass

            @dataclass(frozen=True)
            class SLI:
                name: str = ""
                metric: str = ""
                kind: str = ""

            GOOD = SLI(
                name="ok",
                metric="gubernator_documented_total",
                kind="ratio",
            )
            BAD = SLI(
                name="ghost",
                metric="gubernator_never_registered",
                kind="ratio",
            )
            """
        ),
    )
    findings = driftcheck.check(root, [])
    details = {(f.rule, f.detail) for f in findings}
    assert ("slo-metric-unregistered", "gubernator_never_registered") \
        in details
    assert not any(
        d == "gubernator_documented_total" for _, d in details
    )


def test_drift_slo_computed_metric_name_is_a_finding(tmp_path):
    """An SLI without a literal metric= is unverifiable — it must
    flag (or carry a reasoned suppression)."""
    from tools.guberlint import driftcheck

    root = _slo_repo(
        tmp_path,
        textwrap.dedent(
            """
            class SLI:
                def __init__(self, **kw):
                    pass

            NAME = "gubernator_documented_total"
            COMPUTED = SLI(name="dyn", metric=NAME, kind="ratio")
            SUPPRESSED = SLI(name="dyn2", metric=NAME, kind="ratio")  # guberlint: ok drift — resolved at import, pinned by tests
            """
        ),
    )
    findings = driftcheck.check(root, [])
    rules = [f.rule for f in findings if f.rule.startswith("slo")]
    assert rules == ["slo-no-metric"]


def test_drift_pass_prose_mention_is_not_a_read(tmp_path):
    """Docstrings and comments naming a knob must not count as reads
    (only call-argument string literals do)."""
    from tools.guberlint import driftcheck

    root = _drift_repo(tmp_path)
    (root / "gubernator_tpu" / "mod.py").write_text(
        '"""GUBER_PROSE_ONLY is merely mentioned here."""\n'
        'import os\n'
        'A = os.environ.get("GUBER_DOCUMENTED")\n'
    )
    findings = driftcheck.check(root, [])
    assert not any("GUBER_PROSE_ONLY" in f.detail for f in findings)


# -------------------------------------------------- C fix-annotations


def test_fix_c_annotations_inserts_stub(tmp_path, monkeypatch):
    import tools.guberlint.__main__ as main_mod
    from tools.guberlint.csource import CSourceFile

    p = tmp_path / "mod.cpp"
    p.write_text(
        textwrap.dedent(
            """
            #include <mutex>

            struct Plane {
              std::mutex mu;
              long count = 0;
            };

            void bump(Plane* p) {
              std::lock_guard<std::mutex> lock(p->mu);
              ++p->count;
            }

            void bump2(Plane* p) {
              std::lock_guard<std::mutex> lock(p->mu);
              p->count += 2;
            }
            """
        )
    )
    monkeypatch.setattr(main_mod, "REPO_ROOT", tmp_path)
    inserted = main_mod.fix_c_annotations([p])
    assert inserted == 1
    assert "long count = 0;  // guberlint: guarded-by mu" in p.read_text()
    # The annotated file now verifies clean.
    from tools.guberlint import nativecheck

    assert nativecheck.check_files([CSourceFile(p, "mod.cpp")]) == []


def test_fix_c_annotations_skips_unlocked_access(tmp_path, monkeypatch):
    import tools.guberlint.__main__ as main_mod

    p = tmp_path / "mod.cpp"
    p.write_text(
        textwrap.dedent(
            """
            #include <mutex>

            struct Plane {
              std::mutex mu;
              long count = 0;
            };

            void bump(Plane* p) {
              std::lock_guard<std::mutex> lock(p->mu);
              ++p->count;
            }

            long read(Plane* p) { return p->count; }
            """
        )
    )
    monkeypatch.setattr(main_mod, "REPO_ROOT", tmp_path)
    assert main_mod.fix_c_annotations([p]) == 0


# ------------------------------------------------------- sarif / only


def test_sarif_output_structure(tmp_path):
    from tools.guberlint.__main__ import to_sarif

    f = Finding(
        "native", "unguarded-access", "a.cpp", 7, "bad", "Plane.count",
        "unguarded",
    )
    doc = to_sarif([f])
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "guberlint"
    assert run["tool"]["driver"]["rules"][0]["id"] == "native/unguarded-access"
    res = run["results"][0]
    assert res["ruleId"] == "native/unguarded-access"
    assert res["locations"][0]["physicalLocation"]["region"]["startLine"] == 7
    assert "guberlint/v1" in res["fingerprints"]


def test_sarif_file_mode_writes_and_keeps_exit_semantics(tmp_path):
    from tools.guberlint.__main__ import main

    out = tmp_path / "guberlint.sarif"
    rc = main(["--sarif", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["runs"][0]["results"] == []


def test_only_flag_restricts_passes(tmp_path, monkeypatch):
    """--only lock on a file full of thread findings reports none (and
    the thread pass on the same file does)."""
    import tools.guberlint.__main__ as main_mod
    from tools.guberlint.__main__ import run

    monkeypatch.setattr(main_mod, "REPO_ROOT", tmp_path)
    p = tmp_path / "mod.py"
    p.write_text(
        textwrap.dedent(
            """
            import threading

            def kick(fn):
                threading.Thread(target=fn, daemon=True).start()
            """
        )
    )
    assert run([p], only="lock") == []
    assert [f.rule for f in run([p], only="thread")] == ["thread-orphan"]


def test_suite_stays_inside_the_ci_budget():
    """ci_fast.sh keeps guberlint as stage one only while the whole
    suite (all seven passes over the repo) stays under 10 s."""
    import time as _time

    from tools.guberlint.__main__ import REPO_ROOT, run
    from tools.guberlint.config import LINT_ROOTS

    t0 = _time.monotonic()
    run([REPO_ROOT / r for r in LINT_ROOTS])
    assert _time.monotonic() - t0 < 10.0


# -------------------------------------------------- drift: trace sub-rule
# (ISSUE 9 satellite: span-name catalog discipline — every span(...)
# site unique + snake_case; deliberate twins need reasoned
# suppressions.)


def test_drift_span_name_style_and_duplicate(tmp_path):
    from tools.guberlint import driftcheck

    root = _drift_repo(tmp_path)
    (root / "gubernator_tpu" / "spans.py").write_text(
        textwrap.dedent(
            """
            from gubernator_tpu.utils.tracing import span

            def a():
                with span("BadName.CamelCase"):
                    pass

            def b():
                with span("dup.site"):
                    pass

            def c():
                with span("dup.site"):
                    pass

            def ok():
                with span("fine.snake_case"):
                    pass
            """
        )
    )
    findings = driftcheck.check(root, [])
    rules = {(f.rule, f.detail) for f in findings}
    assert ("span-name-style", "BadName.CamelCase") in rules
    assert ("span-name-duplicate", "dup.site") in rules
    assert not any(
        d == "fine.snake_case" for _r, d in rules
    )
    # Exactly one duplicate finding (the twin, not the first site).
    assert (
        sum(1 for f in findings if f.rule == "span-name-duplicate") == 1
    )


def test_drift_span_twin_suppression_respected(tmp_path):
    from tools.guberlint import driftcheck

    root = _drift_repo(tmp_path)
    (root / "gubernator_tpu" / "spans.py").write_text(
        textwrap.dedent(
            """
            from gubernator_tpu.utils.tracing import span

            def a():
                with span("twin.site"):
                    pass

            def b():
                # guberlint: ok drift — deliberate sharded twin
                with span("twin.site"):
                    pass
            """
        )
    )
    findings = driftcheck.check(root, [])
    assert not any(f.rule.startswith("span-name") for f in findings)


def test_drift_span_variable_name_not_scanned(tmp_path):
    """Helper-routed spans (variable name argument) are outside the
    literal catalog — no style/duplicate findings for them."""
    from tools.guberlint import driftcheck

    root = _drift_repo(tmp_path)
    (root / "gubernator_tpu" / "spans.py").write_text(
        textwrap.dedent(
            """
            from gubernator_tpu.utils.tracing import span

            def helper(name):
                with span(name):
                    pass
            """
        )
    )
    findings = driftcheck.check(root, [])
    assert not any(f.rule.startswith("span-name") for f in findings)


# -------------------------------------------------- native: event ring
# (ISSUE 9 satellite: an event-ring write that calls a Py* API must
# trip the gil-free check — the ring is reachable from conn_loop.)


def test_native_event_ring_write_calling_py_api_trips_gil_check(tmp_path):
    from tools.guberlint import nativecheck

    code = """
    // guberlint: gil-free
    long evr_record(void* ring, long kind, long dur) {
      PyGILState_Ensure();
      return 1;
    }

    // guberlint: gil-free
    void conn_loop(void* srv, void* ring) {
      evr_record(ring, 1, 42);
    }
    """
    findings = nativecheck.check_files([_csrc(tmp_path, code)])
    gil = [f for f in findings if f.rule == "gil-call"]
    # Both the write itself and the conn_loop root reach the Py* call.
    roots = {f.scope for f in gil}
    assert "evr_record" in roots and "conn_loop" in roots


def test_native_event_ring_clean_write_passes(tmp_path):
    from tools.guberlint import nativecheck

    code = """
    #include <atomic>

    // guberlint: gil-free
    long evr_record(void* ring, long kind, long dur) {
      return kind + dur;
    }

    // guberlint: gil-free
    void conn_loop(void* srv, void* ring) {
      evr_record(ring, 1, 42);
    }
    """
    assert nativecheck.check_files([_csrc(tmp_path, code)]) == []


# --------------------------------------------------------------- proto


def _proto_repo(tmp_path: Path) -> Path:
    """A fixture repo where every REAL registered property is both
    anchored (source annotation) and documented (RESILIENCE.md
    marker): clean by construction, so each test seeds exactly one
    drift."""
    from tools.gubercheck import properties as props

    root = tmp_path / "repo"
    (root / "gubernator_tpu").mkdir(parents=True)
    names = sorted(props.registry())
    (root / "gubernator_tpu" / "mod.py").write_text(
        "\n".join(f"# guberlint: invariant {n}" for n in names) + "\n"
    )
    (root / "RESILIENCE.md").write_text(
        "\n".join(f"- gubercheck: `{n}` — checked" for n in names)
        + "\n"
    )
    return root


def test_proto_pass_synced_fixture_is_clean(tmp_path):
    from tools.guberlint import protocheck

    assert protocheck.check(_proto_repo(tmp_path)) == []


def test_proto_pass_orphan_annotation(tmp_path):
    """A source annotation naming an unregistered property claims
    model-checked protection that does not exist."""
    from tools.guberlint import protocheck

    root = _proto_repo(tmp_path)
    with (root / "gubernator_tpu" / "mod.py").open("a") as f:
        f.write("# guberlint: invariant ghost-prop\n")
    findings = protocheck.check(root)
    assert [(f.rule, f.detail) for f in findings] == [
        ("proto-orphan-annotation", "ghost-prop")
    ]


def test_proto_pass_orphan_annotation_suppression(tmp_path):
    from tools.guberlint import protocheck

    root = _proto_repo(tmp_path)
    with (root / "gubernator_tpu" / "mod.py").open("a") as f:
        # Trailing annotation on a code line so the same-line
        # suppression targets it.
        f.write(
            "X = 1  # guberlint: invariant ghost-prop"
            "  # guberlint: ok proto — registry lands next PR\n"
        )
    assert protocheck.check(root) == []


def test_proto_pass_doc_marker_unregistered(tmp_path):
    """RESILIENCE.md promising a checked bound nothing checks."""
    from tools.guberlint import protocheck

    root = _proto_repo(tmp_path)
    with (root / "RESILIENCE.md").open("a") as f:
        f.write("- gubercheck: `ghost-bound` — totally checked\n")
    findings = protocheck.check(root)
    assert [(f.rule, f.detail, f.file) for f in findings] == [
        ("proto-doc-unregistered", "ghost-bound", "RESILIENCE.md")
    ]


def test_proto_pass_registered_but_undocumented(tmp_path):
    """Dropping one doc marker flags exactly that property."""
    from tools.gubercheck import properties as props
    from tools.guberlint import protocheck

    root = _proto_repo(tmp_path)
    victim = sorted(props.registry())[0]
    doc = root / "RESILIENCE.md"
    doc.write_text(
        "\n".join(
            ln for ln in doc.read_text().splitlines()
            if f"`{victim}`" not in ln
        ) + "\n"
    )
    findings = protocheck.check(root)
    assert [(f.rule, f.detail) for f in findings] == [
        ("proto-invariant-undocumented", victim)
    ]


def test_proto_pass_registered_but_unanchored(tmp_path):
    """Dropping one source annotation flags exactly that property —
    a registry row with no protected site is drift."""
    from tools.gubercheck import properties as props
    from tools.guberlint import protocheck

    root = _proto_repo(tmp_path)
    victim = sorted(props.registry())[-1]
    mod = root / "gubernator_tpu" / "mod.py"
    mod.write_text(
        "\n".join(
            ln for ln in mod.read_text().splitlines()
            if not ln.endswith(f" {victim}")
        ) + "\n"
    )
    findings = protocheck.check(root)
    assert [(f.rule, f.detail) for f in findings] == [
        ("proto-property-unanchored", victim)
    ]


def test_proto_registry_rows_match_scenario_claims():
    """Every property a scenario claims to check is registered, and
    every registered property is claimed by at least one scenario —
    the registry carries no dead rows the model checker never
    exercises."""
    from tools.gubercheck import properties as props
    from tools.gubercheck import scenarios as scn_mod

    registered = set(props.registry())
    claimed = set()
    for name in scn_mod.scenario_names():
        cls = scn_mod.get_scenario(name)
        for p in cls.properties:
            assert p in registered, f"{name} claims unregistered {p}"
            claimed.add(p)
    assert claimed == registered, (
        f"registered but never checked by any scenario: "
        f"{sorted(registered - claimed)}"
    )


# ---------------------------------------------- stale suppressions


def _tracker(declared, hits=()):
    from tools.guberlint.common import SuppressionTracker

    t = SuppressionTracker()
    for rel, line, pass_name in declared:
        t.declared.setdefault(rel, {}).setdefault(line, set()).add(
            pass_name
        )
    for rel, line, pass_name in hits:
        t.hits.setdefault(rel, set()).add((line, pass_name))
    return t


def test_stale_suppression_detected():
    t = _tracker([("gubernator_tpu/x.py", 10, "lock")])
    findings = baseline_mod.stale_suppressions(t, ())
    assert [(f.rule, f.file, f.line) for f in findings] == [
        ("stale-suppression", "gubernator_tpu/x.py", 10)
    ]


def test_hit_suppression_is_not_stale():
    t = _tracker(
        [("gubernator_tpu/x.py", 10, "lock")],
        hits=[("gubernator_tpu/x.py", 10, "lock")],
    )
    assert baseline_mod.stale_suppressions(t, ()) == []


def test_native_and_contract_suppressions_exempt():
    """The C-side passes don't consult SourceFile.suppressed(), so
    their suppressions never register hits — they must not be
    reported stale."""
    t = _tracker(
        [
            ("gubernator_tpu/x.py", 3, "native"),
            ("gubernator_tpu/x.py", 4, "contract"),
        ]
    )
    assert baseline_mod.stale_suppressions(t, ()) == []


def test_trace_suppression_outside_scope_exempt():
    """trace only runs on TRACE_SCOPES files; elsewhere an unhit
    trace suppression proves nothing."""
    t = _tracker([("gubernator_tpu/cluster/x.py", 7, "trace")])
    scopes = ("gubernator_tpu/models/",)
    assert baseline_mod.stale_suppressions(t, scopes) == []
    t2 = _tracker([("gubernator_tpu/models/x.py", 7, "trace")])
    findings = baseline_mod.stale_suppressions(t2, scopes)
    assert [f.rule for f in findings] == ["stale-suppression"]


def test_live_tracker_records_declarations_and_hits(tmp_path):
    """End-to-end through SourceFile: declaring a suppression under an
    active tracker records it; an imminent-finding consult records a
    hit; stale detection then distinguishes the two."""
    from tools.guberlint.common import SuppressionTracker

    code = textwrap.dedent(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guberlint: guarded-by _lock

            def bump(self):
                with self._lock:
                    self._n += 1

            def peek(self):
                return self._n  # guberlint: ok lock — racy read is fine here
        """
    )
    with SuppressionTracker() as t:
        src = _src(tmp_path, code, "y.py")
        findings, _ = _lock_findings(src)
        assert findings == []
        stale = baseline_mod.stale_suppressions(t, ())
    assert src.rel in t.declared
    assert t.hits.get(src.rel), "the consulted suppression must hit"
    assert stale == [], "a hit suppression is not stale"


# ------------------------------------------------------- incremental


def test_changed_flag_rejects_explicit_paths():
    from tools.guberlint.__main__ import main

    assert main(["--changed", "gubernator_tpu/clock.py"]) == 2


def test_changed_lint_paths_filters_to_lint_roots():
    """Whatever git reports, the result only ever contains existing
    .py files under LINT_ROOTS minus EXCLUDE (or None when git can't
    answer — never a silently-empty list standing in for 'clean')."""
    from tools.guberlint.__main__ import changed_lint_paths
    from tools.guberlint.config import EXCLUDE, LINT_ROOTS

    paths = changed_lint_paths()
    if paths is None:
        pytest.skip("not a usable git checkout")
    for p in paths:
        rel = p.relative_to(
            Path(__file__).resolve().parents[1]
        ).as_posix()
        assert rel.endswith(".py")
        assert any(
            rel == r or rel.startswith(r.rstrip("/") + "/")
            for r in LINT_ROOTS
        )
        assert not any(rel.startswith(e) for e in EXCLUDE)
        assert p.exists()


def test_changed_mode_runs_clean_on_this_checkout():
    """`--changed` end-to-end: the current working tree's changed
    files (possibly none) lint clean — same acceptance bar as the
    full run, a fraction of the work."""
    from tools.guberlint.__main__ import main

    assert main(["--changed"]) == 0

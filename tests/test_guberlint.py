"""guberlint proves each pass catches its seeded bad fixture.

Each case writes a known-bad snippet, runs the pass directly, and
asserts the finding (and that the suppression escape hatch silences
it).  STATIC_ANALYSIS.md documents the grammar these fixtures pin.
"""

import json
import textwrap
from pathlib import Path

import pytest

from tools.guberlint import baseline as baseline_mod
from tools.guberlint import lockcheck, threadcheck, tracecheck
from tools.guberlint.common import Finding, SourceFile


def _src(tmp_path: Path, code: str, name: str = "fix.py") -> SourceFile:
    p = tmp_path / name
    p.write_text(textwrap.dedent(code))
    return SourceFile(p, name)


def _lock_findings(src):
    edges = set()
    out = lockcheck.check_file(src, edges)
    return out, edges


# ---------------------------------------------------------------- lock


LOCK_BAD = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0  # guberlint: guarded-by _lock

        def good(self):
            with self._lock:
                self._n += 1

        def bad(self):
            return self._n
"""


def test_lock_pass_catches_unguarded_access(tmp_path):
    findings, _ = _lock_findings(_src(tmp_path, LOCK_BAD))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "unguarded-access"
    assert f.scope == "Counter.bad"
    assert "self._n" in f.message


def test_lock_pass_suppression_escape_hatch(tmp_path):
    code = LOCK_BAD.replace(
        "return self._n",
        "return self._n  # guberlint: ok lock — racy read tolerated, metrics only",
    )
    findings, _ = _lock_findings(_src(tmp_path, code))
    assert findings == []


def test_lock_pass_suppression_requires_reason(tmp_path):
    code = LOCK_BAD.replace(
        "return self._n", "return self._n  # guberlint: ok lock"
    )
    src = _src(tmp_path, code)
    assert any(
        f.rule == "bad-suppression" for f in src.bad_suppressions
    ), "reasonless suppression must itself be a finding"


def test_lock_pass_holds_annotation_and_locked_convention(tmp_path):
    code = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guberlint: guarded-by _lock

            def _bump_locked(self):
                self._n += 1

            def bump_held(self):  # guberlint: holds _lock
                self._n += 1
    """
    findings, _ = _lock_findings(_src(tmp_path, code))
    assert findings == []


def test_lock_pass_condition_alias(tmp_path):
    code = """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._items = []  # guberlint: guarded-by _lock

            def put(self, x):
                with self._cv:
                    self._items.append(x)
    """
    findings, _ = _lock_findings(_src(tmp_path, code))
    assert findings == [], "acquiring the condition acquires the wrapped lock"


def test_lock_pass_nested_def_resets_held_context(tmp_path):
    code = """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guberlint: guarded-by _lock

            def kick(self, pool):
                with self._lock:
                    def later():
                        return self._items.pop()
                    pool.submit(later)
    """
    findings, _ = _lock_findings(_src(tmp_path, code))
    assert len(findings) == 1, "closure may run after the with exits"


def test_lock_order_inversion_detected(tmp_path):
    code = """
        import threading

        class AB:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self.x = 0  # guberlint: guarded-by _a_lock

            def fwd(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def rev(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """
    _, edges = _lock_findings(_src(tmp_path, code))
    cyc = lockcheck.order_findings(edges)
    assert len(cyc) == 1
    assert cyc[0].rule == "lock-order-inversion"
    assert "AB._a_lock" in cyc[0].message and "AB._b_lock" in cyc[0].message


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    code = """
        import threading

        class AB:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self.x = 0  # guberlint: guarded-by _a_lock

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
    """
    _, edges = _lock_findings(_src(tmp_path, code))
    assert lockcheck.order_findings(edges) == []


# --------------------------------------------------------------- trace


def test_trace_pass_catches_tracer_branch(tmp_path):
    code = """
        import jax
        import jax.numpy as jnp

        # guberlint: shapes x [n] on the pad ladder
        @jax.jit
        def f(x):
            if x.sum() > 0:
                return x
            return -x
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["trace-branch"]


def test_trace_pass_static_shape_branch_ok(tmp_path):
    code = """
        import jax

        # guberlint: shapes x [n] on the pad ladder
        @jax.jit
        def f(x):
            if x.shape[0] > 4:
                return x
            return x + 1
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert findings == [], "shape tests are static under trace"


def test_trace_pass_static_argnames_not_tainted(tmp_path):
    code = """
        import jax
        from functools import partial

        # guberlint: shapes x [n]; window static
        @partial(jax.jit, static_argnames=("window",))
        def f(x, window):
            if window > 4:
                return x
            return x + 1
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert findings == []


def test_trace_pass_catches_host_transfer(tmp_path):
    code = """
        import jax
        import numpy as np

        # guberlint: shapes x [n]
        @jax.jit
        def f(x):
            y = x + 1
            return np.asarray(y)
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["trace-transfer"]


def test_trace_pass_transfer_reaches_helpers(tmp_path):
    code = """
        import jax

        def helper(v):
            return float(v)

        # guberlint: shapes x [n]
        @jax.jit
        def f(x):
            return helper(x * 2)
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert any(
        f.rule == "trace-transfer" and f.scope == "helper" for f in findings
    ), "helpers called from jit roots execute traced"


def test_trace_pass_requires_shapes_annotation(tmp_path):
    code = """
        import jax

        @jax.jit
        def f(x):
            return x + 1
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["trace-shapes"]
    # ... and the annotation satisfies it (any of the eligible lines).
    ok = code.replace(
        "@jax.jit", "# guberlint: shapes x [n] padded pow2\n@jax.jit"
    )
    assert tracecheck.check_file(_src(tmp_path, ok, "ok.py")) == []


def test_trace_pass_suppression(tmp_path):
    code = """
        import jax

        # guberlint: ok trace — host callback by design (io_callback wrapper)
        @jax.jit
        def f(x):
            return x + 1
    """
    findings = tracecheck.check_file(_src(tmp_path, code))
    assert findings == []


# -------------------------------------------------------------- thread


def test_thread_pass_catches_orphan_daemon(tmp_path):
    code = """
        import threading

        class Svc:
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                pass
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["thread-orphan"]


def test_thread_pass_joined_daemon_ok(tmp_path):
    code = """
        import threading

        class Svc:
            def __init__(self):
                self._stop = threading.Event()
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                while not self._stop.wait(1.0):
                    pass

            def close(self):
                self._stop.set()
                self._t.join(timeout=2.0)
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert findings == []


def test_thread_pass_local_threads_joined_via_loop(tmp_path):
    code = """
        import threading

        def run(n):
            threads = [
                threading.Thread(target=print, daemon=True) for _ in range(n)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert findings == []


def test_thread_pass_fire_and_forget_needs_suppression(tmp_path):
    code = """
        import threading

        def kick(fn):
            threading.Thread(target=fn, daemon=True).start()
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["thread-orphan"]
    ok = code.replace(
        "    threading.Thread",
        "    # guberlint: ok thread — bounded one-shot drain\n"
        "    threading.Thread",
    )
    assert threadcheck.check_file(_src(tmp_path, ok, "ok.py")) == []


def test_thread_pass_catches_silent_swallow(tmp_path):
    code = """
        import threading

        def loop():
            while True:
                try:
                    work()
                except Exception:
                    pass
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert [f.rule for f in findings] == ["thread-swallow"]


def test_thread_pass_logged_swallow_ok(tmp_path):
    code = """
        import logging
        import threading

        def loop():
            while True:
                try:
                    work()
                except Exception:
                    logging.getLogger("x").exception("work failed")
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert findings == []


def test_thread_pass_non_threaded_module_exempt_from_swallow(tmp_path):
    code = """
        def f():
            try:
                work()
            except Exception:
                pass
    """
    findings = threadcheck.check_file(_src(tmp_path, code))
    assert findings == []


# ------------------------------------------------------------ baseline


def test_baseline_round_trip_and_staleness(tmp_path):
    f1 = Finding("lock", "unguarded-access", "a.py", 3, "C.m", "self.x", "x")
    f2 = Finding("trace", "trace-branch", "b.py", 9, "f", "if@f", "y")
    path = tmp_path / "base.json"
    baseline_mod.save(path, [f1, f2])
    base = baseline_mod.load(path)
    assert len(base) == 2
    # f2 fixed; f3 new.
    f3 = Finding("thread", "thread-orphan", "c.py", 1, "S", "thread@S._t", "z")
    new, accepted, stale = baseline_mod.partition([f1, f3], base)
    assert [f.rule for f in new] == ["thread-orphan"]
    assert [f.rule for f in accepted] == ["unguarded-access"]
    assert len(stale) == 1 and stale[0][1] == "trace-branch"


def test_baseline_save_preserves_audit_record(tmp_path):
    path = tmp_path / "base.json"
    path.write_text(json.dumps({"findings": [], "audited_clean": {"lock": {}}}))
    baseline_mod.save(path, [])
    assert "audited_clean" in json.loads(path.read_text())


def test_repo_is_clean_against_committed_baseline():
    """The acceptance gate: `python -m tools.guberlint` exits 0."""
    from tools.guberlint.__main__ import main

    assert main([]) == 0


# ----------------------------------------------------- fix-annotations


def test_fix_annotations_inserts_stub(tmp_path, monkeypatch):
    import tools.guberlint.__main__ as main_mod

    p = tmp_path / "mod.py"
    p.write_text(
        textwrap.dedent(
            """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1
            """
        )
    )
    monkeypatch.setattr(main_mod, "REPO_ROOT", tmp_path)
    inserted = main_mod.fix_annotations([p])
    assert inserted == 1
    assert "self._n = 0  # guberlint: guarded-by _lock" in p.read_text()
    # The annotated file now verifies clean.
    src = SourceFile(p, "mod.py")
    findings, _ = _lock_findings(src)
    assert findings == []


def test_fix_annotations_skips_mixed_lock_attrs(tmp_path, monkeypatch):
    import tools.guberlint.__main__ as main_mod

    p = tmp_path / "mod.py"
    p.write_text(
        textwrap.dedent(
            """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def read(self):
                    return self._n
            """
        )
    )
    monkeypatch.setattr(main_mod, "REPO_ROOT", tmp_path)
    assert main_mod.fix_annotations([p]) == 0, (
        "an attr with unlocked accesses must not get a stub"
    )


# ----------------------------------------------------------------- net


NET_RETRY_BAD = """
    from gubernator_tpu.cluster.peer_client import PeerError

    def forward(groups, pick):
        while groups:
            retry = []
            for p, ids in groups:
                try:
                    p.rpc(ids)
                except PeerError as e:
                    if e.not_ready:
                        retry.extend(ids)
                        continue
            groups = pick(retry)
"""


def test_net_pass_catches_retry_without_backoff(tmp_path):
    from tools.guberlint import netcheck

    findings = netcheck.check_file(_src(tmp_path, NET_RETRY_BAD))
    assert any(f.rule == "net-retry-no-backoff" for f in findings)


def test_net_pass_backoff_in_enclosing_loop_ok(tmp_path):
    from tools.guberlint import netcheck

    code = NET_RETRY_BAD.replace(
        "            groups = pick(retry)",
        "            time.sleep(backoff_delay(1, 0.01, 0.25))\n"
        "            groups = pick(retry)",
    )
    findings = netcheck.check_file(_src(tmp_path, code))
    assert not [f for f in findings if f.rule == "net-retry-no-backoff"]


def test_net_pass_log_and_continue_is_not_a_retry_loop(tmp_path):
    """multiregion-style per-peer iteration: catching PeerError to
    skip a peer (no not_ready decision, no retry collection) is not a
    retry loop and must not demand backoff."""
    from tools.guberlint import netcheck

    code = """
        from gubernator_tpu.cluster.peer_client import PeerError

        def send_all(by_peer, log):
            for addr, reqs in by_peer.items():
                try:
                    addr.rpc(reqs)
                except PeerError as e:
                    log.error("send to %s failed: %s", addr, e)
                    continue
    """
    findings = netcheck.check_file(_src(tmp_path, code))
    assert not [f for f in findings if f.rule == "net-retry-no-backoff"]


def test_net_pass_catches_rpc_without_timeout(tmp_path):
    from tools.guberlint import netcheck

    code = """
        def flush(peer, reqs):
            peer.send_peer_hits(reqs)
    """
    findings = netcheck.check_file(_src(tmp_path, code))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "net-rpc-no-timeout"
    assert "send_peer_hits" in f.message


def test_net_pass_rpc_with_timeout_ok(tmp_path):
    from tools.guberlint import netcheck

    code = """
        def flush(peer, reqs, conf):
            peer.send_peer_hits(reqs, timeout=conf.global_timeout)
    """
    assert netcheck.check_file(_src(tmp_path, code)) == []


def test_net_pass_server_side_receivers_exempt(tmp_path):
    from tools.guberlint import netcheck

    code = """
        class Adapter:
            def handle(self, reqs):
                return self.instance.get_peer_rate_limits(reqs)

        class Client:
            def one(self, req):
                return self.get_peer_rate_limits([req], timeout=1.0)
    """
    assert netcheck.check_file(_src(tmp_path, code)) == []


def test_net_pass_suppression_escape_hatch(tmp_path):
    from tools.guberlint import netcheck

    code = """
        def flush(peer, reqs):
            peer.send_peer_hits(reqs)  # guberlint: ok net — probe uses channel default
    """
    assert netcheck.check_file(_src(tmp_path, code)) == []


# Handoff RPC discipline (ISSUE 7): TransferBuckets call sites are held
# to the same rules as every peer RPC — an epoch commit waits on the
# sender, so an unbudgeted send or a backoff-free retry loop stalls a
# membership transition, not just one request.

HANDOFF_BAD = """
    from gubernator_tpu.cluster.peer_client import PeerError

    def ship(pending, window):
        while pending:
            for addr, (peer, rows) in list(pending.items()):
                try:
                    peer.transfer_buckets_raw(rows[:window])
                except PeerError as e:
                    if e.not_ready:
                        continue
                pending.pop(addr)
"""


def test_net_pass_catches_handoff_rpc_without_timeout(tmp_path):
    from tools.guberlint import netcheck

    findings = netcheck.check_file(_src(tmp_path, HANDOFF_BAD))
    assert any(
        f.rule == "net-rpc-no-timeout"
        and "transfer_buckets_raw" in f.message
        for f in findings
    )


def test_net_pass_catches_handoff_retry_without_backoff(tmp_path):
    from tools.guberlint import netcheck

    findings = netcheck.check_file(_src(tmp_path, HANDOFF_BAD))
    assert any(f.rule == "net-retry-no-backoff" for f in findings)


def test_net_pass_handoff_with_timeout_and_backoff_ok(tmp_path):
    from tools.guberlint import netcheck

    code = """
        import time
        from gubernator_tpu.cluster.health import backoff_delay
        from gubernator_tpu.cluster.peer_client import PeerError

        def ship(pending, window, deadline):
            attempt = 0
            while pending:
                for addr, (peer, rows) in list(pending.items()):
                    try:
                        peer.transfer_buckets_raw(rows[:window], timeout=1.0)
                    except PeerError as e:
                        if e.not_ready:
                            continue
                    pending.pop(addr)
                time.sleep(backoff_delay(attempt, 0.01, 0.25))
                attempt += 1
    """
    assert netcheck.check_file(_src(tmp_path, code)) == []

"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware isn't available in CI; sharding tests run on a
virtual CPU mesh exactly as SURVEY.md prescribes.  Must run before the
first jax import (hence module level, and conftest loads before test
modules)."""

# The environment's sitecustomize may have force-registered a TPU
# backend before conftest ran; the shared guard's config update wins
# over it and pins ≥8 virtual CPU devices.
from gubernator_tpu.platform_guard import force_cpu_platform

force_cpu_platform(8)

# The step pump auto-disables on the CPU backend (no per-RPC overhead
# to amortize); tests force it ON so the pump/uniform machinery is
# exercised exactly as it runs on TPU.
import os

os.environ.setdefault("GUBER_PUMP", "1")

import pytest

from gubernator_tpu.clock import Clock


def pytest_configure(config):
    # `slow` marks the long fuzz soaks; tier-1 runs -m 'not slow'
    # (ROADMAP.md) so the suite stays inside its timeout.
    config.addinivalue_line(
        "markers", "slow: long-running soak, excluded from tier-1"
    )


@pytest.fixture
def frozen_clock() -> Clock:
    """A frozen, manually advanced clock (reference: functional_test.go:160)."""
    return Clock().freeze()

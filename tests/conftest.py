"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware isn't available in CI; sharding tests run on a
virtual CPU mesh exactly as SURVEY.md prescribes.  Must run before the
first jax import (hence module level, and conftest loads before test
modules)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The environment's sitecustomize may have force-registered a TPU
# backend before conftest ran; the config update wins over it.
jax.config.update("jax_platforms", "cpu")

import pytest

from gubernator_tpu.clock import Clock


@pytest.fixture
def frozen_clock() -> Clock:
    """A frozen, manually advanced clock (reference: functional_test.go:160)."""
    return Clock().freeze()

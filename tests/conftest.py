"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware isn't available in CI; sharding tests run on a
virtual CPU mesh exactly as SURVEY.md prescribes.  Must run before the
first jax import (hence module level, and conftest loads before test
modules)."""

# The environment's sitecustomize may have force-registered a TPU
# backend before conftest ran; the shared guard's config update wins
# over it and pins ≥8 virtual CPU devices.
from gubernator_tpu.platform_guard import force_cpu_platform

force_cpu_platform(8)

# The step pump auto-disables on the CPU backend (no per-RPC overhead
# to amortize); tests force it ON so the pump/uniform machinery is
# exercised exactly as it runs on TPU.
import os

os.environ.setdefault("GUBER_PUMP", "1")

import pytest

from gubernator_tpu.clock import Clock


def pytest_configure(config):
    # `slow` marks the long fuzz soaks; tier-1 runs -m 'not slow'
    # (ROADMAP.md) so the suite stays inside its timeout.
    config.addinivalue_line(
        "markers", "slow: long-running soak, excluded from tier-1"
    )


@pytest.fixture
def frozen_clock() -> Clock:
    """A frozen, manually advanced clock (reference: functional_test.go:160)."""
    return Clock().freeze()


class JitRecompileGuard:
    """Snapshot/assert helper over utils.jit_guard's compile counter.

    Usage: warm the code under test, call `snapshot()`, run the
    steady-state traffic, then `assert_flat("phase name")` — any XLA
    backend compile in between fails the test with the delta."""

    def __init__(self):
        from gubernator_tpu.utils import jit_guard

        self._guard = jit_guard
        self.live = jit_guard.install()
        self._mark = None

    def count(self) -> int:
        return self._guard.compile_count()

    def snapshot(self) -> int:
        self._mark = self.count()
        return self._mark

    def assert_flat(self, what: str = "steady state") -> None:
        assert self._mark is not None, "call snapshot() after warmup first"
        now = self.count()
        assert now == self._mark, (
            f"{now - self._mark} XLA recompile(s) during {what} — an "
            "unpinned shape/dtype reached a jit program after warmup"
        )


@pytest.fixture
def jit_recompile_guard():
    """Recompile guard over a steady-state soak (skips if the jax
    monitoring hook is unavailable on this jax version)."""
    g = JitRecompileGuard()
    if not g.live:
        pytest.skip("jax monitoring hook unavailable; recompiles untracked")
    return g

"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware isn't available in CI; sharding tests run on a
virtual CPU mesh exactly as SURVEY.md prescribes.  Must run before the
first jax import (hence module level, and conftest loads before test
modules)."""

# The environment's sitecustomize may have force-registered a TPU
# backend before conftest ran; the shared guard's config update wins
# over it and pins ≥8 virtual CPU devices.
from gubernator_tpu.platform_guard import force_cpu_platform

force_cpu_platform(8)

import pytest

from gubernator_tpu.clock import Clock


@pytest.fixture
def frozen_clock() -> Clock:
    """A frozen, manually advanced clock (reference: functional_test.go:160)."""
    return Clock().freeze()

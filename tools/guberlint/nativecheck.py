"""Pass 5 — native tier: the C decision plane's concurrency contract.

Four rules over ``gubernator_tpu/core/native/*.cpp`` (parsed by
tools/guberlint/csource.py; STATIC_ANALYSIS.md documents the grammar
and limits):

- ``native-unguarded-access`` — a struct field annotated
  ``// guberlint: guarded-by <mutex>`` is touched outside a lexical
  ``lock_guard``/``unique_lock`` region on the same receiver's mutex
  (functions named ``*_locked`` or annotated ``holds`` are callee-held,
  constructors/destructors are pre-publication).
- ``native-gil-call`` — a function annotated ``// guberlint: gil-free``
  reaches (transitively, through functions defined in the scanned
  sources) a ``Py*`` C-API call or a GIL-acquiring trampoline
  (config.NATIVE_GIL_CALLS, i.e. the ctypes window callback).  The
  native plane's zero-GIL guarantee becomes checked, not claimed.
- ``native-blocking-under-lock`` — a call from
  config.NATIVE_BLOCKING_CALLS (socket/sleep syscalls) while a mutex
  is lexically held: every thread contending that mutex convoys behind
  the kernel.  Designed exceptions carry reasoned suppressions.
- ``native-blocking-in-reactor`` — a blocking socket syscall
  (``send``/``recv`` without ``MSG_DONTWAIT``, ``accept`` without
  ``SOCK_NONBLOCK`` — config.REACTOR_NONBLOCK_TOKENS) reachable
  (transitively, through functions defined in the scanned sources)
  from a function annotated ``// guberlint: epoll-root``: a reactor
  thread parked in the kernel stalls every connection on its lane.
  Suppressions live at the offending call site (e.g. the threaded-
  plane branch a runtime guard keeps off the reactor path).
- ``native-atomic-order`` — an explicit relaxed/acquire/release/
  acq_rel/consume memory order: each use must carry a reasoned
  suppression citing the happens-before argument it relies on (the
  default seq_cst never needs one).
"""

from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

from tools.guberlint.common import Finding
from tools.guberlint.config import (
    NATIVE_BLOCKING_CALLS,
    NATIVE_GIL_CALLS,
    REACTOR_NONBLOCK_TOKENS,
)
from tools.guberlint.csource import CFunction, CSourceFile, _CALL_RE

PASS = "native"

_PY_API_RE = re.compile(r"\bPy[A-Z_]\w*\s*\(")
_ATOMIC_ORDER_RE = re.compile(
    r"\bmemory_order_(relaxed|acquire|release|acq_rel|consume)\b"
)


def check_files(srcs: List[CSourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    table = _function_table(srcs)
    for src in srcs:
        findings.extend(src.bad_suppressions)
        _check_guards(src, findings)
        _check_blocking(src, findings)
        _check_atomics(src, findings)
    _check_gil(srcs, table, findings)
    _check_reactor(srcs, table, findings)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


# -- guard discipline --------------------------------------------------


def _check_guards(src: CSourceFile, findings: List[Finding]) -> None:
    guarded: List[Tuple[str, str, str]] = []  # (struct, field, mutex)
    for s in src.structs:
        for field, mutex in s.guards.items():
            guarded.append((s.name, field, mutex))
    if not guarded:
        return
    for fn in src.functions:
        body = src.code[fn.body_start:fn.body_end]
        for sname, field, mutex in guarded:
            for m in re.finditer(
                r"(?:([A-Za-z_]\w*)\s*(?:->|\.)\s*)?\b%s\b" % re.escape(field),
                body,
            ):
                recv = m.group(1) or ""
                if recv and m.group(0).startswith(recv):
                    pass
                elif not recv and fn.struct != sname:
                    continue  # bare name in a foreign scope: a local
                offset = fn.body_start + m.start()
                if _held_ok(src, fn, offset, recv, mutex):
                    continue
                line = src.line_of(offset)
                if src.suppressed(line, PASS):
                    continue
                ref = f"{recv}->{field}" if recv else field
                findings.append(
                    Finding(
                        PASS, "unguarded-access", src.rel, line,
                        fn.name, f"{sname}.{field}",
                        f"access to {ref} (guarded by {mutex} in "
                        f"{sname}) outside a lock region on {mutex}",
                    )
                )
                break  # one finding per (fn, field): fingerprint-stable


def _held_ok(
    src: CSourceFile, fn: CFunction, offset: int, recv: str, mutex: str
) -> bool:
    held = src.held_at(fn, offset)
    for h_recv, h_mutex in held:
        if h_mutex == "*":  # *_locked convention: caller holds
            return True
        if h_mutex != mutex:
            continue
        # Bare-held (holds annotation or member-scope guard) vouches
        # for any receiver; otherwise receivers must match textually.
        if h_recv == "" or h_recv == recv or recv == "":
            return True
    return False


# -- blocking calls under a mutex --------------------------------------

_BLOCKING_RE = re.compile(
    r"\b(%s)\s*\(" % "|".join(re.escape(c) for c in NATIVE_BLOCKING_CALLS)
)


def _check_blocking(src: CSourceFile, findings: List[Finding]) -> None:
    for fn in src.functions:
        body = src.code[fn.body_start:fn.body_end]
        for m in _BLOCKING_RE.finditer(body):
            offset = fn.body_start + m.start()
            if not src.held_at(fn, offset):
                continue
            line = src.line_of(offset)
            if src.suppressed(line, PASS):
                continue
            findings.append(
                Finding(
                    PASS, "blocking-under-lock", src.rel, line, fn.name,
                    f"{fn.name}:{m.group(1)}",
                    f"blocking call {m.group(1)}() while a mutex is "
                    "held — contending threads convoy behind the "
                    "kernel; move it outside the lock or suppress "
                    "with the bounding argument",
                )
            )


# -- atomics / memory order --------------------------------------------


def _check_atomics(src: CSourceFile, findings: List[Finding]) -> None:
    for m in _ATOMIC_ORDER_RE.finditer(src.code):
        line = src.line_of(m.start())
        if src.suppressed(line, PASS):
            continue
        findings.append(
            Finding(
                PASS, "atomic-order", src.rel, line, "<module>",
                f"memory_order_{m.group(1)}:{line}",
                f"explicit memory_order_{m.group(1)}: non-seq_cst "
                "orders need a reasoned suppression citing the "
                "happens-before edge they rely on",
            )
        )


# -- reactor discipline ------------------------------------------------

_REACTOR_CALL_RE = re.compile(
    r"\b(%s)\s*\("
    % "|".join(re.escape(c) for c in REACTOR_NONBLOCK_TOKENS)
)


def _call_args(body: str, open_idx: int) -> str:
    """The argument text of a call, from its '(' to the matching ')'
    (blanked code: parens in strings/comments are already gone)."""
    depth = 0
    for i in range(open_idx, len(body)):
        c = body[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return body[open_idx : i + 1]
    return body[open_idx:]


def _check_reactor(
    srcs: List[CSourceFile],
    table: Dict[str, Tuple[CSourceFile, CFunction]],
    findings: List[Finding],
) -> None:
    """blocking-in-reactor: BFS the in-scan call graph from every
    ``epoll-root`` function; any reached socket call missing its
    nonblocking token (REACTOR_NONBLOCK_TOKENS) is a lane stall."""
    for src in srcs:
        for root in src.functions:
            if not src.epoll_root(root):
                continue
            seen: Set[str] = {root.name}
            emitted: Set[str] = set()
            frontier: List[Tuple[CSourceFile, CFunction, str]] = [
                (src, root, root.name)
            ]
            while frontier:
                fsrc, fn, path = frontier.pop()
                body = fsrc.code[fn.body_start:fn.body_end]
                for m in _REACTOR_CALL_RE.finditer(body):
                    callee = m.group(1)
                    token = REACTOR_NONBLOCK_TOKENS[callee]
                    if token in _call_args(body, m.end() - 1):
                        continue
                    line = fsrc.line_of(fn.body_start + m.start())
                    if fsrc.suppressed(line, PASS):
                        continue
                    key = f"{root.name}->{callee}:{fsrc.rel}:{line}"
                    if key in emitted:
                        continue
                    emitted.add(key)
                    findings.append(
                        Finding(
                            PASS, "blocking-in-reactor", src.rel,
                            root.name_line, root.name,
                            f"{root.name}->{callee}",
                            f"epoll-root {root.name} reaches blocking "
                            f"{callee}() without {token} via {path} "
                            f"({fsrc.rel}:{line}) — a reactor thread "
                            "parked in the kernel stalls every "
                            "connection on its lane",
                        )
                    )
                for m in _CALL_RE.finditer(body):
                    callee = m.group(1)
                    if callee in seen or callee not in table:
                        continue
                    seen.add(callee)
                    nsrc, nfn = table[callee]
                    frontier.append((nsrc, nfn, f"{path}->{callee}"))


# -- GIL discipline ----------------------------------------------------


def _function_table(srcs: List[CSourceFile]) -> Dict[str, Tuple[CSourceFile, CFunction]]:
    table: Dict[str, Tuple[CSourceFile, CFunction]] = {}
    for src in srcs:
        for fn in src.functions:
            prev = table.get(fn.name)
            # Prefer the longest body: a real definition over a
            # forward-declared stub parsed from another file.
            if prev is None or (
                (fn.body_end - fn.body_start)
                > (prev[1].body_end - prev[1].body_start)
            ):
                table[fn.name] = (src, fn)
    return table


def _check_gil(
    srcs: List[CSourceFile],
    table: Dict[str, Tuple[CSourceFile, CFunction]],
    findings: List[Finding],
) -> None:
    for src in srcs:
        for root in src.functions:
            if not src.gil_free(root):
                continue
            # BFS through the in-scan call graph.
            seen: Set[str] = {root.name}
            emitted: Set[str] = set()
            frontier: List[Tuple[CSourceFile, CFunction, str]] = [
                (src, root, root.name)
            ]
            while frontier:
                fsrc, fn, path = frontier.pop()
                body = fsrc.code[fn.body_start:fn.body_end]
                for m in _PY_API_RE.finditer(body):
                    line = fsrc.line_of(fn.body_start + m.start())
                    if fsrc.suppressed(line, PASS):
                        continue
                    findings.append(
                        Finding(
                            PASS, "gil-call", src.rel, root.name_line,
                            root.name,
                            f"{root.name}->{m.group(0).rstrip('(').strip()}",
                            f"gil-free {root.name} reaches Python C-API "
                            f"call {m.group(0).rstrip('(').strip()} via "
                            f"{path} ({fsrc.rel}:{line})",
                        )
                    )
                for m in _CALL_RE.finditer(body):
                    callee = m.group(1)
                    if callee in NATIVE_GIL_CALLS:
                        # Suppression lives at the offending CALL SITE
                        # (same contract as the Py-API branch above).
                        line = fsrc.line_of(fn.body_start + m.start())
                        if fsrc.suppressed(line, PASS):
                            continue
                        if f"{root.name}->{callee}" in emitted:
                            continue
                        emitted.add(f"{root.name}->{callee}")
                        findings.append(
                            Finding(
                                PASS, "gil-call", src.rel,
                                root.name_line, root.name,
                                f"{root.name}->{callee}",
                                f"gil-free {root.name} reaches the "
                                f"GIL-acquiring trampoline {callee!r} "
                                f"via {path} ({fsrc.rel}:{line})",
                            )
                        )
                        continue
                    if callee in seen or callee not in table:
                        continue
                    seen.add(callee)
                    nsrc, nfn = table[callee]
                    frontier.append((nsrc, nfn, f"{path}->{callee}"))

"""Baseline load/save/compare.

The committed ``guberlint_baseline.json`` pins the accepted findings
(ideally empty).  CI fails on findings NOT in the baseline; stale
baseline entries (fixed findings still listed) are reported so the
file shrinks monotonically.  Fingerprints exclude line numbers, so
unrelated edits don't churn the file.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, List, Set, Tuple

from tools.guberlint.common import Finding, SuppressionTracker

_KEYS = ("pass", "rule", "file", "scope", "detail")


def load(path: Path) -> Set[Tuple[str, str, str, str, str]]:
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return {
        (e["pass"], e["rule"], e["file"], e["scope"], e["detail"])
        for e in data.get("findings", [])
    }


def save(path: Path, findings: Iterable[Finding]) -> None:
    entries = sorted(
        {f.fingerprint() for f in findings}
    )
    doc = {
        "comment": (
            "guberlint accepted-findings baseline — see "
            "STATIC_ANALYSIS.md.  Prefer fixing or suppressing "
            "with a reasoned '# guberlint: ok <pass> — <why>' "
            "over growing this file."
        ),
        "findings": [dict(zip(_KEYS, fp)) for fp in entries],
    }
    if path.exists():
        try:
            old = json.loads(path.read_text())
            # The audit record (clean modules per pass) is maintained
            # by hand; rewriting the fingerprints must not drop it.
            if "audited_clean" in old:
                doc["audited_clean"] = old["audited_clean"]
        except ValueError:
            pass
    path.write_text(json.dumps(doc, indent=2) + "\n")


def partition(
    findings: List[Finding], base: Set[Tuple[str, str, str, str, str]]
) -> Tuple[List[Finding], List[Finding], List[Tuple[str, ...]]]:
    """(new, accepted, stale-baseline-entries)."""
    new: List[Finding] = []
    accepted: List[Finding] = []
    current = set()
    for f in findings:
        fp = f.fingerprint()
        current.add(fp)
        (accepted if fp in base else new).append(f)
    stale = sorted(base - current)
    return new, accepted, stale


# -- stale suppressions ------------------------------------------------
#
# A `# guberlint: ok <pass>` whose pass no longer fires at that site
# is leftover armor: the defect it silenced was fixed (or moved), and
# the comment now stands ready to swallow the NEXT real finding on
# that line.  The driver arms a SuppressionTracker for full-suite
# runs; here the declared-minus-hit difference becomes findings.

#: Passes whose Python-side suppressions the detector can adjudicate.
#: ``trace`` only runs on config.TRACE_SCOPES files (handled by the
#: caller passing those prefixes); ``native``/``contract`` suppressions
#: live in C sources with their own scanner and are out of scope here.
_DETECTABLE = ("lock", "trace", "thread", "net", "drift", "proto")


def stale_suppressions(
    tracker: SuppressionTracker, trace_scopes: Tuple[str, ...]
) -> List[Finding]:
    out: List[Finding] = []
    for rel in sorted(tracker.declared):
        hits = tracker.hits.get(rel, set())
        for line in sorted(tracker.declared[rel]):
            for pass_name in sorted(tracker.declared[rel][line]):
                if pass_name not in _DETECTABLE:
                    continue
                if pass_name == "trace" and not rel.startswith(
                    tuple(trace_scopes)
                ):
                    continue  # the trace pass never ran on this file
                if (line, pass_name) in hits:
                    continue
                out.append(
                    Finding(
                        "meta", "stale-suppression", rel, line,
                        "<module>", f"{pass_name}@{line}",
                        f"'# guberlint: ok {pass_name}' here silenced "
                        "nothing this run — the finding it suppressed "
                        "is gone; delete the comment (leftover "
                        "suppressions swallow the next real finding "
                        "on this line)",
                    )
                )
    return out

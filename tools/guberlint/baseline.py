"""Baseline load/save/compare.

The committed ``guberlint_baseline.json`` pins the accepted findings
(ideally empty).  CI fails on findings NOT in the baseline; stale
baseline entries (fixed findings still listed) are reported so the
file shrinks monotonically.  Fingerprints exclude line numbers, so
unrelated edits don't churn the file.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, List, Set, Tuple

from tools.guberlint.common import Finding

_KEYS = ("pass", "rule", "file", "scope", "detail")


def load(path: Path) -> Set[Tuple[str, str, str, str, str]]:
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return {
        (e["pass"], e["rule"], e["file"], e["scope"], e["detail"])
        for e in data.get("findings", [])
    }


def save(path: Path, findings: Iterable[Finding]) -> None:
    entries = sorted(
        {f.fingerprint() for f in findings}
    )
    doc = {
        "comment": (
            "guberlint accepted-findings baseline — see "
            "STATIC_ANALYSIS.md.  Prefer fixing or suppressing "
            "with a reasoned '# guberlint: ok <pass> — <why>' "
            "over growing this file."
        ),
        "findings": [dict(zip(_KEYS, fp)) for fp in entries],
    }
    if path.exists():
        try:
            old = json.loads(path.read_text())
            # The audit record (clean modules per pass) is maintained
            # by hand; rewriting the fingerprints must not drop it.
            if "audited_clean" in old:
                doc["audited_clean"] = old["audited_clean"]
        except ValueError:
            pass
    path.write_text(json.dumps(doc, indent=2) + "\n")


def partition(
    findings: List[Finding], base: Set[Tuple[str, str, str, str, str]]
) -> Tuple[List[Finding], List[Finding], List[Tuple[str, ...]]]:
    """(new, accepted, stale-baseline-entries)."""
    new: List[Finding] = []
    accepted: List[Finding] = []
    current = set()
    for f in findings:
        fp = f.fingerprint()
        current.add(fp)
        (accepted if fp in base else new).append(f)
    stale = sorted(base - current)
    return new, accepted, stale

"""Pass 3 — thread lifecycle.

- ``thread-orphan`` — every ``threading.Thread(daemon=True)`` must have
  a reachable stop/join path registered with its owner's shutdown:

  * assigned to ``self.X`` → some method of the same class must call
    ``self.X.join(...)`` (directly or via an attribute collection the
    class joins);
  * assigned to a local / collected into a local list → the enclosing
    function must join it;
  * fire-and-forget ``threading.Thread(...).start()`` → finding unless
    suppressed with a reasoned ``# guberlint: ok thread — <why>``.

  Non-daemon threads are exempt (the interpreter already refuses to
  exit while they run, so they cannot silently outlive their owner).

- ``thread-swallow`` — in modules that import ``threading``, an
  ``except Exception:``/bare ``except:`` whose body neither re-raises,
  logs, returns a value, nor records the swallow metric
  (``record_swallowed``) is banned: a background thread dying silently
  is the failure mode this repo can least afford (STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.guberlint.common import Finding, SourceFile, attr_path

PASS = "thread"


def _is_thread_ctor(node: ast.Call) -> bool:
    return attr_path(node.func) in ("threading.Thread", "Thread")


def _is_daemon(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "daemon":
            return not (
                isinstance(kw.value, ast.Constant) and kw.value.value is False
            )
    return False


def _class_joins(cls: ast.ClassDef) -> Set[str]:
    """Attribute names X for which `self.X.join(...)` (or
    `<anything>.join(...)` over an iteration of self.X, or a join of a
    local alias `y = self.X; y.join()` — the snapshot-under-lock
    shape the lock pass encourages for guarded thread handles)
    appears in the class."""
    joined: Set[str] = set()
    iterated: Set[str] = set()
    # local alias name -> self attr it snapshots (per class; aliases
    # are method-local in practice and attr names don't collide).
    aliases: dict = {}
    has_bare_join = False
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, path = node.targets[0], attr_path(node.value)
            if (
                isinstance(tgt, ast.Name)
                and path and path.startswith("self.")
                and path.count(".") == 1
            ):
                aliases[tgt.id] = path.split(".")[1]
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "join":
                path = attr_path(node.func.value)
                if path and path.startswith("self."):
                    joined.add(path.split(".")[1])
                elif path and path in aliases:
                    joined.add(aliases[path])
                else:
                    has_bare_join = True
        if isinstance(node, ast.For):
            path = attr_path(node.iter)
            if path and path.startswith("self."):
                iterated.add(path.split(".")[1])
    if has_bare_join:
        # `for t in self._threads: t.join()` — credit iterated attrs.
        joined |= iterated
    return joined


def _func_joins(fn: ast.AST) -> Set[str]:
    """Local names joined within the function (directly or via a loop
    over a local list)."""
    joined: Set[str] = set()
    loops = []  # (target name, iterated name)
    bare = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "join":
                if isinstance(node.func.value, ast.Name):
                    joined.add(node.func.value.id)
                else:
                    bare = True
        if isinstance(node, ast.For) and isinstance(node.iter, ast.Name):
            if isinstance(node.target, ast.Name):
                loops.append((node.target.id, node.iter.id))
    # `for t in threads: t.join()` joins the whole collection.
    for target, coll in loops:
        if target in joined:
            joined.add(coll)
        if bare:
            joined.add(coll)
    return joined


def _swallows_silently(handler: ast.ExceptHandler) -> bool:
    """True when the handler body is pure swallow: only ``pass``/
    ``continue``/``...``.  Any raise, return-with-value, assignment,
    or call (logging, metrics, fallback work) counts as handling — the
    ban is on the literal `except Exception: pass` shape."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
        ):
            continue  # docstring / ellipsis
        if isinstance(stmt, ast.Return) and stmt.value is None:
            continue  # bare return is still a swallow
        return False
    return True


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names = []
    if isinstance(handler.type, ast.Tuple):
        names = [attr_path(e) for e in handler.type.elts]
    else:
        names = [attr_path(handler.type)]
    return any(n in ("Exception", "BaseException") for n in names)


def check_file(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    if src.tree is None:
        return findings
    threaded = "threading" in src.text and any(
        isinstance(n, (ast.Import, ast.ImportFrom))
        and (
            any(a.name.split(".")[0] == "threading" for a in n.names)
            if isinstance(n, ast.Import)
            else (n.module or "").split(".")[0] == "threading"
        )
        for n in ast.walk(src.tree)
    )

    # -- thread-swallow -----------------------------------------------
    if threaded:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _catches_broad(node):
                continue
            if not _swallows_silently(node):
                continue
            if src.suppressed(node.lineno, PASS):
                continue
            findings.append(
                Finding(
                    PASS, "thread-swallow", src.rel, node.lineno,
                    "<module>", f"except@{node.lineno}",
                    "broad `except Exception` swallowed silently in a "
                    "threaded module — narrow it, or log + "
                    "record_swallowed() so the failure is visible",
                )
            )

    # -- thread-orphan -------------------------------------------------
    # Map every Thread(...) creation to its binding context.
    classes = {
        id(n): n for n in ast.walk(src.tree) if isinstance(n, ast.ClassDef)
    }
    funcs = [
        n for n in ast.walk(src.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]

    def enclosing(node: ast.AST, pool) -> Optional[ast.AST]:
        best = None
        for cand in pool:
            if (
                cand.lineno <= node.lineno
                and getattr(cand, "end_lineno", cand.lineno) >= node.lineno
            ):
                if best is None or cand.lineno > best.lineno:
                    best = cand
        return best

    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
            continue
        if not _is_daemon(node):
            continue
        if src.suppressed(node.lineno, PASS):
            continue
        cls = enclosing(node, classes.values())
        fn = enclosing(node, funcs)
        ok = False
        # Find the assignment target wrapping this call (self.X = ... /
        # local = ... / element of a list literal that is assigned).
        target_attr = None
        target_local = None
        for stmt in ast.walk(src.tree):
            if isinstance(stmt, ast.Assign) and any(
                node is sub or any(node is c for c in ast.walk(sub))
                for sub in [stmt.value]
            ):
                for tgt in stmt.targets:
                    path = attr_path(tgt)
                    if path and path.startswith("self."):
                        target_attr = path.split(".")[1]
                    elif isinstance(tgt, ast.Name):
                        target_local = tgt.id
                break
        if target_local and target_attr is None and fn is not None \
                and cls is not None:
            # Publish pattern: `t = Thread(...); t.start();
            # self.X = t` (start-before-publish, so a concurrent
            # close() never joins an unstarted thread) — the thread is
            # self.X-owned and the class join path applies.
            for stmt in ast.walk(fn):
                if (
                    isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Name)
                    and stmt.value.id == target_local
                ):
                    for tgt in stmt.targets:
                        path = attr_path(tgt)
                        if (
                            path and path.startswith("self.")
                            and path.count(".") == 1
                        ):
                            target_attr = path.split(".")[1]
        if target_attr and cls is not None:
            ok = target_attr in _class_joins(cls)
        elif target_local and fn is not None:
            ok = target_local in _func_joins(fn)
        elif fn is not None and not target_attr and not target_local:
            # Thread in an expression (list literal arg, direct
            # .start()): credit a join anywhere in the same function
            # over a comprehension/list the thread landed in.
            ok = bool(_func_joins(fn)) and ".start()" not in (
                src.line_text(node.lineno)
            )
        if not ok:
            findings.append(
                Finding(
                    PASS, "thread-orphan", src.rel, node.lineno,
                    getattr(cls, "name", None) or getattr(fn, "name", "<module>"),
                    f"thread@{getattr(cls, 'name', '')}."
                    f"{target_attr or target_local or node.lineno}",
                    "daemon thread without a reachable stop/join path "
                    "registered with its owner's shutdown — join it in "
                    "close(), or suppress with a reasoned "
                    "`# guberlint: ok thread — <why>`",
                )
            )
    return findings

"""Pass 6 — the Python↔C boundary, pinned bit-equal.

Three surfaces (STATIC_ANALYSIS.md documents grammar and limits):

1. **Wire layout** (``contract-wire-*``): every codec function in the
   native sources carries ``// guberlint: wire <Message>
   <field>=<num>:<kind>`` annotations.  The pass parses the .proto
   files (the source the Python codec is generated from) and checks
   each annotation three ways: the message exists, every declared
   field matches the proto's number AND wire kind
   (len/varint/64bit/32bit), and the function body actually uses
   exactly the declared field numbers (recognized idioms: ``(N << 3)``
   tag builds, ``case N:`` / ``field == N`` / ``sf == N`` decode
   dispatch, ``field >= A && field <= B`` ranges, and hex tag-byte
   ``push_back(0xNN)``).  Mutating the proto, the annotation, or the
   C literals trips it — the three can only move together.
2. **Protocol constants** (``contract-constant-mismatch``):
   config.CONTRACT_CONSTANTS pairs (decision-plane record kinds vs
   core/ledger.py's _K_* states, the lease breaker mask vs the bridge
   copy) must be numerically identical.  C values parse from
   constexpr/const declarations; Python values evaluate module-level
   int expressions (types.py enum members resolve).
3. **Enums** (``contract-enum-mismatch``): every proto enum member
   must exist in its types.py IntEnum twin with the same value
   (Python may extend — Behavior.SKETCH has no wire presence).
4. **Knobs** (``contract-knob-homeless``): every ``getenv("GUBER_*")``
   in the native sources must appear in config.py (the canonical
   env-surface index) — a C-only knob is invisible to operators.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from tools.guberlint.common import Finding
from tools.guberlint.config import (
    CONTRACT_CONSTANTS,
    ENUM_CONTRACTS,
    KNOB_HOME,
    PROTO_FILES,
)
from tools.guberlint.csource import CSourceFile

PASS = "contract"

# proto scalar type -> wire kind (proto3 wire format).
_WIRE_KINDS = {
    "int32": "varint", "int64": "varint", "uint32": "varint",
    "uint64": "varint", "sint32": "varint", "sint64": "varint",
    "bool": "varint", "enum": "varint",
    "fixed64": "64bit", "sfixed64": "64bit", "double": "64bit",
    "fixed32": "32bit", "sfixed32": "32bit", "float": "32bit",
    "string": "len", "bytes": "len", "message": "len", "map": "len",
}

_FIELD_NUM_PATTERNS = (
    re.compile(r"\((\d+)\s*<<\s*3\)"),
    re.compile(r"\bcase\s+(\d+)\s*:"),
    re.compile(r"\b(?:field|sf|f)\s*==\s*(\d+)"),
    re.compile(r"\(\s*(?:tag|t)\s*>>\s*3\s*\)\s*[!=]=\s*(\d+)"),
)
_FIELD_RANGE_PATTERNS = (
    re.compile(
        r"\b(?:field|sf)\s*>=\s*(\d+)\s*&&\s*(?:field|sf)\s*<=\s*(\d+)"
    ),
)
_TAG_BYTE_RE = re.compile(r"push_back\(0x([0-9a-fA-F]{1,2})\)")


# -- proto parsing -----------------------------------------------------


class ProtoSchema:
    def __init__(self) -> None:
        # message -> field name -> (number, wire kind)
        self.messages: Dict[str, Dict[str, Tuple[int, str]]] = {}
        # enum -> member -> value
        self.enums: Dict[str, Dict[str, int]] = {}


_PROTO_FIELD_RE = re.compile(
    r"^\s*(?:repeated\s+|optional\s+)?"
    r"(map\s*<[^>]*>|[\w.]+)\s+(\w+)\s*=\s*(\d+)\s*;"
)
_PROTO_ENUM_MEMBER_RE = re.compile(r"^\s*([A-Z][A-Z0-9_]*)\s*=\s*(\d+)\s*;")
_PROTO_BLOCK_RE = re.compile(r"^\s*(message|enum)\s+(\w+)\s*\{")


def parse_protos(paths: List[Path]) -> ProtoSchema:
    schema = ProtoSchema()
    for path in paths:
        _parse_proto(path.read_text(), schema)
    return schema


def _parse_proto(text: str, schema: ProtoSchema) -> None:
    text = re.sub(r"//[^\n]*", "", text)
    # Block stack: (kind, name) entries pushed per '{'.
    stack: List[Tuple[str, str]] = []
    for line in text.splitlines():
        m = _PROTO_BLOCK_RE.match(line)
        if m:
            stack.append((m.group(1), m.group(2)))
            if m.group(1) == "message":
                schema.messages.setdefault(m.group(2), {})
            else:
                schema.enums.setdefault(m.group(2), {})
            continue
        if re.match(r"^\s*(service|rpc|oneof)\b.*\{", line):
            stack.append(("other", ""))
            continue
        if stack:
            kind, name = stack[-1]
            if kind == "enum":
                em = _PROTO_ENUM_MEMBER_RE.match(line)
                if em:
                    schema.enums[name][em.group(1)] = int(em.group(2))
            elif kind == "message":
                fm = _PROTO_FIELD_RE.match(line)
                if fm:
                    ptype = fm.group(1).strip()
                    if ptype.startswith("map"):
                        wire = "len"
                    else:
                        base = ptype.split(".")[-1]
                        wire = _WIRE_KINDS.get(base)
                        if wire is None:
                            # Message or enum reference.
                            wire = (
                                "varint"
                                if base in schema.enums else "len"
                            )
                    schema.messages[name][fm.group(2)] = (
                        int(fm.group(3)), wire,
                    )
        if "}" in line and stack:
            stack.pop()


# -- constant evaluation -----------------------------------------------


def _cpp_constants(text: str) -> Dict[str, int]:
    """Module-level constexpr/const integer declarations, including
    comma-separated multi-declarations."""
    out: Dict[str, int] = {}
    for m in re.finditer(
        r"\b(?:constexpr|const)\s+[\w:<>]+\s+([^;=]*=[^;]*);", text
    ):
        for chunk in m.group(1).split(","):
            cm = re.match(
                r"\s*([A-Za-z_]\w*)\s*=\s*(-?(?:0x[0-9a-fA-F]+|\d+))\s*$",
                chunk,
            )
            if cm:
                out[cm.group(1)] = int(cm.group(2), 0)
    return out


class _PyConstEvaluator:
    """Evaluate module-level int constants in a .py file, resolving
    enum attributes (Behavior.GLOBAL, Status.OVER_LIMIT, ...) through
    the enum classes defined in gubernator_tpu/types.py."""

    def __init__(self, repo_root: Path):
        self.repo_root = repo_root
        self._enums: Optional[Dict[str, Dict[str, int]]] = None
        self._cache: Dict[str, Dict[str, Optional[int]]] = {}

    def enums(self) -> Dict[str, Dict[str, int]]:
        if self._enums is None:
            self._enums = parse_py_enums(
                self.repo_root / "gubernator_tpu" / "types.py"
            )
        return self._enums

    def lookup(self, rel: str, symbol: str) -> Optional[int]:
        if rel not in self._cache:
            self._cache[rel] = self._module_constants(rel)
        return self._cache[rel].get(symbol)

    def _module_constants(self, rel: str) -> Dict[str, Optional[int]]:
        path = self.repo_root / rel
        out: Dict[str, Optional[int]] = {}
        try:
            tree = ast.parse(path.read_text())
        except (OSError, SyntaxError):
            return out
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = self._eval(node.value, out)
        return out

    def _eval(self, node: ast.AST, env: Dict[str, Optional[int]]) -> Optional[int]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return int(node.value)
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            return self.enums().get(node.value.id, {}).get(node.attr)
        if isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Name) and func.id == "int"
                and len(node.args) == 1
            ):
                return self._eval(node.args[0], env)
            return None
        if isinstance(node, ast.BinOp):
            left = self._eval(node.left, env)
            right = self._eval(node.right, env)
            if left is None or right is None:
                return None
            ops = {
                ast.BitOr: lambda a, b: a | b,
                ast.BitAnd: lambda a, b: a & b,
                ast.BitXor: lambda a, b: a ^ b,
                ast.Add: lambda a, b: a + b,
                ast.Sub: lambda a, b: a - b,
                ast.LShift: lambda a, b: a << b,
                ast.Mult: lambda a, b: a * b,
            }
            fn = ops.get(type(node.op))
            return fn(left, right) if fn else None
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            v = self._eval(node.operand, env)
            return -v if v is not None else None
        return None


def parse_py_enums(path: Path) -> Dict[str, Dict[str, int]]:
    """IntEnum/IntFlag class bodies -> {class: {member: value}}."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        tree = ast.parse(path.read_text())
    except (OSError, SyntaxError):
        return out
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        members: Dict[str, int] = {}
        for stmt in node.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, int)
            ):
                members[stmt.targets[0].id] = int(stmt.value.value)
        if members:
            out[node.name] = members
    return out


# -- the pass ----------------------------------------------------------


def check(
    csrcs: List[CSourceFile],
    repo_root: Path,
    *,
    proto_files: Tuple[str, ...] = PROTO_FILES,
    constants: Tuple[Tuple[str, str, str, str], ...] = CONTRACT_CONSTANTS,
    enum_contracts: Tuple[Tuple[str, str], ...] = ENUM_CONTRACTS,
    knob_home: str = KNOB_HOME,
) -> List[Finding]:
    findings: List[Finding] = []
    schema = parse_protos(
        [repo_root / p for p in proto_files if (repo_root / p).exists()]
    )
    for src in csrcs:
        _check_wire(src, schema, findings)
        _check_getenv(src, repo_root, knob_home, findings)
    _check_constants(csrcs, repo_root, constants, findings)
    _check_enums(repo_root, schema, enum_contracts, findings)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def _check_wire(
    src: CSourceFile, schema: ProtoSchema, findings: List[Finding]
) -> None:
    for fn in src.functions:
        decls = src.wire_decls(fn)
        if not decls:
            continue
        declared_nums: Set[int] = set()
        for msg, fields, ln in decls:
            proto_fields = schema.messages.get(msg)
            if proto_fields is None:
                if not src.suppressed(ln, PASS):
                    findings.append(
                        Finding(
                            PASS, "wire-unknown-message", src.rel, ln,
                            fn.name, f"{fn.name}:{msg}",
                            f"wire annotation names message {msg!r} "
                            "not found in the proto contract",
                        )
                    )
                continue
            for fname, (num, kind) in sorted(fields.items()):
                declared_nums.add(num)
                proto = proto_fields.get(fname)
                if proto is None:
                    if not src.suppressed(ln, PASS):
                        findings.append(
                            Finding(
                                PASS, "wire-mismatch", src.rel, ln,
                                fn.name, f"{msg}.{fname}",
                                f"{msg}.{fname} declared in "
                                f"{fn.name}'s wire annotation does "
                                "not exist in the proto",
                            )
                        )
                    continue
                pnum, pkind = proto
                if pnum != num or pkind != kind:
                    if not src.suppressed(ln, PASS):
                        findings.append(
                            Finding(
                                PASS, "wire-mismatch", src.rel, ln,
                                fn.name, f"{msg}.{fname}",
                                f"{msg}.{fname}: annotation says "
                                f"{num}:{kind}, proto says "
                                f"{pnum}:{pkind} — the codec and the "
                                "Python contract have drifted",
                            )
                        )
        # Code-literal check: the body must use exactly the declared
        # field-number set through the recognized idioms.
        used = _field_numbers(src.code[fn.body_start:fn.body_end])
        anno_line = decls[0][2]
        if src.suppressed(anno_line, PASS):
            continue
        for num in sorted(declared_nums - used):
            findings.append(
                Finding(
                    PASS, "wire-unimplemented-field", src.rel,
                    anno_line, fn.name, f"{fn.name}:{num}",
                    f"{fn.name} declares wire field number {num} but "
                    "its body never builds or dispatches on it",
                )
            )
        for num in sorted(used - declared_nums):
            findings.append(
                Finding(
                    PASS, "wire-undeclared-field", src.rel, anno_line,
                    fn.name, f"{fn.name}:{num}",
                    f"{fn.name} handles wire field number {num} that "
                    "its annotation does not declare — declare it so "
                    "the proto pin covers it",
                )
            )


def _field_numbers(body: str) -> Set[int]:
    out: Set[int] = set()
    for pat in _FIELD_NUM_PATTERNS:
        for m in pat.finditer(body):
            out.add(int(m.group(1)))
    for pat in _FIELD_RANGE_PATTERNS:
        for m in pat.finditer(body):
            out.update(range(int(m.group(1)), int(m.group(2)) + 1))
    for m in _TAG_BYTE_RE.finditer(body):
        b = int(m.group(1), 16)
        field, wt = b >> 3, b & 7
        if field >= 1 and wt in (0, 1, 2, 5):
            out.add(field)
    return out


def _check_getenv(
    src: CSourceFile, repo_root: Path, knob_home: str,
    findings: List[Finding],
) -> None:
    home_path = repo_root / knob_home
    home_text = home_path.read_text() if home_path.exists() else ""
    code = src.code
    for lineno, value in src.strings:
        if not value.startswith("GUBER_"):
            continue
        # Only getenv("...") reads count (docs/log strings don't).
        line_code = src.lines[lineno - 1] if lineno <= len(src.lines) else ""
        prev_code = src.lines[lineno - 2] if lineno >= 2 else ""
        if "getenv" not in line_code and "getenv" not in prev_code:
            continue
        if value in home_text:
            continue
        if src.suppressed(lineno, PASS):
            continue
        findings.append(
            Finding(
                PASS, "knob-homeless", src.rel, lineno, "<module>",
                value,
                f"C reads {value} but {knob_home} (the canonical "
                "GUBER_* index) never mentions it — a C-only knob is "
                "invisible to operators",
            )
        )


def _check_constants(
    csrcs: List[CSourceFile],
    repo_root: Path,
    constants: Tuple[Tuple[str, str, str, str], ...],
    findings: List[Finding],
) -> None:
    ev = _PyConstEvaluator(repo_root)
    cpp_cache: Dict[str, Dict[str, int]] = {}

    def value_of(rel: str, symbol: str) -> Optional[int]:
        if rel.endswith((".cpp", ".cc", ".c", ".h", ".hpp")):
            if rel not in cpp_cache:
                for src in csrcs:
                    if src.rel == rel:
                        cpp_cache[rel] = _cpp_constants(src.code)
                        break
                else:
                    path = repo_root / rel
                    cpp_cache[rel] = (
                        _cpp_constants(path.read_text())
                        if path.exists() else {}
                    )
            return cpp_cache[rel].get(symbol)
        return ev.lookup(rel, symbol)

    for file_a, sym_a, file_b, sym_b in constants:
        va = value_of(file_a, sym_a)
        vb = value_of(file_b, sym_b)
        detail = f"{file_a}:{sym_a}<->{file_b}:{sym_b}"
        if va is None or vb is None:
            missing = f"{file_a}:{sym_a}" if va is None else f"{file_b}:{sym_b}"
            findings.append(
                Finding(
                    PASS, "constant-unresolved", file_a, 0, "<module>",
                    detail,
                    f"contract constant {missing} could not be "
                    "resolved — the pinned pair no longer parses "
                    "(renamed or restructured?)",
                )
            )
            continue
        if va != vb:
            findings.append(
                Finding(
                    PASS, "constant-mismatch", file_a, 0, "<module>",
                    detail,
                    f"{file_a}:{sym_a} = {va} but {file_b}:{sym_b} = "
                    f"{vb} — the two tiers of the protocol have "
                    "drifted",
                )
            )


def _check_enums(
    repo_root: Path,
    schema: ProtoSchema,
    enum_contracts: Tuple[Tuple[str, str], ...],
    findings: List[Finding],
) -> None:
    for enum_name, py_rel in enum_contracts:
        proto_members = schema.enums.get(enum_name)
        if proto_members is None:
            continue
        py_enums = parse_py_enums(repo_root / py_rel)
        py_members = py_enums.get(enum_name)
        if py_members is None:
            findings.append(
                Finding(
                    PASS, "enum-mismatch", py_rel, 0, enum_name,
                    f"{enum_name}:<missing>",
                    f"proto enum {enum_name} has no {py_rel} twin",
                )
            )
            continue
        for member, value in sorted(proto_members.items()):
            pv = py_members.get(member)
            if pv != value:
                findings.append(
                    Finding(
                        PASS, "enum-mismatch", py_rel, 0, enum_name,
                        f"{enum_name}.{member}",
                        f"{enum_name}.{member} is {value} on the wire "
                        f"but {pv} in {py_rel} — enum drift",
                    )
                )

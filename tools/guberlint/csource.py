"""Structural C/C++ parsing layer for the native guberlint tier.

Stdlib-only, deliberately NOT a compiler: a comment/string-aware
scanner plus brace matching gives the passes what they need —
function spans, struct spans with annotated fields, lexical
lock-guard regions, string literals, and the same annotation /
suppression grammar the Python tier uses (STATIC_ANALYSIS.md
documents the full contract and its limits).

Annotation grammar mirrored from the Python side:

- ``// guberlint: guarded-by <mutex>`` — trailing comment on a struct
  field declaration: every access outside a ``*_locked`` function (or
  one annotated ``holds``) must happen while a
  ``std::lock_guard``/``unique_lock``/``scoped_lock`` on the SAME
  receiver's ``<mutex>`` is lexically live.
- ``// guberlint: guard a, b by <mutex>`` — per-struct registry form.
- ``// guberlint: holds <mutex>[, ...]`` — on (or directly above) a
  function signature: the function is documented to be CALLED with
  those mutexes held.
- ``// guberlint: gil-free`` — on (or above) a function: no ``Py*``
  API call and no GIL-acquiring trampoline (config.NATIVE_GIL_CALLS)
  may be reachable from it through functions defined in the scanned
  native sources.
- ``// guberlint: epoll-root`` — on (or above) a function: it is an
  event-loop body (epoll reactor); no blocking socket syscall —
  ``send``/``recv`` without ``MSG_DONTWAIT``, ``accept`` without
  ``SOCK_NONBLOCK`` (config.REACTOR_NONBLOCK_TOKENS) — may be
  reachable from it: a reactor thread parked in the kernel stalls
  every connection on its lane.
- ``// guberlint: wire <Message> <field>=<num>:<kind> ...`` — on (or
  above) a codec function: declares the wire layout the body
  implements; the contract pass pins it against the .proto AND
  against the field-number literals in the body.
- ``// guberlint: ok <pass> — <reason>`` — suppression, same grammar
  as Python (a reasonless one is itself a finding).

Documented limits (by design — this is a lexical analyzer):

- Lock regions are lexical: a mutex held across a lambda that escapes
  the scope (stored callback) is still counted held inside the lambda
  body.  The repo's native code only uses lambdas for thread bodies
  and cv predicates, where the lexical reading is the correct one.
- Constructor/destructor bodies are exempt from the guard check
  (construction happens before publication, like Python __init__).
- Receiver matching is textual: ``p->items`` needs ``lock(p->mu)``;
  aliasing through references is out of scope.
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.guberlint.common import Finding, PASS_NAMES

_SUPPRESS_RE = re.compile(
    r"//\s*guberlint:\s*ok\s+(\w+)\s*(?:[—–:-]+\s*(.*))?$"
)
_GUARDED_RE = re.compile(r"//\s*guberlint:\s*guarded-by\s+([A-Za-z_]\w*)")
_GUARD_STRUCT_RE = re.compile(
    r"//\s*guberlint:\s*guard\s+([\w,\s]+?)\s+by\s+([A-Za-z_]\w*)"
)
_HOLDS_RE = re.compile(r"//\s*guberlint:\s*holds\s+([\w.>-]+(?:\s*,\s*[\w.>-]+)*)")
_GILFREE_RE = re.compile(r"//\s*guberlint:\s*gil-free\b")
_EPOLLROOT_RE = re.compile(r"//\s*guberlint:\s*epoll-root\b")
_WIRE_RE = re.compile(r"//\s*guberlint:\s*wire\s+(\w+)\s+(.*)$")
_WIRE_FIELD_RE = re.compile(r"([A-Za-z_]\w*)=(\d+):(\w+)")

_LOCK_RE = re.compile(
    r"(?:std::)?(?:lock_guard|unique_lock|scoped_lock)\s*"
    r"<[^;{}]*>\s*\w+\s*\(([^;]*?)\)\s*[;)]"
)
_RECV_RE = re.compile(r"^([A-Za-z_]\w*)\s*(?:->|\.)\s*([A-Za-z_]\w*)$")
_STRUCT_RE = re.compile(r"\b(?:struct|class)\s+([A-Za-z_]\w*)\s*(?::[^{;]*)?\{")
_CALL_RE = re.compile(r"\b([A-Za-z_]\w*)\s*\(")
_STRING_RE = re.compile(r'"((?:[^"\\]|\\.)*)"')

_CONTROL = {
    "if", "for", "while", "switch", "catch", "return", "sizeof",
    "alignof", "static_assert", "defined", "throw", "assert",
}
_POST_SIG = {"const", "noexcept", "override", "final"}


@dataclasses.dataclass
class CStruct:
    name: str
    start: int  # char offset of '{'
    end: int    # char offset of matching '}'
    start_line: int
    guards: Dict[str, str] = dataclasses.field(default_factory=dict)
    mutexes: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class CFunction:
    name: str
    name_line: int
    body_start: int  # char offset of '{'
    body_end: int    # char offset of matching '}'
    start_line: int  # line of '{'
    end_line: int
    struct: Optional[str] = None  # owning struct, if a member


@dataclasses.dataclass(frozen=True)
class LockRegion:
    start: int  # char offset where the guard is constructed
    end: int    # char offset of the enclosing block's '}'
    recv: str   # receiver text ('' = bare / implicit this)
    mutex: str


class CSourceFile:
    """One parsed native source: blanked code + spans + annotations."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        # `code`: comments and string/char literal CONTENTS blanked to
        # spaces (same length/line structure as `text`), so structural
        # regexes never match inside either.
        self.code, self.strings = _blank(self.text)
        self._line_starts = _line_starts(self.text)
        self.brace_match = _match_braces(self.code)
        self.suppressions: Dict[int, Set[str]] = {}
        self.bad_suppressions: List[Finding] = []
        self._scan_suppressions()
        self.structs = self._scan_structs()
        self.functions = self._scan_functions()

    # -- positions -----------------------------------------------------

    def line_of(self, offset: int) -> int:
        """1-based line number of a char offset."""
        lo, hi = 0, len(self._line_starts) - 1
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self._line_starts[mid] <= offset:
                lo = mid
            else:
                hi = mid - 1
        return lo + 1

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    # -- suppressions / annotations ------------------------------------

    def _scan_suppressions(self) -> None:
        for i, raw in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            pass_name, reason = m.group(1), (m.group(2) or "").strip()
            if pass_name not in PASS_NAMES:
                self.bad_suppressions.append(
                    Finding(
                        "meta", "bad-suppression", self.rel, i, "<module>",
                        f"unknown-pass:{pass_name}",
                        f"suppression names unknown pass {pass_name!r} "
                        f"(one of {PASS_NAMES})",
                    )
                )
                continue
            if not reason:
                self.bad_suppressions.append(
                    Finding(
                        "meta", "bad-suppression", self.rel, i, "<module>",
                        f"missing-reason:{pass_name}:{i}",
                        "suppression without a reason — write "
                        "'// guberlint: ok %s — <why>'" % pass_name,
                    )
                )
                continue
            target = i
            if raw.lstrip().startswith("//"):
                for j in range(i + 1, len(self.lines) + 1):
                    s = self.lines[j - 1].strip()
                    if s and not s.startswith("//"):
                        target = j
                        break
            self.suppressions.setdefault(target, set()).add(pass_name)

    def suppressed(self, line: int, pass_name: str) -> bool:
        return pass_name in self.suppressions.get(line, set())

    def _sig_lines(self, fn: CFunction) -> List[int]:
        """Lines an annotation for `fn` may live on: the signature
        line, the line above it, and the '{' line."""
        return [fn.name_line - 1, fn.name_line, fn.start_line]

    def holds(self, fn: CFunction) -> Set[str]:
        out: Set[str] = set()
        for ln in self._sig_lines(fn):
            m = _HOLDS_RE.search(self.line_text(ln))
            if m:
                out |= {s.strip() for s in m.group(1).split(",") if s.strip()}
        return out

    def gil_free(self, fn: CFunction) -> bool:
        return self._annotated(fn, _GILFREE_RE)

    def epoll_root(self, fn: CFunction) -> bool:
        return self._annotated(fn, _EPOLLROOT_RE)

    def _annotated(self, fn: CFunction, pattern) -> bool:
        """True when `pattern` appears on the signature lines or the
        contiguous // block above them."""
        lines = set(self._sig_lines(fn))
        ln = min(lines) - 1
        while ln >= 1 and self.line_text(ln).lstrip().startswith("//"):
            lines.add(ln)
            ln -= 1
        return any(
            pattern.search(self.line_text(ln)) for ln in sorted(lines)
        )

    def wire_decls(self, fn: CFunction) -> List[Tuple[str, Dict[str, Tuple[int, str]], int]]:
        """[(message, {field: (number, kind)}, lineno)] declared on the
        signature lines and contiguous comment block above them."""
        out = []
        lines = set(self._sig_lines(fn))
        # Walk the contiguous // block above the signature.
        ln = min(lines) - 1
        while ln >= 1 and self.line_text(ln).lstrip().startswith("//"):
            lines.add(ln)
            ln -= 1
        for ln in sorted(lines):
            m = _WIRE_RE.search(self.line_text(ln))
            if not m:
                continue
            fields = {
                f: (int(num), kind)
                for f, num, kind in _WIRE_FIELD_RE.findall(m.group(2))
            }
            out.append((m.group(1), fields, ln))
        return out

    # -- structure -----------------------------------------------------

    def _scan_structs(self) -> List[CStruct]:
        out: List[CStruct] = []
        for m in _STRUCT_RE.finditer(self.code):
            open_brace = m.end() - 1
            close = self.brace_match.get(open_brace)
            if close is None:
                continue
            s = CStruct(
                m.group(1), open_brace, close, self.line_of(m.start())
            )
            self._collect_guards(s)
            out.append(s)
        return out

    def _collect_guards(self, s: CStruct) -> None:
        first, last = self.line_of(s.start), self.line_of(s.end)
        for ln in range(first, last + 1):
            raw = self.line_text(ln)
            gm = _GUARD_STRUCT_RE.search(raw)
            if gm:
                for attr in re.split(r"[,\s]+", gm.group(1).strip()):
                    if attr:
                        s.guards[attr] = gm.group(2)
                        s.mutexes.add(gm.group(2))
                continue
            m = _GUARDED_RE.search(raw)
            if not m:
                continue
            for name in _field_names(_code_line(self.code, self._line_starts, ln)):
                s.guards[name] = m.group(1)
                s.mutexes.add(m.group(1))

    def _scan_functions(self) -> List[CFunction]:
        out: List[CFunction] = []
        code = self.code
        struct_spans = [(s.start, s.end, s.name) for s in self.structs]
        for open_brace, close in self.brace_match.items():
            name, name_pos = _function_name_before(code, open_brace)
            if not name or name in _CONTROL:
                continue
            owner = None
            for st, en, sname in struct_spans:
                if st < open_brace < en:
                    owner = sname
            if owner and (name == owner or name == "~" + owner):
                continue  # constructor/destructor: pre-publication
            out.append(
                CFunction(
                    name=name,
                    name_line=self.line_of(name_pos),
                    body_start=open_brace,
                    body_end=close,
                    start_line=self.line_of(open_brace),
                    end_line=self.line_of(close),
                    struct=owner,
                )
            )
        out.sort(key=lambda f: f.body_start)
        # Drop spans nested inside another function span (lambdas that
        # happened to parse function-like): the outer span covers them.
        top: List[CFunction] = []
        for f in out:
            if top and top[-1].body_end > f.body_end:
                continue
            top.append(f)
        return top

    # -- lock regions --------------------------------------------------

    def lock_regions(self, fn: CFunction) -> List[LockRegion]:
        out: List[LockRegion] = []
        body = self.code[fn.body_start:fn.body_end]
        opens = sorted(
            b for b in self.brace_match
            if fn.body_start <= b <= fn.body_end
        )
        for m in _LOCK_RE.finditer(body):
            pos = fn.body_start + m.start()
            # Innermost block containing the guard construction.
            enclosing = fn.body_start
            for b in opens:
                if b < pos < self.brace_match[b]:
                    enclosing = b
            end = self.brace_match[enclosing]
            for arg in _split_args(m.group(1)):
                arg = arg.strip()
                if not arg or "defer_lock" in arg or "adopt_lock" in arg:
                    continue
                rm = _RECV_RE.match(arg)
                if rm:
                    out.append(LockRegion(pos, end, rm.group(1), rm.group(2)))
                elif re.fullmatch(r"[A-Za-z_]\w*", arg):
                    out.append(LockRegion(pos, end, "", arg))
        return out

    def held_at(self, fn: CFunction, offset: int) -> Set[Tuple[str, str]]:
        """(recv, mutex) pairs lexically held at `offset` in `fn`,
        including `holds` annotations and the *_locked convention
        (reported as the wildcard ('', '*'))."""
        held: Set[Tuple[str, str]] = set()
        for r in self.lock_regions(fn):
            if r.start <= offset <= r.end:
                held.add((r.recv, r.mutex))
        for h in self.holds(fn):
            rm = _RECV_RE.match(h)
            if rm:
                held.add((rm.group(1), rm.group(2)))
            else:
                held.add(("", h))
        if fn.name.endswith("_locked"):
            held.add(("", "*"))
        return held


# -- low-level helpers -------------------------------------------------


def _blank(text: str) -> Tuple[str, List[Tuple[int, str]]]:
    """Blank comments and string/char contents to spaces (newlines
    kept).  Returns (code, [(lineno, string_literal_value)])."""
    out = list(text)
    strings: List[Tuple[int, str]] = []
    i, n = 0, len(text)
    line = 1
    while i < n:
        c = text[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            while i < n and text[i] != "\n":
                out[i] = " "
                i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "*":
            out[i] = out[i + 1] = " "
            i += 2
            while i + 1 < n and not (text[i] == "*" and text[i + 1] == "/"):
                if text[i] == "\n":
                    line += 1
                else:
                    out[i] = " "
                i += 1
            if i + 1 < n:
                out[i] = out[i + 1] = " "
                i += 2
            continue
        if c in "\"'":
            quote = c
            start_line = line
            i += 1
            lit = []
            while i < n and text[i] != quote:
                if text[i] == "\\" and i + 1 < n:
                    lit.append(text[i:i + 2])
                    out[i] = out[i + 1] = " "
                    i += 2
                    continue
                if text[i] == "\n":
                    line += 1
                    i += 1
                    continue
                lit.append(text[i])
                out[i] = " "
                i += 1
            if i < n:
                i += 1  # closing quote (kept in `code`)
            if quote == '"':
                strings.append((start_line, "".join(lit)))
            continue
        i += 1
    return "".join(out), strings


def _line_starts(text: str) -> List[int]:
    starts = [0]
    for i, c in enumerate(text):
        if c == "\n":
            starts.append(i + 1)
    return starts


def _match_braces(code: str) -> Dict[int, int]:
    match: Dict[int, int] = {}
    stack: List[int] = []
    for i, c in enumerate(code):
        if c == "{":
            stack.append(i)
        elif c == "}" and stack:
            match[stack.pop()] = i
    return match


def _function_name_before(code: str, open_brace: int) -> Tuple[str, int]:
    """Function name owning the body at `open_brace`, or ('', 0).
    Walks back over trailing qualifiers and the parameter list."""
    i = open_brace - 1
    while True:
        while i >= 0 and code[i].isspace():
            i -= 1
        if i < 0:
            return "", 0
        # Trailing qualifiers between ')' and '{'.
        if code[i].isalpha() or code[i] == "_":
            j = i
            while j >= 0 and (code[j].isalnum() or code[j] == "_"):
                j -= 1
            word = code[j + 1:i + 1]
            if word in _POST_SIG:
                i = j
                continue
            return "", 0  # `struct X {`, `namespace {`, init lists...
        break
    if code[i] != ")":
        return "", 0
    depth = 0
    while i >= 0:
        if code[i] == ")":
            depth += 1
        elif code[i] == "(":
            depth -= 1
            if depth == 0:
                break
        i -= 1
    if i < 0:
        return "", 0
    i -= 1
    while i >= 0 and code[i].isspace():
        i -= 1
    j = i
    while j >= 0 and (code[j].isalnum() or code[j] == "_" or code[j] == "~"):
        j -= 1
    name = code[j + 1:i + 1]
    # Strip a qualifying Class:: prefix if present.
    if j >= 1 and code[j] == ":" and code[j - 1] == ":":
        pass  # name already holds the unqualified tail
    return name, j + 1 if name else 0


def _split_args(s: str) -> List[str]:
    out, depth, cur = [], 0, []
    for c in s:
        if c in "(<[":
            depth += 1
        elif c in ")>]":
            depth -= 1
        if c == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
    out.append("".join(cur))
    return out


def _code_line(code: str, line_starts: List[int], lineno: int) -> str:
    start = line_starts[lineno - 1]
    end = (
        line_starts[lineno] - 1
        if lineno < len(line_starts) else len(code)
    )
    return code[start:end]


def _field_names(decl: str) -> List[str]:
    """Declared names on one struct-field line: strip the trailing ';'
    and initializers, split multi-declarations on commas, take the
    last identifier of each chunk."""
    decl = decl.strip()
    if not decl.endswith(";"):
        return []
    decl = decl[:-1]
    names = []
    for chunk in _split_args(decl):
        chunk = chunk.split("=")[0].strip()
        chunk = re.sub(r"\{[^{}]*\}\s*$", "", chunk).strip()
        m = re.search(r"([A-Za-z_]\w*)\s*(?:\[[^\]]*\])?$", chunk)
        if m:
            names.append(m.group(1))
    return names


def iter_c_files(
    roots: Iterable[Path], repo_root: Path
) -> List[CSourceFile]:
    out: List[CSourceFile] = []
    seen: Set[Path] = set()
    for root in roots:
        if root.is_file():
            paths = [root]
        else:
            paths = sorted(
                p for ext in ("*.cpp", "*.cc", "*.c", "*.h", "*.hpp")
                for p in root.rglob(ext)
            )
        for p in paths:
            if p in seen or p.suffix not in (".cpp", ".cc", ".c", ".h", ".hpp"):
                continue
            seen.add(p)
            out.append(CSourceFile(p, p.relative_to(repo_root).as_posix()))
    return out

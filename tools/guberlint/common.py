"""Shared guberlint plumbing: findings, annotations, suppressions.

Annotation grammar (STATIC_ANALYSIS.md documents the full contract):

- ``# guberlint: guarded-by <lock>`` — trailing comment on a
  ``self.attr = ...`` line: every read/write of ``attr`` outside
  ``__init__`` must happen under ``with <receiver>.<lock>``.
- ``# guberlint: guard a, b by <lock>`` — per-class registry form, a
  standalone comment anywhere in the class body.
- ``# guberlint: holds <lock>[, <lock>...]`` — trailing comment on a
  ``def`` line: the method is documented to be CALLED with those locks
  held (the ``*_locked`` naming convention implies holding every lock
  the class declares).
- ``# guberlint: shapes <contract>`` — on (or directly above) a
  ``jax.jit`` definition site: documents what pins the function's
  argument shapes/dtypes (the columnar layout / warmup ladder).
- ``# guberlint: ok <pass> — <reason>`` — suppression: silences the
  named pass on that line (or, as a standalone comment, on the next
  code line).  A suppression without a reason is itself a finding.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

PASS_NAMES = (
    "lock", "trace", "thread", "net", "native", "contract", "drift",
    "proto",
)

# Reason separator accepts em/en dash, hyphen, or colon.
_SUPPRESS_RE = re.compile(
    r"#\s*guberlint:\s*ok\s+(\w+)\s*(?:[—–:-]+\s*(.*))?$"
)
_GUARDED_RE = re.compile(r"#\s*guberlint:\s*guarded-by\s+([A-Za-z_][\w.]*)")


# -- suppression-usage tracking ----------------------------------------
#
# Every pass consults SourceFile.suppressed() only at a site where a
# finding is otherwise imminent, so "suppressed() returned True" means
# exactly "this suppression silenced a real finding this run".  The
# tracker (armed by the driver for full-suite runs) collects declared
# suppressions and those hits; baseline.stale_suppressions() turns the
# difference into findings — a `# guberlint: ok <pass>` whose pass no
# longer fires at that site is leftover armor that would silently
# swallow the NEXT real finding on that line.

_TRACKER: Optional["SuppressionTracker"] = None


class SuppressionTracker:
    """Context manager collecting declared suppressions and hits for
    one lint run, keyed by repo-relative path."""

    def __init__(self):
        # rel -> {line -> {pass}} (post-resolution target lines)
        self.declared: Dict[str, Dict[int, Set[str]]] = {}
        # rel -> {(line, pass)} that silenced an imminent finding
        self.hits: Dict[str, Set[Tuple[int, str]]] = {}

    def __enter__(self) -> "SuppressionTracker":
        global _TRACKER
        _TRACKER = self
        return self

    def __exit__(self, *exc) -> None:
        global _TRACKER
        _TRACKER = None
_GUARD_CLASS_RE = re.compile(
    r"#\s*guberlint:\s*guard\s+([\w,\s]+?)\s+by\s+([A-Za-z_][\w.]*)"
)
_HOLDS_RE = re.compile(r"#\s*guberlint:\s*holds\s+([\w.]+(?:\s*,\s*[\w.]+)*)")
_SHAPES_RE = re.compile(r"#\s*guberlint:\s*shapes\b[:\s]*(.*)$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One guberlint finding.

    ``detail`` is the stable fingerprint component (attribute / symbol
    name) so baselines survive line drift; ``line`` is for humans.
    """

    pass_name: str
    rule: str
    file: str  # repo-relative posix path
    line: int
    scope: str  # "Class.method", "func", or "<module>"
    detail: str
    message: str

    def fingerprint(self) -> Tuple[str, str, str, str, str]:
        return (self.pass_name, self.rule, self.file, self.scope, self.detail)

    def render(self) -> str:
        return (
            f"{self.file}:{self.line}: [{self.pass_name}/{self.rule}] "
            f"{self.scope}: {self.message}"
        )


class SourceFile:
    """One parsed module: AST + raw lines + suppression/annotation maps."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.text)
        except SyntaxError as e:  # surfaced as a finding by the driver
            self.parse_error = str(e)
        # line (1-based) -> set of pass names suppressed there
        self.suppressions: Dict[int, Set[str]] = {}
        self.bad_suppressions: List[Finding] = []
        self._scan_suppressions()

    # -- suppressions --------------------------------------------------

    def _scan_suppressions(self) -> None:
        for i, raw in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            pass_name = m.group(1)
            reason = (m.group(2) or "").strip()
            if pass_name not in PASS_NAMES:
                self.bad_suppressions.append(
                    Finding(
                        "meta", "bad-suppression", self.rel, i, "<module>",
                        f"unknown-pass:{pass_name}",
                        f"suppression names unknown pass {pass_name!r} "
                        f"(one of {PASS_NAMES})",
                    )
                )
                continue
            if not reason:
                self.bad_suppressions.append(
                    Finding(
                        "meta", "bad-suppression", self.rel, i, "<module>",
                        f"missing-reason:{pass_name}:{i}",
                        "suppression without a reason — write "
                        "'# guberlint: ok %s — <why>'" % pass_name,
                    )
                )
                continue
            target = i
            if raw.lstrip().startswith("#"):
                # Standalone comment: applies to the next code line.
                target = self._next_code_line(i)
            self.suppressions.setdefault(target, set()).add(pass_name)
        if _TRACKER is not None and self.suppressions:
            decl = _TRACKER.declared.setdefault(self.rel, {})
            for line, passes in self.suppressions.items():
                decl.setdefault(line, set()).update(passes)

    def _next_code_line(self, after: int) -> int:
        for j in range(after + 1, len(self.lines) + 1):
            stripped = self.lines[j - 1].strip()
            if stripped and not stripped.startswith("#"):
                return j
        return after

    def suppressed(self, line: int, pass_name: str) -> bool:
        hit = pass_name in self.suppressions.get(line, set())
        if hit and _TRACKER is not None:
            _TRACKER.hits.setdefault(self.rel, set()).add(
                (line, pass_name)
            )
        return hit

    def suppressed_span(self, node: ast.AST, pass_name: str) -> bool:
        """Suppression on the node's first line (or the `def` line of a
        decorated statement)."""
        line = getattr(node, "lineno", 0)
        if self.suppressed(line, pass_name):
            return True
        for deco in getattr(node, "decorator_list", []):
            if self.suppressed(deco.lineno, pass_name):
                return True
        return False

    # -- annotations ---------------------------------------------------

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def guarded_by(self, lineno: int) -> Optional[str]:
        m = _GUARDED_RE.search(self.line_text(lineno))
        return m.group(1) if m else None

    def class_registry(self, start: int, end: int) -> Dict[str, str]:
        """``# guberlint: guard a, b by lock`` lines in [start, end]."""
        out: Dict[str, str] = {}
        for i in range(start, min(end, len(self.lines)) + 1):
            m = _GUARD_CLASS_RE.search(self.lines[i - 1])
            if m:
                lock = m.group(2)
                for attr in re.split(r"[,\s]+", m.group(1).strip()):
                    if attr:
                        out[attr] = lock
        return out

    def holds(self, node: ast.AST) -> Set[str]:
        """Locks a `def` is annotated as holding (def line, decorator
        lines, or the line directly above)."""
        lines = [getattr(node, "lineno", 0)]
        lines += [d.lineno for d in getattr(node, "decorator_list", [])]
        first = min(lines)
        lines.append(first - 1)
        out: Set[str] = set()
        for ln in lines:
            m = _HOLDS_RE.search(self.line_text(ln))
            if m:
                out |= {s.strip() for s in m.group(1).split(",") if s.strip()}
        return out

    def shapes_annotation(self, *linenos: int) -> bool:
        """A ``# guberlint: shapes`` contract on any of the given lines
        or the line directly above any of them (decorator line, def
        line, or jit-assignment line all work)."""
        check = set(linenos)
        check |= {ln - 1 for ln in linenos}
        return any(_SHAPES_RE.search(self.line_text(ln)) for ln in check)


def attr_path(node: ast.AST) -> Optional[str]:
    """Dotted path of a Name/Attribute chain ('self.engine._lock'), or
    None when the chain includes calls/subscripts."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_py_files(
    roots: Iterable[Path], repo_root: Path, exclude: Tuple[str, ...] = ()
) -> List[SourceFile]:
    out: List[SourceFile] = []
    seen: Set[Path] = set()
    for root in roots:
        paths = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for p in paths:
            if p in seen or p.suffix != ".py":
                continue
            rel = p.relative_to(repo_root).as_posix()
            if any(rel.startswith(e) for e in exclude):
                continue
            seen.add(p)
            out.append(SourceFile(p, rel))
    return out

"""Pass 2 — JAX trace hygiene.

Over the jit-reachable kernel code (``ops/``, ``core/engine.py``,
``core/pump.py``, ``core/readback.py``, ``parallel/`` — see
config.TRACE_SCOPES), flag:

- ``trace-branch`` — Python ``if``/``while``/``assert`` on a
  traced-value expression inside a jit-reachable function (tracers
  raise ``TracerBoolConversionError`` at runtime, or worse, silently
  specialize and recompile per value when the input is weakly typed);
- ``trace-transfer`` — host transfers (``np.asarray``/``np.array``/
  ``float()``/``int()``/``bool()``/``.item()``/``.tolist()``) applied
  to traced values inside jit-reachable code: each one is a device
  sync + d2h round trip in the serve path;
- ``trace-shapes`` — every jit definition site must carry a
  ``# guberlint: shapes <contract>`` annotation documenting what pins
  its argument shapes/dtypes (the columnar layout / warmup ladder), so
  an unpinned call surface — the source of surprise XLA recompiles —
  is visible in review.

Taint model: function parameters are traced (minus ``static_argnums``/
``static_argnames`` declared by the jit wrapper), taint propagates
through assignments; ``.shape``/``.ndim``/``.dtype``/``len()``/
``isinstance()`` and attribute constants strip taint (they are static
under trace).  jit-reachability is the transitive closure of
module-level calls from jit roots within each scanned file.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.guberlint.common import Finding, SourceFile, attr_path

PASS = "trace"

_JIT_NAMES = {
    "jax.jit", "jit", "pjit", "jax.pmap", "pmap",
    # Pallas kernels are jit roots too: a pallas_call site pins its
    # block/out shapes exactly like a jit signature pins arg shapes,
    # so it carries the same `# guberlint: shapes` contract.
    "pl.pallas_call", "pallas_call", "jax.experimental.pallas.pallas_call",
}
_STATIC_STRIP_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type"}
_STATIC_CALLS = {"len", "isinstance", "range", "tuple", "type", "hasattr",
                 "getattr"}
_TRANSFER_CALLS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "np.frombuffer", "float", "int", "bool", "np.ascontiguousarray",
}
_TRANSFER_METHODS = {"item", "tolist", "block_until_ready"}


def _is_jit_call(node: ast.Call) -> bool:
    path = attr_path(node.func)
    if path in _JIT_NAMES:
        return True
    # functools.partial(jax.jit, ...) used as a decorator factory.
    if path in ("partial", "functools.partial") and node.args:
        inner = attr_path(node.args[0])
        return inner in _JIT_NAMES
    return False


def _static_args_of(call: ast.Call) -> Set[str]:
    """static_argnames declared on the jit call (names only; positional
    static_argnums are resolved against the wrapped def by the caller)."""
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for elt in ast.walk(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    names.add(elt.value)
    return names


def _static_argnums_of(call: ast.Call) -> Set[int]:
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for elt in ast.walk(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    nums.add(elt.value)
    return nums


class _JitRoot:
    def __init__(self, func: ast.AST, call: Optional[ast.Call],
                 site_lines: Tuple[int, ...]):
        self.func = func  # FunctionDef or Lambda
        self.call = call  # the jax.jit(...) call, when present
        self.site_lines = site_lines  # lines eligible for the shapes tag


def _collect_roots(src: SourceFile) -> Tuple[List[_JitRoot], Dict[str, ast.FunctionDef]]:
    """(jit roots, module-level function table)."""
    funcs: Dict[str, ast.FunctionDef] = {}
    roots: List[_JitRoot] = []
    assert src.tree is not None
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, node)

    def root_from_call(call: ast.Call, assign_line: int) -> None:
        if not call.args:
            return
        target = call.args[0]
        if isinstance(target, ast.Lambda):
            roots.append(_JitRoot(target, call, (assign_line,)))
        else:
            name = attr_path(target)
            fn = funcs.get(name.split(".")[-1]) if name else None
            if fn is not None:
                roots.append(_JitRoot(fn, call, (assign_line, fn.lineno)))

    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if isinstance(deco, ast.Call) and _is_jit_call(deco):
                    roots.append(
                        _JitRoot(node, deco, (deco.lineno, node.lineno))
                    )
                elif attr_path(deco) in _JIT_NAMES:
                    roots.append(
                        _JitRoot(node, None, (deco.lineno, node.lineno))
                    )
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jit_call(node.value):
                root_from_call(node.value, node.lineno)
    return roots, funcs


def _reachable(
    roots: List[_JitRoot], funcs: Dict[str, ast.FunctionDef]
) -> Set[str]:
    """Names of module-level functions transitively called from jit
    roots (they execute traced)."""
    queue = []
    for r in roots:
        queue.append(r.func)
    seen: Set[str] = set()
    out: Set[str] = set()
    while queue:
        fn = queue.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = attr_path(node.func)
                if name and name.split(".")[-1] in funcs:
                    callee = funcs[name.split(".")[-1]]
                    if callee.name not in out:
                        out.add(callee.name)
                        queue.append(callee)
    return out


class _TaintChecker(ast.NodeVisitor):
    def __init__(self, src: SourceFile, scope: str, params: Set[str],
                 findings: List[Finding]):
        self.src = src
        self.scope = scope
        self.tainted = set(params)
        self.findings = findings

    # -- taint of an expression ---------------------------------------

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_STRIP_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            # x.shape[0] is static; arr[i] of a traced arr is traced.
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            name = attr_path(node.func)
            if name and name.split(".")[-1] in _STATIC_CALLS:
                return False
            if name and name.split(".")[0] in ("jnp", "jax", "lax"):
                return any(self.is_tainted(a) for a in node.args) or any(
                    self.is_tainted(k.value) for k in node.keywords
                )
            # Method call on a traced receiver stays traced
            # (x.astype(...), x.sum()).
            if isinstance(node.func, ast.Attribute):
                return self.is_tainted(node.func.value)
            return False
        if isinstance(node, (ast.BinOp,)):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        return False

    # -- statement walk ------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        tainted = self.is_tainted(node.value)
        for tgt in node.targets:
            for name in ast.walk(tgt):
                if isinstance(name, ast.Name):
                    if tainted:
                        self.tainted.add(name.id)
                    else:
                        self.tainted.discard(name.id)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.target, ast.Name) and self.is_tainted(node.value):
            self.tainted.add(node.target.id)
        self.generic_visit(node)

    def _flag_branch(self, node, kind: str) -> None:
        if self.is_tainted(node.test) and not self.src.suppressed(
            node.lineno, PASS
        ):
            self.findings.append(
                Finding(
                    PASS, "trace-branch", self.src.rel, node.lineno,
                    self.scope, f"{kind}@{self.scope}",
                    f"Python `{kind}` on a traced value — use jnp.where/"
                    "lax.cond or hoist the branch out of the jit",
                )
            )

    def visit_If(self, node: ast.If) -> None:
        self._flag_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._flag_branch(node, "while")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        if self.is_tainted(node.test) and not self.src.suppressed(
            node.lineno, PASS
        ):
            self.findings.append(
                Finding(
                    PASS, "trace-branch", self.src.rel, node.lineno,
                    self.scope, f"assert@{self.scope}",
                    "Python `assert` on a traced value inside jit",
                )
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = attr_path(node.func)
        if name:
            if name in _TRANSFER_CALLS and any(
                self.is_tainted(a) for a in node.args
            ):
                if not self.src.suppressed(node.lineno, PASS):
                    self.findings.append(
                        Finding(
                            PASS, "trace-transfer", self.src.rel,
                            node.lineno, self.scope,
                            f"{name}@{self.scope}",
                            f"host transfer `{name}()` of a traced value "
                            "inside jit-reachable code",
                        )
                    )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _TRANSFER_METHODS
                and self.is_tainted(node.func.value)
            ):
                if not self.src.suppressed(node.lineno, PASS):
                    self.findings.append(
                        Finding(
                            PASS, "trace-transfer", self.src.rel,
                            node.lineno, self.scope,
                            f".{node.func.attr}@{self.scope}",
                            f"host transfer `.{node.func.attr}()` of a "
                            "traced value inside jit-reachable code",
                        )
                    )
        self.generic_visit(node)


def _params_of(func: ast.AST, call: Optional[ast.Call]) -> Set[str]:
    args = func.args
    names = [a.arg for a in args.posonlyargs + args.args]
    names += [a.arg for a in args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    static: Set[str] = set()
    if call is not None:
        static |= _static_args_of(call)
        positional = [a.arg for a in args.posonlyargs + args.args]
        for i in _static_argnums_of(call):
            if 0 <= i < len(positional):
                static.add(positional[i])
    return {n for n in names if n not in static and n != "self"}


def check_file(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    if src.tree is None:
        return findings
    roots, funcs = _collect_roots(src)

    # trace-shapes: every jit definition site carries a shapes contract.
    for root in roots:
        if src.shapes_annotation(*root.site_lines):
            continue
        if any(src.suppressed(ln, PASS) for ln in root.site_lines):
            continue
        name = getattr(root.func, "name", "<lambda>")
        findings.append(
            Finding(
                PASS, "trace-shapes", src.rel, min(root.site_lines),
                name, f"shapes:{name}",
                "jit definition without a `# guberlint: shapes <contract>` "
                "annotation pinning its argument shapes/dtypes",
            )
        )

    # trace-branch / trace-transfer over jit roots + reachable helpers.
    reachable = _reachable(roots, funcs)
    checked: Set[int] = set()
    for root in roots:
        fn = root.func
        if id(fn) in checked or isinstance(fn, ast.Lambda):
            continue
        checked.add(id(fn))
        scope = getattr(fn, "name", "<lambda>")
        checker = _TaintChecker(src, scope, _params_of(fn, root.call), findings)
        for stmt in fn.body:
            checker.visit(stmt)
    for name in sorted(reachable):
        fn = funcs[name]
        if id(fn) in checked:
            continue
        checked.add(id(fn))
        checker = _TaintChecker(src, name, _params_of(fn, None), findings)
        for stmt in fn.body:
            checker.visit(stmt)
    return findings

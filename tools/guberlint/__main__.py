"""guberlint driver: ``python -m tools.guberlint [paths...]``.

Exit codes: 0 = no findings outside the baseline; 1 = new findings (or
a parse failure); 2 = bad invocation.

Options:
  --baseline FILE    baseline JSON (default: guberlint_baseline.json
                     at the repo root)
  --write-baseline   rewrite the baseline to the current finding set
  --fix-annotations  insert `# guberlint: guarded-by` stubs for
                     attributes whose every non-__init__ access already
                     happens under one consistent lock (review the diff
                     before committing)
  --json             machine-readable output
  --no-baseline      ignore the baseline (report everything)
"""

from __future__ import annotations

import argparse
import ast
import json
import sys
from pathlib import Path
from typing import Dict, List, Set, Tuple

from tools.guberlint import baseline as baseline_mod
from tools.guberlint import lockcheck, netcheck, threadcheck, tracecheck
from tools.guberlint.common import Finding, SourceFile, attr_path, iter_py_files
from tools.guberlint.config import EXCLUDE, LINT_ROOTS, TRACE_SCOPES

REPO_ROOT = Path(__file__).resolve().parents[2]


def run(paths: List[Path]) -> List[Finding]:
    files = iter_py_files(paths, REPO_ROOT, exclude=EXCLUDE)
    findings: List[Finding] = []
    edges: Set[Tuple[str, str, str, int]] = set()
    for src in files:
        if src.parse_error:
            findings.append(
                Finding(
                    "meta", "parse-error", src.rel, 0, "<module>",
                    "parse", f"syntax error: {src.parse_error}",
                )
            )
            continue
        findings.extend(src.bad_suppressions)
        findings.extend(lockcheck.check_file(src, edges))
        if any(src.rel.startswith(s) for s in TRACE_SCOPES):
            findings.extend(tracecheck.check_file(src))
        findings.extend(threadcheck.check_file(src))
        findings.extend(netcheck.check_file(src))
    findings.extend(lockcheck.order_findings(edges))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


# -- --fix-annotations -------------------------------------------------


def fix_annotations(paths: List[Path]) -> int:
    """Insert `# guberlint: guarded-by <lock>` stubs on __init__
    assignment lines of attributes whose every access outside __init__
    is under one consistent `with self.<lock>` block.  Conservative:
    skips attrs with any unlocked access or mixed locks."""
    files = iter_py_files(paths, REPO_ROOT, exclude=EXCLUDE)
    inserted = 0
    for src in files:
        if src.tree is None:
            continue
        new_lines = list(src.lines)
        changed = False
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            usage = _attr_lock_usage(cls)
            init = next(
                (
                    n for n in cls.body
                    if isinstance(n, ast.FunctionDef) and n.name == "__init__"
                ),
                None,
            )
            if init is None:
                continue
            declared = _declared_attrs(src, cls)
            for stmt in ast.walk(init):
                if not isinstance(stmt, ast.Assign):
                    continue
                for tgt in stmt.targets:
                    path = attr_path(tgt)
                    if not path or not path.startswith("self."):
                        continue
                    attr = path[len("self."):]
                    if "." in attr or attr in declared:
                        continue
                    locks = usage.get(attr)
                    if not locks or len(locks) != 1 or None in locks:
                        continue
                    ln = stmt.lineno - 1
                    if "guberlint" in new_lines[ln]:
                        continue
                    new_lines[ln] = (
                        new_lines[ln].rstrip()
                        + f"  # guberlint: guarded-by {next(iter(locks))}"
                    )
                    changed = True
                    inserted += 1
        if changed:
            src.path.write_text("\n".join(new_lines) + "\n")
            print(f"annotated {src.rel}")
    return inserted


def _declared_attrs(src: SourceFile, cls: ast.ClassDef) -> Set[str]:
    end = max(getattr(cls, "end_lineno", cls.lineno), cls.lineno)
    declared = set(src.class_registry(cls.lineno, end))
    for stmt in ast.walk(cls):
        if isinstance(stmt, ast.Assign) and src.guarded_by(stmt.lineno):
            for tgt in stmt.targets:
                path = attr_path(tgt)
                if path and path.startswith("self."):
                    declared.add(path.split(".")[1])
    return declared


def _attr_lock_usage(cls: ast.ClassDef) -> Dict[str, Set[str]]:
    """attr -> set of lock names (None = some unlocked access) over
    every method except __init__."""
    usage: Dict[str, Set[str]] = {}

    def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            add = []
            for item in node.items:
                path = attr_path(item.context_expr)
                if path and path.startswith("self.") and path.count(".") == 1:
                    add.append(path.split(".")[1])
            for stmt in node.body:
                walk(stmt, held + tuple(add))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                walk(stmt, ())
            return
        if isinstance(node, ast.Attribute):
            path = attr_path(node)
            if path and path.startswith("self.") and path.count(".") >= 1:
                attr = path.split(".")[1]
                usage.setdefault(attr, set()).add(held[-1] if held else None)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if item.name == "__init__":
                continue
            for stmt in item.body:
                walk(stmt, ())
    return usage


# -- CLI ---------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="guberlint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=[])
    ap.add_argument("--baseline", default=str(REPO_ROOT / "guberlint_baseline.json"))
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--fix-annotations", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.paths:
        paths = [Path(p).resolve() for p in args.paths]
    else:
        paths = [REPO_ROOT / r for r in LINT_ROOTS]
    for p in paths:
        if not p.exists():
            print(f"guberlint: no such path: {p}", file=sys.stderr)
            return 2
        try:
            p.relative_to(REPO_ROOT)
        except ValueError:
            print(
                f"guberlint: path outside the repo root ({REPO_ROOT}): {p}",
                file=sys.stderr,
            )
            return 2

    if args.fix_annotations:
        n = fix_annotations(paths)
        print(f"guberlint: inserted {n} guarded-by stub(s) — review the diff")
        return 0

    findings = run(paths)
    base_path = Path(args.baseline)
    base = set() if args.no_baseline else baseline_mod.load(base_path)

    if args.write_baseline:
        baseline_mod.save(base_path, findings)
        print(
            f"guberlint: wrote {len(set(f.fingerprint() for f in findings))} "
            f"fingerprint(s) to {base_path}"
        )
        return 0

    new, accepted, stale = baseline_mod.partition(findings, base)
    if args.as_json:
        print(
            json.dumps(
                {
                    "new": [f.__dict__ for f in new],
                    "accepted": [f.__dict__ for f in accepted],
                    "stale_baseline": [list(s) for s in stale],
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.render())
        if accepted:
            print(f"guberlint: {len(accepted)} baselined finding(s) suppressed")
        for s in stale:
            print(f"guberlint: stale baseline entry (fixed?): {s}")
    if new:
        print(
            f"guberlint: {len(new)} new finding(s) — fix, suppress with a "
            "reasoned '# guberlint: ok <pass> — <why>', or (last resort) "
            "re-run with --write-baseline",
            file=sys.stderr,
        )
        return 1
    print(
        f"guberlint: clean ({len(accepted)} baselined, "
        f"{len(stale)} stale baseline entr{'y' if len(stale)==1 else 'ies'})"
        if (accepted or stale) else "guberlint: clean"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

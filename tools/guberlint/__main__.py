"""guberlint driver: ``python -m tools.guberlint [paths...]``.

Exit codes: 0 = no findings outside the baseline; 1 = new findings (or
a parse failure); 2 = bad invocation.

Options:
  --baseline FILE    baseline JSON (default: guberlint_baseline.json
                     at the repo root)
  --write-baseline   rewrite the baseline to the current finding set
  --fix-annotations  insert guarded-by stubs (Python attributes AND C
                     struct fields) whose every access already happens
                     under one consistent lock (review the diff before
                     committing)
  --only PASS        run a single pass (lock/trace/thread/net/native/
                     contract/drift) for fast local iteration
  --json             machine-readable output
  --sarif [FILE]     write SARIF 2.1.0 (CI annotations); with no FILE,
                     SARIF replaces the console output
  --no-baseline      ignore the baseline (report everything)
"""

from __future__ import annotations

import argparse
import ast
import json
import re
import sys
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from tools.guberlint import baseline as baseline_mod
from tools.guberlint import (
    contractcheck,
    driftcheck,
    lockcheck,
    nativecheck,
    netcheck,
    protocheck,
    threadcheck,
    tracecheck,
)
from tools.guberlint.common import (
    PASS_NAMES,
    Finding,
    SourceFile,
    SuppressionTracker,
    attr_path,
    iter_py_files,
)
from tools.guberlint.config import (
    EXCLUDE,
    LINT_ROOTS,
    NATIVE_ROOTS,
    TRACE_SCOPES,
)
from tools.guberlint.csource import CSourceFile, iter_c_files

REPO_ROOT = Path(__file__).resolve().parents[2]


def run(
    paths: List[Path],
    only: Optional[str] = None,
    repo_scope: Optional[bool] = None,
) -> List[Finding]:
    """Run the suite.  `paths` filters the per-file Python passes; the
    native/contract passes scan config.NATIVE_ROOTS and the drift pass
    scans the whole repo surface — those three run only when the
    default roots are linted (`repo_scope`, inferred from `paths` when
    not given) or when --only selects one directly, so a single-file
    invocation stays a single-file report."""
    if repo_scope is None:
        repo_scope = sorted(paths) == sorted(
            REPO_ROOT / r for r in LINT_ROOTS
        )

    def want(name: str) -> bool:
        if name in ("native", "contract", "drift", "proto"):
            return only == name or (only is None and repo_scope)
        return only is None or only == name

    # Stale-suppression detection needs every pass to have had its
    # chance to consult every suppression, so it only fires on the
    # full default suite at repo scope.
    detect_stale = repo_scope and only is None

    findings: List[Finding] = []
    with SuppressionTracker() as tracker:
        edges: Set[Tuple[str, str, str, int]] = set()
        py_passes = any(
            want(p) for p in ("lock", "trace", "thread", "net")
        )
        if py_passes:
            for src in iter_py_files(paths, REPO_ROOT, exclude=EXCLUDE):
                if src.parse_error:
                    findings.append(
                        Finding(
                            "meta", "parse-error", src.rel, 0,
                            "<module>", "parse",
                            f"syntax error: {src.parse_error}",
                        )
                    )
                    continue
                findings.extend(src.bad_suppressions)
                if want("lock"):
                    findings.extend(lockcheck.check_file(src, edges))
                if want("trace") and any(
                    src.rel.startswith(s) for s in TRACE_SCOPES
                ):
                    findings.extend(tracecheck.check_file(src))
                if want("thread"):
                    findings.extend(threadcheck.check_file(src))
                if want("net"):
                    findings.extend(netcheck.check_file(src))
            if want("lock"):
                findings.extend(lockcheck.order_findings(edges))
        if want("native") or want("contract") or want("drift"):
            csrcs = iter_c_files(
                [REPO_ROOT / r for r in NATIVE_ROOTS], REPO_ROOT
            )
            if want("native"):
                findings.extend(nativecheck.check_files(csrcs))
            if want("contract"):
                findings.extend(contractcheck.check(csrcs, REPO_ROOT))
            if want("drift"):
                findings.extend(driftcheck.check(REPO_ROOT, csrcs))
        if want("proto"):
            findings.extend(protocheck.check(REPO_ROOT))
        if detect_stale:
            findings.extend(
                baseline_mod.stale_suppressions(tracker, TRACE_SCOPES)
            )
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


# -- --changed ---------------------------------------------------------


def changed_lint_paths() -> Optional[List[Path]]:
    """Lintable Python files changed vs the merge-base with the
    upstream default branch (plus working-tree edits and untracked
    files).  Returns None when git can't answer — the caller falls
    back to the full-repo run, never to a silently-empty one."""
    import subprocess

    def git(*args: str) -> Optional[str]:
        try:
            p = subprocess.run(
                ["git", *args], cwd=REPO_ROOT, capture_output=True,
                text=True, timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        return p.stdout if p.returncode == 0 else None

    base = None
    for ref in ("origin/main", "origin/master", "main@{upstream}"):
        out = git("merge-base", "HEAD", ref)
        if out and out.strip():
            base = out.strip()
            break
    names: Set[str] = set()
    committed = git("diff", "--name-only", base) if base else None
    worktree = git("diff", "--name-only", "HEAD")
    untracked = git("ls-files", "--others", "--exclude-standard")
    if worktree is None and committed is None:
        return None  # not a git checkout (or git broke): full run
    for blob in (committed, worktree, untracked):
        if blob:
            names.update(ln.strip() for ln in blob.splitlines())
    out_paths: List[Path] = []
    for rel in sorted(names):
        if not rel.endswith(".py"):
            continue
        if not any(
            rel == r or rel.startswith(r.rstrip("/") + "/")
            for r in LINT_ROOTS
        ):
            continue
        if any(rel.startswith(e) for e in EXCLUDE):
            continue
        p = REPO_ROOT / rel
        if p.exists():  # deleted files have nothing to lint
            out_paths.append(p)
    return out_paths


# -- --fix-annotations -------------------------------------------------


def fix_annotations(paths: List[Path]) -> int:
    """Insert `# guberlint: guarded-by <lock>` stubs on __init__
    assignment lines of attributes whose every access outside __init__
    is under one consistent `with self.<lock>` block.  Conservative:
    skips attrs with any unlocked access or mixed locks."""
    files = iter_py_files(paths, REPO_ROOT, exclude=EXCLUDE)
    inserted = 0
    for src in files:
        if src.tree is None:
            continue
        new_lines = list(src.lines)
        changed = False
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            usage = _attr_lock_usage(cls)
            init = next(
                (
                    n for n in cls.body
                    if isinstance(n, ast.FunctionDef) and n.name == "__init__"
                ),
                None,
            )
            if init is None:
                continue
            declared = _declared_attrs(src, cls)
            for stmt in ast.walk(init):
                if not isinstance(stmt, ast.Assign):
                    continue
                for tgt in stmt.targets:
                    path = attr_path(tgt)
                    if not path or not path.startswith("self."):
                        continue
                    attr = path[len("self."):]
                    if "." in attr or attr in declared:
                        continue
                    locks = usage.get(attr)
                    if not locks or len(locks) != 1 or None in locks:
                        continue
                    ln = stmt.lineno - 1
                    if "guberlint" in new_lines[ln]:
                        continue
                    new_lines[ln] = (
                        new_lines[ln].rstrip()
                        + f"  # guberlint: guarded-by {next(iter(locks))}"
                    )
                    changed = True
                    inserted += 1
        if changed:
            src.path.write_text("\n".join(new_lines) + "\n")
            print(f"annotated {src.rel}")
    return inserted


def _declared_attrs(src: SourceFile, cls: ast.ClassDef) -> Set[str]:
    end = max(getattr(cls, "end_lineno", cls.lineno), cls.lineno)
    declared = set(src.class_registry(cls.lineno, end))
    for stmt in ast.walk(cls):
        if isinstance(stmt, ast.Assign) and src.guarded_by(stmt.lineno):
            for tgt in stmt.targets:
                path = attr_path(tgt)
                if path and path.startswith("self."):
                    declared.add(path.split(".")[1])
    return declared


def fix_c_annotations(paths: List[Path]) -> int:
    """C twin of fix_annotations: insert `// guberlint: guarded-by
    <mutex>` stubs on struct-field declaration lines whose every
    access across the scanned sources happens under one consistent
    mutex.  Conservative: any unlocked access or mixed mutexes skips
    the field."""
    from tools.guberlint.csource import _code_line, _field_names

    csrcs = iter_c_files(paths, REPO_ROOT)
    inserted = 0
    for src in csrcs:
        field_lines: Dict[Tuple[str, str], int] = {}
        declared: Set[Tuple[str, str]] = set()
        fn_spans = [(f.body_start, f.body_end) for f in src.functions]
        for s in src.structs:
            for attr in s.guards:
                declared.add((s.name, attr))
            first, last = src.line_of(s.start), src.line_of(s.end)
            for ln in range(first + 1, last + 1):
                off = src._line_starts[ln - 1]
                if any(a < off < b for a, b in fn_spans):
                    continue  # a local inside a member function body
                decl = _code_line(src.code, src._line_starts, ln)
                if "mutex" in decl or "atomic" in decl \
                        or "condition_variable" in decl:
                    continue  # locks/atomics are not guarded data
                if "constexpr" in decl or "static" in decl:
                    continue  # compile-time constants need no guard
                for name in _field_names(decl):
                    field_lines.setdefault((s.name, name), ln)
        if not field_lines:
            continue
        usage: Dict[Tuple[str, str], Set[Optional[str]]] = {}
        for fn in src.functions:
            body = src.code[fn.body_start:fn.body_end]
            for (sname, attr), _ln in field_lines.items():
                for m in re.finditer(
                    r"(?:([A-Za-z_]\w*)\s*(?:->|\.)\s*)?\b%s\b"
                    % re.escape(attr), body,
                ):
                    recv = m.group(1) or ""
                    if not recv and fn.struct != sname:
                        continue
                    held = src.held_at(fn, fn.body_start + m.start())
                    mutexes = {
                        mu for r, mu in held
                        if mu != "*" and (r == "" or r == recv or recv == "")
                    }
                    usage.setdefault((sname, attr), set()).add(
                        next(iter(mutexes)) if len(mutexes) == 1
                        else (sorted(mutexes)[0] if mutexes else None)
                    )
        new_lines = list(src.lines)
        changed = False
        for key, locks in sorted(usage.items()):
            if key in declared or None in locks or len(locks) != 1:
                continue
            ln = field_lines[key] - 1
            if "guberlint" in new_lines[ln]:
                continue
            new_lines[ln] = (
                new_lines[ln].rstrip()
                + f"  // guberlint: guarded-by {next(iter(locks))}"
            )
            changed = True
            inserted += 1
        if changed:
            src.path.write_text("\n".join(new_lines) + "\n")
            print(f"annotated {src.rel}")
    return inserted


def _attr_lock_usage(cls: ast.ClassDef) -> Dict[str, Set[str]]:
    """attr -> set of lock names (None = some unlocked access) over
    every method except __init__."""
    usage: Dict[str, Set[str]] = {}

    def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            add = []
            for item in node.items:
                path = attr_path(item.context_expr)
                if path and path.startswith("self.") and path.count(".") == 1:
                    add.append(path.split(".")[1])
            for stmt in node.body:
                walk(stmt, held + tuple(add))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                walk(stmt, ())
            return
        if isinstance(node, ast.Attribute):
            path = attr_path(node)
            if path and path.startswith("self.") and path.count(".") >= 1:
                attr = path.split(".")[1]
                usage.setdefault(attr, set()).add(held[-1] if held else None)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if item.name == "__init__":
                continue
            for stmt in item.body:
                walk(stmt, ())
    return usage


# -- SARIF -------------------------------------------------------------


def to_sarif(findings: List[Finding]) -> dict:
    """SARIF 2.1.0 document for CI annotation surfaces: one rule per
    (pass, rule), one result per finding."""
    rules: Dict[str, dict] = {}
    results = []
    for f in findings:
        rule_id = f"{f.pass_name}/{f.rule}"
        rules.setdefault(
            rule_id,
            {
                "id": rule_id,
                "shortDescription": {"text": f.rule},
                "helpUri": "STATIC_ANALYSIS.md",
            },
        )
        results.append(
            {
                "ruleId": rule_id,
                "level": "error",
                "message": {"text": f"{f.scope}: {f.message}"},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.file},
                            "region": {"startLine": max(1, f.line)},
                        }
                    }
                ],
                "fingerprints": {
                    "guberlint/v1": ":".join(f.fingerprint()),
                },
            }
        )
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "guberlint",
                        "informationUri": "STATIC_ANALYSIS.md",
                        "rules": sorted(
                            rules.values(), key=lambda r: r["id"]
                        ),
                    }
                },
                "results": results,
            }
        ],
    }


# -- CLI ---------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="guberlint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=[])
    ap.add_argument("--baseline", default=str(REPO_ROOT / "guberlint_baseline.json"))
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--fix-annotations", action="store_true")
    ap.add_argument(
        "--only", choices=PASS_NAMES, default=None,
        help="run a single pass (fast local iteration)",
    )
    ap.add_argument(
        "--changed", action="store_true",
        help="incremental mode: lint only files changed vs the "
        "merge-base with the upstream default branch (plus working-"
        "tree and untracked files); falls back to the full run when "
        "git can't answer.  Repo-scope passes (native/contract/drift/"
        "proto and stale-suppression detection) are skipped — run the "
        "full suite before shipping.",
    )
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument(
        "--sarif", nargs="?", const="-", default=None, metavar="FILE",
        help="write SARIF 2.1.0 to FILE (console output kept); with "
        "no FILE, SARIF replaces the console output",
    )
    args = ap.parse_args(argv)

    if args.changed and args.paths:
        print(
            "guberlint: --changed and explicit paths are mutually "
            "exclusive", file=sys.stderr,
        )
        return 2
    if args.paths:
        paths = [Path(p).resolve() for p in args.paths]
    elif args.changed:
        changed = changed_lint_paths()
        if changed is None:
            print(
                "guberlint: --changed could not consult git — "
                "falling back to the full-repo run", file=sys.stderr,
            )
            paths = [REPO_ROOT / r for r in LINT_ROOTS]
        elif not changed:
            print("guberlint: clean (no lintable files changed)")
            return 0
        else:
            paths = changed
    else:
        paths = [REPO_ROOT / r for r in LINT_ROOTS]
    for p in paths:
        if not p.exists():
            print(f"guberlint: no such path: {p}", file=sys.stderr)
            return 2
        try:
            p.relative_to(REPO_ROOT)
        except ValueError:
            print(
                f"guberlint: path outside the repo root ({REPO_ROOT}): {p}",
                file=sys.stderr,
            )
            return 2

    if args.fix_annotations:
        n = fix_annotations(paths)
        n += fix_c_annotations(
            [REPO_ROOT / r for r in NATIVE_ROOTS]
            if not args.paths else paths
        )
        print(f"guberlint: inserted {n} guarded-by stub(s) — review the diff")
        return 0

    findings = run(paths, only=args.only)
    base_path = Path(args.baseline)
    base = set() if args.no_baseline else baseline_mod.load(base_path)

    if args.write_baseline:
        baseline_mod.save(base_path, findings)
        print(
            f"guberlint: wrote {len(set(f.fingerprint() for f in findings))} "
            f"fingerprint(s) to {base_path}"
        )
        return 0

    new, accepted, stale = baseline_mod.partition(findings, base)
    if args.sarif is not None:
        doc = json.dumps(to_sarif(new), indent=2)
        if args.sarif == "-":
            print(doc)
        else:
            Path(args.sarif).write_text(doc + "\n")
    if args.as_json:
        print(
            json.dumps(
                {
                    "new": [f.__dict__ for f in new],
                    "accepted": [f.__dict__ for f in accepted],
                    "stale_baseline": [list(s) for s in stale],
                },
                indent=2,
            )
        )
    elif args.sarif == "-":
        pass  # SARIF replaced the console report
    else:
        for f in new:
            print(f.render())
        if accepted:
            print(f"guberlint: {len(accepted)} baselined finding(s) suppressed")
        for s in stale:
            print(f"guberlint: stale baseline entry (fixed?): {s}")
    if new:
        print(
            f"guberlint: {len(new)} new finding(s) — fix, suppress with a "
            "reasoned '# guberlint: ok <pass> — <why>', or (last resort) "
            "re-run with --write-baseline",
            file=sys.stderr,
        )
        return 1
    if not (args.as_json or args.sarif == "-"):
        print(
            f"guberlint: clean ({len(accepted)} baselined, "
            f"{len(stale)} stale baseline entr{'y' if len(stale)==1 else 'ies'})"
            if (accepted or stale) else "guberlint: clean"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Repo-specific guberlint configuration.

Everything here is DATA the passes consult; the pass logic itself is
repo-agnostic.  Documented in STATIC_ANALYSIS.md.
"""

from __future__ import annotations

# Files/dirs (repo-relative prefixes) scanned by the trace pass: the
# jit-reachable kernel surface.  The lock and thread passes scan the
# whole package.
TRACE_SCOPES = (
    "gubernator_tpu/ops/",
    "gubernator_tpu/core/engine.py",
    "gubernator_tpu/core/pump.py",
    "gubernator_tpu/core/readback.py",
    "gubernator_tpu/parallel/",
)

# Lint roots (repo-relative).
LINT_ROOTS = ("gubernator_tpu",)

# Prefixes excluded from all passes (generated code).
EXCLUDE = ("gubernator_tpu/net/pb/",)

# Attribute-name -> class hints for qualifying dotted lock paths in
# the acquisition-order graph: `with self.engine._lock` inside
# StepPump orders against DecisionEngine's own `with self._lock`.
ATTR_CLASS_HINTS = {
    "engine": "DecisionEngine",
    "_engine": "DecisionEngine",
    "ledger": "DecisionLedger",
    "led": "DecisionLedger",
    "pump": "StepPump",
    "_hits": "IntervalBatcher",
    "_updates": "IntervalBatcher",
    "combiner": "ReadbackCombiner",
    # Elastic-membership plane (post-PR-3 audit): the membership
    # manager's epoch state machine and the handoff sender/receiver
    # state it drives (cluster/membership.py, cluster/handoff.py).
    "membership": "MembershipManager",
    "mem": "MembershipManager",
    "_membership": "MembershipManager",
    "sender": "HandoffSender",
    "_sender": "HandoffSender",
}

# ---------------------------------------------------------------------
# Native tier (tools/guberlint/csource.py + nativecheck.py): the C
# decision plane under gubernator_tpu/core/native/.

# C/C++ sources scanned by the native + contract passes.
NATIVE_ROOTS = ("gubernator_tpu/core/native",)

# Calls that can block the calling thread for an unbounded/system-
# scheduler amount of time: making one while a mutex is held convoys
# every thread contending that mutex behind the kernel (the h2 front's
# per-connection threads share per-conn and per-server mutexes).  The
# designed exceptions (the write path serializes on write_mu) carry
# reasoned suppressions.
NATIVE_BLOCKING_CALLS = (
    "send", "recv", "sendmsg", "recvmsg", "sendto", "recvfrom",
    "accept", "connect", "poll", "select", "epoll_wait",
    "sleep", "usleep", "nanosleep", "sleep_for", "sleep_until",
    "getaddrinfo",
)

# Call names that re-enter Python (acquire the GIL) even though they
# are not Py* API: the h2 server's window callback is a ctypes-built
# CFUNCTYPE trampoline, so any call through it blocks on the GIL.
NATIVE_GIL_CALLS = ("callback",)

# Reactor discipline (nativecheck blocking-in-reactor): inside code
# reachable from an `// guberlint: epoll-root` function, these socket
# calls must carry the named nonblocking token in their argument list
# — a reactor thread parked in a blocking syscall stalls EVERY
# connection on its lane (h2_server.cpp reactor_loop owns thousands).
# Plain accept() can never carry SOCK_NONBLOCK (it is accept4's
# flag), so bare accept in a reactor always flags: use accept4.
REACTOR_NONBLOCK_TOKENS = {
    "send": "MSG_DONTWAIT",
    "recv": "MSG_DONTWAIT",
    "sendto": "MSG_DONTWAIT",
    "recvfrom": "MSG_DONTWAIT",
    "sendmsg": "MSG_DONTWAIT",
    "recvmsg": "MSG_DONTWAIT",
    "accept": "SOCK_NONBLOCK",
    "accept4": "SOCK_NONBLOCK",
}

# ---------------------------------------------------------------------
# Contract pass (tools/guberlint/contractcheck.py): the Python<->C
# boundary, pinned bit-equal.

# Proto files — the wire-layout source of truth for BOTH tiers (the
# Python codec is generated from these; the C codec declares its
# layout via `// guberlint: wire` annotations checked against them).
PROTO_FILES = (
    "gubernator_tpu/net/proto/gubernator.proto",
    "gubernator_tpu/net/proto/peers.proto",
)

# Cross-tier constants that must be numerically identical: (file_a,
# symbol_a, file_b, symbol_b).  .cpp symbols parse from constexpr/const
# declarations; .py symbols evaluate module-level int expressions
# (types.py enum members resolve).
CONTRACT_CONSTANTS = (
    # Decision-plane record kinds: the C table's kOver/kLease are the
    # ledger's _K_OVER/_K_LEASE (dp_pull returns them; core/ledger.py
    # branches on the value).
    ("gubernator_tpu/core/native/decision_plane.cpp", "kOver",
     "gubernator_tpu/core/ledger.py", "_K_OVER"),
    ("gubernator_tpu/core/native/decision_plane.cpp", "kLease",
     "gubernator_tpu/core/ledger.py", "_K_LEASE"),
    # Lease-eligibility breaker mask: duplicated on the bridge side so
    # the plane declines exactly what the ledger would revoke on.
    ("gubernator_tpu/core/ledger.py", "_BREAKERS",
     "gubernator_tpu/core/native_plane.py", "_BREAKERS"),
)

# Proto enums pinned against the Python IntEnum twins: every proto
# member must exist with the same value (Python may EXTEND the enum —
# Behavior.SKETCH is a repo extension with no wire presence).
ENUM_CONTRACTS = (
    ("Algorithm", "gubernator_tpu/types.py"),
    ("Behavior", "gubernator_tpu/types.py"),
    ("Status", "gubernator_tpu/types.py"),
)

# Every getenv("GUBER_*") in C must have its home in this file (the
# canonical env-surface index).
KNOB_HOME = "gubernator_tpu/config.py"

# ---------------------------------------------------------------------
# Drift pass (tools/guberlint/driftcheck.py): knob/metric/doc surface.

# Where GUBER_* knob reads are collected from (the package + native
# sources; scripts and tests consume knobs, they don't define them).
KNOB_SCAN_ROOTS = ("gubernator_tpu",)

# Every knob read anywhere must have a row in the README table.
KNOB_DOC_FILE = "README.md"

# Metric registry + the doc surface every registered metric must
# appear in (at least one of these files).
METRIC_REGISTRY = "gubernator_tpu/utils/metrics.py"
METRIC_DOC_FILES = (
    "README.md", "PERF.md", "RESILIENCE.md", "STATIC_ANALYSIS.md",
    "OBSERVABILITY.md", "scripts/bench_trend.py",
)

# The SLI declaration file (obs/slo.py): the drift `slo` sub-rule
# checks every SLI(...) declaration there names a metric the registry
# actually exports — an SLI over a dropped series would silently
# evaluate nothing.
SLO_REGISTRY = "gubernator_tpu/obs/slo.py"

# Methods known to acquire a lock at their top level: a call to one of
# these while holding other locks creates an acquisition-order edge
# (one level of indirection across the ledger/batch_loop/
# global_manager/pump trio).
KNOWN_LOCKING_CALLS = {
    # DecisionEngine serializes on its RLock.
    "apply_columnar": "DecisionEngine._lock",
    "get_rate_limits": "DecisionEngine._lock",
    "sweep": "DecisionEngine._lock",
    # DecisionLedger entry points.
    "plan": "DecisionLedger._lock",
    "flush_settles": "DecisionLedger._lock",
    "invalidate_keys": "DecisionLedger._lock",
    "readonly_overlay": "DecisionLedger._lock",
    # IntervalBatcher producers/drains.
    "add_chunk": "IntervalBatcher._lock",
    "add_many": "IntervalBatcher._lock",
    "flush_now": "IntervalBatcher._lock",
    # StepPump flush path runs under the engine lock.
    "flush_for": "DecisionEngine._lock",
}

"""Repo-specific guberlint configuration.

Everything here is DATA the passes consult; the pass logic itself is
repo-agnostic.  Documented in STATIC_ANALYSIS.md.
"""

from __future__ import annotations

# Files/dirs (repo-relative prefixes) scanned by the trace pass: the
# jit-reachable kernel surface.  The lock and thread passes scan the
# whole package.
TRACE_SCOPES = (
    "gubernator_tpu/ops/",
    "gubernator_tpu/core/engine.py",
    "gubernator_tpu/core/pump.py",
    "gubernator_tpu/core/readback.py",
    "gubernator_tpu/parallel/",
)

# Lint roots (repo-relative).
LINT_ROOTS = ("gubernator_tpu",)

# Prefixes excluded from all passes (generated code).
EXCLUDE = ("gubernator_tpu/net/pb/",)

# Attribute-name -> class hints for qualifying dotted lock paths in
# the acquisition-order graph: `with self.engine._lock` inside
# StepPump orders against DecisionEngine's own `with self._lock`.
ATTR_CLASS_HINTS = {
    "engine": "DecisionEngine",
    "_engine": "DecisionEngine",
    "ledger": "DecisionLedger",
    "led": "DecisionLedger",
    "pump": "StepPump",
    "_hits": "IntervalBatcher",
    "_updates": "IntervalBatcher",
    "combiner": "ReadbackCombiner",
}

# Methods known to acquire a lock at their top level: a call to one of
# these while holding other locks creates an acquisition-order edge
# (one level of indirection across the ledger/batch_loop/
# global_manager/pump trio).
KNOWN_LOCKING_CALLS = {
    # DecisionEngine serializes on its RLock.
    "apply_columnar": "DecisionEngine._lock",
    "get_rate_limits": "DecisionEngine._lock",
    "sweep": "DecisionEngine._lock",
    # DecisionLedger entry points.
    "plan": "DecisionLedger._lock",
    "flush_settles": "DecisionLedger._lock",
    "invalidate_keys": "DecisionLedger._lock",
    "readonly_overlay": "DecisionLedger._lock",
    # IntervalBatcher producers/drains.
    "add_chunk": "IntervalBatcher._lock",
    "add_many": "IntervalBatcher._lock",
    "flush_now": "IntervalBatcher._lock",
    # StepPump flush path runs under the engine lock.
    "flush_for": "DecisionEngine._lock",
}

"""Pass 1 — lock discipline.

Classes declare guarded attributes (``# guberlint: guarded-by <lock>``
on the attribute's init line, or a per-class ``# guberlint: guard a, b
by <lock>`` registry).  The pass verifies every read/write of a guarded
attribute happens lexically inside ``with <receiver>.<lock>`` (or a
method annotated ``# guberlint: holds <lock>``; the repo's ``*_locked``
naming convention implies holding every lock the class declares), and
builds a lock acquisition-order graph across the concurrent trio
(ledger / batch_loop / global_manager / pump / engine) to flag
ordering inversions (cycles).

Soundness notes (documented limits, STATIC_ANALYSIS.md §lock):

- The analysis is lexical and receiver-textual: ``led._items`` requires
  ``with led._lock`` (same receiver text).  Attribute aliasing through
  containers or threads is out of scope.
- ``threading.Condition(self.X)`` aliases the condition attribute to
  ``X`` (acquiring the condition acquires the wrapped lock);
  ``threading.Condition()`` is its own lock.
- Nested ``def``/``lambda`` bodies reset the held-lock context: they
  may run on another thread after the enclosing ``with`` exits.
- ``__init__`` is exempt (construction happens before publication).
- Only intra-class access is checked for ``self.attr``; cross-object
  reads of plain counters (metrics scrapes) are outside the contract.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.guberlint.common import Finding, SourceFile, attr_path
from tools.guberlint.config import ATTR_CLASS_HINTS, KNOWN_LOCKING_CALLS

PASS = "lock"


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        self.guards: Dict[str, str] = {}  # attr -> lock path (self-rel)
        self.aliases: Dict[str, str] = {}  # condition attr -> base lock
        self.lock_names: Set[str] = set()

    def resolve(self, lock: str) -> str:
        """Map a condition-variable attr to its wrapped base lock."""
        return self.aliases.get(lock, lock)


def _collect_class(src: SourceFile, node: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(node.name)
    end = max(getattr(node, "end_lineno", node.lineno), node.lineno)
    info.guards.update(src.class_registry(node.lineno, end))
    for stmt in ast.walk(node):
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            continue
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        for tgt in targets:
            path = attr_path(tgt)
            if path is None or not path.startswith("self."):
                continue
            attr = path[len("self."):]
            if "." in attr:
                continue
            lock = src.guarded_by(stmt.lineno)
            if lock:
                info.guards[attr] = lock
            # Condition aliasing: self.cv = threading.Condition(self.X)
            val = stmt.value
            if (
                isinstance(val, ast.Call)
                and attr_path(val.func) in ("threading.Condition", "Condition")
            ):
                if val.args:
                    base = attr_path(val.args[0])
                    if base and base.startswith("self."):
                        info.aliases[attr] = base[len("self."):]
                else:
                    info.aliases[attr] = attr
    info.lock_names = set(info.guards.values())
    return info


def _qualify(owner_class: str, lock_path: str) -> str:
    """Normalize a receiver-relative lock path to a graph node name:
    'self._lock' in class C -> 'C._lock'; 'self.engine._lock' ->
    'DecisionEngine._lock' via ATTR_CLASS_HINTS; otherwise keep the
    dotted tail as-is (receiver-stripped)."""
    parts = lock_path.split(".")
    if parts and parts[0] == "self":
        parts = parts[1:]
    if len(parts) == 1:
        return f"{owner_class}.{parts[0]}"
    hint = ATTR_CLASS_HINTS.get(parts[-2])
    if hint:
        return f"{hint}.{parts[-1]}"
    return ".".join(parts)


class _MethodChecker(ast.NodeVisitor):
    """Walk one method body tracking the lexically-held lock set."""

    def __init__(
        self,
        src: SourceFile,
        cls: _ClassInfo,
        module_guards: Dict[str, Tuple[str, str]],
        scope: str,
        held: Set[str],
        findings: List[Finding],
        edges: Set[Tuple[str, str, str, int]],
    ):
        self.src = src
        self.cls = cls
        self.module_guards = module_guards
        self.scope = scope
        self.held = set(held)
        self.findings = findings
        self.edges = edges

    # -- helpers -------------------------------------------------------

    def _lock_node_of(self, path: str) -> Optional[str]:
        """Held-set entry for a `with` target path, or None when the
        expression is not a lock-ish attribute chain."""
        if path is None:
            return None
        parts = path.split(".")
        if parts[0] == "self" and len(parts) == 2:
            return "self." + self.cls.resolve(parts[1])
        return path

    def _record_acquire(self, lock: str, lineno: int) -> None:
        qual = _qualify(self.cls.name, lock)
        for h in self.held:
            hq = _qualify(self.cls.name, h)
            if hq != qual:
                self.edges.add((hq, qual, self.src.rel, lineno))

    # -- visitors ------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func  # e.g. `with self._lock:` vs acquire()
                path = attr_path(expr)
                # with span(...), with self._lock.acquire_timeout(...):
                if path and path.endswith((".acquire", ".acquire_timeout")):
                    path = path.rsplit(".", 1)[0]
                elif path and not path.endswith(("_lock", "_cv", "_mutex")):
                    path = None
            else:
                path = attr_path(expr)
            lock = self._lock_node_of(path) if path else None
            if lock and (
                lock.split(".")[-1] in self.cls.lock_names
                or lock.split(".")[-1].endswith(("_lock", "_cv", "_mutex"))
                or lock.split(".")[-1] in self.cls.aliases
            ):
                self._record_acquire(lock, node.lineno)
                if lock not in self.held:
                    acquired.append(lock)
                    self.held.add(lock)
        for stmt in node.body:
            self.visit(stmt)
        for lock in acquired:
            self.held.discard(lock)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._nested(node)

    def _nested(self, node) -> None:
        # A nested callable may run on another thread after the
        # enclosing `with` exits: reset the held-lock context, honoring
        # any `holds` annotation on the nested def itself.
        held = {
            h if h.startswith("self.") else "self." + h
            for h in self.src.holds(node)
        }
        sub = _MethodChecker(
            self.src, self.cls, self.module_guards,
            self.scope + ".<nested>", held, self.findings, self.edges,
        )
        for stmt in node.body if isinstance(node.body, list) else [node.body]:
            sub.visit(stmt)

    def visit_Call(self, node: ast.Call) -> None:
        # One-level indirection: calls into methods known (config) to
        # acquire a lock create an ordering edge from every held lock.
        path = attr_path(node.func)
        if path and self.held:
            target = KNOWN_LOCKING_CALLS.get(path.split(".")[-1])
            if target:
                for h in self.held:
                    hq = _qualify(self.cls.name, h)
                    if hq != target:
                        self.edges.add((hq, target, self.src.rel, node.lineno))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if id(node) in getattr(self, "_chain_seen", ()):
            self.generic_visit(node)
            return
        path = attr_path(node)
        if path:
            # Mark the whole chain visited so nested Attribute nodes
            # don't re-report the same access.
            seen = self.__dict__.setdefault("_chain_seen", set())
            sub = node
            while isinstance(sub, ast.Attribute):
                seen.add(id(sub))
                sub = sub.value
            parts = path.split(".")
            # Check the GUARDED attribute segment wherever it appears
            # in the chain (e.g. `self._items.get`, `led._pending[...]`).
            for i in range(1, len(parts)):
                recv = ".".join(parts[:i])
                attr = parts[i]
                self._check_access(recv, attr, node)
        self.generic_visit(node)

    def _check_access(self, recv: str, attr: str, node: ast.Attribute) -> None:
        if recv == "self":
            lock = self.cls.guards.get(attr)
            owner = self.cls.name
        else:
            entry = self.module_guards.get(attr)
            if entry is None:
                return
            owner, lock = entry
            # Receiver-based matching only where the config vouches
            # for the receiver's class (`led` -> DecisionLedger):
            # attribute names alone collide across classes
            # (LedgerPlan.settles vs DecisionLedger.settles).
            hinted = ATTR_CLASS_HINTS.get(recv.split(".")[-1])
            if hinted != owner:
                return
        if lock is None:
            return
        required = f"{recv}.{self.cls.resolve(lock) if recv == 'self' else lock}"
        if required in self.held:
            return
        # `holds` annotations may name the lock without the receiver.
        if recv == "self" and ("self." + lock) in self.held:
            return
        if self.src.suppressed(node.lineno, PASS):
            return
        self.findings.append(
            Finding(
                PASS, "unguarded-access", self.src.rel, node.lineno,
                self.scope, f"{recv}.{attr}",
                f"access to {recv}.{attr} (guarded by {lock} in {owner}) "
                f"outside `with {required}`",
            )
        )


def check_file(
    src: SourceFile,
    edges: Set[Tuple[str, str, str, int]],
) -> List[Finding]:
    findings: List[Finding] = []
    if src.tree is None:
        return findings
    classes = [
        n for n in ast.walk(src.tree) if isinstance(n, ast.ClassDef)
    ]
    infos = {c: _collect_class(src, c) for c in classes}
    # Module-wide attr -> (class, lock) map for non-self receivers;
    # attrs guarded in more than one class are checked via self only.
    module_guards: Dict[str, Tuple[str, str]] = {}
    conflicted: Set[str] = set()
    for info in infos.values():
        for attr, lock in info.guards.items():
            if attr in module_guards and module_guards[attr][1] != lock:
                conflicted.add(attr)
            else:
                module_guards[attr] = (info.name, lock)
    for attr in conflicted:
        module_guards.pop(attr, None)

    for cls_node, info in infos.items():
        if not info.guards:
            continue
        for item in cls_node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue
            scope = f"{info.name}.{item.name}"
            held: Set[str] = set()
            for lock in src.holds(item):
                held.add(lock if lock.startswith("self.") else "self." + lock)
            if item.name.endswith("_locked"):
                # Repo convention: *_locked methods run with the
                # class's declared locks held by the caller.
                for lock in info.lock_names:
                    held.add(
                        lock if lock.startswith("self.") else "self." + lock
                    )
            checker = _MethodChecker(
                src, info, module_guards, scope, held, findings, edges,
            )
            for stmt in item.body:
                checker.visit(stmt)
    return findings


def order_findings(
    edges: Set[Tuple[str, str, str, int]]
) -> List[Finding]:
    """Cycle detection over the acquisition-order graph.  An edge
    A -> B means 'B acquired while A held'; any cycle is a potential
    deadlock between threads taking the locks in opposite orders."""
    graph: Dict[str, Set[str]] = {}
    where: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for a, b, f, ln in edges:
        graph.setdefault(a, set()).add(b)
        where.setdefault((a, b), (f, ln))
    findings: List[Finding] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(node: str, stack: List[str], on_stack: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):] + [nxt]
                canon = tuple(sorted(set(cyc)))
                if canon in seen_cycles:
                    continue
                seen_cycles.add(canon)
                f, ln = where.get((node, nxt), ("<graph>", 0))
                findings.append(
                    Finding(
                        PASS, "lock-order-inversion", f, ln,
                        "<lock-graph>", "->".join(cyc),
                        "lock acquisition-order cycle: "
                        + " -> ".join(cyc),
                    )
                )
            elif nxt in graph:
                stack.append(nxt)
                on_stack.add(nxt)
                dfs(nxt, stack, on_stack)
                on_stack.discard(nxt)
                stack.pop()

    for start in sorted(graph):
        dfs(start, [start], {start})
    return findings

"""Pass 4 — peer-network discipline (the health-plane contract).

- ``net-retry-no-backoff`` — a retry loop over peer RPCs (a
  ``while``/``for`` whose body catches ``PeerError`` and makes a
  retry decision: references ``not_ready``/``circuit_open``, feeds a
  ``retry``-named collection, or calls a ``requeue``-named method)
  must contain a backoff call somewhere in the loop —
  ``time.sleep``, ``backoff_delay``, or a ``.wait(...)``.  A
  backoff-free re-pick spin is exactly the tail-latency amplifier
  the health plane exists to remove ("When Two is Worse Than One",
  PAPERS.md); the reference's 5-retry loop had this bug.  The
  multiregion send path's historical log-and-continue suppression is
  GONE: since the §12 rewrite its fan-out carries real
  timeout+backoff+requeue and passes this rule on its own — and a
  requeue-without-backoff loop (the shape that suppression used to
  hide) now flags, because a requeue call IS a retry decision.

- ``net-rpc-no-timeout`` — call sites of the PeerClient RPC surface
  (``get_peer_rate_limit(s)``, ``send_peer_hits(_raw)``,
  ``update_peer_globals(_raw)``) must pass an explicit ``timeout=``.
  The methods have defaults, but a call site that doesn't say its
  deadline is a call site nobody budgeted — the GLOBAL fan-out stall
  fixed in this round came from exactly such a site.  Server-side
  receivers (``self`` / ``*.instance``) are exempt: those are the
  V1Instance methods of the same names, which answer locally.

Suppress with the usual grammar: ``# guberlint: ok net — <why>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.guberlint.common import Finding, SourceFile, attr_path

PASS = "net"

# The PeerClient RPC surface (every one takes timeout=).  The handoff
# RPC (cluster/handoff.py) is held to the same discipline: an epoch
# commit waits on the sender, so an unbudgeted TransferBuckets call
# would let one slow peer stall a membership transition indefinitely.
# The replication RPC (cluster/replication.py) likewise: an unbudgeted
# grant would let one slow replica stall the owner's promotion tick —
# and with it every other promoted key's lease refresh.
PEER_RPC_METHODS = {
    "get_peer_rate_limit",
    "get_peer_rate_limits",
    "send_peer_hits",
    "send_peer_hits_raw",
    "update_peer_globals",
    "update_peer_globals_raw",
    "transfer_buckets",
    "transfer_buckets_raw",
    "replicate_keys",
    "replicate_keys_raw",
    # The fleet rollup scrape (obs/fleet.py): an unbudgeted
    # ObsSnapshot would let one slow peer stall the rollup barrier.
    "obs_snapshot_raw",
}

# Backoff-shaped calls that satisfy net-retry-no-backoff.
_BACKOFF_CALL_NAMES = {"sleep", "backoff_delay", "wait"}


def _scope_name(src: SourceFile, node: ast.AST) -> str:
    """Innermost Class.method / func enclosing `node` (for findings)."""
    best_cls = best_fn = None
    if src.tree is None:
        return "<module>"
    for n in ast.walk(src.tree):
        if not isinstance(
            n, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        if (
            n.lineno <= node.lineno
            and getattr(n, "end_lineno", n.lineno) >= node.lineno
        ):
            if isinstance(n, ast.ClassDef):
                if best_cls is None or n.lineno > best_cls.lineno:
                    best_cls = n
            elif best_fn is None or n.lineno > best_fn.lineno:
                best_fn = n
    if best_cls is not None and best_fn is not None:
        return f"{best_cls.name}.{best_fn.name}"
    if best_fn is not None:
        return best_fn.name
    return "<module>"


def _catches_peer_error(handler: ast.ExceptHandler) -> bool:
    types = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        path = attr_path(t) if t is not None else None
        if path and path.split(".")[-1] == "PeerError":
            return True
    return False


def _is_retry_decision(handler: ast.ExceptHandler) -> bool:
    """The handler decides to RETRY: it inspects not_ready /
    circuit_open, feeds a retry collection, or re-queues the failed
    items for a later attempt (a requeue IS a retry — deferring it to
    another window without backoff is the same spin, one hop
    removed).  A log-and-continue handler iterating unrelated peers
    is not a retry loop."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Attribute) and node.attr in (
            "not_ready",
            "circuit_open",
        ):
            return True
        if not isinstance(node, ast.Call):
            continue
        callee = attr_path(node.func) or getattr(node.func, "id", "")
        if "requeue" in (callee or "").lower():
            return True
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("append", "extend")
        ):
            recv = attr_path(node.func.value) or ""
            if "retry" in recv.lower():
                return True
    return False


def _has_backoff(loop: ast.AST) -> bool:
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        path = attr_path(node.func)
        name = (
            path.split(".")[-1]
            if path
            else getattr(node.func, "attr", getattr(node.func, "id", ""))
        )
        if name in _BACKOFF_CALL_NAMES:
            return True
    return False


def check_file(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    if src.tree is None:
        return findings

    # -- net-retry-no-backoff -----------------------------------------
    all_loops = [
        n for n in ast.walk(src.tree) if isinstance(n, (ast.While, ast.For))
    ]
    for loop in all_loops:
        retry_handler: Optional[ast.ExceptHandler] = None
        for node in ast.walk(loop):
            if (
                isinstance(node, ast.ExceptHandler)
                and _catches_peer_error(node)
                and _is_retry_decision(node)
            ):
                retry_handler = node
                break
        if retry_handler is None:
            continue
        # Backoff anywhere in this loop OR an enclosing loop counts:
        # the canonical shape sleeps between ROUNDS (the outer while),
        # not inside the per-group for.
        enclosing = [
            l for l in all_loops
            if l.lineno <= loop.lineno
            and getattr(l, "end_lineno", l.lineno)
            >= getattr(loop, "end_lineno", loop.lineno)
        ]
        if any(_has_backoff(l) for l in enclosing):
            continue
        if src.suppressed(loop.lineno, PASS) or src.suppressed(
            retry_handler.lineno, PASS
        ):
            continue
        findings.append(
            Finding(
                PASS, "net-retry-no-backoff", src.rel, loop.lineno,
                _scope_name(src, loop), f"retry-loop@{loop.lineno}",
                "peer-RPC retry loop without backoff — sleep a capped "
                "exponential with jitter (cluster/health.backoff_delay) "
                "between attempts, or suppress with a reasoned "
                "`# guberlint: ok net — <why>`",
            )
        )

    # -- net-rpc-no-timeout -------------------------------------------
    for node in ast.walk(src.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in PEER_RPC_METHODS
        ):
            continue
        recv = attr_path(node.func.value)
        # Server-side same-name methods (V1Instance answers locally).
        if recv is not None and (
            recv == "self"
            or recv == "instance"
            or recv.endswith(".instance")
        ):
            continue
        if any(kw.arg == "timeout" for kw in node.keywords):
            continue
        if src.suppressed(node.lineno, PASS):
            continue
        findings.append(
            Finding(
                PASS, "net-rpc-no-timeout", src.rel, node.lineno,
                _scope_name(src, node),
                f"{node.func.attr}@{recv or '?'}",
                f"peer RPC `{node.func.attr}` without an explicit "
                "timeout= — every peer send must state its deadline "
                "(the fan-out barrier budgets depend on it), or "
                "suppress with a reasoned `# guberlint: ok net — <why>`",
            )
        )
    return findings

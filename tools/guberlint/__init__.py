"""guberlint — repo-native static analysis for gubernator_tpu.

Seven passes over the concurrent host tier AND the native C decision
plane (STATIC_ANALYSIS.md):

- ``lock``     — guarded-attribute discipline + lock acquisition-order
  inversions (tools/guberlint/lockcheck.py);
- ``trace``    — JAX trace hygiene over the jit-reachable kernel code
  (tools/guberlint/tracecheck.py);
- ``thread``   — daemon-thread lifecycle + silent exception swallowing
  (tools/guberlint/threadcheck.py);
- ``net``      — peer-network discipline: retry backoff + RPC timeouts
  (tools/guberlint/netcheck.py);
- ``native``   — C tier over core/native/*.cpp: mutex guard
  discipline, GIL-freedom, blocking-calls-under-mutex, atomics
  memory-order audit (tools/guberlint/nativecheck.py, parsed by
  tools/guberlint/csource.py);
- ``contract`` — the Python<->C boundary pinned bit-equal: wire field
  layout vs the proto, decision-plane protocol constants vs
  core/ledger.py, C GUBER_* reads vs config.py
  (tools/guberlint/contractcheck.py);
- ``drift``    — knob/metric/doc surface: every GUBER_* read has a
  config.py home + README row, every registered metric is documented
  (tools/guberlint/driftcheck.py).

Run locally with ``python -m tools.guberlint`` (``--only <pass>`` for
fast iteration, ``--sarif`` for CI annotations); CI fails on findings
not present in the committed ``guberlint_baseline.json``.
"""

from tools.guberlint.common import Finding, SourceFile  # noqa: F401

__all__ = ["Finding", "SourceFile"]

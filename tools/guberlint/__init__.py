"""guberlint — repo-native static analysis for gubernator_tpu.

Three AST passes over the concurrent host tier (STATIC_ANALYSIS.md):

- ``lock``   — guarded-attribute discipline + lock acquisition-order
  inversions (tools/guberlint/lockcheck.py);
- ``trace``  — JAX trace hygiene over the jit-reachable kernel code
  (tools/guberlint/tracecheck.py);
- ``thread`` — daemon-thread lifecycle + silent exception swallowing
  (tools/guberlint/threadcheck.py).

Run locally with ``python -m tools.guberlint``; CI fails on findings
not present in the committed ``guberlint_baseline.json``.
"""

from tools.guberlint.common import Finding, SourceFile  # noqa: F401

__all__ = ["Finding", "SourceFile"]

"""Pass 7 — knob/metric/doc drift across the whole surface.

The operator contract, enforced (STATIC_ANALYSIS.md):

- ``drift-knob-no-config-home`` — a GUBER_* env var is read somewhere
  (Python call-site string literal under config.KNOB_SCAN_ROOTS, or a
  getenv in the native sources) but config.py — the canonical
  env-surface index — never mentions it.  Daemon knobs load there;
  debug/infra knobs read elsewhere are indexed by the KNOWN_ENV_KNOBS
  registry.
- ``drift-knob-undocumented`` — a knob is read but has no row in the
  README's configuration table (config.KNOB_DOC_FILE).
- ``drift-knob-stale`` — the README documents a GUBER_* knob nothing
  reads any more: the row promises a lever that no longer exists.
- ``drift-metric-undocumented`` — a metric registered in
  utils/metrics.py appears in none of config.METRIC_DOC_FILES (README/
  PERF/RESILIENCE/STATIC_ANALYSIS or the bench-trend columns).
- ``drift-metric-stale`` — a doc names a ``gubernator_*`` metric the
  registry no longer exports.
- ``drift-span-name-style`` / ``drift-span-name-duplicate`` — the
  trace sub-rule: every literal ``span("name", ...)`` site must be
  dot-separated snake_case (span names are an operator-facing query
  surface: /debug/trace, the OTel backend, OBSERVABILITY.md's
  catalog), and each name must identify ONE site — two sites sharing
  a name make "where did this span come from" unanswerable.
  Deliberate twins (the sharded engine mirrors engine.py's stages
  under the same names so the tests/oracles stay backend-agnostic)
  carry reasoned suppressions at the twin site.
- ``drift-slo-metric-unregistered`` / ``drift-slo-no-metric`` — the
  slo sub-rule: every ``SLI(...)`` declaration in config.SLO_REGISTRY
  (obs/slo.py) must carry a literal ``metric=`` naming a series
  utils/metrics.py actually registers.  An SLI is an operator promise
  ("this burn rate watches that metric"); one over a dropped or
  mistyped series would silently evaluate nothing.

Knob reads are collected from the AST (string literals used as call
arguments), so prose/docstrings never count as reads; metric
registrations are the first-argument literals of ``*MetricFamily``
constructors; span sites are calls to a function named ``span`` with
a literal first argument.  Suppression uses the normal grammar at the
read / registration / span site.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Set, Tuple

from tools.guberlint.common import Finding, SourceFile, iter_py_files
from tools.guberlint.config import (
    EXCLUDE,
    KNOB_DOC_FILE,
    KNOB_HOME,
    KNOB_SCAN_ROOTS,
    METRIC_DOC_FILES,
    METRIC_REGISTRY,
    SLO_REGISTRY,
)
from tools.guberlint.csource import CSourceFile

PASS = "drift"

_KNOB_RE = re.compile(r"^GUBER_[A-Z0-9_]+$")
_DOC_KNOB_RE = re.compile(r"\bGUBER_[A-Z0-9_]+\b")
_DOC_METRIC_RE = re.compile(r"\bgubernator_[a-z0-9_]+\b")
# Tokens the metric regex matches that are not metrics.
_METRIC_TOKEN_EXCLUDE = {"gubernator_tpu", "gubernator_pb2", "gubernator_pool"}


def check(repo_root: Path, csrcs: List[CSourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    reads = _knob_reads(repo_root, csrcs)
    _check_knobs(repo_root, reads, findings)
    _check_metrics(repo_root, findings)
    _check_spans(repo_root, findings)
    _check_slo(repo_root, findings)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


# -- knob surface ------------------------------------------------------


def _knob_reads(
    repo_root: Path, csrcs: List[CSourceFile]
) -> Dict[str, List[Tuple[SourceFile, int]]]:
    """knob -> [(source, line)] read sites.  A 'read' is a GUBER_*
    string literal appearing as a call argument (env lookups), never a
    docstring/prose mention."""
    reads: Dict[str, List[Tuple[object, int]]] = {}
    roots = [repo_root / r for r in KNOB_SCAN_ROOTS]
    for src in iter_py_files(roots, repo_root, exclude=EXCLUDE):
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and _KNOB_RE.match(arg.value)
                ):
                    reads.setdefault(arg.value, []).append(
                        (src, arg.lineno)
                    )
    for csrc in csrcs:
        for lineno, value in csrc.strings:
            if _KNOB_RE.match(value):
                line = csrc.lines[lineno - 1] if lineno <= len(csrc.lines) else ""
                prev = csrc.lines[lineno - 2] if lineno >= 2 else ""
                if "getenv" in line or "getenv" in prev:
                    reads.setdefault(value, []).append((csrc, lineno))
    return reads


def _check_knobs(
    repo_root: Path,
    reads: Dict[str, List[Tuple[object, int]]],
    findings: List[Finding],
) -> None:
    home_path = repo_root / KNOB_HOME
    home_text = home_path.read_text() if home_path.exists() else ""
    doc_path = repo_root / KNOB_DOC_FILE
    doc_text = doc_path.read_text() if doc_path.exists() else ""
    for knob in sorted(reads):
        src, lineno = reads[knob][0]
        rel = getattr(src, "rel", "")
        # C getenv reads: the config-home side is the CONTRACT pass's
        # rule (contract/knob-homeless) — reporting it here too would
        # double-bill one defect.  The README-row check below still
        # applies to C-read knobs.
        is_c_read = rel.endswith((".cpp", ".cc", ".c", ".h", ".hpp"))
        if rel != KNOB_HOME and not is_c_read and knob not in home_text:
            if not src.suppressed(lineno, PASS):
                findings.append(
                    Finding(
                        PASS, "knob-no-config-home", src.rel, lineno,
                        "<module>", knob,
                        f"{knob} is read here but config.py (the "
                        "canonical GUBER_* index) never mentions it — "
                        "add it to the daemon config or the "
                        "KNOWN_ENV_KNOBS registry",
                    )
                )
        if knob not in doc_text:
            if not src.suppressed(lineno, PASS):
                findings.append(
                    Finding(
                        PASS, "knob-undocumented", src.rel, lineno,
                        "<module>", knob,
                        f"{knob} is read here but {KNOB_DOC_FILE}'s "
                        "configuration table has no row for it",
                    )
                )
    # Reverse: documented knobs nothing reads.
    for m in _DOC_KNOB_RE.finditer(doc_text):
        knob = m.group(0)
        if knob in reads:
            continue
        # Prefix rows like GUBER_TLS_CLIENT_AUTH cover their family.
        if any(r.startswith(knob) for r in reads):
            continue
        lineno = doc_text[: m.start()].count("\n") + 1
        findings.append(
            Finding(
                PASS, "knob-stale", KNOB_DOC_FILE, lineno, "<module>",
                knob,
                f"{KNOB_DOC_FILE} documents {knob} but nothing reads "
                "it — drop the row or re-wire the knob",
            )
        )


# -- span-site surface (the trace sub-rule) ----------------------------

# Dot-separated snake_case: "global.hits_window", "engine.batch".
_SPAN_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)*$")


def _span_sites(
    repo_root: Path,
) -> List[Tuple[str, SourceFile, int]]:
    """(name, source, line) for every literal span("name", ...) call
    under KNOB_SCAN_ROOTS.  Helper-routed spans (a variable name
    argument) are invisible here by design — the rule governs the
    literal catalog OBSERVABILITY.md indexes."""
    out: List[Tuple[str, SourceFile, int]] = []
    roots = [repo_root / r for r in KNOB_SCAN_ROOTS]
    for src in iter_py_files(roots, repo_root, exclude=EXCLUDE):
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else ""
            )
            if name != "span":
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append((arg.value, src, node.lineno))
    return out


def _check_spans(repo_root: Path, findings: List[Finding]) -> None:
    sites = _span_sites(repo_root)
    by_name: Dict[str, List[Tuple[SourceFile, int]]] = {}
    for name, src, line in sites:
        if not _SPAN_NAME_RE.match(name):
            if not src.suppressed(line, PASS):
                findings.append(
                    Finding(
                        PASS, "span-name-style", src.rel, line,
                        "<module>", name,
                        f"span name {name!r} is not dot-separated "
                        "snake_case — span names are the /debug/trace "
                        "+ OTel query surface (OBSERVABILITY.md)",
                    )
                )
        by_name.setdefault(name, []).append((src, line))
    for name, where in sorted(by_name.items()):
        if len(where) < 2:
            continue
        first_src, first_line = where[0]
        for src, line in where[1:]:
            if src.suppressed(line, PASS):
                continue
            findings.append(
                Finding(
                    PASS, "span-name-duplicate", src.rel, line,
                    "<module>", name,
                    f"span name {name!r} is also emitted at "
                    f"{first_src.rel}:{first_line} — a span name must "
                    "identify one site; rename, or suppress the "
                    "deliberate twin with its reason",
                )
            )


# -- SLI surface (the slo sub-rule) ------------------------------------


def _check_slo(repo_root: Path, findings: List[Finding]) -> None:
    """Every SLI(...) declaration in config.SLO_REGISTRY must name a
    registered metric via a literal ``metric=`` kwarg."""
    path = repo_root / SLO_REGISTRY
    if not path.exists():
        return
    src = SourceFile(path, SLO_REGISTRY)
    if src.tree is None:
        return
    registered = {name for name, _src, _line in _registered_metrics(repo_root)}
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = (
            func.attr if isinstance(func, ast.Attribute)
            else func.id if isinstance(func, ast.Name) else ""
        )
        if name != "SLI":
            continue
        metric = None
        for kw in node.keywords:
            if kw.arg == "metric" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                metric = kw.value.value
        if metric is None:
            if not src.suppressed(node.lineno, PASS):
                findings.append(
                    Finding(
                        PASS, "slo-no-metric", src.rel, node.lineno,
                        "<module>", f"SLI@{node.lineno}",
                        "SLI declaration without a literal metric= — "
                        "every declared SLI must name the documented "
                        "metric backing it (the drift slo sub-rule "
                        "cannot verify a computed name)",
                    )
                )
            continue
        if metric in registered:
            continue
        if src.suppressed(node.lineno, PASS):
            continue
        findings.append(
            Finding(
                PASS, "slo-metric-unregistered", src.rel, node.lineno,
                "<module>", metric,
                f"SLI declares metric {metric} but "
                f"{METRIC_REGISTRY} never registers it — the burn "
                "rate would watch a series that does not exist",
            )
        )


# -- metric surface ----------------------------------------------------


def _registered_metrics(repo_root: Path) -> List[Tuple[str, SourceFile, int]]:
    path = repo_root / METRIC_REGISTRY
    if not path.exists():
        return []
    src = SourceFile(path, METRIC_REGISTRY)
    out: List[Tuple[str, SourceFile, int]] = []
    if src.tree is None:
        return out
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = (
            func.attr if isinstance(func, ast.Attribute)
            else func.id if isinstance(func, ast.Name) else ""
        )
        if not name.endswith("MetricFamily"):
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            out.append((node.args[0].value, src, node.args[0].lineno))
    return out


def _check_metrics(repo_root: Path, findings: List[Finding]) -> None:
    registered = _registered_metrics(repo_root)
    doc_texts = {
        rel: (repo_root / rel).read_text()
        for rel in METRIC_DOC_FILES
        if (repo_root / rel).exists()
    }
    names: Set[str] = set()
    for metric, src, lineno in registered:
        names.add(metric)
        if any(metric in text for text in doc_texts.values()):
            continue
        if src.suppressed(lineno, PASS):
            continue
        findings.append(
            Finding(
                PASS, "metric-undocumented", METRIC_REGISTRY, lineno,
                "<module>", metric,
                f"metric {metric} is registered but appears in none "
                f"of {', '.join(METRIC_DOC_FILES)} — document what it "
                "means or it is noise on the scrape",
            )
        )
    # Reverse: docs promising metrics the registry no longer exports.
    # Hierarchical names are fine: a doc token that is a PREFIX of a
    # registered metric (or vice versa) still refers to a live series.
    for rel, text in doc_texts.items():
        seen: Set[str] = set()
        for m in _DOC_METRIC_RE.finditer(text):
            token = m.group(0)
            if token in seen or token in _METRIC_TOKEN_EXCLUDE:
                continue
            seen.add(token)
            if any(
                token == n or token.startswith(n) or n.startswith(token)
                for n in names
            ):
                continue
            lineno = text[: m.start()].count("\n") + 1
            findings.append(
                Finding(
                    PASS, "metric-stale", rel, lineno, "<module>",
                    token,
                    f"{rel} names metric {token} but utils/metrics.py "
                    "never registers it — stale doc or a dropped "
                    "series",
                )
            )

"""Pass 8 — protocol invariant drift (``proto``).

gubercheck (tools/gubercheck) model-checks the lease/handoff/
replication protocols against a registry of named invariants
(tools/gubercheck/properties.py).  That registry is only trustworthy
while three surfaces stay in sync, and this pass pins them pairwise:

- ``proto-orphan-annotation`` — a ``# guberlint: invariant <name>``
  source annotation names a property the registry does not register:
  the code claims model-checked protection that does not exist.
- ``proto-doc-unregistered`` — a RESILIENCE.md ``gubercheck: `name` ``
  marker names an unregistered property: the doc promises a checked
  bound nothing checks.
- ``proto-invariant-undocumented`` — a registered property has no
  RESILIENCE.md marker: the checker enforces a bound operators can't
  read about (every checked invariant is part of the resilience
  contract).
- ``proto-property-unanchored`` — a registered property has no
  ``# guberlint: invariant`` annotation anywhere in the package: a
  registry row with no protected site is dead weight (or the guard it
  described was deleted — either way, drift).

Annotation grammar (STATIC_ANALYSIS.md):

- source:  ``# guberlint: invariant <kebab-name>`` — trailing or
  standalone comment at the guard/commit site the property protects.
- doc:     ``gubercheck: `kebab-name` `` anywhere in RESILIENCE.md
  prose (backticks required: they keep the marker greppable and
  unambiguous vs ordinary text).

The registry import is cheap by contract: properties.py is stdlib-only
(no jax/numpy/package imports), so this pass adds no measurable weight
to the 10 s guberlint budget.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, List, Tuple

from tools.guberlint.common import Finding, iter_py_files
from tools.guberlint.config import EXCLUDE, LINT_ROOTS

PASS = "proto"

#: Where the prose contract lives (the doc side of the drift check).
PROTO_DOC_FILE = "RESILIENCE.md"
#: The registry module (the anchor for registry-side findings).
PROTO_REGISTRY = "tools/gubercheck/properties.py"

_ANNOTATION_RE = re.compile(
    r"#\s*guberlint:\s*invariant\s+([A-Za-z0-9][A-Za-z0-9-]*)"
)
_DOC_MARKER_RE = re.compile(r"gubercheck:\s*`([A-Za-z0-9][A-Za-z0-9-]*)`")


def _registry() -> Dict[str, object]:
    from tools.gubercheck import properties as props

    return props.registry()


def _register_line(repo_root: Path, name: str) -> int:
    """Line of the property's register(...) call, for anchoring
    registry-side findings somewhere a human can act on."""
    path = repo_root / PROTO_REGISTRY
    try:
        lines = path.read_text().splitlines()
    except OSError:
        return 0
    for i, raw in enumerate(lines, start=1):
        if f'"{name}"' in raw or f"'{name}'" in raw:
            return i
    return 0


def check(repo_root: Path, paths=None) -> List[Finding]:
    findings: List[Finding] = []
    registered = _registry()

    # -- source annotations -------------------------------------------
    anchored: Dict[str, List[Tuple[str, int]]] = {}
    roots = paths if paths is not None else [
        repo_root / r for r in LINT_ROOTS
    ]
    for src in iter_py_files(roots, repo_root, exclude=EXCLUDE):
        for lineno, raw in enumerate(src.lines, start=1):
            m = _ANNOTATION_RE.search(raw)
            if not m:
                continue
            name = m.group(1)
            anchored.setdefault(name, []).append((src.rel, lineno))
            if name not in registered and not src.suppressed(
                lineno, PASS
            ):
                findings.append(
                    Finding(
                        PASS, "proto-orphan-annotation", src.rel,
                        lineno, "<module>", name,
                        f"invariant annotation {name!r} matches no "
                        "property registered in "
                        f"{PROTO_REGISTRY} — the code claims "
                        "model-checked protection that does not exist "
                        "(register it, or fix the name)",
                    )
                )

    # -- doc markers ---------------------------------------------------
    documented: Dict[str, int] = {}
    doc_path = repo_root / PROTO_DOC_FILE
    if doc_path.exists():
        for lineno, raw in enumerate(
            doc_path.read_text().splitlines(), start=1
        ):
            for m in _DOC_MARKER_RE.finditer(raw):
                name = m.group(1)
                documented.setdefault(name, lineno)
                if name not in registered:
                    findings.append(
                        Finding(
                            PASS, "proto-doc-unregistered",
                            PROTO_DOC_FILE, lineno, "<module>", name,
                            f"{PROTO_DOC_FILE} promises a checked "
                            f"bound `{name}` but no such property is "
                            f"registered in {PROTO_REGISTRY} — the "
                            "doc claims coverage nothing checks",
                        )
                    )

    # -- registry completeness ----------------------------------------
    for name in sorted(registered):
        if name not in documented:
            findings.append(
                Finding(
                    PASS, "proto-invariant-undocumented",
                    PROTO_REGISTRY, _register_line(repo_root, name),
                    "<module>", name,
                    f"property {name!r} is registered and checked but "
                    f"{PROTO_DOC_FILE} has no 'gubercheck: `{name}`' "
                    "marker — every checked invariant is part of the "
                    "documented resilience contract",
                )
            )
        if name not in anchored:
            findings.append(
                Finding(
                    PASS, "proto-property-unanchored",
                    PROTO_REGISTRY, _register_line(repo_root, name),
                    "<module>", name,
                    f"property {name!r} has no '# guberlint: "
                    f"invariant {name}' annotation anywhere under "
                    f"{'/'.join(LINT_ROOTS)} — a registry row with no "
                    "protected site is drift (annotate the guard it "
                    "checks, or delete the row)",
                )
            )
    return findings

"""The invariant registry: every protocol claim gets a named property.

This module is the three-way anchor that guberlint's ``proto`` pass
(pass 8) cross-checks:

- RESILIENCE.md states a bound   → it must carry a ``gubercheck:
  `name` `` marker naming a property registered here;
- source code marks the site     → ``# guberlint: invariant <name>``
  must name a property registered here;
- a property registered here     → must be documented AND anchored in
  source (no dead registry rows).

IMPORT-WEIGHT CONTRACT: stdlib only.  The linter imports this module
on every run; pulling numpy/jax (or any gubernator_tpu module) in
here would tax every lint invocation and break minimal environments.
The predicates therefore take plain data extracted by scenarios.py,
never live protocol objects.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Sequence, Tuple


class PropertyViolation(AssertionError):
    """An invariant failed at a schedule step.  Carries the property
    name so the explorer can attribute the finding."""

    def __init__(self, prop: str, detail: str):
        super().__init__(f"{prop}: {detail}")
        self.prop = prop
        self.detail = detail


@dataclass(frozen=True)
class Property:
    """One registered invariant."""

    name: str
    summary: str
    doc: str  # where RESILIENCE.md states the bound (section ref)


_REGISTRY: Dict[str, Property] = {}


def register(name: str, summary: str, doc: str) -> Property:
    p = Property(name, summary, doc)
    _REGISTRY[name] = p
    return p


def get(name: str) -> Property:
    return _REGISTRY[name]


def names() -> List[str]:
    return sorted(_REGISTRY)


def registry() -> Dict[str, Property]:
    return dict(_REGISTRY)


# ---------------------------------------------------------------------
# The catalog.  Keep names kebab-case; they appear verbatim in
# RESILIENCE.md §13, in `# guberlint: invariant <name>` annotations,
# and in scenario `properties` tuples.

register(
    "sticky-over-exact",
    "A ledger OVER entry is exact: whenever the ledger answers OVER "
    "from a cached entry, the device bucket's stored remaining is 0 "
    "(the entry was inserted from a post-settle snapshot, not a "
    "pre-return or pre-renewal one).",
    "RESILIENCE.md §13",
)
register(
    "hot-key-no-starvation",
    "After leases settle, a probe through the ledger answers exactly "
    "what the sequential spec answers — returned credit is servable, "
    "never stranded behind a stale sticky-OVER entry.",
    "RESILIENCE.md §13",
)
register(
    "over-admission-bound",
    "Admitted hits for one key in one bucket window never exceed the "
    "window limit on a single node (pre-debited lease credit cannot "
    "over-admit); across a partitioned cluster the bound relaxes to "
    "N_partitions x limit.",
    "RESILIENCE.md §3",
)
register(
    "lease-single-tier",
    "A key's drainable lease credit lives in exactly one tier: the "
    "Python ledger entry or the native plane's table, never both "
    "(delegation hands off; pull linearizes before the next drain).",
    "RESILIENCE.md §13",
)
register(
    "epoch-monotonic-commit",
    "Membership epochs commit in strictly increasing order; a "
    "superseded transition never commits after its successor.",
    "RESILIENCE.md §10",
)
register(
    "dual-window-no-third-owner",
    "During a dual-ring handoff window every key routes to its old "
    "owner or its new owner — never to a third node.",
    "RESILIENCE.md §10",
)
register(
    "region-no-double-send",
    "Requeue-and-converge never double-sends: the hits delivered to a "
    "region never exceed the hits offered to it (a delivered batch is "
    "not requeued; a requeued batch was not delivered).",
    "RESILIENCE.md §12",
)
register(
    "circuit-legal-transitions",
    "Peer circuit breakers move only along the documented transition "
    "table (healthy->suspect->broken->half-open->{healthy,broken}, "
    "plus the racing-success broken->healthy edge).",
    "RESILIENCE.md §1",
)


# ---------------------------------------------------------------------
# Predicates.  Pure functions over plain data; raise PropertyViolation
# with the registered name on failure.  scenarios.py extracts the data
# from live protocol objects at quiescent points.


def check_sticky_over_exact(
    entries: Iterable[Tuple[bytes, int, bool]],
) -> None:
    """entries: (key, device_remaining, device_live) for every ledger
    OVER entry whose recorded reset has not passed."""
    for key, remaining, live in entries:
        if live and remaining != 0:
            raise PropertyViolation(
                "sticky-over-exact",
                f"ledger answers OVER for {key!r} while the device "
                f"bucket holds remaining={remaining}",
            )


def check_probe_conformance(
    key: bytes,
    ledger_answer: Tuple[int, int],
    spec_answer: Tuple[int, int],
) -> None:
    """(status, remaining) of a terminal hits=0 probe served through
    the ledger vs the same probe against the spec state directly."""
    if ledger_answer != spec_answer:
        raise PropertyViolation(
            "hot-key-no-starvation",
            f"terminal probe of {key!r} diverges: ledger answers "
            f"{ledger_answer}, spec answers {spec_answer}",
        )


def check_over_admission(
    key: bytes, admitted: int, limit: int, n_nodes: int = 1
) -> None:
    """admitted: total hits answered UNDER for ``key`` inside one
    bucket window (status-based counting under-counts the sticky
    consume-while-OVER quirk, which only weakens the check — it can
    never mask a true over-admission)."""
    bound = n_nodes * limit
    if admitted > bound:
        raise PropertyViolation(
            "over-admission-bound",
            f"{key!r}: admitted {admitted} > {n_nodes}x{limit}",
        )


def check_lease_single_tier(
    entries: Iterable[Tuple[bytes, str, bool]],
) -> None:
    """entries: (key, tier, plane_holds_lease) where tier is the
    ledger entry kind name ('lease'|'native'|'over'|'counter')."""
    for key, tier, in_plane in entries:
        if tier == "lease" and in_plane:
            raise PropertyViolation(
                "lease-single-tier",
                f"{key!r} drainable in BOTH tiers (python lease + "
                "native plane entry)",
            )
        if tier == "native" and not in_plane:
            raise PropertyViolation(
                "lease-single-tier",
                f"{key!r} marked delegated but the plane has no entry "
                "(credit lives in NO tier)",
            )


def check_epoch_monotonic(commits: Sequence[int]) -> None:
    """commits: epoch numbers in the order they committed."""
    for a, b in zip(commits, commits[1:]):
        if b <= a:
            raise PropertyViolation(
                "epoch-monotonic-commit",
                f"epoch {b} committed after epoch {a}",
            )


def check_dual_window_routing(
    routes: Iterable[Tuple[bytes, str, Tuple[str, str]]],
) -> None:
    """routes: (key, routed_addr, (old_owner, new_owner))."""
    for key, addr, owners in routes:
        if addr not in owners:
            raise PropertyViolation(
                "dual-window-no-third-owner",
                f"{key!r} routed to {addr} outside the dual window "
                f"owners {owners}",
            )


def check_region_no_double_send(
    offered: Dict[Tuple[str, bytes], int],
    delivered: Dict[Tuple[str, bytes], int],
) -> None:
    """Per (region, key): hits delivered must never exceed hits
    offered — requeue-and-converge re-sends only what never landed."""
    for rk, got in delivered.items():
        if got > offered.get(rk, 0):
            raise PropertyViolation(
                "region-no-double-send",
                f"region/key {rk}: delivered {got} > offered "
                f"{offered.get(rk, 0)}",
            )


#: The legal circuit-breaker edges (RESILIENCE.md §1).  Self-loops are
#: absorbed inside PeerHealth._to (no transition recorded), so every
#: recorded edge must appear here.
CIRCUIT_LEGAL_EDGES = frozenset({
    ("healthy", "suspect"),      # first failure
    ("suspect", "healthy"),      # success before threshold
    ("suspect", "broken"),       # threshold failures
    ("broken", "half-open"),     # open period expired, probe slot won
    ("half-open", "healthy"),    # probe succeeded
    ("half-open", "broken"),     # probe failed (period doubles)
    ("broken", "healthy"),       # racing in-flight success
})


def check_circuit_transitions(
    edges: Iterable[Tuple[str, str]],
) -> None:
    """edges: observed (from_state, to_state) transitions."""
    for edge in edges:
        if edge not in CIRCUIT_LEGAL_EDGES:
            raise PropertyViolation(
                "circuit-legal-transitions",
                f"illegal circuit transition {edge[0]} -> {edge[1]}",
            )
